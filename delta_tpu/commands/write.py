"""Batch write command: append / overwrite / replaceWhere.

Equivalent of `commands/WriteIntoDelta.scala:46-138` plus the implicit
metadata logic of `schema/ImplicitMetadataOperation.scala:30-62`: first write
creates the table (schema inferred from the Arrow batch), `mergeSchema`
evolves it, `overwriteSchema` replaces it (overwrite mode only);
`replaceWhere` turns overwrite into a predicate-scoped atomic replacement
after validating every written row matches the predicate; `rearrangeOnly`
flips `dataChange=False` on all emitted actions (`:129-131`).
"""
from __future__ import annotations

from dataclasses import replace
from typing import Any, Dict, List, Optional, Sequence, Union

import pyarrow as pa

from delta_tpu.commands import operations as ops
from delta_tpu.exec import write as write_exec
from delta_tpu.expr import ir
from delta_tpu.expr import partition as partition_expr
from delta_tpu.expr.parser import parse_predicate
from delta_tpu.protocol.actions import Action, AddFile, Metadata
from delta_tpu.schema import schema_utils
from delta_tpu.schema.arrow_interop import schema_from_arrow
from delta_tpu.schema.types import StructType
from delta_tpu.utils.errors import DeltaAnalysisError, DeltaIllegalArgumentError
from delta_tpu.utils import errors

__all__ = ["WriteIntoDelta", "update_metadata_on_write", "coerce_to_table"]

MODES = ("append", "overwrite", "error", "errorifexists", "ignore")


def coerce_to_table(data: Any) -> pa.Table:
    """Accept pa.Table / RecordBatch / dict-of-lists / list-of-dicts."""
    if isinstance(data, pa.Table):
        return data
    if isinstance(data, pa.RecordBatch):
        return pa.Table.from_batches([data])
    if isinstance(data, dict):
        return pa.table(data)
    if isinstance(data, list):
        return pa.Table.from_pylist(data)
    try:  # pandas, polars, anything with an Arrow bridge
        return pa.table(data)
    except Exception:
        raise DeltaIllegalArgumentError(
            f"Cannot convert {type(data).__name__} to an Arrow table"
        )


def update_metadata_on_write(
    txn,
    data_schema: StructType,
    partition_columns: Sequence[str],
    configuration: Optional[Dict[str, str]] = None,
    is_overwrite: bool = False,
    merge_schema: bool = False,
    overwrite_schema: bool = False,
) -> None:
    """`ImplicitMetadataOperation.updateMetadata` semantics."""
    table_exists = txn.read_version >= 0 and txn.metadata.schema_string is not None
    if overwrite_schema and not is_overwrite:
        raise DeltaAnalysisError("overwriteSchema requires mode('overwrite')")
    if not table_exists:
        schema_utils.check_partition_columns(partition_columns, data_schema)
        txn.update_metadata(
            Metadata(
                schema_string=data_schema.to_json(),
                partition_columns=list(partition_columns),
                configuration=dict(configuration or {}),
            )
        )
        return
    current = txn.metadata
    if partition_columns and [c.lower() for c in partition_columns] != [
        c.lower() for c in current.partition_columns
    ]:
        raise errors.partition_columns_mismatch(
            partition_columns, current.partition_columns)
    if overwrite_schema:
        new_meta = replace(
            current,
            schema_string=data_schema.to_json(),
            partition_columns=list(partition_columns or current.partition_columns),
        )
        txn.update_metadata(new_meta)
        return
    if merge_schema:
        merged = schema_utils.merge_schemas(current.schema, data_schema)
        if merged.to_json() != current.schema.to_json():
            txn.update_metadata(replace(current, schema_string=merged.to_json()))
        return
    # plain enforcement: the batch must fit the table schema
    schema_utils.enforce_write_compatibility(current.schema, data_schema)


class WriteIntoDelta:
    def __init__(
        self,
        delta_log,
        mode: str,
        data: Any,
        partition_columns: Sequence[str] = (),
        replace_where: Optional[Union[str, ir.Expression]] = None,
        merge_schema: bool = False,
        overwrite_schema: bool = False,
        rearrange_only: bool = False,
        configuration: Optional[Dict[str, str]] = None,
        user_metadata: Optional[str] = None,
    ):
        mode = mode.lower()
        if mode not in MODES:
            raise DeltaIllegalArgumentError(f"Unknown save mode {mode!r}")
        if replace_where is not None and mode != "overwrite":
            raise DeltaAnalysisError("replaceWhere is only supported with mode('overwrite')")
        self.delta_log = delta_log
        self.mode = mode
        self.table = coerce_to_table(data)
        self.partition_columns = list(partition_columns)
        self.replace_where = (
            parse_predicate(replace_where) if isinstance(replace_where, str) else replace_where
        )
        self.merge_schema = merge_schema
        self.overwrite_schema = overwrite_schema
        self.rearrange_only = rearrange_only
        self.configuration = configuration
        self.user_metadata = user_metadata

    def run(self) -> int:
        from delta_tpu.utils.telemetry import record_operation

        with record_operation("delta.dml.write", mode=self.mode,
                              path=self.delta_log.data_path):
            return self._run_impl()

    def _run_impl(self) -> int:
        log = self.delta_log
        if log.table_exists:
            if self.mode == "ignore":
                return log.snapshot.version
            if self.mode in ("error", "errorifexists"):
                raise errors.table_already_exists(log.data_path)

        def body(txn):
            actions = self.write(txn)
            adds = [a for a in actions if isinstance(a, AddFile)]
            txn.report_metrics(
                numFiles=len(adds),
                numOutputBytes=sum(a.size or 0 for a in adds),
                numOutputRows=self.table.num_rows,
            )
            op = ops.Write(
                mode=self.mode,
                partition_by=self.partition_columns or None,
                predicate=self.replace_where.sql() if self.replace_where else None,
            )
            return txn.commit(actions, op)

        return log.with_new_transaction(body)

    def write(self, txn) -> List[Action]:
        data_schema = schema_from_arrow(self.table.schema)
        is_overwrite = self.mode == "overwrite"
        update_metadata_on_write(
            txn,
            data_schema,
            self.partition_columns or txn.metadata.partition_columns,
            configuration=self.configuration,
            is_overwrite=is_overwrite,
            merge_schema=self.merge_schema,
            overwrite_schema=self.overwrite_schema,
        )
        metadata = txn.metadata

        adds = write_exec.write_files(
            self.delta_log.data_path,
            self.table,
            metadata,
            data_change=not self.rearrange_only,
        )

        removes: List[Action] = []
        if is_overwrite:
            if self.replace_where is None:
                removes = [f.remove(data_change=not self.rearrange_only)
                           for f in txn.filter_files()]
            else:
                removes = self._replace_where_removes(txn, adds)
        return list(adds) + removes

    def _replace_where_removes(self, txn, written: List[AddFile]) -> List[Action]:
        """Validate written files land inside the predicate, then remove the
        matching files (`WriteIntoDelta.scala:112-125`). Like the reference,
        only partition predicates are supported — removing a file matched by
        a *data* predicate would also delete its non-matching rows."""
        pred = self.replace_where
        metadata = txn.metadata
        part_schema = metadata.partition_schema
        pcols = metadata.partition_columns
        conjuncts = ir.split_conjuncts(pred)
        if not all(partition_expr.is_partition_predicate(c, pcols) for c in conjuncts):
            raise errors.replace_where_needs_partition_columns(pred.sql(), pcols)
        for add in written:
            if not partition_expr.matches(pred, add, part_schema):
                raise errors.replace_where_mismatch(
                    pred.sql(), f"partitions {add.partition_values}"
                )
        matched = txn.filter_files([pred])
        data_change = not self.rearrange_only
        return [f.remove(data_change=data_change) for f in matched]
