"""Streaming source/sink suites.

Behavioral spec: `DeltaSourceSuite` / `DeltaSinkSuite` (SURVEY §4) — initial
snapshot serving, log tailing, admission control, hygiene checks, offset
restart, sink exactly-once.
"""
import pyarrow as pa
import pytest

from delta_tpu import DeltaLog
from delta_tpu.commands.delete import DeleteCommand
from delta_tpu.commands.update import UpdateCommand
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.exec.scan import scan_to_table
from delta_tpu.streaming.offset import DeltaSourceOffset
from delta_tpu.streaming.query import StreamingQuery
from delta_tpu.streaming.sink import DeltaSink
from delta_tpu.streaming.source import DeltaSource
from delta_tpu.utils.errors import DeltaIllegalStateError


def write(log, data, mode="append", **kw):
    return WriteIntoDelta(log, mode, data, **kw).run()


def drain(source, start=None):
    """Pull every pending batch; returns list of non-empty id-lists."""
    out = []
    cur = start
    while True:
        anchor = cur if cur is not None else source.initial_offset()
        end = source.latest_offset(anchor)
        if end is None:
            return out, cur
        t = source.get_batch(cur, end)
        if t.num_rows:
            out.append(sorted(t.column("id").to_pylist()))
        cur = end


def test_source_initial_snapshot_then_tail(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2]})
    write(log, {"id": [3]})
    source = DeltaSource(log)
    batches, cur = drain(source)
    assert batches == [[1, 2, 3]]  # initial snapshot in one batch
    # now tail new commits
    write(log, {"id": [4, 5]})
    batches, cur = drain(source, cur)
    assert batches == [[4, 5]]
    # nothing new -> no batch
    batches, _ = drain(source, cur)
    assert batches == []


def test_source_max_files_per_trigger(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    for i in range(4):
        write(log, {"id": [i]})
    source = DeltaSource(log, max_files_per_trigger=2)
    batches, _ = drain(source)
    assert batches == [[0, 1], [2, 3]]


def test_source_max_bytes_always_admits_one(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    for i in range(3):
        write(log, {"id": [i]})
    source = DeltaSource(log, max_files_per_trigger=None, max_bytes_per_trigger=1)
    batches, _ = drain(source)
    # 1 byte cap still admits one file per trigger (no stall)
    assert batches == [[0], [1], [2]]


def test_source_starting_version_skips_snapshot(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1]})
    write(log, {"id": [2]})
    write(log, {"id": [3]})
    source = DeltaSource(log, starting_version=1)
    batches, _ = drain(source)
    assert batches == [[2, 3]]


def test_source_delete_fails_stream(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2]})
    source = DeltaSource(log)
    _, cur = drain(source)
    DeleteCommand(log, None).run()
    with pytest.raises(DeltaIllegalStateError):
        drain(source, cur)


def test_source_ignore_deletes(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2]})
    write(log, {"id": [3]})
    source = DeltaSource(log, ignore_deletes=True)
    _, cur = drain(source)
    DeleteCommand(log, None).run()
    write(log, {"id": [9]})
    batches, _ = drain(source, cur)
    assert batches == [[9]]


def test_source_update_requires_ignore_changes(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2], "v": [1, 1]})
    source = DeltaSource(log)
    _, cur = drain(source)
    UpdateCommand(log, {"v": "2"}, condition="id = 1").run()
    with pytest.raises(DeltaIllegalStateError):
        drain(source, cur)
    # with ignoreChanges the rewritten file is re-emitted
    source2 = DeltaSource(log, ignore_changes=True)
    _, cur2 = drain(source2)
    UpdateCommand(log, {"v": "3"}, condition="id = 1").run()
    batches, _ = drain(source2, cur2)
    assert batches == [[1, 2]]  # whole rewritten file re-emitted


def test_source_schema_change_fails(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1]})
    source = DeltaSource(log)
    _, cur = drain(source)
    write(log, {"id": [2], "extra": ["x"]}, merge_schema=True)
    with pytest.raises(DeltaIllegalStateError):
        drain(source, cur)


def test_offset_json_roundtrip_and_table_id_check():
    off = DeltaSourceOffset(7, 3, True, "tbl-1")
    back = DeltaSourceOffset.from_json(off.json(), "tbl-1")
    assert back == off
    with pytest.raises(DeltaIllegalStateError):
        DeltaSourceOffset.from_json(off.json(), "other-table")


def test_sink_exactly_once(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    sink = DeltaSink(log, query_id="q1")
    assert sink.add_batch(0, {"id": [1]}) is True
    assert sink.add_batch(0, {"id": [1]}) is False  # replay skipped
    assert sink.add_batch(1, {"id": [2]}) is True
    assert sorted(scan_to_table(log.update()).column("id").to_pylist()) == [1, 2]


def test_sink_complete_mode(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    sink = DeltaSink(log, query_id="q1", output_mode="complete")
    sink.add_batch(0, {"id": [1, 2]})
    sink.add_batch(1, {"id": [9]})
    assert scan_to_table(log.update()).column("id").to_pylist() == [9]


def test_query_end_to_end_and_restart(tmp_table, tmp_path):
    src_log = DeltaLog.for_table(tmp_table)
    dst_path = str(tmp_path / "dst")
    ckpt = str(tmp_path / "ckpt")
    write(src_log, {"id": [1, 2]})

    def run_query():
        dst_log = DeltaLog.for_table(dst_path)
        source = DeltaSource(src_log, max_files_per_trigger=1)
        q = StreamingQuery(source, DeltaSink(dst_log, query_id="qx"), ckpt)
        return q.process_all_available()

    assert run_query() == 1
    assert sorted(
        scan_to_table(DeltaLog.for_table(dst_path).update()).column("id").to_pylist()
    ) == [1, 2]
    # new upstream commits; a fresh query object resumes from the checkpoint
    write(src_log, {"id": [3]})
    write(src_log, {"id": [4]})
    # one empty snapshot→tail transition batch + one file per trigger
    assert run_query() == 3
    assert sorted(
        scan_to_table(DeltaLog.for_table(dst_path).update()).column("id").to_pylist()
    ) == [1, 2, 3, 4]
    # drained: no more batches, no duplicates
    assert run_query() == 0
    assert sorted(
        scan_to_table(DeltaLog.for_table(dst_path).update()).column("id").to_pylist()
    ) == [1, 2, 3, 4]


def test_query_recovers_unfinished_batch(tmp_table, tmp_path):
    import os

    src_log = DeltaLog.for_table(tmp_table)
    dst_path = str(tmp_path / "dst")
    ckpt = str(tmp_path / "ckpt")
    write(src_log, {"id": [1]})

    source = DeltaSource(src_log)
    dst_log = DeltaLog.for_table(dst_path)
    q = StreamingQuery(source, DeltaSink(dst_log, query_id="qy"), ckpt)
    q.process_all_available()
    # simulate crash after writing the offset but before running batch 1
    write(src_log, {"id": [2]})
    end = source.latest_offset(q._read_offset(0))
    q._write_offset(1, end)
    # restart: the planned batch must run exactly once
    q2 = StreamingQuery(
        DeltaSource(src_log), DeltaSink(dst_log, query_id="qy"), ckpt
    )
    ran = q2.process_all_available()
    assert ran == 2  # recovered transition batch + the data batch
    assert sorted(
        scan_to_table(DeltaLog.for_table(dst_path).update()).column("id").to_pylist()
    ) == [1, 2]


# -- review regressions -----------------------------------------------------


def test_source_rearrange_only_commit_does_not_spin(tmp_table):
    from delta_tpu.commands.optimize import OptimizeCommand

    log = DeltaLog.for_table(tmp_table)
    for i in range(3):
        write(log, {"id": [i]})
    source = DeltaSource(log, ignore_changes=True)
    _, cur = drain(source)
    OptimizeCommand(log).run()  # dataChange=False commit
    # the offset advances past the data-less commit exactly once, then stops
    end = source.latest_offset(cur)
    if end is not None:
        assert source.latest_offset(end) is None
        assert source.get_batch(cur, end).num_rows == 0


def test_query_recovery_of_initial_snapshot_batch(tmp_table, tmp_path):
    src_log = DeltaLog.for_table(tmp_table)
    dst_path = str(tmp_path / "dst")
    ckpt = str(tmp_path / "ckpt")
    write(src_log, {"id": [1, 2]})

    # plan batch 0 (initial snapshot) but crash before running it
    source = DeltaSource(src_log)
    q = StreamingQuery(source, DeltaSink(DeltaLog.for_table(dst_path), query_id="qz"), ckpt)
    end0 = source.latest_offset(source.initial_offset())
    q._write_offset(0, end0)
    # upstream moves on before the restart
    write(src_log, {"id": [3]})
    q2 = StreamingQuery(
        DeltaSource(src_log), DeltaSink(DeltaLog.for_table(dst_path), query_id="qz"), ckpt
    )
    q2.process_all_available()
    got = sorted(
        scan_to_table(DeltaLog.for_table(dst_path).update()).column("id").to_pylist()
    )
    assert got == [1, 2, 3]  # snapshot rows must NOT be lost


# -- depth: options, restarts, data loss (≈ DeltaSourceSuite's long tail) ----


def test_source_exclude_regex(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1]})
    source = DeltaSource(log, exclude_regex=r"never-matches")
    batches, cur = drain(source)
    assert batches == [[1]]
    # a regex matching every file excludes the data entirely
    source2 = DeltaSource(log, exclude_regex=r"part-")
    batches2, _ = drain(source2)
    assert batches2 == []


def test_source_starting_version_latest_skips_everything(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1]})
    write(log, {"id": [2]})
    source = DeltaSource(log, starting_version="latest")
    start = source.initial_offset()  # pin "latest" once, like an engine would
    batches, cur = drain(source, start)
    assert batches == []
    write(log, {"id": [3]})
    batches, _ = drain(source, cur if cur is not None else start)
    assert batches == [[3]]


def test_source_starting_timestamp(tmp_table):
    import os

    from delta_tpu.protocol import filenames

    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1]})   # v0
    write(log, {"id": [2]})   # v1
    base = 1_700_000_000_000
    for v in (0, 1):
        p = f"{log.log_path}/{filenames.delta_file(v)}"
        os.utime(p, ((base + v * 3_600_000) / 1000,) * 2)
    source = DeltaSource(log, starting_timestamp=base + 60_000)
    batches, _ = drain(source)
    # starts at the active commit at that time (v0) -> tails v0..v1
    assert sorted(x for b in batches for x in b) == [1, 2]


def test_source_max_bytes_admission_on_tail_path(tmp_table):
    """Byte-based admission must also apply in TAIL mode (_changes_from):
    the sibling test at line 66 covers the initial-snapshot path; with
    starting_version=0 every file arrives through the log tail instead."""
    log = DeltaLog.for_table(tmp_table)
    for i in range(3):
        write(log, {"id": [i]})
    source = DeltaSource(log, starting_version=0, max_files_per_trigger=None,
                         max_bytes_per_trigger=1)
    batches, _ = drain(source)
    assert batches == [[0], [1], [2]]


def test_source_data_loss_detection(tmp_table):
    import os

    from delta_tpu.protocol import filenames

    log = DeltaLog.for_table(tmp_table)
    for i in range(3):
        write(log, {"id": [i]})
    log.checkpoint()
    os.remove(f"{log.log_path}/{filenames.delta_file(0)}")
    os.remove(f"{log.log_path}/{filenames.delta_file(1)}")
    DeltaLog.clear_cache()
    log2 = DeltaLog.for_table(tmp_table)
    strict = DeltaSource(log2, starting_version=0, fail_on_data_loss=True)
    with pytest.raises(DeltaIllegalStateError):
        drain(strict)
    lax = DeltaSource(log2, starting_version=0, fail_on_data_loss=False)
    batches, _ = drain(lax)
    assert batches == [[2]]  # resumes at what's left, no error


def test_source_concurrent_appends_between_batches(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1]})
    source = DeltaSource(log, max_files_per_trigger=1)
    cur = source.initial_offset()
    end = source.latest_offset(cur)
    # writer races in BEFORE the first get_batch
    write(log, {"id": [2]})
    t = source.get_batch(None, end)
    assert sorted(t.column("id").to_pylist()) == [1], (
        "a planned batch must serve exactly its planned offset range"
    )
    batches, _ = drain(source, end)
    assert batches == [[2]]


def test_offset_ordering_never_regresses(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    for i in range(3):
        write(log, {"id": [i]})
    source = DeltaSource(log, max_files_per_trigger=1)
    cur = source.initial_offset()
    seen = []
    while True:
        end = source.latest_offset(cur)
        if end is None:
            break
        seen.append((end.reservoir_version, end.index))
        cur = end
    assert seen == sorted(seen)
    assert len(set(seen)) == len(seen)


def test_sink_append_then_read_back_via_source(tmp_table, tmp_path):
    src_path = str(tmp_path / "src")
    src_log = DeltaLog.for_table(src_path)
    write(src_log, {"id": [1, 2, 3]})
    sink_log = DeltaLog.for_table(tmp_table)
    sink = DeltaSink(sink_log, query_id="sink-rb")
    source = DeltaSource(src_log)
    cur = source.initial_offset()
    end = source.latest_offset(cur)
    sink.add_batch(0, source.get_batch(None, end))
    got = scan_to_table(sink_log.update())
    assert sorted(got.column("id").to_pylist()) == [1, 2, 3]
    # replaying the same batch id is a no-op (exactly-once)
    sink.add_batch(0, source.get_batch(None, end))
    assert scan_to_table(sink_log.update()).num_rows == 3


def test_sink_schema_widens_with_merge_schema(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    sink = DeltaSink(log, query_id="sink-ms", merge_schema=True)
    sink.add_batch(0, pa.table({"id": pa.array([1], pa.int64())}))
    sink.add_batch(1, pa.table({
        "id": pa.array([2], pa.int64()),
        "extra": pa.array(["e"]),
    }))
    got = scan_to_table(log.update())
    assert "extra" in got.column_names


def test_query_restart_does_not_duplicate_mid_tail(tmp_table, tmp_path):
    """Crash after commit-but-before-offset-persist must not double-write
    (the sink's SetTransaction guard)."""
    src_path = str(tmp_path / "src2")
    src_log = DeltaLog.for_table(src_path)
    write(src_log, {"id": [1]})
    ckpt = str(tmp_path / "ckpt")
    q = StreamingQuery(DeltaSource(src_log),
                       DeltaSink(DeltaLog.for_table(tmp_table), query_id="q-dup"),
                       ckpt)
    q.process_all_available()
    write(src_log, {"id": [2]})
    q.process_all_available()
    got = scan_to_table(DeltaLog.for_table(tmp_table).update())
    assert sorted(got.column("id").to_pylist()) == [1, 2]
    # simulate the crash window: the sink committed the last batch but the
    # query died before writing its commits/<batchId> marker — delete the
    # marker so the restart re-runs that batch against the sink
    import os

    markers = sorted(os.listdir(os.path.join(ckpt, "commits")), key=int)
    os.remove(os.path.join(ckpt, "commits", markers[-1]))
    q2 = StreamingQuery(DeltaSource(src_log),
                        DeltaSink(DeltaLog.for_table(tmp_table), query_id="q-dup"),
                        ckpt)
    assert q2.process_all_available() >= 1  # the batch re-runs...
    got = scan_to_table(DeltaLog.for_table(tmp_table).update())
    assert sorted(got.column("id").to_pylist()) == [1, 2]
