"""Multi-host coordination: DCN-level fan-out around the ICI mesh.

SURVEY §2.8's distribution model, made explicit. The reference's data plane
fans out over Spark executors with driver⇄executor RPC; here the equivalent
split is:

* **intra-slice (ICI)** — `jax.lax` collectives under `shard_map` over the
  device mesh (`parallel/mesh.py`): the replay, join, and skipping kernels.
* **inter-host (DCN)** — `jax.distributed` + the deterministic per-host
  work partitioner below: every host computes the same assignment with no
  RPC — strided by default, size-weighted LPT when byte weights are known
  (see :func:`lpt_assign`). Consumers: VACUUM's delete fan-out (`commands/vacuum.py`),
  multi-host scan decode (`exec/scan.read_files_as_table(distribute=True)`),
  checkpoint part writing (`log/checkpoints.write_checkpoint` — proc 0
  publishes `_last_checkpoint` after all hosts' parts are visible), and
  CONVERT's footer/stats collection (`commands/convert.py` — fragments
  exchanged through the shared store, proc 0 commits). A real 2-process
  `jax.distributed` cluster exercises all of these in
  `tests/test_multihost.py`.
* **control plane** — unchanged from single-host: commits still serialize
  through the LogStore's atomic create, which is host-agnostic. There is
  deliberately no lock service (the reference's stance,
  `storage/LogStore.scala:30-43`).

On a single host every function degrades to a no-op/identity, so the same
program runs unchanged from a laptop to a multi-host slice.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

__all__ = [
    "initialize",
    "process_info",
    "host_partition",
    "host_shard_indices",
    "lpt_assign",
    "lpt_loads",
    "bytes_skew",
]


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> Tuple[int, int]:
    """Join the multi-host runtime; returns (process_id, num_processes).

    With explicit arguments they are passed through. With none,
    `jax.distributed.initialize()` is attempted bare so its cluster
    AUTO-DETECTION (Cloud TPU metadata, SLURM, GKE) still applies; when no
    cluster environment is detected this degrades to single-host (0, 1)
    instead of raising — safe to call unconditionally at engine startup.
    """
    import jax

    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (RuntimeError, ValueError):
        if coordinator_address is not None or num_processes not in (None, 1):
            raise  # explicitly-requested cluster must not silently degrade
        return 0, 1
    return jax.process_index(), jax.process_count()


def process_info() -> Tuple[int, int]:
    """(process_index, process_count) of the current runtime — (0, 1) when
    no multi-host runtime was initialized."""
    import jax

    try:
        return jax.process_index(), jax.process_count()
    except RuntimeError:  # backend not initialized yet
        return 0, 1


def lpt_assign(sizes: Sequence[int], count: int) -> List[List[int]]:
    """Deterministic size-weighted LPT (longest-processing-time) assignment
    of ``len(sizes)`` items over ``count`` hosts; returns per-host item-index
    lists (each sorted ascending).

    The strided partition balances item *counts*; on a zipf-skewed file
    list one host inherits the hot shard's bytes and the whole job waits on
    it. LPT sorts by size descending (ties broken by index, so every host
    computes the identical assignment with no RPC) and gives each item to
    the currently least-loaded host (ties broken by host id) — the classic
    4/3-approximation to makespan, which is what a stride can't bound.
    """
    if count <= 1:
        return [list(range(len(sizes)))]
    loads = [0] * count
    buckets: List[List[int]] = [[] for _ in range(count)]
    order = sorted(range(len(sizes)), key=lambda j: (-int(sizes[j] or 0), j))
    for j in order:
        h = min(range(count), key=lambda i: (loads[i], i))
        loads[h] += int(sizes[j] or 0)
        buckets[h].append(j)
    for b in buckets:
        b.sort()
    return buckets


def lpt_loads(sizes: Sequence[int],
              assignment: Sequence[Sequence[int]]) -> List[int]:
    """Per-bin byte loads of an assignment — the LPT-predicted cost shares.
    The executor stamps these on its ``delta.dist.job`` span and the trace
    analyzer (`obs/trace_store.analyze_trace`) diffs each worker's measured
    busy time against its share, so a straggler shard is attributable to
    either byte skew (predicted) or per-byte slowness (not predicted)."""
    return [sum(int(sizes[j] or 0) for j in b) for b in assignment]


def bytes_skew(sizes: Sequence[int], assignment: Sequence[Sequence[int]]) -> float:
    """max/mean per-host bytes ratio of an assignment — 1.0 is perfectly
    balanced; the zipf-100k regression gate in tests/bench watches this."""
    per_host = lpt_loads(sizes, assignment)
    if not per_host or sum(per_host) == 0:
        return 1.0
    mean = sum(per_host) / len(per_host)
    return max(per_host) / mean if mean else 1.0


def host_shard_indices(n_items: int, index: Optional[int] = None,
                       count: Optional[int] = None,
                       sizes: Optional[Sequence[int]] = None) -> List[int]:
    """This host's item positions in a global work list.

    Without ``sizes``: deterministic strided partition — host i takes items
    i, i+n, i+2n, … Every host computes the same assignment with no RPC,
    the DCN-free analogue of the reference's driver→executor task
    scheduling. With ``sizes`` (per-item byte weights): size-weighted LPT
    via :func:`lpt_assign`, still deterministic and RPC-free, so a
    zipf-skewed file list can't hand one host the hot shard's bytes.

    ``index``/``count`` must be given together (or neither, to use the
    runtime's process info).
    """
    if (index is None) != (count is None):
        raise ValueError("host partitioning needs both index and count (or neither)")
    if index is None:
        index, count = process_info()
    if count <= 1:
        return list(range(n_items))
    if sizes is not None:
        if len(sizes) != n_items:
            raise ValueError(
                f"sizes has {len(sizes)} entries for {n_items} items")
        return lpt_assign(sizes, count)[index]
    return list(range(index, n_items, count))


def host_partition(items: Sequence, index: Optional[int] = None,
                   count: Optional[int] = None,
                   sizes: Optional[Sequence[int]] = None) -> List:
    """This host's slice of a global work list (see
    :func:`host_shard_indices` for the assignment rule)."""
    return [items[j] for j in host_shard_indices(len(items), index, count,
                                                 sizes=sizes)]
