"""OCC conflict checker.

Reference: ``OptimisticTransaction.checkForConflicts``
(``OptimisticTransaction.scala:733-859``). After losing the race to write
``<v>.json``, replay each winning commit and decide whether this transaction's
reads/writes are still valid; if so, retry at the next version.

Conflict matrix (winning commit → our txn):
  * Protocol action               → ProtocolChangedException (always)
  * Metadata action               → MetadataChangedException (always)
  * AddFiles matching our reads   → ConcurrentAppendException
      - under Serializable: all winning adds are checked
      - under WriteSerializable: blind-append commits are exempt
      - under SnapshotIsolation: never checked
  * RemoveFile of a file we read  → ConcurrentDeleteReadException
  * RemoveFile of a file we also remove → ConcurrentDeleteDeleteException
  * SetTransaction appId we read  → ConcurrentTransactionException
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set

from delta_tpu.expr import partition as part
from delta_tpu.protocol.actions import (
    Action,
    AddFile,
    CommitInfo,
    Metadata,
    Protocol,
    RemoveFile,
    SetTransaction,
)
from delta_tpu.txn import isolation
from delta_tpu.utils import errors

__all__ = ["WinningCommitSummary", "check_for_conflicts"]


@dataclass
class WinningCommitSummary:
    version: int
    actions: List[Action]
    protocol: Optional[Protocol] = None
    metadata_updates: List[Metadata] = field(default_factory=list)
    added_files: List[AddFile] = field(default_factory=list)
    removed_files: List[RemoveFile] = field(default_factory=list)
    txns: List[SetTransaction] = field(default_factory=list)
    commit_info: Optional[CommitInfo] = None

    @staticmethod
    def of(version: int, actions: Sequence[Action]) -> "WinningCommitSummary":
        s = WinningCommitSummary(version, list(actions))
        for a in actions:
            if isinstance(a, Protocol):
                s.protocol = a
            elif isinstance(a, Metadata):
                s.metadata_updates.append(a)
            elif isinstance(a, AddFile):
                s.added_files.append(a)
            elif isinstance(a, RemoveFile):
                s.removed_files.append(a)
            elif isinstance(a, SetTransaction):
                s.txns.append(a)
            elif isinstance(a, CommitInfo):
                s.commit_info = a
        return s

    @property
    def is_blind_append(self) -> bool:
        return bool(self.commit_info and self.commit_info.is_blind_append)

    def commit_brief(self) -> Dict:
        ci = self.commit_info
        return {
            "version": self.version,
            "operation": ci.operation if ci else None,
            "timestamp": ci.timestamp if ci else None,
        }


def check_for_conflicts(txn, winning_version: int, actions: Sequence[Action]) -> None:
    """Raise a DeltaConcurrentModificationException subtype if the winning
    commit at ``winning_version`` invalidates ``txn``; return normally if the
    txn can be retried on top of it."""
    summary = WinningCommitSummary.of(winning_version, actions)
    brief = summary.commit_brief()

    # 1. Protocol changed (OptimisticTransaction.scala:763-772)
    if summary.protocol is not None:
        txn.delta_log.assert_protocol_read(summary.protocol)
        txn.delta_log.assert_protocol_write(summary.protocol)
        raise errors.protocol_changed_exception(brief)

    # 2. Metadata changed (scala:774-778)
    if summary.metadata_updates:
        raise errors.metadata_changed_exception(brief)

    # 3. Concurrent appends in regions we read (scala:795-826)
    level = txn.commit_isolation_level
    if level is isolation.Serializable:
        adds_to_check = summary.added_files
    elif level is isolation.WriteSerializable and not summary.is_blind_append:
        adds_to_check = summary.added_files
    else:
        adds_to_check = []
    if adds_to_check:
        pschema = txn.metadata.partition_schema
        conflicting: Optional[AddFile] = None
        if txn.read_the_whole_table:
            conflicting = adds_to_check[0]
        else:
            for pred in txn.read_predicates:
                for f in adds_to_check:
                    if part.matches_maybe(pred, f, pschema):
                        conflicting = f
                        break
                if conflicting:
                    break
        if conflicting is not None:
            raise errors.concurrent_append_exception(
                f"the table (for example {conflicting.path})", brief
            )

    # 4. Deleted files that we read (scala:829-839)
    read_paths: Set[str] = set(txn.read_files)
    for r in summary.removed_files:
        if r.path in read_paths or txn.read_the_whole_table:
            raise errors.concurrent_delete_read_exception(r.path, brief)

    # 5. Delete/delete overlap (scala:842-845)
    our_removed = {a.path for a in txn.staged_removes}
    for r in summary.removed_files:
        if r.path in our_removed:
            raise errors.concurrent_delete_delete_exception(r.path, brief)

    # 6. SetTransaction overlap (scala:848-852)
    read_apps = set(txn.read_txn)
    for t in summary.txns:
        if t.app_id in read_apps:
            raise errors.concurrent_transaction_exception(brief, app_id=t.app_id)
