"""Autopilot execution — run one :class:`~delta_tpu.obs.actions.
MaintenanceAction` under guardrails and report what happened.

Guardrails enforced HERE (the daemon owns scheduling-level ones):

* **bytes cost cap** — OPTIMIZE/ZORDER/PURGE run with
  ``max_rewrite_bytes`` (``delta.tpu.autopilot.maxBytesPerRun``): an
  over-budget selection raises pre-IO and comes back as a ``skipped``
  outcome, never a half-done rewrite.
* **lose-to-foreground** — table-mutating actions commit under
  :class:`~delta_tpu.txn.transaction.commit_attempts_cap`
  (``delta.tpu.autopilot.maxCommitAttempts``): a maintenance commit that
  keeps losing races aborts as ``abortedContention`` instead of
  retry-storming against foreground writers.
* **crash transparency** — only ``Exception`` is classified; a
  :class:`~delta_tpu.storage.faults.SimulatedCrash` (BaseException, a real
  process death in the torture harness) pierces to the caller, which has
  already journaled the ``started`` ledger entry durably.

The audit half: :func:`audit_metrics` names, per action kind, the doctor
dimension + metric keys whose before/after delta measures the action's
realized improvement (lower is better for every audited metric).
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

from delta_tpu.obs.actions import MaintenanceAction, spec
from delta_tpu.utils import errors, telemetry
from delta_tpu.utils.config import conf

__all__ = ["ExecutionResult", "execute", "audit_metrics", "build_audit"]


@dataclass
class ExecutionResult:
    status: str                      # executed | skipped | failed | abortedContention
    metrics: Dict[str, Any] = field(default_factory=dict)
    reason: str = ""
    error: str = ""
    duration_ms: float = 0.0

    def to_dict(self) -> Dict[str, Any]:
        out = {"status": self.status, "metrics": dict(self.metrics),
               "durationMs": round(self.duration_ms, 3)}
        if self.reason:
            out["reason"] = self.reason
        if self.error:
            out["error"] = self.error
        return out


#: action kind → (doctor dimension, audited metric keys); every audited
#: metric improves DOWNWARD (counts, staleness, pressure)
_AUDIT = {
    "OPTIMIZE": ("smallFiles", ("count", "estReduction")),
    "CHECKPOINT": ("checkpoint", ("commitsSince", "tailBytes")),
    "PURGE": ("dv", ("deletedPct", "filesPastPurge")),
    "VACUUM": ("tombstones", ("count", "bytes")),
    "EVICT": ("device", ("hbmBytes", "pressure")),
}


def audit_metrics(kind: str) -> Optional[Tuple[str, Tuple[str, ...]]]:
    """The doctor dimension + metrics auditing this action kind, or None
    for actions whose realized effect only shows up longitudinally
    (ZORDER via future scans' pruning, RECALIBRATE via router audits)."""
    return _AUDIT.get(kind)


def build_audit(action: MaintenanceAction, before, after) -> Dict[str, Any]:
    """Predicted-vs-realized audit from the doctor reports bracketing the
    action. ``before``/``after`` are :class:`TableHealthReport`\\ s (after
    may be None when the action failed before a re-measure)."""
    audit: Dict[str, Any] = {"predicted": dict(action.predicted)}
    mapped = audit_metrics(action.kind)
    if mapped is None or after is None:
        if (mapped is None and action.kind == "ZORDER"
                and conf.get_bool("delta.tpu.autopilot.shadowAudit", True)):
            # ZORDER has no doctor dimension to diff, but when a shadow
            # scorecard covered this (kind, target) its trace can replay
            # against the now-rewritten LIVE table — a measured realized
            # verdict instead of a pending longitudinal one
            try:
                from delta_tpu.replay.shadow import realized_audit

                shadow = realized_audit(action.table_path, action.kind,
                                        action.target)
            except Exception:  # noqa: BLE001 — audit must not fail the run
                shadow = None
            if shadow is not None:
                audit.update(shadow)
                audit["auditSource"] = "shadowReplay"
                return audit
        audit["verdict"] = "pending"
        audit["detail"] = ("longitudinal action: realized effect shows up "
                           "in future journal history"
                           if mapped is None else "no post-action measure")
        return audit
    dim_name, keys = mapped
    try:
        b = before.dimension(dim_name)
        a = after.dimension(dim_name)
    except KeyError:
        audit["verdict"] = "pending"
        return audit
    audit["before"] = {k: b.metrics.get(k) for k in keys}
    audit["after"] = {k: a.metrics.get(k) for k in keys}
    audit["severityBefore"] = b.severity
    audit["severityAfter"] = a.severity
    realized: Dict[str, Any] = {}
    improved = worse = False
    for k in keys:
        bv, av = b.metrics.get(k), a.metrics.get(k)
        if isinstance(bv, (int, float)) and isinstance(av, (int, float)):
            realized[k] = round(bv - av, 6)  # positive = improvement
            improved = improved or av < bv
            worse = worse or av > bv
    from delta_tpu.obs.doctor import SEVERITY_RANK

    if SEVERITY_RANK[a.severity] < SEVERITY_RANK[b.severity]:
        improved = True
    audit["realized"] = realized
    audit["verdict"] = ("improved" if improved and not worse
                        else "worse" if worse and not improved
                        else "mixed" if improved
                        else "unchanged")
    return audit


# ---------------------------------------------------------------------------
# Per-kind execution
# ---------------------------------------------------------------------------


def _run_optimize(delta_log, action: MaintenanceAction,
                  max_bytes: Optional[int]) -> Dict[str, Any]:
    from delta_tpu.commands.optimize import OptimizeCommand

    kwargs: Dict[str, Any] = {"max_rewrite_bytes": max_bytes}
    if action.kind == "ZORDER":
        kwargs["z_order_by"] = list(action.params.get("columns") or [])
    elif action.kind == "PURGE":
        kwargs["purge"] = True
    cmd = OptimizeCommand(delta_log, **kwargs)
    cmd.run()
    return dict(cmd.metrics)


def _run_checkpoint(delta_log) -> Dict[str, Any]:
    meta = delta_log.checkpoint()
    return {"checkpointVersion": getattr(meta, "version", None)}


def _run_vacuum(delta_log) -> Dict[str, Any]:
    from delta_tpu.commands.vacuum import VacuumCommand

    res = VacuumCommand(delta_log).run()
    return {"filesDeleted": res.files_deleted, "dirsDeleted": res.dirs_deleted}


def _run_evict() -> Dict[str, Any]:
    from delta_tpu.obs import hbm_ledger

    before = hbm_ledger.totals()["total"]
    applied = hbm_ledger.maybe_relieve()
    after = hbm_ledger.totals()["total"]
    return {"pressureApplied": bool(applied),
            "bytesBefore": before, "bytesAfter": after,
            "bytesFreed": max(0, before - after)}


def _run_recalibrate(delta_log) -> Dict[str, Any]:
    from delta_tpu.obs import calibration

    state = calibration.apply_state(delta_log.log_path)
    return {"calibrationEnabled": calibration.enabled(),
            "constantsInstalled": len(state)}


def execute(delta_log, action: MaintenanceAction,
            max_bytes: Optional[int] = None,
            attempts_cap: Optional[int] = None) -> ExecutionResult:
    """Execute one action against ``delta_log``. Classifies Exceptions into
    skipped (over budget) / abortedContention (lost to a foreground
    writer) / failed; BaseException (simulated or real process death)
    propagates — the caller journaled ``started`` durably first."""
    from delta_tpu.commands.optimize import OptimizeBudgetExceeded
    from delta_tpu.txn.transaction import commit_attempts_cap

    kind = spec(action.kind)
    t0 = time.monotonic()

    def _done(status: str, metrics: Dict[str, Any], **kw) -> ExecutionResult:
        return ExecutionResult(status=status, metrics=metrics,
                               duration_ms=(time.monotonic() - t0) * 1000.0,
                               **kw)

    try:
        with commit_attempts_cap(attempts_cap if kind.mutates_table else None):
            if action.kind in ("OPTIMIZE", "ZORDER", "PURGE"):
                metrics = _run_optimize(delta_log, action, max_bytes)
            elif action.kind == "CHECKPOINT":
                metrics = _run_checkpoint(delta_log)
            elif action.kind == "VACUUM":
                metrics = _run_vacuum(delta_log)
            elif action.kind == "EVICT":
                metrics = _run_evict()
            elif action.kind == "RECALIBRATE":
                metrics = _run_recalibrate(delta_log)
            else:
                return _done("skipped", {},
                             reason=f"action {action.kind} is not executable")
    except OptimizeBudgetExceeded as e:
        telemetry.bump_counter("autopilot.actions.skipped")
        return _done("skipped",
                     {"estBytes": e.est_bytes, "capBytes": e.cap_bytes,
                      "files": e.files},
                     reason="over maxBytesPerRun cost cap")
    except (errors.DeltaConcurrentModificationException,
            errors.CommitAttemptsExhausted) as e:
        telemetry.bump_counter("autopilot.contentionAborts")
        return _done("abortedContention", {},
                     reason="lost to a foreground writer",
                     error=f"{type(e).__name__}: {e}")
    except Exception as e:  # noqa: BLE001 — classified: genuine failure
        telemetry.bump_counter("autopilot.actions.failed")
        return _done("failed", {}, error=f"{type(e).__name__}: {e}")
    telemetry.bump_counter("autopilot.actions.executed")
    return _done("executed", metrics)
