"""≈ ``FileNamesSuite``."""
import pytest

from delta_tpu.protocol import filenames as fn


def test_delta_file():
    assert fn.delta_file(12) == "00000000000000000012.json"
    assert fn.is_delta_file("00000000000000000012.json")
    assert not fn.is_delta_file("12.json.tmp")
    assert fn.delta_version("/a/b/_delta_log/00000000000000000012.json") == 12


def test_checkpoint_single():
    assert fn.checkpoint_file_single(3) == "00000000000000000003.checkpoint.parquet"
    assert fn.is_checkpoint_file("00000000000000000003.checkpoint.parquet")
    assert fn.checkpoint_version("00000000000000000003.checkpoint.parquet") == 3
    assert fn.checkpoint_part("00000000000000000003.checkpoint.parquet") is None


def test_checkpoint_multipart():
    parts = fn.checkpoint_file_with_parts(5, 3)
    assert parts == [
        "00000000000000000005.checkpoint.0000000001.0000000003.parquet",
        "00000000000000000005.checkpoint.0000000002.0000000003.parquet",
        "00000000000000000005.checkpoint.0000000003.0000000003.parquet",
    ]
    assert fn.checkpoint_part(parts[1]) == (2, 3)
    assert fn.checkpoint_version(parts[2]) == 5


def test_checksum():
    assert fn.checksum_file(7) == "00000000000000000007.crc"
    assert fn.is_checksum_file("00000000000000000007.crc")
    assert fn.checksum_version("00000000000000000007.crc") == 7


def test_get_file_version():
    assert fn.get_file_version("00000000000000000009.json") == 9
    assert fn.get_file_version("00000000000000000009.crc") == 9
    assert fn.get_file_version("_last_checkpoint") is None


def test_version_prefix_ordering():
    # zero padding makes lexicographic == numeric ordering
    assert fn.delta_file(9) < fn.delta_file(10) < fn.delta_file(100)
