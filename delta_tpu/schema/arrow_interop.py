"""Arrow ⇄ Delta schema conversion.

The reference converts between Spark `StructType` and Parquet schemas inside
Spark; here the engine's interchange format is Arrow, so schema inference for
new tables (`schema/ImplicitMetadataOperation.scala:30-62`) starts from a
`pyarrow.Schema`.
"""
from __future__ import annotations

import pyarrow as pa

from delta_tpu.schema.types import (
    ArrayType,
    BinaryType,
    BooleanType,
    ByteType,
    DataType,
    DateType,
    DecimalType,
    DoubleType,
    FloatType,
    IntegerType,
    LongType,
    MapType,
    ShortType,
    StringType,
    StructField,
    StructType,
    TimestampType,
)
from delta_tpu.utils.errors import DeltaAnalysisError, SchemaMismatchError
from delta_tpu.utils import errors

__all__ = ["delta_type_from_arrow", "schema_from_arrow"]


def delta_type_from_arrow(t: pa.DataType) -> DataType:
    if pa.types.is_boolean(t):
        return BooleanType()
    if pa.types.is_int8(t):
        return ByteType()
    if pa.types.is_int16(t):
        return ShortType()
    if pa.types.is_int32(t) or pa.types.is_uint8(t) or pa.types.is_uint16(t):
        return IntegerType()
    if pa.types.is_uint64(t):
        # uint64 values >= 2^63 cannot round-trip through LongType; reject
        # here rather than fail with a confusing cast error at write time
        raise SchemaMismatchError(
            "uint64 columns are not supported (Delta long is signed 64-bit); "
            "cast to int64 or decimal first"
        )
    if pa.types.is_int64(t) or pa.types.is_uint32(t):
        return LongType()
    if pa.types.is_float32(t) or pa.types.is_float16(t):
        return FloatType()
    if pa.types.is_float64(t):
        return DoubleType()
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return StringType()
    if pa.types.is_binary(t) or pa.types.is_large_binary(t):
        return BinaryType()
    if pa.types.is_date(t):
        return DateType()
    if pa.types.is_timestamp(t):
        return TimestampType()
    if pa.types.is_decimal(t):
        return DecimalType(t.precision, t.scale)
    if pa.types.is_list(t) or pa.types.is_large_list(t):
        return ArrayType(delta_type_from_arrow(t.value_type))
    if pa.types.is_map(t):
        return MapType(delta_type_from_arrow(t.key_type), delta_type_from_arrow(t.item_type))
    if pa.types.is_struct(t):
        return StructType(
            [
                StructField(t.field(i).name, delta_type_from_arrow(t.field(i).type), t.field(i).nullable)
                for i in range(t.num_fields)
            ]
        )
    if pa.types.is_null(t):
        return StringType()  # all-null columns default to string, like Spark
    raise errors.unsupported_arrow_type(t)


def schema_from_arrow(schema: pa.Schema) -> StructType:
    return StructType(
        [StructField(f.name, delta_type_from_arrow(f.type), f.nullable) for f in schema]
    )
