"""delta-tpu: a TPU-native lakehouse framework.

Same capabilities as Delta Lake (reference mounted at ``/root/reference``):
an ACID transaction log over Parquet with optimistic concurrency, snapshot
isolation, time travel, schema enforcement/evolution, constraints, streaming
source/sink, and MERGE/UPDATE/DELETE/VACUUM — with the data plane rebuilt
for TPUs on JAX/XLA (sharded log replay, device-evaluated data skipping,
columnar MERGE kernels) instead of Spark. The on-disk transaction-log format
is byte-compatible with the Delta protocol.
"""

__version__ = "0.1.0"

from delta_tpu.log.deltalog import DeltaLog  # noqa: F401
from delta_tpu.utils.config import conf  # noqa: F401

__all__ = ["DeltaLog", "conf", "__version__"]
