"""Hardening: corruption recovery, cross-process races, reverse-goldens.

The reference's resilience behaviors this suite pins:
- corrupted/partial ``_last_checkpoint`` → fall back to listing
  (``Checkpoints.scala:152-175``);
- corrupt checkpoint parquet → recover from an earlier checkpoint or full
  JSON replay (``SnapshotManagement.scala:118-126`` re-listing);
- multi-*process* commit mutual exclusion through ``LocalLogStore``'s
  atomic create (the LogStore contract, ``storage/LogStore.scala:30-43``);
- reading tables written by the real reference implementation (golden
  fixtures under ``core/src/test/resources/delta/``).
"""
import glob
import json
import os
import subprocess
import sys

import numpy as np
import pyarrow as pa
import pytest

from delta_tpu import DeltaLog
from delta_tpu.commands.write import WriteIntoDelta


def _build(tmp_path, n_commits=13):
    path = str(tmp_path / "t")
    log = DeltaLog.for_table(path)
    for i in range(n_commits):
        WriteIntoDelta(log, "append", pa.table({"a": [i]})).run()
    return path, log


def _reload(path):
    DeltaLog.clear_cache()
    return DeltaLog.for_table(path).update()


# -- checkpoint corruption ---------------------------------------------------


def test_garbage_last_checkpoint_falls_back_to_listing(tmp_path):
    path, log = _build(tmp_path)
    lc = os.path.join(path, "_delta_log", "_last_checkpoint")
    assert os.path.exists(lc)
    with open(lc, "w") as f:
        f.write("{ NOT JSON !!!")
    snap = _reload(path)
    assert snap.version == 12
    assert len(snap.all_files) == 13


def test_truncated_last_checkpoint_falls_back(tmp_path):
    path, log = _build(tmp_path)
    lc = os.path.join(path, "_delta_log", "_last_checkpoint")
    with open(lc, "r+b") as f:
        f.truncate(os.path.getsize(lc) // 2)
    snap = _reload(path)
    assert snap.version == 12 and len(snap.all_files) == 13


def test_truncated_checkpoint_part_recovers_from_deltas(tmp_path):
    path, log = _build(tmp_path)
    cks = glob.glob(os.path.join(path, "_delta_log", "*.checkpoint*"))
    assert cks, "expected a checkpoint at version 10"
    with open(cks[0], "r+b") as f:
        f.truncate(os.path.getsize(cks[0]) // 2)
    snap = _reload(path)
    assert snap.version == 12
    assert len(snap.all_files) == 13
    assert snap.metadata is not None


def test_corrupt_checkpoint_recovers_to_earlier_checkpoint(tmp_path):
    # two checkpoints (v10 and v20); corrupt the later one: recovery should
    # land on v10's checkpoint + deltas 11..22 rather than a full replay
    path, log = _build(tmp_path, n_commits=23)
    cks = sorted(glob.glob(os.path.join(path, "_delta_log", "*.checkpoint*")))
    assert len(cks) == 2
    with open(cks[-1], "r+b") as f:
        f.truncate(10)
    snap = _reload(path)
    assert snap.version == 22
    assert len(snap.all_files) == 23
    assert snap.segment.checkpoint_version == 10


def test_zero_byte_checkpoint_ignored_at_listing(tmp_path):
    path, log = _build(tmp_path)
    cks = glob.glob(os.path.join(path, "_delta_log", "*.checkpoint*"))
    with open(cks[0], "w"):
        pass  # zero bytes: filtered out during listing, full replay instead
    snap = _reload(path)
    assert snap.version == 12 and len(snap.all_files) == 13


def test_unknown_future_action_lines_ignored(tmp_path):
    path, log = _build(tmp_path, n_commits=3)
    with open(os.path.join(path, "_delta_log",
                           "00000000000000000003.json"), "w") as f:
        f.write(json.dumps({"futureAction": {"x": 1}}) + "\n")
        f.write(json.dumps({"add": {
            "path": "extra.parquet", "partitionValues": {}, "size": 1,
            "modificationTime": 0, "dataChange": True}}) + "\n")
    snap = _reload(path)
    assert snap.version == 3
    assert len(snap.all_files) == 4


def test_recovered_snapshot_survives_update_early_exit(tmp_path):
    # after recovery, update() must early-exit on the recovered segment, not
    # re-run the decode-fail-recover cycle every poll
    path, log = _build(tmp_path)
    cks = glob.glob(os.path.join(path, "_delta_log", "*.checkpoint*"))
    with open(cks[0], "r+b") as f:
        f.truncate(10)
    DeltaLog.clear_cache()
    log2 = DeltaLog.for_table(path)
    snap = log2.update()
    assert len(snap.all_files) == 13  # triggers recovery
    again = log2.update()
    assert again is snap  # early-exit returned the cached snapshot


def test_corrupt_delta_json_is_not_blamed_on_checkpoint(tmp_path):
    # a truncated delta JSON must surface as its own error, not silently
    # exclude the (healthy) checkpoint
    path, log = _build(tmp_path)
    delta12 = os.path.join(path, "_delta_log", "00000000000000000012.json")
    with open(delta12, "r+b") as f:
        f.truncate(os.path.getsize(delta12) // 2)
    DeltaLog.clear_cache()
    log2 = DeltaLog.for_table(path)
    with pytest.raises(Exception):
        log2.update().all_files
    assert not log2.corrupt_checkpoints


# -- cross-process commit race ----------------------------------------------


_RACE_SCRIPT = """
import sys
sys.path.insert(0, {repo!r})
from delta_tpu.storage.logstore import LocalLogStore
try:
    LocalLogStore().write({target!r}, ["{{}}"])
    print("WIN")
except FileExistsError:
    print("LOSE")
"""


def test_multiprocess_commit_race_exactly_one_winner(tmp_path):
    path, log = _build(tmp_path, n_commits=1)
    target = os.path.join(path, "_delta_log", "00000000000000000001.json")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = _RACE_SCRIPT.format(repo=repo, target=target)
    procs = [
        subprocess.Popen([sys.executable, "-c", script],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        for _ in range(8)
    ]
    outs = [p.communicate()[0].decode().strip() for p in procs]
    assert outs.count("WIN") == 1, outs
    assert outs.count("LOSE") == 7, outs


# -- reverse-goldens: tables written by the reference ------------------------

GOLDEN_ROOT = "/root/reference/core/src/test/resources/delta"
needs_goldens = pytest.mark.skipif(
    not os.path.isdir(GOLDEN_ROOT), reason="reference goldens not mounted"
)


@needs_goldens
def test_golden_delta_0_1_0_snapshot():
    log = DeltaLog.for_table(os.path.join(GOLDEN_ROOT, "delta-0.1.0"))
    snap = log.update()
    assert snap.version == 3
    assert len(snap.all_files) == 3
    assert [f.name for f in snap.metadata.schema.fields] == ["id", "value"]


@needs_goldens
def test_golden_delta_0_1_0_time_travel_and_history():
    DeltaLog.clear_cache()
    log = DeltaLog.for_table(os.path.join(GOLDEN_ROOT, "delta-0.1.0"))
    log.update()
    for v in range(4):
        snap = log.get_snapshot_at(v)
        assert snap.version == v
    hist = log.history.get_history()
    assert len(hist) == 4


@needs_goldens
def test_golden_generated_columns_metadata_roundtrip():
    from delta_tpu.schema.generated import generation_expressions

    path = os.path.join(GOLDEN_ROOT, "dbr_8_1_generated_columns")
    DeltaLog.clear_cache()
    log = DeltaLog.for_table(path)
    snap = log.update()
    exprs = generation_expressions(snap.metadata.schema)
    assert exprs, "expected at least one generated column in the golden table"
    # writer protocol must gate at 4 for generated columns
    assert snap.protocol.min_writer_version >= 4


@needs_goldens
def test_golden_non_generated_columns_table_reads():
    path = os.path.join(GOLDEN_ROOT, "dbr_8_0_non_generated_columns")
    DeltaLog.clear_cache()
    snap = DeltaLog.for_table(path).update()
    assert snap.metadata is not None
    assert snap.version >= 0


@needs_goldens
def test_golden_history_0_2_0_checkpointed_log():
    path = os.path.join(GOLDEN_ROOT, "history", "delta-0.2.0")
    DeltaLog.clear_cache()
    log = DeltaLog.for_table(path)
    snap = log.update()
    assert snap.version >= 0
    assert log.history.get_history()
