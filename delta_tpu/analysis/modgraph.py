"""Per-module structural facts shared by the analysis passes.

Builds, for one :class:`~delta_tpu.analysis.core.SourceFile`:

* the set of **locks** — module globals and ``self.*`` attributes assigned
  a ``threading.Lock/RLock/Condition`` — with canonical ids that unify
  cross-module references (``dl.lock`` in ``txn/group_commit.py`` and
  ``self.lock`` in ``log/deltalog.py`` both canonicalize to
  ``DeltaLog.lock`` via the global attribute index);
* a **function index** (module functions, methods, nested defs) with
  module-local call resolution;
* per-function **events** from a held-lock-tracking walk: calls, lock
  entries, and mutations of shared state (module globals / self
  attributes), each annotated with the locks lexically held;
* **thread entry points**: ``Thread(target=...)`` targets and
  ``pool.submit/map`` callables (unwrapping ``telemetry.propagated``);
* an **effective-held** fixpoint: a private helper called only under a
  lock inherits that lock (how ``journal._write_batch`` — "callers hold
  ``_IO_LOCK``" — is seen as guarded without an annotation).

Everything here is heuristic and syntactic; the passes compensate with
inline waivers for the residue. Known imprecision: ``.acquire()`` /
``.release()`` pairs are not tracked (the engine uses ``with``), and call
resolution never crosses module boundaries.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from delta_tpu.analysis.core import AnalysisContext, SourceFile

__all__ = ["GlobalLockIndex", "ModuleGraph", "FunctionUnit", "CallEvent",
           "EnterEvent", "MutateEvent", "terminal_name", "call_name",
           "shallow_walk", "global_lock_index", "module_graph"]


def _cache(ctx: AnalysisContext) -> dict:
    cache = getattr(ctx, "_modgraph_cache", None)
    if cache is None:
        cache = {}
        setattr(ctx, "_modgraph_cache", cache)
    return cache


def global_lock_index(ctx: AnalysisContext) -> "GlobalLockIndex":
    """The context's lock index, built once and shared across passes."""
    cache = _cache(ctx)
    if "index" not in cache:
        cache["index"] = GlobalLockIndex(ctx)
    return cache["index"]


def module_graph(ctx: AnalysisContext, sf: SourceFile) -> "ModuleGraph":
    """One ModuleGraph per file per context — the held-lock walk and the
    effective-held fixpoint are the engine's dominant cost, so every
    concurrency pass shares them instead of rebuilding."""
    cache = _cache(ctx)
    if sf.rel not in cache:
        cache[sf.rel] = ModuleGraph(sf, global_lock_index(ctx))
    return cache[sf.rel]


def shallow_walk(root: ast.AST):
    """``ast.walk`` that does not descend into nested function/class/lambda
    bodies — those are separate analysis units. The root itself may be a
    function node; only *nested* definitions are skipped."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))

LOCK_CTORS = frozenset({"Lock", "RLock", "Condition"})

#: attribute names that read as locks even when we never saw the ctor
_LOCKISH_RE = re.compile(r"(?:^|_)(?:lock|cv|cond|mutex)$", re.IGNORECASE)

#: methods that mutate their receiver in place
MUTATOR_METHODS = frozenset({
    "append", "extend", "insert", "add", "remove", "discard", "pop",
    "popitem", "clear", "update", "setdefault", "appendleft",
})


def terminal_name(expr: ast.expr) -> Optional[str]:
    """The last identifier of a Name/Attribute chain (``a.b.c`` -> ``c``)."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return terminal_name(call.func)


@dataclass
class FunctionUnit:
    qualname: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    cls: Optional[str] = None
    parent: Optional[str] = None  # enclosing function qualname (nested defs)


@dataclass
class CallEvent:
    node: ast.Call
    held: Tuple[str, ...]
    resolved: Optional[str]  # module-local qualname, when resolvable


@dataclass
class EnterEvent:
    lock: str
    held_before: Tuple[str, ...]
    node: ast.AST


@dataclass
class MutateEvent:
    key: str  # canonical shared-state id
    held: Tuple[str, ...]
    node: ast.AST
    kind: str  # "assign" | "augassign" | "method"


@dataclass
class FunctionFacts:
    calls: List[CallEvent] = field(default_factory=list)
    enters: List[EnterEvent] = field(default_factory=list)
    mutations: List[MutateEvent] = field(default_factory=list)


class GlobalLockIndex:
    """Cross-file index: lock attribute name -> owning ``Class.attr`` ids.
    Lets ``other.lock`` canonicalize to ``DeltaLog.lock`` when exactly one
    analyzed class owns a lock attribute of that name."""

    def __init__(self, ctx: AnalysisContext):
        self.attr_owners: Dict[str, Set[str]] = {}
        for sf in ctx.files:
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                for attr in _class_lock_attrs(node):
                    self.attr_owners.setdefault(attr, set()).add(
                        f"{node.name}.{attr}")

    def canonical_attr(self, attr: str) -> Optional[str]:
        owners = self.attr_owners.get(attr)
        if owners is None:
            return f"@{attr}" if _LOCKISH_RE.search(attr) else None
        if len(owners) == 1:
            return next(iter(owners))
        return f"@{attr}"


def _is_lock_ctor(value: ast.expr) -> bool:
    return (isinstance(value, ast.Call)
            and terminal_name(value.func) in LOCK_CTORS)


def _class_lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """Lock attributes of a class: ``self.X = Lock()`` in any method plus
    ``X = Lock()`` in the class body."""
    out: Set[str] = set()
    for stmt in cls.body:
        if isinstance(stmt, ast.Assign) and _is_lock_ctor(stmt.value):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
            for t in node.targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    out.add(t.attr)
    return out


class ModuleGraph:
    def __init__(self, sf: SourceFile, index: GlobalLockIndex):
        self.sf = sf
        self.index = index
        self.module_locks: Dict[str, str] = {}   # name -> canonical id
        self.class_locks: Dict[str, Set[str]] = {}
        self.module_globals: Set[str] = set()
        self.functions: Dict[str, FunctionUnit] = {}
        self.facts: Dict[str, FunctionFacts] = {}
        self._collect_module_level()
        self._collect_functions()
        for qn in self.functions:
            self.facts[qn] = self._walk_function(qn)
        self.effective: Dict[str, FrozenSet[str]] = self._effective_held()

    # -- collection -------------------------------------------------------

    def _collect_module_level(self) -> None:
        for stmt in self.sf.tree.body:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        if _is_lock_ctor(stmt.value):
                            self.module_locks[t.id] = \
                                f"{self.sf.rel}::{t.id}"
                        else:
                            self.module_globals.add(t.id)
            elif isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name):
                if stmt.value is not None and _is_lock_ctor(stmt.value):
                    self.module_locks[stmt.target.id] = \
                        f"{self.sf.rel}::{stmt.target.id}"
                else:
                    self.module_globals.add(stmt.target.id)
            elif isinstance(stmt, ast.ClassDef):
                self.class_locks[stmt.name] = _class_lock_attrs(stmt)
        # names declared `global` anywhere also count as module state
        for node in ast.walk(self.sf.tree):
            if isinstance(node, ast.Global):
                for n in node.names:
                    if n not in self.module_locks:
                        self.module_globals.add(n)

    def _collect_functions(self) -> None:
        def visit(body: Sequence[ast.stmt], cls: Optional[str],
                  parent: Optional[str]) -> None:
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qn = (f"{parent}.<locals>.{stmt.name}" if parent
                          else f"{cls}.{stmt.name}" if cls else stmt.name)
                    self.functions[qn] = FunctionUnit(qn, stmt, cls, parent)
                    visit(stmt.body, cls, qn)
                elif isinstance(stmt, ast.ClassDef):
                    # classes nested in functions/classes too: their methods
                    # (HTTP handler classes defined inline) must not escape
                    # the crash-safety/lock-discipline view
                    nested = (f"{parent}.<locals>.{stmt.name}" if parent
                              else f"{cls}.{stmt.name}" if cls
                              else stmt.name)
                    visit(stmt.body, nested, None)

        visit(self.sf.tree.body, None, None)

    # -- lock / state canonicalization -----------------------------------

    def lock_id(self, expr: ast.expr, cls: Optional[str]) -> Optional[str]:
        """Canonical lock id for an expression used as ``with <expr>:``."""
        if isinstance(expr, ast.Name):
            return self.module_locks.get(expr.id)
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            if isinstance(expr.value, ast.Name) and expr.value.id == "self":
                if cls and attr in self.class_locks.get(cls, ()):
                    return f"{cls}.{attr}"
                return self.index.canonical_attr(attr)
            # receiver is another object (dl.lock, conf._lock, cls attr)
            return self.index.canonical_attr(attr)
        return None

    def _state_key(self, expr: ast.expr, unit: FunctionUnit
                   ) -> Optional[str]:
        """Canonical shared-state id for a mutation target base: a module
        global or a ``self`` attribute (locks themselves excluded)."""
        while isinstance(expr, ast.Subscript):
            expr = expr.value
        if isinstance(expr, ast.Name):
            if (expr.id in self.module_globals
                    and expr.id not in self.module_locks):
                return f"{self.sf.rel}::{expr.id}"
            return None
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and unit.cls):
            if expr.attr in self.class_locks.get(unit.cls, ()):
                return None
            return f"{self.sf.rel}::{unit.cls}.{expr.attr}"
        return None

    # -- call resolution --------------------------------------------------

    def resolve_call(self, call: ast.Call, unit: FunctionUnit
                     ) -> Optional[str]:
        f = call.func
        if isinstance(f, ast.Name):
            return self._resolve_name(f.id, unit)
        if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and f.value.id == "self" and unit.cls):
            qn = f"{unit.cls}.{f.attr}"
            return qn if qn in self.functions else None
        return None

    def _resolve_name(self, name: str, unit: FunctionUnit) -> Optional[str]:
        # nested defs of the enclosing function chain shadow module scope
        scope = unit.qualname
        while scope:
            qn = f"{scope}.<locals>.{name}"
            if qn in self.functions:
                return qn
            scope = self.functions[scope].parent if scope in self.functions \
                else None
        if name in self.functions:
            return name
        if unit.cls and f"{unit.cls}.{name}" in self.functions:
            return f"{unit.cls}.{name}"
        return None

    def resolve_callable_expr(self, expr: ast.expr, unit: FunctionUnit
                              ) -> Optional[str]:
        """Resolve a callable-valued expression (a ``target=`` kwarg, a
        ``pool.submit`` argument), unwrapping ``telemetry.propagated(f)``."""
        if (isinstance(expr, ast.Call)
                and terminal_name(expr.func) == "propagated" and expr.args):
            expr = expr.args[0]
        if isinstance(expr, ast.Name):
            return self._resolve_name(expr.id, unit)
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and unit.cls):
            qn = f"{unit.cls}.{expr.attr}"
            return qn if qn in self.functions else None
        return None

    # -- held-lock walk ---------------------------------------------------

    def _walk_function(self, qualname: str) -> FunctionFacts:
        unit = self.functions[qualname]
        facts = FunctionFacts()

        def walk(stmts: Sequence[ast.stmt], held: Tuple[str, ...]) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue  # nested defs are separate units
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    inner = held
                    for item in stmt.items:
                        lid = self.lock_id(item.context_expr, unit.cls)
                        self._scan_exprs([item.context_expr], inner, unit,
                                         facts)
                        if lid is not None:
                            facts.enters.append(
                                EnterEvent(lid, inner, item.context_expr))
                            if lid not in inner:
                                inner = inner + (lid,)
                    walk(stmt.body, inner)
                    continue
                self._scan_stmt(stmt, held, unit, facts)
                for _name, sub in ast.iter_fields(stmt):
                    for blocks in _stmt_bodies(sub):
                        walk(blocks, held)
        walk(unit.node.body, ())
        return facts

    def _scan_stmt(self, stmt: ast.stmt, held: Tuple[str, ...],
                   unit: FunctionUnit, facts: FunctionFacts) -> None:
        # mutations: assignment / augassign targets over shared state
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                key = self._state_key(t, unit)
                if key is not None:
                    facts.mutations.append(MutateEvent(
                        key, held, stmt,
                        "subscript" if isinstance(t, ast.Subscript)
                        else "assign"))
        elif isinstance(stmt, ast.AugAssign):
            key = self._state_key(stmt.target, unit)
            if key is not None:
                facts.mutations.append(
                    MutateEvent(key, held, stmt, "augassign"))
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            key = self._state_key(stmt.target, unit)
            if key is not None:
                facts.mutations.append(MutateEvent(key, held, stmt, "assign"))
        self._scan_exprs(
            [n for n in ast.iter_child_nodes(stmt)
             if isinstance(n, ast.expr)], held, unit, facts)

    def _scan_exprs(self, exprs: Sequence[ast.expr], held: Tuple[str, ...],
                    unit: FunctionUnit, facts: FunctionFacts) -> None:
        for expr in exprs:
            for node in ast.walk(expr):
                if isinstance(node, (ast.Lambda, ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue
                if not isinstance(node, ast.Call):
                    continue
                facts.calls.append(CallEvent(
                    node, held, self.resolve_call(node, unit)))
                # mutating method call on shared state
                f = node.func
                if (isinstance(f, ast.Attribute)
                        and f.attr in MUTATOR_METHODS):
                    key = self._state_key(f.value, unit)
                    if key is not None:
                        facts.mutations.append(
                            MutateEvent(key, held, node, "method"))

    # -- thread entry points ----------------------------------------------

    def thread_entries(self) -> Dict[str, str]:
        """``{qualname: how}`` for functions handed to another thread:
        ``Thread(target=...)`` / ``pool.submit(f)`` / ``pool.map(f, ...)``."""
        out: Dict[str, str] = {}
        for qn, facts in self.facts.items():
            unit = self.functions[qn]
            for ev in facts.calls:
                name = call_name(ev.node)
                if name == "Thread":
                    for kw in ev.node.keywords:
                        if kw.arg == "target":
                            t = self.resolve_callable_expr(kw.value, unit)
                            if t:
                                out.setdefault(t, "Thread target")
                elif name in ("submit", "map") and ev.node.args:
                    recv = terminal_name(ev.node.func.value) \
                        if isinstance(ev.node.func, ast.Attribute) else None
                    if recv and re.search(r"pool|executor|ex\b", recv,
                                          re.IGNORECASE):
                        t = self.resolve_callable_expr(ev.node.args[0], unit)
                        if t:
                            out.setdefault(t, f"pool.{name} callable")
        return out

    def reachable_from(self, roots: Sequence[str]) -> Set[str]:
        seen: Set[str] = set()
        stack = [r for r in roots if r in self.functions]
        while stack:
            qn = stack.pop()
            if qn in seen:
                continue
            seen.add(qn)
            for ev in self.facts[qn].calls:
                if ev.resolved and ev.resolved not in seen:
                    stack.append(ev.resolved)
        return seen

    # -- effective held locks (caller-context propagation) ----------------

    def _effective_held(self) -> Dict[str, FrozenSet[str]]:
        """Locks a function can assume held on EVERY entry: the intersection,
        over all module-local call sites, of locks lexically held at the
        site plus the caller's own effective set. Public functions (no
        leading underscore on the terminal name) and thread entry points
        assume nothing — they are callable from anywhere."""
        entries = set(self.thread_entries())
        sites: Dict[str, List[Tuple[str, Tuple[str, ...]]]] = {}
        for qn, facts in self.facts.items():
            for ev in facts.calls:
                if ev.resolved:
                    sites.setdefault(ev.resolved, []).append((qn, ev.held))
        universe = frozenset(
            lid for f in self.facts.values() for e in f.enters
            for lid in (e.lock,))
        eff: Dict[str, FrozenSet[str]] = {}
        for qn in self.functions:
            simple = qn.rsplit(".", 1)[-1]
            if (qn in entries or not simple.startswith("_")
                    or qn not in sites):
                eff[qn] = frozenset()
            else:
                eff[qn] = universe
        for _ in range(len(self.functions) + 1):
            changed = False
            for qn, qsites in sites.items():
                if eff.get(qn) == frozenset() and (
                        qn in entries
                        or not qn.rsplit(".", 1)[-1].startswith("_")):
                    continue
                new = None
                for caller, held in qsites:
                    s = frozenset(held) | eff.get(caller, frozenset())
                    new = s if new is None else (new & s)
                new = new if new is not None else frozenset()
                if new != eff.get(qn):
                    eff[qn] = new
                    changed = True
            if not changed:
                break
        return eff


def _stmt_bodies(field_val) -> List[List[ast.stmt]]:
    """The statement-list fields of one field value (body/orelse/finalbody/
    handler bodies), so the walker recurses without double-visiting."""
    out: List[List[ast.stmt]] = []
    if isinstance(field_val, list):
        stmts = [n for n in field_val if isinstance(n, ast.stmt)]
        if stmts:
            out.append(stmts)
        for n in field_val:
            if isinstance(n, ast.ExceptHandler):
                out.append(list(n.body))
    return out
