"""Structured telemetry — the usage-logging interface.

Reference: ``metering/DeltaLogging.scala:50-109`` wraps every user action in
``recordDeltaOperation(opType)`` / ``recordDeltaEvent`` with hierarchical op
types (e.g. ``delta.commit.retry.conflictCheck``) and JSON payloads; the OSS
backend is a no-op stub. Here the backend is real: events go to an in-process
ring buffer (inspectable in tests / ops tooling) and to a standard ``logging``
logger, and each operation is additionally wrapped in a JAX profiler trace
annotation when JAX is initialized, so device timelines line up with engine
operations.
"""
from __future__ import annotations

import contextlib
import json
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, Iterator, List, Optional

logger = logging.getLogger("delta_tpu.usage")

__all__ = ["record_event", "record_operation", "with_status", "recent_events", "clear_events", "UsageEvent"]


@dataclass
class UsageEvent:
    op_type: str
    timestamp_ms: int
    duration_ms: Optional[int] = None
    tags: Dict[str, str] = field(default_factory=dict)
    data: Dict[str, Any] = field(default_factory=dict)
    error: Optional[str] = None

    def to_json(self) -> str:
        return json.dumps(
            {
                "opType": self.op_type,
                "timestamp": self.timestamp_ms,
                "durationMs": self.duration_ms,
                "tags": self.tags,
                "data": self.data,
                "error": self.error,
            },
            separators=(",", ":"),
            default=str,
        )


_BUFFER: Deque[UsageEvent] = deque(maxlen=4096)
_LOCK = threading.Lock()


def record_event(op_type: str, data: Optional[Dict[str, Any]] = None, **tags: str) -> None:
    ev = UsageEvent(op_type, int(time.time() * 1000), tags={k: str(v) for k, v in tags.items()},
                    data=data or {})
    with _LOCK:
        _BUFFER.append(ev)
    logger.debug("%s", ev.to_json())


@contextlib.contextmanager
def record_operation(op_type: str, data: Optional[Dict[str, Any]] = None, **tags: str) -> Iterator[UsageEvent]:
    """Wrap an operation: duration + error capture + JAX profiler annotation."""
    ev = UsageEvent(op_type, int(time.time() * 1000), tags={k: str(v) for k, v in tags.items()},
                    data=dict(data or {}))
    start = time.monotonic()
    trace_ctx = _maybe_jax_trace(op_type)
    try:
        with trace_ctx:
            yield ev
    except BaseException as e:
        ev.error = f"{type(e).__name__}: {e}"
        raise
    finally:
        ev.duration_ms = int((time.monotonic() - start) * 1000)
        with _LOCK:
            _BUFFER.append(ev)
        logger.debug("%s", ev.to_json())


@contextlib.contextmanager
def with_status(message: str, **tags: str) -> Iterator[None]:
    """Human-readable job description around a long step — the analogue of
    the reference's ``DeltaProgressReporter.withStatusCode`` ("Filtering
    files for query", `PartitionFiltering.scala:34`). Logs at INFO on entry
    and records a `delta.status` usage event with the duration on exit, so
    operators can see WHAT a long-running command is doing, not just that
    it is running."""
    logger.info("%s", message)
    with record_operation("delta.status", {"message": message}, **tags):
        yield


def _maybe_jax_trace(name: str):
    try:
        import sys

        jax = sys.modules.get("jax")
        if jax is not None:
            return jax.named_scope(name.replace("delta.", "delta/"))
    except Exception:  # noqa: BLE001
        pass
    return contextlib.nullcontext()


def recent_events(op_prefix: str = "") -> List[UsageEvent]:
    with _LOCK:
        return [e for e in _BUFFER if e.op_type.startswith(op_prefix)]


def clear_events() -> None:
    with _LOCK:
        _BUFFER.clear()


# -- monotonic counters ------------------------------------------------------
#
# Cheap process-wide tallies for questions like "what fraction of scan
# plans actually served from the resident state cache, and why did the
# rest fall back?" — the serving envelope as a NUMBER, not a hope.

_COUNTERS: Dict[str, int] = {}


def bump_counter(name: str, by: int = 1) -> None:
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + by


def counters(prefix: str = "") -> Dict[str, int]:
    with _LOCK:
        return {k: v for k, v in _COUNTERS.items() if k.startswith(prefix)}


def clear_counters() -> None:
    with _LOCK:
        _COUNTERS.clear()
