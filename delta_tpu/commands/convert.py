"""CONVERT TO DELTA — in-place conversion of a Parquet directory.

Mirrors `commands/ConvertToDeltaCommand.scala:73-655`: list every data file,
merge the Parquet footers into one schema, parse partition values from the
hive-style directory names against the user-provided partition schema
(required when the table is partitioned, like the reference's
``CONVERT TO DELTA t PARTITIONED BY (...)``), synthesize `AddFile`s, and
write everything in a single commit (version 0). Already-delta tables are a
no-op; collecting stats during convert is optional (the reference collects
none).
"""
from __future__ import annotations

import os
import urllib.parse
from typing import Dict, List, Optional, Tuple

import pyarrow.parquet as pq

from delta_tpu.commands import operations as ops
from delta_tpu.exec.write import unescape_partition_value
from delta_tpu.protocol.actions import Action, AddFile, Metadata
from delta_tpu.schema.arrow_interop import schema_from_arrow
from delta_tpu.schema.types import StructType
from delta_tpu.utils.errors import (
    DeltaAnalysisError,
    DeltaFileNotFoundError,
    DeltaIllegalStateError,
)
from delta_tpu.utils import errors

__all__ = ["ConvertToDeltaCommand"]


class ConvertToDeltaCommand:
    def __init__(
        self,
        delta_log,
        partition_schema: Optional[StructType] = None,
        collect_stats: bool = False,
        distribute: bool = False,
    ):
        self.delta_log = delta_log
        self.partition_schema = partition_schema
        self.collect_stats = collect_stats
        # multi-process conversion: each host footers/stats its slice of the
        # listing and publishes a fragment through the shared store; process
        # 0 gathers the fragments and commits (SURVEY §2.8's executor
        # fan-out, coordinated through the filesystem like everything else)
        self.distribute = distribute

    def _list_parquet_files(self) -> List[Tuple[str, int, int]]:
        """(rel_path, size, mtime_ms) for every data file under the table."""
        base = self.delta_log.data_path
        out = []
        for root, dirs, files in os.walk(base):
            # sorted traversal: multi-host convert relies on every process
            # computing the IDENTICAL index->file mapping, and os.scandir
            # order is filesystem-dependent
            dirs[:] = sorted(
                d for d in dirs
                if not ((d.startswith("_") or d.startswith(".")) and "=" not in d)
            )
            for name in sorted(files):
                if name.startswith("_") or name.startswith("."):
                    continue
                if not name.endswith(".parquet"):
                    continue
                abs_p = os.path.join(root, name)
                st = os.stat(abs_p)
                rel = os.path.relpath(abs_p, base).replace(os.sep, "/")
                out.append((rel, st.st_size, int(st.st_mtime * 1000)))
        return out

    def _partition_values(self, rel: str) -> Dict[str, Optional[str]]:
        """Parse ``col=value`` path segments (`createDeltaActions :286`)."""
        parts = rel.split("/")[:-1]
        values: Dict[str, Optional[str]] = {}
        for seg in parts:
            if "=" not in seg:
                raise errors.partition_path_segment_invalid(seg, rel)
            k, _, v = seg.partition("=")
            values[k] = unescape_partition_value(v)
        expected = [f.name for f in (self.partition_schema.fields if self.partition_schema else [])]
        if sorted(values) != sorted(expected):
            raise errors.partition_path_mismatch(rel, values, expected)
        return values

    def run(self) -> int:
        from delta_tpu.utils.telemetry import record_operation

        with record_operation("delta.utility.convertToDelta",
                              path=self.delta_log.data_path):
            return self._run_impl()

    def _run_impl(self) -> int:
        log = self.delta_log
        if log.table_exists:
            return log.snapshot.version  # already delta: no-op

        files = self._list_parquet_files()
        if not files:
            raise DeltaFileNotFoundError(
                f"No parquet files found in {log.data_path} to convert"
            )

        if self.distribute:
            from delta_tpu.parallel.distributed import (
                host_shard_indices, process_info,
            )

            proc, n_procs = process_info()
        else:
            proc, n_procs = 0, 1

        # per-file work (footer read for the schema merge; optional stats
        # read): this host's deterministic slice of the listing
        mine = (host_shard_indices(len(files), proc, n_procs)
                if n_procs > 1 else range(len(files)))
        merged = None
        frag_adds: List[dict] = []
        for i in mine:
            rel, size, mtime = files[i]
            abs_p = os.path.join(log.data_path, rel.replace("/", os.sep))
            s = pq.ParquetFile(abs_p).schema_arrow
            merged = s if merged is None else _merge_arrow(merged, s)
            frag_adds.append({
                "i": i, "rel": rel, "size": size, "mtime": mtime,
                "stats": self._stats_for(rel) if self.collect_stats else None,
            })

        if n_procs > 1:
            merged, frag_adds = self._exchange_fragments(
                proc, n_procs, merged, frag_adds, files
            )
            if proc != 0:
                # non-coordinators published their fragment; the commit is
                # process 0's — wait for it so every process returns the
                # same version
                return self._await_converted()
        data_schema = schema_from_arrow(merged)

        part_fields = list(self.partition_schema.fields) if self.partition_schema else []
        full = StructType(list(data_schema.fields) + part_fields)
        metadata = Metadata(
            schema_string=full.to_json(),
            partition_columns=[f.name for f in part_fields],
        )

        adds: List[Action] = []
        for f in sorted(frag_adds, key=lambda d: d["i"]):
            rel = f["rel"]
            pv = self._partition_values(rel)
            adds.append(
                AddFile(
                    path=urllib.parse.quote(rel, safe="/:@!$&'()*+,;=-._~"),
                    partition_values=pv,
                    size=f["size"],
                    modification_time=f["mtime"],
                    data_change=True,
                    stats=f["stats"],
                )
            )

        def body(txn):
            txn.update_metadata(metadata)
            op = ops.Convert(
                num_files=len(adds),
                partition_by=[f.name for f in part_fields],
            )
            return txn.commit(adds, op)

        return log.with_new_transaction(body)

    def _stats_for(self, rel: str) -> str:
        """AddFile stats for one data file: derived from footer row-group
        statistics whenever the footer can stand in for a full decode
        (shared with the read path's row-group planner, `exec/rowgroups`);
        decode only when footer stats are absent or unsafe (stats-disabled
        writers, NaN-polluted float bounds, bounds withheld for oversized
        binary values)."""
        import json as _json

        from delta_tpu.exec.parquet import stats_json
        from delta_tpu.exec.rowgroups import read_footer, stats_from_footer
        from delta_tpu.utils.telemetry import bump_counter

        abs_p = os.path.join(self.delta_log.data_path, rel.replace("/", os.sep))
        try:
            stats = stats_from_footer(read_footer(abs_p))
        except Exception:
            stats = None
        if stats is not None:
            bump_counter("convert.stats.fromFooter")
            return _json.dumps(stats)
        bump_counter("convert.stats.fromDecode")
        return stats_json(pq.read_table(abs_p))

    # -- multi-process fragment exchange (shared-store coordination) ------

    @staticmethod
    def _listing_token(files) -> str:
        """Deterministic attempt token: a hash of the (sorted) listing. All
        hosts of one attempt compute the same token; a retry after the data
        changed gets a fresh namespace, so stale fragments from a crashed
        earlier attempt can never be consumed (fragments from an identical
        listing ARE valid — same inputs, same outputs)."""
        import hashlib
        import json as _json

        payload = _json.dumps(files, sort_keys=True).encode()
        return hashlib.sha256(payload).hexdigest()[:16]

    def _fragment_path(self, token: str, proc: int) -> str:
        return (f"{self.delta_log.log_path}/.convert_fragments/"
                f"{token}-part-{proc:05d}.json")

    @staticmethod
    def _timeout_s() -> float:
        from delta_tpu.utils.config import conf

        return int(conf.get("delta.tpu.distributed.timeoutMs", 600_000)) / 1000

    def _exchange_fragments(self, proc, n_procs, merged, frag_adds, files):
        """Publish this host's fragment (schema + per-file rows) through the
        store; process 0 gathers every fragment and returns the combined
        (schema, rows). An empty slice publishes a schema-less fragment."""
        import io
        import json as _json
        import time as _time

        import pyarrow as pa

        store = self.delta_log.store
        token = self._listing_token(files)
        schema_hex = None
        if merged is not None:
            sink = io.BytesIO()
            pa.ipc.new_stream(sink, pa.schema(merged)).close()
            schema_hex = sink.getvalue().hex()
        payload = _json.dumps({"schema_ipc": schema_hex, "adds": frag_adds})
        store.write_bytes(self._fragment_path(token, proc), payload.encode(),
                          overwrite=True)
        if proc != 0:
            return merged, frag_adds
        deadline = _time.monotonic() + self._timeout_s()
        out_adds = list(frag_adds)
        for other in range(1, n_procs):
            path = self._fragment_path(token, other)
            while not store.exists(path):
                if _time.monotonic() > deadline:
                    raise DeltaIllegalStateError(
                        f"Timed out waiting for convert fragment {path}"
                    )
                _time.sleep(0.05)
            d = _json.loads(store.read_bytes(path))
            if d["schema_ipc"] is not None:
                other_schema = pa.ipc.open_stream(
                    bytes.fromhex(d["schema_ipc"])).schema
                merged = (other_schema if merged is None
                          else _merge_arrow(merged, other_schema))
            out_adds.extend(d["adds"])
        if len(out_adds) != len(files):
            raise DeltaIllegalStateError(
                f"Convert fragments cover {len(out_adds)} of {len(files)} files"
            )
        return merged, out_adds

    def _await_converted(self) -> int:
        import time as _time

        deadline = _time.monotonic() + self._timeout_s()
        log = self.delta_log
        while True:
            snap = log.update()
            if snap.version >= 0:
                return snap.version
            if _time.monotonic() > deadline:
                raise DeltaIllegalStateError(
                    "Timed out waiting for the coordinating process's "
                    "CONVERT commit"
                )
            _time.sleep(0.05)


def _merge_arrow(a, b):
    import pyarrow as pa

    names = list(a.names)
    fields = {f.name: f for f in a}
    for f in b:
        if f.name not in fields:
            names.append(f.name)
            fields[f.name] = f
        elif fields[f.name].type != f.type:
            # widen to the later file's type when types differ numerically
            if pa.types.is_integer(fields[f.name].type) and pa.types.is_floating(f.type):
                fields[f.name] = f
    return pa.schema([fields[n] for n in names])
