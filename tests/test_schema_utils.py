"""Schema machinery tests (reference spec: ``SchemaUtilsSuite``, 1,311 LoC).

Started with the ALTER widening + Arrow interop edge cases that round-1
review flagged; grows toward the full SchemaUtilsSuite matrix.
"""
import pyarrow as pa
import pytest

from delta_tpu.schema import schema_utils
from delta_tpu.schema.arrow_interop import delta_type_from_arrow
from delta_tpu.schema.types import (
    ArrayType,
    ByteType,
    DoubleType,
    FloatType,
    IntegerType,
    LongType,
    MapType,
    NullType,
    ShortType,
    StringType,
    StructField,
    StructType,
)
from delta_tpu.utils.errors import SchemaMismatchError


class TestCanChangeDataType:
    def test_widening_lattice(self):
        ok = [
            (ByteType(), ShortType()),
            (ByteType(), IntegerType()),
            (ByteType(), LongType()),
            (ShortType(), IntegerType()),
            (ShortType(), LongType()),
            (IntegerType(), LongType()),
            (FloatType(), DoubleType()),
        ]
        for f, t in ok:
            assert schema_utils.can_change_data_type(f, t), (f, t)

    def test_narrowing_refused(self):
        bad = [
            (LongType(), IntegerType()),
            (IntegerType(), ShortType()),
            (DoubleType(), FloatType()),
            (IntegerType(), StringType()),
            (StringType(), IntegerType()),
            (IntegerType(), DoubleType()),  # long would lose precision; not in lattice
        ]
        for f, t in bad:
            assert not schema_utils.can_change_data_type(f, t), (f, t)

    def test_null_type_to_anything(self):
        assert schema_utils.can_change_data_type(NullType(), StringType())

    def test_nested_widening(self):
        assert schema_utils.can_change_data_type(
            ArrayType(IntegerType()), ArrayType(LongType())
        )
        assert schema_utils.can_change_data_type(
            MapType(IntegerType(), FloatType()), MapType(LongType(), DoubleType())
        )
        inner_f = StructType().add("x", IntegerType())
        inner_t = StructType().add("x", LongType())
        assert schema_utils.can_change_data_type(inner_f, inner_t)
        assert not schema_utils.can_change_data_type(inner_t, inner_f)


def test_uint64_arrow_rejected():
    with pytest.raises(SchemaMismatchError, match="uint64"):
        delta_type_from_arrow(pa.uint64())


def test_uint32_arrow_widens_to_long():
    assert delta_type_from_arrow(pa.uint32()) == LongType()


# ---------------------------------------------------------------------------
# mergeSchemas matrix (SchemaUtilsSuite "schema merging" cases)
# ---------------------------------------------------------------------------


def _s(*fields) -> StructType:
    st = StructType()
    for name, dt, *rest in fields:
        nullable = rest[0] if rest else True
        st = st.add(name, dt, nullable)
    return st


class TestMergeSchemas:
    def test_append_new_column_at_end(self):
        merged = schema_utils.merge_schemas(
            _s(("a", IntegerType())), _s(("a", IntegerType()), ("b", StringType()))
        )
        assert [f.name for f in merged.fields] == ["a", "b"]

    def test_existing_column_keeps_position_and_case(self):
        merged = schema_utils.merge_schemas(
            _s(("Alpha", IntegerType()), ("beta", StringType())),
            _s(("NEW", DoubleType()), ("ALPHA", IntegerType())),
        )
        assert [f.name for f in merged.fields] == ["Alpha", "beta", "NEW"]

    def test_existing_column_keeps_current_nullability_and_metadata(self):
        cur = StructType().add("a", IntegerType(), False, {"comment": "keep me"})
        new = StructType().add("a", IntegerType(), True, {"comment": "ignore"})
        merged = schema_utils.merge_schemas(cur, new)
        assert merged.fields[0].nullable is False
        assert merged.fields[0].metadata == {"comment": "keep me"}

    def test_nested_struct_merge_appends_inner_field(self):
        cur = _s(("s", _s(("x", IntegerType()))))
        new = _s(("s", _s(("x", IntegerType()), ("y", StringType()))))
        merged = schema_utils.merge_schemas(cur, new)
        inner = merged.fields[0].data_type
        assert [f.name for f in inner.fields] == ["x", "y"]

    def test_deeply_nested_struct_merge(self):
        cur = _s(("a", _s(("b", _s(("c", IntegerType()))))))
        new = _s(("a", _s(("b", _s(("c", IntegerType()), ("d", LongType()))))))
        merged = schema_utils.merge_schemas(cur, new)
        inner = merged.fields[0].data_type.fields[0].data_type
        assert [f.name for f in inner.fields] == ["c", "d"]

    def test_array_of_struct_merge(self):
        cur = _s(("arr", ArrayType(_s(("x", IntegerType())))))
        new = _s(("arr", ArrayType(_s(("x", IntegerType()), ("y", LongType())))))
        merged = schema_utils.merge_schemas(cur, new)
        elem = merged.fields[0].data_type.element_type
        assert [f.name for f in elem.fields] == ["x", "y"]

    def test_map_of_struct_merge_both_sides(self):
        cur = _s(("m", MapType(_s(("k", IntegerType())), _s(("v", IntegerType())))))
        new = _s(("m", MapType(
            _s(("k", IntegerType()), ("k2", StringType())),
            _s(("v", IntegerType()), ("v2", StringType())),
        )))
        merged = schema_utils.merge_schemas(cur, new)
        mt = merged.fields[0].data_type
        assert [f.name for f in mt.key_type.fields] == ["k", "k2"]
        assert [f.name for f in mt.value_type.fields] == ["v", "v2"]

    def test_array_keeps_current_contains_null(self):
        cur = _s(("arr", ArrayType(IntegerType(), contains_null=False)))
        new = _s(("arr", ArrayType(IntegerType(), contains_null=True)))
        merged = schema_utils.merge_schemas(cur, new)
        assert merged.fields[0].data_type.contains_null is False

    def test_int32_family_always_unifies_to_widest(self):
        # parquet stores byte/short/int as INT32 (SchemaUtils.scala:901-909)
        for cur, new, want in [
            (ByteType(), ShortType(), ShortType()),
            (ShortType(), ByteType(), ShortType()),
            (ByteType(), IntegerType(), IntegerType()),
            (IntegerType(), ByteType(), IntegerType()),
            (ShortType(), IntegerType(), IntegerType()),
            (IntegerType(), ShortType(), IntegerType()),
        ]:
            merged = schema_utils.merge_schemas(_s(("a", cur)), _s(("a", new)))
            assert merged.fields[0].data_type == want, (cur, new)

    def test_int_to_long_requires_implicit_conversions(self):
        with pytest.raises(SchemaMismatchError, match="Failed to merge"):
            schema_utils.merge_schemas(
                _s(("a", LongType())), _s(("a", IntegerType()))
            )
        merged = schema_utils.merge_schemas(
            _s(("a", LongType())), _s(("a", IntegerType())),
            allow_implicit_conversions=True,
        )
        assert merged.fields[0].data_type == LongType()

    def test_implicit_conversion_picks_higher_precedence(self):
        merged = schema_utils.merge_schemas(
            _s(("a", IntegerType())), _s(("a", DoubleType())),
            allow_implicit_conversions=True,
        )
        assert merged.fields[0].data_type == DoubleType()

    def test_null_type_upgrades_either_direction(self):
        assert schema_utils.merge_schemas(
            _s(("a", NullType())), _s(("a", StringType()))
        ).fields[0].data_type == StringType()
        assert schema_utils.merge_schemas(
            _s(("a", StringType())), _s(("a", NullType()))
        ).fields[0].data_type == StringType()

    def test_incompatible_types_error_names_the_path(self):
        cur = _s(("s", _s(("x", StringType()))))
        new = _s(("s", _s(("x", IntegerType()))))
        with pytest.raises(SchemaMismatchError, match="s.x"):
            schema_utils.merge_schemas(cur, new)

    def test_keep_existing_type_squashes_primitive_clash(self):
        merged = schema_utils.merge_schemas(
            _s(("a", StringType())), _s(("a", IntegerType())),
            keep_existing_type=True,
        )
        assert merged.fields[0].data_type == StringType()

    def test_fixed_type_columns_refuse_type_change(self):
        from delta_tpu.utils.errors import DeltaAnalysisError

        with pytest.raises(DeltaAnalysisError, match="generated column"):
            schema_utils.merge_schemas(
                _s(("g", IntegerType())), _s(("g", LongType())),
                allow_implicit_conversions=True, fixed_type_columns={"g"},
            )

    def test_duplicate_columns_in_incoming_schema_rejected(self):
        from delta_tpu.utils.errors import DeltaAnalysisError

        dup = StructType().add("a", IntegerType()).add("A", StringType())
        with pytest.raises(DeltaAnalysisError, match="duplicate"):
            schema_utils.merge_schemas(_s(("a", IntegerType())), dup)

    def test_decimal_mismatch_errors(self):
        from delta_tpu.schema.types import DecimalType

        with pytest.raises(SchemaMismatchError, match="precision 10 and 12"):
            schema_utils.merge_schemas(
                _s(("d", DecimalType(10, 2))), _s(("d", DecimalType(12, 2)))
            )
        with pytest.raises(SchemaMismatchError, match="scale 2 and 4"):
            schema_utils.merge_schemas(
                _s(("d", DecimalType(10, 2))), _s(("d", DecimalType(10, 4)))
            )
        with pytest.raises(SchemaMismatchError, match="precision 10 and 12"):
            schema_utils.merge_schemas(
                _s(("d", DecimalType(10, 2))), _s(("d", DecimalType(12, 4)))
            )


# ---------------------------------------------------------------------------
# addColumn / dropColumn positions (SchemaUtilsSuite "add/drop column" cases)
# ---------------------------------------------------------------------------


class TestAddColumn:
    def test_add_at_front_middle_end(self):
        base = _s(("a", IntegerType()), ("b", StringType()))
        f = StructField("x", LongType())
        assert [f2.name for f2 in schema_utils.add_column(base, f, [0]).fields] == [
            "x", "a", "b"
        ]
        assert [f2.name for f2 in schema_utils.add_column(base, f, [1]).fields] == [
            "a", "x", "b"
        ]
        assert [f2.name for f2 in schema_utils.add_column(base, f, [2]).fields] == [
            "a", "b", "x"
        ]

    def test_add_nested_inside_struct(self):
        # tableSchema: <a:STRUCT<a1,a2,a3>, b, c:STRUCT<c1,c3>>; add c2 at [2,1]
        base = _s(
            ("a", _s(("a1", IntegerType()), ("a2", IntegerType()), ("a3", IntegerType()))),
            ("b", IntegerType()),
            ("c", _s(("c1", IntegerType()), ("c3", IntegerType()))),
        )
        out = schema_utils.add_column(base, StructField("c2", LongType()), [2, 1])
        inner = out.fields[2].data_type
        assert [f.name for f in inner.fields] == ["c1", "c2", "c3"]

    def test_add_inside_array_element_struct(self):
        base = _s(("arr", ArrayType(_s(("x", IntegerType())))))
        out = schema_utils.add_column(
            base, StructField("y", LongType()),
            [0, schema_utils.ARRAY_ELEMENT_INDEX, 1],
        )
        elem = out.fields[0].data_type.element_type
        assert [f.name for f in elem.fields] == ["x", "y"]

    def test_add_inside_map_key_and_value(self):
        base = _s(("m", MapType(_s(("k", IntegerType())), _s(("v", IntegerType())))))
        out = schema_utils.add_column(
            base, StructField("k2", LongType()),
            [0, schema_utils.MAP_KEY_INDEX, 1],
        )
        assert [f.name for f in out.fields[0].data_type.key_type.fields] == ["k", "k2"]
        out = schema_utils.add_column(
            base, StructField("v2", LongType()),
            [0, schema_utils.MAP_VALUE_INDEX, 0],
        )
        assert [f.name for f in out.fields[0].data_type.value_type.fields] == ["v2", "v"]

    def test_add_non_nullable_into_nullable_parent_errors(self):
        from delta_tpu.utils.errors import DeltaAnalysisError

        base = _s(("s", _s(("x", IntegerType()))))  # parent nullable
        with pytest.raises(DeltaAnalysisError, match="non-nullable nested field"):
            schema_utils.add_column(
                base, StructField("y", LongType(), nullable=False), [0, 0]
            )

    def test_add_position_out_of_bounds_errors(self):
        from delta_tpu.utils.errors import DeltaAnalysisError

        base = _s(("a", IntegerType()))
        with pytest.raises(DeltaAnalysisError, match="larger than struct length"):
            schema_utils.add_column(base, StructField("x", LongType()), [5])
        with pytest.raises(DeltaAnalysisError, match="lower than 0"):
            schema_utils.add_column(base, StructField("x", LongType()), [-1])

    def test_add_into_non_struct_parent_errors(self):
        from delta_tpu.utils.errors import DeltaAnalysisError

        base = _s(("a", IntegerType()))
        with pytest.raises(DeltaAnalysisError, match="parent is not a StructType"):
            schema_utils.add_column(base, StructField("x", LongType()), [0, 0])

    def test_add_duplicate_top_level_errors(self):
        from delta_tpu.utils.errors import DeltaAnalysisError

        base = _s(("a", IntegerType()))
        with pytest.raises(DeltaAnalysisError, match="already exists"):
            schema_utils.add_column(base, StructField("A", LongType()), [1])


class TestDropColumn:
    def test_drop_top_level_by_position(self):
        base = _s(("a", IntegerType()), ("b", StringType()), ("c", LongType()))
        out, dropped = schema_utils.drop_column_at(base, [1])
        assert [f.name for f in out.fields] == ["a", "c"]
        assert dropped.name == "b"

    def test_drop_nested(self):
        base = _s(
            ("a", IntegerType()),
            ("c", _s(("c1", IntegerType()), ("c2", LongType()), ("c3", StringType()))),
        )
        out, dropped = schema_utils.drop_column_at(base, [1, 1])
        assert dropped.name == "c2"
        assert [f.name for f in out.fields[1].data_type.fields] == ["c1", "c3"]

    def test_drop_out_of_bounds_errors(self):
        from delta_tpu.utils.errors import DeltaAnalysisError

        base = _s(("a", IntegerType()))
        with pytest.raises(DeltaAnalysisError, match="larger than struct length"):
            schema_utils.drop_column_at(base, [1])

    def test_drop_last_column_by_name_errors(self):
        from delta_tpu.utils.errors import DeltaAnalysisError

        base = _s(("a", IntegerType()))
        with pytest.raises(DeltaAnalysisError, match="Cannot drop all columns"):
            schema_utils.drop_column(base, "a")
        # the positional API allows it (CHANGE COLUMN moves drop-then-add)
        out, dropped = schema_utils.drop_column_at(base, [0])
        assert dropped.name == "a" and len(out.fields) == 0

    def test_drop_from_non_struct_errors(self):
        from delta_tpu.utils.errors import DeltaAnalysisError

        base = _s(("a", ArrayType(IntegerType())))
        with pytest.raises(DeltaAnalysisError, match="StructType"):
            schema_utils.drop_column_at(base, [0, 0])


class TestFindColumnPosition:
    BASE = _s(
        ("a", _s(("a1", IntegerType()), ("a2", IntegerType()))),
        ("b", IntegerType()),
        ("arr", ArrayType(_s(("x", IntegerType()), ("y", IntegerType())))),
        ("m", MapType(_s(("k", IntegerType())), _s(("v", IntegerType())))),
    )

    def test_top_level(self):
        assert schema_utils.find_column_position(["b"], self.BASE) == [1]

    def test_nested_struct_case_insensitive(self):
        assert schema_utils.find_column_position(["A", "A2"], self.BASE) == [0, 1]

    def test_array_element(self):
        assert schema_utils.find_column_position(
            ["arr", "element", "y"], self.BASE
        ) == [2, schema_utils.ARRAY_ELEMENT_INDEX, 1]

    def test_map_key_value(self):
        assert schema_utils.find_column_position(
            ["m", "key", "k"], self.BASE
        ) == [3, schema_utils.MAP_KEY_INDEX, 0]
        assert schema_utils.find_column_position(
            ["m", "value", "v"], self.BASE
        ) == [3, schema_utils.MAP_VALUE_INDEX, 0]

    def test_missing_column_errors(self):
        from delta_tpu.utils.errors import DeltaAnalysisError

        with pytest.raises(DeltaAnalysisError, match="Couldn't find column"):
            schema_utils.find_column_position(["zz"], self.BASE)

    def test_array_without_element_keyword_errors(self):
        from delta_tpu.utils.errors import DeltaAnalysisError

        with pytest.raises(DeltaAnalysisError, match="ArrayType"):
            schema_utils.find_column_position(["arr", "x"], self.BASE)

    def test_round_trips_with_add_column(self):
        pos = schema_utils.find_column_position(["a", "a2"], self.BASE)
        out = schema_utils.add_column(self.BASE, StructField("mid", LongType()), pos)
        inner = out.fields[0].data_type
        assert [f.name for f in inner.fields] == ["a1", "mid", "a2"]


# ---------------------------------------------------------------------------
# duplication + read compatibility + name hygiene
# ---------------------------------------------------------------------------


class TestDuplication:
    def test_top_level_case_insensitive(self):
        from delta_tpu.utils.errors import DeltaAnalysisError

        dup = StructType().add("x", IntegerType()).add("X", LongType())
        with pytest.raises(DeltaAnalysisError, match="duplicate"):
            schema_utils.check_column_name_duplication(dup, "in test")

    def test_nested_duplicate_detected(self):
        from delta_tpu.utils.errors import DeltaAnalysisError

        dup = _s(("s", StructType().add("y", IntegerType()).add("Y", LongType())))
        with pytest.raises(DeltaAnalysisError, match="duplicate"):
            schema_utils.check_column_name_duplication(dup, "in test")

    def test_same_name_at_different_levels_is_fine(self):
        ok = _s(("x", _s(("x", IntegerType()))))
        schema_utils.check_column_name_duplication(ok, "in test")


class TestReadCompatibility:
    def test_adding_nullable_column_is_compatible(self):
        old = _s(("a", IntegerType()))
        new = _s(("a", IntegerType()), ("b", StringType()))
        assert schema_utils.is_read_compatible(old, new)

    def test_dropping_column_is_incompatible(self):
        old = _s(("a", IntegerType()), ("b", StringType()))
        new = _s(("a", IntegerType()))
        assert not schema_utils.is_read_compatible(old, new)

    def test_type_change_is_incompatible(self):
        old = _s(("a", IntegerType()))
        new = _s(("a", LongType()))
        assert not schema_utils.is_read_compatible(old, new)

    def test_tightening_nullability_is_incompatible(self):
        old = StructType().add("a", IntegerType(), True)
        new = StructType().add("a", IntegerType(), False)
        assert not schema_utils.is_read_compatible(old, new)

    def test_nested_struct_checked(self):
        old = _s(("s", _s(("x", IntegerType()))))
        new = _s(("s", _s(("x", LongType()))))
        assert not schema_utils.is_read_compatible(old, new)


class TestNameHygiene:
    def test_invalid_characters_rejected(self):
        from delta_tpu.utils.errors import DeltaAnalysisError

        for bad in ["a b", "a,b", "a;b", "a{b", "a(b", "a=b", "a\tb"]:
            with pytest.raises(DeltaAnalysisError, match="invalid character"):
                schema_utils.check_column_names(_s((bad, IntegerType())))

    def test_nested_invalid_name_rejected(self):
        from delta_tpu.utils.errors import DeltaAnalysisError

        bad = _s(("ok", _s(("bad name", IntegerType()))))
        with pytest.raises(DeltaAnalysisError, match="invalid character"):
            schema_utils.check_column_names(bad)

    def test_normalize_reports_case_fixups(self):
        table = _s(("Alpha", IntegerType()), ("beta", StringType()))
        data = _s(("ALPHA", IntegerType()), ("beta", StringType()))
        assert schema_utils.normalize_column_names(table, data) == [("ALPHA", "Alpha")]
