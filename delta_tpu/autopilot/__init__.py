"""Autopilot — closed-loop maintenance scheduler (ROADMAP item 2).

The advisor (`obs/advisor`) emits ranked, evidence-backed recommendations
and the doctor (`obs/doctor`) names remedies; this package ACTS on them,
unattended, under guardrails — the step that makes a fleet of tables
operable without a human running OPTIMIZE/CHECKPOINT/VACUUM by hand.
"Only Aggressive Elephants are Fast Elephants" (PAPERS.md) is the
precedent: aggressive automatic layout/metadata maintenance is safe
exactly when every failure path is as tested as the fast path — which the
fault injector (PR 5), group commit (PR 9), and the static-analysis gates
(PR 10) made true here first.

* :mod:`~delta_tpu.autopilot.planner` — decide: doctor + advisor →
  deduped, prioritized :class:`~delta_tpu.obs.actions.MaintenanceAction`
  plan; quiet-window / cooldown / backoff guardrail inputs.
* :mod:`~delta_tpu.autopilot.executor` — act: run one action under the
  cost caps and the maintenance commit-attempts cap; build the
  predicted-vs-realized audit.
* :mod:`~delta_tpu.autopilot.daemon` — the loop: :func:`run_once` per
  table, the ``delta-autopilot`` daemon thread, and :func:`status` for
  the ``/autopilot`` HTTP route.

Everything persists through the workload journal's action ledger (journal
kind ``autopilot``), which `advise()` reads back — executed actions are
cited with their realized deltas instead of being re-recommended during
their cooldown. ``tools/journal_dump.py --autopilot`` prints the ledger.
"""
from delta_tpu.autopilot.daemon import (
    Autopilot,
    RunReport,
    dry_run,
    enabled,
    last_runs,
    reset,
    run_once,
    status,
)

__all__ = ["Autopilot", "RunReport", "run_once", "status", "enabled",
           "dry_run", "last_runs", "reset"]
