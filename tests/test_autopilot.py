"""Autopilot maintenance scheduler (`delta_tpu/autopilot/`): the closed
observe→decide→act→audit loop, its guardrails (dry-run, cost caps,
cooldowns, quiet windows, contention backoff, capped maintenance commit
attempts), the shared action catalog both the doctor and the advisor now
cite, the persistent action ledger, and crash consistency under fault
injection.
"""
import json
import http.client
import time
from collections import Counter

import pyarrow as pa
import pytest

from delta_tpu import autopilot
from delta_tpu.api.tables import DeltaTable
from delta_tpu.autopilot import executor as executor_mod
from delta_tpu.autopilot import planner as planner_mod
from delta_tpu.log.deltalog import DeltaLog
from delta_tpu.obs import actions as actions_mod
from delta_tpu.obs import journal
from delta_tpu.obs.actions import MaintenanceAction
from delta_tpu.obs.advisor import advise
from delta_tpu.obs.doctor import SEVERITY_RANK, doctor
from delta_tpu.storage.faults import FaultPlan, SimulatedCrash
from delta_tpu.utils import errors, telemetry
from delta_tpu.utils.config import conf


@pytest.fixture(autouse=True)
def _fresh_state():
    telemetry.reset_all()
    journal.reset()
    autopilot.reset()
    yield
    autopilot.reset()
    journal.reset()
    telemetry.reset_all()


def _ids(n, start=0):
    import numpy as np

    return pa.table({
        "id": np.arange(start, start + n).astype("int64"),
        "v": (np.arange(start, start + n) * 7 % 1000).astype("int64"),
    })


def _debt_table(path, appends=24, checkpoint_interval="1000"):
    """A table with seeded small-file + stale-checkpoint debt: many tiny
    commits, interval checkpointing effectively off."""
    t = DeltaTable.create(
        path, data=_ids(8),
        configuration={"delta.checkpointInterval": checkpoint_interval},
    )
    for i in range(appends):
        t.write(_ids(8, start=1000 * (i + 1)))
    return t


def _ledger(path):
    log_path = path.rstrip("/") + "/_delta_log"
    journal.flush(log_path)
    return journal.read_entries(log_path, kinds=["autopilot"])


def _quiet_conf(**extra):
    base = {
        "delta.tpu.autopilot.dryRun": False,
        # the debt-seeding commits happened milliseconds ago: shrink the
        # quiet window so the table counts as quiet without sleeping long
        "delta.tpu.autopilot.quietWindowMs": 50,
    }
    base.update(extra)
    return conf.set_temporarily(**base)


# -- shared action catalog ---------------------------------------------------


def test_action_catalog_validates_names():
    assert actions_mod.spec("OPTIMIZE").executable
    assert actions_mod.spec("OPTIMIZE").mutates_table
    assert not actions_mod.spec("REPARTITION").executable
    assert not actions_mod.spec("EVICT").mutates_table
    with pytest.raises(ValueError, match="not registered"):
        actions_mod.spec("DEFRAG")
    with pytest.raises(ValueError):
        MaintenanceAction(kind="DEFRAG", table_path="/t")
    assert set(actions_mod.executable_kinds()) == {
        "OPTIMIZE", "ZORDER", "CHECKPOINT", "VACUUM", "PURGE", "EVICT",
        "RECALIBRATE"}
    # every advisor kind maps into the catalog
    for kind, action in actions_mod.RECOMMENDATION_ACTIONS.items():
        assert action in actions_mod.CATALOG, (kind, action)


def test_maintenance_action_roundtrip_and_malformed():
    a = MaintenanceAction(kind="ZORDER", table_path="/t", target="v",
                          params={"columns": ["v"]}, source="advisor:ZORDER",
                          priority=3.5, predicted={"pruningMissRate": 1.0})
    back = MaintenanceAction.from_dict(a.to_dict())
    assert back is not None and back.key == a.key == "ZORDER:v"
    assert back.params == {"columns": ["v"]}
    assert MaintenanceAction.from_dict({"kind": "NOPE"}) is None
    assert MaintenanceAction.from_dict({}) is None


def test_doctor_and_advisor_cite_the_catalog(tmp_table):
    """Remedy unification satellite: both report surfaces emit only catalog
    keys and cite the catalog reference in to_dict."""
    t = _debt_table(tmp_table, appends=24)
    doc = t.doctor().to_dict()
    assert doc["remedyCatalog"] == actions_mod.CATALOG_REF
    for d in doc["dimensions"]:
        if d["remedy"] is not None:
            assert d["remedy"] in actions_mod.CATALOG
    log_path = t.delta_log.log_path
    from delta_tpu.expr.parser import parse_predicate

    for _ in range(4):
        journal.record_scan(log_path, report_dict={
            "filesTotal": 8, "filesScanned": 8, "rowGroupsTotal": 8},
            predicate=parse_predicate("v = 2"))
    adv = advise(tmp_table).to_dict()
    assert adv["remedyCatalog"] == actions_mod.CATALOG_REF
    zorder = [r for r in adv["recommendations"] if r["kind"] == "ZORDER"]
    assert zorder and zorder[0]["remedy"] == "ZORDER"
    for r in adv["recommendations"]:
        assert r["remedy"] in actions_mod.CATALOG


# -- dry run (the default posture) -------------------------------------------


def test_dry_run_journals_plan_and_executes_nothing(tmp_table):
    t = _debt_table(tmp_table)
    v_before = t.delta_log.update().version
    assert autopilot.dry_run()  # default ON
    rep = autopilot.run_once(tmp_table)
    assert rep.status == "dry-run"
    assert rep.planned and {a["kind"] for a in rep.planned} >= {"OPTIMIZE"}
    assert rep.outcomes == []
    # nothing committed, nothing rewritten
    assert t.delta_log.update().version == v_before
    entries = _ledger(tmp_table)
    assert entries and all(e["phase"] == "planned" for e in entries)
    assert all(e.get("dryRun") is True for e in entries)
    # a dry-run plan arms no cooldown: the next pass re-plans it
    rep2 = autopilot.run_once(tmp_table)
    assert rep2.planned and rep2.cooled == []


# -- the closed-loop acceptance scenario -------------------------------------


def test_closed_loop_acceptance(tmp_table):
    """Seeded small-file + stale-checkpoint debt; the autopilot (non-dry)
    executes the remedies in a quiet window; doctor severities improve; the
    ledger records predicted-vs-realized deltas; advise() cites the
    executed actions and run 2 cooldown-filters them."""
    t = _debt_table(tmp_table, appends=24)
    doc_before = t.doctor()
    assert doc_before.dimension("smallFiles").severity != "ok"
    assert doc_before.dimension("checkpoint").severity != "ok"

    with _quiet_conf():
        time.sleep(0.1)  # let the seeding commits age out of the window
        rep = autopilot.run_once(tmp_table)
    assert rep.status == "ok"
    assert rep.quiet["quiet"] is True
    by_action = {o["action"]: o for o in rep.outcomes}
    assert by_action["OPTIMIZE"]["status"] == "executed"
    assert by_action["CHECKPOINT"]["status"] == "executed"
    # rewritten bytes are metered (they draw down the per-run byte pool)
    assert by_action["OPTIMIZE"]["result"]["metrics"]["numRemovedBytes"] > 0

    # doctor severities improved
    doc_after = DeltaTable.for_path(tmp_table).doctor()
    for dim in ("smallFiles", "checkpoint"):
        assert (SEVERITY_RANK[doc_after.dimension(dim).severity]
                < SEVERITY_RANK[doc_before.dimension(dim).severity])

    # the ledger records predicted-vs-realized
    executed = [e for e in _ledger(tmp_table) if e["phase"] == "executed"]
    assert {e["action"]["kind"] for e in executed} >= {"OPTIMIZE",
                                                       "CHECKPOINT"}
    opt = next(e for e in executed if e["action"]["kind"] == "OPTIMIZE")
    audit = opt["audit"]
    assert audit["predicted"]["count"] == doc_before.dimension(
        "smallFiles").metrics["count"]
    assert audit["realized"]["count"] > 0
    assert audit["verdict"] == "improved"
    assert audit["severityBefore"] != "ok" and audit["severityAfter"] == "ok"

    # advise() cites the executed actions...
    adv = DeltaTable.for_path(tmp_table).advise()
    ap = adv.facts["autopilot"]
    assert ap["executed"] >= 2
    assert "OPTIMIZE" in ap["cooldownActive"]
    cited = {a["kind"]: a for a in ap["recentActions"]}
    assert cited["OPTIMIZE"]["verdict"] == "improved"
    assert cited["OPTIMIZE"]["realized"]["count"] > 0

    # ...and run 2 does not re-plan them (cooldown)
    with _quiet_conf():
        rep2 = autopilot.run_once(tmp_table)
    replanned = {a["kind"] for a in rep2.planned}
    assert "OPTIMIZE" not in replanned and "CHECKPOINT" not in replanned
    json.dumps(rep.to_dict())  # report JSON-able end to end


def test_zorder_from_advisor_executes_and_is_suppressed(tmp_table):
    """The advisor's ZORDER recommendation becomes an executed action, and
    the NEXT advise() suppresses the recommendation, citing the ledger."""
    t = DeltaTable.create(tmp_table, data=_ids(64))
    log_path = t.delta_log.log_path
    from delta_tpu.expr.parser import parse_predicate

    for _ in range(4):  # filtered, never pruned: ZORDER evidence
        journal.record_scan(log_path, report_dict={
            "filesTotal": 8, "filesScanned": 8, "rowGroupsTotal": 8},
            predicate=parse_predicate("v = 2"))
    adv = advise(tmp_table)
    assert [r for r in adv.recommendations
            if r.kind == "ZORDER" and r.target == "v"]

    with _quiet_conf():
        time.sleep(0.1)
        rep = autopilot.run_once(tmp_table)
    zorder = [o for o in rep.outcomes if o["action"] == "ZORDER:v"]
    assert zorder and zorder[0]["status"] == "executed"
    # longitudinal action: realized effect pending until fresh scans land
    assert zorder[0]["audit"]["verdict"] == "pending"
    assert zorder[0]["audit"]["predicted"]["pruningMissRate"] == 1.0

    adv2 = advise(tmp_table)
    assert not [r for r in adv2.recommendations
                if r.kind == "ZORDER" and r.target == "v"]
    sup = adv2.facts["autopilot"]["suppressed"]
    assert any(s["remedy"] == "ZORDER" and s["target"] == "v" for s in sup)


# -- guardrails --------------------------------------------------------------


def test_cost_cap_aborts_over_budget_optimize(tmp_table):
    t = _debt_table(tmp_table, appends=20, checkpoint_interval="10")
    v_before = t.delta_log.update().version
    with _quiet_conf(**{"delta.tpu.autopilot.maxBytesPerRun": 1}):
        time.sleep(0.1)
        rep = autopilot.run_once(tmp_table)
    opt = next(o for o in rep.outcomes if o["action"] == "OPTIMIZE")
    assert opt["status"] == "skipped"
    assert "cost cap" in opt["result"]["reason"]
    assert opt["result"]["metrics"]["estBytes"] > 1
    assert opt["result"]["metrics"]["capBytes"] == 1
    # journaled SKIPPED outcome, and no commit happened
    skipped = [e for e in _ledger(tmp_table) if e["phase"] == "skipped"]
    assert skipped and skipped[0]["action"]["kind"] == "OPTIMIZE"
    assert t.delta_log.update().version == v_before


def test_quiet_window_defers_then_force_executes(tmp_table):
    t = _debt_table(tmp_table, appends=20, checkpoint_interval="10")
    # default 60s window: the seeding commits are fresh, so NOT quiet
    with conf.set_temporarily(**{"delta.tpu.autopilot.dryRun": False}):
        rep = autopilot.run_once(tmp_table)
        assert rep.status == "deferred"
        assert rep.quiet["quiet"] is False
        assert rep.quiet["recentCommits"] > 0
        assert rep.outcomes == []
        deferred = [e for e in _ledger(tmp_table)
                    if e["phase"] == "deferred"]
        assert deferred and deferred[0]["reason"] == "window not quiet"
        # deferral arms no cooldown; force executes NOW
        rep2 = autopilot.run_once(tmp_table, force=True)
    assert any(o["status"] == "executed" for o in rep2.outcomes)
    assert t.doctor().dimension("smallFiles").severity == "ok"


def test_contention_backoff_blocks_the_table(tmp_table):
    t = _debt_table(tmp_table, appends=20, checkpoint_interval="10")
    log_path = t.delta_log.log_path
    # a maintenance commit just lost to a foreground writer
    a = MaintenanceAction(kind="OPTIMIZE", table_path=tmp_table)
    journal.record_autopilot(log_path, "abortedContention", a.to_dict())
    with _quiet_conf(**{"delta.tpu.autopilot.contentionBackoffMs": 60_000,
                        # cooldown must not mask what we test: the OPTIMIZE
                        # attempt itself is inside its cooldown too, so
                        # check the backoff via a would-be CHECKPOINT
                        "delta.tpu.autopilot.cooldownMs": 1}):
        time.sleep(0.1)
        rep = autopilot.run_once(tmp_table, force=True)
    assert rep.status == "deferred"
    assert rep.backoff_until_ms is not None
    assert rep.outcomes == []


def test_cooldown_prevents_reexecution_after_started_only_entry(tmp_table):
    """A 'started' ledger entry with NO terminal outcome (= crashed
    mid-action) must block re-planning — the crash-loop guard."""
    t = _debt_table(tmp_table, appends=20, checkpoint_interval="10")
    a = MaintenanceAction(kind="OPTIMIZE", table_path=tmp_table)
    journal.record_autopilot(t.delta_log.log_path, "started", a.to_dict())
    rep = autopilot.run_once(tmp_table)
    assert "OPTIMIZE" in rep.cooled
    assert not any(p["kind"] == "OPTIMIZE" for p in rep.planned)


def test_cooldown_survives_ledger_sweep(tmp_table):
    """The journal's size/age sweep may evict the segment holding a
    'started' entry mid-cooldown; the sweep-proof sidecar must keep the
    cooldown armed anyway."""
    import os

    t = _debt_table(tmp_table, appends=20, checkpoint_interval="10")
    log_path = t.delta_log.log_path
    a = MaintenanceAction(kind="OPTIMIZE", table_path=tmp_table)
    assert journal.record_autopilot(log_path, "started", a.to_dict())
    assert journal.record_attempt(log_path, a.key, "started",
                                  int(time.time() * 1000))
    # simulate the sweep taking every ledger segment
    jdir = journal.journal_dir(log_path)
    journal.flush(log_path)
    journal.reset()
    for n in os.listdir(jdir):
        if n.startswith(journal.SEGMENT_PREFIX):
            os.remove(os.path.join(jdir, n))
    assert journal.read_entries(log_path, kinds=["autopilot"]) == []
    blocked = planner_mod.cooldown_blocked([], int(time.time() * 1000),
                                           log_path=log_path)
    assert "OPTIMIZE" in blocked
    assert blocked["OPTIMIZE"]["source"] == "stateFile"
    rep = autopilot.run_once(tmp_table)
    assert "OPTIMIZE" in rep.cooled


def test_degraded_journal_refuses_to_execute(tmp_table):
    """An unwritable journal directory cannot arm a cooldown — the
    autopilot must skip the action (ledgerUnwritable), not execute with a
    crash-loop window open. (A plain file squatting on the _journal path
    makes every segment/sidecar write fail, even for root.)"""
    import os
    import shutil

    t = _debt_table(tmp_table, appends=20, checkpoint_interval="10")
    log_path = t.delta_log.log_path
    v_before = t.delta_log.update().version
    jdir = journal.journal_dir(log_path)
    journal.flush(log_path)
    journal.reset()
    shutil.rmtree(jdir, ignore_errors=True)
    with open(jdir, "w") as f:  # a FILE where the journal dir must go
        f.write("squatter")
    try:
        with _quiet_conf():
            time.sleep(0.1)
            rep = autopilot.run_once(tmp_table, force=True)
    finally:
        os.remove(jdir)
    assert rep.planned  # it still planned (journal conf is on)...
    assert rep.outcomes  # ...but refused to execute anything
    assert all(o["status"] == "skipped"
               and o["reason"] == "ledgerUnwritable" for o in rep.outcomes)
    assert t.delta_log.update().version == v_before


def test_run_budget_skips_remaining_actions(tmp_table):
    t = _debt_table(tmp_table, appends=24)
    with _quiet_conf(**{"delta.tpu.autopilot.budgetMs": 0}):
        time.sleep(0.1)
        rep = autopilot.run_once(tmp_table)
    assert rep.planned
    assert all(o["status"] == "skipped" and o["reason"] == "runBudget"
               for o in rep.outcomes)
    skipped = [e for e in _ledger(tmp_table) if e["phase"] == "skipped"]
    assert skipped and "budget" in skipped[0]["reason"]


def test_journal_disabled_refuses_to_act(tmp_table):
    t = _debt_table(tmp_table, appends=20, checkpoint_interval="10")
    v_before = t.delta_log.update().version
    with conf.set_temporarily(**{"delta.tpu.autopilot.dryRun": False,
                                 "delta.tpu.journal.enabled": False}):
        rep = autopilot.run_once(tmp_table, force=True)
    assert rep.status == "journal disabled"
    assert rep.planned == [] and rep.outcomes == []
    assert t.delta_log.update().version == v_before


# -- maintenance commits lose gracefully -------------------------------------


def test_commit_attempts_cap_loses_gracefully(tmp_table):
    """Under commit_attempts_cap a racing commit exhausts as
    CommitAttemptsExhausted instead of retrying 10M times; without the cap
    the same race retries and wins."""
    from delta_tpu.commands.write import WriteIntoDelta
    from delta_tpu.txn.transaction import commit_attempts_cap

    t = DeltaTable.create(tmp_table, data=_ids(8))
    log = t.delta_log

    def _racing_txn():
        txn = log.start_transaction()
        # a foreground writer lands a version before we commit
        WriteIntoDelta(DeltaLog(tmp_table), "append", _ids(8, 500)).run()
        return txn

    txn = _racing_txn()
    with commit_attempts_cap(1):
        with pytest.raises(errors.CommitAttemptsExhausted):
            from delta_tpu.commands import operations as ops

            txn.commit([], ops.Optimize(predicate=[]))
    # same race, no cap: the retry loop absorbs it
    txn2 = _racing_txn()
    from delta_tpu.commands import operations as ops

    assert txn2.commit([], ops.Optimize(predicate=[])) >= 0


def test_attempts_cap_never_leaks_to_stamped_foreground_txns():
    """A group-commit leader running inside a maintenance cap processes
    foreground batchmates: their txn stamp (None = uncapped) is
    authoritative, the leader thread's contextvar must not apply."""
    from delta_tpu.txn import transaction as txn_mod

    class _Stamped:
        _attempts_cap = None  # a foreground member: commit() stamped None

    limit = conf.get("delta.tpu.maxCommitAttempts")
    with txn_mod.commit_attempts_cap(3):
        assert txn_mod.effective_max_commit_attempts(_Stamped()) == limit
        # the maintenance thread's own (unstamped) context stays capped
        assert txn_mod.effective_max_commit_attempts(None) == 3
    assert txn_mod.effective_max_commit_attempts(None) == limit


def test_executor_classifies_contention(tmp_table):
    """An executor-level conflict comes back as abortedContention and bumps
    the contention counter (no retry storm: attempts were capped)."""
    t = _debt_table(tmp_table, appends=20, checkpoint_interval="10")
    real_run = executor_mod._run_optimize

    def _losing_run(*a, **kw):
        raise errors.CommitAttemptsExhausted("lost the race (test)")

    executor_mod._run_optimize = _losing_run
    try:
        res = executor_mod.execute(
            t.delta_log,
            MaintenanceAction(kind="OPTIMIZE", table_path=tmp_table),
            attempts_cap=1)
    finally:
        executor_mod._run_optimize = real_run
    assert res.status == "abortedContention"
    assert "foreground" in res.reason
    assert telemetry.counters("autopilot.contentionAborts")


# -- crash consistency (fault injection) -------------------------------------


def test_contention_abort_halts_the_rest_of_the_run(tmp_table):
    """One lost maintenance commit backs the whole table off IN-RUN: the
    remaining planned actions defer instead of racing the same writers."""
    t = _debt_table(tmp_table, appends=24)  # CHECKPOINT + OPTIMIZE plan
    real_run = executor_mod._run_checkpoint

    def _losing_run(*a, **kw):
        raise errors.CommitAttemptsExhausted("lost the race (test)")

    executor_mod._run_checkpoint = _losing_run  # first action in the plan
    try:
        with _quiet_conf():
            time.sleep(0.1)
            rep = autopilot.run_once(tmp_table, force=True)
    finally:
        executor_mod._run_checkpoint = real_run
    statuses = {o["action"]: o["status"] for o in rep.outcomes}
    assert statuses["CHECKPOINT"] == "abortedContention"
    assert statuses["OPTIMIZE"] == "deferred"
    # and the armed backoff blocks the NEXT pass too
    with _quiet_conf():
        rep2 = autopilot.run_once(tmp_table, force=True)
    assert rep2.status == "deferred" and rep2.backoff_until_ms


def test_simulated_crash_mid_maintenance(tmp_table):
    """A SimulatedCrash inside the maintenance commit: the table stays
    consistent, the interrupted action is journaled, and the cooldown
    prevents crash-loop re-execution on the restarted process."""
    t = _debt_table(tmp_table, appends=20, checkpoint_interval="10")
    rows_before = sorted(t.to_arrow(columns=["id"]).column("id").to_pylist())
    plan = FaultPlan(script=[("write.commit", "crash_before_publish")])
    with _quiet_conf(**{"delta.tpu.faults.plan": plan}):
        time.sleep(0.1)
        with pytest.raises(SimulatedCrash):
            autopilot.run_once(tmp_table)

    # the restarted process: fresh log over whatever the crash left
    DeltaLog.invalidate_cache(tmp_table)
    t2 = DeltaTable.for_path(tmp_table)
    rows_after = sorted(
        t2.to_arrow(columns=["id"]).column("id").to_pylist())
    assert rows_after == rows_before  # no row lost, none duplicated

    phases = Counter(e["phase"] for e in _ledger(tmp_table))
    assert phases["started"] == 1
    assert phases["interrupted"] == 1
    assert phases.get("executed", 0) == 0

    # crash-loop guard: the restarted autopilot cooldown-filters the action
    with _quiet_conf():
        rep = autopilot.run_once(tmp_table, force=True)
    assert "OPTIMIZE" in rep.cooled
    assert not any(o["action"].startswith("OPTIMIZE")
                   for o in rep.outcomes)


def test_torture_with_autopilot_tier1(tmp_path):
    """Fixed-seed torture subset with the autopilot in the mix: all PR 5
    invariants hold across crashes, and no action key is ever ATTEMPTED
    twice inside its cooldown window (crash-loop guard, ledger-verified)."""
    from delta_tpu.testing.harness import run_torture

    path = str(tmp_path / "torture")
    report = run_torture(path, seed=42, steps=100, autopilot=True)
    assert report.op_counts.get("autopilot", 0) >= 2
    assert report.invariant_checks >= 10
    entries = journal.read_entries(path + "/_delta_log",
                                   kinds=["autopilot"])
    phases = Counter(e["phase"] for e in entries)
    assert phases["started"] >= 1  # maintenance really ran under faults
    # every started has a terminal sibling or the run crashed right there —
    # and attempts per action key never violate the cooldown
    cooldown_ms = 2000  # harness default autopilot_cooldown_ms
    attempts = {}
    for e in entries:
        if e["phase"] not in planner_mod.COOLDOWN_PHASES:
            continue
        key = e["action"]["kind"] + (
            ":" + e["action"]["target"] if e["action"].get("target") else "")
        ts = e["ts"]
        prev = attempts.get(key)
        # "started" + its terminal entry share one attempt window; compare
        # only across distinct started markers
        if e["phase"] == "started" and prev is not None:
            assert ts - prev >= cooldown_ms, (
                f"{key} re-attempted {ts - prev}ms after the last attempt")
        if e["phase"] == "started":
            attempts[key] = ts


# -- EVICT / RECALIBRATE (process-local actions) -----------------------------


def test_evict_and_recalibrate_execute(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(8))
    res = executor_mod.execute(
        t.delta_log, MaintenanceAction(kind="EVICT", table_path=tmp_table))
    assert res.status == "executed"
    assert res.metrics["pressureApplied"] is False  # no budget configured
    res = executor_mod.execute(
        t.delta_log,
        MaintenanceAction(kind="RECALIBRATE", table_path=tmp_table))
    assert res.status == "executed"
    assert res.metrics["calibrationEnabled"] is False
    assert res.metrics["constantsInstalled"] == 0


def test_planner_plans_evict_under_hbm_pressure(tmp_table):
    from delta_tpu.obs import hbm_ledger

    t = DeltaTable.create(tmp_table, data=_ids(8))
    hbm_ledger.adjust("keyCache", 1000)
    try:
        with conf.set_temporarily(
                **{"delta.tpu.device.hbmBudgetBytes": 100}):
            doc = t.doctor()
            assert doc.dimension("device").severity != "ok"
            plan = planner_mod.plan(doc, advise(tmp_table))
        assert any(a.kind == "EVICT" for a in plan)
    finally:
        hbm_ledger.reset()


# -- daemon ------------------------------------------------------------------


def test_daemon_is_opt_in_and_ticks(tmp_table):
    with pytest.raises(errors.DeltaIllegalStateError, match="opt-in"):
        autopilot.Autopilot()
    _debt_table(tmp_table, appends=20, checkpoint_interval="10")
    with conf.set_temporarily(**{"delta.tpu.autopilot.enabled": True,
                                 "delta.tpu.autopilot.intervalMs": 50}):
        pilot = autopilot.Autopilot(tables=[tmp_table]).start()
        try:
            assert pilot.running
            deadline = time.monotonic() + 10
            while (tmp_table not in autopilot.last_runs()
                   and time.monotonic() < deadline):
                time.sleep(0.02)
        finally:
            pilot.stop()
        assert not pilot.running
    run = autopilot.last_runs()[tmp_table]
    assert run["dryRun"] is True  # dry-run posture holds in the daemon
    assert run["planned"], "the daemon pass planned the seeded debt"
    st = autopilot.status()
    assert st["dryRun"] is True and st["daemonRunning"] is False
    assert st["guardrails"]["maxCommitAttempts"] == 3
    json.dumps(st)


def test_one_table_at_a_time_lock(tmp_table):
    _debt_table(tmp_table, appends=20, checkpoint_interval="10")
    from delta_tpu.autopilot import daemon as daemon_mod

    assert daemon_mod._EXEC_LOCK.acquire(blocking=False)
    try:
        with _quiet_conf():
            time.sleep(0.1)
            rep = autopilot.run_once(tmp_table, force=True)
    finally:
        daemon_mod._EXEC_LOCK.release()
    assert rep.status == "busy"
    assert rep.outcomes == []


# -- surfaces: HTTP route + dump tool ----------------------------------------


def test_autopilot_http_route(tmp_table):
    from delta_tpu.obs.server import ObsServer

    t = _debt_table(tmp_table, appends=20, checkpoint_interval="10")
    a = MaintenanceAction(kind="OPTIMIZE", table_path=tmp_table)
    journal.record_autopilot(t.delta_log.log_path, "planned", a.to_dict(),
                             dryRun=True)
    server = ObsServer(port=0)
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.port)
        conn.request("GET", "/autopilot")
        body = json.loads(conn.getresponse().read())
        assert body["enabled"] is False and body["dryRun"] is True
        assert "guardrails" in body and "ledger" not in body
        conn.request("GET", f"/autopilot?path={tmp_table}&limit=10")
        body = json.loads(conn.getresponse().read())
        assert body["ledger"] and body["ledger"][-1]["phase"] == "planned"
        assert body["ledger"][-1]["action"]["kind"] == "OPTIMIZE"
        # malformed limit degrades, never a 500
        conn.request("GET", f"/autopilot?path={tmp_table}&limit=bogus")
        assert conn.getresponse().status == 200
    finally:
        server.stop()


def test_journal_dump_autopilot_flag(tmp_table, capsys):
    import tools.journal_dump as dump

    t = _debt_table(tmp_table, appends=20, checkpoint_interval="10")
    with _quiet_conf():
        time.sleep(0.1)
        autopilot.run_once(tmp_table)
    assert dump.main([tmp_table, "--autopilot"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["entries"] >= 3  # planned + started + executed at minimum
    assert out["byPhase"].get("executed", 0) >= 1
    assert out["executedVerdicts"].get("improved", 0) >= 1
    kinds = {e["action"]["kind"] for e in out["ledger"]}
    assert "OPTIMIZE" in kinds


# -- blackout / counters -----------------------------------------------------


def test_counters_and_gauge_are_cataloged(tmp_table):
    from delta_tpu.obs import metric_names

    _debt_table(tmp_table, appends=20, checkpoint_interval="10")
    with _quiet_conf():
        time.sleep(0.1)
        autopilot.run_once(tmp_table)
    for name in ("autopilot.runs", "autopilot.actions.planned",
                 "autopilot.actions.executed"):
        assert name in metric_names.COUNTERS
        assert telemetry.counters(name), name
    assert "autopilot.lastRunTimestamp" in metric_names.GAUGES
    assert telemetry.gauges("autopilot.lastRunTimestamp")


def test_optimize_budget_exceeded_is_pre_io(tmp_table):
    """The cost cap aborts before any file is read or written: no parquet
    file appears and no commit lands."""
    import os

    from delta_tpu.commands.optimize import (OptimizeBudgetExceeded,
                                             OptimizeCommand)

    t = _debt_table(tmp_table, appends=20, checkpoint_interval="10")
    v = t.delta_log.update().version
    files_before = {f for f in os.listdir(tmp_table) if f.endswith(".parquet")}
    with pytest.raises(OptimizeBudgetExceeded) as ei:
        OptimizeCommand(t.delta_log, max_rewrite_bytes=1).run()
    assert ei.value.est_bytes > ei.value.cap_bytes == 1
    assert ei.value.files >= 16
    assert t.delta_log.update().version == v
    assert {f for f in os.listdir(tmp_table)
            if f.endswith(".parquet")} == files_before
