"""Deterministic fault-injection test harness (crash-consistency torture).

See :mod:`delta_tpu.testing.harness`.
"""
from delta_tpu.testing.harness import TortureHarness, TortureReport, run_torture

__all__ = ["TortureHarness", "TortureReport", "run_torture"]
