"""Shared DML machinery: candidate selection and file rewrites.

The reference's `commands/DeltaCommand.scala:48-219` equivalent — resolve the
files a predicate may touch (partition pruning + stats skipping), read them,
and rewrite survivors — but columnar: per-file row masks come from one
vectorized predicate evaluation instead of `input_file_name()` joins.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import List, Optional, Sequence

import pyarrow as pa

from delta_tpu.exec.scan import read_files_as_table
from delta_tpu.expr import ir
from delta_tpu.expr.vectorized import boolean_mask
from delta_tpu.ops import pruning
from delta_tpu.protocol.actions import AddFile

__all__ = ["TouchedFile", "candidate_files", "read_candidates", "Timer"]


class Timer:
    """Phase timer for operation metrics (scanTimeMs / rewriteTimeMs)."""

    def __init__(self):
        self.t0 = time.perf_counter()

    def lap_ms(self) -> int:
        now = time.perf_counter()
        ms = int((now - self.t0) * 1000)
        self.t0 = now
        return ms

    def peek_ms(self) -> int:
        return int((time.perf_counter() - self.t0) * 1000)


@dataclass
class TouchedFile:
    add: AddFile
    table: pa.Table  # full rows of the file (with partition columns)
    mask: pa.ChunkedArray  # True = row matches the predicate


def candidate_files(txn, predicate: Optional[ir.Expression]) -> List[AddFile]:
    """Files the predicate may touch; registers the read set on the txn.

    Conjuncts are split so a mixed predicate (``part='a' AND data>5``)
    records the partition leg as the transaction's read predicate — keeping
    the OCC read set partition-scoped instead of whole-table — while stats
    skipping still applies the data leg."""
    if predicate is None:
        return txn.filter_files()
    conjuncts = ir.split_conjuncts(predicate)
    matched = txn.filter_files(conjuncts)
    scan = pruning.files_for_scan(txn.snapshot, [predicate])
    kept_paths = {f.path for f in scan.files}
    return [f for f in matched if f.path in kept_paths]


def read_candidates(
    data_path: str,
    files: Sequence[AddFile],
    metadata,
    predicate: Optional[ir.Expression],
) -> List[TouchedFile]:
    """Read each candidate (parallel decode) and compute its match mask."""
    out: List[TouchedFile] = []
    tables = read_files_as_table(data_path, files, metadata, per_file=True)
    for add, t in zip(files, tables):
        if predicate is None:
            mask = pa.chunked_array([pa.array([True] * t.num_rows)])
        else:
            mask = boolean_mask(predicate, t)
        out.append(TouchedFile(add=add, table=t, mask=mask))
    return out
