"""Persistent per-table workload journal — longitudinal observability.

Doctor (`obs/doctor`) and the router audit ledger (`obs/router_audit`) are
point-in-time and per-process: when the process exits, every scan report,
commit stat, and routing decision is gone, and nothing can answer "what
layout does this table need for the queries it *actually* serves". This
module persists that evidence: one compact JSONL entry per operation,
batched into size/age-bounded segment files under
``<table>/_delta_log/_journal/`` and LRU-swept like the tmp-orphan sweep
(`log/cleanup.sweep_tmp_orphans`).

Entry kinds
===========

``scan``
    The per-query :class:`~delta_tpu.obs.scan_report.ScanReport` plus a
    normalized **predicate fingerprint** — columns referenced, per-conjunct
    op shapes with literals abstracted (``eq(v,?)``), and the
    prunable-vs-residual split (which conjuncts the shared skipping rewrite
    used by ``exec/rowgroups`` can lower to min/max stats, and which can
    only run as residual filters).
``commit``
    CommitStats (`txn/transaction`) plus the conflict/reconcile outcome and
    retry count — the raw material for contention-window analysis.
``dml``
    One entry per routed DML command (MERGE/UPDATE/DELETE): the router
    decision and the audit verdict when one was recorded.
``router``
    Every `obs/router_audit` record (merge joins AND scan-planning picks),
    so predicted-vs-actual routing history survives the audit ring.
``autopilot``
    The maintenance scheduler's **action ledger** (`delta_tpu/autopilot`):
    one entry per planned/started/executed/skipped/deferred action with the
    shared :mod:`~delta_tpu.obs.actions` model, its cited evidence, and —
    for executed actions — the predicted-vs-realized audit. Written through
    a synchronous flush (the autopilot's cooldowns survive a crash only if
    the "started" entry is on disk before the action runs).
``shadow``
    One :class:`~delta_tpu.replay.shadow.ShadowScorecard` per shadow-
    optimizer run (`delta_tpu/replay`): candidate layouts ranked by their
    MEASURED replay deltas against the baseline clone. The advisor
    attaches these verdicts to matching recommendations, and the
    autopilot's ``requireShadow`` guardrail gates rewrites on them.

Scan entries additionally carry a bounded **literal-sample reservoir**:
the first ``delta.tpu.journal.literalSamples`` (default 3) scans per
fingerprint key persist their concrete predicate SQL as ``sample`` —
deterministic first-K, so replays are stable — and every scan past the
bound has its report ``predicate`` redacted, making the reservoir the only
place concrete literals persist (size-bounded via :data:`SAMPLE_MAX_SQL`,
blackout-inert like every other journal write).

Hooks live in ``exec/scan.py``, ``txn/transaction.py``, ``commands/*`` and
``obs/router_audit.py``; each hook is a dict append under a lock — the IO
runs on a dedicated ``delta-journal-writer`` daemon thread (or inline in
:func:`flush`), never on the operation's thread. Fully inert under a
telemetry blackout (``delta.tpu.telemetry.enabled=false``) or with
``delta.tpu.journal.enabled=false``: zero bytes are written. Object-store
tables (``scheme://`` paths) skip journaling like `obs/calibration` skips
state files — the journal is plain local-file IO by design.

`obs/advisor` aggregates the journal into workload facts and ranked layout
recommendations; ``tools/journal_dump.py`` prints it offline.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf

__all__ = ["enabled", "journal_dir", "predicate_fingerprint", "record_scan",
           "record_commit", "record_dml", "record_router",
           "record_autopilot", "record_shadow", "record_dist",
           "attempt_state", "record_attempt", "flush", "read_entries",
           "sweep", "live_writer_spared", "reset"]

SEGMENT_PREFIX = "journal-"
SEGMENT_SUFFIX = ".jsonl"

#: Sweep-proof sidecar mirroring the autopilot's LAST attempt per action
#: key. Ledger entries live in journal segments the size/age sweep may
#: legitimately delete well inside a cooldown on a busy table; this one
#: small JSON file (not SEGMENT_PREFIX-named, so never swept) keeps the
#: cooldown/backoff guardrail durable for both the planner and the
#: advisor's suppression regardless of sweep pressure.
STATE_FILE = "_autopilot_state.json"

# per-table buffers keyed by journal dir; entries are ready-to-write dicts
_LOCK = threading.Lock()
_BUFFERS: Dict[str, List[Dict[str, Any]]] = {}
_OLDEST: Dict[str, float] = {}  # monotonic time of each buffer's oldest entry
# active segment per journal dir: (path, bytes_written) — files are opened
# in append mode per batch, never held open
_ACTIVE: Dict[str, Tuple[str, int]] = {}
_SWEPT: set = set()  # dirs swept at least once this process
_SEQ = 0
# IO serialization: the writer thread and synchronous flush() never
# interleave lines within a segment
_IO_LOCK = threading.Lock()
_WRITER: Optional[threading.Thread] = None
_WAKE = threading.Event()
_ATEXIT = False  # final synchronous drain registered (once per process)

#: hard cap per table buffer — a stalled writer degrades to dropped entries
#: (counted), never to unbounded memory
MAX_BUFFERED = 4096

#: longest predicate SQL a literal-sample reservoir slot accepts — one
#: pathological megabyte predicate must not blow the segment size bound
#: just to preserve a replay literal (truncated SQL would not parse back)
SAMPLE_MAX_SQL = 2048

#: literal-sample reservoir bookkeeping: journal dir → fingerprint key →
#: samples stamped so far this process. Deterministic first-K (not random
#: reservoir sampling): the same workload replayed over a fresh journal
#: yields the same sampled literals, which keeps shadow replays stable
_SAMPLE_COUNTS: Dict[str, Dict[str, int]] = {}


def enabled(log_path: Optional[str] = None) -> bool:
    """Journaling is on: the journal conf AND telemetry are enabled, and the
    table's log lives on a local filesystem (``scheme://`` paths skip it)."""
    if not conf.get_bool("delta.tpu.journal.enabled", True):
        return False
    if not conf.get_bool("delta.tpu.telemetry.enabled", True):
        return False
    if log_path is not None and "://" in log_path:
        return False
    return True


def journal_dir(log_path: str) -> str:
    """The segment directory for a table's ``_delta_log`` path."""
    return os.path.join(log_path, "_journal")


def _segment_bytes() -> int:
    try:
        n = int(conf.get("delta.tpu.journal.segmentBytes", 1 << 20))
    except (TypeError, ValueError):
        n = 1 << 20
    return n if n > 0 else 1 << 20


def _max_bytes() -> int:
    try:
        n = int(conf.get("delta.tpu.journal.maxBytes", 16 << 20))
    except (TypeError, ValueError):
        n = 16 << 20
    return n if n > 0 else 16 << 20


def _retention_ms() -> int:
    try:
        n = int(conf.get("delta.tpu.journal.retentionMs", 7 * 86_400_000))
    except (TypeError, ValueError):
        n = 7 * 86_400_000
    return n


def _flush_entries() -> int:
    try:
        n = int(conf.get("delta.tpu.journal.flushEntries", 64))
    except (TypeError, ValueError):
        n = 64
    return n if n > 0 else 64


def _flush_interval_s() -> float:
    try:
        ms = float(conf.get("delta.tpu.journal.flushIntervalMs", 2000))
    except (TypeError, ValueError):
        ms = 2000.0
    return max(ms, 100.0) / 1000.0


# ---------------------------------------------------------------------------
# Predicate fingerprint
# ---------------------------------------------------------------------------


def predicate_fingerprint(predicate, partition_cols: Iterable[str] = (),
                          types: Optional[Dict[str, Any]] = None
                          ) -> Optional[Dict[str, Any]]:
    """Normalize a predicate into its workload fingerprint: referenced
    columns, per-conjunct op shapes, and the prunable-vs-residual split —
    a conjunct is *prunable* when the shared skipping rewrite
    (``ops.pruning.skipping_predicate``, the same one ``exec/rowgroups``
    evaluates against footer stats) lowers it to something min/max-evaluable;
    otherwise it can only run as a residual filter and no amount of
    clustering will ever let it skip data. With ``types`` (lowercased
    column name → schema DataType) the rewrite includes the synthesis
    fallback, and each conjunct carries ``synthesizable``: prunable ONLY
    thanks to a synthesized rewrite — the advisor splits never-pruned
    evidence into layout vs shape vs synthesized-but-layout-bound with it."""
    if predicate is None:
        return None
    from delta_tpu.expr import ir, synthesis
    from delta_tpu.ops.pruning import skipping_predicate

    pcols = frozenset(c.lower() for c in partition_cols)
    conjuncts = []
    prunable_cols: set = set()
    residual_cols: set = set()
    for c in ir.split_conjuncts(predicate):
        cols = sorted({r.lower() for r in ir.references(c)})
        try:
            # typed but synthesis-free baseline: the NOT pushdown is a
            # base-rule fix, so it must read prunable, not synthesizable
            base_prunable = synthesis.can_exclude(
                skipping_predicate(c, pcols, types, synthesize=False))
            # synthesize=True: this runs DEFERRED on the writer thread —
            # the conf decision was resolved at scan time (record_scan
            # passes types=None when synthesis was off), so the process
            # conf's state at flush time must not re-decide it
            prunable = base_prunable or (
                types is not None
                and synthesis.can_exclude(
                    skipping_predicate(c, pcols, types, synthesize=True)))
        except Exception:  # noqa: BLE001 — fingerprinting must not fail a scan
            base_prunable = prunable = False
        (prunable_cols if prunable else residual_cols).update(cols)
        conjuncts.append({
            "shape": synthesis.shape(c),
            "columns": cols,
            "prunable": prunable,
            "synthesizable": prunable and not base_prunable,
            "partition": bool(cols) and all(col in pcols for col in cols),
        })
    return {
        "columns": sorted({col for c in conjuncts for col in c["columns"]}),
        "conjuncts": conjuncts,
        "prunableColumns": sorted(prunable_cols),
        "residualColumns": sorted(residual_cols - prunable_cols),
        "key": "&".join(sorted(c["shape"] for c in conjuncts)),
    }


# ---------------------------------------------------------------------------
# Recording hooks
# ---------------------------------------------------------------------------


def _record(log_path: str, entry: Dict[str, Any]) -> bool:
    """Buffer one entry for ``log_path``'s journal; the write happens on the
    writer thread (or a synchronous :func:`flush`). Returns False when the
    journal is inert for this table. Never raises: the commit hook runs
    AFTER version N is durably on disk and the conflict hook sits on the
    exception path — a journaling failure (e.g. ``Thread.start`` at
    interpreter shutdown) must not misreport a landed commit as failed or
    mask the conflict being raised."""
    if not enabled(log_path):
        return False
    try:
        entry.setdefault("ts", int(time.time() * 1000))
        jdir = journal_dir(log_path)
        wake = False
        with _LOCK:
            buf = _BUFFERS.setdefault(jdir, [])
            if len(buf) >= MAX_BUFFERED:
                telemetry.bump_counter("journal.entriesDropped")
                return False
            if not buf:
                _OLDEST[jdir] = time.monotonic()
            buf.append(entry)
            if len(buf) >= _flush_entries():
                wake = True
        _ensure_writer()
        if wake:
            _WAKE.set()
        return True
    except Exception:  # noqa: BLE001 — best-effort, never fail the caller
        telemetry.logger.debug("journal record failed", exc_info=True)
        return False


def record_scan(log_path: str, report=None, predicate=None,
                partition_cols: Iterable[str] = (),
                report_dict: Optional[Dict[str, Any]] = None,
                types: Optional[Dict[str, Any]] = None) -> None:
    """Journal one completed scan: the ScanReport plus the normalized
    predicate fingerprint (hook: ``exec/scan.scan_to_table``). The hot path
    pays only a dict append: callers pass the ``report_dict`` they already
    serialized for the span, and the fingerprint (an IR walk + the skipping
    rewrite per conjunct) is deferred to the writer thread — predicate IR
    expressions and the schema ``types`` map are immutable, so walking them
    off-thread is safe."""
    if not enabled(log_path):
        return
    # the reservoir bound is resolved NOW, like the synthesis decision in
    # the fingerprint input: the writer thread must not re-read a conf the
    # caller's set_temporarily scope may have exited by flush time
    _record(log_path, {
        "kind": "scan",
        "report": (report_dict if report_dict is not None
                   else report.to_dict()),
        "_fingerprint_input": (predicate, tuple(partition_cols), types),
        "_sample_limit": (conf.get_int("delta.tpu.journal.literalSamples", 3)
                          if predicate is not None else 0),
    })


def record_commit(log_path: str, stats: Dict[str, Any],
                  outcome: str = "committed") -> None:
    """Journal one commit attempt's CommitStats + outcome (``committed``,
    ``reconciledWin``, or ``conflict`` for a genuine logical conflict) —
    hook: ``txn/transaction.OptimisticTransaction``."""
    if not enabled(log_path):
        return
    _record(log_path, {"kind": "commit", "outcome": outcome,
                       "stats": dict(stats)})


def record_dml(log_path: str, op: str, **payload: Any) -> None:
    """Journal one DML command: the router decision + audit verdict for
    MERGE, mode + metrics for UPDATE/DELETE (hooks: ``commands/*``)."""
    if not enabled(log_path):
        return
    _record(log_path, {"kind": "dml", "op": op, **payload})


def record_router(log_path: str, audit: Dict[str, Any]) -> None:
    """Journal one router audit record (hook: ``obs/router_audit``)."""
    if not enabled(log_path):
        return
    _record(log_path, {"kind": "router", "audit": dict(audit)})


def record_autopilot(log_path: str, phase: str, action: Dict[str, Any],
                     durable: bool = True, **payload: Any) -> bool:
    """Journal one autopilot action-ledger entry (hook:
    ``delta_tpu/autopilot``). ``phase`` is the lifecycle stage (``planned``
    / ``started`` / ``executed`` / ``skipped`` / ``deferred`` / ``failed``
    / ``interrupted`` / ``abortedContention``); ``action`` is a
    :meth:`~delta_tpu.obs.actions.MaintenanceAction.to_dict` payload.
    ``durable=True`` (the default) bypasses the write-behind buffer and
    appends synchronously under the IO lock: the cooldown guardrail only
    works if attempt entries hit disk BEFORE the action executes — a
    crash mid-maintenance must leave the attempt visible to the restarted
    process. Returns False when the journal is inert OR (durable) when
    the write did not land — an unwritable journal directory drops the
    batch, and the caller must treat "not on disk" as "do not act"
    rather than execute with an unarmed cooldown."""
    if not enabled(log_path):
        return False
    entry = {"kind": "autopilot", "phase": phase, "action": dict(action),
             **payload}
    if not durable:
        return _record(log_path, entry)
    entry.setdefault("ts", int(time.time() * 1000))
    try:
        with _IO_LOCK:
            return _write_batch(journal_dir(log_path), [entry]) > 0
    except Exception:  # noqa: BLE001 — report failure, never raise into
        # the maintenance loop; the caller skips the action instead
        telemetry.logger.debug("durable autopilot journal write failed",
                               exc_info=True)
        return False


def record_shadow(log_path: str, scorecard: Dict[str, Any]) -> bool:
    """Journal one shadow-optimizer scorecard (hook:
    ``delta_tpu/replay/shadow.shadow_run``): the ranked candidate verdicts
    with their measured replay deltas. Buffered like scans — the shadow
    runner calls :func:`flush` itself so the NEXT ``advise()`` sees the
    verdicts read-after-write."""
    if not enabled(log_path):
        return False
    return _record(log_path, {"kind": "shadow", "scorecard": dict(scorecard)})


def record_dist(log_path: str, event: Dict[str, Any]) -> bool:
    """Journal one distributed-execution supervision event (hooks:
    ``parallel/leases`` orphan recovery, ``commands/optimize`` quarantine
    reports) — e.g. ``{"event": "dist.sliceRecovered", "groups": 3}``. The
    postmortem record of WHY a job's topology differs from its plan."""
    if not enabled(log_path):
        return False
    return _record(log_path, {"kind": "dist", **dict(event)})


def _state_path(log_path: str) -> str:
    return os.path.join(journal_dir(log_path), STATE_FILE)


def attempt_state(log_path: str) -> Dict[str, Dict[str, Any]]:
    """The autopilot sidecar's last-attempt map: action key →
    ``{"phase", "ts"}`` (see :data:`STATE_FILE`); empty when absent."""
    try:
        with open(_state_path(log_path), encoding="utf-8") as f:
            d = json.load(f)
        return d if isinstance(d, dict) else {}
    except (OSError, ValueError):
        return {}


def record_attempt(log_path: str, key: str, phase: str, ts_ms: int) -> bool:
    """Durably mirror one autopilot attempt into the sidecar (atomic
    replace). Returns False when the write failed — the autopilot treats
    an un-persistable attempt as "do not act": without it on disk, a
    crash mid-action would leave the restarted process free to
    crash-loop."""
    import contextlib
    import uuid

    path = _state_path(log_path)
    state = attempt_state(log_path)
    state[key] = {"phase": phase, "ts": int(ts_ms)}
    tmp = f"{path}.{uuid.uuid4().hex}.tmp"
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(state, f, separators=(",", ":"))
            os.replace(tmp, path)
        finally:
            with contextlib.suppress(OSError):
                os.unlink(tmp)  # replace won: gone already; crash: no orphan
    except OSError:
        return False
    return True


# ---------------------------------------------------------------------------
# Literal-sample reservoir
# ---------------------------------------------------------------------------


def _stamp_sample(jdir: str, e: Dict[str, Any], predicate,
                  limit: int) -> None:
    """Persist the first ``limit`` concrete predicate SQLs per fingerprint
    key as ``e["sample"]`` — the bounded literal store that lets the replay
    layer (`delta_tpu/replay/trace`) rehydrate abstract fingerprints
    (``eq(v,?)``) back into executable scans. Entries past the bound get
    their report ``predicate`` redacted instead: the reservoir is then the
    ONLY place concrete literals persist, so the bound is a real bound.
    Runs on the writer thread; callers hold ``_IO_LOCK``."""
    fp = e.get("fingerprint") or {}
    key = fp.get("key")
    if key and limit > 0:
        counts = _SAMPLE_COUNTS.setdefault(jdir, {})
        if counts.get(key, 0) < limit:
            try:
                sql = predicate.sql()
            except Exception:  # noqa: BLE001 — sampling must not drop entries
                sql = None
            if sql and len(sql) <= SAMPLE_MAX_SQL:
                e["sample"] = sql
                counts[key] = counts.get(key, 0) + 1
                telemetry.bump_counter("journal.literalSamples")
                return
    report = e.get("report")
    if isinstance(report, dict) and report.get("predicate") is not None:
        # COPY before redacting — the caller's report dict is the SAME
        # object attached to the scan span's ``scanReport`` payload
        e["report"] = {**report, "predicate": None}


# ---------------------------------------------------------------------------
# Writer thread + segment IO
# ---------------------------------------------------------------------------


def _ensure_writer() -> None:
    global _WRITER, _ATEXIT
    if _WRITER is not None and _WRITER.is_alive():
        return
    with _LOCK:
        if _WRITER is not None and _WRITER.is_alive():
            return
        if not _ATEXIT:
            # a short-lived process (scan + commit + exit inside the flush
            # interval) must not lose its buffered entries with the daemon
            # writer: drain synchronously at interpreter exit
            atexit.register(_final_flush)
            _ATEXIT = True
        _WRITER = threading.Thread(target=_writer_loop, daemon=True,
                                   name="delta-journal-writer")
        _WRITER.start()


def _final_flush() -> None:  # pragma: no cover — exercised via subprocess test
    try:
        _drain(aged_only=False)
    except Exception:  # noqa: BLE001 — exiting anyway
        pass


def _writer_loop() -> None:  # pragma: no cover — exercised via flush() too
    while True:
        _WAKE.wait(timeout=_flush_interval_s())
        _WAKE.clear()
        try:
            _drain(aged_only=True)
        except Exception:  # noqa: BLE001 — journaling must never kill the thread
            telemetry.logger.debug("journal writer flush failed", exc_info=True)


def _take_batches(aged_only: bool,
                  only_dir: Optional[str]) -> List[Tuple[str, List[dict]]]:
    now = time.monotonic()
    interval = _flush_interval_s()
    limit = _flush_entries()
    out = []
    with _LOCK:
        for jdir in list(_BUFFERS):
            if only_dir is not None and jdir != only_dir:
                continue
            buf = _BUFFERS[jdir]
            if not buf:
                continue
            aged = now - _OLDEST.get(jdir, now) >= interval
            if aged_only and not (aged or len(buf) >= limit):
                continue
            out.append((jdir, buf))
            _BUFFERS[jdir] = []
            _OLDEST.pop(jdir, None)
    return out


def _drain(aged_only: bool = False, only_dir: Optional[str] = None) -> int:
    """Take buffered batches and write them. The WHOLE cycle (take + write)
    runs under ``_IO_LOCK``: a concurrent :func:`flush` blocks until any
    in-flight writer batch is on disk before taking its own, so
    read-after-flush sees every entry recorded before the call and batches
    land in take order (``read_entries``'s oldest-first contract)."""
    written = 0
    with _IO_LOCK:
        for jdir, entries in _take_batches(aged_only, only_dir):
            written += _write_batch(jdir, entries)
    return written


def _next_segment(jdir: str) -> str:
    global _SEQ
    with _LOCK:
        _SEQ += 1
        seq = _SEQ
    name = f"{SEGMENT_PREFIX}{int(time.time() * 1000):013d}-" \
           f"{os.getpid()}-{seq:06d}{SEGMENT_SUFFIX}"
    return os.path.join(jdir, name)


def _write_batch(jdir: str, entries: List[dict]) -> int:
    """Append one batch as JSONL, rotating the active segment at the size
    bound and sweeping the directory on rotation. Deferred work entries
    carry (the scan fingerprint) happens HERE, on the writer thread, not on
    the operation's thread. Callers hold ``_IO_LOCK`` (via :func:`_drain`).
    Best-effort: an unwritable directory drops the batch (counted), never
    fails the caller."""
    lines = []
    for e in entries:
        fp_in = e.pop("_fingerprint_input", None)
        sample_limit = e.pop("_sample_limit", 0)
        if fp_in is not None:
            try:
                e["fingerprint"] = predicate_fingerprint(
                    fp_in[0], fp_in[1], fp_in[2] if len(fp_in) > 2 else None)
            except Exception:  # noqa: BLE001 — never lose the report over it
                e["fingerprint"] = None
            if fp_in[0] is not None:
                _stamp_sample(jdir, e, fp_in[0], sample_limit)
        try:
            lines.append(json.dumps(e, separators=(",", ":"), default=str))
        except (TypeError, ValueError):
            continue
    if not lines:
        return 0
    # byte accounting must match what lands on disk (non-ASCII escapes via
    # default=str can still multi-byte), or rotation and the sweep disagree
    data = ("\n".join(lines) + "\n").encode("utf-8")
    seg_limit = _segment_bytes()
    rotated = False
    try:
        os.makedirs(jdir, exist_ok=True)
        active = _ACTIVE.get(jdir)
        if active is None or active[1] >= seg_limit \
                or not os.path.exists(active[0]):
            if jdir not in _SWEPT or active is not None:
                sweep(jdir)
            active = (_next_segment(jdir), 0)
            rotated = True
        # delta-lint: ignore[lock-blocking] -- _IO_LOCK is the journal's IO
        # serialization lock; appending under it is its entire purpose
        with open(active[0], "ab") as f:
            f.write(data)
        _ACTIVE[jdir] = (active[0], active[1] + len(data))
    except OSError:
        telemetry.bump_counter("journal.entriesDropped", len(lines))
        return 0
    if rotated:
        # counted only once the file actually exists — an unwritable dir
        # re-enters the rotation branch every batch and must not inflate it
        telemetry.bump_counter("journal.segments.written")
    telemetry.bump_counter("journal.entries", len(lines))
    telemetry.bump_counter("journal.bytes.written", len(data))
    # per-table write volume for the fleet plane (label: hashed table path
    # — jdir is <table>/_delta_log/_journal). KiB, not bytes: the shared
    # log2 histogram buckets top out at 65536, so byte-valued flushes over
    # 64 KiB would all collapse into +Inf
    from delta_tpu.obs.fleet import table_label

    table_path = os.path.dirname(os.path.dirname(jdir))
    telemetry.observe("journal.flushKb", len(data) / 1024.0,
                      table=table_label(table_path))
    return len(lines)


def flush(log_path: Optional[str] = None) -> int:
    """Synchronously write every buffered entry (for one table's log path,
    or all); returns entries written. The advisor and tests call this —
    steady-state writes stay on the writer thread."""
    only = journal_dir(log_path) if log_path is not None else None
    return _drain(aged_only=False, only_dir=only)


def live_writer_spared(stats: List[Tuple[str, int, float]],
                       grace_s: float) -> set:
    """The possibly-live subset of per-process files in a shared directory:
    among ``(path, size, mtime)`` stats whose basenames embed the creating
    pid at dash-field 2 (``<prefix>-<ts>-<pid>-...``), the newest file per
    pid, while touched within ``grace_s`` seconds. A process writes only to
    ITS newest file (journal segments rotate forward; dist leases heartbeat
    in place), so anything else — or anything grace-stale, since a live
    writer touches its file at least every flush/heartbeat interval — is
    guaranteed dead and fair game for the caller's sweep. One immune file
    per CI/cron run would make size caps and lease expiry unenforceable.
    Shared by the journal sweep and ``parallel/leases.sweep_leases`` so the
    two sweeps cannot drift on what "live" means."""
    newest_per_pid: Dict[str, str] = {}
    mtimes: Dict[str, float] = {}
    for p, _size, mtime in sorted(stats):  # name-sorted oldest → newest
        parts = os.path.basename(p).split("-")
        newest_per_pid[parts[2] if len(parts) >= 4 else ""] = p
        mtimes[p] = mtime
    now = time.time()
    return {p for p in newest_per_pid.values()
            if now - mtimes[p] <= grace_s}


def sweep(jdir: str) -> int:
    """Bound the journal directory: segments older than
    ``delta.tpu.journal.retentionMs`` are deleted, then oldest-first until
    the total is within ``delta.tpu.journal.maxBytes`` — the same
    aged-orphan discipline as ``log/cleanup.sweep_tmp_orphans``."""
    # _SWEPT is shared with the writer daemon (_write_batch's rotation
    # check) and sweep() is public API — mutate under the buffer lock
    with _LOCK:
        _SWEPT.add(jdir)
    try:
        names = sorted(n for n in os.listdir(jdir)
                       if n.startswith(SEGMENT_PREFIX)
                       and n.endswith(SEGMENT_SUFFIX))
    except OSError:
        return 0
    cutoff = time.time() - _retention_ms() / 1000.0
    max_total = _max_bytes()
    stats = []
    for n in names:
        p = os.path.join(jdir, n)
        try:
            st = os.stat(p)
        except OSError:
            continue
        stats.append((p, st.st_size, st.st_mtime))
    total = sum(s[1] for s in stats)
    deleted = 0
    active = _ACTIVE.get(jdir)
    active_path = active[0] if active is not None else None
    # Age expiry spares nothing: a table that stopped journaling must shed
    # its final segment too — except this process's own active file (tests
    # run with tiny retention windows while entries are still buffered for
    # it). Size pressure additionally spares possibly-live concurrent
    # writers' newest segments (see live_writer_spared).
    spared_set = live_writer_spared(stats,
                                    max(60.0, 10 * _flush_interval_s()))
    for p, size, mtime in stats:
        if p == active_path:
            continue
        if mtime <= cutoff or (total > max_total and p not in spared_set):
            try:
                os.remove(p)
                deleted += 1
                total -= size
            except OSError:
                continue
    if deleted:
        telemetry.bump_counter("journal.segments.swept", deleted)
    return deleted


# ---------------------------------------------------------------------------
# Reading
# ---------------------------------------------------------------------------


def read_entries(log_path: str, kinds: Optional[Iterable[str]] = None,
                 limit: Optional[int] = None) -> List[Dict[str, Any]]:
    """Parse every journal segment for a table, oldest entry first.
    Segment-name order (names embed the creation epoch) is only a first
    pass — two processes journaling the same table interleave in time while
    each appends to its OWN active segment, so entries are stable-sorted by
    their recorded ``ts`` (within-segment order kept on ties). Malformed
    lines are skipped — a torn tail write must never poison the history.
    ``kinds`` filters entry kinds; ``limit`` keeps the LAST N entries (a
    genuine recent window, thanks to the sort)."""
    jdir = journal_dir(log_path)
    try:
        names = sorted(n for n in os.listdir(jdir)
                       if n.startswith(SEGMENT_PREFIX)
                       and n.endswith(SEGMENT_SUFFIX))
    except OSError:
        return []
    want = frozenset(kinds) if kinds is not None else None
    out: List[Dict[str, Any]] = []
    for n in names:
        try:
            with open(os.path.join(jdir, n), encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        e = json.loads(line)
                    except ValueError:
                        continue
                    if not isinstance(e, dict):
                        continue
                    if want is None or e.get("kind") in want:
                        out.append(e)
        except OSError:
            continue
    out.sort(key=lambda e: e.get("ts") or 0)  # stable: ties keep file order
    if limit is not None and limit >= 0:
        # out[-0:] would be the WHOLE list — limit=0 means "no entries"
        out = out[-limit:] if limit > 0 else []
    return out


def reset() -> None:
    """Drop in-memory buffers and active-segment bookkeeping (tests, bench
    per-config isolation). On-disk segments are left alone — delete the
    ``_journal`` directory to forget a table's history."""
    with _LOCK:
        _BUFFERS.clear()
        _OLDEST.clear()
        _SWEPT.clear()
    with _IO_LOCK:
        _ACTIVE.clear()
        _SAMPLE_COUNTS.clear()
