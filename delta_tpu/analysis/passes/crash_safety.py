"""Crash-safety pass: SimulatedCrash must pierce, tmp files must not orphan.

The fault injector (``storage/faults.py``) raises :class:`SimulatedCrash`
— a ``BaseException`` — at named fault points so that no ``except
Exception`` recovery path can "survive" a process death. Three rules keep
that contract reviewable:

``crash-except``
    An ``except Exception`` handler whose try body reaches a fault surface
    (a LogStore op, a ``faults.fire(...)`` point, or a module-local call
    that transitively does). ``SimulatedCrash`` pierces such a handler by
    construction — the flag forces each site to be a *reviewed* decision
    (waiver or baseline) that its cleanup is crash-safe, instead of
    silence. New fault-adjacent swallowing can't ship unnoticed.
``crash-swallow``
    ``except BaseException`` (or bare ``except:``) that neither re-raises
    nor stores/forwards the exception: a ``SimulatedCrash`` would be
    swallowed and the "dead" context would keep running — the
    crash-between-batch-members class PR 9's review caught by hand.
``crash-tmpfile``
    A ``*.tmp`` staging path that is written without a ``try/finally``
    unlinking it (the PR 5 orphan class): any exception between staging
    and publish strands the temp file for the cleanup sweep to find.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from delta_tpu.analysis.core import (AnalysisContext, AnalysisPass, Finding)
from delta_tpu.analysis.modgraph import (ModuleGraph, module_graph,
                                         shallow_walk, terminal_name)
from delta_tpu.analysis.passes.lock_discipline import (STORE_OPS,
                                                       _receiver_chain)

__all__ = ["CrashSafetyPass"]

_TMP_RE = re.compile(r"\.tmp\b")


def _fault_surface_desc(call: ast.Call) -> Optional[str]:
    """Non-None when ``call`` is directly a fault surface: a LogStore op on
    a store-ish receiver, or an engine fault point ``fire("...")``."""
    f = call.func
    name = terminal_name(f)
    if name == "fire" and call.args and isinstance(
            call.args[0], ast.Constant) and isinstance(
            call.args[0].value, str):
        return f"faults.fire({call.args[0].value!r})"
    if isinstance(f, ast.Attribute) and f.attr in STORE_OPS:
        chain = _receiver_chain(f.value)
        if any("store" in part.lower() for part in chain):
            return f"store.{f.attr}"
    return None


class CrashSafetyPass(AnalysisPass):
    name = "crash-safety"
    description = ("except-Exception on fault-point paths, swallowed "
                   "BaseException, tmp files without finally-cleanup")
    rules = ("crash-except", "crash-swallow", "crash-tmpfile")

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        out: List[Finding] = []
        for sf in ctx.files:
            g = module_graph(ctx, sf)
            surface = self._fault_surfaces(g)
            for qn, unit in g.functions.items():
                out.extend(self._handler_findings(g, qn, surface))
                out.extend(self._tmpfile_findings(g, qn))
        return out

    # -- fault-surface summary -------------------------------------------

    def _fault_surfaces(self, g: ModuleGraph) -> Dict[str, Optional[str]]:
        """qualname -> a fault-surface description if the function (or a
        module-local transitive callee) touches one, else None."""
        direct: Dict[str, Optional[str]] = {}
        for qn, facts in g.facts.items():
            desc = None
            for ev in facts.calls:
                desc = _fault_surface_desc(ev.node)
                if desc:
                    break
            direct[qn] = desc
        # transitive closure (bounded fixpoint)
        summary = dict(direct)
        for _ in range(len(g.functions) + 1):
            changed = False
            for qn, facts in g.facts.items():
                if summary[qn]:
                    continue
                for ev in facts.calls:
                    if ev.resolved and summary.get(ev.resolved):
                        callee = ev.resolved.rsplit(".", 1)[-1]
                        summary[qn] = f"via {callee}: {summary[ev.resolved]}"
                        changed = True
                        break
            if not changed:
                break
        return summary

    # -- except handlers --------------------------------------------------

    def _try_surface(self, g: ModuleGraph, unit, body: List[ast.stmt],
                     summary: Dict[str, Optional[str]]) -> Optional[str]:
        """First fault-surface description reachable from ``body``."""
        for stmt in body:
            for node in shallow_walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                desc = _fault_surface_desc(node)
                if desc:
                    return desc
                resolved = g.resolve_call(node, unit)
                if resolved and summary.get(resolved):
                    callee = resolved.rsplit(".", 1)[-1]
                    return f"via {callee}: {summary[resolved]}"
        return None

    @staticmethod
    def _catches(handler: ast.ExceptHandler, name: str) -> bool:
        t = handler.type
        if t is None:
            return name == "BaseException"  # bare except == BaseException
        names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
        return any(terminal_name(n) == name for n in names)

    _LOG_METHODS = frozenset({"debug", "info", "warning", "error",
                              "exception", "critical", "log"})

    @classmethod
    def _handler_propagates(cls, handler: ast.ExceptHandler) -> bool:
        """True when the handler re-raises or stores/forwards the caught
        exception (``raise``, ``fut.set_exception(e)``, ``state['err'] =
        e``) — the crash still reaches someone. Merely LOGGING the bound
        name (``logger.warning("%s", e)``) is not propagation, and a
        ``raise`` inside a nested def executes later, not here."""
        bound = handler.name
        logged_loads = set()
        for node in shallow_walk(handler):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) \
                    and node.func.attr in cls._LOG_METHODS:
                for sub in node.args + [kw.value for kw in node.keywords]:
                    for n in ast.walk(sub):
                        logged_loads.add(id(n))
        for node in shallow_walk(handler):
            if isinstance(node, ast.Raise):
                return True
            if bound is None:
                continue
            if isinstance(node, ast.Name) and node.id == bound \
                    and isinstance(node.ctx, ast.Load) \
                    and id(node) not in logged_loads:
                return True
        return False

    def _handler_findings(self, g: ModuleGraph, qn: str,
                          summary: Dict[str, Optional[str]]) -> List[Finding]:
        unit = g.functions[qn]
        out: List[Finding] = []
        for node in shallow_walk(unit.node):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if self._catches(handler, "BaseException"):
                    if not self._handler_propagates(handler):
                        out.append(Finding(
                            "crash-swallow", g.sf.rel, handler.lineno,
                            f"handler in {qn} catches BaseException and "
                            f"continues — a SimulatedCrash (process death) "
                            f"would be swallowed"))
                    continue
                if not self._catches(handler, "Exception"):
                    continue
                desc = self._try_surface(g, unit, node.body, summary)
                if desc is None:
                    continue
                out.append(Finding(
                    "crash-except", g.sf.rel, handler.lineno,
                    f"'except Exception' in {qn} around fault-point IO "
                    f"({desc}) — SimulatedCrash pierces this handler; its "
                    f"cleanup must be crash-safe"))
        return out

    # -- tmp files --------------------------------------------------------

    def _tmpfile_findings(self, g: ModuleGraph, qn: str) -> List[Finding]:
        unit = g.functions[qn]
        tmp_names: Dict[str, int] = {}
        for node in shallow_walk(unit.node):
            if not isinstance(node, ast.Assign):
                continue
            has_tmp = any(
                isinstance(v, ast.Constant) and isinstance(v.value, str)
                and _TMP_RE.search(v.value)
                for v in ast.walk(node.value))
            if not has_tmp:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    tmp_names.setdefault(t.id, node.lineno)
        if not tmp_names:
            return []
        cleaned = self._finally_cleaned(unit.node)
        out: List[Finding] = []
        for name, line in sorted(tmp_names.items(), key=lambda kv: kv[1]):
            if name in cleaned:
                continue
            if not self._is_written(unit.node, name):
                continue
            out.append(Finding(
                "crash-tmpfile", g.sf.rel, line,
                f"tmp file '{name}' in {qn} is written without a "
                f"try/finally unlink — an exception between staging and "
                f"publish strands an orphan (PR 5 class)"))
        return out

    @staticmethod
    def _finally_cleaned(fn: ast.AST) -> Set[str]:
        """Names passed to ``os.unlink``/``os.remove`` inside any
        ``finally:`` block (or except handler) of ``fn``."""
        out: Set[str] = set()
        for node in shallow_walk(fn):
            if not isinstance(node, ast.Try):
                continue
            regions: List[ast.stmt] = list(node.finalbody)
            for h in node.handlers:
                regions.extend(h.body)
            for stmt in regions:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call) and terminal_name(
                            sub.func) in ("unlink", "remove"):
                        for arg in sub.args:
                            if isinstance(arg, ast.Name):
                                out.add(arg.id)
        return out

    @staticmethod
    def _is_written(fn: ast.AST, name: str) -> bool:
        """Is ``name`` used as a write target: ``open(name, ...)`` or an
        argument to a ``write*``/``link`` call?"""
        for node in shallow_walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = terminal_name(node.func)
            if callee == "open" or (callee or "").startswith("write") \
                    or callee == "link":
                if any(isinstance(a, ast.Name) and a.id == name
                       for a in node.args):
                    return True
        return False
