"""Failure flight recorder — post-mortems that survive the ring buffer.

When an instrumented operation raises (commit conflict, scan/DML error), the
spans that explain it sit in a 4096-event ring buffer and are overwritten
within seconds on a busy table. This module registers a telemetry failure
hook (``utils/telemetry.add_failure_hook``) that — while
``delta.tpu.obs.incidentDir`` is set — snapshots the moment of failure into
one bounded incident JSON file:

* the open span stack at the instant of the raise (innermost span included,
  with its payload and elapsed time),
* the last N ring-buffer events (``delta.tpu.obs.incidentEvents``, def. 64),
* every counter, and the error itself.

Files are named ``incident-<epoch_ms>-<seq>-<opType>.json`` and pruned
oldest-first to ``delta.tpu.obs.incidentKeep`` (default 20). Off by default:
with ``incidentDir`` unset the hook exits on one conf probe, and hooks only
run on the error path at all. An exception unwinding through nested spans
fires the hook once per span — incidents dedupe on exception identity, so
one failure is one file (with the innermost, fullest stack).
"""
from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Any, Dict, List, Optional

from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf

__all__ = ["install", "uninstall", "record_incident", "incident_files"]

_LOCK = threading.Lock()
_SEQ = 0
# id()s of exceptions already recorded: the same exception unwinding through
# every enclosing span must not write one incident per span
_SEEN_EXC: "deque[int]" = deque(maxlen=64)
_installed = False


def _incident_dir() -> Optional[str]:
    d = conf.get("delta.tpu.obs.incidentDir")
    return str(d) if d else None


def incident_files(directory: Optional[str] = None) -> List[str]:
    """Incident file paths in ``directory`` (default: the configured dir),
    oldest first (the name embeds the timestamp and a monotonic sequence)."""
    d = directory or _incident_dir()
    if not d or not os.path.isdir(d):
        return []
    return sorted(
        os.path.join(d, f) for f in os.listdir(d)
        if f.startswith("incident-") and f.endswith(".json")
    )


def _sanitize(op_type: str) -> str:
    return "".join(c if c.isalnum() or c in "._-" else "_" for c in op_type)


def record_incident(ev, exc: BaseException) -> Optional[str]:
    """The failure hook body: write one incident file for ``exc`` (deduped)
    and prune the directory. Returns the path written, or None."""
    directory = _incident_dir()
    if directory is None:
        return None
    # one exception unwinding through N nested spans = one incident: mark
    # the exception object itself (id() alone can be recycled after gc)
    if getattr(exc, "_delta_incident_recorded", False):
        return None
    try:
        exc._delta_incident_recorded = True  # type: ignore[attr-defined]
    except Exception:  # noqa: BLE001 — slotted exceptions: fall back to id()
        with _LOCK:
            if id(exc) in _SEEN_EXC:
                return None
            _SEEN_EXC.append(id(exc))
    with _LOCK:
        global _SEQ
        _SEQ += 1
        seq = _SEQ
    try:
        keep = int(conf.get("delta.tpu.obs.incidentKeep", 20))
    except (TypeError, ValueError):
        keep = 20
    try:
        n_events = int(conf.get("delta.tpu.obs.incidentEvents", 64))
    except (TypeError, ValueError):
        n_events = 64
    events = telemetry.recent_events()[-max(n_events, 0):]
    incident: Dict[str, Any] = {
        "timestamp": ev.timestamp_ms,
        "opType": ev.op_type,
        # the failing span's trace: errors force-sample, so this links to
        # a spooled, stitchable /traces/<id> view of the incident
        "traceId": (getattr(ev, "trace_id", "")
                    or telemetry.current_trace_id()),
        "error": f"{type(exc).__name__}: {exc}",
        "tags": dict(ev.tags),
        "data": _jsonable(ev.data),
        "spanStack": _jsonable(telemetry.span_stack_snapshot()),
        "recentEvents": [json.loads(e.to_json()) for e in events],
        "counters": telemetry.counters(),
        "pid": os.getpid(),
        "thread": threading.current_thread().name,
    }
    # what the query was DOING, not just the span stack: the scan report
    # in flight on this context (e.g. a bench-deadline breach mid-scan) and
    # the last router-audit record, when they exist
    try:
        from delta_tpu.obs import router_audit, scan_report

        rep = scan_report.current_report()
        if rep is not None:
            incident["scanReport"] = rep.to_dict()
        audit = router_audit.last_audit()
        if audit is not None:
            incident["routerAudit"] = audit.to_dict()
    except Exception:  # noqa: BLE001 — the recorder must never raise
        pass
    os.makedirs(directory, exist_ok=True)
    name = f"incident-{ev.timestamp_ms:013d}-{seq:06d}-{_sanitize(ev.op_type)}.json"
    path = os.path.join(directory, name)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(incident, f, indent=1, default=str)
    telemetry.bump_counter("obs.incidents.written")
    if keep > 0:
        for old in incident_files(directory)[:-keep]:
            try:
                os.remove(old)
            except OSError:
                pass
    return path


def _jsonable(obj):
    return json.loads(json.dumps(obj, default=str))


def install() -> None:
    """Register the recorder hook (idempotent). Inert until
    ``delta.tpu.obs.incidentDir`` is set; importing ``delta_tpu.obs``
    installs it."""
    global _installed
    if not _installed:
        telemetry.add_failure_hook(record_incident)
        _installed = True


def uninstall() -> None:
    global _installed
    telemetry.remove_failure_hook(record_incident)
    _installed = False
