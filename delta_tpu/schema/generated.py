"""Generated columns — compute-on-write + validation.

Mirrors `GeneratedColumn.scala:79-365`: a column whose value is computed
from other columns via an expression stored in its field metadata under
``delta.generationExpression``; gated on writer version 4 (the protocol
bump lives in `txn/transaction.py`). On write (`exec/write.py`):

* column missing from the batch → computed from the expression;
* column provided → verified null-safe-equal to the computed value
  (the reference emits an equivalent CHECK constraint, `:267`).

The determinism whitelist (`SupportedGenerationExpressions.scala`) is the
expression IR itself: every node the parser can produce — arithmetic,
comparisons, CASE, casts, and the fixed `ir.Func.FUNCS` scalar set — is
deterministic, so "parses into IR" = "whitelisted". Validation adds the
structural rules: references must exist and must not be generated columns
themselves (no chains, no self-reference).
"""
from __future__ import annotations

from typing import Dict, List

import pyarrow as pa
import pyarrow.compute as pc

from delta_tpu.expr import ir
from delta_tpu.expr.parser import parse_expression
from delta_tpu.expr.vectorized import arrow_type_for, evaluate
from delta_tpu.schema.types import DataType, StructField, StructType
from delta_tpu.utils.errors import DeltaAnalysisError, InvariantViolationError
from delta_tpu.utils import errors

__all__ = [
    "GENERATION_EXPRESSION_KEY",
    "generated_field",
    "generation_expressions",
    "generated_column_names",
    "fixed_type_columns",
    "has_generated_columns",
    "validate_generated_columns",
    "compute_on_write",
    "columns_to_recompute",
]

GENERATION_EXPRESSION_KEY = "delta.generationExpression"


def generated_field(
    name: str, data_type: DataType, expr_sql: str, nullable: bool = True
) -> StructField:
    """Build a StructField carrying a generation expression (DDL helper)."""
    return StructField(
        name, data_type, nullable, metadata={GENERATION_EXPRESSION_KEY: expr_sql}
    )


def generation_expressions(schema: StructType) -> Dict[str, ir.Expression]:
    """column name → parsed generation expression (whitelist-enforced: the
    parser only produces deterministic IR; unknown functions raise)."""
    out: Dict[str, ir.Expression] = {}
    for f in schema.fields:
        sql = (f.metadata or {}).get(GENERATION_EXPRESSION_KEY)
        if sql is not None:
            try:
                out[f.name] = parse_expression(sql)
            except DeltaAnalysisError as e:
                raise errors.invalid_generation_expression(f.name, e) from e
    return out


def generated_column_names(schema: StructType) -> set:
    """Lowered names of generated columns (shared by MERGE's star-coverage
    check and insert projection — one definition, or they diverge)."""
    return {name.lower() for name in generation_expressions(schema)}


def fixed_type_columns(schema: StructType) -> set:
    """Lowered names whose types schema evolution must never change:
    generated columns and every column their expressions reference
    (≈ GeneratedColumn.getGeneratedColumnsAndColumnsUsedByGeneratedColumns,
    consumed by mergeSchemas' fixedTypeColumns)."""
    out = set()
    for name, expr in generation_expressions(schema).items():
        out.add(name.lower())
        out.update(r.lower() for r in ir.references(expr))
    return out


def has_generated_columns(schema: StructType) -> bool:
    return any(
        GENERATION_EXPRESSION_KEY in (f.metadata or {}) for f in schema.fields
    )


def validate_generated_columns(schema: StructType) -> None:
    """Structural rules (`GeneratedColumn.scala` validateGeneratedColumns):
    expressions parse, references exist, and no generated column references
    another generated column (or itself)."""
    exprs = generation_expressions(schema)
    names = {f.name.lower() for f in schema.fields}
    gen_names = {c.lower() for c in exprs}
    for col, e in exprs.items():
        for r in ir.references(e):
            rl = r.lower()
            if rl not in names:
                raise errors.generation_expr_unknown_column(col, r)
            if rl in gen_names:
                raise errors.generation_expr_references_generated(col, r)


def _computed(col_name: str, e: ir.Expression, table: pa.Table,
              dtype: DataType) -> pa.ChunkedArray:
    vals = evaluate(e, table)
    at = arrow_type_for(dtype)
    if vals.type != at:
        try:
            vals = pc.cast(vals, at)
        except (pa.ArrowInvalid, pa.ArrowNotImplementedError) as exc:
            raise errors.generation_expr_type_mismatch(col_name, vals.type, at, exc)
    return vals


def compute_on_write(table: pa.Table, schema: StructType) -> pa.Table:
    """Fill in missing generated columns; verify provided ones match.

    Must run *before* ``normalize_data`` (which turns missing columns into
    nulls, losing provided-ness)."""
    exprs = generation_expressions(schema)
    if not exprs:
        return table
    lower_present = {c.lower() for c in table.column_names}
    by_lower = {f.name.lower(): f for f in schema.fields}
    # a batch may legally omit a nullable base column the expressions
    # reference (normalize_data null-fills it later) — null-fill it here
    # first so generation expressions compute over NULLs instead of failing
    for f in schema.fields:
        if f.name.lower() in lower_present or f.name.lower() in {
            c.lower() for c in exprs
        }:
            continue
        table = table.append_column(
            pa.field(f.name, arrow_type_for(f.data_type), True),
            pa.nulls(table.num_rows, arrow_type_for(f.data_type)),
        )
        lower_present.add(f.name.lower())
    for col, e in exprs.items():
        f = by_lower[col.lower()]
        if col.lower() not in lower_present:
            table = table.append_column(
                pa.field(col, arrow_type_for(f.data_type), f.nullable),
                _computed(col, e, table, f.data_type),
            )
        else:
            provided = None
            for c in table.column_names:
                if c.lower() == col.lower():
                    provided = table.column(c)
                    break
            expected = _computed(col, e, table, f.data_type)
            if provided.type != expected.type:
                provided = pc.cast(provided, expected.type)
            # null-safe equality: values equal, or both NULL
            eq = pc.fill_null(pc.equal(provided, expected), False)
            both_null = pc.and_(pc.is_null(provided), pc.is_null(expected))
            ok = pc.or_(eq, both_null)
            bad = pc.sum(pc.invert(ok)).as_py() or 0
            if bad:
                raise InvariantViolationError(
                    f"CHECK constraint Generated Column ({col} <=> {e.sql()}) "
                    f"violated by {bad} row(s): provided values do not match "
                    "the generation expression"
                )
    return table


def recompute_stale(
    table: pa.Table, schema: StructType, assigned: List[str], mask=None
) -> pa.Table:
    """Recompute generated columns whose referenced base columns appear in
    ``assigned`` (an UPDATE / MERGE-update's SET targets) over ``table``;
    rows where ``mask`` is false keep their existing values. Stale copies
    would fail the write-time verification in :func:`compute_on_write`."""
    stale = columns_to_recompute(schema, assigned)
    if not stale:
        return table
    exprs = generation_expressions(schema)
    by_lower = {f.name.lower(): f for f in schema.fields}
    for col in stale:
        f = by_lower[col.lower()]
        actual = next(c for c in table.column_names if c.lower() == col.lower())
        new = pc.cast(evaluate(exprs[col], table), table.column(actual).type)
        if mask is not None:
            new = pc.if_else(mask, new, table.column(actual))
        i = table.column_names.index(actual)
        table = table.set_column(i, pa.field(actual, new.type, f.nullable), new)
    return table


def columns_to_recompute(schema: StructType, assigned: List[str]) -> List[str]:
    """Generated columns whose references intersect ``assigned`` (an UPDATE /
    MERGE-update's SET targets) and which were not explicitly assigned —
    these must be recomputed, not copied, or write-time verification would
    reject the stale values."""
    assigned_low = {a.lower() for a in assigned}
    out = []
    for col, e in generation_expressions(schema).items():
        if col.lower() in assigned_low:
            continue
        if any(r.lower() in assigned_low for r in ir.references(e)):
            out.append(col)
    return out
