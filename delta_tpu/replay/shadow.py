"""Sandboxed what-if execution — the shadow optimizer.

A shadow run clones the table into a temp sandbox (CLONE machinery — the
clones are shallow, so prep costs metadata + any candidate rewrite, never a
second copy of untouched data), applies each candidate layout/configuration,
and re-executes the trace's scans through the REAL ``exec/scan`` path with
the flight-recorder/scan-report plane armed. What comes back is *measured*:
bytes skipped, row groups pruned, planning p50 — per candidate, against a
baseline replay on an untouched clone of the same table. The ranked
:class:`ShadowScorecard` journals as a ``shadow`` entry, the advisor
attaches its verdicts to matching recommendations
(``shadowVerdict: confirmed|refuted|untested``), and the autopilot's
``delta.tpu.autopilot.requireShadow`` guardrail defers unproven rewrites
until a confirming run exists (`autopilot/planner.shadow_gate`).

Candidate kinds:

- ``ZORDER``   — clone + ``OPTIMIZE ZORDER BY (columns)`` on the clone
- ``PARTITION``— rebuild the clone's data into a table partitioned by
  ``columns`` (CTAS; heaviest prep, full data rewrite)
- ``ROW_GROUP_ROWS`` — clone + compaction rewrite under an alternative
  ``delta.tpu.write.rowGroupRows`` (``rows``)
- ``CONF``     — no rewrite; replay under conf overrides (``conf`` dict:
  cache-budget deltas, synthesis on/off, ...)

Every replayed scan's ``rowsOut`` is checked against the baseline's: a
layout change that alters query RESULTS is a correctness failure and the
candidate is refuted outright (``resultMismatch``), whatever its score.
"""
from __future__ import annotations

import os
import shutil
import tempfile
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Tuple

from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf

from delta_tpu.replay.trace import WorkloadTrace, build_trace, _resolve_log

__all__ = ["Candidate", "ShadowScorecard", "default_candidates",
           "realized_audit", "shadow_run", "shadow_verdicts"]

#: planning-p50 dead-band (ms): deltas below this are scheduler jitter,
#: not a candidate effect, and contribute nothing to the score
PLAN_NOISE_MS = 2.0

#: score band treated as noise: |score| below this is ``inconclusive``
SCORE_EPS = 0.01

#: relative realized-vs-shadow-baseline band for the post-execution audit
REALIZED_EPS = 0.01


@dataclass
class Candidate:
    """One what-if configuration to score against the baseline replay."""

    kind: str  # ZORDER | PARTITION | ROW_GROUP_ROWS | CONF
    params: Dict[str, Any] = field(default_factory=dict)

    @property
    def label(self) -> str:
        if self.kind in ("ZORDER", "PARTITION"):
            return f"{self.kind}:{','.join(self.params.get('columns') or ())}"
        if self.kind == "ROW_GROUP_ROWS":
            return f"ROW_GROUP_ROWS:{self.params.get('rows')}"
        keys = ",".join(sorted(self.params.get("conf") or ()))
        return f"CONF:{keys}"

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "label": self.label,
                "params": dict(self.params)}

    def match_keys(self) -> List[Tuple[str, str]]:
        """(kind, target) keys this candidate's verdict applies to, in the
        advisor-recommendation / autopilot-action namespaces — ZORDER and
        PARTITION per clustered column, ROW_GROUP_ROWS to both the
        compaction action and the advisor's ROW_GROUP_SIZE conf rec."""
        if self.kind in ("ZORDER", "PARTITION"):
            return [(self.kind, str(c).lower())
                    for c in self.params.get("columns") or ()]
        if self.kind == "ROW_GROUP_ROWS":
            return [("OPTIMIZE", ""),
                    ("ROW_GROUP_SIZE", "delta.tpu.write.rowgrouprows")]
        return [("CONF", self.label.split(":", 1)[1].lower())]


@dataclass
class ShadowScorecard:
    """Ranked measured outcomes of one shadow run."""

    path: str
    ts: int
    trace: Dict[str, Any]
    baseline: Dict[str, Any]
    candidates: List[Dict[str, Any]]  # ranked by score, best first

    @property
    def top(self) -> Optional[Dict[str, Any]]:
        return self.candidates[0] if self.candidates else None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "path": self.path, "ts": self.ts, "trace": dict(self.trace),
            "baseline": dict(self.baseline),
            "candidates": [dict(c) for c in self.candidates],
            "topCandidate": (self.top or {}).get("candidate", {}).get("label"),
        }


# ---------------------------------------------------------------------------
# Replay + scoring
# ---------------------------------------------------------------------------


def _replay_scans(table_path: str, scans: Iterable[Any],
                  conf_overrides: Optional[Dict[str, Any]] = None,
                  discount: Optional[float] = None) -> Dict[str, Any]:
    """Re-execute trace scans against ``table_path`` through the real scan
    path and aggregate the measured ScanReports. Events with synthesized
    literals contribute at ``discount`` weight. The replayed table's own
    journal stays silent (``journal.enabled=false`` for the scope) — a
    shadow run must not feed the workload history it replays."""
    from delta_tpu.api.tables import DeltaTable
    from delta_tpu.obs import scan_report

    if discount is None:
        try:
            discount = float(
                conf.get("delta.tpu.replay.literalDiscount", 0.5))
        except (TypeError, ValueError):
            discount = 0.5
    overrides: Dict[str, Any] = {"delta.tpu.journal.enabled": False}
    overrides.update(conf_overrides or {})
    table = DeltaTable.for_path(table_path)
    agg: Dict[str, Any] = {
        "scans": 0, "errors": 0, "weight": 0.0, "rowsOut": 0,
        "bytesRead": 0.0, "bytesSkipped": 0.0, "bytesSkippedPlanned": 0.0,
        "rowGroupsTotal": 0.0, "rowGroupsPruned": 0.0,
        "filesScanned": 0.0, "filesPruned": 0.0,
    }
    planning: List[float] = []
    with conf.set_temporarily(**overrides):
        for ev in scans:
            w = discount if ev.synthesized else 1.0
            try:
                filters = (ev.predicate,) if ev.predicate else ()
                out = table.to_arrow(filters=filters)
            except Exception:  # noqa: BLE001 — a stale literal must not
                agg["errors"] += 1  # sink the whole run
                continue
            rep = scan_report.last_scan_report()
            telemetry.bump_counter("replay.scans.replayed")
            agg["scans"] += 1
            agg["weight"] += w
            agg["rowsOut"] += out.num_rows
            if rep is None:
                continue
            agg["bytesRead"] += w * rep.bytes_read
            agg["bytesSkipped"] += w * rep.bytes_skipped
            agg["bytesSkippedPlanned"] += w * rep.bytes_skipped_planned
            agg["rowGroupsTotal"] += w * rep.row_groups_total
            agg["rowGroupsPruned"] += w * (rep.row_groups_pruned
                                           + rep.row_groups_late_skipped)
            agg["filesScanned"] += w * rep.files_scanned
            agg["filesPruned"] += w * rep.files_pruned
            planning.append(float(rep.phase_ms.get("planning", 0)))
    planning.sort()
    agg["planningP50Ms"] = (planning[len(planning) // 2] if planning else 0.0)
    return agg


def _score(base: Dict[str, Any], cand: Dict[str, Any]) -> Dict[str, Any]:
    """Measured deltas candidate-vs-baseline, collapsed to one score: the
    fraction of the workload's bytes no longer READ (file-tier pruning
    losses surface here — a skipped file never shows in bytesSkipped, but
    un-skipping one inflates the read), plus the fraction newly skipped,
    plus quarter-weight terms for planner-tier skips (bytes a
    late-materialization skip still pays to open, a planned skip never
    touches) and row-group pruning, minus a tenth-weight planning-latency
    term."""
    byte_denom = max(base["bytesRead"] + base["bytesSkipped"], 1.0)
    d_read = (base["bytesRead"] - cand["bytesRead"]) / byte_denom
    d_bytes = (cand["bytesSkipped"] - base["bytesSkipped"]) / byte_denom
    d_planned = ((cand["bytesSkippedPlanned"] - base["bytesSkippedPlanned"])
                 / byte_denom)
    d_rg = ((cand["rowGroupsPruned"] - base["rowGroupsPruned"])
            / max(base["rowGroupsTotal"], 1.0))
    # the planning-latency term is a tie-breaker, not a primary signal:
    # sub-PLAN_NOISE_MS median shifts are host scheduler jitter (the
    # baseline replays first, so cold-start noise lands on ITS p50) and
    # must never outvote the deterministic byte terms — dead-band then
    # clamp, bounding the term's reach to +/-0.1 score
    raw_d_plan = cand["planningP50Ms"] - base["planningP50Ms"]
    if abs(raw_d_plan) < PLAN_NOISE_MS:
        raw_d_plan = 0.0
    d_plan = max(-1.0, min(
        1.0, raw_d_plan / max(base["planningP50Ms"], 1.0)))
    mismatch = cand["rowsOut"] != base["rowsOut"] or cand["errors"] > base["errors"]
    score = (d_read + d_bytes + 0.25 * d_planned + 0.25 * d_rg
             - 0.1 * d_plan)
    if mismatch:
        verdict = "refuted"
    elif score >= SCORE_EPS:
        verdict = "confirmed"
    elif score <= -SCORE_EPS:
        verdict = "refuted"
    else:
        verdict = "inconclusive"
    return {
        "score": round(score, 6), "verdict": verdict,
        "resultMismatch": mismatch,
        "deltas": {
            "bytesRead": round(cand["bytesRead"] - base["bytesRead"], 1),
            "bytesSkipped": round(cand["bytesSkipped"] - base["bytesSkipped"], 1),
            "bytesSkippedFrac": round(d_bytes, 6),
            "bytesSkippedPlanned": round(cand["bytesSkippedPlanned"]
                                         - base["bytesSkippedPlanned"], 1),
            "rowGroupsPruned": round(cand["rowGroupsPruned"]
                                     - base["rowGroupsPruned"], 1),
            "planningP50Ms": round(cand["planningP50Ms"]
                                   - base["planningP50Ms"], 3),
        },
    }


# ---------------------------------------------------------------------------
# Candidate prep
# ---------------------------------------------------------------------------


def _clone(src_log, target: str) -> None:
    from delta_tpu.commands.clone import CloneCommand

    CloneCommand(src_log, target).run()


def _prep_candidate(src_log, cand: Candidate, target: str) -> Dict[str, Any]:
    """Materialize one candidate under ``target``; returns the replay-time
    conf overrides. Runs on the ``delta-replay-prep`` pool for prep that
    never touches process conf (ZORDER/PARTITION/CONF); ROW_GROUP_ROWS
    preps sequentially on the caller because its rewrite rides a
    ``set_temporarily`` scope other threads must not observe."""
    from delta_tpu.commands.optimize import OptimizeCommand
    from delta_tpu.log.deltalog import DeltaLog

    if cand.kind == "ZORDER":
        _clone(src_log, target)
        OptimizeCommand(DeltaLog.for_table(target),
                        z_order_by=list(cand.params.get("columns") or ()),
                        min_file_size=0).run()
        return {}
    if cand.kind == "PARTITION":
        from delta_tpu.api.tables import DeltaTable

        data = DeltaTable(src_log).to_arrow()
        DeltaTable.create(target, partition_columns=list(
            cand.params.get("columns") or ()), data=data)
        return {}
    if cand.kind == "ROW_GROUP_ROWS":
        _clone(src_log, target)
        rows = int(cand.params.get("rows") or 0) or 131_072
        with conf.set_temporarily(**{"delta.tpu.write.rowGroupRows": rows}):
            # every file is "small" at this threshold: the compaction
            # rewrites the whole table under the candidate row-group size
            # (min_file_size=0 would select nothing — a no-op rewrite)
            OptimizeCommand(DeltaLog.for_table(target),
                            min_file_size=1 << 60).run()
        return {}
    # CONF: baseline layout, alternative runtime configuration
    _clone(src_log, target)
    return dict(cand.params.get("conf") or {})


def default_candidates(table: Any, advisor_report: Any = None
                       ) -> List[Candidate]:
    """Derive candidates from the advisor's current recommendations —
    every ZORDER/PARTITION target plus a ROW_GROUP_SIZE alternative."""
    out: List[Candidate] = []
    if advisor_report is None:
        from delta_tpu.obs.advisor import advise

        advisor_report = advise(table)
    seen = set()
    for r in getattr(advisor_report, "recommendations", ()):
        if r.kind in ("ZORDER", "PARTITION"):
            key = (r.kind, r.target.lower())
            if key not in seen:
                seen.add(key)
                out.append(Candidate(r.kind, {"columns": [r.target]}))
        elif r.kind == "ROW_GROUP_SIZE" and ("RGR",) not in seen:
            seen.add(("RGR",))
            rows = max(1024, conf.get_int(
                "delta.tpu.write.rowGroupRows", 131_072) // 4)
            out.append(Candidate("ROW_GROUP_ROWS", {"rows": rows}))
    return out


# ---------------------------------------------------------------------------
# shadow_run
# ---------------------------------------------------------------------------


def shadow_run(table: Any, trace: Optional[WorkloadTrace] = None,
               candidates: Optional[List[Candidate]] = None,
               limit: Optional[int] = None) -> ShadowScorecard:
    """Score ``candidates`` (default: advisor-derived) against a baseline
    replay of ``trace`` (default: rebuilt from the journal) in a temp
    sandbox. The sandbox is removed on EVERY exit — including
    KeyboardInterrupt — so an aborted run never leaks clones."""
    import time as _time

    from delta_tpu.obs import journal

    delta_log = _resolve_log(table)
    if trace is None:
        trace = build_trace(delta_log, limit=limit)
    scans = trace.scans()
    if candidates is None:
        candidates = default_candidates(delta_log)

    sandbox_root = conf.get("delta.tpu.replay.sandboxDir") or None
    sandbox = tempfile.mkdtemp(prefix="delta-shadow-", dir=sandbox_root)
    rows: List[Dict[str, Any]] = []
    try:
        _clone(delta_log, os.path.join(sandbox, "baseline"))
        workers = max(1, conf.get_int("delta.tpu.replay.prepWorkers", 2))
        pooled = [(i, c) for i, c in enumerate(candidates)
                  if c.kind != "ROW_GROUP_ROWS"]
        serial = [(i, c) for i, c in enumerate(candidates)
                  if c.kind == "ROW_GROUP_ROWS"]
        overrides: Dict[int, Dict[str, Any]] = {}
        failed: Dict[int, str] = {}

        def _prep(item):
            i, c = item
            return i, _prep_candidate(delta_log, c,
                                      os.path.join(sandbox, f"cand-{i}"))

        if pooled:
            with ThreadPoolExecutor(
                    max_workers=workers,
                    thread_name_prefix="delta-replay-prep") as pool:
                futures = [(i, c, pool.submit(_prep, (i, c)))
                           for i, c in pooled]
                for i, c, fut in futures:
                    try:
                        overrides[i] = fut.result()[1]
                    except Exception as exc:  # noqa: BLE001
                        failed[i] = f"{type(exc).__name__}: {exc}"
        for i, c in serial:
            try:
                overrides[i] = _prep((i, c))[1]
            except Exception as exc:  # noqa: BLE001
                failed[i] = f"{type(exc).__name__}: {exc}"

        # one untimed warm-up replay first: the baseline is measured before
        # any candidate, so process-level cold-start (first-parquet
        # machinery, lazy imports) would otherwise inflate ITS planning p50
        # and bias every candidate's timing tie-breaker toward "confirmed"
        if scans:
            _replay_scans(os.path.join(sandbox, "baseline"), scans[:1])
        base = _replay_scans(os.path.join(sandbox, "baseline"), scans)
        for i, c in enumerate(candidates):
            telemetry.bump_counter("shadow.candidates")
            if i in failed:
                rows.append({"candidate": c.to_dict(), "verdict": "error",
                             "error": failed[i], "score": float("-inf")})
                continue
            metrics = _replay_scans(os.path.join(sandbox, f"cand-{i}"), scans,
                                    conf_overrides=overrides.get(i))
            row = {"candidate": c.to_dict(), "metrics": metrics}
            row.update(_score(base, metrics))
            rows.append(row)
    finally:
        # BaseException-safe: KeyboardInterrupt mid-replay still cleans up
        shutil.rmtree(sandbox, ignore_errors=True)

    rows.sort(key=lambda r: r.get("score", float("-inf")), reverse=True)
    card = ShadowScorecard(
        path=delta_log.data_path, ts=int(_time.time() * 1000),
        trace={"source": trace.source, "events": len(trace.events),
               "scansReplayed": len(scans),
               "synthesizedLiterals": trace.synthesized_literals},
        baseline=base, candidates=rows,
    )
    telemetry.bump_counter("shadow.runs")
    if rows and rows[0].get("score", 0) not in (float("-inf"),):
        telemetry.set_gauge("shadow.topScore", float(rows[0]["score"]),
                            path=delta_log.data_path)
    journal.record_shadow(delta_log.log_path, card.to_dict())
    journal.flush(delta_log.log_path)
    return card


# ---------------------------------------------------------------------------
# Verdict lookups + realized audit
# ---------------------------------------------------------------------------


def shadow_verdicts(entries: Iterable[Dict[str, Any]]
                    ) -> Dict[Tuple[str, str], Dict[str, Any]]:
    """(kind, target)-keyed latest shadow verdicts from journal entries —
    the lookup the advisor and the planner's ``requireShadow`` gate share.
    ``entries`` is any journal slice; non-``shadow`` kinds are skipped, and
    later scorecards overwrite earlier ones per key (entries arrive
    ts-sorted from ``journal.read_entries``)."""
    out: Dict[Tuple[str, str], Dict[str, Any]] = {}
    for e in entries:
        if e.get("kind") != "shadow":
            continue
        sc = e.get("scorecard") or {}
        for rank, row in enumerate(sc.get("candidates") or ()):
            cand = Candidate(str((row.get("candidate") or {}).get("kind", "")),
                             dict((row.get("candidate") or {}).get("params")
                                  or {}))
            payload = {
                "verdict": row.get("verdict", "untested"),
                "score": row.get("score"),
                "deltas": dict(row.get("deltas") or {}),
                "rank": rank,
                "label": cand.label,
                "ts": int(e.get("ts") or sc.get("ts") or 0),
                "scorecardTs": int(sc.get("ts") or 0),
            }
            for key in cand.match_keys():
                out[key] = payload
    return out


def realized_audit(table_path: str, kind: str, target: str
                   ) -> Optional[Dict[str, Any]]:
    """Post-execution audit: after the autopilot executes a shadow-scored
    action, replay the SAME workload the scorecard measured against the now
    live (rewritten) table and compare realized bytes-skipped against the
    scorecard's stored baseline. Verdict ``improved`` / ``worse`` /
    ``unchanged`` with the realized numbers — the autopilot executor
    attaches it to the action's audit. Returns None when no journaled
    scorecard covers (kind, target), or the covered trace has no scans."""
    from delta_tpu.log.deltalog import DeltaLog
    from delta_tpu.obs import journal

    delta_log = DeltaLog.for_table(table_path)
    journal.flush(delta_log.log_path)
    entries = journal.read_entries(delta_log.log_path, kinds=("shadow",))
    want = (str(kind), str(target).lower())
    match: Optional[Tuple[Dict[str, Any], Dict[str, Any]]] = None
    for e in entries:  # ts-sorted: the LAST match wins
        sc = e.get("scorecard") or {}
        for row in sc.get("candidates") or ():
            cand = Candidate(str((row.get("candidate") or {}).get("kind", "")),
                             dict((row.get("candidate") or {}).get("params")
                                  or {}))
            if want in cand.match_keys():
                match = (sc, row)
    if match is None:
        return None
    sc, row = match
    base = sc.get("baseline") or {}
    trace = build_trace(delta_log, before_ts=int(sc.get("ts") or 0) or None)
    scans = trace.scans()
    if not scans or not base:
        return None
    realized = _replay_scans(delta_log.data_path, scans)
    base_skipped = float(base.get("bytesSkipped", 0.0))
    base_read = float(base.get("bytesRead", 0.0))
    d_skip = realized["bytesSkipped"] - base_skipped
    d_read = realized["bytesRead"] - base_read
    # same measure the scorecard scored on: bytes newly skipped plus bytes
    # no longer read (file-tier pruning shows only in the read side)
    gain = d_skip - d_read
    band = REALIZED_EPS * max(base_skipped + base_read, 1.0)
    verdict = ("improved" if gain > band
               else "worse" if gain < -band else "unchanged")
    return {
        "verdict": verdict,
        "bytesSkippedDelta": round(d_skip, 1),
        "bytesReadDelta": round(d_read, 1),
        "realized": {"bytesSkipped": realized["bytesSkipped"],
                     "bytesRead": realized["bytesRead"],
                     "planningP50Ms": realized["planningP50Ms"],
                     "scans": realized["scans"]},
        "shadowBaseline": {"bytesSkipped": base.get("bytesSkipped"),
                           "bytesRead": base.get("bytesRead"),
                           "planningP50Ms": base.get("planningP50Ms")},
        "shadowPredicted": dict(row.get("deltas") or {}),
        "shadowScore": row.get("score"),
    }
