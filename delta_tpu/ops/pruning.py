"""File pruning: partition filters + min/max data skipping, device-evaluated.

The reference only prunes on partition values (`PartitionFiltering.scala:27-42`)
— per-column min/max skipping is spec'd (`PROTOCOL.md:441-480`) and stats are
carried on every AddFile, but `filesForScan` never uses them (`stats/` holds
only shells, SURVEY §2.3). We implement the full skipping path: a data
predicate is rewritten into a *can-match* predicate over per-file stats
columns (``min.c`` / ``max.c`` / ``nullCount.c`` / ``numRecords``) and
evaluated either on device (jaxeval over `FileStateArrays`, numeric columns)
or on host (Arrow kernels over `stats_table`, covers strings).

Conservativeness invariant: a file is dropped only when the rewritten
predicate is *definitely False*; NULL (missing stats) keeps the file. Kleene
logic gives this for free: False AND unknown = False (safe to drop — the
False conjunct alone excludes every row), False OR unknown = unknown (kept).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from functools import lru_cache
from typing import List, Optional, Sequence

import numpy as np
import pyarrow.compute as pc

from delta_tpu.utils.jaxcompat import enable_x64
from delta_tpu.expr import ir
from delta_tpu.expr import partition as partition_expr
from delta_tpu.expr import synthesis
from delta_tpu.protocol.actions import AddFile, Metadata
from delta_tpu.ops import state_export
from delta_tpu.utils.config import conf

__all__ = ["DataSize", "DeltaScan", "skipping_predicate", "ConjunctRewrite",
           "conjunct_rewrites", "prune_files", "files_for_scan"]


@dataclass
class DataSize:
    bytes_compressed: Optional[int] = None
    rows: Optional[int] = None
    files: Optional[int] = None


@dataclass
class DeltaScan:
    """Result of file pruning (shape of `stats/DeltaScan.scala:29-61`)."""

    version: int
    files: List[AddFile]
    total: DataSize
    partition: DataSize
    scanned: DataSize
    partition_filters: List[ir.Expression] = field(default_factory=list)
    data_filters: List[ir.Expression] = field(default_factory=list)


def _min(c: str) -> ir.Expression:
    return ir.Column(f"min.{c}")


def _max(c: str) -> ir.Expression:
    return ir.Column(f"max.{c}")


def _nulls(c: str) -> ir.Expression:
    return ir.Column(f"nullCount.{c}")


_UNKNOWN = ir.Literal(None)

#: Resident-path fired-rewrite attribution isolates a conjunct with an
#: extra host lane pass — observability-only work, bounded to tables where
#: it is noise next to the plan itself; beyond this, scan-level attribution
#: (documented over-attribution) applies.
_ATTRIBUTION_ISOLATE_MAX_FILES = 65_536


#: De Morgan / comparison flips for pushing NOT through (`Not(Lt)` ≡ `Ge`
#: etc.; `Not(Eq)` stays UNKNOWN: excluding on min=max=lit would trust
#: possibly-truncated foreign bounds to be exact). The inequality flips are
#: NOT equivalent over floating columns: a NaN row fails every comparison
#: (Python/IEEE semantics, which this engine's evaluators share), so
#: ``NOT (f < L)`` is TRUE for it while ``f >= L`` is FALSE — and min/max
#: stats ignore NaN, so the flipped rewrite would prune the NaN row's file.
#: They therefore require ``types`` and only fire when every referenced
#: column is non-floating; ``Not(Ne)`` ≡ ``Eq`` is safe either way (both
#: sides are FALSE for a NaN row).
_NOT_FLIP = {ir.Lt: ir.Ge, ir.Le: ir.Gt, ir.Gt: ir.Le, ir.Ge: ir.Lt,
             ir.Ne: ir.Eq}


def _not_flip_safe(c: ir.Expression, types) -> bool:
    if type(c) is ir.Ne:
        return True
    if types is None:
        return False
    from delta_tpu.schema.types import DoubleType, FloatType

    return not any(isinstance(types.get(col.lower()), (FloatType, DoubleType))
                   for col in ir.references(c))


def skipping_predicate(
    e: ir.Expression, partition_cols: frozenset = frozenset(),
    types=None, synthesize: Optional[bool] = None,
) -> ir.Expression:
    """Rewrite a data predicate into a can-match predicate over stats columns.
    Returns ``Literal(None)`` (= keep) for unsupported shapes. Partition
    columns have no stats lanes — references to them rewrite to UNKNOWN
    (they only reach here inside mixed OR branches; pure partition conjuncts
    are routed to partition pruning upstream).

    ``types`` (lowercased column name → schema DataType) arms the
    synthesis fallback (`expr/synthesis`): arithmetic / string / temporal
    shapes the base rules cannot lower rewrite into sound interval or
    monotone-wrap can-match predicates instead of UNKNOWN. With
    ``types=None`` (or ``delta.tpu.read.predicateSynthesis=false``) the
    base behavior is unchanged."""

    def _is_part(col: ir.Expression) -> bool:
        return isinstance(col, ir.Column) and col.name.lower() in partition_cols

    t = type(e)
    if t is ir.And:
        return ir.And(
            skipping_predicate(e.left, partition_cols, types, synthesize),
            skipping_predicate(e.right, partition_cols, types, synthesize),
        )
    if t is ir.Or:
        return ir.Or(
            skipping_predicate(e.left, partition_cols, types, synthesize),
            skipping_predicate(e.right, partition_cols, types, synthesize),
        )
    if t is ir.Not:
        c = e.child
        if isinstance(c, ir.IsNull):
            return skipping_predicate(ir.IsNotNull(c.child), partition_cols, types, synthesize)
        if isinstance(c, ir.IsNotNull):
            return skipping_predicate(ir.IsNull(c.child), partition_cols, types, synthesize)
        if all(col.lower() in partition_cols for col in ir.references(c)):
            return e  # exact per-file partition verdict, negation included
        if isinstance(c, ir.Not):
            return skipping_predicate(c.child, partition_cols, types, synthesize)
        tc = type(c)
        if tc in _NOT_FLIP and _not_flip_safe(c, types):
            # NULL operands agree (both sides yield NULL for a NULL row);
            # the NaN hazard is gated by _not_flip_safe
            return skipping_predicate(
                _NOT_FLIP[tc](c.left, c.right), partition_cols, types, synthesize)
        if tc is ir.And:  # De Morgan: each side rewrites conservatively
            return skipping_predicate(
                ir.Or(ir.Not(c.left), ir.Not(c.right)), partition_cols, types, synthesize)
        if tc is ir.Or:
            return skipping_predicate(
                ir.And(ir.Not(c.left), ir.Not(c.right)), partition_cols, types, synthesize)
        return _synth_fallback(e, partition_cols, types, synthesize)
    if any(_is_part(c) for c in getattr(e, "children", ())):
        # a partition column's value is constant per file: keep the predicate
        # as-is and evaluate it exactly against the bound partition value —
        # unless it also references data columns (no lane to bind)
        if all(col.lower() in partition_cols for col in ir.references(e)):
            return e
        return _UNKNOWN
    # normalize <col> <op> <lit>
    cmp_map = {ir.Eq: ir.Eq, ir.Lt: ir.Lt, ir.Le: ir.Le, ir.Gt: ir.Gt, ir.Ge: ir.Ge}
    if t in cmp_map:
        l, r = e.left, e.right
        flip = {ir.Lt: ir.Gt, ir.Le: ir.Ge, ir.Gt: ir.Lt, ir.Ge: ir.Le, ir.Eq: ir.Eq}
        if isinstance(l, ir.Literal) and isinstance(r, ir.Column):
            e = flip[t](r, l)  # type: ignore[operator]
            t = type(e)
            l, r = e.left, e.right
        if not (isinstance(l, ir.Column) and isinstance(r, ir.Literal)):
            return _synth_fallback(e, partition_cols, types, synthesize)
        c, lit = l.name, r
        if lit.value is None:
            return ir.Literal(False)  # col <op> NULL matches nothing
        if t is ir.Eq:
            return ir.And(ir.Le(_min(c), lit), ir.Ge(_max(c), lit))
        if t is ir.Lt:
            return ir.Lt(_min(c), lit)
        if t is ir.Le:
            return ir.Le(_min(c), lit)
        if t is ir.Gt:
            return ir.Gt(_max(c), lit)
        if t is ir.Ge:
            return ir.Ge(_max(c), lit)
    if t is ir.In and isinstance(e.value, ir.Column):
        opts = [o for o in e.options if isinstance(o, ir.Literal) and o.value is not None]
        if len(opts) != len(e.options):
            return _UNKNOWN
        out: Optional[ir.Expression] = None
        for o in opts:
            one = skipping_predicate(ir.Eq(e.value, o), partition_cols, types, synthesize)
            out = one if out is None else ir.Or(out, one)
        return out if out is not None else ir.Literal(False)
    if t is ir.IsNull and isinstance(e.child, ir.Column):
        return ir.Gt(_nulls(e.child.name), ir.Literal(0))
    if t is ir.IsNotNull and isinstance(e.child, ir.Column):
        return ir.Lt(_nulls(e.child.name), ir.Column("numRecords"))
    if t is ir.StartsWith and isinstance(e.left, ir.Column) and isinstance(e.right, ir.Literal):
        p = e.right.value
        if isinstance(p, str) and p:
            c = e.left.name
            lower = ir.Ge(_max(c), ir.Literal(p))  # some value >= the prefix
            hi = _prefix_upper_bound(p)
            if hi is None:
                return lower
            # every string with prefix p is strictly < hi
            return ir.And(ir.Lt(_min(c), ir.Literal(hi)), lower)
    return _synth_fallback(e, partition_cols, types, synthesize)


def _synth_fallback(e: ir.Expression, partition_cols: frozenset,
                    types, synthesize: Optional[bool]) -> ir.Expression:
    """Hand an unsupported leaf to the synthesis layer when armed.
    ``synthesize`` is tri-state: ``False`` (the attribution baseline in
    :func:`conjunct_rewrites`) skips it even with types present; ``True``
    forces it past the conf — the journal's DEFERRED fingerprinting uses
    this, having resolved the conf at SCAN time into ``types`` (reading
    the process-global conf on the writer thread would stamp a scan with
    whatever conf window happens to be active at flush time); ``None``
    (callers on the scan path) consults the conf here."""
    if types is None or synthesize is False:
        return _UNKNOWN
    if synthesize is None and not conf.get_bool(
            "delta.tpu.read.predicateSynthesis", True):
        return _UNKNOWN
    return synthesis.synthesize(
        e, partition_cols, types,
        base=lambda x: skipping_predicate(x, partition_cols))


def _prefix_upper_bound(p: str) -> Optional[str]:
    """Smallest string greater than every string with prefix ``p`` (in
    code-point order): bump the last bumpable char. None = unbounded."""
    chars = list(p)
    while chars:
        cp = ord(chars[-1])
        if cp < 0x10FFFF:
            nxt = cp + 1
            if 0xD800 <= nxt <= 0xDFFF:  # skip the surrogate block
                nxt = 0xE000
            chars[-1] = chr(nxt)
            return "".join(chars)
        chars.pop()
    return None


@dataclass
class ConjunctRewrite:
    """One conjunct's skipping rewrite plus its synthesis attribution:
    ``attempted`` means the base rules could not exclude on this shape (so
    synthesis was consulted); ``synthesized`` that synthesis produced a
    rewrite that can; ``family`` is the rewrite family label (arithmetic /
    string / cast / ...)."""

    conjunct: ir.Expression
    rewritten: ir.Expression
    attempted: bool = False
    synthesized: bool = False
    family: Optional[str] = None


def conjunct_rewrites(
    filters: Sequence[ir.Expression],
    partition_cols: frozenset,
    types,
) -> List[ConjunctRewrite]:
    """Per-conjunct skipping rewrites with synthesis attribution. The AND
    of the rewrites equals ``skipping_predicate(and_all(filters))`` (the
    rewrite distributes over conjunctions), so callers can evaluate the
    fused predicate AND still attribute which conjuncts only lower thanks
    to synthesis."""
    out: List[ConjunctRewrite] = []
    for f in filters:
        for c in ir.split_conjuncts(f):
            # the attribution baseline is TYPED but synthesis-free: the NOT
            # comparison pushdown (a base-rule fix, type-gated for the NaN
            # hazard) must not read as "synthesized"
            base_rw = skipping_predicate(c, partition_cols, types,
                                         synthesize=False)
            base_ok = synthesis.can_exclude(base_rw)
            if base_ok or types is None:
                out.append(ConjunctRewrite(c, base_rw))
                continue
            rw = skipping_predicate(c, partition_cols, types)
            ok = synthesis.can_exclude(rw)
            out.append(ConjunctRewrite(
                c, rw, attempted=True, synthesized=ok,
                family=synthesis.classify_family(c) if ok else None))
    return out


def _count_rewrites(rewrites: Sequence[ConjunctRewrite]) -> None:
    """One ``scan.rewrites.{synthesized,unknown}`` event per conjunct the
    base rules couldn't lower — bumped by the tier that actually SERVED the
    prune (resident serve or the generic prune), never both."""
    from delta_tpu.utils.telemetry import bump_counter

    for r in rewrites:
        if r.attempted:
            bump_counter("scan.rewrites.synthesized" if r.synthesized
                         else "scan.rewrites.unknown")


def _record_fired(rewrite: ConjunctRewrite) -> None:
    from delta_tpu.obs import scan_report

    scan_report.record_rewrite_fired(
        rewrite.family or "other",
        synthesis.shape(rewrite.conjunct),
        synthesis.shape(rewrite.rewritten),
    )


def _attribute_fired(
    rewrites: Sequence[ConjunctRewrite],
    excluded: Sequence[AddFile],
    metadata: Metadata,
) -> None:
    """Per-conjunct attribution of a file-tier prune: a synthesized rewrite
    *fired* when it alone excludes at least one of the files the fused
    predicate dropped. Best-effort — attribution must never fail a scan."""
    synths = [r for r in rewrites if r.synthesized]
    if not synths or not excluded:
        return
    from delta_tpu.expr.vectorized import evaluate

    try:
        table = state_export.stats_table(excluded, metadata)
    except Exception:  # noqa: BLE001 — attribution is observability only
        return
    for r in synths:
        try:
            verdict = evaluate(r.rewritten, table)
            hit = pc.any(pc.equal(pc.cast(verdict, "bool"), False)).as_py()
        except Exception:  # noqa: BLE001
            hit = False
        if hit:
            _record_fired(r)


def _prune_host(files: Sequence[AddFile], metadata: Metadata, pred: ir.Expression) -> np.ndarray:
    from delta_tpu.expr.vectorized import evaluate

    table = state_export.stats_table(files, metadata)
    try:
        verdict = evaluate(pred, table)
        # keep unless definitely False
        keep = pc.fill_null(pc.cast(verdict, "bool"), True)
    except Exception:  # noqa: BLE001 — a stats/type surprise (e.g. foreign
        # stats that contradict the declared schema under a synthesized
        # rewrite) must degrade to keep-everything, never fail the scan
        return np.ones(len(files), bool)
    return np.asarray(keep)


@lru_cache(maxsize=256)
def _compiled_skipping(pred: ir.Expression):
    """jit-compiled skipping predicate, cached per expression so repeat scans
    reuse the executable (env shapes are the jit cache key)."""
    import jax

    from delta_tpu.expr.jaxeval import compile_expr

    return jax.jit(compile_expr(pred))


def _prune_device(arrays: state_export.FileStateArrays, pred: ir.Expression) -> Optional[np.ndarray]:
    import jax

    from delta_tpu.expr.jaxeval import NotDeviceCompilable

    try:
        fn = _compiled_skipping(pred)
    except NotDeviceCompilable:
        return None
    try:
        with enable_x64():
            col = fn(arrays.device_env())
    except Exception:
        return None
    keep = np.asarray(col.values, bool) | ~np.asarray(col.valid, bool)  # NULL keeps
    if keep.ndim == 0:
        keep = np.full(arrays.num_files, bool(keep))
    return keep


def prune_files(
    files: Sequence[AddFile],
    metadata: Metadata,
    data_filters: Sequence[ir.Expression],
    prefer_device: bool = True,
) -> List[AddFile]:
    """Apply min/max skipping; returns the files that may contain matches."""
    if not files or not data_filters:
        return list(files)
    pcols = frozenset(c.lower() for c in metadata.partition_columns)
    rewrites = conjunct_rewrites(list(data_filters), pcols,
                                 synthesis.schema_types(metadata))
    _count_rewrites(rewrites)
    pred = ir.and_all([r.rewritten for r in rewrites])
    keep: Optional[np.ndarray] = None
    # The device path pays a dispatch + transfer per scan; below a few
    # thousand files the vectorized host evaluator finishes before a single
    # device round-trip even on PCIe-attached chips, so route small file
    # lists to the host (delta.tpu.device.pruning.minFiles to tune).
    min_files = int(conf.get("delta.tpu.device.pruning.minFiles", 4096))
    if prefer_device and len(files) >= min_files:
        arrays = state_export.files_to_arrays(files, metadata)
        keep = _prune_device(arrays, pred)
    if keep is None:
        keep = _prune_host(files, metadata, pred)
    kept = [f for f, k in zip(files, keep) if k]
    if len(kept) < len(files):
        _attribute_fired(rewrites, [f for f, k in zip(files, keep) if not k],
                         metadata)
    return kept


def _resident_scan(
    snapshot,
    partition_filters: Sequence[ir.Expression],
    data_filters: Sequence[ir.Expression],
) -> Optional[DeltaScan]:
    """Serve a scan from the HBM/mirror-resident state cache
    (`ops/state_cache`, the reference's `StateCache` role): only the few
    surviving files materialize as dataclasses — ``all_files`` (every
    AddFile as a Python object) is never built. Partition predicates lower
    to dictionary-code ranges on the same lanes (the reference's primary
    pruning path, `PartitionFiltering.scala:27-43`). Only taken when the
    range lowering is EXACT (no strict comparison was relaxed), so the
    result matches the evaluator file-for-file. None → normal path."""
    if not conf.get_bool("delta.tpu.stateCache.serveScans", True):
        return None
    if getattr(snapshot, "delta_log", None) is None:
        return None  # synthetic snapshots (tests/tools) have no log handle
    import numpy as np

    from delta_tpu.ops.state_cache import DeviceStateCache, extract_range_union
    from delta_tpu.utils.telemetry import bump_counter

    entry = DeviceStateCache.instance().get(snapshot)
    if entry is None:
        bump_counter("stateCache.scan.fallback.noentry")
        return None
    pcols = frozenset(c.lower() for c in snapshot.metadata.partition_columns)
    rewrites = conjunct_rewrites(
        list(partition_filters) + list(data_filters), pcols,
        synthesis.schema_types(snapshot.metadata))
    pred = ir.and_all([r.rewritten for r in rewrites])
    terms = extract_range_union(pred, entry.columns, entry.part_info,
                                str_lanes=entry.str_lanes)
    if not terms or not all(t.exact for t in terms):
        bump_counter("stateCache.scan.fallback.lowering")
        return None
    n_main = len(terms)
    if partition_filters and data_filters:
        # partition-only leg: same lanes, stats bounds dropped — one batch,
        # one dispatch; feeds the DataSize the scan reports for the
        # partition-pruning stage. (Pure-partition queries skip it: the
        # main leg IS the partition leg.)
        ppred = skipping_predicate(ir.and_all(list(partition_filters)), pcols)
        pterms = extract_range_union(ppred, entry.columns, entry.part_info,
                                     str_lanes=entry.str_lanes)
        if not pterms or not all(t.exact for t in pterms):
            bump_counter("stateCache.scan.fallback.lowering")
            return None
        terms = terms + pterms
    plans = entry.plan_ranges(terms, k=max(entry.num_rows, 1),
                              expected_version=snapshot.version)
    if plans is None:
        bump_counter("stateCache.scan.fallback.version")
        return None
    bump_counter("stateCache.scan.resident")
    _count_rewrites(rewrites)  # this tier serves: it owns the count

    def _union(chunk):
        if len(chunk) == 1:
            return chunk[0].rows
        return np.unique(np.concatenate([p.rows for p in chunk]))

    rows = _union(plans[:n_main])
    paths = [entry.paths[i] for i in rows]
    kept = snapshot.files_for_paths(paths)
    alive = entry.h_alive[: entry.num_rows]
    sizes = entry.h_size[: entry.num_rows]
    total_bytes = int(sizes[alive].sum())
    n_alive = int(alive.sum())
    if len(rows) < n_alive:
        # fired-rewrite attribution on the resident path: isolate each
        # synthesized conjunct on the host mirrors when its rewrite lowers
        # to a single range term; multi-term/unlowerable rewrites — and
        # large tables, where an extra per-conjunct host lane pass would
        # rival the resident plan this path exists to keep O(ms) —
        # attribute at scan level (the scan did prune and the conjunct is
        # part of the conjunction that pruned it)
        isolate = n_alive <= _ATTRIBUTION_ISOLATE_MAX_FILES
        for r in (x for x in rewrites if x.synthesized):
            fired = True
            if isolate:
                terms_i = extract_range_union(r.rewritten, entry.columns,
                                              entry.part_info,
                                              str_lanes=entry.str_lanes)
                if terms_i is not None and len(terms_i) == 1:
                    plans_i = entry.plan_ranges(
                        terms_i, k=1, use_device=False,
                        expected_version=snapshot.version)
                    if plans_i is not None:
                        fired = plans_i[0].count < n_alive
            if fired:
                _record_fired(r)
    total = DataSize(bytes_compressed=total_bytes, files=n_alive)
    if partition_filters:
        prows = _union(plans[n_main:]) if data_filters else rows
        partition = DataSize(
            bytes_compressed=int(sizes[prows].sum()), files=len(prows))
    else:
        partition = total  # unpartitioned: nothing pruned by partition
    return DeltaScan(
        version=snapshot.version,
        files=kept,
        total=total,
        partition=partition,
        scanned=DataSize(
            bytes_compressed=sum(f.size or 0 for f in kept),
            files=len(kept),
            rows=sum(f.num_logical_records or 0 for f in kept) or None,
        ),
        partition_filters=list(partition_filters),
        data_filters=list(data_filters),
    )


def files_for_scan(
    snapshot,
    filters: Sequence[ir.Expression] = (),
    keep_num_indexed_cols: Optional[int] = None,
) -> DeltaScan:
    """Partition-prune then stats-prune the snapshot's files for a query.

    The partition step matches `PartitionFiltering.scala:27-42`; the stats
    step is the skipping path the reference leaves unwired. Unpartitioned
    tables with an exactly-lowerable predicate serve from the resident
    state cache instead of materializing every AddFile."""
    from delta_tpu.utils.telemetry import observe, record_operation, with_status

    with record_operation("delta.scan.planning") as pev:
        with with_status("Filtering files for query"):
            scan = _files_for_scan_impl(snapshot, filters, keep_num_indexed_cols)
        pev.data.update(
            filesTotal=scan.total.files, filesAfterPartition=scan.partition.files,
            filesScanned=scan.scanned.files,
        )
    # unmeasured (telemetry blackout) or a bare snapshot shim (tests prune
    # synthetic file lists with no DeltaLog behind them): skip the series
    delta_log = getattr(snapshot, "delta_log", None)
    if pev.duration_us is not None and delta_log is not None:
        from delta_tpu.obs.fleet import table_label

        # hashed table label ONLY — a new series has no back-compat pull
        # toward the raw-path label, and bounded label bytes is the whole
        # point of the hash (the fleet registry resolves it back)
        observe("delta.scan.planning.duration_ms", pev.duration_us / 1000.0,
                table=table_label(delta_log.data_path))
    return scan


def _files_for_scan_impl(
    snapshot,
    filters: Sequence[ir.Expression],
    keep_num_indexed_cols: Optional[int],
) -> DeltaScan:
    metadata = snapshot.metadata
    # read-side char padding (ApplyCharTypePadding): literals compared to
    # char(n) columns pad to width, so they match the stored padded form
    from delta_tpu.schema.char_varchar import pad_char_literals

    filters = [pad_char_literals(f, metadata) for f in filters]
    part_schema = metadata.partition_schema
    part_cols = metadata.partition_columns
    partition_filters: List[ir.Expression] = []
    data_filters: List[ir.Expression] = []
    for f in filters:
        for conj in ir.split_conjuncts(f):
            if partition_expr.is_partition_predicate(conj, part_cols):
                partition_filters.append(conj)
            else:
                data_filters.append(conj)

    if data_filters or partition_filters:
        from delta_tpu.utils.telemetry import record_operation

        with record_operation("delta.scan.stateCache") as rev:
            fast = _resident_scan(snapshot, partition_filters, data_filters)
            rev.data["served"] = fast is not None
        if fast is not None:
            return fast

    all_files = snapshot.all_files
    total = DataSize(
        bytes_compressed=sum(f.size or 0 for f in all_files), files=len(all_files)
    )
    if partition_filters:
        pred = ir.and_all(partition_filters)
        # strict: a NULL partition verdict is constant for the whole file, so
        # no row in it can satisfy the WHERE clause — prune it
        after_part = [
            f for f in all_files if partition_expr.matches(pred, f, part_schema)
        ]
    else:
        after_part = list(all_files)
    partition = DataSize(
        bytes_compressed=sum(f.size or 0 for f in after_part), files=len(after_part)
    )

    from delta_tpu.utils.telemetry import record_operation as _rec_op

    with _rec_op("delta.scan.prune", {"candidates": len(after_part)}):
        kept = prune_files(after_part, metadata, data_filters)
    scanned = DataSize(
        bytes_compressed=sum(f.size or 0 for f in kept),
        files=len(kept),
        rows=sum(f.num_logical_records or 0 for f in kept) or None,
    )
    return DeltaScan(
        version=snapshot.version,
        files=kept,
        total=total,
        partition=partition,
        scanned=scanned,
        partition_filters=partition_filters,
        data_filters=data_filters,
    )
