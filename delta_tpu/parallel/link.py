"""Host↔device link calibration — the cost model behind executor routing.

The reference never needs this: its data plane and control plane share one
JVM address space, and Spark's planner assumes executor-local data. A
TPU-native engine has a real boundary instead — host Arrow buffers vs
device HBM — and the profitability of a device kernel is decided by the
*link*, not the FLOPs. On a PCIe/DMA-attached chip host↔device moves
10-50 GB/s and every sizable kernel wins; on a network-tunneled chip
(this harness: ~250 MB/s on a fresh process that collapses to ~6 MB/s up /
~4 MB/s down once the first XLA execution touches the device — measured,
persistent) bulk transfers dominate everything, and the only winning
device kernels are the ones whose operands already live in HBM or fit in
a few MB.

So executors ask this module before shipping operands:

    est = link.estimate(up_bytes, down_bytes, device_flop_rows)
    if est.device_s < host_estimate_s: ...launch device kernel...

Calibration runs once per process, lazily, *after* forcing a trivial XLA
execution (so we measure the steady-state link, not the fresh-process fast
path), and costs two ~1 MB probes. `delta.tpu.link.uploadMBps` /
`downloadMBps` override the probe for tests and known deployments.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Optional, Sequence

__all__ = [
    "LinkProfile", "Estimate", "profile", "estimate_device_s", "reset",
    "KERNEL_S_PER_ROW", "HOST_JOIN_S_PER_ROW",
    "HOST_PRUNE_S_PER_CELL", "DEVICE_PRUNE_S_PER_CELL",
    "HOST_KEY_DECODE_S_PER_ROW", "RESIDENT_PROBE_S_PER_ROW",
    "RESIDENT_PROBE_FIXED_S", "RESIDENT_FINALIZE_S_PER_ROW",
    "RESIDENT_PAIR_S_PER_ROW", "DEVICE_SORT_S_PER_ROW",
    "HOST_RESIDUAL_S_PER_CELL", "DEVICE_RESIDUAL_S_PER_CELL",
    "SHARD_DISPATCH_S", "SHARD_GATHER_S_PER_SHARD", "DIST_ITEM_S",
    "resident_probe_device_s", "cold_merge_device_s",
    "host_residual_filter_s", "device_residual_mask_s",
    "sharded_plan_device_s", "dist_execute_s",
    "CALIBRATABLE", "constant", "set_calibrated", "calibrated_constants",
    "clear_calibrated",
]

_PROBE_BYTES = 1 << 20  # 1 MB
# sort-merge probe throughput on one chip, measured: ~1.8s for 17.8M rows.
# Comparable per-row to the host hash join on one core — a single chip wins
# on the join itself only by freeing the host; the real speedup is the mesh
# (per-shard sort is rows/p) and link-resident operands.
KERNEL_S_PER_ROW = 1.1e-7
# Arrow hash join, one host core, measured: ~1.1s for 11M rows
HOST_JOIN_S_PER_ROW = 1.0e-7
# batched min/max pruning, host numpy: ~0.6s for 100 preds x 1M files x 4
# stat columns (DRAM-bound boolean reductions)
HOST_PRUNE_S_PER_CELL = 1.5e-9
# projected Parquet key-column decode, host Arrow: ~260ms for 10M rows —
# the cost the resident-key probe avoids and the host join must pay
HOST_KEY_DECODE_S_PER_ROW = 2.6e-8
# resident-key membership probe kernel (ops/key_cache._probe_sorted_kernel,
# r5 block-bucketed brute design): measured 0.43s at 10M and 0.68-0.71s at
# 100M slab rows on one v5e — a ~0.4s dispatch floor plus ~3e-9 s/row of
# VPU compare/reduce work. The old per-probe-sort kernel cost 3.2e-8 s/row.
RESIDENT_PROBE_S_PER_ROW = 3.0e-9
# fixed per-probe device overhead EXCLUDING round trips (those are charged
# via the latency terms in resident_probe_device_s): kernel launch chain +
# the m<=1M source sort
RESIDENT_PROBE_FIXED_S = 0.3
# LEGACY (pre-fused path) host-side finalize work per TARGET row: bitmask
# unpack + bits_for_file mapping + host first-match pairing recovery. The
# fused probe computes the pairing on device and downloads O(matched)
# pairs instead; kept exported for calibration comparisons.
RESIDENT_FINALIZE_S_PER_ROW = 3.0e-8
# fused-path host finalize per MATCHED pair: positions searchsorted +
# scatter into t_first_s (estimate pending on-device recalibration; the
# bench's phase breakdown records the live number each round)
RESIDENT_PAIR_S_PER_ROW = 1.0e-7
# device slab sort (lax.sort of the key lane + permutation), amortized per
# row — paid once per cold build / tail append, not per probe
DEVICE_SORT_S_PER_ROW = 5.0e-8
# residual predicate over decoded Arrow columns, host compute kernels
# (`expr/vectorized`): DRAM-bound compares + Kleene combines per cell
HOST_RESIDUAL_S_PER_CELL = 1.5e-8
# the same residual from HBM-resident SoA lanes (`ops/column_cache`), one
# fused jitted pass: VPU elementwise compares at HBM bandwidth
DEVICE_RESIDUAL_S_PER_CELL = 5.0e-10
# fixed per-dispatch overhead of a shard_map launch over the mesh: program
# dispatch + the all-gather of the surviving-bitmap shards. Dominates tiny
# plans — the router must not shard a 10k-file table over 8 devices.
SHARD_DISPATCH_S = 2.0e-3
# incremental gather cost per participating shard (each shard contributes
# its packed survivor bitmap to the ICI all-gather)
SHARD_GATHER_S_PER_SHARD = 2.0e-4
# per-item scheduling overhead of the distributed executor (deque push/pop,
# steal checks, timing capture) — charged when pricing a fan-out against
# running the same items inline
DIST_ITEM_S = 5.0e-5


# -- self-calibration --------------------------------------------------------
#
# The per-row/per-cell constants above were fit on ONE bench machine; on
# different hardware the router silently prices the wrong side. The router
# audit ledger (`obs/router_audit`) measures every routed decision against
# its prediction, and the EWMA calibrator (`obs/calibration`) re-fits these
# constants from observed samples — opt-in via
# ``delta.tpu.router.calibration.enabled`` — by installing overrides here.
# Cost functions and routers read the constants through :func:`constant`, so
# a calibrated value takes effect everywhere at once.

#: Constant names the calibrator may override.
CALIBRATABLE = frozenset({
    "KERNEL_S_PER_ROW", "HOST_JOIN_S_PER_ROW", "HOST_PRUNE_S_PER_CELL",
    "DEVICE_PRUNE_S_PER_CELL", "HOST_KEY_DECODE_S_PER_ROW",
    "RESIDENT_PROBE_S_PER_ROW", "RESIDENT_PAIR_S_PER_ROW",
    "DEVICE_SORT_S_PER_ROW", "HOST_RESIDUAL_S_PER_CELL",
    "DEVICE_RESIDUAL_S_PER_CELL",
    "SHARD_DISPATCH_S", "SHARD_GATHER_S_PER_SHARD", "DIST_ITEM_S",
})

_calibrated: dict = {}


def constant(name: str) -> float:
    """The live value of a cost-model constant: the calibrated override when
    one is installed, else the module default."""
    v = _calibrated.get(name)
    return v if v is not None else globals()[name]


def set_calibrated(name: str, value: float) -> None:
    """Install a calibrated override (``obs/calibration``). Rejects unknown
    names and non-positive values — a bad sample must not poison routing."""
    if name not in CALIBRATABLE:
        raise ValueError(f"{name!r} is not a calibratable link constant")
    value = float(value)
    if not value > 0.0:
        raise ValueError(f"calibrated {name} must be positive, got {value}")
    _calibrated[name] = value


def calibrated_constants() -> dict:
    """The installed overrides (empty when running on module defaults)."""
    return dict(_calibrated)


def clear_calibrated() -> None:
    """Back to module defaults (tests, `calibration.reset`)."""
    _calibrated.clear()


def resident_probe_device_s(n: int, m: int, p: "LinkProfile") -> float:
    """The router's cost model for one steady-state resident MERGE probe
    (n resident target rows, m source rows) on the FUSED path: source
    upload (int32-narrowed, optimistic), the head download (s_bits +
    matched count), the block-bucketed kernel, the compacted pair download
    (matched count unknown pre-probe: modeled at the upsert-typical m/2
    pairs x 8 bytes), the O(matched) host pair mapping, a fixed dispatch
    floor, and the probe's sequential round trips. ONE definition — the
    production router (`commands/merge.py`) and the bench's
    `auto_routes_device` report both call this, so they cannot drift
    apart."""
    est_pairs = m // 2
    return (
        p.upload_s(m * 4)
        + p.download_s(m // 8 + 6)
        + (n + m) * constant("RESIDENT_PROBE_S_PER_ROW")
        + p.download_s(est_pairs * 8)
        + est_pairs * constant("RESIDENT_PAIR_S_PER_ROW")
        + RESIDENT_PROBE_FIXED_S
        + 3 * p.latency_s
    )


def cold_merge_device_s(n: int, m: int, p: "LinkProfile") -> float:
    """Cost of the COLD fused device MERGE (no resident entry): the tiled
    slab upload (int32-narrowed, optimistic — in the live pipeline it
    overlaps the host Parquet key decode, so this is conservative), the
    one-time device sort, then a steady-state probe. Priced separately
    from the cache-hit case (`resident_probe_device_s`) — the router must
    not charge a hot table for an upload it will skip."""
    return (
        p.upload_s(n * 4)
        + n * constant("DEVICE_SORT_S_PER_ROW")
        + resident_probe_device_s(n, m, p)
    )
# the same cells on-device from HBM-resident f32 lanes (see ops/state_cache):
# ~2 f32 reads/cell at HBM bandwidth, fused compares
DEVICE_PRUNE_S_PER_CELL = 2.0e-11


def host_residual_filter_s(rows: int, ncols: int) -> float:
    """The router's cost model for evaluating a scan's residual predicate on
    host over already-decoded Arrow columns. Residual *evaluation* only —
    the host decode of non-predicate projection columns is common to both
    sides and cancels. ONE definition — `ops/column_cache` and the device
    scan bench both call this, so they cannot drift apart."""
    return rows * ncols * constant("HOST_RESIDUAL_S_PER_CELL")


def device_residual_mask_s(cold_rows: int, resident_rows: int, ncols: int,
                           p: "LinkProfile") -> float:
    """Cost model for the device residual-mask pass: cold predicate-column
    decode on host (resident rows skip it — that's the cache's winnings),
    the cold lane upload, one fused elementwise kernel over every row, the
    bool-mask download (~1 byte/row), and the dispatch round trips. Priced
    against :func:`host_residual_filter_s`; audited as ``scan.residual``."""
    rows = cold_rows + resident_rows
    return (
        cold_rows * ncols * constant("HOST_KEY_DECODE_S_PER_ROW")
        + p.upload_s(cold_rows * ncols * 8)
        + rows * ncols * constant("DEVICE_RESIDUAL_S_PER_CELL")
        + p.download_s(rows)
        + 2 * p.latency_s
    )


def sharded_plan_device_s(cells: int, shards: int, p: "LinkProfile") -> float:
    """Cost model for the shard_map pruning plan: each device evaluates the
    predicate over its 1/shards slice of the stat lanes in parallel, then the
    packed survivor bitmaps all-gather over ICI and the merged bitmap
    downloads (~cells/8 per predicate batch is already folded into the
    per-cell constant's fit). Priced against the single-device plan
    (``cells * DEVICE_PRUNE_S_PER_CELL``) and the host plan — the
    ``scan.plan`` router audit records which side actually won. ONE
    definition — `ops/state_cache` routing and the sharded-scan bench both
    call this, so they cannot drift apart."""
    shards = max(int(shards), 1)
    return (
        (cells / shards) * constant("DEVICE_PRUNE_S_PER_CELL")
        + constant("SHARD_DISPATCH_S")
        + shards * constant("SHARD_GATHER_S_PER_SHARD")
        + p.latency_s
    )


def dist_execute_s(item_s: Sequence[float], workers: int) -> float:
    """Makespan estimate for fanning per-item costs out over ``workers``
    via the LPT executor (`parallel/executor`): the max per-worker load of
    the deterministic LPT assignment plus the per-item scheduling tax.
    ``workers<=1`` degrades to the inline sum — so the comparison
    ``dist_execute_s(costs, n) < dist_execute_s(costs, 1)`` is exactly the
    router's fan-out-or-not question, audited as ``dist.execute``."""
    costs = [max(float(c), 0.0) for c in item_s]
    overhead = len(costs) * constant("DIST_ITEM_S")
    if workers <= 1 or len(costs) <= 1:
        return sum(costs)
    from delta_tpu.parallel.distributed import lpt_assign

    scaled = [int(c * 1e9) for c in costs]
    buckets = lpt_assign(scaled, workers)
    return max((sum(costs[j] for j in b) for b in buckets), default=0.0) \
        + overhead


@dataclass(frozen=True)
class LinkProfile:
    up_mbps: float
    down_mbps: float
    latency_s: float
    probed: bool  # False when conf-overridden

    def upload_s(self, nbytes: int) -> float:
        return self.latency_s + nbytes / (self.up_mbps * 1e6)

    def download_s(self, nbytes: int) -> float:
        return self.latency_s + nbytes / (self.down_mbps * 1e6)


@dataclass(frozen=True)
class Estimate:
    device_s: float
    up_s: float
    down_s: float
    kernel_s: float


_lock = threading.Lock()
_profile: Optional[LinkProfile] = None


def reset() -> None:
    """Drop the cached profile (tests)."""
    global _profile
    with _lock:
        _profile = None


def profile() -> LinkProfile:
    """The process-wide link profile (conf override, else one-shot probe)."""
    global _profile
    with _lock:
        if _profile is not None:
            return _profile
        from delta_tpu.utils.config import conf

        up = conf.get("delta.tpu.link.uploadMBps", None)
        down = conf.get("delta.tpu.link.downloadMBps", None)
        if up is not None and down is not None:
            _profile = LinkProfile(float(up), float(down), 0.005, probed=False)
            return _profile
        _profile = _probe()
        return _profile


def _probe() -> LinkProfile:
    import jax
    import jax.numpy as jnp
    import numpy as np

    # force one XLA execution first: the fresh-process link is 40-90x
    # faster than the steady state and would mis-route every kernel
    np.asarray(jax.jit(lambda a: a + 1)(jnp.arange(8)))

    # latency: tiny round trip
    t0 = time.perf_counter()
    np.asarray(jax.device_put(np.zeros(8, np.int32)))
    latency = time.perf_counter() - t0

    buf = np.random.randint(0, 1 << 30, _PROBE_BYTES // 4).astype(np.int32)
    up_best = down_best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        dev = jax.device_put(buf)
        jax.block_until_ready(dev)
        up_best = min(up_best, time.perf_counter() - t0)
        t0 = time.perf_counter()
        np.asarray(dev)
        down_best = min(down_best, time.perf_counter() - t0)
        del dev
    # Subtracting a noisy latency sample from a fast transfer can go ~zero
    # and report effectively infinite bandwidth (seen under host contention:
    # 10 GB/s on a ~10 MB/s tunnel), which mis-routes every kernel. Floor
    # the denominator at a quarter of the measured wall time so the derived
    # bandwidth can never exceed 4x what was actually observed.
    up_mbps = (_PROBE_BYTES / 1e6) / max(up_best - latency, up_best / 4, 1e-4)
    down_mbps = (_PROBE_BYTES / 1e6) / max(down_best - latency, down_best / 4, 1e-4)
    return LinkProfile(up_mbps, down_mbps, max(latency, 1e-4), probed=True)


def estimate_device_s(
    up_bytes: int, down_bytes: int, kernel_rows: int, shards: int = 1
) -> Estimate:
    """Wall-clock estimate for shipping operands + one sort-merge-class
    kernel + shipping results. ``kernel_rows`` is the per-shard row count
    when the caller already divided by the mesh; otherwise pass ``shards``
    and the kernel term scales 1/shards (the sort is shard-local)."""
    p = profile()
    up_s = p.upload_s(up_bytes)
    down_s = p.download_s(down_bytes)
    dispatch_s = 3 * p.latency_s  # put + exec + fetch round trips
    kernel_s = (kernel_rows / max(shards, 1)) * constant("KERNEL_S_PER_ROW") \
        + dispatch_s
    return Estimate(up_s + down_s + kernel_s, up_s, down_s, kernel_s)
