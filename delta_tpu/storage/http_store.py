"""Network object-store LogStore: atomic commits via conditional PUT.

The reference's LogStore contract (``storage/LogStore.scala:30-43``) demands
(1) atomic visibility, (2) mutual exclusion, (3) consistent listing. Over
HDFS it uses atomic rename (``HDFSLogStore.scala:46-90``); real object
stores need none of that machinery because a conditional create maps the
contract directly onto one HTTP request:

* **GCS dialect** — upload with ``x-goog-if-generation-match: 0``: the PUT
  succeeds only if no live generation of the object exists; a losing racer
  gets ``412 Precondition Failed``.
* **S3 dialect** — ``If-None-Match: *`` conditional PUT (supported by S3
  since 2024 and by most S3-compatible stores); same 412 semantics.

Either way the object becomes visible atomically (object stores have no
partial objects), so ``is_partial_write_visible() == False`` and checkpoint
writers can skip the temp+rename dance (``Checkpoints.scala:271-303``).

Retry policy: idempotent requests (GET/HEAD/DELETE/LIST, unconditional PUT)
retry on connection errors / timeouts / 429 / 5xx with exponential backoff.
A *conditional* PUT is also retried, but a 412 on a retry attempt is
ambiguous — our first attempt may have landed before the response was lost.
The client disambiguates by reading the object back: byte-identical content
means we won (commit succeeded), anything else is a genuine conflict. The
commit payload embeds a unique CommitInfo txnId upstream, so byte-equality
is a reliable ownership test for log commits.

The server side of this dialect (for tests and local development) lives in
``delta_tpu.storage.object_store_emulator``.
"""
from __future__ import annotations

import http.client
import io
import json
import socket
import time
import urllib.parse
from typing import Iterable, Iterator, Optional, Tuple

from delta_tpu.storage.logstore import FileStatus, LogStore
from delta_tpu.utils.errors import DeltaIOError
# RetryPolicy moved to (and is re-exported from) the shared module: the
# same bounded-backoff-with-deadline policy now drives every store's
# transient handling, not a private copy here.
from delta_tpu.utils.retries import RetryPolicy

__all__ = ["HttpObjectLogStore", "RetryPolicy"]

_RETRYABLE_STATUS = frozenset({429, 500, 502, 503, 504})


class _Response:
    def __init__(self, status: int, body: bytes, headers):
        self.status = status
        self.body = body
        self.headers = headers


class HttpObjectLogStore(LogStore):
    """LogStore over an HTTP object store (GCS- or S3-style conditional PUT).

    ``endpoint`` is the server base URL (e.g. ``http://127.0.0.1:4443``);
    paths are ``gs://bucket/key`` or ``s3://bucket/key`` URIs mapped
    path-style onto the endpoint (``{endpoint}/{bucket}/{key}``).
    """

    def __init__(self, endpoint: str, dialect: str = "gcs",
                 retry: Optional[RetryPolicy] = None):
        if dialect not in ("gcs", "s3"):
            raise DeltaIOError(f"Unknown object-store dialect {dialect!r}")
        parsed = urllib.parse.urlparse(endpoint)
        if parsed.scheme not in ("http", "https") or not parsed.netloc:
            raise DeltaIOError(
                f"Object-store endpoint must be an http(s) URL, got {endpoint!r}"
            )
        self.endpoint = endpoint.rstrip("/")
        self._host = parsed.netloc
        self._tls = parsed.scheme == "https"
        self._base_path = parsed.path.rstrip("/")
        self.dialect = dialect
        self.retry = retry or RetryPolicy()

    # -- request plumbing ------------------------------------------------

    @staticmethod
    def _split(path: str) -> Tuple[str, str]:
        parsed = urllib.parse.urlparse(path)
        if not parsed.scheme or not parsed.netloc:
            raise DeltaIOError(f"Expected scheme://bucket/key URI, got {path!r}")
        return parsed.netloc, parsed.path.lstrip("/")

    def _url(self, bucket: str, key: str = "", query: str = "") -> str:
        url = f"{self._base_path}/{bucket}"
        if key:
            url += "/" + urllib.parse.quote(key)
        if query:
            url += "?" + query
        return url

    def _request_once(self, method: str, url: str, body: Optional[bytes],
                      headers: dict) -> _Response:
        conn = (http.client.HTTPSConnection if self._tls
                else http.client.HTTPConnection)(self._host, timeout=self.retry.timeout_s)
        try:
            conn.request(method, url, body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            return _Response(resp.status, data, resp.headers)
        finally:
            conn.close()

    def _request(self, method: str, url: str, body: Optional[bytes] = None,
                 headers: Optional[dict] = None, *,
                 ambiguous_hook=None) -> _Response:
        """Run a request with retries. ``ambiguous_hook(attempt)`` is invoked
        before each retry of a non-idempotent request so the caller can
        resolve did-my-first-attempt-land ambiguity."""
        from delta_tpu.utils import telemetry

        headers = dict(headers or {})
        last_exc: Optional[Exception] = None
        start = time.monotonic()
        attempts_made = 0
        for attempt in range(self.retry.max_attempts):
            attempts_made = attempt + 1
            if attempt and ambiguous_hook is not None:
                resolved = ambiguous_hook(attempt)
                if resolved is not None:
                    return resolved
            try:
                resp = self._request_once(method, url, body, headers)
            except (ConnectionError, socket.timeout, http.client.HTTPException, OSError) as e:
                last_exc = e
            else:
                if resp.status not in _RETRYABLE_STATUS:
                    return resp
                last_exc = DeltaIOError(
                    f"{method} {url} -> HTTP {resp.status}: {resp.body[:200]!r}"
                )
            # total-deadline bound: a flapping store fails in deadline_s,
            # not max_attempts * max_delay_s
            if self.retry.give_up(attempt, start):
                break
            telemetry.bump_counter("storage.retry.attempts")
            time.sleep(self.retry.delay(attempt))
        telemetry.bump_counter("storage.retry.exhausted")
        raise DeltaIOError(
            f"{method} {self.endpoint}{url} failed after "
            f"{attempts_made} attempts in "
            f"{time.monotonic() - start:.1f}s: {last_exc}"
        )

    # -- LogStore API ----------------------------------------------------

    def read_bytes(self, path: str) -> bytes:
        bucket, key = self._split(path)
        resp = self._request("GET", self._url(bucket, key))
        if resp.status == 404:
            raise FileNotFoundError(path)
        if resp.status != 200:
            raise DeltaIOError(f"GET {path} -> HTTP {resp.status}")
        return resp.body

    def read_iter(self, path: str) -> Iterator[str]:
        data = self.read_bytes(path)
        for line in io.StringIO(data.decode("utf-8")):
            yield line.rstrip("\r\n")

    def write(self, path: str, lines: Iterable[str], overwrite: bool = False) -> None:
        data = ("".join(line + "\n" for line in lines)).encode("utf-8")
        self.write_bytes(path, data, overwrite=overwrite)

    def write_bytes(self, path: str, data: bytes, overwrite: bool = False) -> None:
        bucket, key = self._split(path)
        headers = {"Content-Length": str(len(data))}
        if not overwrite:
            if self.dialect == "gcs":
                headers["x-goog-if-generation-match"] = "0"
            else:
                headers["If-None-Match"] = "*"

        def resolve_ambiguity(attempt: int) -> Optional[_Response]:
            # A retried conditional PUT that now sees the object existing may
            # be observing its *own* first attempt (response lost in flight).
            # Byte-identical content = we won.
            if overwrite:
                return None
            try:
                existing = self.read_bytes(path)
            except FileNotFoundError:
                return None  # not created yet; retry the PUT
            if existing == data:
                return _Response(200, b"", {})
            raise FileExistsError(path)

        resp = self._request("PUT", self._url(bucket, key), body=data,
                             headers=headers, ambiguous_hook=resolve_ambiguity)
        if resp.status in (412, 409):
            raise FileExistsError(path)
        if resp.status not in (200, 201):
            raise DeltaIOError(f"PUT {path} -> HTTP {resp.status}: {resp.body[:200]!r}")

    def list_from(self, path: str) -> Iterator[FileStatus]:
        bucket, key = self._split(path)
        parent, _, start = key.rpartition("/")
        prefix = parent + "/" if parent else ""
        query = urllib.parse.urlencode({"prefix": prefix, "start-after-name": start})
        resp = self._request("GET", self._url(bucket, query=f"list&{query}"))
        if resp.status == 404:
            raise FileNotFoundError(path)
        if resp.status != 200:
            raise DeltaIOError(f"LIST {path} -> HTTP {resp.status}")
        payload = json.loads(resp.body.decode("utf-8"))
        objects = payload.get("objects", [])
        if not objects and not payload.get("prefix_exists", False):
            # object stores have no directories; an empty prefix with no
            # objects at all is the contract's missing-directory case
            raise FileNotFoundError(path)
        scheme = urllib.parse.urlparse(path).scheme
        for o in sorted(objects, key=lambda o: o["name"]):
            name = o["name"]
            # listing is prefix-recursive; emulate directory listing by
            # excluding deeper "subdirectory" objects
            rest = name[len(prefix):]
            if "/" in rest:
                continue
            yield FileStatus(
                f"{scheme}://{bucket}/{name}", int(o["size"]), int(o["updated"])
            )

    def exists(self, path: str) -> bool:
        bucket, key = self._split(path)
        resp = self._request("HEAD", self._url(bucket, key))
        if resp.status == 200:
            return True
        if resp.status == 404:
            return False
        raise DeltaIOError(f"HEAD {path} -> HTTP {resp.status}")

    def delete(self, path: str) -> bool:
        bucket, key = self._split(path)
        resp = self._request("DELETE", self._url(bucket, key))
        if resp.status in (200, 204):
            return True
        if resp.status == 404:
            return False
        raise DeltaIOError(f"DELETE {path} -> HTTP {resp.status}")

    def is_partial_write_visible(self, path: str) -> bool:
        return False  # object PUTs are atomic: no partial objects, ever

    def mkdirs(self, path: str) -> None:
        pass  # object stores have no directories
