"""Offline distributed-trace inspector.

Reads the JSONL span spools a sharded job's processes wrote under
``delta.tpu.trace.dir`` (`delta_tpu/obs/trace_store.py`) and stitches them
without a running obs server::

    python tools/trace_dump.py --dir /tmp/spool list            # trace index
    python tools/trace_dump.py --dir /tmp/spool show <traceId>  # Chrome JSON
    python tools/trace_dump.py --dir /tmp/spool show <traceId> -o t.json
    python tools/trace_dump.py --dir /tmp/spool analyze <traceId>

``list`` prints one JSON row per trace, newest first (pipe into ``jq``);
``show`` emits the stitched Perfetto-loadable Chrome-trace JSON (load the
``-o`` file at https://ui.perfetto.dev); ``analyze`` prints the
critical-path / straggler analysis — which shard set the makespan and by
how much it overran its LPT-predicted byte share. ``--dir`` defaults to the
configured ``delta.tpu.trace.dir``.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", dest="directory", default=None,
                    help="spool directory (default: conf delta.tpu.trace.dir)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_list = sub.add_parser("list", help="index of spooled traces, newest first")
    p_list.add_argument("--limit", type=int, default=20,
                        help="newest N traces (default 20)")
    p_show = sub.add_parser("show", help="stitched Chrome-trace JSON")
    p_show.add_argument("trace_id", help="128-bit hex trace id (see `list`)")
    p_show.add_argument("-o", "--out", default=None,
                        help="write to a file instead of stdout")
    p_an = sub.add_parser("analyze",
                          help="critical path + straggler analysis")
    p_an.add_argument("trace_id", help="128-bit hex trace id (see `list`)")
    args = ap.parse_args(argv)

    from delta_tpu.obs import trace_store
    from delta_tpu.utils.config import conf

    directory = args.directory or conf.get("delta.tpu.trace.dir")
    if not directory:
        print("no spool directory: pass --dir or set delta.tpu.trace.dir",
              file=sys.stderr)
        return 2
    directory = str(directory)

    if args.cmd == "list":
        for row in trace_store.recent_traces(directory, limit=args.limit):
            print(json.dumps(row))
        return 0

    if args.cmd == "show":
        trace = trace_store.stitch_trace(directory, args.trace_id)
        if trace is None:
            print(f"no spooled spans for trace {args.trace_id!r} in "
                  f"{directory}", file=sys.stderr)
            return 1
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                json.dump(trace, f, default=str)
            rows = sum(1 for r in trace["traceEvents"]
                       if r.get("cat") == "delta")
            print(f"wrote {rows} spans to {args.out} "
                  f"(load at https://ui.perfetto.dev)")
        else:
            print(json.dumps(trace, default=str))
        return 0

    analysis = trace_store.analyze_trace(directory, args.trace_id)
    if analysis is None:
        print(f"no spooled spans for trace {args.trace_id!r} in {directory}",
              file=sys.stderr)
        return 1
    print(json.dumps(analysis, indent=1, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
