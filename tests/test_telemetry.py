"""Usage-logging telemetry (SURVEY §5; ``metering/DeltaLogging.scala:50-109``):
hierarchical spans (contextvar nesting, Chrome-trace export), the metrics
registry (counters/gauges/log-bucket histograms, Prometheus exposition),
CommitStats parity events, and the engine wiring. (The AST lints that used
to live here — command-entry-point instrumentation, the metric catalog and
its DESCRIPTIONS — are now passes in the ``delta_tpu/analysis`` engine,
exercised by ``tests/test_analysis.py`` and ``tools/analyze.py``.)
"""
import json
import os
import threading

import pyarrow as pa
import pytest

from delta_tpu.api.tables import DeltaTable
from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf


@pytest.fixture(autouse=True)
def _fresh_buffer():
    telemetry.clear_events()
    yield
    telemetry.clear_events()


def test_record_event_and_query_by_prefix():
    telemetry.record_event("delta.test.alpha", {"n": 1}, path="/t")
    telemetry.record_event("delta.test.beta", {"n": 2})
    telemetry.record_event("other.op")
    got = telemetry.recent_events("delta.test")
    assert [e.op_type for e in got] == ["delta.test.alpha", "delta.test.beta"]
    assert got[0].tags == {"path": "/t"}
    assert got[0].data == {"n": 1}


def test_record_operation_captures_duration():
    with telemetry.record_operation("delta.test.op") as ev:
        pass
    [got] = telemetry.recent_events("delta.test.op")
    assert got is ev
    assert got.duration_ms is not None and got.duration_ms >= 0
    assert got.error is None


def test_record_operation_captures_error_and_reraises():
    with pytest.raises(ValueError):
        with telemetry.record_operation("delta.test.boom"):
            raise ValueError("kapow")
    [got] = telemetry.recent_events("delta.test.boom")
    assert got.error and "kapow" in got.error


def test_event_json_round_trips():
    telemetry.record_event("delta.test.json", {"k": [1, 2]}, table="x")
    [ev] = telemetry.recent_events("delta.test.json")
    d = json.loads(ev.to_json())
    assert d["opType"] == "delta.test.json"
    assert d["data"] == {"k": [1, 2]}


def test_prefix_matching_respects_dotted_boundaries():
    """`recent_events("delta.commit")` must not match `delta.commitFoo.*`."""
    telemetry.record_event("delta.commit")
    telemetry.record_event("delta.commit.stats")
    telemetry.record_event("delta.commitFoo")
    telemetry.record_event("delta.commitFoo.bar")
    got = [e.op_type for e in telemetry.recent_events("delta.commit")]
    assert got == ["delta.commit", "delta.commit.stats"]

    telemetry.clear_counters()
    telemetry.bump_counter("scan.files", 1)
    telemetry.bump_counter("scan.files.read", 2)
    telemetry.bump_counter("scan.filesFoo", 3)
    assert telemetry.counters("scan.files") == {
        "scan.files": 1, "scan.files.read": 2,
    }


def test_ring_buffer_bounded():
    for _ in range(5000):
        telemetry.record_event("delta.test.flood")
    # deque(maxlen=4096): exactly full — also catches silent non-recording
    assert len(telemetry.recent_events()) == 4096


def test_ring_buffer_size_configurable():
    with conf.set_temporarily(delta__tpu__telemetry__bufferSize=16):
        for _ in range(100):
            telemetry.record_event("delta.test.small")
        assert len(telemetry.recent_events()) == 16
    # back to the default on the next record
    telemetry.record_event("delta.test.restored")
    assert len(telemetry.recent_events()) == 17  # resize preserves contents


# -- hierarchical spans ------------------------------------------------------


def test_span_nesting_parent_child_ordering():
    with telemetry.record_operation("delta.test.outer") as outer:
        telemetry.record_event("delta.test.point")
        with telemetry.record_operation("delta.test.outer.mid") as mid:
            with telemetry.record_operation("delta.test.outer.mid.leaf") as leaf:
                pass
    assert outer.parent_id is None and outer.depth == 0
    assert mid.parent_id == outer.span_id and mid.depth == 1
    assert leaf.parent_id == mid.span_id and leaf.depth == 2
    # point events parent to the enclosing span
    [pt] = telemetry.recent_events("delta.test.point")
    assert pt.parent_id == outer.span_id
    # children close (and land in the buffer) before their parent
    order = [e.op_type for e in telemetry.recent_events("delta.test")]
    assert order.index("delta.test.outer.mid.leaf") < order.index("delta.test.outer.mid")
    assert order.index("delta.test.outer.mid") < order.index("delta.test.outer")


def test_span_data_attaches_to_innermost_open_span():
    with telemetry.record_operation("delta.test.host") as ev:
        telemetry.add_span_data(rows=7)
    assert ev.data == {"rows": 7}
    # no open span: silently a no-op
    telemetry.add_span_data(ignored=True)


def test_span_nesting_isolated_across_threads():
    """Each thread gets its own contextvar stack: concurrent spans never
    parent across threads, and nesting inside each thread stays intact."""
    results = {}
    barrier = threading.Barrier(2)

    def worker(name):
        barrier.wait()
        with telemetry.record_operation(f"delta.test.{name}") as root:
            barrier.wait()  # both roots open simultaneously
            with telemetry.record_operation(f"delta.test.{name}.child") as child:
                pass
        results[name] = (root, child)

    ts = [threading.Thread(target=worker, args=(n,)) for n in ("t1", "t2")]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    r1, c1 = results["t1"]
    r2, c2 = results["t2"]
    assert r1.parent_id is None and r2.parent_id is None
    assert c1.parent_id == r1.span_id
    assert c2.parent_id == r2.span_id
    assert r1.span_id != r2.span_id
    assert c1.thread_id != c2.thread_id


def test_span_stack_snapshot_reports_open_chain():
    assert telemetry.span_stack_snapshot() == []
    with telemetry.record_operation("delta.test.a", path="/t") as a:
        with telemetry.record_operation("delta.test.a.b") as b:
            telemetry.add_span_data(rows=3)
            snap = telemetry.span_stack_snapshot()
    assert [s["opType"] for s in snap] == ["delta.test.a", "delta.test.a.b"]
    assert snap[0]["spanId"] == a.span_id and snap[1]["parentId"] == a.span_id
    assert snap[1]["data"] == {"rows": 3}
    assert snap[0]["tags"] == {"path": "/t"}
    assert all(s["elapsedMs"] >= 0 for s in snap)
    assert b.span_id  # snapshot is JSON-able copies, not the live events
    json.dumps(snap)


def test_failure_hooks_fire_once_per_span_with_stack():
    calls = []

    def hook(ev, exc):
        calls.append((ev.op_type, str(exc),
                      [s["opType"] for s in telemetry.span_stack_snapshot()]))

    telemetry.add_failure_hook(hook)
    try:
        with pytest.raises(ValueError):
            with telemetry.record_operation("delta.test.outer"):
                with telemetry.record_operation("delta.test.outer.leaf"):
                    raise ValueError("pow")
    finally:
        telemetry.remove_failure_hook(hook)
    # innermost fires first, with the full open stack; the same exception
    # then fires again as it unwinds the outer span
    assert calls[0] == ("delta.test.outer.leaf", "pow",
                        ["delta.test.outer", "delta.test.outer.leaf"])
    assert calls[1] == ("delta.test.outer", "pow", ["delta.test.outer"])
    # a broken hook never masks the real error
    broken = lambda ev, exc: 1 / 0  # noqa: E731
    telemetry.add_failure_hook(broken)
    try:
        with pytest.raises(ValueError):
            with telemetry.record_operation("delta.test.brokenhook"):
                raise ValueError("real")
    finally:
        telemetry.remove_failure_hook(broken)


def test_chrome_trace_includes_open_spans_with_clamped_duration():
    """Regression: spans still open at export time used to be dropped (they
    live in _ACTIVE, not the ring buffer) — they must export as clamped
    complete events flagged incomplete."""
    telemetry.clear_events()
    with telemetry.record_operation("delta.test.live") as live:
        with telemetry.record_operation("delta.test.live.closedchild"):
            pass
        trace = telemetry.export_chrome_trace()
        rows = [r for r in trace["traceEvents"]
                if r.get("name") == "delta.test.live"]
        assert len(rows) == 1, "open span must appear exactly once"
        [row] = rows
        assert row["ph"] == "X" and row["dur"] >= 0
        assert row["args"]["incomplete"] is True
        assert row["args"]["spanId"] == live.span_id
        # the closed child exported normally alongside it
        assert any(r.get("name") == "delta.test.live.closedchild"
                   and "incomplete" not in r["args"]
                   for r in trace["traceEvents"])
    # after the span closes, a fresh export has the real (final) row only
    trace = telemetry.export_chrome_trace()
    rows = [r for r in trace["traceEvents"]
            if r.get("name") == "delta.test.live"]
    assert len(rows) == 1 and "incomplete" not in rows[0]["args"]


# -- metrics registry --------------------------------------------------------


def test_histogram_bucket_boundaries():
    telemetry.reset_all()
    telemetry.observe("delta.test.hist", 1.0)     # == first bound -> le=1
    telemetry.observe("delta.test.hist", 1.5)     # -> le=2
    telemetry.observe("delta.test.hist", 2.0)     # == bound -> le=2
    telemetry.observe("delta.test.hist", 65536.0)  # == last bound
    telemetry.observe("delta.test.hist", 1e9)     # -> +Inf
    [(key, h)] = telemetry.histograms("delta.test.hist").items()
    assert key == ("delta.test.hist", ())
    bounds = telemetry.HISTOGRAM_BUCKETS
    assert h.counts[bounds.index(1.0)] == 1
    assert h.counts[bounds.index(2.0)] == 2
    assert h.counts[bounds.index(65536.0)] == 1
    assert h.counts[-1] == 1  # +Inf
    assert h.count == 5
    assert h.sum == pytest.approx(1.0 + 1.5 + 2.0 + 65536.0 + 1e9)


def test_gauges_with_labels():
    telemetry.reset_all()
    telemetry.set_gauge("delta.test.gauge", 3, path="/a")
    telemetry.set_gauge("delta.test.gauge", 5, path="/a")  # overwrite
    telemetry.set_gauge("delta.test.gauge", 7, path="/b")
    g = telemetry.gauges("delta.test.gauge")
    assert g[("delta.test.gauge", (("path", "/a"),))] == 5.0
    assert g[("delta.test.gauge", (("path", "/b"),))] == 7.0


def test_prometheus_text_golden():
    telemetry.reset_all()
    telemetry.bump_counter("commit.total", 3)
    telemetry.set_gauge("delta.cache.bytes", 128, path="/t")
    telemetry.observe("delta.op.ms", 3.0, path="/t")
    telemetry.observe("delta.op.ms", 5.0, path="/t")
    text = telemetry.prometheus_text()
    bucket_lines = "".join(
        f'delta_op_ms_bucket{{path="/t",le="{b}"}} '
        f"{0 if b < 4 else (1 if b < 8 else 2)}\n"
        for b in (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024,
                  2048, 4096, 8192, 16384, 32768, 65536)
    )
    expected = (
        # cataloged metrics carry a # HELP line from metric_names.DESCRIPTIONS;
        # ad-hoc names (delta.cache.bytes, delta.op.ms) get TYPE only
        "# HELP commit_total_total "
        "Commits attempted through the transaction pipeline.\n"
        "# TYPE commit_total_total counter\n"
        "commit_total_total 3\n"
        "# TYPE delta_cache_bytes gauge\n"
        'delta_cache_bytes{path="/t"} 128\n'
        "# TYPE delta_op_ms histogram\n"
        + bucket_lines
        + 'delta_op_ms_bucket{path="/t",le="+Inf"} 2\n'
        'delta_op_ms_sum{path="/t"} 8\n'
        'delta_op_ms_count{path="/t"} 2\n'
    )
    assert text == expected


def test_prometheus_type_emitted_once_per_metric_name():
    """Label sets of one gauge share a single # HELP/# TYPE header —
    Prometheus parsers reject duplicate TYPE lines for a name."""
    telemetry.reset_all()
    telemetry.set_gauge("router.missRate", 0.25)
    telemetry.set_gauge("table.health.severity", 1, path="/a")
    telemetry.set_gauge("table.health.severity", 2, path="/b")
    text = telemetry.prometheus_text()
    assert text.count("# TYPE table_health_severity gauge") == 1
    assert text.count("# HELP table_health_severity ") == 1
    assert 'table_health_severity{path="/a"} 1' in text
    assert 'table_health_severity{path="/b"} 2' in text
    assert "# HELP router_missRate " in text


def test_prometheus_escapes_label_values():
    telemetry.reset_all()
    telemetry.set_gauge("delta.test.esc", 1, path='C:\\data\\"t"\ntbl')
    text = telemetry.prometheus_text()
    assert 'path="C:\\\\data\\\\\\"t\\"\\ntbl"' in text
    assert "\n\n" not in text  # raw newline never leaks into the exposition


def test_metrics_snapshot_is_json_serializable():
    telemetry.reset_all()
    telemetry.bump_counter("a.b", 2)
    telemetry.set_gauge("g", 1.5)
    telemetry.observe("h.ms", 10, path="/t")
    snap = json.loads(json.dumps(telemetry.metrics_snapshot()))
    assert snap["counters"] == {"a.b": 2}
    assert snap["gauges"] == {"g": 1.5}
    assert snap["histograms"]["h.ms{path=/t}"]["count"] == 1
    compact = json.loads(json.dumps(telemetry.bench_snapshot()))
    assert compact["counters"]["a.b"] == 2
    assert compact["histograms"]["h.ms{path=/t}"]["p50"] == 16.0


def test_bench_snapshot_includes_matching_gauges():
    """bench.py snapshots carry table.health.* gauges via the include list."""
    telemetry.reset_all()
    telemetry.set_gauge("table.health.severity", 1, path="/t")
    telemetry.set_gauge("unrelated.gauge", 9)
    snap = telemetry.bench_snapshot(include=("table.health",))
    assert snap["gauges"] == {"table.health.severity{path=/t}": 1.0}
    assert "gauges" not in telemetry.bench_snapshot()


# -- zero-overhead disable ---------------------------------------------------


def test_telemetry_disabled_records_nothing_counters_still_work():
    telemetry.reset_all()
    with conf.set_temporarily(delta__tpu__telemetry__enabled=False):
        telemetry.record_event("delta.test.blackout")
        with telemetry.record_operation("delta.test.blackout.op") as ev:
            telemetry.add_span_data(x=1)
        telemetry.bump_counter("hot.counter")
    assert telemetry.recent_events() == []
    assert ev.duration_ms is None  # span never timed or buffered
    assert telemetry.counters("hot.counter") == {"hot.counter": 1}
    # no fabricated 0-ms samples leak into the latency histograms
    assert telemetry.histograms("delta.streaming") == {}
    # re-enabled: recording resumes
    telemetry.record_event("delta.test.back")
    assert len(telemetry.recent_events()) == 1


# -- engine wiring -----------------------------------------------------------


def test_commits_emit_usage_events(tmp_table):
    t = DeltaTable.create(
        tmp_table, data=pa.table({"id": pa.array([1], pa.int64())})
    )
    t.delete("id = 1")
    commits = [e for e in telemetry.recent_events("delta.commit")
               if e.op_type == "delta.commit"]
    assert len(commits) >= 2  # create + delete
    assert all(e.duration_ms is not None for e in commits)
    assert all(e.tags.get("path") == tmp_table for e in commits)


def test_commit_stats_on_clean_commit(tmp_table):
    DeltaTable.create(
        tmp_table, data=pa.table({"id": pa.array([1, 2], pa.int64())})
    )
    [stats] = [e.data for e in telemetry.recent_events("delta.commit.stats")]
    assert stats["readVersion"] == -1 and stats["commitVersion"] == 0
    assert stats["attempts"] == 1
    assert stats["numAdd"] >= 1 and stats["numRemove"] == 0
    assert stats["bytesNew"] > 0
    assert stats["isolationLevel"] == "WriteSerializable"
    for phase in ("prepare", "write", "postCommit"):
        assert phase in stats["phaseDurationsMs"]
    # phase spans nest under the commit span
    [commit] = [e for e in telemetry.recent_events("delta.commit")
                if e.op_type == "delta.commit"]
    kids = {e.op_type for e in telemetry.recent_events()
            if e.parent_id == commit.span_id}
    assert {"delta.commit.prepare", "delta.commit.write",
            "delta.commit.postCommit"} <= kids


def test_commit_stats_on_conflict_retry(tmp_table):
    """A commit that loses the race retries through the conflict checker and
    reports attempts/conflictCheck duration in its CommitStats."""
    from delta_tpu.commands import operations as ops
    from delta_tpu.commands.write import WriteIntoDelta
    from delta_tpu.exec import write as write_exec

    t = DeltaTable.create(
        tmp_table, data=pa.table({"id": pa.array([0], pa.int64())})
    )
    log = t.delta_log
    txn = log.start_transaction()
    # interleaving writer wins version 1 before our txn commits
    WriteIntoDelta(log, "append", pa.table({"id": pa.array([1], pa.int64())})).run()
    telemetry.clear_events()
    actions = write_exec.write_files(
        log.data_path, pa.table({"id": pa.array([2], pa.int64())}),
        txn.metadata, data_change=True,
    )
    version = txn.commit(actions, ops.Write(mode="Append"))
    assert version == 2
    assert txn.stats.attempts == 2
    [stats] = [e.data for e in telemetry.recent_events("delta.commit.stats")]
    assert stats["attempts"] == 2
    assert "conflictCheck" in stats["phaseDurationsMs"]
    checks = [e for e in telemetry.recent_events("delta.commit.retry.conflictCheck")]
    assert checks and checks[0].data["winningCommits"] == 1
    assert telemetry.counters("commit.retries") == {"commit.retries": 1}


def test_concurrent_commits_each_emit_stats(tmp_table):
    """Chaos-harness shape: racing writers all emit CommitStats, spans stay
    thread-local (no cross-thread parenting)."""
    from delta_tpu.commands.write import WriteIntoDelta

    t = DeltaTable.create(
        tmp_table, data=pa.table({"id": pa.array([0], pa.int64())})
    )
    telemetry.clear_events()
    N = 6
    errs = []

    def appender(i):
        try:
            WriteIntoDelta(t.delta_log, "append", pa.table({
                "id": pa.array([100 + i], pa.int64()),
            })).run()
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=appender, args=(i,)) for i in range(N)]
    for th in ts:
        th.start()
    for th in ts:
        th.join()
    assert errs == []
    stats = telemetry.recent_events("delta.commit.stats")
    assert len(stats) == N
    assert sorted(e.data["commitVersion"] for e in stats) == list(range(1, N + 1))
    # every commit span is parented by a dml span from ITS OWN thread
    by_id = {e.span_id: e for e in telemetry.recent_events() if e.span_id}
    for c in (e for e in telemetry.recent_events("delta.commit")
              if e.op_type == "delta.commit"):
        parent = by_id[c.parent_id]
        assert parent.thread_id == c.thread_id


def test_history_metrics_disabled_suppresses_stats_op_metrics(tmp_table):
    t = DeltaTable.create(
        tmp_table, data=pa.table({"id": pa.array(range(5), pa.int64())})
    )
    telemetry.clear_events()
    with conf.set_temporarily(delta__tpu__history__metricsEnabled=False):
        t.delete("id = 1")
    [stats] = [e.data for e in telemetry.recent_events("delta.commit.stats")]
    assert "opMetrics" not in stats


# -- acceptance: MERGE observability end to end ------------------------------


def test_merge_produces_span_tree_stats_prometheus_and_trace(tmp_table, tmp_path):
    from delta_tpu.protocol import filenames
    from delta_tpu.protocol.actions import AddFile, RemoveFile, actions_from_lines

    telemetry.reset_all()
    t = DeltaTable.create(
        tmp_table,
        data=pa.table({"id": pa.array(range(10), pa.int64()),
                       "v": pa.array(["x"] * 10)}),
    )
    src = pa.table({"id": pa.array([3, 100], pa.int64()),
                    "v": pa.array(["u", "i"])})
    (t.alias("t").merge(src, "t.id = s.id", source_alias="s")
     .when_matched_update_all().when_not_matched_insert_all().execute())

    # 1. nested span tree: merge -> commit -> {prepare, write, postCommit}
    [merge] = telemetry.recent_events("delta.dml.merge")
    commits = [e for e in telemetry.recent_events("delta.commit")
               if e.op_type == "delta.commit" and e.parent_id == merge.span_id]
    assert commits, "delta.commit span must nest under delta.dml.merge"
    commit = commits[-1]
    kids = {e.op_type for e in telemetry.recent_events()
            if e.parent_id == commit.span_id}
    assert {"delta.commit.prepare", "delta.commit.write"} <= kids
    # DML rewrite metrics attached to the merge span via report_metrics
    assert "numTargetRowsUpdated" in merge.data

    # 2. stats event matches the actions actually committed
    stats = telemetry.recent_events("delta.commit.stats")[-1].data
    version = stats["commitVersion"]
    committed = actions_from_lines(t.delta_log.store.read_iter(
        f"{t.delta_log.log_path}/{filenames.delta_file(version)}"))
    num_add = sum(isinstance(a, AddFile) for a in committed)
    num_remove = sum(isinstance(a, RemoveFile) for a in committed)
    assert stats["numAdd"] == num_add >= 1
    assert stats["numRemove"] == num_remove >= 1

    # 3. prometheus exposition includes at least one histogram
    text = telemetry.prometheus_text()
    assert "# TYPE delta_commit_duration_ms histogram" in text
    assert "_bucket{" in text and "_count{" in text

    # 4. Perfetto-loadable Chrome trace JSON
    out = tmp_path / "trace.json"
    trace = telemetry.export_chrome_trace(str(out))
    loaded = json.loads(out.read_text())
    assert loaded["traceEvents"] == json.loads(json.dumps(
        trace["traceEvents"], default=str))
    complete = [r for r in loaded["traceEvents"] if r.get("ph") == "X"]
    names = {r["name"] for r in complete}
    assert {"delta.dml.merge", "delta.commit"} <= names
    mrow = next(r for r in complete if r["name"] == "delta.dml.merge")
    crow = next(r for r in complete
                if r["name"] == "delta.commit"
                and r["args"].get("parentId") == mrow["args"]["spanId"])
    # child timeline contained within the parent's
    assert mrow["ts"] <= crow["ts"]
    assert crow["ts"] + crow["dur"] <= mrow["ts"] + mrow["dur"] + 1000


# -- engine status events (pre-existing behavior) ----------------------------


def test_with_status_records_event_and_duration(tmp_table):
    import numpy as np

    from delta_tpu import DeltaLog
    from delta_tpu.commands.write import WriteIntoDelta
    from delta_tpu.exec.scan import scan_files

    telemetry.clear_events()
    log = DeltaLog.for_table(tmp_table)
    WriteIntoDelta(log, "append", pa.table({"a": np.arange(5)})).run()
    scan_files(log.update(), ["a > 1"])
    evs = [e for e in telemetry.recent_events("delta.status")
           if e.data.get("message") == "Filtering files for query"]
    assert evs and evs[-1].duration_ms is not None
    # the status event nests under the scan-planning span
    planning = telemetry.recent_events("delta.scan.planning")
    assert planning and evs[-1].parent_id == planning[-1].span_id

    telemetry.clear_events()
    from delta_tpu.commands.vacuum import VacuumCommand

    VacuumCommand(log, retention_hours=1000, dry_run=True).run()
    evs = telemetry.recent_events("delta.status")
    assert any("VACUUM" in e.data.get("message", "") for e in evs)
    # and the whole command ran under its utility span
    assert telemetry.recent_events("delta.utility.vacuum")


def test_logstore_io_counters(tmp_table):
    telemetry.reset_all()
    DeltaTable.create(
        tmp_table, data=pa.table({"id": pa.array([1], pa.int64())})
    )
    io = telemetry.counters("logstore")
    assert io.get("logstore.write.calls", 0) >= 1
    assert io.get("logstore.write.bytes", 0) > 0
    assert io.get("logstore.list.calls", 0) >= 1


# -- cross-thread span propagation -------------------------------------------


def test_span_context_propagates_into_pool_workers():
    """propagated() captures the submitter's open span chain: worker-thread
    spans parent under it (on their own thread lanes) instead of starting
    orphan roots."""
    from concurrent.futures import ThreadPoolExecutor

    def work(i):
        with telemetry.record_operation("delta.test.prop.child") as w:
            pass
        return w

    with telemetry.record_operation("delta.test.prop") as parent:
        with ThreadPoolExecutor(max_workers=2) as pool:
            children = list(pool.map(telemetry.propagated(work), range(4)))
    assert all(c.parent_id == parent.span_id for c in children)
    assert any(c.thread_id != parent.thread_id for c in children)
    # the submitter's own stack is untouched by the workers
    assert telemetry.span_context() == ()


def test_chrome_trace_emits_process_and_pool_thread_metadata():
    """Named worker-pool lanes render labeled in Perfetto: the export
    carries a process_name metadata row, and a tid whose first event came
    from a generic Thread-N later adopts the engine pool's name."""
    import os as _os
    from concurrent.futures import ThreadPoolExecutor

    telemetry.clear_events()

    def work(i):
        with telemetry.record_operation("delta.test.pool.child"):
            pass

    with telemetry.record_operation("delta.test.pool"):
        with ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="delta-scan-decode"
        ) as pool:
            list(pool.map(telemetry.propagated(work), range(4)))
    trace = telemetry.export_chrome_trace()
    meta = [r for r in trace["traceEvents"] if r.get("ph") == "M"]
    procs = [r for r in meta if r["name"] == "process_name"]
    assert procs and procs[0]["args"]["name"] == "delta-tpu"
    assert procs[0]["pid"] == _os.getpid()
    tnames = {r["tid"]: r["args"]["name"] for r in meta
              if r["name"] == "thread_name"}
    assert any(n.startswith("delta-scan-decode") for n in tnames.values())
    # every span row's tid has a thread_name metadata row
    for r in trace["traceEvents"]:
        if r.get("ph") == "X":
            assert r["tid"] in tnames


def test_adopt_span_context_restores_on_exit():
    with telemetry.record_operation("delta.test.adopt") as parent:
        carrier = telemetry.span_context()
    assert carrier == (parent.span_id,)
    with telemetry.adopt_span_context(carrier):
        telemetry.record_event("delta.test.adopt.point")
    assert telemetry.span_context() == ()
    [pt] = telemetry.recent_events("delta.test.adopt.point")
    assert pt.parent_id == parent.span_id


def test_propagated_is_identity_with_no_span_or_blackout():
    def f(x):
        return x

    assert telemetry.propagated(f) is f  # no open span: nothing to carry
    with conf.set_temporarily(delta__tpu__telemetry__enabled=False):
        with telemetry.record_operation("delta.test.dark"):
            assert telemetry.propagated(f) is f  # blackout: zero overhead


def test_obs_public_api_matches_catalog():
    """Each obs module's ``__all__`` must equal its PUBLIC_API entry — a new
    entry point (or a rename) has to land in the catalog too. (A runtime
    import check, not an AST lint — the AST lints moved to the
    delta_tpu/analysis engine; see tests/test_analysis.py.)"""
    import importlib

    from delta_tpu.obs import metric_names

    obs_dir = os.path.join(
        os.path.dirname(__file__), "..", "delta_tpu", "obs")
    modules = sorted(
        f[:-3] for f in os.listdir(obs_dir)
        if f.endswith(".py") and f != "__init__.py"
    )
    assert set(modules) == set(metric_names.PUBLIC_API), (
        "obs modules and PUBLIC_API catalog diverge"
    )
    for mod in modules:
        m = importlib.import_module(f"delta_tpu.obs.{mod}")
        assert tuple(sorted(m.__all__)) == tuple(
            sorted(metric_names.PUBLIC_API[mod])
        ), f"obs/{mod}.py __all__ out of sync with PUBLIC_API"
