"""Schema machinery tests (reference spec: ``SchemaUtilsSuite``, 1,311 LoC).

Started with the ALTER widening + Arrow interop edge cases that round-1
review flagged; grows toward the full SchemaUtilsSuite matrix.
"""
import pyarrow as pa
import pytest

from delta_tpu.schema import schema_utils
from delta_tpu.schema.arrow_interop import delta_type_from_arrow
from delta_tpu.schema.types import (
    ArrayType,
    ByteType,
    DoubleType,
    FloatType,
    IntegerType,
    LongType,
    MapType,
    NullType,
    ShortType,
    StringType,
    StructType,
)
from delta_tpu.utils.errors import SchemaMismatchError


class TestCanChangeDataType:
    def test_widening_lattice(self):
        ok = [
            (ByteType(), ShortType()),
            (ByteType(), IntegerType()),
            (ByteType(), LongType()),
            (ShortType(), IntegerType()),
            (ShortType(), LongType()),
            (IntegerType(), LongType()),
            (FloatType(), DoubleType()),
        ]
        for f, t in ok:
            assert schema_utils.can_change_data_type(f, t), (f, t)

    def test_narrowing_refused(self):
        bad = [
            (LongType(), IntegerType()),
            (IntegerType(), ShortType()),
            (DoubleType(), FloatType()),
            (IntegerType(), StringType()),
            (StringType(), IntegerType()),
            (IntegerType(), DoubleType()),  # long would lose precision; not in lattice
        ]
        for f, t in bad:
            assert not schema_utils.can_change_data_type(f, t), (f, t)

    def test_null_type_to_anything(self):
        assert schema_utils.can_change_data_type(NullType(), StringType())

    def test_nested_widening(self):
        assert schema_utils.can_change_data_type(
            ArrayType(IntegerType()), ArrayType(LongType())
        )
        assert schema_utils.can_change_data_type(
            MapType(IntegerType(), FloatType()), MapType(LongType(), DoubleType())
        )
        inner_f = StructType().add("x", IntegerType())
        inner_t = StructType().add("x", LongType())
        assert schema_utils.can_change_data_type(inner_f, inner_t)
        assert not schema_utils.can_change_data_type(inner_t, inner_f)


def test_uint64_arrow_rejected():
    with pytest.raises(SchemaMismatchError, match="uint64"):
        delta_type_from_arrow(pa.uint64())


def test_uint32_arrow_widens_to_long():
    assert delta_type_from_arrow(pa.uint32()) == LongType()
