"""Device ops: sharded replay kernel + data-skipping pruning.

The host `LogReplay` is the spec (PROTOCOL.md "Action Reconciliation");
the device kernel must compute identical alive/tombstone sets on random
action streams, single-device and sharded over the virtual 8-CPU mesh.
"""
import json
import random

import numpy as np
import pytest

from delta_tpu.log.replay import LogReplay
from delta_tpu.ops import pruning, replay_kernel, state_export
from delta_tpu.expr.parser import parse_predicate
from delta_tpu.parallel.mesh import state_mesh
from delta_tpu.protocol.actions import AddFile, Metadata, RemoveFile
from delta_tpu.schema.types import (
    DoubleType,
    IntegerType,
    LongType,
    StringType,
    StructType,
)


def _random_stream(seed, n_versions=40, n_paths=25):
    rng = random.Random(seed)
    versioned = []
    for v in range(n_versions):
        actions = []
        for _ in range(rng.randint(1, 6)):
            p = f"part-{rng.randrange(n_paths):05d}.parquet"
            if rng.random() < 0.7:
                actions.append(
                    AddFile(path=p, partition_values={}, size=rng.randrange(1, 1000),
                            modification_time=v, data_change=True)
                )
            else:
                actions.append(
                    RemoveFile(path=p, deletion_timestamp=v * 1000, data_change=True)
                )
        versioned.append((v, actions))
    return versioned


def _host_state(versioned, min_retention=0):
    replay = LogReplay(min_file_retention_timestamp=min_retention)
    for v, actions in versioned:
        replay.append(v, actions)
    alive = set(replay.active_files.keys())
    tombs = {r.path for r in replay.get_tombstones()}
    return alive, tombs


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_replay_kernel_matches_host(seed):
    versioned = _random_stream(seed)
    arrays = state_export.actions_to_arrays(versioned)
    result = replay_kernel.replay_alive_mask(arrays, min_retention_ts=0)
    alive_paths = {
        arrays.paths[arrays.path_id[i]]
        for i in range(arrays.num_rows)
        if bool(result.alive[i])
    }
    tomb_paths = {
        arrays.paths[arrays.path_id[i]]
        for i in range(arrays.num_rows)
        if bool(result.tombstone[i])
    }
    host_alive, host_tombs = _host_state(versioned)
    assert alive_paths == host_alive
    assert tomb_paths == host_tombs
    assert int(result.stats.num_files) == len(host_alive)


@pytest.mark.parametrize("seed", [0, 5])
def test_replay_sharded_matches_host(seed):
    versioned = _random_stream(seed, n_versions=60, n_paths=50)
    arrays = state_export.actions_to_arrays(versioned)
    mesh = state_mesh()
    result = replay_kernel.replay_sharded(arrays, mesh, min_retention_ts=0)
    alive_paths = {
        arrays.paths[arrays.path_id[i]]
        for i in range(arrays.num_rows)
        if bool(result.alive[i])
    }
    host_alive, _ = _host_state(versioned)
    assert alive_paths == host_alive
    assert int(result.stats.num_files) == len(host_alive)
    replay = LogReplay()
    for v, actions in versioned:
        replay.append(v, actions)
    assert int(result.stats.total_size) == sum(
        f.size for f in replay.active_files.values()
    )


def test_replay_tombstone_retention():
    versioned = [
        (0, [AddFile(path="a", partition_values={}, size=1, modification_time=0, data_change=True)]),
        (1, [RemoveFile(path="a", deletion_timestamp=500, data_change=True)]),
        (2, [AddFile(path="b", partition_values={}, size=2, modification_time=0, data_change=True)]),
    ]
    arrays = state_export.actions_to_arrays(versioned)
    kept = replay_kernel.replay_alive_mask(arrays, min_retention_ts=100)
    assert int(kept.stats.num_tombstones) == 1
    expired = replay_kernel.replay_alive_mask(arrays, min_retention_ts=1000)
    assert int(expired.stats.num_tombstones) == 0


# -- pruning ----------------------------------------------------------------

SCHEMA = (
    StructType()
    .add("id", LongType())
    .add("price", DoubleType())
    .add("name", StringType())
    .add("part", StringType())
)


def _meta():
    return Metadata(schema_string=SCHEMA.to_json(), partition_columns=["part"])


def _file(path, part, id_min, id_max, price_min, price_max, nulls_name=0, num=100):
    stats = {
        "numRecords": num,
        "minValues": {"id": id_min, "price": price_min, "name": "a"},
        "maxValues": {"id": id_max, "price": price_max, "name": "z"},
        "nullCount": {"id": 0, "price": 0, "name": nulls_name},
    }
    return AddFile(
        path=path,
        partition_values={"part": part},
        size=1000,
        modification_time=0,
        data_change=True,
        stats=json.dumps(stats),
    )


FILES = [
    _file("f1", "us", 0, 99, 1.0, 9.9),
    _file("f2", "us", 100, 199, 10.0, 19.9),
    _file("f3", "eu", 200, 299, 20.0, 29.9, nulls_name=100),
    _file("f4", "eu", 300, 399, 30.0, 39.9),
]


class _FakeSnapshot:
    version = 7
    all_files = FILES
    metadata = _meta()


def _scan(sql):
    return pruning.files_for_scan(_FakeSnapshot(), [parse_predicate(sql)])


def test_partition_pruning():
    scan = _scan("part = 'us'")
    assert [f.path for f in scan.files] == ["f1", "f2"]
    assert scan.partition.files == 2


def test_stats_eq_pruning():
    assert [f.path for f in _scan("id = 150").files] == ["f2"]


def test_stats_range_pruning():
    assert [f.path for f in _scan("price >= 25.0").files] == ["f3", "f4"]
    assert [f.path for f in _scan("id < 100").files] == ["f1"]


def test_stats_combined_partition_and_data():
    scan = _scan("part = 'eu' AND id <= 250")
    assert [f.path for f in scan.files] == ["f3"]


def test_stats_in_pruning():
    assert [f.path for f in _scan("id IN (5, 305)").files] == ["f1", "f4"]


def test_stats_null_count_pruning():
    assert [f.path for f in _scan("name IS NULL").files] == ["f3"]
    # f3 is all-null for name -> IS NOT NULL prunes it
    assert [f.path for f in _scan("name IS NOT NULL").files] == ["f1", "f2", "f4"]


def test_missing_stats_keeps_file():
    no_stats = AddFile(path="f5", partition_values={"part": "eu"}, size=10,
                       modification_time=0, data_change=True)

    class S:
        version = 1
        all_files = FILES + [no_stats]
        metadata = _meta()

    scan = pruning.files_for_scan(S(), [parse_predicate("id = 150")])
    assert [f.path for f in scan.files] == ["f2", "f5"]


def test_unsupported_predicate_keeps_all():
    scan = _scan("name LIKE '%x%'")
    assert len(scan.files) == 4


def test_string_stats_pruned_on_host():
    # string min/max can't ship to device; host Arrow path must still prune
    scan = _scan("name > 'zz'")
    assert scan.files == []


def test_startswith_pruning_astral_chars():
    # regression: prefix upper bound must cover code points above U+FFFF
    f = _file("fx", "us", 0, 9, 1.0, 2.0)
    st = json.loads(f.stats)
    st["minValues"]["name"] = st["maxValues"]["name"] = "ap\U0001F600"
    f = AddFile(path="fx", partition_values={"part": "us"}, size=1000,
                modification_time=0, data_change=True, stats=json.dumps(st))

    class S:
        version = 1
        all_files = [f]
        metadata = _meta()

    from delta_tpu.expr import ir
    scan = pruning.files_for_scan(
        S(), [ir.StartsWith(ir.Column("name"), ir.Literal("ap"))]
    )
    assert [x.path for x in scan.files] == ["fx"]
    scan2 = pruning.files_for_scan(
        S(), [ir.StartsWith(ir.Column("name"), ir.Literal("zz"))]
    )
    assert scan2.files == []


def test_int64_literal_falls_back_to_host():
    # regression: id > 2**31 must not crash scan planning
    scan = _scan("id > 2147483648")
    assert scan.files == []
    scan2 = _scan("id >= 2147483647")
    assert scan2.files == []


def test_null_partition_value_pruned():
    # a NULL partition verdict is constant for the file: prune strictly
    f = AddFile(path="fnull", partition_values={"part": None}, size=1,
                modification_time=0, data_change=True)

    class S:
        version = 1
        all_files = FILES + [f]
        metadata = _meta()

    scan = pruning.files_for_scan(S(), [parse_predicate("part = 'us'")])
    assert [x.path for x in scan.files] == ["f1", "f2"]


def test_mixed_partition_data_or_predicate():
    # regression: partition col inside an OR with a data col must not crash
    scan = _scan("part = 'us' OR id > 350")
    assert [f.path for f in scan.files] == ["f1", "f2", "f4"]


def test_int64_stats_beyond_float53_kept():
    # regression: int stats beyond 2^53 must not be pruned on rounded bounds
    big = 2**53
    f = _file("fbig", "us", 0, 0, 1.0, 2.0)
    st = json.loads(f.stats)
    st["minValues"]["id"] = big
    st["maxValues"]["id"] = big + 1
    f = AddFile(path="fbig", partition_values={"part": "us"}, size=1,
                modification_time=0, data_change=True, stats=json.dumps(st))

    class S:
        version = 1
        all_files = [f]
        metadata = _meta()

    scan = pruning.files_for_scan(S(), [parse_predicate(f"id > {big}")])
    assert [x.path for x in scan.files] == ["fbig"]


def test_prefix_upper_bound_surrogates():
    from delta_tpu.ops.pruning import _prefix_upper_bound

    assert _prefix_upper_bound("퟿") == ""
    assert _prefix_upper_bound("a") == "b"
    assert _prefix_upper_bound("a\U0010FFFF") == "b"
    assert _prefix_upper_bound("\U0010FFFF") is None


def test_sharded_replay_1m_actions_matches_host():
    """Scale test: 1M actions over the 8-device mesh; sharded result must
    equal the host reference replay exactly, with no per-shard Python loops
    in the bucketing/unscatter path (they are one argsort + scatters now)."""
    import time

    import numpy as np

    from delta_tpu.ops import replay_kernel
    from delta_tpu.ops.state_export import ReplayArrays
    from delta_tpu.parallel.mesh import state_mesh

    n = 1_000_000
    n_paths = 120_000
    rng = np.random.RandomState(13)
    path_id = rng.randint(0, n_paths, n).astype(np.int32)
    version = np.sort(rng.randint(0, 50_000, n).astype(np.int64))
    pos = np.arange(n, dtype=np.int64) % (1 << 20)
    seq = (version << 31) | pos
    is_add = rng.rand(n) < 0.8
    size = rng.randint(1, 1 << 20, n).astype(np.int64)
    del_ts = np.where(is_add, 0, 1 + version).astype(np.int64)
    arrays = ReplayArrays(
        paths=[], path_id=path_id, seq=seq, is_add=is_add, size=size,
        deletion_timestamp=del_ts,
    )

    # host reference: last action per path wins
    last = {}
    order = np.argsort(seq, kind="stable")
    for i in order:
        last[path_id[i]] = i
    expected_alive = np.zeros(n, bool)
    for p, i in last.items():
        if is_add[i]:
            expected_alive[i] = True

    t0 = time.perf_counter()
    res = replay_kernel.replay_sharded(arrays, state_mesh(), min_retention_ts=0)
    sharded_s = time.perf_counter() - t0
    got = np.asarray(res.alive)
    assert (got == expected_alive).all()
    assert int(res.stats.num_files) == int(expected_alive.sum())
    # tombstones: winning removes with deletion_ts > retention
    assert int(res.stats.num_tombstones) == sum(
        1 for p, i in last.items() if not is_add[i] and del_ts[i] > 0
    )
    print(f"sharded 1M replay: {sharded_s*1000:.0f}ms")
