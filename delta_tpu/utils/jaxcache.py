"""Persistent XLA compilation cache.

Device kernels here compile against a handful of bucketed shapes
(`join_kernel._bucket`, `state_cache._next_pow2`), but on a tunneled TPU a
single cold compile costs tens of seconds — enough to wipe out a kernel's
win the first time a process touches a new shape. JAX's persistent
compilation cache amortizes that across processes: first contact per
machine compiles, everything after loads from disk.

Enabled lazily by the device-kernel modules; best-effort (an unwritable
dir or an unsupported backend silently degrades to in-memory caching).
``delta.tpu.xla.cacheDir`` overrides the location; empty string disables.
"""
from __future__ import annotations

import os
import threading

__all__ = ["ensure_compilation_cache"]

_done = False
_lock = threading.Lock()


def ensure_compilation_cache() -> None:
    global _done
    with _lock:
        if _done:
            return
        _done = True
        try:
            from delta_tpu.utils.config import conf

            cache_dir = conf.get("delta.tpu.xla.cacheDir")
            if cache_dir is None:  # None = auto; "" disables
                cache_dir = os.path.join(
                    os.path.expanduser("~"), ".cache", "delta_tpu", "xla")
            if not cache_dir:
                return
            os.makedirs(cache_dir, exist_ok=True)
            import jax

            jax.config.update("jax_compilation_cache_dir", str(cache_dir))
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception:
            pass  # in-memory compile cache only
