"""Error-factory contract: message text mirrors the reference's
DeltaErrors.scala for the situations this engine can hit, and the factories
are actually wired into the raise sites."""
import pyarrow as pa
import pytest

from delta_tpu import DeltaLog
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.utils import errors


def test_concurrent_message_composition():
    e = errors.concurrent_write_exception({"version": 7, "operation": "WRITE"})
    msg = str(e)
    assert "A concurrent transaction has written new data" in msg
    assert '"version": 7' in msg
    assert "concurrency-control.html" in msg
    assert e.conflicting_commit["version"] == 7


def test_protocol_changed_empty_dir_hint():
    plain = errors.protocol_changed_exception({"version": 3})
    assert "multiple writers are writing to an empty directory" not in str(plain)
    v0 = errors.protocol_changed_exception({"version": 0})
    assert "multiple writers are writing to an empty directory" in str(v0)


def test_conflict_checker_raises_factory_messages(tmp_path):
    # two txns race: loser's error carries the winning commit provenance
    path = str(tmp_path / "t")
    log = DeltaLog.for_table(path)
    WriteIntoDelta(log, "append", pa.table({"a": [1]})).run()
    txn = log.start_transaction()
    txn.read_whole_table()
    WriteIntoDelta(log, "overwrite", pa.table({"a": [9]})).run()  # winner
    from delta_tpu.commands import operations as ops
    from delta_tpu.protocol.actions import AddFile

    with pytest.raises(errors.DeltaConcurrentModificationException) as exc:
        txn.commit(
            [AddFile(path="x.parquet", size=1, modification_time=0,
                     data_change=True)],
            ops.Write("Append"),
        )
    assert "Conflicting commit" in str(exc.value)
    assert "concurrency-control.html" in str(exc.value)


def test_append_only_error_text(tmp_path):
    from delta_tpu.commands import alter
    from delta_tpu.commands.delete import DeleteCommand

    path = str(tmp_path / "ao")
    log = DeltaLog.for_table(path)
    WriteIntoDelta(log, "append", pa.table({"a": [1]})).run()
    alter.set_table_properties(log, {"delta.appendOnly": "true"})
    with pytest.raises(errors.DeltaUnsupportedOperationError,
                       match="configured to only allow appends"):
        DeleteCommand(log, None).run()


def test_not_null_and_check_constraint_texts(tmp_path):
    from delta_tpu.api.tables import DeltaTable
    from delta_tpu.commands import alter
    from delta_tpu.schema.types import LongType, StructType

    t = DeltaTable.create(
        str(tmp_path / "nn"),
        StructType().add("id", LongType(), nullable=False).add("v", LongType()),
    )
    with pytest.raises(errors.InvariantViolationError,
                       match="NOT NULL constraint violated for column: id"):
        t.write(pa.table({"id": pa.array([None], pa.int64()),
                          "v": pa.array([1], pa.int64())}))
    t.write({"id": [1], "v": [5]})
    alter.add_constraint(t.delta_log, "vpos", "v > 0")
    with pytest.raises(errors.InvariantViolationError,
                       match=r"CHECK constraint vpos \(.*\) violated by row"):
        t.write({"id": [2], "v": [-3]})


def test_vacuum_retention_error_text(tmp_path):
    from delta_tpu.commands.vacuum import VacuumCommand

    path = str(tmp_path / "v")
    log = DeltaLog.for_table(path)
    WriteIntoDelta(log, "append", pa.table({"a": [1]})).run()
    with pytest.raises(errors.DeltaIllegalArgumentError,
                       match="such a low retention period"):
        VacuumCommand(log, retention_hours=0.0).run()


def test_not_a_delta_table_text(tmp_path):
    from delta_tpu.api.tables import DeltaTable

    with pytest.raises(errors.DeltaAnalysisError, match="is not a Delta table"):
        DeltaTable.for_path(str(tmp_path / "nope"))


def test_unset_nonexistent_property_text(tmp_path):
    from delta_tpu.commands import alter

    path = str(tmp_path / "p")
    log = DeltaLog.for_table(path)
    WriteIntoDelta(log, "append", pa.table({"a": [1]})).run()
    with pytest.raises(errors.DeltaAnalysisError,
                       match="unset non-existent property"):
        alter.unset_table_properties(log, ["nope"])


def test_no_bare_fstring_analysis_errors():
    """Every analysis-error path goes through a named factory in
    utils/errors.py (the DeltaErrors.scala contract): no call site may raise
    a bare f-string DeltaAnalysisError/DeltaParseError (VERDICT r3 item 5)."""
    import pathlib
    import re

    root = pathlib.Path(__file__).resolve().parent.parent / "delta_tpu"
    pattern = re.compile(
        r"raise\s+Delta(Analysis|Parse)Error\(\s*f[\"']", re.S
    )
    offenders = []
    for path in sorted(root.rglob("*.py")):
        if path.name == "errors.py":
            continue  # the factories themselves compose messages
        for m in pattern.finditer(path.read_text()):
            line = path.read_text()[: m.start()].count("\n") + 1
            offenders.append(f"{path.relative_to(root)}:{line}")
    assert not offenders, (
        "bare f-string analysis errors (add a named factory to "
        f"utils/errors.py instead): {offenders}"
    )
