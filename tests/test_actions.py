"""Action JSON round-trips vs literal strings (≈ ``ActionSerializerSuite``)."""
import json

from delta_tpu.protocol.actions import (
    AddCDCFile,
    AddFile,
    CommitInfo,
    Metadata,
    Protocol,
    RemoveFile,
    SetTransaction,
    action_from_json,
)


def roundtrip(action):
    s = action.json()
    back = action_from_json(s)
    assert back == action, f"{back!r} != {action!r}"
    return s


def test_protocol():
    s = roundtrip(Protocol(1, 2))
    assert s == '{"protocol":{"minReaderVersion":1,"minWriterVersion":2}}'


def test_set_transaction():
    s = roundtrip(SetTransaction("app-1", 2, 3))
    assert s == '{"txn":{"appId":"app-1","version":2,"lastUpdated":3}}'
    s2 = roundtrip(SetTransaction("app-1", 2))
    assert s2 == '{"txn":{"appId":"app-1","version":2}}'


def test_add_file():
    a = AddFile("a/b.parquet", {"x": "1"}, 100, 1234, True, stats='{"numRecords":5}')
    s = roundtrip(a)
    d = json.loads(s)["add"]
    assert d["path"] == "a/b.parquet"
    assert d["partitionValues"] == {"x": "1"}
    assert d["size"] == 100
    assert d["modificationTime"] == 1234
    assert d["dataChange"] is True
    assert d["stats"] == '{"numRecords":5}'
    assert "tags" not in d


def test_add_file_null_partition_value():
    a = AddFile("f", {"x": None}, 1, 1, True)
    s = roundtrip(a)
    assert json.loads(s)["add"]["partitionValues"] == {"x": None}


def test_remove_file():
    r = AddFile("a", {}, 1, 1, True).remove(deletion_timestamp=99)
    s = roundtrip(r)
    d = json.loads(s)["remove"]
    assert d["deletionTimestamp"] == 99
    assert d["dataChange"] is True
    assert d["extendedFileMetadata"] is True
    assert d["size"] == 1


def test_remove_minimal_fields_parse():
    # Old writers emit remove without extended metadata.
    r = action_from_json('{"remove":{"path":"abc","deletionTimestamp":123}}')
    assert isinstance(r, RemoveFile)
    assert r.path == "abc"
    assert r.delete_timestamp == 123


def test_metadata_roundtrip():
    m = Metadata(
        id="test-id",
        schema_string='{"type":"struct","fields":[{"name":"id","type":"integer","nullable":true,"metadata":{}}]}',
        partition_columns=["id"],
        configuration={"delta.appendOnly": "true"},
        created_time=1000,
    )
    s = roundtrip(m)
    d = json.loads(s)["metaData"]
    assert d["format"] == {"provider": "parquet", "options": {}}
    assert d["partitionColumns"] == ["id"]
    assert m.schema.field_names == ["id"]
    assert m.partition_schema.field_names == ["id"]
    assert m.data_schema.field_names == []


def test_cdc_file():
    c = AddCDCFile("cdc-0", {"p": "1"}, 10)
    s = roundtrip(c)
    d = json.loads(s)["cdc"]
    assert d["dataChange"] is False


def test_commit_info():
    ci = CommitInfo(version=1, timestamp=123, operation="WRITE",
                    operation_parameters={"mode": "Append"}, is_blind_append=True)
    s = roundtrip(ci)
    d = json.loads(s)["commitInfo"]
    assert d["operation"] == "WRITE"
    assert d["isBlindAppend"] is True
    assert "engineInfo" not in d


def test_reference_golden_lines_parse():
    """Lines written by Delta 0.1.0 (reference golden table) parse exactly."""
    line = (
        '{"add":{"path":"part-00000-f4aeebd0.snappy.parquet","partitionValues":{},'
        '"size":525,"modificationTime":1501109075000,"dataChange":true}}'
    )
    a = action_from_json(line)
    assert isinstance(a, AddFile)
    assert a.size == 525


def test_unknown_action_ignored():
    assert action_from_json('{"someFutureAction":{"x":1}}') is None
    assert action_from_json("") is None
