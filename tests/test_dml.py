"""DML suites: DELETE / UPDATE / MERGE behavior.

Behavioral spec: `DeleteSuiteBase` / `UpdateSuiteBase` / `MergeIntoSuiteBase`
(SURVEY §4) — case structure, clause ordering, multi-match errors, metrics.
"""
import pyarrow as pa
import pytest

from delta_tpu import DeltaLog
from delta_tpu.commands.delete import DeleteCommand
from delta_tpu.commands.merge import MergeClause, MergeIntoCommand
from delta_tpu.commands.update import UpdateCommand
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.exec.scan import scan_to_table
from delta_tpu.utils.errors import DeltaAnalysisError, DeltaUnsupportedOperationError


def write(log, data, mode="append", **kw):
    return WriteIntoDelta(log, mode, data, **kw).run()


def rows(log, columns=None):
    t = scan_to_table(log.update(), columns=columns)
    return sorted(t.to_pylist(), key=lambda r: tuple(str(v) for v in r.values()))


def ids(log):
    return sorted(scan_to_table(log.update()).column("id").to_pylist())


# -- DELETE -----------------------------------------------------------------


def test_delete_whole_table(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2, 3]})
    cmd = DeleteCommand(log)
    cmd.run()
    assert ids(log) == []
    assert cmd.metrics["numRemovedFiles"] == 1
    assert cmd.metrics["numAddedFiles"] == 0


def test_delete_partition_only_metadata(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2, 3, 4], "c": ["a", "a", "b", "b"]},
          partition_columns=["c"])
    cmd = DeleteCommand(log, "c = 'a'")
    cmd.run()
    assert ids(log) == [3, 4]
    # metadata-only: no files rewritten, no rows read
    assert cmd.metrics["numAddedFiles"] == 0
    assert cmd.metrics["numDeletedRows"] == -1


def test_delete_data_predicate_rewrites(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2, 3, 4, 5]})
    cmd = DeleteCommand(log, "id > 3")
    cmd.run()
    assert ids(log) == [1, 2, 3]
    assert cmd.metrics["numDeletedRows"] == 2
    assert cmd.metrics["numRemovedFiles"] == 1
    assert cmd.metrics["numAddedFiles"] == 1


def test_delete_no_matches_no_op(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2]})
    v = log.update().version
    cmd = DeleteCommand(log, "id > 100")
    cmd.run()
    assert ids(log) == [1, 2]
    assert cmd.metrics["numRemovedFiles"] == 0
    # commit still happens (a no-op delta), matching reference behavior
    assert log.update().version == v + 1


def test_delete_whole_file_no_rewrite(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2]})
    write(log, {"id": [100, 200]})
    cmd = DeleteCommand(log, "id >= 100")
    cmd.run()
    assert ids(log) == [1, 2]
    # the 100/200 file is dropped whole; nothing rewritten
    assert cmd.metrics["numRemovedFiles"] == 1
    assert cmd.metrics["numAddedFiles"] == 0


def test_delete_null_predicate_rows_kept(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, None, 3]})
    DeleteCommand(log, "id > 0").run()
    # NULL predicate rows are NOT deleted (SQL semantics)
    assert scan_to_table(log.update()).column("id").to_pylist() == [None]


# -- UPDATE -----------------------------------------------------------------


def test_update_unconditional(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2], "v": [10, 20]})
    cmd = UpdateCommand(log, {"v": "v + 1"})
    cmd.run()
    assert rows(log) == [{"id": 1, "v": 11}, {"id": 2, "v": 21}]
    assert cmd.metrics["numUpdatedRows"] == 2


def test_update_with_condition(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2, 3], "v": [10, 20, 30]})
    UpdateCommand(log, {"v": "0"}, condition="id = 2").run()
    assert rows(log) == [{"id": 1, "v": 10}, {"id": 2, "v": 0}, {"id": 3, "v": 30}]


def test_update_multiple_columns_and_expressions(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2], "v": [10, 20], "name": ["a", "b"]})
    UpdateCommand(log, {"v": "v * 2", "name": "upper(name)"}, condition="id = 1").run()
    assert rows(log) == [
        {"id": 1, "v": 20, "name": "A"},
        {"id": 2, "v": 20, "name": "b"},
    ]


def test_update_unknown_column_fails(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1]})
    with pytest.raises(DeltaAnalysisError):
        UpdateCommand(log, {"nope": "1"}).run()


def test_update_partition_column_moves_rows(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2], "c": ["a", "a"]}, partition_columns=["c"])
    UpdateCommand(log, {"c": "'b'"}, condition="id = 2").run()
    snap = log.update()
    t = scan_to_table(snap, ["c = 'b'"])
    assert t.column("id").to_pylist() == [2]


# -- MERGE ------------------------------------------------------------------


def _merge(log, source, cond, matched=(), not_matched=(), **kw):
    cmd = MergeIntoCommand(log, source, cond, matched, not_matched, **kw)
    cmd.run()
    return cmd


def test_merge_quickstart_upsert(tmp_table):
    # quickstart: upsert ids 0..19 into table of 0..4
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": list(range(5))})
    cmd = _merge(
        log,
        {"id": list(range(20))},
        "oldData.id = newData.id",
        matched=[MergeClause("update", assignments={"id": "newData.id"})],
        not_matched=[MergeClause("insert", assignments={"id": "newData.id"})],
        source_alias="newData",
        target_alias="oldData",
    )
    assert ids(log) == list(range(20))
    assert cmd.metrics["numTargetRowsUpdated"] == 5
    assert cmd.metrics["numTargetRowsInserted"] == 15


def test_merge_update_all_insert_all(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2], "v": [10, 20]})
    _merge(
        log,
        {"id": [2, 3], "v": [99, 30]},
        "t.id = s.id",
        matched=[MergeClause("update")],  # updateAll
        not_matched=[MergeClause("insert")],  # insertAll
        source_alias="s",
        target_alias="t",
    )
    assert rows(log) == [
        {"id": 1, "v": 10},
        {"id": 2, "v": 99},
        {"id": 3, "v": 30},
    ]


def test_merge_matched_delete(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2, 3]})
    cmd = _merge(
        log,
        {"id": [2]},
        "t.id = s.id",
        matched=[MergeClause("delete")],
        source_alias="s",
        target_alias="t",
    )
    assert ids(log) == [1, 3]
    assert cmd.metrics["numTargetRowsDeleted"] == 1


def test_merge_clause_conditions_ordered(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2], "v": [5, 50]})
    _merge(
        log,
        {"id": [1, 2], "nv": [100, 100]},
        "t.id = s.id",
        matched=[
            MergeClause("update", condition="t.v < 10", assignments={"v": "s.nv"}),
            MergeClause("delete"),
        ],
        source_alias="s",
        target_alias="t",
    )
    # id=1 hits the first clause (v<10 -> update); id=2 falls through to delete
    assert rows(log) == [{"id": 1, "v": 100}]


def test_merge_conditional_insert(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1], "v": [1]})
    _merge(
        log,
        {"id": [2, 3], "v": [20, 30]},
        "t.id = s.id",
        not_matched=[
            MergeClause("insert", condition="s.v > 25",
                        assignments={"id": "s.id", "v": "s.v"})
        ],
        source_alias="s",
        target_alias="t",
    )
    assert rows(log) == [{"id": 1, "v": 1}, {"id": 3, "v": 30}]


def test_merge_insert_only_no_rewrites(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2]})
    cmd = _merge(
        log,
        {"id": [2, 5]},
        "t.id = s.id",
        not_matched=[MergeClause("insert")],
        source_alias="s",
        target_alias="t",
    )
    assert ids(log) == [1, 2, 5]
    # insert-only fast path: no target files removed
    assert cmd.metrics["numTargetFilesRemoved"] == 0


def test_merge_multi_match_errors(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1]})
    with pytest.raises(DeltaUnsupportedOperationError):
        _merge(
            log,
            {"id": [1, 1]},  # two source rows match target row 1
            "t.id = s.id",
            matched=[MergeClause("update")],
            source_alias="s",
            target_alias="t",
        )


def test_merge_multi_match_ok_for_unconditional_delete(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2]})
    _merge(
        log,
        {"id": [1, 1]},
        "t.id = s.id",
        matched=[MergeClause("delete")],
        source_alias="s",
        target_alias="t",
    )
    assert ids(log) == [2]


def test_merge_untouched_files_not_rewritten(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2]})
    write(log, {"id": [100, 200]})
    cmd = _merge(
        log,
        {"id": [1]},
        "t.id = s.id",
        matched=[MergeClause("delete")],
        source_alias="s",
        target_alias="t",
    )
    assert ids(log) == [2, 100, 200]
    assert cmd.metrics["numTargetFilesRemoved"] == 1


def test_merge_copied_rows_preserved(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2, 3], "v": [1, 2, 3]})
    cmd = _merge(
        log,
        {"id": [2], "v": [99]},
        "t.id = s.id",
        matched=[MergeClause("update")],
        source_alias="s",
        target_alias="t",
    )
    assert rows(log) == [{"id": 1, "v": 1}, {"id": 2, "v": 99}, {"id": 3, "v": 3}]
    assert cmd.metrics["numTargetRowsCopied"] == 2


def test_merge_non_equi_condition(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 5]})
    _merge(
        log,
        {"lo": [4], "hi": [6], "nid": [50]},
        "t.id >= s.lo AND t.id <= s.hi",
        matched=[MergeClause("update", assignments={"id": "s.nid"})],
        source_alias="s",
        target_alias="t",
    )
    assert ids(log) == [1, 50]


def test_merge_partitioned_target(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2, 3, 4], "c": ["a", "a", "b", "b"]},
          partition_columns=["c"])
    _merge(
        log,
        {"id": [2, 9], "c": ["a", "b"]},
        "t.id = s.id",
        matched=[MergeClause("delete")],
        not_matched=[MergeClause("insert")],
        source_alias="s",
        target_alias="t",
    )
    assert ids(log) == [1, 3, 4, 9]
    t = scan_to_table(log.update(), ["c = 'b'"])
    assert sorted(t.column("id").to_pylist()) == [3, 4, 9]


def test_merge_only_last_clause_unconditional(tmp_table):
    with pytest.raises(DeltaAnalysisError):
        MergeIntoCommand(
            None,
            {"id": [1]},
            "t.id = s.id",
            matched_clauses=[
                MergeClause("update"),  # unconditional, not last
                MergeClause("delete", condition="t.id = 1"),
            ],
        )


# -- OPTIMIZE ---------------------------------------------------------------


def test_optimize_compacts_small_files(tmp_table):
    from delta_tpu.commands.optimize import OptimizeCommand

    log = DeltaLog.for_table(tmp_table)
    for i in range(5):
        write(log, {"id": [i]})
    assert len(log.update().all_files) == 5
    cmd = OptimizeCommand(log)
    cmd.run()
    snap = log.update()
    assert len(snap.all_files) == 1
    assert ids(log) == [0, 1, 2, 3, 4]
    assert cmd.metrics["numRemovedFiles"] == 5
    # rearrange-only: no dataChange
    _, actions = list(log.get_changes(snap.version))[0]
    from delta_tpu.protocol.actions import AddFile, RemoveFile
    for a in actions:
        if isinstance(a, (AddFile, RemoveFile)):
            assert a.data_change is False


def test_optimize_partition_scoped(tmp_table):
    from delta_tpu.commands.optimize import OptimizeCommand

    log = DeltaLog.for_table(tmp_table)
    for i in range(3):
        write(log, {"id": [i, i + 10], "c": ["a", "b"]}, partition_columns=["c"])
    OptimizeCommand(log, predicate="c = 'a'").run()
    snap = log.update()
    a_files = [f for f in snap.all_files if f.partition_values.get("c") == "a"]
    b_files = [f for f in snap.all_files if f.partition_values.get("c") == "b"]
    assert len(a_files) == 1
    assert len(b_files) == 3


def test_zorder_improves_skipping(tmp_table):
    from delta_tpu.commands.optimize import OptimizeCommand
    from delta_tpu.exec.scan import scan_files
    import random

    rng = random.Random(0)
    log = DeltaLog.for_table(tmp_table)
    # two uncorrelated dims: without clustering every file spans both ranges
    xs, ys = [], []
    for _ in range(4000):
        xs.append(rng.randrange(100))
        ys.append(rng.randrange(100))
    write(log, {"x": xs, "y": ys})
    cmd = OptimizeCommand(log, z_order_by=["x", "y"], target_rows=500)
    cmd.run()
    snap = log.update()
    assert len(snap.all_files) == 8
    # point query on both dims must hit a small fraction of the 8 files
    scan = scan_files(snap, ["x = 7 AND y = 93"])
    assert scan.scanned.files <= 2, scan.scanned.files
    t = scan_to_table(snap, ["x = 7 AND y = 93"])
    expected = sum(1 for x, y in zip(xs, ys) if x == 7 and y == 93)
    assert t.num_rows == expected


def test_zorder_rejects_partition_column(tmp_table):
    from delta_tpu.commands.optimize import OptimizeCommand

    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1], "c": ["a"]}, partition_columns=["c"])
    with pytest.raises(DeltaAnalysisError):
        OptimizeCommand(log, z_order_by=["c"]).run()


# -- review regressions -----------------------------------------------------


def test_merge_unknown_qualifier_raises(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2, 3]})
    with pytest.raises(DeltaAnalysisError):
        # 't'/'s' qualifiers with no aliases must not silently resolve
        MergeIntoCommand(
            log, {"id": [2]}, "t.id = s.id",
            [MergeClause("delete")],
        ).run()
    assert ids(log) == [1, 2, 3]


def test_merge_join_key_widening(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [4294967297, 7]})  # int64 beyond int32
    src = pa.table({"id": pa.array([1], pa.int32())})
    _merge(
        log, src, "t.id = s.id",
        matched=[MergeClause("delete")],
        source_alias="s", target_alias="t",
    )
    # int64 key must not wrap into int32 and fabricate a match
    assert ids(log) == [7, 4294967297]


def test_merge_insert_only_duplicate_source_ok(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2]})
    _merge(
        log, {"id": [1, 1, 5]}, "t.id = s.id",
        not_matched=[MergeClause("insert")],
        source_alias="s", target_alias="t",
    )
    assert ids(log) == [1, 2, 5]


def test_merge_copied_counts_unclaimed_pairs(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2], "v": [5, 50]})
    cmd = _merge(
        log, {"id": [1, 2], "nv": [9, 9]}, "t.id = s.id",
        matched=[MergeClause("update", condition="t.v < 10",
                             assignments={"v": "s.nv"})],
        source_alias="s", target_alias="t",
    )
    # id=2 matched but unclaimed (v=50): copied, and counted as copied
    assert cmd.metrics["numTargetRowsCopied"] == 1
    assert rows(log) == [{"id": 1, "v": 9}, {"id": 2, "v": 50}]


def test_zorder_with_nulls(tmp_table):
    from delta_tpu.commands.optimize import OptimizeCommand

    log = DeltaLog.for_table(tmp_table)
    write(log, {"x": [3, None, 1, 2], "y": [1, 2, None, 4]})
    OptimizeCommand(log, z_order_by=["x", "y"], target_rows=2).run()
    t = scan_to_table(log.update())
    assert t.num_rows == 4


def test_optimize_null_partition_values(tmp_table):
    from delta_tpu.commands.optimize import OptimizeCommand

    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1], "p": [None]}, partition_columns=["p"])
    write(log, {"id": [2], "p": [None]})
    write(log, {"id": [3], "p": ["x"]})
    write(log, {"id": [4], "p": ["x"]})
    OptimizeCommand(log).run()
    snap = log.update()
    assert len(snap.all_files) == 2
    assert ids(log) == [1, 2, 3, 4]


def test_zorder_all_null_column(tmp_table):
    from delta_tpu.commands.optimize import OptimizeCommand

    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2], "s": pa.array([None, None], pa.string())})
    OptimizeCommand(log, z_order_by=["s", "id"], target_rows=1).run()
    assert ids(log) == [1, 2]


def test_merge_int64_float_keys_no_collapse(tmp_table):
    big = 2**53
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [big, big + 1]})
    src = pa.table({"id": pa.array([float(big)], pa.float64())})
    _merge(
        log, src, "t.id = s.id",
        matched=[MergeClause("delete")],
        source_alias="s", target_alias="t",
    )
    # only the exactly-equal key may match; big+1 must survive
    assert ids(log) == [big + 1]


# -- device join path parity ------------------------------------------------


def _run_merge_both_paths(tmp_path, name, target_data, source, cond, matched,
                          not_matched, **kw):
    """Run the same MERGE with the device kernel on and off; return the two
    (final rows, metrics) results plus the device command for inspection."""
    from delta_tpu.utils.config import conf

    results = []
    cmds = []
    for device in (True, False):
        path = str(tmp_path / f"{name}_{device}")
        log = DeltaLog.for_table(path)
        write(log, target_data)
        with conf.set_temporarily(**{
            "delta.tpu.merge.devicePath.enabled": device,
            # force: routing economics are exercised separately; these tests
            # pin the executor to check kernel/host parity
            "delta.tpu.merge.devicePath.mode": "force" if device else "off",
        }):
            cmd = MergeIntoCommand(log, source, cond, matched, not_matched, **kw)
            cmd.run()
        cmds.append(cmd)
        results.append((rows(log), {k: v for k, v in cmd.metrics.items()
                                    if not k.endswith("Ms")}))
    assert cmds[0]._device_join is not None, "device path did not run"
    assert cmds[1]._device_join is None, "host path ran the device kernel"
    return results


def test_merge_device_matches_host(tmp_path):
    import numpy as np

    rng = np.random.RandomState(42)
    n_t, n_s = 500, 200
    # duplicate TARGET keys are legal (several target rows match one source
    # row) and exercise the device/host structural difference; duplicate
    # SOURCE keys would be a multi-match error, so draw those unique
    target = {
        "id": rng.randint(0, 400, n_t).tolist(),
        "v": rng.randint(0, 1000, n_t).tolist(),
    }
    source = pa.table({
        "id": rng.choice(np.arange(0, 700), size=n_s, replace=False).tolist(),
        "v": rng.randint(1000, 2000, n_s).tolist(),
    })
    (dev_rows, dev_m), (host_rows, host_m) = _run_merge_both_paths(
        tmp_path, "parity", target, source, "t.id = s.id",
        matched=[MergeClause("update", assignments=None)],
        not_matched=[MergeClause("insert", assignments=None)],
        source_alias="s", target_alias="t",
    )
    assert dev_rows == host_rows
    assert dev_m == host_m


def test_merge_device_null_keys_never_match(tmp_path):
    source = pa.table({"id": pa.array([2, None, 5], pa.int64()),
                       "v": pa.array([200, 999, 500], pa.int64())})
    (dev_rows, dev_m), (host_rows, host_m) = _run_merge_both_paths(
        tmp_path, "nulls", {"id": [1, 2, 3], "v": [10, 20, 30]}, source,
        "t.id = s.id",
        matched=[MergeClause("update", assignments=None)],
        not_matched=[MergeClause("insert", assignments=None)],
        source_alias="s", target_alias="t",
    )
    assert dev_rows == host_rows
    assert dev_m == host_m
    # NULL source key inserts (not-matched), never updates
    assert dev_m["numTargetRowsInserted"] == 2
    assert dev_m["numTargetRowsUpdated"] == 1


def test_merge_device_multi_match_errors(tmp_path):
    from delta_tpu.utils.config import conf

    path = str(tmp_path / "mm")
    log = DeltaLog.for_table(path)
    write(log, {"id": [1, 2], "v": [10, 20]})
    src = pa.table({"id": [1, 1], "v": [100, 101]})
    with conf.set_temporarily(**{"delta.tpu.merge.devicePath.enabled": True,
                                 "delta.tpu.merge.devicePath.mode": "force"}):
        cmd = MergeIntoCommand(
            log, src, "t.id = s.id",
            [MergeClause("update", assignments=None)], [],
            source_alias="s", target_alias="t",
        )
        with pytest.raises(DeltaUnsupportedOperationError):
            cmd.run()
        assert cmd._device_join is not None


def test_merge_device_insert_only_fast_path(tmp_path):
    # insert-only: device flags drive the anti-join; target data columns are
    # not needed (only the key column is read)
    (dev_rows, dev_m), (host_rows, host_m) = _run_merge_both_paths(
        tmp_path, "io", {"id": [1, 2, 3], "v": [10, 20, 30]},
        pa.table({"id": [3, 4], "v": [300, 400]}),
        "t.id = s.id", [],
        [MergeClause("insert", assignments=None)],
        source_alias="s", target_alias="t",
    )
    assert dev_rows == host_rows
    assert dev_m["numTargetRowsInserted"] == 1
    assert dev_m == host_m


def test_merge_device_string_key_falls_back_to_host(tmp_path):
    from delta_tpu.utils.config import conf

    path = str(tmp_path / "str")
    log = DeltaLog.for_table(path)
    write(log, {"id": ["a", "b"], "v": [1, 2]})
    with conf.set_temporarily(**{"delta.tpu.merge.devicePath.enabled": True,
                                 "delta.tpu.merge.devicePath.mode": "force"}):
        cmd = MergeIntoCommand(
            log, pa.table({"id": ["b", "c"], "v": [20, 30]}), "t.id = s.id",
            [MergeClause("update", assignments=None)],
            [MergeClause("insert", assignments=None)],
            source_alias="s", target_alias="t",
        )
        cmd.run()
    assert cmd._device_join is None  # string keys -> Arrow hash join
    assert rows(log) == [{"id": "a", "v": 1}, {"id": "b", "v": 20},
                         {"id": "c", "v": 30}]


def test_merge_device_multimatch_delete_metrics_parity(tmp_path):
    # single unconditional DELETE legally multi-matches; numTargetRowsDeleted
    # must count distinct target rows on both paths
    (dev_rows, dev_m), (host_rows, host_m) = _run_merge_both_paths(
        tmp_path, "mmdel", {"id": [1, 2], "v": [10, 20]},
        pa.table({"id": [1, 1], "v": [0, 0]}),
        "t.id = s.id", [MergeClause("delete")], [],
        source_alias="s", target_alias="t",
    )
    assert dev_rows == host_rows == [{"id": 2, "v": 20}]
    assert dev_m == host_m
    assert dev_m["numTargetRowsDeleted"] == 1


def test_merge_device_composite_key_parity(tmp_path):
    """Two-column equi-key: the device kernel packs both int32-fitting
    components into one int64 lane (hi<<32 | lo) — results must match the
    host hash join exactly, including negative components."""
    import numpy as np

    rng = np.random.RandomState(7)
    n_t = 300
    k1 = rng.randint(-50, 50, n_t)
    k2 = rng.randint(-20, 40, n_t)  # negative LO lane: the & 0xFFFFFFFF mask
    # is what stops sign-extension from clobbering the packed hi bits
    target = {
        "a": k1.tolist(),
        "b": k2.tolist(),
        "v": rng.randint(0, 1000, n_t).tolist(),
    }
    # source: unique composite keys, half overlapping the target domain
    pairs = {(int(a), int(b)) for a, b in zip(k1[:60], k2[:60])}
    pairs |= {(999 + i, 999 + i) for i in range(40)}
    src_a, src_b = zip(*sorted(pairs))
    source = pa.table({
        "a": pa.array(src_a, pa.int64()),
        "b": pa.array(src_b, pa.int64()),
        "v": pa.array([5000 + i for i in range(len(src_a))], pa.int64()),
    })
    (dev_rows, dev_m), (host_rows, host_m) = _run_merge_both_paths(
        tmp_path, "composite", target, source,
        "t.a = s.a AND t.b = s.b",
        matched=[MergeClause("update", assignments=None)],
        not_matched=[MergeClause("insert", assignments=None)],
        source_alias="s", target_alias="t",
    )
    assert dev_rows == host_rows
    assert dev_m == host_m
    assert dev_m["numTargetRowsInserted"] >= 40
