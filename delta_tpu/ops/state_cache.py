"""Device-resident snapshot state: table metadata cached in HBM.

The reference caches reconstructed state as a Spark-memory Dataset
(`util/StateCache.scala:34-110` backing `Snapshot.scala:88-111`), so repeat
queries replay nothing. The TPU-native equivalent keeps the *scan-planning
lanes* of the reconciled state — per-file min/max/nullCount stats, sizes,
aliveness — resident in HBM, keyed by table, and updates them incrementally
as the log tails forward: each new commit appends a handful of rows
device-side (one small upload + one scatter/slice kernel), so steady-state
queries pay **zero bulk upload**.

Why this is the piece that makes the chip win: on any link (PCIe or
tunneled), re-uploading O(files) state per query prices the device out of
interactive planning; from residency, a *batch* of N predicates over F files
and C stat columns is one dispatch reading N·F·C lanes from HBM (~800 GB/s)
against a host evaluator bound by DRAM (~10 GB/s single-core), and one
small packed block-bitmap download finished exactly on the host mirrors
(coarse-fine; see ``_plan_device``).

Precision: stats lanes are stored as float32 with **conservative rounding**
— min lanes round toward -inf, max lanes toward +inf, and query bounds round
outward the same way (`_f32_down`/`_f32_up`) — so a float32 verdict can only
*keep* extra files, never drop a matching one. NaN = missing stat = keep.
The skipping rewrite only ever tests ``min.c`` against upper bounds and
``max.c`` against lower bounds (`ops/pruning.skipping_predicate`), which is
what makes one rounding direction per lane sufficient.
"""
from __future__ import annotations

import functools
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from delta_tpu.expr import ir
from delta_tpu.utils.config import conf

__all__ = [
    "ResidentState", "DeviceStateCache", "PlanResult", "extract_ranges",
    "RangeSet",
]


def _f32_down(x: np.ndarray) -> np.ndarray:
    """float64 → float32 rounded toward -inf (result <= x). NaN passes."""
    with np.errstate(invalid="ignore", over="ignore"):
        f = x.astype(np.float32)
        bump = f.astype(np.float64) > x
    if bump.any():
        f = f.copy()
        f[bump] = np.nextafter(f[bump], np.float32(-np.inf))
    return f


def _f32_up(x: np.ndarray) -> np.ndarray:
    """float64 → float32 rounded toward +inf (result >= x). NaN passes."""
    with np.errstate(invalid="ignore", over="ignore"):
        f = x.astype(np.float32)
        bump = f.astype(np.float64) < x
    if bump.any():
        f = f.copy()
        f[bump] = np.nextafter(f[bump], np.float32(np.inf))
    return f


def _next_pow2(n: int, floor: int = 1024) -> int:
    p = floor
    while p < n:
        p *= 2
    return p


# -- range extraction from skipping predicates ------------------------------


@dataclass
class RangeSet:
    """One query as per-column bounds: keep file iff for every column c,
    ``max.c >= lo[c] AND min.c <= hi[c]`` (NaN bound = unconstrained).
    ``verdict`` short-circuits structural cases: 'empty' (matches nothing),
    'all' (prunes nothing)."""

    lo: np.ndarray  # float64, len C, NaN = -inf
    hi: np.ndarray  # float64, len C, NaN = +inf
    verdict: Optional[str] = None  # None | 'empty' | 'all'
    # True when the lowering lost nothing: no strict comparison was relaxed
    # to non-strict, so the range verdict EQUALS the exact evaluator's
    exact: bool = True


def extract_ranges(pred: ir.Expression, columns: Sequence[str]) -> Optional[RangeSet]:
    """Lower a *rewritten* skipping predicate (over ``min.c``/``max.c`` lanes)
    to per-column range bounds, or None when the shape doesn't fit (ORs,
    null-count tests, unknown columns → caller routes that query to the
    generic path). Strict comparisons are relaxed to non-strict — pruning may
    keep a boundary file it could have dropped, never the reverse."""
    col_ix = {c: i for i, c in enumerate(columns)}
    lo = np.full(len(columns), np.nan)
    hi = np.full(len(columns), np.nan)
    empty = False
    exact = True

    def walk(e: ir.Expression) -> bool:
        nonlocal empty, exact
        t = type(e)
        if t is ir.And:
            return walk(e.left) and walk(e.right)
        if t is ir.Literal:
            if e.value is None or e.value is True:
                return True  # unknown/true conjunct prunes nothing
            if e.value is False:
                empty = True
                return True
            return False
        if t in (ir.Le, ir.Lt, ir.Ge, ir.Gt):
            l, r = e.left, e.right
            if not (isinstance(l, ir.Column) and isinstance(r, ir.Literal)):
                return False
            if not isinstance(r.value, (int, float)) or isinstance(r.value, bool):
                return False
            v = float(r.value)
            name = l.name
            if name.startswith("min.") and t in (ir.Le, ir.Lt):
                i = col_ix.get(name[4:])
                if i is None:
                    return False
                if t is ir.Lt:
                    exact = False
                hi[i] = v if np.isnan(hi[i]) else min(hi[i], v)
                return True
            if name.startswith("max.") and t in (ir.Ge, ir.Gt):
                i = col_ix.get(name[4:])
                if i is None:
                    return False
                if t is ir.Gt:
                    exact = False
                lo[i] = v if np.isnan(lo[i]) else max(lo[i], v)
                return True
            return False
        return False

    if not walk(pred):
        return None
    if empty:
        return RangeSet(lo, hi, verdict="empty", exact=exact)
    if np.isnan(lo).all() and np.isnan(hi).all():
        return RangeSet(lo, hi, verdict="all", exact=exact)
    return RangeSet(lo, hi, exact=exact)


# -- the resident entry ------------------------------------------------------


@dataclass
class PlanResult:
    """One query's plan from the resident state. ``rows`` are row indices
    into the entry's layout (map to paths via ``ResidentState.paths``);
    ``overflow`` means more than K files survived and the caller must
    fall back for this query (counts stay exact)."""

    count: int
    rows: np.ndarray
    overflow: bool = False
    via: str = "host-resident"  # 'device' | 'host-resident' | 'verdict'


class ResidentState:
    """One table's scan-planning lanes in HBM + exact host mirrors.

    Rows are append-only (a re-added path gets a fresh row; the old one's
    alive bit drops); device arrays are padded to a power-of-two capacity so
    tail appends hit a handful of compiled kernel shapes.
    """

    def __init__(self, log_path: str, metadata_id: str, version: int,
                 columns: List[str], paths: List[str],
                 lanes: Dict[str, np.ndarray]):
        self.log_path = log_path
        self.metadata_id = metadata_id
        self.version = version
        self.columns = columns
        self.paths = list(paths)
        self.path_to_row: Dict[str, int] = {p: i for i, p in enumerate(paths)}
        n = len(paths)
        self.num_rows = n
        self.capacity = _next_pow2(max(n, 1))
        # exact host mirrors (float64 bounds; the device carries f32)
        self.h_alive = np.ones(n, bool)
        self.h_lo = lanes["min"]  # (C, n) float64
        self.h_hi = lanes["max"]
        self.h_size = lanes["size"]  # (n,) int64
        self._dead = 0
        self._dev = None  # lazily-built device arrays
        self._lock = threading.RLock()
        self.last_used = 0.0

    # -- device residency -------------------------------------------------

    def _pad2(self, a: np.ndarray, fill) -> np.ndarray:
        out = np.full((a.shape[0], self.capacity), fill, np.float32)
        out[:, : a.shape[1]] = a
        return out

    def _build_device(self) -> None:
        import jax.numpy as jnp

        mins = self._pad2(_f32_down(self.h_lo), np.nan)
        maxs = self._pad2(_f32_up(self.h_hi), np.nan)
        alive = np.zeros(self.capacity, bool)
        alive[: self.num_rows] = self.h_alive[: self.num_rows]
        self._dev = {
            "mins": jnp.asarray(mins),
            "maxs": jnp.asarray(maxs),
            "alive": jnp.asarray(alive),
        }

    @property
    def device_bytes(self) -> int:
        c = len(self.columns)
        return self.capacity * (2 * c * 4 + 1)

    def ensure_resident(self) -> None:
        with self._lock:
            if self._dev is None:
                self._build_device()

    @property
    def is_resident(self) -> bool:
        return self._dev is not None

    def drop_device(self) -> None:
        with self._lock:
            self._dev = None

    # -- incremental tail apply ------------------------------------------

    def apply_tail(self, version: int, removed_paths: Sequence[str],
                   added: Tuple[List[str], np.ndarray, np.ndarray, np.ndarray]) -> bool:
        """Advance to ``version``: drop removed paths, append added rows
        (paths, lo(C,k), hi(C,k), size(k)). Returns False when the entry
        must be rebuilt instead (capacity overflow / too much garbage)."""
        add_paths, add_lo, add_hi, add_size = added
        k = len(add_paths)
        with self._lock:
            # Pass 1: count dead rows WITHOUT mutating the mirrors, so the
            # rebuild-needed verdict below can bail with the entry still
            # exactly at its old version (a concurrent plan_ranges holding
            # expected_version=old must keep seeing consistent state).
            dead_rows: List[int] = []
            seen_dead = set()
            for p in removed_paths:
                r = self.path_to_row.get(p)
                if r is not None and self.h_alive[r] and r not in seen_dead:
                    dead_rows.append(r)
                    seen_dead.add(r)
            for p in add_paths:
                # re-add supersedes the old row's stats
                r = self.path_to_row.get(p)
                if r is not None and self.h_alive[r] and r not in seen_dead:
                    dead_rows.append(r)
                    seen_dead.add(r)
            start = self.num_rows
            if (start + k > self.capacity
                    or self._dead + len(dead_rows) > max(1024, self.num_rows // 2)):
                return False
            # Pass 2: committed — kill exactly the rows Pass 1 counted
            # (re-added paths keep their mapping until the append below
            # overwrites it; removed paths drop theirs)
            for p in removed_paths:
                self.path_to_row.pop(p, None)
            self.h_alive[dead_rows] = False
            self._dead += len(dead_rows)
            if k:
                self.h_alive = np.concatenate([self.h_alive, np.ones(k, bool)])
                self.h_lo = np.concatenate([self.h_lo, add_lo], axis=1)
                self.h_hi = np.concatenate([self.h_hi, add_hi], axis=1)
                self.h_size = np.concatenate([self.h_size, add_size])
                for i, p in enumerate(add_paths):
                    self.paths.append(p)
                    self.path_to_row[p] = start + i
                self.num_rows = start + k
            if self._dev is not None:
                self._apply_tail_device(dead_rows, start, k, add_lo, add_hi)
            self.version = version
            return True

    def _apply_tail_device(self, dead_rows, start, k, add_lo, add_hi) -> None:
        """One small upload + one jitted scatter/slice update in HBM.

        Shapes are bucketed (pow2 pads; out-of-range scatter indices use
        XLA drop semantics) so a steady commit stream reuses a handful of
        compiled executables."""
        import jax.numpy as jnp

        dev = self._dev
        cap = self.capacity
        d = _next_pow2(max(len(dead_rows), 1), floor=8)
        dead = np.full(d, cap, np.int32)  # cap = out of bounds -> dropped
        dead[: len(dead_rows)] = dead_rows
        a = _next_pow2(max(k, 1), floor=8)
        rows = np.full(a, cap, np.int32)
        rows[:k] = np.arange(start, start + k, dtype=np.int32)
        lo32 = np.full((self.h_lo.shape[0], a), np.nan, np.float32)
        hi32 = np.full((self.h_hi.shape[0], a), np.nan, np.float32)
        lo32[:, :k] = _f32_down(add_lo)
        hi32[:, :k] = _f32_up(add_hi)
        dev["alive"] = _scatter_bool(dev["alive"], jnp.asarray(dead), False)
        dev["alive"] = _scatter_bool(dev["alive"], jnp.asarray(rows), True)
        dev["mins"] = _scatter_cols(dev["mins"], jnp.asarray(rows), jnp.asarray(lo32))
        dev["maxs"] = _scatter_cols(dev["maxs"], jnp.asarray(rows), jnp.asarray(hi32))

    # -- serving ----------------------------------------------------------

    def plan_ranges(self, ranges: Sequence[RangeSet], k: int = 256,
                    use_device: Optional[bool] = None,
                    expected_version: Optional[int] = None) -> Optional[List[PlanResult]]:
        """Evaluate a batch of range queries against the resident lanes:
        one dispatch, one packed-bitmap download. Structural verdicts
        short-circuit; device/host routing follows the link cost model unless
        pinned (each PlanResult records the route in ``via``).

        Runs under the entry lock so a concurrent ``apply_tail`` cannot
        mutate the mirrors mid-plan; ``expected_version`` guards the other
        race — the entry advancing *past* the caller's snapshot between
        lookup and plan — by returning None (caller re-plans or falls back).
        """
        with self._lock:
            if expected_version is not None and self.version != expected_version:
                return None
            n = len(ranges)
            real_ix = [i for i, r in enumerate(ranges) if r.verdict is None]
            out: List[Optional[PlanResult]] = [None] * n
            alive_rows = np.nonzero(self.h_alive[: self.num_rows])[0]
            for i, r in enumerate(ranges):
                if r.verdict == "empty":
                    out[i] = PlanResult(0, np.empty(0, np.int64), via="verdict")
                elif r.verdict == "all":
                    out[i] = PlanResult(len(alive_rows), alive_rows[:k],
                                        overflow=len(alive_rows) > k, via="verdict")
            if not real_ix:
                return out  # type: ignore[return-value]
            lo = np.stack([ranges[i].lo for i in real_ix])  # (M, C)
            hi = np.stack([ranges[i].hi for i in real_ix])
            if use_device is None:
                use_device = self._device_profitable(len(real_ix), k)
            results = (self._plan_device(lo, hi, k) if use_device
                       else self._plan_host(lo, hi, k))
            via = "device" if use_device else "host-resident"
            for j, i in enumerate(real_ix):
                results[j].via = via
                out[i] = results[j]
            return out  # type: ignore[return-value]

    def _device_profitable(self, m: int, k: int) -> bool:
        if not conf.get_bool("delta.tpu.stateCache.devicePlan.enabled", True):
            return False
        mode = conf.get("delta.tpu.stateCache.devicePlan.mode", "auto")
        if mode == "force":
            return True
        if mode == "off":
            return False
        from delta_tpu.parallel import link

        cells = m * self.num_rows * max(len(self.columns), 1)
        host_s = cells * link.HOST_PRUNE_S_PER_CELL
        p = link.profile()
        down_bytes = m * max(self.capacity // BLOCK // 8, 1)
        device_s = (2 * p.latency_s + p.download_s(down_bytes)
                    + cells * link.DEVICE_PRUNE_S_PER_CELL)
        if self._dev is None:
            # cold build ships the full lanes once; amortized over later
            # queries, but charge it to this call for honest routing
            device_s += p.upload_s(self.device_bytes)
        return device_s < host_s

    def _plan_host(self, lo: np.ndarray, hi: np.ndarray, k: int) -> List[PlanResult]:
        n = self.num_rows
        mins, maxs = self.h_lo[:, :n], self.h_hi[:, :n]
        alive = self.h_alive[:n]
        out = []
        for q in range(lo.shape[0]):
            keep = alive.copy()
            for c in range(lo.shape[1]):
                if not np.isnan(lo[q, c]):
                    keep &= ~(maxs[c] < lo[q, c])  # NaN stat keeps
                if not np.isnan(hi[q, c]):
                    keep &= ~(mins[c] > hi[q, c])
            rows = np.nonzero(keep)[0]
            out.append(PlanResult(len(rows), rows[:k], overflow=len(rows) > k))
        return out

    def _plan_device(self, lo: np.ndarray, hi: np.ndarray, k: int) -> List[PlanResult]:
        """Coarse-fine plan: the device culls 1024-file BLOCKS (one dispatch
        over the resident f32 lanes, one tiny packed-bitmap download); the
        host then evaluates exactly (float64 mirrors) inside the surviving
        blocks only. Index extraction never runs on device — measured on a
        v5e, a vmapped ``nonzero``/``top_k`` over (256, 1M) costs 0.7-2.4 s
        where the block-bitmap reduction costs ~0.1 s — and the fine pass
        erases the f32 slop, so device results equal host results exactly."""
        import jax.numpy as jnp

        self.ensure_resident()
        m = lo.shape[0]
        mb = _next_pow2(m, floor=8)  # bucket the query-batch dim too
        lo_p = np.full((mb, lo.shape[1]), np.nan, np.float32)
        hi_p = np.full((mb, hi.shape[1]), np.nan, np.float32)
        lo_p[:m] = _f32_down(lo)
        hi_p[:m] = _f32_up(hi)
        bits = _block_kernel(
            self._dev["mins"], self._dev["maxs"], self._dev["alive"],
            jnp.asarray(lo_p), jnp.asarray(hi_p), BLOCK,
        )
        n_blocks = self.capacity // BLOCK
        blocks = np.unpackbits(np.asarray(bits)[:m], axis=1, count=n_blocks)
        n = self.num_rows
        mins, maxs, alive = self.h_lo[:, :n], self.h_hi[:, :n], self.h_alive[:n]
        out = []
        for q in range(m):
            hit = np.nonzero(blocks[q])[0]
            if not len(hit):
                out.append(PlanResult(0, np.empty(0, np.int64)))
                continue
            cand = np.concatenate([
                np.arange(b * BLOCK, min((b + 1) * BLOCK, n)) for b in hit
            ])
            keep = alive[cand].copy()
            for c in range(lo.shape[1]):
                if not np.isnan(lo[q, c]):
                    keep &= ~(maxs[c][cand] < lo[q, c])
                if not np.isnan(hi[q, c]):
                    keep &= ~(mins[c][cand] > hi[q, c])
            rows = cand[keep]
            out.append(PlanResult(len(rows), rows[:k], overflow=len(rows) > k))
        return out


@functools.lru_cache(maxsize=None)
def _scatter_bool_fn(value: bool):
    import jax

    return jax.jit(lambda a, r: a.at[r].set(value, mode="drop"))


def _scatter_bool(arr, rows, value: bool):
    return _scatter_bool_fn(value)(arr, rows)


@functools.lru_cache(maxsize=None)
def _scatter_cols_fn():
    import jax

    return jax.jit(lambda a, r, v: a.at[:, r].set(v, mode="drop"))


def _scatter_cols(arr, rows, vals):
    return _scatter_cols_fn()(arr, rows, vals)


# device block-cull granularity: pow2 ≤ the capacity floor in _next_pow2, so
# the padded capacity always divides evenly
BLOCK = 1024


@functools.lru_cache(maxsize=None)
def _block_kernel_fn(block: int):
    from delta_tpu.utils.jaxcache import ensure_compilation_cache

    ensure_compilation_cache()
    import jax
    import jax.numpy as jnp

    def kernel(mins, maxs, alive, lo, hi):
        # mins/maxs: (C, cap) f32; alive: (cap,) bool; lo/hi: (M, C) f32.
        # keep[m, f] = alive[f] AND over columns: the file's [min,max] range
        # can intersect the query's [lo,hi]; NaN (either side) = no bound.
        keep = jnp.broadcast_to(alive[None, :], (lo.shape[0], alive.shape[0]))
        for c in range(lo.shape[1]):  # static unroll: C is a lane count
            mn, mx = mins[c][None, :], maxs[c][None, :]
            lo_c, hi_c = lo[:, c:c + 1], hi[:, c:c + 1]
            keep = keep & (jnp.isnan(mx) | jnp.isnan(lo_c) | (mx >= lo_c))
            keep = keep & (jnp.isnan(mn) | jnp.isnan(hi_c) | (mn <= hi_c))
        blocks = keep.reshape(keep.shape[0], keep.shape[1] // block, block).any(axis=2)
        return jnp.packbits(blocks, axis=1)

    return jax.jit(kernel)


def _block_kernel(mins, maxs, alive, lo, hi, block: int):
    return _block_kernel_fn(block)(mins, maxs, alive, lo, hi)


# -- building entries from snapshots ----------------------------------------


def _lanes_from_arrays(arr, columns: Sequence[str]):
    lo = np.stack([arr.stats_min[c] for c in columns]) if columns else np.empty((0, arr.num_files))
    hi = np.stack([arr.stats_max[c] for c in columns]) if columns else np.empty((0, arr.num_files))
    return {"min": lo, "max": hi, "size": arr.size.astype(np.int64)}


def build_entry(snapshot) -> Optional[ResidentState]:
    """Full build of a resident entry from a snapshot's columnar state.
    None when the table shape is unsupported (partitioned / odd stats)."""
    from delta_tpu.ops.state_export import arrays_from_columns

    arr = arrays_from_columns(
        snapshot._columnar, snapshot._alive_mask, snapshot.metadata
    )
    if arr is None:
        return None
    columns = sorted(arr.stats_min.keys())
    return ResidentState(
        log_path=snapshot.delta_log.log_path,
        metadata_id=snapshot.metadata.id,
        version=snapshot.version,
        columns=columns,
        paths=list(arr.paths),
        lanes=_lanes_from_arrays(arr, columns),
    )


def _decode_tail(snapshot, from_version: int):
    """Decode commits (from_version, snapshot.version] to (removed_paths,
    (add_paths, lo, hi, size)) or None when incremental apply isn't safe
    (metadata change in the tail, missing commit files, partitioned...)."""
    from delta_tpu.log.columnar import decode_segment
    from delta_tpu.ops.state_export import arrays_from_columns
    from delta_tpu.protocol import filenames
    from delta_tpu.protocol.actions import Metadata

    log = snapshot.delta_log
    paths = [
        f"{log.log_path}/{filenames.delta_file(v)}"
        for v in range(from_version + 1, snapshot.version + 1)
    ]
    try:
        cols = decode_segment(log.store, [], paths)
    except Exception:
        return None
    if any(isinstance(a, Metadata) for a in cols.other_actions):
        return None  # schema/config may have changed -> rebuild
    w = cols.winner_mask()
    alive, _ = cols.replay(winner=w)
    dead_winner = w & ~alive
    removed = cols.paths_for(np.nonzero(dead_winner)[0])
    arr = arrays_from_columns(cols, alive, snapshot.metadata)
    if arr is None:
        return None
    columns = sorted(arr.stats_min.keys())
    lanes = _lanes_from_arrays(arr, columns)
    return removed, (list(arr.paths), lanes["min"], lanes["max"], lanes["size"]), columns


class DeviceStateCache:
    """Process-wide registry of :class:`ResidentState` entries with an HBM
    byte budget (`delta.tpu.stateCache.maxBytes`) and LRU eviction — the
    TPU analogue of the reference's `StateCache` Spark-memory cache."""

    _instance: Optional["DeviceStateCache"] = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._entries: Dict[str, ResidentState] = {}
        self._lock = threading.RLock()
        self._build_locks: Dict[str, threading.Lock] = {}
        self._tick = 0

    @classmethod
    def instance(cls) -> "DeviceStateCache":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = DeviceStateCache()
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        with cls._instance_lock:
            cls._instance = None

    def invalidate(self, log_path: str) -> None:
        with self._lock:
            self._entries.pop(log_path, None)
            self._build_locks.pop(log_path, None)

    def _lookup(self, key: str, snapshot):
        """Registry-lock lookup. Returns (entry_or_None, verdict): 'hit',
        'older' (serve from host), or 'advance' (tail apply / rebuild)."""
        with self._lock:
            e = self._entries.get(key)
            if e is not None and e.metadata_id != snapshot.metadata.id:
                e = None  # table replaced in place
            if e is None:
                return None, "advance"
            if e.version > snapshot.version:
                return None, "older"  # time travel below residency
            return e, ("hit" if e.version == snapshot.version else "advance")

    def get(self, snapshot) -> Optional[ResidentState]:
        """Entry current at the snapshot's version: cache hit, incremental
        tail apply, or full rebuild. None when unsupported or disabled.

        The registry lock covers only lookups/inserts; the seconds-long
        decode/build work runs under a per-table build lock so a cold build
        for one table never stalls cache hits for another."""
        if not conf.get_bool("delta.tpu.stateCache.enabled", True):
            return None
        key = snapshot.delta_log.log_path
        with self._lock:
            self._tick += 1
            tick = self._tick
            build_lock = self._build_locks.setdefault(key, threading.Lock())
        e, verdict = self._lookup(key, snapshot)
        if verdict == "older":
            return None
        if verdict == "hit":
            e.last_used = tick
            return e
        with build_lock:
            # re-check: another thread may have advanced/built meanwhile
            e, verdict = self._lookup(key, snapshot)
            if verdict == "older":
                return None
            if verdict == "hit":
                e.last_used = tick
                return e
            if e is not None:  # behind: try the incremental tail
                tail = _decode_tail(snapshot, e.version)
                ok = False
                if tail is not None:
                    removed, added, columns = tail
                    if columns == e.columns or not added[0]:
                        ok = e.apply_tail(snapshot.version, removed, added)
                if not ok:
                    e = None
            if e is None:
                e = build_entry(snapshot)
                if e is None:
                    return None
                with self._lock:
                    self._entries[key] = e
            e.last_used = tick
            with self._lock:
                self._evict_over_budget(keep=key)
            return e

    def _evict_over_budget(self, keep: str) -> None:
        # HBM budget: drop device arrays LRU (host mirrors keep serving)
        budget = int(conf.get("delta.tpu.stateCache.maxBytes", 2 << 30))
        resident = [(p, e) for p, e in self._entries.items() if e.is_resident]
        total = sum(e.device_bytes for _, e in resident)
        for p, e in sorted(resident, key=lambda kv: kv[1].last_used):
            if total <= budget:
                break
            if p == keep:
                continue
            e.drop_device()
            total -= e.device_bytes
        # host budget: entries (mirrors + path dictionaries) are themselves
        # sizable — drop whole tables LRU beyond maxEntries
        max_entries = int(conf.get("delta.tpu.stateCache.maxEntries", 16))
        if len(self._entries) > max_entries:
            for p, _e in sorted(self._entries.items(),
                                key=lambda kv: kv[1].last_used):
                if p == keep:
                    continue
                self._entries.pop(p, None)
                self._build_locks.pop(p, None)
                if len(self._entries) <= max_entries:
                    break
