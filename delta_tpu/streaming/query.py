"""Micro-batch driver: the engine loop Spark Structured Streaming provides.

The reference delegates scheduling, offset logging and commit logging to
Spark (`SURVEY §2.5`); this module is our replacement: a `StreamingQuery`
tracks offsets in a checkpoint directory (``offsets/<batchId>`` written
*before* running the batch, ``commits/<batchId>`` after — Spark's WAL
protocol), so a restarted query reruns at most the last unfinished batch and
the sink's SetTransaction idempotency makes that rerun a no-op.
"""
from __future__ import annotations

import json
import os
import uuid
from typing import Optional


from delta_tpu.streaming.offset import DeltaSourceOffset
from delta_tpu.streaming.sink import DeltaSink
from delta_tpu.streaming.source import DeltaSource

__all__ = ["StreamingQuery"]


class StreamingQuery:
    def __init__(
        self,
        source: DeltaSource,
        sink_or_fn,
        checkpoint_dir: str,
        query_id: Optional[str] = None,
    ):
        self.source = source
        self.sink = sink_or_fn if isinstance(sink_or_fn, DeltaSink) else None
        self.foreach = sink_or_fn if not isinstance(sink_or_fn, DeltaSink) else None
        self.checkpoint_dir = checkpoint_dir
        os.makedirs(os.path.join(checkpoint_dir, "offsets"), exist_ok=True)
        os.makedirs(os.path.join(checkpoint_dir, "commits"), exist_ok=True)
        self.query_id = query_id or self._load_or_create_query_id()

    def _load_or_create_query_id(self) -> str:
        meta_path = os.path.join(self.checkpoint_dir, "metadata")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                return json.load(f)["id"]
        qid = str(uuid.uuid4())
        with open(meta_path, "w") as f:
            json.dump({"id": qid}, f)
        return qid

    # -- offset log -------------------------------------------------------

    def _batch_ids(self, kind: str):
        d = os.path.join(self.checkpoint_dir, kind)
        return sorted(int(n) for n in os.listdir(d) if n.isdigit())

    def _read_offset(self, batch_id: int) -> DeltaSourceOffset:
        with open(os.path.join(self.checkpoint_dir, "offsets", str(batch_id))) as f:
            return DeltaSourceOffset.from_json(f.read(), self.source.table_id)

    def _write_offset(self, batch_id: int, off: DeltaSourceOffset) -> None:
        p = os.path.join(self.checkpoint_dir, "offsets", str(batch_id))
        with open(p, "w") as f:
            f.write(off.json())

    def _mark_committed(self, batch_id: int) -> None:
        with open(os.path.join(self.checkpoint_dir, "commits", str(batch_id)), "w") as f:
            f.write("{}")

    # -- the loop ---------------------------------------------------------

    def process_all_available(self) -> int:
        """Run micro-batches until the source is drained; returns #batches."""
        offsets = self._batch_ids("offsets")
        commits = set(self._batch_ids("commits"))
        ran = 0

        if offsets:
            last = offsets[-1]
            start = self._read_offset(offsets[-2]) if len(offsets) > 1 else None
            if last not in commits:
                # recover: re-run the planned-but-uncommitted batch
                end = self._read_offset(last)
                self._run_batch(last, start, end)
                ran += 1
            prev_end: Optional[DeltaSourceOffset] = self._read_offset(last)
            next_id = last + 1
        else:
            prev_end = None
            next_id = 0

        while True:
            anchor = prev_end if prev_end is not None else self.source.initial_offset()
            end = self.source.latest_offset(anchor)
            if end is None:
                return ran
            self._write_offset(next_id, end)
            self._run_batch(next_id, prev_end, end)
            prev_end = end
            next_id += 1
            ran += 1

    def _run_batch(self, batch_id: int, start: Optional[DeltaSourceOffset],
                   end: DeltaSourceOffset) -> None:
        table = self.source.get_batch(start, end)
        if self.sink is not None:
            self.sink.add_batch(batch_id, table)
        else:
            self.foreach(batch_id, table)
        self._mark_committed(batch_id)
