"""Deterministic fault injection + the hardening it drives.

Covers the tentpole subsystem end to end: scripted and seeded FaultPlans,
the transient-retry layer (`storage/retrying.py` over `utils/retries.py`),
ambiguous-commit reconciliation via `commitInfo.txnId`, crash-orphan
sweeping, torn/stale checkpoint recovery under injection, streaming
crash-replay idempotency, and the zero-overhead-when-unset contract.
"""
import glob
import json
import os
import time

import pyarrow as pa
import pytest

from delta_tpu.api.tables import DeltaTable
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.log.deltalog import DeltaLog
from delta_tpu.protocol import filenames
from delta_tpu.storage import faults
from delta_tpu.storage.faults import (
    FaultInjectingLogStore,
    FaultPlan,
    SimulatedCrash,
)
from delta_tpu.storage.logstore import LocalLogStore, MemoryLogStore
from delta_tpu.storage.retrying import RetryingLogStore
from delta_tpu.utils import retries, telemetry
from delta_tpu.utils.config import conf
from delta_tpu.utils.retries import RetryPolicy, TransientIOError


@pytest.fixture(autouse=True)
def _fresh_metrics():
    telemetry.reset_all()
    yield
    telemetry.reset_all()


def _ids(path):
    DeltaLog.invalidate_cache(path)
    with conf.set_temporarily(delta__tpu__faults__plan=None):
        return sorted(DeltaTable.for_path(path).to_arrow(columns=["id"])
                      .column("id").to_pylist())


def _table(path, *, plan=None, rows=(1, 2, 3)):
    """Create a table fault-free, then (optionally) re-open under `plan`."""
    with conf.set_temporarily(delta__tpu__faults__plan=None):
        DeltaTable.create(path, data=pa.table({"id": pa.array(rows, pa.int64())}))
    DeltaLog.invalidate_cache(path)
    if plan is not None:
        conf.set("delta.tpu.faults.plan", plan)
    try:
        return DeltaLog(path)
    finally:
        if plan is not None:
            conf.unset("delta.tpu.faults.plan")


# -- retry policy / layer ----------------------------------------------------


def test_retry_policy_deadline_bounds_total_time():
    """A flapping store fails in deadline_s, not max_attempts * max_delay_s."""
    calls = []

    def always_fails():
        calls.append(1)
        raise TransientIOError("flap")

    policy = RetryPolicy(max_attempts=1000, base_delay_s=0.01,
                         max_delay_s=0.02, deadline_s=0.15)
    t0 = time.monotonic()
    with pytest.raises(TransientIOError):
        retries.call_with_retries(always_fails, policy=policy)
    assert time.monotonic() - t0 < 2.0  # far under 1000 * 0.02
    assert 2 <= len(calls) < 20
    assert telemetry.counters("storage.retry")["storage.retry.exhausted"] == 1
    assert telemetry.counters("storage.retry")["storage.retry.attempts"] == len(calls) - 1


def test_retry_exhaustion_writes_flight_recorder_incident(tmp_path):
    from delta_tpu.obs import flight_recorder

    flight_recorder.install()
    with conf.set_temporarily(delta__tpu__obs__incidentDir=str(tmp_path / "inc")):
        with pytest.raises(TransientIOError):
            retries.call_with_retries(
                lambda: (_ for _ in ()).throw(TransientIOError("down")),
                policy=RetryPolicy(max_attempts=2, base_delay_s=0.001),
                op_name="read",
            )
        files = flight_recorder.incident_files(str(tmp_path / "inc"))
    assert len(files) == 1
    body = json.loads(open(files[0]).read())
    assert body["opType"] == "delta.storage.retry.exhausted"
    assert body["data"]["op"] == "read"


def test_is_transient_classification():
    assert retries.is_transient(TransientIOError("x"))
    assert retries.is_transient(ConnectionResetError())
    assert retries.is_transient(TimeoutError())
    assert not retries.is_transient(FileNotFoundError("v.json"))
    assert not retries.is_transient(FileExistsError("v.json"))  # OCC signal
    assert not retries.is_transient(ValueError("bug"))
    from delta_tpu.utils.errors import DeltaIOError

    assert not retries.is_transient(DeltaIOError("final verdict"))


def test_retrying_store_retries_reads_never_commit_creates():
    plan = FaultPlan(script=[("read", "transient"), ("write.commit", "transient")])
    base = MemoryLogStore()
    store = RetryingLogStore(
        FaultInjectingLogStore(base, plan),
        RetryPolicy(max_attempts=4, base_delay_s=0.001),
    )
    store.write("/t/_delta_log/00000000000000000000.json", ["a"])  # no fault yet? script head is read
    # scripted read transient: retried transparently
    assert store.read("/t/_delta_log/00000000000000000000.json") == ["a"]
    # scripted commit-create transient: surfaces immediately (sub=0 means the
    # write LANDED before the error — the ambiguity belongs to the txn layer)
    with pytest.raises(TransientIOError):
        store.write("/t/_delta_log/00000000000000000001.json", ["b"])
    assert base.read("/t/_delta_log/00000000000000000001.json") == ["b"]
    assert telemetry.counters("storage.retry")["storage.retry.attempts"] == 1
    assert telemetry.counters("faults")["faults.injected"] == 2


def test_retrying_store_retries_overwrite_writes():
    plan = FaultPlan(script=[("write.other", "transient")])
    base = MemoryLogStore()
    store = RetryingLogStore(
        FaultInjectingLogStore(base, plan),
        RetryPolicy(max_attempts=4, base_delay_s=0.001),
    )
    store.write_bytes("/t/_delta_log/whatever.bin", b"x", overwrite=True)
    assert base.read_bytes("/t/_delta_log/whatever.bin") == b"x"


# -- fault plan --------------------------------------------------------------


def test_plan_spec_parsing_and_unknown_keys():
    p = faults._parse_spec("seed=7,rate=0.25,kinds=transient|slow,maxFaults=3,slowMs=1")
    assert (p.seed, p.rate, p.kinds, p.max_faults, p.slow_ms) == (
        7, 0.25, ("transient", "slow"), 3, 1.0)
    with pytest.raises(ValueError):
        faults._parse_spec("seed=1,bogus=2")
    with pytest.raises(ValueError):
        FaultPlan(kinds=("not_a_kind",))


def test_plan_from_conf_caches_by_spec_string():
    spec = "seed=99,rate=0.5,kinds=transient"
    with conf.set_temporarily(delta__tpu__faults__plan=spec):
        a = faults.plan_from_conf()
        b = faults.plan_from_conf()
    assert a is b  # plan state persists across DeltaLog re-creation
    # a fresh independent run over the same spec needs a reset to get a
    # fresh seeded sequence, not the half-consumed streams
    faults.reset_plan_cache()
    with conf.set_temporarily(delta__tpu__faults__plan=spec):
        assert faults.plan_from_conf() is not a
    faults.reset_plan_cache()


def test_for_table_rebuilds_when_plan_installed_later(tmp_table):
    """The documented install path must work on an already-cached table:
    conf changes rebuild the cached DeltaLog's store stack."""
    with conf.set_temporarily(delta__tpu__faults__plan=None):
        DeltaTable.create(tmp_table, data=pa.table({"id": pa.array([1], pa.int64())}))
        log = DeltaLog.for_table(tmp_table)
        assert not isinstance(log.store.base, FaultInjectingLogStore)
    plan = FaultPlan(seed=1, rate=0.0)
    with conf.set_temporarily(delta__tpu__faults__plan=plan):
        wrapped = DeltaLog.for_table(tmp_table)
        assert wrapped.store.base.plan is plan
    # and back: unsetting the plan drops the injector again on next lookup
    with conf.set_temporarily(delta__tpu__faults__plan=None):
        clean = DeltaLog.for_table(tmp_table)
        assert not isinstance(clean.store.base, FaultInjectingLogStore)


def test_run_all_parts_crash_outranks_ordinary_failures():
    """A simulated process death in ANY part must surface over lower-index
    Exception failures — `except Exception` recovery may not survive it."""
    from delta_tpu.log.checkpoints import _run_all_parts

    def part(i):
        if i == 0:
            raise ValueError("ordinary part failure")
        if i == 2:
            raise SimulatedCrash("write.checkpoint")

    with pytest.raises(SimulatedCrash):
        _run_all_parts(4, part)
    # without a crash, the lowest-index failure surfaces (all attempted)
    ran = []

    def part2(i):
        ran.append(i)
        if i in (1, 3):
            raise ValueError(f"part {i}")

    with pytest.raises(ValueError, match="part 1"):
        _run_all_parts(4, part2)
    assert sorted(ran) == [0, 1, 2, 3]


def test_seeded_plan_is_deterministic_per_point():
    def run(seed):
        plan = FaultPlan(seed=seed, rate=0.3)
        store = FaultInjectingLogStore(MemoryLogStore(), plan)
        for i in range(120):
            try:
                store.write(f"/t/_delta_log/{filenames.delta_file(i)}", ["x"])
            except BaseException:  # noqa: BLE001 — crashes/transients expected
                pass
            try:
                list(store.list_from("/t/_delta_log/0"))
            except BaseException:  # noqa: BLE001
                pass
        return plan.per_point

    assert run(42) == run(42)
    assert run(42) != run(43)


def test_maybe_wrap_zero_overhead_when_unset():
    base = MemoryLogStore()
    with conf.set_temporarily(delta__tpu__faults__plan=None):
        assert faults.maybe_wrap(base) is base


def test_deltalog_store_stack_wiring(tmp_table):
    with conf.set_temporarily(delta__tpu__faults__plan=None):
        DeltaTable.create(tmp_table, data=pa.table({"id": pa.array([1], pa.int64())}))
        DeltaLog.invalidate_cache(tmp_table)
        log = DeltaLog(tmp_table)
        # no plan: retry layer directly over the base store — NO fault wrapper
        assert isinstance(log.store, RetryingLogStore)
        assert not isinstance(log.store.base, FaultInjectingLogStore)
    plan = FaultPlan(seed=1, rate=0.0)
    with conf.set_temporarily(delta__tpu__faults__plan=plan):
        log = DeltaLog(tmp_table)
        assert isinstance(log.store.base, FaultInjectingLogStore)
        assert log.store.base.plan is plan
    with conf.set_temporarily(delta__tpu__storage__retry__enabled=False,
                              delta__tpu__faults__plan=None):
        log = DeltaLog(tmp_table)
        assert not isinstance(log.store, RetryingLogStore)


# -- ambiguous commit reconciliation ----------------------------------------


def test_ambiguous_commit_reconciled_as_won(tmp_table):
    """Commit create raises a transient AFTER the write landed (lost
    response): the txn re-reads version N, sees its own txnId, and reports
    success — exactly one commit, no double-commit, no false failure."""
    plan = FaultPlan(script=[("write.commit", "transient")])
    log = _table(tmp_table, plan=plan)
    WriteIntoDelta(log, "append", pa.table({"id": pa.array([9], pa.int64())})).run()
    assert log.update().version == 1
    assert _ids(tmp_table) == [1, 2, 3, 9]
    assert telemetry.counters("commit")["commit.reconciled"] == 1
    [ev] = telemetry.recent_events("delta.commit.reconcile")
    assert ev.data["won"] is True
    # the landed commit carries the reconciliation token
    line = log.store.read(f"{log.log_path}/{filenames.delta_file(1)}")[0]
    assert json.loads(line)["commitInfo"]["txnId"]


def test_ambiguous_commit_reconciled_as_lost(tmp_table):
    """Version N exists but belongs to ANOTHER writer: reconciliation says
    lost, and the commit proceeds through the conflict checker to N+1."""
    log = _table(tmp_table)
    txn = log.start_transaction()
    token_winner = "deadbeef" * 4
    winner = {"commitInfo": {"timestamp": 0, "operation": "WRITE", "txnId": token_winner}}
    add = {"add": {"path": "w.parquet", "partitionValues": {}, "size": 1,
                   "modificationTime": 0, "dataChange": True}}
    log.store.write(f"{log.log_path}/{filenames.delta_file(1)}",
                    [json.dumps(winner), json.dumps(add)])
    txn._commit_token = "feedface" * 4
    assert txn._reconcile_ambiguous_commit(1, TransientIOError("lost resp")) is False
    assert telemetry.counters("commit")["commit.reconciled"] == 1
    # absent version: provably not landed
    assert txn._reconcile_ambiguous_commit(5, TransientIOError("x")) is None


def test_ambiguous_commit_error_before_write_retries_and_lands(tmp_table):
    """Transient raised BEFORE the create reached storage: reconciliation
    finds no file and the loop safely re-attempts the same version."""
    plan = FaultPlan(script=[("write.commit", "transient", 0.9)])
    log = _table(tmp_table, plan=plan)
    WriteIntoDelta(log, "append", pa.table({"id": pa.array([7], pa.int64())})).run()
    assert _ids(tmp_table) == [1, 2, 3, 7]
    # reconciled (as not-landed), then clean single commit at version 1
    assert telemetry.counters("commit")["commit.reconciled"] == 1
    assert not os.path.exists(
        os.path.join(tmp_table, "_delta_log", filenames.delta_file(2)))


# -- crashes -----------------------------------------------------------------


def test_crash_before_publish_leaves_orphan_and_no_commit(tmp_table):
    plan = FaultPlan(script=[("write.commit", "crash_before_publish")])
    log = _table(tmp_table, plan=plan)
    with pytest.raises(SimulatedCrash):
        WriteIntoDelta(log, "append", pa.table({"id": pa.array([9], pa.int64())})).run()
    # no commit landed; a staged .tmp orphan remains (what a dead writer leaves)
    assert _ids(tmp_table) == [1, 2, 3]
    orphans = glob.glob(os.path.join(tmp_table, "_delta_log", ".*.tmp"))
    assert orphans
    # recovery: fresh log resumes and the next commit takes version 1
    DeltaLog.invalidate_cache(tmp_table)
    log2 = DeltaLog(tmp_table)
    WriteIntoDelta(log2, "append", pa.table({"id": pa.array([9], pa.int64())})).run()
    assert _ids(tmp_table) == [1, 2, 3, 9]


def test_crash_after_publish_commit_is_durable(tmp_table):
    plan = FaultPlan(script=[("write.commit", "crash_after_publish")])
    log = _table(tmp_table, plan=plan)
    with pytest.raises(SimulatedCrash):
        WriteIntoDelta(log, "append", pa.table({"id": pa.array([9], pa.int64())})).run()
    # the writer died AFTER the create: the commit is visible to recovery
    assert _ids(tmp_table) == [1, 2, 3, 9]


def test_simulated_crash_pierces_except_exception():
    with pytest.raises(SimulatedCrash):
        try:
            raise SimulatedCrash("write.commit")
        except Exception:  # noqa: BLE001 — must NOT catch a crash
            pytest.fail("SimulatedCrash must not be swallowed by except Exception")


# -- orphan sweeping ---------------------------------------------------------


def test_cleanup_sweeps_aged_tmp_orphans_keeps_young(tmp_table):
    from delta_tpu.log.cleanup import sweep_tmp_orphans

    log = _table(tmp_table)
    log_dir = os.path.join(tmp_table, "_delta_log")
    old = os.path.join(log_dir, ".00000000000000000009.json.aaaa.tmp")
    young = os.path.join(log_dir, ".00000000000000000009.json.bbbb.tmp")
    for p in (old, young):
        with open(p, "wb") as f:
            f.write(b"orphan")
    aged = (time.time() - 7200) * 1000  # 2h old vs the 1h default TTL
    os.utime(old, (aged / 1000, aged / 1000))
    swept = sweep_tmp_orphans(log, int(time.time() * 1000))
    assert swept == 1
    assert not os.path.exists(old) and os.path.exists(young)
    # delta/checkpoint/_last_checkpoint files untouched
    assert os.path.exists(os.path.join(log_dir, filenames.delta_file(0)))


def test_local_overwrite_write_failure_leaves_no_tmp(tmp_path, monkeypatch):
    """Satellite fix: the overwrite branch now stages in try/finally."""
    store = LocalLogStore()
    target = str(tmp_path / "_delta_log" / "_last_checkpoint")
    os.makedirs(os.path.dirname(target))
    real_replace = os.replace

    def boom(src, dst):
        raise OSError(5, "injected EIO")

    monkeypatch.setattr(os, "replace", boom)
    with pytest.raises(OSError):
        store.write_bytes(target, b"{}", overwrite=True)
    monkeypatch.setattr(os, "replace", real_replace)
    assert glob.glob(str(tmp_path / "_delta_log" / "*.tmp")) == []
    assert glob.glob(str(tmp_path / "_delta_log" / ".*.tmp")) == []


# -- checkpoint faults -------------------------------------------------------


def _commit_n(log, n, start=100):
    for i in range(n):
        WriteIntoDelta(log, "append",
                       pa.table({"id": pa.array([start + i], pa.int64())})).run()


def test_torn_multipart_checkpoint_never_blocks_progress(tmp_table):
    log = _table(tmp_table)
    _commit_n(log, 5)
    plan = FaultPlan(script=[("write.checkpoint", "torn_checkpoint")])
    with conf.set_temporarily(delta__tpu__faults__plan=plan,
                              delta__tpu__checkpointPartSize=2):
        DeltaLog.invalidate_cache(tmp_table)
        flog = DeltaLog(tmp_table)
        with pytest.raises(SimulatedCrash):
            flog.checkpoint()
    # some parts landed, the set is incomplete, the pointer never moved
    parts = glob.glob(os.path.join(tmp_table, "_delta_log", "*.checkpoint.*.parquet"))
    assert parts, "torn checkpoint should leave partial parts behind"
    assert not os.path.exists(os.path.join(tmp_table, "_delta_log", "_last_checkpoint"))
    # recovery reads the table fine (partial checkpoint ignored) and a fresh
    # checkpoint at a later version completes
    assert len(_ids(tmp_table)) == 8
    DeltaLog.invalidate_cache(tmp_table)
    log2 = DeltaLog(tmp_table)
    _commit_n(log2, 1, start=500)
    log2.checkpoint()
    assert os.path.exists(os.path.join(tmp_table, "_delta_log", "_last_checkpoint"))
    assert len(_ids(tmp_table)) == 9


def test_stale_last_checkpoint_pointer_is_survivable(tmp_table):
    log = _table(tmp_table)
    _commit_n(log, 3)
    log.checkpoint()  # honest pointer at v3
    before = open(os.path.join(tmp_table, "_delta_log", "_last_checkpoint")).read()
    plan = FaultPlan(script=[("write.lastCheckpoint", "stale_last_checkpoint")])
    with conf.set_temporarily(delta__tpu__faults__plan=plan):
        DeltaLog.invalidate_cache(tmp_table)
        flog = DeltaLog(tmp_table)
        _commit_n(flog, 2, start=200)
        flog.checkpoint()  # checkpoint parts land; pointer update LOST
    after = open(os.path.join(tmp_table, "_delta_log", "_last_checkpoint")).read()
    assert after == before  # pointer is stale (points at v3, log is at v5)
    # readers list past the stale pointer and see everything
    snap = DeltaLog(tmp_table).update()
    assert snap.version == 5
    assert len(_ids(tmp_table)) == 8


def test_listing_lag_serves_older_consistent_snapshot(tmp_table):
    log = _table(tmp_table)
    _commit_n(log, 2)  # versions 1, 2
    plan = FaultPlan(script=[("list", "listing_lag")])
    with conf.set_temporarily(delta__tpu__faults__plan=plan):
        DeltaLog.invalidate_cache(tmp_table)
        lag = DeltaLog(tmp_table)  # init update: newest delta hidden once
        assert lag.snapshot.version == 1  # older but consistent
        assert lag.update().version == 2  # next listing sees it


def test_slow_fault_only_delays(tmp_table):
    plan = FaultPlan(script=[("write.commit", "slow")], slow_ms=1)
    log = _table(tmp_table, plan=plan)
    WriteIntoDelta(log, "append", pa.table({"id": pa.array([4], pa.int64())})).run()
    assert _ids(tmp_table) == [1, 2, 3, 4]
    assert plan.kinds_seen() == {"slow": 1}


# -- streaming crash-replay idempotency (satellite) --------------------------


def test_streaming_sink_crash_replay_is_idempotent(tmp_table):
    """Injected crash-after-publish on the sink's commit: the engine
    re-delivers the batch with the same txnId/batchId — the replay must be
    a no-op (SetTransaction dedup), rows exactly once."""
    from delta_tpu.streaming.sink import DeltaSink

    log = _table(tmp_table)
    plan = FaultPlan(script=[("write.commit", "crash_after_publish")])
    data = pa.table({"id": pa.array([10, 11], pa.int64())})
    with conf.set_temporarily(delta__tpu__faults__plan=plan):
        DeltaLog.invalidate_cache(tmp_table)
        flog = DeltaLog(tmp_table)
        sink = DeltaSink(flog, "q-replay")
        with pytest.raises(SimulatedCrash):
            sink.add_batch(0, data)
        # crash-recover: fresh log + sink, SAME batch re-delivered
        DeltaLog.invalidate_cache(tmp_table)
        flog2 = DeltaLog(tmp_table)
        committed = DeltaSink(flog2, "q-replay").add_batch(0, data)
    assert committed is False  # dedup: already committed by the crashed attempt
    assert _ids(tmp_table) == [1, 2, 3, 10, 11]
    # and a NEW batch still goes through
    with conf.set_temporarily(delta__tpu__faults__plan=None):
        DeltaLog.invalidate_cache(tmp_table)
        assert DeltaSink(DeltaLog(tmp_table), "q-replay").add_batch(
            1, pa.table({"id": pa.array([12], pa.int64())})) is True
    assert _ids(tmp_table) == [1, 2, 3, 10, 11, 12]


def test_streaming_sink_crash_before_publish_replay_commits(tmp_table):
    from delta_tpu.streaming.sink import DeltaSink

    log = _table(tmp_table)
    plan = FaultPlan(script=[("write.commit", "crash_before_publish")])
    data = pa.table({"id": pa.array([20], pa.int64())})
    with conf.set_temporarily(delta__tpu__faults__plan=plan):
        DeltaLog.invalidate_cache(tmp_table)
        with pytest.raises(SimulatedCrash):
            DeltaSink(DeltaLog(tmp_table), "q2").add_batch(0, data)
        DeltaLog.invalidate_cache(tmp_table)
        committed = DeltaSink(DeltaLog(tmp_table), "q2").add_batch(0, data)
    assert committed is True  # first attempt never landed; replay commits
    assert _ids(tmp_table) == [1, 2, 3, 20]
