"""Configuration system.

Three tiers, mirroring the reference (SURVEY §5 "Config / flag system"):

1. **Session confs** (:class:`SqlConf`) ≈ ``sources/DeltaSQLConf.scala`` —
   process-wide engine knobs under ``delta.tpu.*``.
2. **Table properties** (:class:`DeltaConfigs`) ≈ ``DeltaConfig.scala:114-433``
   — typed, validated ``delta.*`` keys persisted in ``Metadata.configuration``,
   with session-level defaults via ``delta.tpu.properties.defaults.*``.
3. Per-operation reader/writer options (≈ ``DeltaOptions.scala``) are keyword
   arguments on the command constructors (e.g. ``merge_schema`` /
   ``replace_where`` on ``delta_tpu.commands.write.WriteIntoDelta``).
"""
from __future__ import annotations

import re
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Generic, Optional, TypeVar

from delta_tpu.utils.errors import DeltaIllegalArgumentError

T = TypeVar("T")

__all__ = ["SqlConf", "conf", "DeltaConfig", "DeltaConfigs", "parse_interval_ms"]


# ---------------------------------------------------------------------------
# Session conf
# ---------------------------------------------------------------------------

class SqlConf:
    """Process-wide conf with defaults; thread-safe; supports ``with
    conf.set_temporarily(...)`` for tests (≈ SQLConf + withSQLConf)."""

    _DEFAULTS: Dict[str, Any] = {
        # ≈ DELTA_MAX_RETRY_COMMIT_ATTEMPTS (DeltaSQLConf.scala:182)
        "delta.tpu.maxCommitAttempts": 10_000_000,
        # Group commit (txn/group_commit): concurrent commit() calls on one
        # DeltaLog enqueue; a leader drains the queue, reads the log tail
        # ONCE, conflict-checks the batch (against the tail AND each other)
        # and writes members as consecutive versions — amortizing the
        # per-writer list/read-tail/CAS cycle under contention. Default OFF:
        # with it off the commit path is byte-identical to the ungrouped
        # engine (regression-tested).
        "delta.tpu.commit.group.enabled": False,
        # Max transactions one leader writes per batch drain.
        "delta.tpu.commit.group.maxBatch": 32,
        # How long a new leader lingers for the queue to fill before
        # draining (the classic group-commit accumulation window).
        "delta.tpu.commit.group.maxWaitMs": 2,
        # Asynchronous interval checkpointing (log/checkpointer): the
        # every-Nth-commit checkpoint (`delta.checkpointInterval`) is
        # enqueued to a background daemon instead of stalling the
        # committing writer on an O(table) synchronous write. Default OFF.
        "delta.tpu.checkpoint.async": False,
        # Incremental checkpoint builds (log/checkpointer): checkpoint N is
        # built from the cached reconciled columns of checkpoint M plus a
        # decode of ONLY the tail commits M+1..N, instead of re-decoding
        # the whole base checkpoint. Falls back to full reconstruction (and
        # re-seeds the cache) on any gap/overflow. Default OFF.
        "delta.tpu.checkpoint.incremental": False,
        # Cached incremental bases kept across tables (LRU).
        "delta.tpu.checkpoint.incremental.maxTables": 8,
        # ≈ DELTA_CHECKPOINT_PART_SIZE — actions per checkpoint part
        "delta.tpu.checkpointPartSize": 1_000_000,
        # Run the MERGE equi-join on device (ops/join_kernel) when the
        # condition is 1-2 integer equi-keys with no residual conjuncts
        # (composite keys pack into one int64 lane).
        "delta.tpu.merge.devicePath.enabled": True,
        # Executor routing for the MERGE join: "auto" prices the device leg
        # against the measured link profile (parallel/link.py) — separately
        # for the resident-cache-hit and the cold slab-upload cases — and
        # declines the device when the host hash join is cheaper; "force"
        # always engages the device; "off" never does.
        "delta.tpu.merge.devicePath.mode": "auto",
        # On a multichip mesh, prefer the all-gather sharded sort-merge
        # kernel (ops/join_kernel) over the single-device resident-slab
        # pipeline. Off by default: the resident pipeline wins on link
        # economics until the multichip executor (ROADMAP item 2) is real.
        "delta.tpu.merge.devicePath.preferMesh": False,
        # Cross-MERGE resident key cache (ops/key_cache): keep packed target
        # join keys HBM-resident keyed by snapshot version + rewrite epoch,
        # so repeated MERGEs against a hot table skip both the key decode
        # and the upload. False disables caching AND the background build
        # (the fused device path then rebuilds a transient slab per merge).
        # `delta.tpu.merge.residentKeys.enabled` is the legacy alias; either
        # set to false disables.
        "delta.tpu.merge.keyCache.enabled": True,
        "delta.tpu.merge.residentKeys.enabled": True,
        # Minimum estimated table rows before the post-commit background
        # key-lane build kicks in (small tables never win on device).
        "delta.tpu.merge.residentKeys.minRows": 1 << 20,
        # Resident key-cache budgets (ops/key_cache.KeyCache._evict).
        "delta.tpu.keyCache.maxBytes": 1 << 30,
        "delta.tpu.keyCache.maxEntries": 8,
        # Device residual-filter path (ops/column_cache): "auto" prices
        # device vs host per scan through parallel/link, "force" always
        # engages (bench legs), "off" disables the path and the cache.
        "delta.tpu.read.deviceResidual.mode": "auto",
        # Scan column-cache budgets (ops/column_cache.ColumnCache._evict);
        # entries are per-(file, column) lanes, hence the larger count.
        "delta.tpu.columnCache.maxBytes": 1 << 30,
        "delta.tpu.columnCache.maxEntries": 4096,
        # Process-wide soft budget over EVERY device-resident byte the
        # engine holds (key-cache slabs + state-cache lanes + join scratch
        # + scan column lanes, obs/hbm_ledger). When set, each LRU cache
        # prices itself against budget minus everyone else, so growth
        # anywhere becomes eviction pressure instead of OOM. None =
        # unlimited.
        "delta.tpu.device.hbmBudgetBytes": None,
        # Router audit ledger (obs/router_audit): last N routed decisions
        # kept for the HTTP /router route.
        "delta.tpu.router.auditKeep": 256,
        # Self-calibrating cost model (obs/calibration): EWMA re-fit of the
        # parallel/link.py throughput constants from the audit ledger's
        # measured samples. Off by default — routing then runs on the
        # shipped constants.
        "delta.tpu.router.calibration.enabled": False,
        # Where calibration state persists. None = next to the log of the
        # table that produced the samples (<log dir>/.router_calibration
        # .json, local paths only); set for object-store tables or to share
        # one state file across tables on the same hardware.
        "delta.tpu.router.calibration.statePath": None,
        # EWMA blend weight of each new sample (0.01..1.0].
        "delta.tpu.router.calibration.alpha": 0.2,
        # Samples a constant needs before its calibrated value overrides
        # the shipped default (guards against one noisy first merge).
        "delta.tpu.router.calibration.minSamples": 3,
        # Hot-path (scan planner) ingests throttle the state-file write to
        # at most one per this interval; merges always flush.
        "delta.tpu.router.calibration.flushIntervalMs": 2000,
        # Link profile overrides (MB/s). Unset = probe once per process.
        "delta.tpu.link.uploadMBps": None,
        "delta.tpu.link.downloadMBps": None,
        # Non-equi MERGE pair-streaming tile budget: peak candidate pairs
        # materialized per tile of the target x source grid.
        "delta.tpu.merge.nonEquiPairBudget": 8_000_000,
        # Device-resident state cache (ops/state_cache): keep decoded
        # snapshot stat lanes HBM-resident across queries.
        "delta.tpu.stateCache.enabled": True,
        "delta.tpu.stateCache.maxBytes": 2 << 30,
        "delta.tpu.stateCache.maxEntries": 16,
        # Serve file-tier prunes from resident lanes (ops/pruning).
        "delta.tpu.stateCache.serveScans": True,
        # Plan scans on device from resident lanes; "auto" prices the
        # device leg against the link profile, "force"/"off" override.
        "delta.tpu.stateCache.devicePlan.enabled": True,
        "delta.tpu.stateCache.devicePlan.mode": "auto",
        # ≈ DELTA_VACUUM_RETENTION_CHECK_ENABLED
        "delta.tpu.retentionDurationCheck.enabled": True,
        # ≈ DELTA_STATE_CORRUPTION_IS_FATAL
        "delta.tpu.state.corruptionIsFatal": True,
        # ≈ DELTA_ASYNC_UPDATE_STALENESS_TIME_LIMIT (DeltaSQLConf.scala:262)
        "delta.tpu.stalenessLimitMs": 0,
        # Preferred spelling of the staleness bound (log/deltalog.update
        # stale_ok path); None falls back to delta.tpu.stalenessLimitMs.
        "delta.tpu.snapshot.stalenessLimitMs": None,
        # ≈ DELTA_SCHEMA_AUTO_MIGRATE (merge schema on write by default off)
        "delta.tpu.schema.autoMerge.enabled": False,
        # ≈ DELTA_HISTORY_METRICS_ENABLED
        "delta.tpu.history.metricsEnabled": True,
        # Usage-event/span recording (utils/telemetry). False = no events or
        # spans are buffered (zero-overhead blackout); counters stay live.
        "delta.tpu.telemetry.enabled": True,
        # Telemetry ring-buffer capacity (events + spans).
        "delta.tpu.telemetry.bufferSize": 4096,
        # Distributed-trace plane (utils/telemetry + obs/trace_store).
        # Head-sampling probability for NEW root traces; errors and
        # SLO-burn windows force-sample regardless.
        "delta.tpu.trace.sampleRate": 1.0,
        # Directory receiving per-process JSONL span spools (and the
        # collector's stitch source for /traces). None = no spooling —
        # spans stay in the in-process ring only.
        "delta.tpu.trace.dir": None,
        # Per-process spool byte cap; past it spans drop (counted in
        # trace.spansDropped) instead of filling the disk.
        "delta.tpu.trace.maxBytes": 32 * 1024 * 1024,
        # Operator HTTP endpoint (obs/server): serve /metrics, /healthz,
        # /events, /trace, /doctor on this port. None = no server; 0 = an
        # ephemeral port (tests). Opt-in only — nothing listens by default.
        "delta.tpu.obs.port": None,
        # Failure flight recorder (obs/flight_recorder): directory receiving
        # incident JSON files when an instrumented operation raises. None =
        # recorder off (the default; span-error hooks cost nothing then).
        "delta.tpu.obs.incidentDir": None,
        # Max incident files kept in incidentDir (oldest deleted first).
        "delta.tpu.obs.incidentKeep": 20,
        # Last N ring-buffer events snapshotted into each incident file.
        "delta.tpu.obs.incidentEvents": 64,
        # Persistent per-table workload journal (obs/journal): one JSONL
        # entry per scan/commit/DML/router decision, batched into segment
        # files under <table>/_delta_log/_journal/ for the layout advisor
        # (obs/advisor). Inert under a telemetry blackout either way;
        # object-store (scheme://) tables never journal.
        "delta.tpu.journal.enabled": True,
        # Active segment rotates past this many bytes.
        "delta.tpu.journal.segmentBytes": 1 << 20,
        # Total on-disk bound per table; oldest segments swept first.
        "delta.tpu.journal.maxBytes": 16 << 20,
        # Segments older than this are swept regardless of the size bound.
        "delta.tpu.journal.retentionMs": 7 * 86_400_000,
        # Buffered entries flush to disk at this count or age, whichever
        # comes first — the IO runs on the journal writer thread, never on
        # the operation's thread.
        "delta.tpu.journal.flushEntries": 64,
        "delta.tpu.journal.flushIntervalMs": 2000,
        # Literal-sample reservoir: the first K scans per predicate
        # fingerprint persist their concrete SQL (deterministic first-K,
        # replay-stable); past the bound the report predicate is redacted,
        # so K bounds how many concrete literals ever hit disk. 0 redacts
        # everything (fingerprints only — workload replay then falls back
        # to stats-guided literal synthesis).
        "delta.tpu.journal.literalSamples": 3,
        # -- workload replay + shadow optimizer (delta_tpu/replay) -----------
        # Scans replayed per trace (newest kept) — bounds a shadow run's
        # cost on a long-journaled table.
        "delta.tpu.replay.maxScans": 256,
        # Sandbox root for shadow clones; None = a fresh tempfile.mkdtemp
        # per run. Always removed afterwards, BaseException included.
        "delta.tpu.replay.sandboxDir": None,
        # Score weight for scans whose literal was synthesized from file
        # stats instead of sampled from the journal — measured-on-real-
        # literals evidence counts full, synthesized counts this fraction.
        "delta.tpu.replay.literalDiscount": 0.5,
        # Candidate clones are prepared concurrently on the
        # delta-replay-prep pool (replays themselves run sequentially: the
        # per-scan flight recorder is process-global).
        "delta.tpu.replay.prepWorkers": 2,
        # -- fleet observability plane (obs/fleet, obs/timeseries, obs/slo) --
        # Process-wide table registry: every DeltaLog auto-registers on
        # construction (weakref'd) so fleet_doctor()/fleet_advise() can
        # sweep all live tables. Inert under a telemetry blackout either
        # way; this switch turns just the registry off.
        "delta.tpu.obs.fleet.enabled": True,
        # Metrics scraper daemon (obs/timeseries): snapshot the telemetry
        # registry every intervalMs into bounded in-memory rings of
        # `keep` samples per series (counter cumulatives, gauge values,
        # histogram bucket counts). 10s x 400 ~= 67min of history —
        # deliberately PAST the 1h SLO slow window, so the slow-window
        # baseline is a real sample, not the counts-from-zero fallback.
        "delta.tpu.obs.scrape.intervalMs": 10_000,
        "delta.tpu.obs.scrape.keep": 400,
        # Hard cap on distinct series tracked across the rings; past it
        # the series whose value went stale longest ago are evicted
        # (bounds memory under table churn — dead tables' labeled series
        # stop changing and age out first).
        "delta.tpu.obs.scrape.maxSeries": 8192,
        # SLO burn-rate monitors (obs/slo) over the scraped series,
        # evaluated after each scrape: an objective fires only when BOTH
        # the fast and the slow window burn past 1.0 (multi-window rule),
        # and clears with hysteresis once the fast window drops below
        # clearRatio. Firing alerts write a flight-recorder incident
        # (when incidentDir is set) and boost the autopilot's priority
        # for the offending table's actions by priorityBoost.
        "delta.tpu.obs.slo.enabled": True,
        "delta.tpu.obs.slo.fastWindowMs": 300_000,
        "delta.tpu.obs.slo.slowWindowMs": 3_600_000,
        "delta.tpu.obs.slo.clearRatio": 0.8,
        # Observation floor per window before an alert may fire: right
        # after scraper start both windows see the same counts-from-zero
        # delta, so one cold-start outlier must not page.
        "delta.tpu.obs.slo.minObservations": 10,
        "delta.tpu.obs.slo.priorityBoost": 25.0,
        # Default objectives (obs/slo.objectives): per-table latency
        # quantiles and process-wide failure-rate ceilings.
        "delta.tpu.obs.slo.commitLatencyP99Ms": 2_000.0,
        "delta.tpu.obs.slo.scanPlanningP99Ms": 500.0,
        "delta.tpu.obs.slo.commitConflictRate": 0.05,
        "delta.tpu.obs.slo.retryExhaustionRate": 0.02,
        "delta.tpu.obs.slo.journalDropRate": 0.01,
        # Streaming backlog gauges walk at most this many pending files past
        # each batch end (a deeply lagging consumer must not re-read its
        # whole remaining log tail per micro-batch; the published count is a
        # floor when the cap is hit). <= 0 publishes only the version lag.
        "delta.tpu.obs.streamingBacklogMaxFiles": 1024,
        # Materialize parsed per-file stats as typed Parquet struct columns
        # (`add.stats_parsed` / `add.partitionValues_parsed`) in checkpoints
        # when the table does not set delta.checkpoint.writeStatsAsStruct
        # itself. Default ON: the cold state-cache build then reads typed
        # columns instead of re-parsing per-file stats JSON (the dominant
        # cost of a 1M-file cold build — see BENCH metric 6).
        "delta.tpu.checkpoint.writeStatsAsStruct": True,
        # ≈ DELTA_WRITE_CHECKSUM_ENABLED
        "delta.tpu.writeChecksum.enabled": True,
        # Target max rows per written data file (write-path sharding unit).
        "delta.tpu.write.targetFileRows": 4_000_000,
        # BYTE_STREAM_SPLIT encoding for float columns: much faster decode,
        # equal size. Disable for parquet-mr < 1.12 readers (Spark <= 3.1).
        "delta.tpu.write.byteStreamSplit": True,
        # "auto" = snappy only on string/float columns, high-entropy ints
        # uncompressed (snappy on random int64 is 14x slower to decode for
        # ~10% size); or a codec name applied to all columns.
        "delta.tpu.write.compression": "auto",
        # Predicate pushdown synthesis (expr/synthesis): arithmetic /
        # string / temporal predicates the base skipping rules can't lower
        # (`price * qty > 1000`, `substr(id,1,4) = 'us-w'`, `year(d) =
        # 2026`) rewrite into sound can-match predicates over the same
        # min/max stats lanes, at BOTH pruning tiers (file + row group).
        # False disables the synthesis fallback: such shapes keep every
        # file/row group and run as residual filters only. The NOT
        # comparison pushdown (`Not(Lt)` ≡ `Ge`, type-gated) is a
        # base-rule fix and stays on either way.
        "delta.tpu.read.predicateSynthesis": True,
        # Second pruning tier inside the Parquet decode (exec/rowgroups):
        # footer row-group stats skip non-matching row groups, and predicate
        # columns decode first so remaining columns decode only for row
        # groups with possible matches (late materialization). False = every
        # surviving file decodes in full (the pre-tier behavior).
        "delta.tpu.read.rowGroupSkipping": True,
        # Bounded LRU of parsed Parquet footers keyed by path and validated
        # by (size, mtime): hot-table queries stop re-parsing footers per
        # open. 0 disables caching (footers parse on every open).
        "delta.tpu.read.footerCacheEntries": 1024,
        # Max rows per row group written by the engine (the skipping granule
        # of the read tier above). Arrow's 1Mi default would leave most
        # files as a single group with nothing to skip. <= 0 = Arrow default.
        "delta.tpu.write.rowGroupRows": 131_072,
        # Below this many candidate files, stats skipping runs on the host
        # (one device round-trip costs more than the whole numpy pass).
        "delta.tpu.device.pruning.minFiles": 4096,
        # Deterministic fault injection (storage/faults.py): a FaultPlan
        # object or a spec string like "seed=42,rate=0.05,kinds=transient".
        # None (the default) installs NO wrapper — zero overhead, asserted
        # by bench.py.
        "delta.tpu.faults.plan": None,
        # Transient-retry layer over every table's LogStore (storage/
        # retrying.py): idempotent ops (reads, listings, overwrite-PUTs)
        # retry under utils/retries.RetryPolicy; the commit create-if-
        # absent is NEVER retried (ambiguity is reconciled in the txn
        # layer via commitInfo.txnId instead).
        "delta.tpu.storage.retry.enabled": True,
        "delta.tpu.storage.retry.maxAttempts": 5,
        "delta.tpu.storage.retry.baseDelayMs": 20,
        "delta.tpu.storage.retry.maxDelayMs": 1000,
        # Total wall-clock bound across attempts+sleeps of one op: a
        # flapping store fails in bounded time.
        "delta.tpu.storage.retry.deadlineMs": 15_000,
        # Metadata cleanup also sweeps aged .{name}.{uuid}.tmp staging
        # orphans (crashed writers) from _delta_log; younger files may be
        # in-flight writes and are kept.
        "delta.tpu.cleanup.tmpOrphanTtlMs": 3_600_000,
        # Named-table catalog (catalog/catalog.py): persistence path (None
        # = in-memory only) and how long an in-flight foreign-host CREATE
        # claim stays live before the name is forfeited.
        "delta.tpu.catalog.path": None,
        "delta.tpu.catalog.claimTimeoutMs": 600_000,
        # Multi-host barrier/gather timeout (parallel/distributed).
        "delta.tpu.distributed.timeoutMs": 600_000,
        # Sharded work-item executor (parallel/executor): worker count
        # (None = min(8, cpu count)) and deque work stealing for the
        # zipf hot-shard case.
        "delta.tpu.distributed.workers": None,
        "delta.tpu.distributed.workStealing.enabled": True,
        # shard_map scan planning (ops/state_cache sharded lanes): "auto"
        # prices sharded-vs-single with the per-shard link constants,
        # "force"/"off" pin the choice.
        "delta.tpu.distributed.plan.enabled": True,
        "delta.tpu.distributed.plan.mode": "auto",
        # Distributed OPTIMIZE: rewrite bin-pack groups on executor
        # workers (None = delta.tpu.distributed.workers).
        "delta.tpu.distributed.optimize.workers": None,
        # Distributed MERGE: probe candidate files for touched ones on
        # executor workers before the join (Spark's findTouchedFiles job);
        # minFiles gates the fan-out below which inline always wins.
        "delta.tpu.distributed.merge.probe.enabled": True,
        "delta.tpu.distributed.merge.probe.minFiles": 8,
        # Funnel distributed-job commits through the group-commit
        # coordinator (txn/group_commit) as the single-writer fan-in.
        "delta.tpu.distributed.singleWriterFanIn": True,
        # Per-item transient retry inside the sharded executor
        # (parallel/executor): bounded attempts + a total per-item
        # deadline via the shared utils/retries.RetryPolicy. Only
        # Exceptions classified transient retry; permanent failures
        # quarantine or abort per the job's on_failure policy.
        "delta.tpu.distributed.retry.maxAttempts": 3,
        "delta.tpu.distributed.retry.baseDelayMs": 10,
        "delta.tpu.distributed.retry.maxDelayMs": 200,
        "delta.tpu.distributed.retry.deadlineMs": 10_000,
        # Stuck-item supervision: the delta-dist-supervisor thread marks
        # items whose heartbeat age exceeds max(itemTimeoutMs, measured
        # ms/byte x LPT byte estimate x slackFactor) — the floor is a
        # conf, the effective timeout is priced per item — and
        # speculatively re-dispatches them to an idle worker,
        # first-completion-wins. itemTimeoutMs <= 0 disables supervision.
        "delta.tpu.distributed.itemTimeoutMs": 120_000,
        "delta.tpu.distributed.speculation.enabled": True,
        "delta.tpu.distributed.speculation.slackFactor": 4.0,
        "delta.tpu.distributed.supervisor.intervalMs": 25,
        # Multihost orphaned-slice recovery (parallel/leases): hosts in a
        # distributed OPTIMIZE write heartbeat lease files under
        # _delta_log/_dist/; after fan-in the coordinator re-executes
        # slices whose lease expired (ttlMs past the last heartbeat)
        # without being cleared. Leases are local-file IO like the
        # journal; object-store tables skip them.
        "delta.tpu.distributed.lease.enabled": True,
        "delta.tpu.distributed.lease.ttlMs": 60_000,
        # How long the coordinator lingers after its own commit waiting
        # for peer leases to APPEAR before concluding there are none — a
        # peer that dies pre-lease lost no committed data, so the wait is
        # deliberately short; once a lease is seen, it is tracked to
        # clear/expiry regardless of this window.
        "delta.tpu.distributed.lease.settleMs": 250,
        # DML writes per-file deletion vectors instead of rewriting files
        # when the table enables them (commands/dml_common).
        "delta.tpu.deletionVectors.enabled": True,
        # Network object stores (storage/logstore): the HTTP endpoint for
        # s3/gs schemes (required — no silent local fallback) and the
        # conditional-PUT dialect (None = auto by scheme).
        "delta.tpu.storage.objectStore.endpoint": None,
        "delta.tpu.storage.objectStore.dialect": None,
        # Persistent XLA compilation cache directory (utils/jaxcache).
        # None = ~/.cache/delta_tpu/xla; empty string disables.
        "delta.tpu.xla.cacheDir": None,
        # Autopilot maintenance scheduler (delta_tpu/autopilot): closes the
        # observe→decide→act→audit loop over the doctor's remedies and the
        # advisor's recommendations. Strictly opt-in: the daemon only runs
        # when enabled=true AND start() is called, and even then dryRun
        # (default ON) journals the plan without executing anything.
        "delta.tpu.autopilot.enabled": False,
        "delta.tpu.autopilot.dryRun": True,
        # Daemon tick interval between maintenance passes over the
        # registered tables.
        "delta.tpu.autopilot.intervalMs": 60_000,
        # Per-run cost caps: total bytes an OPTIMIZE/ZORDER/PURGE may
        # select for rewrite (over-budget jobs abort pre-IO with a
        # journaled SKIPPED outcome), wall-clock budget across a run's
        # actions, and how many actions one run may execute.
        "delta.tpu.autopilot.maxBytesPerRun": 2 << 30,
        "delta.tpu.autopilot.budgetMs": 300_000,
        "delta.tpu.autopilot.maxActionsPerRun": 4,
        # Per-action cooldown: an ATTEMPTED action (started / executed /
        # failed / interrupted) is not re-planned for this long — also the
        # crash-loop guard, since "started" ledger entries are flushed to
        # disk before execution.
        "delta.tpu.autopilot.cooldownMs": 6 * 3_600_000,
        # After a maintenance commit loses to a foreground writer, the
        # whole table backs off for this long.
        "delta.tpu.autopilot.contentionBackoffMs": 300_000,
        # Quiet-window pick: execute only when the journal shows at most
        # quietMaxCommits foreground commits inside the last quietWindowMs
        # (the same 60s bucketing the advisor's contention analysis uses).
        "delta.tpu.autopilot.quietWindowMs": 60_000,
        "delta.tpu.autopilot.quietMaxCommits": 0,
        # Maintenance commits lose gracefully: attempts are capped at this
        # (txn.transaction.commit_attempts_cap) instead of retry-storming
        # through delta.tpu.maxCommitAttempts against foreground writers.
        "delta.tpu.autopilot.maxCommitAttempts": 3,
        # Shadow-validation guardrail: when on, rewrite-class actions
        # (OPTIMIZE/ZORDER/PURGE) whose selection exceeds
        # requireShadowMinBytes only execute once a journaled shadow run
        # CONFIRMED them — refuted candidates are suppressed with the
        # measured deltas cited, untested ones deferred until a shadow run
        # exists. 0 gates every rewrite; unknown sizes are treated as over
        # the threshold (fail closed).
        "delta.tpu.autopilot.requireShadow": False,
        "delta.tpu.autopilot.requireShadowMinBytes": 0,
        # After an executed ZORDER, audit the realized effect by replaying
        # the shadow run's trace against the live table (replay/shadow.
        # realized_audit) instead of reporting a pending longitudinal
        # verdict.
        "delta.tpu.autopilot.shadowAudit": True,
    }

    def __init__(self):
        self._values: Dict[str, Any] = {}
        self._lock = threading.RLock()
        self._generation = 0

    def generation(self) -> int:
        """Monotonic mutation counter, bumped on every set/unset (including
        ``set_temporarily`` enter/exit). Hot paths cache conf-derived values
        keyed on this instead of paying a locked lookup per call."""
        return self._generation

    def get(self, key: str, default: Any = None) -> Any:
        with self._lock:
            if key in self._values:
                return self._values[key]
        if key in self._DEFAULTS:
            return self._DEFAULTS[key]
        return default

    def get_bool(self, key: str, default: bool = False) -> bool:
        """Boolean conf with string coercion: "false"/"0"/"off" (any case)
        are False — a raw ``bool(conf.get(...))`` treats "false" as truthy."""
        v = self.get(key, default)
        if isinstance(v, str):
            return v.strip().lower() not in ("false", "0", "off", "no", "")
        return bool(v)

    def get_int(self, key: str, default: int = 0) -> int:
        """Integer conf with coercion; malformed user-set values fall back
        to ``default`` (for registered keys the registry default makes
        None impossible). One helper so numeric-guardrail readers don't
        each re-implement the try/int dance."""
        v = self.get(key, default)
        try:
            return int(v)
        except (TypeError, ValueError):
            return int(default)

    def set(self, key: str, value: Any) -> None:
        with self._lock:
            self._values[key] = value
            self._generation += 1

    def unset(self, key: str) -> None:
        with self._lock:
            self._values.pop(key, None)
            self._generation += 1

    def set_temporarily(self, **kv: Any):
        """Context manager: ``with conf.set_temporarily(**{'k': v}): ...``"""
        outer = self

        class _Ctx:
            def __enter__(self):
                self._saved = {}
                for k, v in kv.items():
                    key = k.replace("__", ".")
                    with outer._lock:
                        self._saved[key] = outer._values.get(key, _MISSING)
                        outer._values[key] = v
                        outer._generation += 1
                return outer

            def __exit__(self, *exc):
                for key, old in self._saved.items():
                    with outer._lock:
                        if old is _MISSING:
                            outer._values.pop(key, None)
                        else:
                            outer._values[key] = old
                        outer._generation += 1
                return False

        return _Ctx()


_MISSING = object()
conf = SqlConf()


# ---------------------------------------------------------------------------
# Interval parsing (CalendarInterval subset: "interval N unit [N unit ...]")
# ---------------------------------------------------------------------------

_UNIT_MS = {
    "millisecond": 1,
    "second": 1000,
    "minute": 60_000,
    "hour": 3_600_000,
    "day": 86_400_000,
    "week": 7 * 86_400_000,
}

_INTERVAL_RE = re.compile(r"(-?\d+)\s+(millisecond|second|minute|hour|day|week)s?", re.IGNORECASE)


def parse_interval_ms(s: str) -> int:
    """Parse ``"interval 30 days"``-style durations to millis. Months/years are
    rejected, matching ``DeltaConfigs.isValidIntervalConfigValue`` which bans
    non-fixed durations."""
    text = s.strip()
    if text.lower().startswith("interval"):
        text = text[len("interval"):]
    ms = 0
    matched = False
    for m in _INTERVAL_RE.finditer(text):
        matched = True
        ms += int(m.group(1)) * _UNIT_MS[m.group(2).lower()]
    if not matched:
        raise DeltaIllegalArgumentError(f"Invalid interval: {s!r}")
    if ms < 0:
        raise DeltaIllegalArgumentError(f"Interval must be non-negative: {s!r}")
    return ms


# ---------------------------------------------------------------------------
# Table properties
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class DeltaConfig(Generic[T]):
    key: str  # full key incl. "delta." prefix
    default: str
    from_string: Callable[[str], T]
    validate: Optional[Callable[[T], bool]] = None
    help: str = ""

    @property
    def _session_default_key(self) -> str:
        return f"delta.tpu.properties.defaults.{self.key[len('delta.'):]}"

    def is_explicit(self, metadata) -> bool:
        """True when the table (or the session defaults tier) sets this
        property, i.e. :meth:`from_metadata` would NOT fall back to the
        built-in default."""
        return ((metadata.configuration or {}).get(self.key) is not None
                or conf.get(self._session_default_key) is not None)

    def from_metadata(self, metadata) -> T:
        raw = (metadata.configuration or {}).get(self.key)
        if raw is None:
            raw = conf.get(self._session_default_key)
        if raw is None:
            raw = self.default
        try:
            value = self.from_string(str(raw))
        except DeltaIllegalArgumentError:
            raise
        except (ValueError, TypeError) as e:
            raise DeltaIllegalArgumentError(
                f"Invalid value {raw!r} for table property {self.key}: {e}"
            )
        if self.validate and not self.validate(value):
            raise DeltaIllegalArgumentError(
                f"Invalid value {raw!r} for table property {self.key}"
            )
        return value


def _bool(s: str) -> bool:
    if s.lower() in ("true", "1"):
        return True
    if s.lower() in ("false", "0"):
        return False
    raise ValueError(f"not a boolean: {s!r}")


class DeltaConfigs:
    """Registry of table properties (``DeltaConfig.scala:227-433``)."""

    LOG_RETENTION = DeltaConfig(
        "delta.logRetentionDuration", "interval 30 days", parse_interval_ms,
        help="How long commit/checkpoint files are kept before cleanup.",
    )
    TOMBSTONE_RETENTION = DeltaConfig(
        "delta.deletedFileRetentionDuration", "interval 1 week", parse_interval_ms,
        help="How long RemoveFile tombstones (and their data files) are kept.",
    )
    CHECKPOINT_INTERVAL = DeltaConfig(
        "delta.checkpointInterval", "10", int, lambda v: v > 0,
        help="Checkpoint every N commits.",
    )
    ENABLE_EXPIRED_LOG_CLEANUP = DeltaConfig(
        "delta.enableExpiredLogCleanup", "true", _bool,
    )
    IS_APPEND_ONLY = DeltaConfig(
        "delta.appendOnly", "false", _bool,
        help="When true, deletes/updates are rejected (protocol writer v2 feature).",
    )
    ISOLATION_LEVEL = DeltaConfig(
        "delta.isolationLevel", "WriteSerializable", str,
        lambda v: v in ("Serializable", "WriteSerializable"),
        help="Write isolation for data-changing commits "
             "(isolationLevels.scala:27-91).",
    )
    ENABLE_DELETION_VECTORS = DeltaConfig(
        "delta.tpu.enableDeletionVectors", "false", _bool,
        help="DML marks deleted rows in per-file deletion vectors instead of "
             "rewriting whole files (beyond-reference feature; bumps the "
             "table protocol to (3, 7)).",
    )
    CHECKPOINT_WRITE_STATS_AS_JSON = DeltaConfig(
        "delta.checkpoint.writeStatsAsJson", "true", _bool,
    )
    CHECKPOINT_WRITE_STATS_AS_STRUCT = DeltaConfig(
        "delta.checkpoint.writeStatsAsStruct", "false", _bool,
    )
    DATA_SKIPPING_NUM_INDEXED_COLS = DeltaConfig(
        "delta.dataSkippingNumIndexedCols", "32", int, lambda v: v >= -1,
        help="First N schema columns get min/max/nullCount stats (-1 = all).",
    )
    SYMLINK_FORMAT_MANIFEST_ENABLED = DeltaConfig(
        "delta.compatibility.symlinkFormatManifest.enabled", "false", _bool,
    )
    RANDOMIZE_FILE_PREFIXES = DeltaConfig(
        "delta.randomizeFilePrefixes", "false", _bool,
    )
    RANDOM_PREFIX_LENGTH = DeltaConfig(
        "delta.randomPrefixLength", "2", int, lambda v: v > 0,
    )
    CHANGE_DATA_FEED = DeltaConfig(
        "delta.enableChangeDataFeed", "false", _bool,
        help="Write change-data files for UPDATE/DELETE/MERGE.",
    )
    MIN_READER_VERSION = DeltaConfig(
        "delta.minReaderVersion", "1", int, lambda v: v > 0,
    )
    MIN_WRITER_VERSION = DeltaConfig(
        "delta.minWriterVersion", "2", int, lambda v: v > 0,
    )

    _ALL: Dict[str, DeltaConfig] = {}

    @classmethod
    def all_configs(cls) -> Dict[str, DeltaConfig]:
        if not cls._ALL:
            for name in dir(cls):
                v = getattr(cls, name)
                if isinstance(v, DeltaConfig):
                    cls._ALL[v.key.lower()] = v
        return cls._ALL

    @classmethod
    def validate_configuration(cls, configuration: Dict[str, str]) -> Dict[str, str]:
        """Type-check user-provided ``delta.*`` keys; unknown ``delta.`` keys
        are rejected (``DeltaConfig.scala verifyTableProperties``)."""
        registry = cls.all_configs()
        out = {}
        for k, v in configuration.items():
            lk = k.lower()
            if lk.startswith("delta."):
                cfg = registry.get(lk)
                if cfg is None:
                    # The reference allows unknown keys through when they match
                    # no validator only for forward-compat "delta.constraints.*"
                    # and arbitrary user keys are kept; constraints use this.
                    if lk.startswith("delta.constraints."):
                        out[k] = v
                        continue
                    raise DeltaIllegalArgumentError(f"Unknown configuration was specified: {k}")
                # run the parser for validation, store canonical key
                probe = Metadata_probe(configuration={cfg.key: v})
                cfg.from_metadata(probe)
                out[cfg.key] = v
            else:
                out[k] = v
        return out

    @classmethod
    def merge_global_configs(cls, configuration: Dict[str, str]) -> Dict[str, str]:
        """Apply session-level defaults ``delta.tpu.properties.defaults.*``
        for keys the user didn't set (``DeltaConfig.mergeGlobalConfigs``)."""
        out = dict(configuration)
        for cfg in cls.all_configs().values():
            if cfg.key in out:
                continue
            default = conf.get(f"delta.tpu.properties.defaults.{cfg.key[len('delta.'):]}" )
            if default is not None:
                out[cfg.key] = str(default)
        return out


class Metadata_probe:
    """Minimal object exposing .configuration for DeltaConfig.from_metadata."""

    def __init__(self, configuration: Dict[str, str]):
        self.configuration = configuration
