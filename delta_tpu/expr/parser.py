"""SQL-ish predicate/expression parser.

The reference parses predicates through Spark's SQL parser
(``DeltaCommand.parsePredicates``, ``commands/DeltaCommand.scala:48-59``);
this is our equivalent for strings like ``"date > '2020-01-01' AND id IN
(1,2,3)"`` used by delete/update/merge/replaceWhere/constraints.

Grammar (Pratt parser, precedence low→high):
    OR < AND < NOT < comparison (= == != <> < <= > >= <=> IS IN BETWEEN LIKE)
    < additive (+ -) < multiplicative (* / %) < unary (- NOT) < primary
"""
from __future__ import annotations

import re
from typing import List, Optional

from delta_tpu.expr import ir
from delta_tpu.schema.types import parse_data_type
from delta_tpu.utils.errors import DeltaAnalysisError
from delta_tpu.utils import errors

__all__ = ["parse_expression", "parse_predicate"]

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<num>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+(?:[eE][+-]?\d+)?[LlDd]?)
  | (?P<str>'(?:[^']|'')*'|"(?:[^"]|"")*")
  | (?P<bq>`(?:[^`]|``)+`)
  | (?P<op><=>|==|!=|<>|<=|>=|<|>|=|\+|-|\*|/|%|\(|\)|,|\.)
  | (?P<id>[A-Za-z_][A-Za-z0-9_]*)
    """,
    re.VERBOSE,
)

_KEYWORDS = {
    "AND", "OR", "NOT", "IN", "IS", "NULL", "TRUE", "FALSE", "BETWEEN",
    "LIKE", "CAST", "AS", "CASE", "WHEN", "THEN", "ELSE", "END",
}


class _Tok:
    def __init__(self, kind: str, text: str):
        self.kind = kind  # num | str | id | kw | op | bq
        self.text = text

    def __repr__(self):
        return f"{self.kind}:{self.text}"


def _tokenize(s: str) -> List[_Tok]:
    out: List[_Tok] = []
    pos = 0
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if not m:
            raise errors.cannot_tokenize_predicate(s[pos:pos+20])
        pos = m.end()
        if m.lastgroup == "ws":
            continue
        text = m.group()
        kind = m.lastgroup
        if kind == "id" and text.upper() in _KEYWORDS:
            out.append(_Tok("kw", text.upper()))
        else:
            out.append(_Tok(kind, text))
    return out


class _Parser:
    def __init__(self, tokens: List[_Tok], source: str):
        self.toks = tokens
        self.i = 0
        self.source = source

    def peek(self, k: int = 0) -> Optional[_Tok]:
        j = self.i + k
        return self.toks[j] if j < len(self.toks) else None

    def next(self) -> _Tok:
        t = self.peek()
        if t is None:
            raise errors.unexpected_end_of_expression(self.source)
        self.i += 1
        return t

    def accept(self, kind: str, text: Optional[str] = None) -> Optional[_Tok]:
        t = self.peek()
        if t and t.kind == kind and (text is None or t.text == text):
            self.i += 1
            return t
        return None

    def expect(self, kind: str, text: Optional[str] = None) -> _Tok:
        t = self.accept(kind, text)
        if t is None:
            raise errors.parse_expected(text or kind, self.peek(), self.source)
        return t

    # precedence climbing ------------------------------------------------

    def parse(self) -> ir.Expression:
        e = self.parse_or()
        if self.peek() is not None:
            raise errors.trailing_tokens(self.peek(), self.source)
        return e

    def parse_or(self) -> ir.Expression:
        left = self.parse_and()
        while self.accept("kw", "OR"):
            left = ir.Or(left, self.parse_and())
        return left

    def parse_and(self) -> ir.Expression:
        left = self.parse_not()
        while self.accept("kw", "AND"):
            left = ir.And(left, self.parse_not())
        return left

    def parse_not(self) -> ir.Expression:
        if self.accept("kw", "NOT"):
            return ir.Not(self.parse_not())
        return self.parse_comparison()

    def parse_comparison(self) -> ir.Expression:
        left = self.parse_additive()
        t = self.peek()
        if t is None:
            return left
        if t.kind == "op" and t.text in ("=", "==", "!=", "<>", "<", "<=", ">", ">=", "<=>"):
            self.next()
            right = self.parse_additive()
            return {
                "=": ir.Eq, "==": ir.Eq, "!=": ir.Ne, "<>": ir.Ne,
                "<": ir.Lt, "<=": ir.Le, ">": ir.Gt, ">=": ir.Ge,
                "<=>": ir.NullSafeEq,
            }[t.text](left, right)
        if t.kind == "kw" and t.text == "IS":
            self.next()
            negate = self.accept("kw", "NOT") is not None
            self.expect("kw", "NULL")
            return ir.IsNotNull(left) if negate else ir.IsNull(left)
        negate = False
        if t.kind == "kw" and t.text == "NOT" and self.peek(1) and self.peek(1).kind == "kw" \
                and self.peek(1).text in ("IN", "BETWEEN", "LIKE"):
            self.next()
            negate = True
            t = self.peek()
        if t and t.kind == "kw" and t.text == "IN":
            self.next()
            self.expect("op", "(")
            opts = [self.parse_additive()]
            while self.accept("op", ","):
                opts.append(self.parse_additive())
            self.expect("op", ")")
            e: ir.Expression = ir.In(left, opts)
            return ir.Not(e) if negate else e
        if t and t.kind == "kw" and t.text == "BETWEEN":
            self.next()
            lo = self.parse_additive()
            self.expect("kw", "AND")
            hi = self.parse_additive()
            e = ir.And(ir.Ge(left, lo), ir.Le(left, hi))
            return ir.Not(e) if negate else e
        if t and t.kind == "kw" and t.text == "LIKE":
            self.next()
            e = ir.Like(left, self.parse_additive())
            return ir.Not(e) if negate else e
        return left

    def parse_additive(self) -> ir.Expression:
        left = self.parse_multiplicative()
        while True:
            t = self.peek()
            if t and t.kind == "op" and t.text in ("+", "-"):
                self.next()
                right = self.parse_multiplicative()
                left = (ir.Add if t.text == "+" else ir.Sub)(left, right)
            else:
                return left

    def parse_multiplicative(self) -> ir.Expression:
        left = self.parse_unary()
        while True:
            t = self.peek()
            if t and t.kind == "op" and t.text in ("*", "/", "%"):
                self.next()
                right = self.parse_unary()
                left = {"*": ir.Mul, "/": ir.Div, "%": ir.Mod}[t.text](left, right)
            else:
                return left

    def parse_unary(self) -> ir.Expression:
        if self.accept("op", "-"):
            return ir.Neg(self.parse_unary())
        if self.accept("op", "+"):
            return self.parse_unary()
        return self.parse_primary()

    def parse_primary(self) -> ir.Expression:
        t = self.next()
        if t.kind == "num":
            text = t.text
            if text[-1] in "LlDd" and not text[-1].isdigit():
                suffix, text = text[-1].lower(), text[:-1]
                return ir.Literal(int(text) if suffix == "l" else float(text))
            if "." in text or "e" in text.lower():
                return ir.Literal(float(text))
            return ir.Literal(int(text))
        if t.kind == "str":
            q = t.text[0]
            return ir.Literal(t.text[1:-1].replace(q * 2, q))
        if t.kind == "kw":
            if t.text == "NULL":
                return ir.Literal(None)
            if t.text == "TRUE":
                return ir.Literal(True)
            if t.text == "FALSE":
                return ir.Literal(False)
            if t.text == "CAST":
                self.expect("op", "(")
                e = self.parse_or()
                self.expect("kw", "AS")
                type_name = self._parse_type_name()
                self.expect("op", ")")
                return ir.Cast(e, parse_data_type(type_name))
            if t.text == "CASE":
                branches = []
                while self.accept("kw", "WHEN"):
                    c = self.parse_or()
                    self.expect("kw", "THEN")
                    v = self.parse_or()
                    branches.append((c, v))
                default = None
                if self.accept("kw", "ELSE"):
                    default = self.parse_or()
                self.expect("kw", "END")
                return ir.CaseWhen(branches, default)
            if t.text == "NOT":
                return ir.Not(self.parse_not())
            raise errors.unexpected_keyword(t.text, self.source)
        if t.kind == "op" and t.text == "(":
            e = self.parse_or()
            self.expect("op", ")")
            return e
        if t.kind in ("id", "bq"):
            name = t.text[1:-1].replace("``", "`") if t.kind == "bq" else t.text
            # function call?
            if t.kind == "id" and self.peek() and self.peek().kind == "op" and self.peek().text == "(":
                self.next()
                args: List[ir.Expression] = []
                if not self.accept("op", ")"):
                    args.append(self.parse_or())
                    while self.accept("op", ","):
                        args.append(self.parse_or())
                    self.expect("op", ")")
                lname = name.lower()
                if lname == "coalesce":
                    return ir.Coalesce(*args)
                if lname == "startswith" and len(args) == 2:
                    return ir.StartsWith(args[0], args[1])
                return ir.Func(name, args)
            # dotted column path → single column name "a.b.c"
            parts = [name]
            while self.peek() and self.peek().kind == "op" and self.peek().text == ".":
                self.next()
                nxt = self.next()
                if nxt.kind not in ("id", "bq"):
                    raise errors.bad_column_path(self.source)
                parts.append(nxt.text[1:-1].replace("``", "`") if nxt.kind == "bq" else nxt.text)
            return ir.Column(".".join(parts))
        raise errors.unexpected_token(t, self.source)

    def _parse_type_name(self) -> str:
        tok = self.next()
        if tok.kind not in ("id", "kw"):
            raise errors.expected_type_name(tok)
        name = tok.text.lower()
        if name == "decimal" and self.accept("op", "("):
            p = self.next().text
            self.expect("op", ",")
            s = self.next().text
            self.expect("op", ")")
            return f"decimal({p},{s})"
        return name


def parse_expression(s: str) -> ir.Expression:
    if isinstance(s, ir.Expression):
        return s
    return _Parser(_tokenize(s), s).parse()


def parse_predicate(s: str) -> ir.Expression:
    """Alias with intent: the result is used as a boolean filter."""
    return parse_expression(s)
