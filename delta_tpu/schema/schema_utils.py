"""Schema enforcement & evolution rules.

Reference: ``schema/SchemaUtils.scala`` (1,112 lines — the behavioral spec,
per SURVEY §7 "Hard parts"). Key semantics reproduced here:

* column-name hygiene (``checkFieldNames :1049``);
* case-insensitive (but case-preserving) column resolution;
* write-compatibility enforcement: data columns must exist in the table
  schema unless ``mergeSchema`` evolution is requested;
* ``merge_schemas`` (``:817``): recursive struct/array/map merge, new fields
  appended at the end, NullType upgraded, type conflicts rejected (with an
  opt-in widening lattice for CONVERT's parquet import);
* ``is_read_compatible`` (``:265``) for streaming schema-change detection;
* ALTER helpers: add/drop column at a position, ``can_change_data_type``.
"""
from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

from delta_tpu.schema.types import (
    ArrayType,
    AtomicType,
    ByteType,
    DataType,
    DoubleType,
    FloatType,
    IntegerType,
    LongType,
    MapType,
    NullType,
    ShortType,
    StructField,
    StructType,
)
from delta_tpu.utils.errors import DeltaAnalysisError, SchemaMismatchError

__all__ = [
    "check_column_names",
    "check_partition_columns",
    "find_field",
    "merge_schemas",
    "enforce_write_compatibility",
    "normalize_column_names",
    "is_read_compatible",
    "add_column",
    "drop_column",
    "can_change_data_type",
    "column_path_to_name",
]

# checkFieldNames (SchemaUtils.scala:1049): these break Parquet/Hive paths.
_INVALID_CHARS = set(' ,;{}()\n\t=')


def check_column_names(schema: StructType) -> None:
    def walk(dt: DataType, path: str):
        if isinstance(dt, StructType):
            for f in dt.fields:
                bad = [c for c in f.name if c in _INVALID_CHARS]
                if bad:
                    raise DeltaAnalysisError(
                        f"Attribute name \"{path + f.name}\" contains invalid character(s) "
                        f"among \" ,;{{}}()\\n\\t=\". Please use alias to rename it."
                    )
                walk(f.data_type, path + f.name + ".")
        elif isinstance(dt, ArrayType):
            walk(dt.element_type, path)
        elif isinstance(dt, MapType):
            walk(dt.key_type, path)
            walk(dt.value_type, path)

    walk(schema, "")


def check_partition_columns(partition_columns: Sequence[str], schema: StructType) -> None:
    names = {f.name.lower() for f in schema.fields}
    for c in partition_columns:
        if c.lower() not in names:
            raise DeltaAnalysisError(
                f"Partition column `{c}` not found in schema {schema.simple_string()}"
            )


def find_field(schema: StructType, name: str) -> Optional[StructField]:
    """Case-insensitive lookup; dotted names traverse nested structs."""
    parts = name.split(".")
    current: DataType = schema
    field = None
    for p in parts:
        if not isinstance(current, StructType):
            return None
        field = next((f for f in current.fields if f.name.lower() == p.lower()), None)
        if field is None:
            return None
        current = field.data_type
    return field


def column_path_to_name(path: Sequence[str]) -> str:
    return ".".join(path)


# ---------------------------------------------------------------------------
# Schema merging (evolution)
# ---------------------------------------------------------------------------

# Opt-in widening for parquet imports (CONVERT TO DELTA), matching the
# allowed conversions in mergeSchemas(allowImplicitConversions=true).
_WIDENING: List[Tuple[type, type]] = [
    (ByteType, ShortType),
    (ByteType, IntegerType),
    (ByteType, LongType),
    (ShortType, IntegerType),
    (ShortType, LongType),
    (IntegerType, LongType),
    (FloatType, DoubleType),
]


def _can_widen(from_t: DataType, to_t: DataType) -> bool:
    return any(isinstance(from_t, a) and isinstance(to_t, b) for a, b in _WIDENING)


def merge_schemas(
    current: StructType,
    new: StructType,
    allow_implicit_conversions: bool = False,
    path: str = "",
) -> StructType:
    """Merge ``new`` into ``current``: existing columns keep the current
    type/position/case, new columns are appended (``SchemaUtils.scala:817``)."""
    merged: List[StructField] = []
    new_by_lower = {f.name.lower(): f for f in new.fields}
    for cur in current.fields:
        incoming = new_by_lower.pop(cur.name.lower(), None)
        if incoming is None:
            merged.append(cur)
            continue
        merged_type = _merge_types(
            cur.data_type, incoming.data_type, allow_implicit_conversions,
            path + cur.name,
        )
        metadata = dict(cur.metadata)
        if incoming.metadata:
            metadata.update(incoming.metadata)
        merged.append(
            StructField(cur.name, merged_type, cur.nullable or incoming.nullable, metadata)
        )
    # Append genuinely new fields, preserving their order in `new`.
    remaining = set(new_by_lower)
    for f in new.fields:
        if f.name.lower() in remaining:
            merged.append(f)
    return StructType(merged)


def _merge_types(cur: DataType, new: DataType, widen: bool, path: str) -> DataType:
    if isinstance(cur, StructType) and isinstance(new, StructType):
        return merge_schemas(cur, new, widen, path + ".")
    if isinstance(cur, ArrayType) and isinstance(new, ArrayType):
        return ArrayType(
            _merge_types(cur.element_type, new.element_type, widen, path + ".element"),
            cur.contains_null or new.contains_null,
        )
    if isinstance(cur, MapType) and isinstance(new, MapType):
        return MapType(
            _merge_types(cur.key_type, new.key_type, widen, path + ".key"),
            _merge_types(cur.value_type, new.value_type, widen, path + ".value"),
            cur.value_contains_null or new.value_contains_null,
        )
    if isinstance(cur, NullType):
        return new
    if isinstance(new, NullType):
        return cur
    if cur == new:
        return cur
    if widen and _can_widen(new, cur):
        return cur
    if widen and _can_widen(cur, new):
        return new
    raise SchemaMismatchError(
        f"Failed to merge fields '{path}': incompatible types "
        f"{cur.simple_string()} and {new.simple_string()}"
    )


# ---------------------------------------------------------------------------
# Write enforcement
# ---------------------------------------------------------------------------

def enforce_write_compatibility(table_schema: StructType, data_schema: StructType) -> None:
    """Reject writes whose columns don't exist in the table (the
    ``A schema mismatch detected`` error family). Missing table columns in
    the data are fine (filled with nulls). Type equality is checked for
    overlapping columns (after normalization casts are the writer's job)."""
    extra = []
    mismatched = []
    table_by_lower = {f.name.lower(): f for f in table_schema.fields}
    for f in data_schema.fields:
        t = table_by_lower.get(f.name.lower())
        if t is None:
            extra.append(f.name)
        elif not _write_type_compatible(f.data_type, t.data_type):
            mismatched.append(
                f"{f.name}: data {f.data_type.simple_string()} vs table {t.data_type.simple_string()}"
            )
    if extra or mismatched:
        raise SchemaMismatchError(
            "A schema mismatch detected when writing to the Delta table.\n"
            + (f"Data columns not in the table schema: {extra}.\n" if extra else "")
            + (f"Type mismatches: {mismatched}.\n" if mismatched else "")
            + "To allow schema migration, set option mergeSchema=true."
        )


def _write_type_compatible(data_t: DataType, table_t: DataType) -> bool:
    """Data can be written into the table column: equal type, NullType, or an
    implicit numeric widening the write path will cast."""
    if data_t == table_t or isinstance(data_t, NullType):
        return True
    if _can_widen(data_t, table_t):
        return True
    if isinstance(data_t, StructType) and isinstance(table_t, StructType):
        table_by_lower = {f.name.lower(): f for f in table_t.fields}
        for f in data_t.fields:
            t = table_by_lower.get(f.name.lower())
            if t is None or not _write_type_compatible(f.data_type, t.data_type):
                return False
        return True
    if isinstance(data_t, ArrayType) and isinstance(table_t, ArrayType):
        return _write_type_compatible(data_t.element_type, table_t.element_type)
    if isinstance(data_t, MapType) and isinstance(table_t, MapType):
        return _write_type_compatible(data_t.key_type, table_t.key_type) and _write_type_compatible(
            data_t.value_type, table_t.value_type
        )
    return False


def normalize_column_names(table_schema: StructType, data_schema: StructType) -> List[Tuple[str, str]]:
    """(data_name, table_name) casing fixups (``normalizeColumnNames :223``)."""
    out = []
    table_by_lower = {f.name.lower(): f for f in table_schema.fields}
    for f in data_schema.fields:
        t = table_by_lower.get(f.name.lower())
        if t is not None and t.name != f.name:
            out.append((f.name, t.name))
    return out


def is_read_compatible(existing: StructType, new: StructType) -> bool:
    """Can data written with ``existing`` still be read as ``new``?
    (``isReadCompatible :265``) — new must contain every existing column with
    the same type and must not tighten nullability."""
    new_by_lower = {f.name.lower(): f for f in new.fields}
    for f in existing.fields:
        n = new_by_lower.get(f.name.lower())
        if n is None:
            return False
        if not _type_read_compatible(f.data_type, n.data_type):
            return False
        if f.nullable and not n.nullable:
            return False
    return True


def _type_read_compatible(old: DataType, new: DataType) -> bool:
    if isinstance(old, StructType) and isinstance(new, StructType):
        return is_read_compatible(old, new)
    if isinstance(old, ArrayType) and isinstance(new, ArrayType):
        return _type_read_compatible(old.element_type, new.element_type)
    if isinstance(old, MapType) and isinstance(new, MapType):
        return _type_read_compatible(old.key_type, new.key_type) and _type_read_compatible(
            old.value_type, new.value_type
        )
    return old == new


# ---------------------------------------------------------------------------
# ALTER helpers
# ---------------------------------------------------------------------------

def add_column(schema: StructType, field: StructField, position: Optional[int] = None) -> StructType:
    """Insert a top-level column at ``position`` (``addColumn :573``)."""
    if any(f.name.lower() == field.name.lower() for f in schema.fields):
        raise DeltaAnalysisError(f"Column {field.name} already exists")
    fields = list(schema.fields)
    if position is None or position >= len(fields):
        fields.append(field)
    else:
        fields.insert(position, field)
    return StructType(fields)


def drop_column(schema: StructType, name: str) -> StructType:
    """Remove a top-level column (``dropColumn :663``)."""
    kept = [f for f in schema.fields if f.name.lower() != name.lower()]
    if len(kept) == len(schema.fields):
        raise DeltaAnalysisError(f"Column {name} does not exist")
    if not kept:
        raise DeltaAnalysisError("Cannot drop all columns from a table")
    return StructType(kept)


def can_change_data_type(from_t: DataType, to_t: DataType) -> bool:
    """ALTER CHANGE COLUMN type changes: NullType→anything, value-preserving
    numeric widening, or nested containers whose element change is legal.
    (Comment/nullability-loosening changes are handled by the caller.)

    Deliberate divergence from the reference (``SchemaUtils.scala:694``,
    which allows only NullType→anything and nested recursion): we also
    accept the ``_WIDENING`` lattice (byte→short→int→long, float→double).
    Widening is lossless, our Arrow read path casts old files up to the
    table schema on scan, and the write path normalizes new data to the
    widened type — so the strictness the reference needs to protect its
    fixed-width Parquet readers does not apply here.
    """
    if isinstance(from_t, NullType):
        return True
    if _can_widen(from_t, to_t):
        return True
    if isinstance(from_t, StructType) and isinstance(to_t, StructType):
        to_by_lower = {f.name.lower(): f for f in to_t.fields}
        for f in from_t.fields:
            t = to_by_lower.get(f.name.lower())
            if t is None or not can_change_data_type(f.data_type, t.data_type):
                return False
        return True
    if isinstance(from_t, ArrayType) and isinstance(to_t, ArrayType):
        return can_change_data_type(from_t.element_type, to_t.element_type)
    if isinstance(from_t, MapType) and isinstance(to_t, MapType):
        return can_change_data_type(from_t.key_type, to_t.key_type) and can_change_data_type(
            from_t.value_type, to_t.value_type
        )
    return from_t == to_t
