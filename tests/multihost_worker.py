"""Worker for the 2-process DCN integration test (`test_multihost.py`).

Each process joins a real `jax.distributed` CPU cluster, then drives the
engine's multi-host paths against a SHARED table directory — the
coordination model is the store, not RPC (SURVEY §2.8):

  scan        — each host decodes its strided partition of the file list
  checkpoint  — each host writes its slice of the parts; proc 0 publishes
                `_last_checkpoint` after all parts are visible
  convert     — each host footers/stats its slice; proc 0 gathers the
                fragments from the store and commits
  vacuum      — each host deletes its slice of the expired files

Results land in <out>/result-<proc>.json for the parent to assert.
"""
import json
import os
import sys


def main() -> None:
    proc = int(sys.argv[1])
    n_procs = int(sys.argv[2])
    port = sys.argv[3]
    table = sys.argv[4]
    convert_dir = sys.argv[5]
    out_dir = sys.argv[6]

    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    from delta_tpu.parallel import distributed as dist

    pid, count = dist.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=n_procs,
        process_id=proc,
    )
    assert (pid, count) == (proc, n_procs), (pid, count)

    from delta_tpu import DeltaLog
    from delta_tpu.exec.scan import scan_to_table
    from delta_tpu.log import checkpoints as ckpt_mod

    result = {"proc": proc, "count": count}

    # -- scan: this host's partition of the pruned file list --------------
    log = DeltaLog.for_table(table)
    snap = log.update()
    part = scan_to_table(snap, distribute=True)
    full = scan_to_table(snap)
    result["scan_rows"] = part.num_rows
    result["scan_ids"] = sorted(part.column("id").to_pylist())
    result["full_rows"] = full.num_rows

    # -- checkpoint: each host writes its slice of the parts --------------
    md = ckpt_mod.write_checkpoint(
        log.store, log.log_path, snap.version, snap.checkpoint_actions(),
        parts=4, distribute=True,
    )
    result["ckpt_parts"] = md.parts

    # -- convert: fragment exchange through the store ---------------------
    from delta_tpu.commands.convert import ConvertToDeltaCommand

    clog = DeltaLog.for_table(convert_dir)
    version = ConvertToDeltaCommand(
        clog, collect_stats=True, distribute=True
    ).run()
    result["convert_version"] = version
    DeltaLog.clear_cache()
    csnap = DeltaLog.for_table(convert_dir).update()
    result["convert_files"] = csnap.num_of_files

    with open(os.path.join(out_dir, f"result-{proc}.json"), "w") as f:
        json.dump(result, f)


if __name__ == "__main__":
    main()
