"""Telemetry-spans pass — migrated from ``tests/test_telemetry.py``.

Every public command entry point in ``delta_tpu/commands/`` (a class
``run()`` method, or a module-level function taking ``delta_log`` first)
must open a ``delta.dml.*`` or ``delta.utility.*`` span via
``record_operation`` — a new command cannot ship uninstrumented.

``span-missing``
    An entry point with no such span.
"""
from __future__ import annotations

import ast
from typing import List

from delta_tpu.analysis.core import AnalysisContext, AnalysisPass, Finding

__all__ = ["TelemetrySpansPass"]

EXEMPT_MODULES = frozenset({"__init__.py", "operations.py", "dml_common.py"})


def _record_operation_op_types(fn: ast.FunctionDef) -> List[str]:
    """All constant op-type strings passed to record_operation inside
    ``fn`` (including nested ``with`` bodies and helpers defined inline)."""
    out = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.With):
            continue
        for item in node.items:
            call = item.context_expr
            if not isinstance(call, ast.Call):
                continue
            callee = call.func
            name = (callee.id if isinstance(callee, ast.Name)
                    else callee.attr if isinstance(callee, ast.Attribute)
                    else None)
            if name != "record_operation" or not call.args:
                continue
            arg = call.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                out.append(arg.value)
    return out


class TelemetrySpansPass(AnalysisPass):
    name = "telemetry-spans"
    description = ("every command entry point opens a delta.dml.*/"
                   "delta.utility.* span")
    rules = ("span-missing",)

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        out: List[Finding] = []
        for sf in ctx.files:
            parts = sf.rel.split("/")
            if "commands" not in parts or parts[-1] in EXEMPT_MODULES:
                continue
            entry_points = []
            for node in sf.tree.body:
                if isinstance(node, ast.ClassDef):
                    for sub in node.body:
                        if isinstance(sub, ast.FunctionDef) \
                                and sub.name == "run":
                            entry_points.append((f"{node.name}.run", sub))
                elif isinstance(node, ast.FunctionDef):
                    if node.name.startswith("_"):
                        continue
                    args = [a.arg for a in node.args.args]
                    if args and args[0] == "delta_log":
                        entry_points.append((node.name, node))
            for label, fn in entry_points:
                ops = _record_operation_op_types(fn)
                if not any(op.startswith(("delta.dml.", "delta.utility."))
                           for op in ops):
                    out.append(Finding(
                        "span-missing", sf.rel, fn.lineno,
                        f"command entry point {label} opens no "
                        f"delta.dml.*/delta.utility.* span"))
        return out
