"""Sharded work-item executor — LPT assignment + work stealing.

The DCN partitioner (`parallel/distributed`) decides which *host* owns each
work item; this module is the per-host engine that actually runs a host's
items: scan decode groups, OPTIMIZE bin-pack rewrites, fused-MERGE probe
batches, checkpoint part writes. The reference delegates the same role to
Spark's task scheduler (TaskSchedulerImpl: per-executor queues + speculative
execution); ours is deliberately smaller:

* **deterministic LPT seed** — items are pre-assigned to worker deques by
  size-weighted LPT (`distributed.lpt_assign`), so the steady state does no
  coordination at all;
* **work stealing** — a worker whose deque drains steals the *tail* item of
  the worker with the most remaining bytes (the zipf hot-shard case: one
  deque inherits the head of the distribution and everyone else finishes
  early). Stealing is conf-gated (`delta.tpu.distributed.workStealing.enabled`)
  and counted (`dist.steals`);
* **measured, not asserted** — every item's wall clock is recorded
  (`dist.item.duration_ms`), and the report carries per-worker totals +
  the max/mean byte skew so benches and the MULTICHIP artifact can print
  per-shard timings instead of an "ok" string.

Threads come from one pool named ``delta-dist-exec`` (pool-naming lint).
Results preserve item order; the first item exception aborts the remaining
queue and re-raises on the caller thread.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from delta_tpu.parallel.distributed import bytes_skew, lpt_assign, lpt_loads

__all__ = ["ShardReport", "WorkerStats", "run_sharded", "default_workers"]


@dataclass
class WorkerStats:
    items: int = 0
    bytes: int = 0
    busy_s: float = 0.0
    stolen: int = 0  # items this worker STOLE from another deque


@dataclass
class ShardReport:
    """What a sharded job actually did — the bench / MULTICHIP evidence."""

    results: List[Any]
    wall_s: float
    workers: int
    steals: int
    skew: float  # max/mean per-worker bytes of the LPT seed assignment
    per_worker: Dict[int, WorkerStats] = field(default_factory=dict)

    def timings(self) -> List[Dict[str, Any]]:
        """Per-shard timing rows for artifacts (sorted by worker id)."""
        return [
            {
                "worker": w,
                "items": s.items,
                "bytes": s.bytes,
                "busy_s": round(s.busy_s, 6),
                "stolen": s.stolen,
            }
            for w, s in sorted(self.per_worker.items())
        ]


def default_workers() -> int:
    """Worker count for sharded jobs: ``delta.tpu.distributed.workers``
    when set, else min(8, cpu count) — sized like the 8-way state mesh."""
    import os

    from delta_tpu.utils.config import conf

    w = conf.get("delta.tpu.distributed.workers", None)
    if w is not None:
        return max(int(w), 1)
    return max(min(8, os.cpu_count() or 1), 1)


def run_sharded(
    items: Sequence,
    fn: Callable[[Any], Any],
    *,
    sizes: Optional[Sequence[int]] = None,
    workers: Optional[int] = None,
    label: str = "job",
) -> ShardReport:
    """Run ``fn(item)`` for every item over a worker pool with LPT seeding
    and work stealing; returns an order-preserving :class:`ShardReport`.

    ``sizes`` are per-item byte weights (defaults to uniform). ``workers``
    defaults to :func:`default_workers`; 1 worker runs inline with no pool,
    so the single-shard leg of a scaling bench measures the job, not the
    machinery.

    The whole job runs inside a ``delta.dist.job`` span; each pool worker
    opens a ``delta.dist.worker`` span (adopting the job's span context —
    pool threads do not inherit contextvars) and each item a
    ``delta.dist.item`` span carrying its index/bytes/stolen flag, so a
    distributed trace can attribute the makespan to a specific shard and
    item (`obs/trace_store.analyze_trace`).
    """
    from delta_tpu.utils import telemetry
    from delta_tpu.utils.config import conf

    n = len(items)
    results: List[Any] = [None] * n
    if workers is None:
        workers = default_workers()
    workers = max(1, min(int(workers), max(n, 1)))
    weights = [int(s or 0) for s in sizes] if sizes is not None else [1] * n
    telemetry.bump_counter("dist.jobs")
    telemetry.bump_counter("dist.items", n)

    with telemetry.record_operation(
        "delta.dist.job", {"items": n, "workers": workers}, job=label
    ) as job_ev:
        t0 = time.perf_counter()
        if workers <= 1 or n <= 1:
            job_ev.data.update(skew=1.0, lptBytes=[sum(weights)])
            stats = WorkerStats()
            for j in range(n):
                it0 = time.perf_counter()
                with telemetry.record_operation(
                    "delta.dist.item", {"index": j, "bytes": weights[j]},
                    job=label,
                ):
                    results[j] = fn(items[j])
                d = time.perf_counter() - it0
                stats.items += 1
                stats.bytes += weights[j]
                stats.busy_s += d
                telemetry.observe("dist.item.duration_ms", d * 1e3, job=label)
            return ShardReport(
                results=results,
                wall_s=time.perf_counter() - t0,
                workers=1,
                steals=0,
                skew=1.0,
                per_worker={0: stats},
            )

        seed = lpt_assign(weights, workers)
        skew = bytes_skew(weights, seed)
        # the per-worker LPT byte shares: what each shard SHOULD cost if
        # bytes predicted time perfectly — analyze_trace diffs the worker
        # spans' measured busy time against exactly these
        job_ev.data.update(
            skew=round(skew, 4), lptBytes=lpt_loads(weights, seed))
        carrier = telemetry.span_context()
        stealing = conf.get_bool("delta.tpu.distributed.workStealing.enabled", True)
        deques: List[List[int]] = [list(b) for b in seed]
        remaining = [sum(weights[j] for j in b) for b in deques]
        lock = threading.Lock()
        stop = threading.Event()
        per_worker = {w: WorkerStats() for w in range(workers)}
        steals = 0
        first_error: List[BaseException] = []

        def _take(w: int) -> Optional[Tuple[int, bool]]:
            nonlocal steals
            with lock:
                if stop.is_set():
                    return None
                if deques[w]:
                    j = deques[w].pop(0)
                    remaining[w] -= weights[j]
                    return j, False
                if not stealing:
                    return None
                # steal the tail of the most-loaded deque: the tail holds that
                # worker's smallest seeded items, so the victim keeps the head
                # it is already streaming through
                victim = max(
                    (v for v in range(workers) if deques[v]),
                    key=lambda v: (remaining[v], -v),
                    default=None,
                )
                if victim is None:
                    return None
                j = deques[victim].pop()
                remaining[victim] -= weights[j]
                steals += 1
                per_worker[w].stolen += 1
                telemetry.bump_counter("dist.steals")
                return j, True

        def _worker(w: int) -> None:
            stats = per_worker[w]
            with telemetry.adopt_span_context(carrier), \
                    telemetry.record_operation(
                        "delta.dist.worker", job=label, worker=str(w)):
                while True:
                    taken = _take(w)
                    if taken is None:
                        return
                    j, stolen = taken
                    it0 = time.perf_counter()
                    try:
                        with telemetry.record_operation(
                            "delta.dist.item",
                            {"index": j, "bytes": weights[j],
                             "stolen": stolen},
                            job=label,
                        ):
                            results[j] = fn(items[j])
                    except BaseException as exc:  # propagate the FIRST failure
                        with lock:
                            if not first_error:
                                first_error.append(exc)
                        stop.set()
                        return
                    d = time.perf_counter() - it0
                    stats.items += 1
                    stats.bytes += weights[j]
                    stats.busy_s += d
                    telemetry.observe("dist.item.duration_ms", d * 1e3,
                                      job=label)

        with ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="delta-dist-exec"
        ) as pool:
            futures = [pool.submit(_worker, w) for w in range(workers)]
            for f in futures:
                f.result()
        if first_error:
            raise first_error[0]
        report = ShardReport(
            results=results,
            wall_s=time.perf_counter() - t0,
            workers=workers,
            steals=steals,
            skew=skew,
            per_worker=per_worker,
        )
        job_ev.data.update(steals=steals, wallMs=int(report.wall_s * 1e3))
        return report
