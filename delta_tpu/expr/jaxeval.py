"""Compile expressions to ``jnp`` ops over device-resident columns.

TPU columns are SoA pairs ``(values, valid)``: a numeric/bool lane array plus a
boolean validity mask (NULL = invalid lane). Strings never reach the device as
bytes — the host dictionary-encodes them (``ops/state_export.py``) and the
device compares int32 codes; that keeps everything MXU/VPU-friendly and
static-shaped.

Three-valued logic is carried explicitly through the mask, matching
:mod:`delta_tpu.expr.ir` row semantics (Kleene AND/OR, NULL-propagating
comparisons). Replaces the role Catalyst codegen plays in the reference
(``constraints/CheckDeltaInvariant.scala``, ``MergeIntoCommand.scala:702-752``)
with XLA-fused vector code.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

from delta_tpu.expr import ir
from delta_tpu.utils.errors import DeltaAnalysisError

__all__ = ["DeviceColumn", "compile_expr", "NotDeviceCompilable"]


class NotDeviceCompilable(DeltaAnalysisError):
    """Raised when an expression cannot be lowered to device ops
    (caller falls back to the host vectorized/row evaluators)."""


class DeviceColumn(NamedTuple):
    """One SoA column: lane values + validity mask (True = non-NULL)."""

    values: Any  # jnp array
    valid: Any  # jnp bool array

    @staticmethod
    def of(values, valid=None) -> "DeviceColumn":
        values = jnp.asarray(values)
        if valid is None:
            valid = jnp.ones(values.shape, dtype=bool)
        return DeviceColumn(values, jnp.asarray(valid, dtype=bool))


Env = Dict[str, DeviceColumn]
_Compiled = Callable[[Env], DeviceColumn]


def _lit(e: ir.Literal) -> _Compiled:
    v = e.value
    if v is None:
        return lambda env: DeviceColumn(jnp.zeros((), jnp.float32), jnp.zeros((), bool))
    # Keep literals as numpy until trace time: wide dtypes (int64/float64)
    # only take effect inside the kernel's jax.enable_x64() scope.
    if isinstance(v, bool):
        arr = np.asarray(v)
    elif isinstance(v, int):
        if not (-(2**63) <= v < 2**63):
            raise NotDeviceCompilable(f"integer literal {v} exceeds int64")
        arr = np.asarray(v, np.int64 if not (-(2**31) <= v < 2**31) else np.int32)
    elif isinstance(v, float):
        arr = np.asarray(v, np.float64)
    else:
        raise NotDeviceCompilable(f"literal {v!r} has no device representation")
    return lambda env: DeviceColumn(jnp.asarray(arr), jnp.ones((), bool))


def _col(e: ir.Column) -> _Compiled:
    name = e.name

    def run(env: Env) -> DeviceColumn:
        c = env.get(name) or env.get(name.lower())
        if c is None:
            raise NotDeviceCompilable(f"column {name!r} not bound in device env")
        return c

    return run


def _binop(e, fn) -> _Compiled:
    lf, rf = compile_expr(e.left), compile_expr(e.right)

    def run(env: Env) -> DeviceColumn:
        l, r = lf(env), rf(env)
        return DeviceColumn(fn(l.values, r.values), l.valid & r.valid)

    return run


def _kleene_and(e: ir.And) -> _Compiled:
    lf, rf = compile_expr(e.left), compile_expr(e.right)

    def run(env: Env) -> DeviceColumn:
        l, r = lf(env), rf(env)
        lt = l.values.astype(bool) & l.valid  # definitely TRUE
        rt = r.values.astype(bool) & r.valid
        lF = ~l.values.astype(bool) & l.valid  # definitely FALSE
        rF = ~r.values.astype(bool) & r.valid
        value = lt & rt
        valid = value | lF | rF
        return DeviceColumn(value, valid)

    return run


def _kleene_or(e: ir.Or) -> _Compiled:
    lf, rf = compile_expr(e.left), compile_expr(e.right)

    def run(env: Env) -> DeviceColumn:
        l, r = lf(env), rf(env)
        lv = l.values.astype(bool) & l.valid
        rv = r.values.astype(bool) & r.valid
        value = lv | rv
        valid = (l.valid & r.valid) | lv | rv
        return DeviceColumn(value, valid)

    return run


def _div(e: ir.Div) -> _Compiled:
    lf, rf = compile_expr(e.left), compile_expr(e.right)

    def run(env: Env) -> DeviceColumn:
        l, r = lf(env), rf(env)
        rnz = r.values != 0
        lv = l.values.astype(jnp.float64)
        rv = jnp.where(rnz, r.values, 1).astype(jnp.float64)
        return DeviceColumn(lv / rv, l.valid & r.valid & rnz)

    return run


_CMP = {
    ir.Eq: lambda a, b: a == b,
    ir.Ne: lambda a, b: a != b,
    ir.Lt: lambda a, b: a < b,
    ir.Le: lambda a, b: a <= b,
    ir.Gt: lambda a, b: a > b,
    ir.Ge: lambda a, b: a >= b,
    ir.Add: lambda a, b: a + b,
    ir.Sub: lambda a, b: a - b,
    ir.Mul: lambda a, b: a * b,
}


def compile_expr(e: ir.Expression) -> _Compiled:
    """Lower an expression tree to a function over a device-column env.

    Raises :class:`NotDeviceCompilable` for string ops / casts / functions
    that belong on the host.
    """
    t = type(e)
    if t is ir.Literal:
        return _lit(e)
    if t is ir.Column:
        return _col(e)
    if t is ir.Alias:
        return compile_expr(e.child)
    if t in _CMP:
        return _binop(e, _CMP[t])
    if t is ir.And:
        return _kleene_and(e)
    if t is ir.Or:
        return _kleene_or(e)
    if t is ir.Div:
        return _div(e)
    if t is ir.Not:
        cf = compile_expr(e.child)
        return lambda env: (lambda c: DeviceColumn(~c.values.astype(bool), c.valid))(cf(env))
    if t is ir.Neg:
        cf = compile_expr(e.child)
        return lambda env: (lambda c: DeviceColumn(-c.values, c.valid))(cf(env))
    if t is ir.IsNull:
        cf = compile_expr(e.child)
        return lambda env: (lambda c: DeviceColumn(~c.valid, jnp.ones_like(c.valid)))(cf(env))
    if t is ir.IsNotNull:
        cf = compile_expr(e.child)
        return lambda env: (lambda c: DeviceColumn(c.valid, jnp.ones_like(c.valid)))(cf(env))
    if t is ir.NullSafeEq:
        lf, rf = compile_expr(e.left), compile_expr(e.right)

        def run_nse(env: Env) -> DeviceColumn:
            l, r = lf(env), rf(env)
            eq = (l.values == r.values) & l.valid & r.valid
            both_null = ~l.valid & ~r.valid
            return DeviceColumn(eq | both_null, jnp.ones_like(eq))

        return run_nse
    if t is ir.In:
        vf = compile_expr(e.value)
        opts = [compile_expr(o) for o in e.options]

        def run_in(env: Env) -> DeviceColumn:
            v = vf(env)
            hit = jnp.zeros(jnp.shape(v.values), bool)
            any_null_opt = jnp.zeros((), bool)
            for of in opts:
                o = of(env)
                hit = hit | ((v.values == o.values) & o.valid)
                any_null_opt = any_null_opt | ~jnp.all(o.valid)
            valid = v.valid & (hit | ~any_null_opt)
            return DeviceColumn(hit, valid)

        return run_in
    if t is ir.Coalesce:
        fns = [compile_expr(c) for c in e.children]

        def run_coalesce(env: Env) -> DeviceColumn:
            cols = [f(env) for f in fns]
            out = cols[-1]
            for c in reversed(cols[:-1]):
                out = DeviceColumn(
                    jnp.where(c.valid, c.values, out.values), c.valid | out.valid
                )
            return out

        return run_coalesce
    if t is ir.CaseWhen:
        conds = [compile_expr(e.children[2 * i]) for i in range(e.n_branches)]
        vals = [compile_expr(e.children[2 * i + 1]) for i in range(e.n_branches)]
        default = compile_expr(e.children[-1])

        def run_case(env: Env) -> DeviceColumn:
            out = default(env)
            for cf, vf2 in zip(reversed(conds), reversed(vals)):
                c, v = cf(env), vf2(env)
                fire = c.values.astype(bool) & c.valid
                out = DeviceColumn(
                    jnp.where(fire, v.values, out.values),
                    jnp.where(fire, v.valid, out.valid),
                )
            return out

        return run_case
    if t is ir.Cast:
        cf = compile_expr(e.child)
        name = e.data_type.name if not hasattr(e.data_type, "precision") else "decimal"
        if name in ("byte", "short", "integer"):
            dtype: Any = jnp.int32
        elif name == "long":
            dtype = jnp.int64
        elif name in ("float", "double", "decimal"):
            # host row-eval casts produce python doubles; match that width
            dtype = jnp.float64
        elif name == "boolean":
            dtype = bool
        else:
            raise NotDeviceCompilable(f"cast to {name} not device-representable")
        return lambda env: (lambda c: DeviceColumn(c.values.astype(dtype), c.valid))(cf(env))
    if t is ir.Func and e.name in ("abs", "floor", "ceil", "exp", "sqrt"):
        cf = compile_expr(e.children[0])
        if e.name == "sqrt":
            # Spark: NULL outside the domain (the row evaluator's contract)
            return lambda env: (lambda c: DeviceColumn(
                jnp.sqrt(jnp.maximum(c.values.astype(jnp.float64), 0.0)),
                c.valid & (c.values >= 0)))(cf(env))
        fn = {"abs": jnp.abs, "floor": jnp.floor, "ceil": jnp.ceil,
              "exp": lambda v: jnp.exp(v.astype(jnp.float64))}[e.name]
        return lambda env: (lambda c: DeviceColumn(fn(c.values), c.valid))(cf(env))
    if t is ir.Func and e.name == "log" and len(e.children) == 1:
        cf = compile_expr(e.children[0])
        return lambda env: (lambda c: DeviceColumn(
            jnp.log(jnp.maximum(c.values.astype(jnp.float64), 1e-300)),
            c.valid & (c.values > 0)))(cf(env))
    if t is ir.Func and e.name in ("pow", "power") and len(e.children) == 2:
        cx = compile_expr(e.children[0])
        cy = compile_expr(e.children[1])
        return lambda env: (lambda a, b: DeviceColumn(
            jnp.power(a.values.astype(jnp.float64), b.values.astype(jnp.float64)),
            a.valid & b.valid))(cx(env), cy(env))
    if t is ir.Func and e.name in ("date_add", "date_sub") and len(e.children) == 2:
        # date lanes are epoch days on device
        cd = compile_expr(e.children[0])
        cn = compile_expr(e.children[1])
        sign = 1 if e.name == "date_add" else -1
        return lambda env: (lambda d, n: DeviceColumn(
            d.values + sign * n.values.astype(d.values.dtype),
            d.valid & n.valid))(cd(env), cn(env))
    if t is ir.Func and e.name == "datediff" and len(e.children) == 2:
        ca = compile_expr(e.children[0])
        cb = compile_expr(e.children[1])
        return lambda env: (lambda a, b: DeviceColumn(
            a.values - b.values, a.valid & b.valid))(ca(env), cb(env))
    if t is ir.Func and e.name in ("minute", "second") and len(e.children) == 1:
        ct = compile_expr(e.children[0])
        div = 60_000_000 if e.name == "minute" else 1_000_000
        return lambda env: (lambda c: DeviceColumn(
            (c.values // div) % 60, c.valid))(ct(env))
    raise NotDeviceCompilable(f"{type(e).__name__} has no device lowering: {e.sql()}")


def columns_from_numpy(data: Dict[str, np.ndarray], masks: Optional[Dict[str, np.ndarray]] = None) -> Env:
    """Build a device env from host numpy columns (tests / small paths)."""
    masks = masks or {}
    return {k: DeviceColumn.of(v, masks.get(k)) for k, v in data.items()}
