"""User API + ALTER + SQL frontend suites.

Behavioral spec: `python/delta/tests/test_deltatable.py`, `test_sql.py`,
`DeltaAlterTableTests` (SURVEY §4).
"""
import os

import pyarrow as pa
import pytest

from delta_tpu.api.tables import DeltaTable
from delta_tpu.commands import alter
from delta_tpu.log.deltalog import DeltaLog
from delta_tpu.schema.types import (
    IntegerType,
    LongType,
    StringType,
    StructField,
    StructType,
)
from delta_tpu.sql.parser import execute_sql
from delta_tpu.utils.errors import DeltaAnalysisError, InvariantViolationError


def make_table(path, data=None):
    t = DeltaTable.create(
        path, StructType().add("id", LongType()).add("v", LongType())
    )
    if data:
        t.write(data)
    return t


def test_for_path_and_is_delta_table(tmp_table):
    with pytest.raises(DeltaAnalysisError):
        DeltaTable.for_path(tmp_table)
    assert DeltaTable.is_delta_table(tmp_table) is False
    make_table(tmp_table)
    t = DeltaTable.for_path(tmp_table)
    assert DeltaTable.is_delta_table(tmp_table) is True
    assert t.version == 0


def test_create_write_read_roundtrip(tmp_table):
    t = make_table(tmp_table, {"id": [1, 2], "v": [10, 20]})
    out = t.to_arrow(filters=["v > 15"])
    assert out.column("id").to_pylist() == [2]
    assert [f.name for f in t.schema().fields] == ["id", "v"]


def test_delete_update_via_api(tmp_table):
    t = make_table(tmp_table, {"id": [1, 2, 3], "v": [1, 2, 3]})
    t.update({"v": "v * 10"}, condition="id = 2")
    m = t.delete("id = 3")
    assert m["numDeletedRows"] == 1
    got = sorted(t.to_arrow().to_pylist(), key=lambda r: r["id"])
    assert got == [{"id": 1, "v": 1}, {"id": 2, "v": 20}]


def test_merge_builder_fluent(tmp_table):
    t = make_table(tmp_table, {"id": [1, 2], "v": [1, 2]}).alias("t")
    metrics = (
        t.merge({"id": [2, 3], "v": [20, 30]}, "t.id = s.id", source_alias="s")
        .when_matched_update(set={"v": "s.v"})
        .when_not_matched_insert_all()
        .execute()
    )
    assert metrics["numTargetRowsUpdated"] == 1
    assert metrics["numTargetRowsInserted"] == 1
    got = sorted(t.to_arrow().to_pylist(), key=lambda r: r["id"])
    assert got == [{"id": 1, "v": 1}, {"id": 2, "v": 20}, {"id": 3, "v": 30}]


def test_time_travel_via_api(tmp_table):
    t = make_table(tmp_table, {"id": [1], "v": [1]})
    t.write({"id": [2], "v": [2]})
    assert len(t.to_arrow(version=1)) == 1  # create(v0) + first write(v1)? no:
    # v0 = create (empty), v1 = first write, v2 = second write
    assert sorted(t.to_arrow(version=2).column("id").to_pylist()) == [1, 2]
    assert t.to_arrow(version=0).num_rows == 0


def test_optimize_builder(tmp_table):
    t = make_table(tmp_table)
    for i in range(3):
        t.write({"id": [i], "v": [i]})
    m = t.optimize().execute_compaction()
    assert m["numRemovedFiles"] == 3
    assert m["numAddedFiles"] == 1


def test_upgrade_protocol(tmp_table):
    t = make_table(tmp_table)
    t.upgrade_table_protocol(1, 3)
    snap = t.delta_log.update()
    assert snap.protocol.min_writer_version == 3


# -- ALTER ------------------------------------------------------------------


def test_alter_properties(tmp_table):
    t = make_table(tmp_table)
    alter.set_table_properties(t.delta_log, {"delta.appendOnly": "true"})
    assert t.detail()["properties"]["delta.appendOnly"] == "true"
    with pytest.raises(DeltaAnalysisError):
        alter.unset_table_properties(t.delta_log, ["nope"])
    alter.unset_table_properties(t.delta_log, ["nope"], if_exists=True)
    alter.unset_table_properties(t.delta_log, ["delta.appendOnly"])
    assert "delta.appendOnly" not in t.detail()["properties"]


def test_alter_append_only_enforced(tmp_table):
    t = make_table(tmp_table, {"id": [1], "v": [1]})
    alter.set_table_properties(t.delta_log, {"delta.appendOnly": "true"})
    with pytest.raises(Exception):
        t.delete("id = 1")
    t.write({"id": [2], "v": [2]})  # appends still fine


def test_alter_add_columns(tmp_table):
    t = make_table(tmp_table, {"id": [1], "v": [1]})
    alter.add_columns(t.delta_log, [StructField("extra", StringType())])
    assert [f.name for f in t.schema().fields] == ["id", "v", "extra"]
    assert t.to_arrow().column("extra").to_pylist() == [None]
    with pytest.raises(DeltaAnalysisError):
        alter.add_columns(t.delta_log, [StructField("id", StringType())])
    with pytest.raises(DeltaAnalysisError):
        alter.add_columns(
            t.delta_log, [StructField("x", StringType(), nullable=False)]
        )


def test_alter_add_columns_first_and_after(tmp_table):
    t = make_table(tmp_table, {"id": [1], "v": [1]})
    alter.add_columns(t.delta_log, [StructField("front", StringType())],
                      positions={"front": "first"})
    alter.add_columns(t.delta_log, [StructField("mid", StringType())],
                      positions={"mid": ("after", "id")})
    assert [f.name for f in t.schema().fields] == ["front", "id", "mid", "v"]
    # data written before the ALTERs reads back with nulls in the new slots
    assert t.to_arrow().to_pylist() == [
        {"front": None, "id": 1, "mid": None, "v": 1}
    ]


def test_alter_add_nested_column(tmp_table):
    from delta_tpu.schema.types import StructType as ST

    path = tmp_table
    inner = ST().add("x", IntegerType())
    t = DeltaTable.create(path, ST().add("id", IntegerType()).add("s", inner))
    alter.add_columns(t.delta_log, [StructField("s.y", StringType())])
    s_type = t.schema()["s"].data_type
    assert [f.name for f in s_type.fields] == ["x", "y"]
    alter.add_columns(t.delta_log, [StructField("s.z", StringType())],
                      positions={"s.z": "first"})
    s_type = t.schema()["s"].data_type
    assert [f.name for f in s_type.fields] == ["z", "x", "y"]


def test_alter_change_nested_column_comment(tmp_table):
    from delta_tpu.schema.types import StructType as ST

    inner = ST().add("x", IntegerType())
    t = DeltaTable.create(tmp_table, ST().add("s", inner).add("id", IntegerType()))
    alter.change_column(t.delta_log, "s.x", new_type=LongType(),
                        comment="widened")
    s_type = t.schema()["s"].data_type
    assert s_type["x"].data_type == LongType()
    assert s_type["x"].metadata["comment"] == "widened"


def test_alter_change_column_position_move(tmp_table):
    t = make_table(tmp_table, {"id": [1], "v": [2]})
    alter.change_column(t.delta_log, "v", position="first")
    assert [f.name for f in t.schema().fields] == ["v", "id"]
    alter.change_column(t.delta_log, "v", position=("after", "id"))
    assert [f.name for f in t.schema().fields] == ["id", "v"]
    assert t.to_arrow().to_pylist() == [{"id": 1, "v": 2}]


def test_alter_change_column_move_sole_column_is_noop(tmp_table):
    t = DeltaTable.create(tmp_table, StructType().add("only", IntegerType()))
    alter.change_column(t.delta_log, "only", position="first")
    assert [f.name for f in t.schema().fields] == ["only"]


def test_alter_add_column_inside_array_element(tmp_table):
    from delta_tpu.schema.types import ArrayType, StructType as ST

    elem = ST().add("x", IntegerType())
    t = DeltaTable.create(
        tmp_table, ST().add("id", IntegerType()).add("arr", ArrayType(elem))
    )
    alter.add_columns(t.delta_log, [StructField("arr.element.y", StringType())])
    arr_t = t.schema()["arr"].data_type
    assert [f.name for f in arr_t.element_type.fields] == ["x", "y"]


def test_alter_change_column_widen(tmp_table):
    path = tmp_table
    t = DeltaTable.create(path, StructType().add("id", IntegerType()))
    t.write({"id": pa.array([1], pa.int32())})
    alter.change_column(t.delta_log, "id", new_type=LongType())
    assert t.schema()["id"].data_type == LongType()
    # narrowing refused
    with pytest.raises(DeltaAnalysisError):
        alter.change_column(t.delta_log, "id", new_type=IntegerType())
    t.write({"id": [2**40]})
    assert sorted(t.to_arrow().column("id").to_pylist()) == [1, 2**40]


def test_alter_constraints(tmp_table):
    t = make_table(tmp_table, {"id": [1], "v": [5]})
    with pytest.raises(DeltaAnalysisError):
        alter.add_constraint(t.delta_log, "vbig", "v > 10")  # existing row violates
    alter.add_constraint(t.delta_log, "vpos", "v > 0")
    with pytest.raises(InvariantViolationError):
        t.write({"id": [9], "v": [-1]})
    with pytest.raises(DeltaAnalysisError):
        alter.add_constraint(t.delta_log, "vpos", "v > 1")  # duplicate name
    alter.drop_constraint(t.delta_log, "vpos")
    t.write({"id": [9], "v": [-1]})  # allowed again


# -- SQL --------------------------------------------------------------------


def test_sql_describe_and_vacuum(tmp_table):
    make_table(tmp_table, {"id": [1], "v": [1]})
    hist = execute_sql(f"DESCRIBE HISTORY delta.`{tmp_table}`")
    assert [h["operation"] for h in hist] == ["WRITE", "CREATE TABLE"] or len(hist) == 2
    detail = execute_sql(f"DESCRIBE DETAIL delta.`{tmp_table}`")
    assert detail["numFiles"] == 1
    res = execute_sql(f"VACUUM delta.`{tmp_table}` RETAIN 200 HOURS DRY RUN")
    assert res.dry_run is True


def test_sql_delete_update(tmp_table):
    t = make_table(tmp_table, {"id": [1, 2, 3], "v": [1, 2, 3]})
    execute_sql(f"UPDATE delta.`{tmp_table}` SET v = v + 100 WHERE id >= 2")
    m = execute_sql(f"DELETE FROM delta.`{tmp_table}` WHERE v > 102")
    assert m["numDeletedRows"] == 1
    got = sorted(t.to_arrow().to_pylist(), key=lambda r: r["id"])
    assert got == [{"id": 1, "v": 1}, {"id": 2, "v": 102}]


def test_sql_convert_and_generate(tmp_table):
    import pyarrow.parquet as pq

    os.makedirs(tmp_table)
    pq.write_table(pa.table({"id": [1, 2]}), os.path.join(tmp_table, "x.parquet"))
    execute_sql(f"CONVERT TO DELTA parquet.`{tmp_table}`")
    assert DeltaTable.is_delta_table(tmp_table)
    execute_sql(f"GENERATE symlink_format_manifest FOR TABLE delta.`{tmp_table}`")
    assert os.path.exists(
        os.path.join(tmp_table, "_symlink_format_manifest", "manifest")
    )
    with pytest.raises(DeltaAnalysisError):
        execute_sql("FROBNICATE TABLE x")


def test_plan_queries_batch(tmp_table):
    import numpy as np

    from delta_tpu.commands.write import WriteIntoDelta

    log = DeltaLog.for_table(tmp_table)
    for i in range(4):
        WriteIntoDelta(log, "append", pa.table({
            "a": np.arange(i * 10, (i + 1) * 10, dtype=np.int64)})).run()
    t = DeltaTable.for_path(tmp_table)
    plans = t.plan_queries([["a = 5"], ["a >= 20 AND a <= 39"], []])
    assert plans[0].count == 1
    assert plans[1].count == 2
    assert plans[2].count == 4  # empty filter = all files


def test_plan_queries_rejects_flat_filter_list(tmp_table):
    import numpy as np

    from delta_tpu.commands.write import WriteIntoDelta
    from delta_tpu.utils.errors import DeltaIllegalArgumentError

    log = DeltaLog.for_table(tmp_table)
    WriteIntoDelta(log, "append", pa.table({"a": np.arange(5)})).run()
    with pytest.raises(DeltaIllegalArgumentError, match="wrap the filter"):
        DeltaTable.for_path(tmp_table).plan_queries(["a = 5"])
