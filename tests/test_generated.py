"""Generated columns (reference spec: ``GeneratedColumnSuite``, 690 LoC;
semantics `GeneratedColumn.scala:79-365` + `SupportedGenerationExpressions`)."""
import pyarrow as pa
import pytest

from delta_tpu import DeltaLog
from delta_tpu.api.tables import DeltaTable
from delta_tpu.commands.merge import MergeClause, MergeIntoCommand
from delta_tpu.commands.update import UpdateCommand
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.exec.scan import scan_to_table
from delta_tpu.schema.generated import generated_field, validate_generated_columns
from delta_tpu.schema.types import IntegerType, LongType, StringType, StructType
from delta_tpu.utils.errors import DeltaAnalysisError, InvariantViolationError


def gen_schema():
    return (
        StructType()
        .add("id", LongType())
        .add("name", StringType())
        .add_field(generated_field("id2", LongType(), "id * 2"))
        .add_field(generated_field("uname", StringType(), "upper(name)"))
    )


@pytest.fixture
def gtable(tmp_table):
    schema = gen_schema()
    if not hasattr(StructType, "add_field"):
        pytest.skip("no add_field")
    return DeltaTable.create(tmp_table, schema)


def rows(log):
    return sorted(scan_to_table(log.update()).to_pylist(), key=lambda r: r["id"])


def test_missing_generated_columns_computed(gtable):
    gtable.write({"id": [1, 2], "name": ["a", "b"]})
    assert rows(gtable.delta_log) == [
        {"id": 1, "name": "a", "id2": 2, "uname": "A"},
        {"id": 2, "name": "b", "id2": 4, "uname": "B"},
    ]


def test_provided_matching_values_accepted(gtable):
    gtable.write({"id": [3], "name": ["c"], "id2": [6], "uname": ["C"]})
    assert rows(gtable.delta_log)[0]["id2"] == 6


def test_provided_mismatching_values_rejected(gtable):
    with pytest.raises(InvariantViolationError, match="Generated Column"):
        gtable.write({"id": [3], "name": ["c"], "id2": [7]})


def test_null_inputs_propagate(gtable):
    gtable.write({"id": [5], "name": [None]})
    r = rows(gtable.delta_log)[0]
    assert r["uname"] is None and r["id2"] == 10


def test_protocol_bumped_to_writer_4(gtable):
    p = gtable.delta_log.update().protocol
    assert p.min_writer_version == 4


def test_unknown_function_rejected():
    schema = StructType().add("id", LongType()).add_field(
        generated_field("r", LongType(), "rand(id)")
    )
    with pytest.raises(DeltaAnalysisError):
        validate_generated_columns(schema)


def test_unknown_reference_rejected():
    schema = StructType().add("id", LongType()).add_field(
        generated_field("g", LongType(), "nope + 1")
    )
    with pytest.raises(DeltaAnalysisError, match="unknown"):
        validate_generated_columns(schema)


def test_generated_referencing_generated_rejected():
    schema = (
        StructType()
        .add("id", LongType())
        .add_field(generated_field("g1", LongType(), "id + 1"))
        .add_field(generated_field("g2", LongType(), "g1 + 1"))
    )
    with pytest.raises(DeltaAnalysisError, match="reference each other"):
        validate_generated_columns(schema)


def test_create_table_validates(tmp_table):
    schema = StructType().add("id", LongType()).add_field(
        generated_field("g", LongType(), "nope + 1")
    )
    with pytest.raises(DeltaAnalysisError):
        DeltaTable.create(tmp_table, schema)


def test_update_recomputes_generated(gtable):
    gtable.write({"id": [1, 2], "name": ["a", "b"]})
    UpdateCommand(gtable.delta_log, {"id": "id + 10"}, condition="name = 'a'").run()
    assert rows(gtable.delta_log) == [
        {"id": 2, "name": "b", "id2": 4, "uname": "B"},
        {"id": 11, "name": "a", "id2": 22, "uname": "A"},
    ]


def test_merge_update_recomputes_and_insert_computes(gtable):
    log = gtable.delta_log
    gtable.write({"id": [1, 2], "name": ["a", "b"]})
    src = pa.table({"k": [2, 5], "nm": ["bb", "e"]})
    MergeIntoCommand(
        log, src, "t.id = s.k",
        [MergeClause("update", assignments={"name": "s.nm"})],
        [MergeClause("insert", assignments={"id": "s.k", "name": "s.nm"})],
        source_alias="s", target_alias="t",
    ).run()
    assert rows(log) == [
        {"id": 1, "name": "a", "id2": 2, "uname": "A"},
        {"id": 2, "name": "bb", "id2": 4, "uname": "BB"},
        {"id": 5, "name": "e", "id2": 10, "uname": "E"},
    ]


def test_write_omitting_referenced_nullable_base_column(gtable):
    # omitting a nullable base column is legal; the generated column
    # computes over NULLs (name missing -> uname NULL, id2 still computed)
    gtable.write({"id": [7]})
    r = rows(gtable.delta_log)[0]
    assert r == {"id": 7, "name": None, "id2": 14, "uname": None}
