"""Workload replay + shadow optimizer (ROADMAP item 5).

The journal already persists a complete replayable workload trace — every
scan's predicate fingerprint (with a bounded literal-sample reservoir),
every commit outcome, every router decision. This package closes the loop:

- :mod:`delta_tpu.replay.trace` reconstructs an ordered
  :class:`~delta_tpu.replay.trace.WorkloadTrace` from the journal,
  rehydrating concrete scan predicates from the reservoir samples (falling
  back to stats-guided literal synthesis, flagged so scores discount them).
- :mod:`delta_tpu.replay.shadow` replays a trace's scans against sandboxed
  clones under candidate layouts/configurations (alternative ZORDER column
  sets, partition schemes, ``rowGroupRows``, conf deltas) and scores the
  MEASURED bytes-skipped / planning-p50 / row-groups-pruned deltas into a
  ranked :class:`~delta_tpu.replay.shadow.ShadowScorecard` — the advisor
  attaches the verdicts to its recommendations and the autopilot's
  ``requireShadow`` guardrail defers unproven rewrites on them.
- :mod:`delta_tpu.replay.scenarios` replays traces time-compressed (10x /
  100x) against the live scraper/SLO plane for capacity testing, and ships
  synthetic scenario traces (zipf hot-key storm, CDC burst, contention
  flood) serialized in the same trace format.
"""
from delta_tpu.replay.trace import TraceEvent, WorkloadTrace, build_trace
from delta_tpu.replay.shadow import (
    Candidate, ShadowScorecard, default_candidates, realized_audit,
    shadow_run, shadow_verdicts,
)
from delta_tpu.replay.scenarios import (
    SCENARIOS, capacity_replay, cdc_burst, contention_flood,
    zipf_hot_key_storm,
)

__all__ = [
    "TraceEvent", "WorkloadTrace", "build_trace",
    "Candidate", "ShadowScorecard", "default_candidates", "realized_audit",
    "shadow_run", "shadow_verdicts",
    "SCENARIOS", "capacity_replay", "cdc_burst", "contention_flood",
    "zipf_hot_key_storm",
]
