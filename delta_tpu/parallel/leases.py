"""Host-slice leases: crash evidence for multihost work distribution.

A distributed OPTIMIZE splits its group list across hosts with no
scheduler RPC (``parallel/distributed.host_shard_indices``) — which also
means no scheduler notices a host dying mid-slice. The lease protocol
makes host death *observable from the shared filesystem*, the only channel
every host already has:

1. Before executing its slice, a host writes
   ``_delta_log/_dist/lease-<ts>-<pid>-<proc>.json`` carrying the job id,
   its slice's bin-packed group keys, and the ``commitInfo.txnId`` token
   its commit WILL carry (``OptimisticTransaction.preset_txn_id``).
2. While rewriting, the host heartbeats the lease (mtime touch) — the
   liveness signal, same convention as a journal writer touching its
   active segment.
3. After its commit lands, the host deletes the lease.

A lease still present with a heartbeat older than
``delta.tpu.distributed.lease.ttlMs`` is an **orphan**: its host died (or
wedged) somewhere between planning and clearing. The coordinator
(``commands/optimize.py``) then reconciles: the recorded txnId appearing
in the log tail means the host committed and only the *clear* was lost
(delete the lease, done); otherwise the slice's work is re-planned from a
fresh snapshot restricted to the recorded group keys and re-executed
locally — idempotent because an already-compacted partition yields no
plannable group.

Leases are local-filesystem-only (``scheme://`` log paths skip the whole
protocol, like the journal) and swept with the same aged-orphan discipline
as ``.tmp`` staging files. The sweep shares the journal's
newest-per-pid/grace liveness rule (``obs/journal.live_writer_spared``) so
"this file may belong to a live process" cannot mean two different things
in the two sweeps.
"""
from __future__ import annotations

import json
import os
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf

__all__ = ["enabled", "dist_dir", "lease_ttl_s", "write_lease",
           "heartbeat_lease", "clear_lease", "read_leases", "sweep_leases",
           "new_token"]

LEASE_PREFIX = "lease-"
LEASE_SUFFIX = ".json"


def enabled(log_path: Optional[str]) -> bool:
    """The lease protocol is on: conf-enabled and the log lives on a local
    filesystem (leases are mtime-heartbeated plain files, meaningless —
    and unpollable — behind an object store)."""
    if not conf.get_bool("delta.tpu.distributed.lease.enabled", True):
        return False
    if log_path is None or "://" in log_path:
        return False
    return True


def dist_dir(log_path: str) -> str:
    """The lease directory for a table's ``_delta_log`` path."""
    return os.path.join(log_path, "_dist")


def lease_ttl_s() -> float:
    try:
        ms = float(conf.get("delta.tpu.distributed.lease.ttlMs", 60_000))
    except (TypeError, ValueError):
        ms = 60_000.0
    return max(ms, 1.0) / 1000.0


def new_token() -> str:
    """A fresh commit token to record in a lease and preset on the slice's
    transaction (``commitInfo.txnId``)."""
    return uuid.uuid4().hex


def _lease_name(proc: int) -> str:
    # pid at dash-field 2 — the layout journal.live_writer_spared parses,
    # so the shared liveness rule applies to lease files unchanged
    return (f"{LEASE_PREFIX}{int(time.time() * 1000):013d}-"
            f"{os.getpid()}-{int(proc)}{LEASE_SUFFIX}")


def write_lease(log_path: str, job: str, proc: int,
                payload: Dict[str, Any]) -> Optional[str]:
    """Publish this host's lease for ``job``; returns its path, or None
    when the protocol is off or the write failed (the slice then proceeds
    *uncovered* — counted ``dist.degraded.lease`` — rather than failing a
    job over its own safety net)."""
    if not enabled(log_path):
        return None
    from delta_tpu.storage import faults

    path = os.path.join(dist_dir(log_path), _lease_name(proc))
    body = dict(payload)
    body.update(job=job, proc=int(proc), pid=os.getpid(),
                ts=int(time.time() * 1000))
    from delta_tpu.utils.retries import TransientIOError

    try:
        faults.fire("dist.leaseWrite", job)
        os.makedirs(dist_dir(log_path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(body, f, separators=(",", ":"), default=str)
    except (TransientIOError, OSError):
        # transient fault or unwritable dir: the slice proceeds UNCOVERED
        # (counted) — the lease is a safety net, not a precondition; a
        # SimulatedCrash pierces like any host death, and a torn lease
        # file is skipped by read_leases' parse guard
        telemetry.bump_counter("dist.degraded.lease")
        return None
    return path


def heartbeat_lease(path: Optional[str]) -> None:
    """Touch the lease's mtime — the liveness signal the coordinator and
    the sweep read. Best-effort: a lost heartbeat risks a spurious-looking
    expiry (recovery is idempotent), never a failed rewrite."""
    if path is None:
        return
    try:
        os.utime(path)
    except OSError:
        pass


def clear_lease(path: Optional[str]) -> None:
    """Delete this host's lease after its commit landed. Best-effort: a
    lost clear leaves an orphan whose recorded txnId reconciles to
    already-committed — cleanup, not re-execution."""
    if path is None:
        return
    try:
        os.remove(path)
    except OSError:
        pass


def read_leases(log_path: str) -> List[Tuple[str, Dict[str, Any], float]]:
    """Every parseable lease under the table's ``_dist/`` directory as
    ``(path, payload, heartbeat_mtime)``, name-sorted. Torn or malformed
    files are skipped — a half-written lease from a dying host must not
    poison the coordinator's reconciliation."""
    ddir = dist_dir(log_path)
    try:
        names = sorted(n for n in os.listdir(ddir)
                       if n.startswith(LEASE_PREFIX)
                       and n.endswith(LEASE_SUFFIX))
    except OSError:
        return []
    out: List[Tuple[str, Dict[str, Any], float]] = []
    for n in names:
        p = os.path.join(ddir, n)
        try:
            mtime = os.stat(p).st_mtime
            with open(p, encoding="utf-8") as f:
                body = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(body, dict):
            out.append((p, body, mtime))
    return out


def sweep_leases(log_path: str) -> int:
    """Delete dead lease files: everything except possibly-live hosts'
    newest leases, per the shared journal liveness rule (newest file per
    embedded pid, heartbeat within the grace window). A dead CI pid's lease
    goes as soon as its heartbeat is stale — one immune lease per crashed
    run would grow ``_dist/`` forever — while this process's own live lease
    is spared exactly the way the journal sweep spares its active segment."""
    from delta_tpu.obs.journal import live_writer_spared

    ddir = dist_dir(log_path)
    try:
        names = [n for n in os.listdir(ddir)
                 if n.startswith(LEASE_PREFIX) and n.endswith(LEASE_SUFFIX)]
    except OSError:
        return 0
    stats = []
    for n in names:
        p = os.path.join(ddir, n)
        try:
            st = os.stat(p)
        except OSError:
            continue
        stats.append((p, st.st_size, st.st_mtime))
    # grace = the lease ttl: past it the coordinator already treats the
    # lease as an orphan to reconcile, so the sweep may reclaim the file
    spared = live_writer_spared(stats, lease_ttl_s())
    deleted = 0
    for p, _size, _mtime in stats:
        if p in spared:
            continue
        try:
            os.remove(p)
            deleted += 1
        except OSError:
            continue
    if deleted:
        telemetry.bump_counter("dist.lease.swept", deleted)
    return deleted
