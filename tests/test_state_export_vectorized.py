"""Parity: vectorized arrays_from_columns vs the dataclass files_to_arrays.

The vectorized path parses every row's stats JSON in one C++ ndjson pass
(`ops/state_export.arrays_from_columns`); the dataclass path parses per file.
Both must produce identical lanes, or pruning verdicts would depend on which
path a table's size happened to route it through.
"""
import json

import numpy as np
import pyarrow as pa
import pytest

from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.log.deltalog import DeltaLog
from delta_tpu.ops.state_export import arrays_from_columns, files_to_arrays
from delta_tpu.protocol.actions import AddFile
from tests.conftest import commit_manually, init_metadata
from delta_tpu.schema.types import (
    DateType, DoubleType, IntegerType, LongType, StringType, StructType,
    TimestampType,
)


def _write_table(path, tables):
    log = DeltaLog.for_table(path)
    for t in tables:
        WriteIntoDelta(log, "append", t).run()
    return log


def _assert_parity(snap, stats_columns=None):
    arr_v = arrays_from_columns(
        snap._columnar, snap._alive_mask, snap.metadata, stats_columns,
        sort_by_path=True,
    )
    assert arr_v is not None
    arr_d = files_to_arrays(snap.all_files, snap.metadata, stats_columns)
    assert arr_v.paths == arr_d.paths
    np.testing.assert_array_equal(arr_v.size, arr_d.size)
    np.testing.assert_array_equal(arr_v.modification_time, arr_d.modification_time)
    np.testing.assert_array_equal(arr_v.num_records, arr_d.num_records)
    assert set(arr_v.stats_min) == set(arr_d.stats_min)
    for c in arr_d.stats_min:
        np.testing.assert_array_equal(arr_v.stats_min[c], arr_d.stats_min[c], err_msg=f"min.{c}")
        np.testing.assert_array_equal(arr_v.stats_max[c], arr_d.stats_max[c], err_msg=f"max.{c}")
        np.testing.assert_array_equal(
            arr_v.stats_null_count[c], arr_d.stats_null_count[c], err_msg=f"nullCount.{c}"
        )
    return arr_v


def test_numeric_parity(tmp_table):
    rng = np.random.RandomState(3)
    tables = [
        pa.table({
            "a": rng.randint(-1000, 1000, 50).astype(np.int64),
            "b": rng.rand(50),
            "s": pa.array([f"x{i}" for i in range(50)]),
        })
        for _ in range(4)
    ]
    log = _write_table(tmp_table, tables)
    _assert_parity(log.update())


def test_nulls_and_missing_stats(tmp_table):
    log = _write_table(tmp_table, [
        pa.table({"a": pa.array([1, None, 3], pa.int64()), "b": pa.array([None, None, None], pa.float64())}),
    ])
    # a file committed without stats at all
    commit_manually(log, 1, [AddFile(path="nostats.parquet", size=10, modification_time=5, data_change=True)])
    snap = log.update()
    arr = _assert_parity(snap)
    i = arr.paths.index("nostats.parquet")
    assert arr.num_records[i] == -1
    for c in arr.stats_min:
        assert np.isnan(arr.stats_min[c][i])
        assert arr.stats_null_count[c][i] == -1


def test_big_int_masked_conservative(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    commit_manually(log, 0, [init_metadata(schema=StructType().add("a", LongType()))])
    stats = json.dumps({"numRecords": 2, "minValues": {"a": -(2**60)},
                        "maxValues": {"a": 2**60}, "nullCount": {"a": 0}})
    commit_manually(log, 1, [AddFile(path="f.parquet", size=1, modification_time=1,
                                     data_change=True, stats=stats)])
    snap = log.update()
    arr = _assert_parity(snap)
    assert np.isnan(arr.stats_min["a"][0]) and np.isnan(arr.stats_max["a"][0])


def test_temporal_lanes(tmp_table):
    schema = StructType().add("d", DateType()).add("ts", TimestampType())
    log = DeltaLog.for_table(tmp_table)
    commit_manually(log, 0, [init_metadata(schema=schema)])
    stats = json.dumps({
        "numRecords": 3,
        "minValues": {"d": "2021-01-01", "ts": "2021-01-01T00:00:00"},
        "maxValues": {"d": "2021-12-31", "ts": "2021-12-31T23:59:59.500"},
        "nullCount": {"d": 0, "ts": 1},
    })
    commit_manually(log, 1, [AddFile(path="f.parquet", size=1, modification_time=1,
                                     data_change=True, stats=stats)])
    arr = _assert_parity(log.update())
    assert arr.stats_min["d"][0] == float(
        (np.datetime64("2021-01-01") - np.datetime64("1970-01-01")).astype(int))
    assert arr.stats_null_count["ts"][0] == 1


def test_pretty_printed_stats_fall_back(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    commit_manually(log, 0, [init_metadata(schema=StructType().add("a", IntegerType()))])
    stats = json.dumps({"numRecords": 1, "minValues": {"a": 1},
                        "maxValues": {"a": 2}, "nullCount": {"a": 0}}, indent=2)
    commit_manually(log, 1, [AddFile(path="f.parquet", size=1, modification_time=1,
                                     data_change=True, stats=stats)])
    snap = log.update()
    assert arrays_from_columns(snap._columnar, snap._alive_mask, snap.metadata) is None
    # the public surface still serves arrays via the dataclass fallback
    arr = snap.files_arrays()
    assert arr.stats_min["a"][0] == 1.0


def test_partitioned_vectorized_codes_match_dataclass(tmp_table):
    """r5: the vectorized path carries partitioned tables too — codes and
    dictionaries must decode the same values the dataclass path sees."""
    log = DeltaLog.for_table(tmp_table)
    commit_manually(log, 0, [init_metadata(
        partition_columns=["p"],
        schema=StructType().add("p", StringType()).add("a", IntegerType()))])
    from delta_tpu.commands.write import WriteIntoDelta

    for p in ("x", "y", "x"):
        WriteIntoDelta(log, "append", pa.table({
            "p": [p] * 4, "a": np.arange(4, dtype=np.int32)})).run()
    snap = log.update()
    arr = arrays_from_columns(snap._columnar, snap._alive_mask, snap.metadata)
    assert arr is not None and "p" in arr.partition_codes
    got = {path: arr.partition_dicts["p"][code] if code >= 0 else None
           for path, code in zip(arr.paths, arr.partition_codes["p"])}
    expect = {f.path: (f.partition_values or {}).get("p")
              for f in snap.all_files}
    assert got == expect


def test_row_order_unsorted_matches_rows(tmp_table):
    """Without sort_by_path, lanes stay in replay-row order (cache layout)."""
    log = _write_table(tmp_table, [
        pa.table({"a": np.arange(5, dtype=np.int64)}),
        pa.table({"a": np.arange(5, 10, dtype=np.int64)}),
    ])
    snap = log.update()
    arr = arrays_from_columns(snap._columnar, snap._alive_mask, snap.metadata)
    rows = np.nonzero(snap._alive_mask)[0]
    assert arr.paths == snap._columnar.paths_for(rows)
    np.testing.assert_array_equal(arr.size, snap._columnar.size[rows])
