"""Link cost model + MERGE executor routing (parallel/link.py).

The device join's profitability is decided by the host↔device link, not
the FLOPs — these tests pin the routing decisions with conf-overridden
link profiles (no probe, deterministic)."""
import numpy as np
import pyarrow as pa
import pytest

from delta_tpu import DeltaLog
from delta_tpu.commands.merge import MergeClause, MergeIntoCommand, _rows_from_stats
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.ops import join_kernel
from delta_tpu.parallel import link
from delta_tpu.utils.config import conf


@pytest.fixture(autouse=True)
def fresh_link():
    link.reset()
    yield
    link.reset()


def _with_link(up, down):
    return conf.set_temporarily(**{
        "delta.tpu.link.uploadMBps": up,
        "delta.tpu.link.downloadMBps": down,
    })


def test_profile_conf_override_skips_probe():
    with _with_link(6.0, 4.0):
        p = link.profile()
    assert not p.probed
    assert p.up_mbps == 6.0 and p.down_mbps == 4.0
    # 60 MB at 6 MB/s ~ 10s
    assert 9.9 < p.upload_s(60_000_000) < 10.2


def test_estimate_scales_kernel_by_shards():
    with _with_link(10_000.0, 10_000.0):
        one = link.estimate_device_s(1 << 20, 1 << 10, kernel_rows=8_000_000)
        link.reset()
    with _with_link(10_000.0, 10_000.0):
        eight = link.estimate_device_s(
            1 << 20, 1 << 10, kernel_rows=8_000_000, shards=8
        )
    assert eight.kernel_s < one.kernel_s
    assert eight.device_s < one.device_s


def test_budget_declines_on_slow_link():
    rng = np.random.RandomState(0)
    t = rng.randint(0, 1000, 50_000).astype(np.int64)
    s = rng.randint(500, 1500, 5_000).astype(np.int64)
    ok_t, ok_s = np.ones(len(t), bool), np.ones(len(s), bool)
    with _with_link(6.0, 4.0):
        # host estimate for 55k rows ~ 5.5ms; shipping 220KB at 6MB/s alone
        # costs ~37ms -> decline
        budget = (len(t) + len(s)) * link.HOST_JOIN_S_PER_ROW
        assert join_kernel.inner_join_async(t, ok_t, s, ok_s, budget_s=budget) is None


def test_budget_accepts_on_fast_link():
    rng = np.random.RandomState(0)
    t = rng.randint(0, 1000, 50_000).astype(np.int64)
    s = rng.randint(500, 1500, 5_000).astype(np.int64)
    ok_t, ok_s = np.ones(len(t), bool), np.ones(len(s), bool)
    with _with_link(50_000.0, 50_000.0):  # PCIe-class
        pending = join_kernel.inner_join_async(
            t, ok_t, s, ok_s, budget_s=10.0
        )
        assert pending is not None
        res = pending.result()
    assert (res.t_matched == np.isin(t, s)).all()
    assert (res.s_matched == np.isin(s, t)).all()


def test_merge_auto_mode_declines_and_stays_correct(tmp_path):
    path = str(tmp_path / "auto")
    log = DeltaLog.for_table(path)
    WriteIntoDelta(log, "append", pa.table({
        "id": np.arange(1000, dtype=np.int64),
        "v": np.zeros(1000, np.int64),
    })).run()
    src = pa.table({"id": np.arange(500, 1500, dtype=np.int64),
                    "v": np.ones(1000, np.int64)})
    with _with_link(6.0, 4.0), conf.set_temporarily(**{
        "delta.tpu.merge.devicePath.mode": "auto",
    }):
        cmd = MergeIntoCommand(
            log, src, "t.id = s.id",
            [MergeClause("update", assignments=None)],
            [MergeClause("insert", assignments=None)],
            source_alias="s", target_alias="t",
        )
        cmd.run()
    assert cmd._device_join is None  # routed to the host hash join
    assert cmd.metrics["numTargetRowsUpdated"] == 500
    assert cmd.metrics["numTargetRowsInserted"] == 500


def test_rows_from_stats_reads_numrecords(tmp_path):
    path = str(tmp_path / "stats")
    log = DeltaLog.for_table(path)
    WriteIntoDelta(log, "append", pa.table({
        "id": np.arange(100, dtype=np.int64)})).run()
    files = log.update().all_files
    assert _rows_from_stats(files) == 100
    # files without stats -> None (fall back to post-decode routing)
    import dataclasses

    no_stats = [dataclasses.replace(f, stats=None) for f in files]
    assert _rows_from_stats(no_stats) is None


def test_host_join_fallback_when_no_sentinel_room():
    info = np.iinfo(np.int64)
    # valid keys span the whole int64 range -> no sentinel fits
    t = np.array([info.min, info.min + 1, 5, info.max - 1, info.max], np.int64)
    t_ok = np.array([True, True, True, True, True])
    s = np.array([info.min, 5, 7, info.max], np.int64)
    s_ok = np.array([True, True, False, True])
    pending = join_kernel.inner_join_async(t, t_ok, s, s_ok)
    assert pending is not None
    res = pending.result()
    assert list(res.t_matched) == [True, False, True, False, True]
    assert list(res.s_matched) == [True, True, False, True]
    assert res.any_multi is False
    # with a budget the caller's fallback is preferred
    with _with_link(6.0, 4.0):
        assert join_kernel.inner_join_async(t, t_ok, s, s_ok, budget_s=100.0) is None


# -- multi-host fan-out helpers (parallel/distributed.py) --------------------


def test_distributed_single_host_noop():
    from delta_tpu.parallel import distributed

    pid, n = distributed.initialize()
    assert (pid, n) == (0, 1)
    assert distributed.process_info()[1] >= 1


def test_host_partition_strided_and_complete():
    from delta_tpu.parallel.distributed import host_partition, host_shard_indices

    items = [f"f{i}" for i in range(10)]
    parts = [host_partition(items, index=i, count=3) for i in range(3)]
    # disjoint and complete
    flat = [x for p in parts for x in p]
    assert sorted(flat) == sorted(items)
    assert len(set(flat)) == len(items)
    # strided: host 0 gets 0,3,6,9
    assert parts[0] == ["f0", "f3", "f6", "f9"]
    # indices line up with the selection
    assert [items[j] for j in host_shard_indices(10, index=1, count=3)] == parts[1]


def test_host_partition_single_host_identity():
    from delta_tpu.parallel.distributed import host_partition

    items = list(range(5))
    assert host_partition(items, index=0, count=1) == items


def test_host_partition_rejects_half_specified_args():
    import pytest

    from delta_tpu.parallel.distributed import host_partition

    with pytest.raises(ValueError):
        host_partition([1, 2, 3], count=4)
    with pytest.raises(ValueError):
        host_partition([1, 2, 3], index=1)


def test_vacuum_deletes_only_this_hosts_slice(tmp_table, monkeypatch):
    """Vacuum's delete fan-out partitions candidates per process: a
    simulated 2-process runtime deletes only the strided half."""
    import os as _os
    import time as _time

    import pyarrow as pa

    from delta_tpu.api.tables import DeltaTable
    from delta_tpu.log.deltalog import DeltaLog
    from delta_tpu.parallel import distributed

    now = [int(_time.time() * 1000)]
    DeltaLog.clear_cache()
    DeltaLog.for_table(tmp_table, clock=lambda: now[0])
    t = DeltaTable.create(
        tmp_table, data=pa.table({"x": pa.array([1], pa.int64())})
    )
    for i in range(4):
        with open(_os.path.join(tmp_table, f"junk{i}.parquet"), "wb") as f:
            f.write(b"z")
    now[0] += 14 * 24 * 3_600_000
    monkeypatch.setattr(distributed, "process_info", lambda: (0, 2))
    r = t.vacuum()
    remaining = [f for f in _os.listdir(tmp_table) if f.startswith("junk")]
    assert len(remaining) == 2, "host 0 of 2 must delete exactly its half"
