"""Router audit ledger — every routed decision priced against what happened.

The link cost model (`parallel/link.py`) decides the MERGE join executor and
the scan-planning device/host pick, but until now nothing measured the miss:
on hardware unlike the bench machine the router silently picks the wrong
side forever. This ledger records one :class:`RouterAudit` per routed
decision — the per-candidate *predicted* costs the router compared, the
*actual* measured duration of the side it chose (from the operation's
existing phase timers), and the hindsight verdict:

    miss = some rejected candidate's predicted cost < the chosen side's
           actual cost

Every audit feeds ``router.predicted_ms`` / ``router.actual_ms`` histograms
(labeled op + decision), the ``router.audits`` / ``router.misses`` counters,
the ``router.missRate`` gauge, and — when calibration is enabled — hands its
attributable ``(constant, units, seconds)`` samples to `obs/calibration` so
the constants re-fit from live traffic. The last N records (bounded by
``delta.tpu.router.auditKeep``) are served by the HTTP ``/router`` route.

Blackout-gated end to end: ``delta.tpu.telemetry.enabled=false`` records
nothing and forwards nothing.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf

__all__ = ["RouterAudit", "record_audit", "recent_audits", "clear_audits",
           "audit_stats", "last_audit"]


@dataclass
class RouterAudit:
    """One routed decision: what the router believed, what actually ran."""

    op: str            # "merge.join" | "scan.plan"
    path: str          # table data path
    decision: str      # chosen route (e.g. "host", "resident", "device")
    predicted_ms: Dict[str, float]  # per candidate route
    actual_ms: float   # measured duration of the chosen route
    miss: bool         # hindsight: a rejected route's prediction beat actual
    units: Dict[str, float] = field(default_factory=dict)  # workload sizes
    extra: Dict[str, Any] = field(default_factory=dict)
    timestamp_ms: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "op": self.op,
            "path": self.path,
            "decision": self.decision,
            "predictedMs": {k: round(v, 3) for k, v in self.predicted_ms.items()},
            "actualMs": round(self.actual_ms, 3),
            "miss": self.miss,
            "units": dict(self.units),
            "extra": dict(self.extra),
            "timestamp": self.timestamp_ms,
        }


_LOCK = threading.Lock()
_AUDITS: "deque[RouterAudit]" = deque(maxlen=256)
_COUNTS = {"audits": 0, "misses": 0}


def _keep() -> int:
    try:
        n = int(conf.get("delta.tpu.router.auditKeep", 256))
    except (TypeError, ValueError):
        n = 256
    return n if n > 0 else 256


def record_audit(op: str, path: str, decision: str,
                 predicted_s: Dict[str, float], actual_s: float,
                 units: Optional[Dict[str, float]] = None,
                 samples: Sequence[Tuple[str, float, float]] = (),
                 log_path: Optional[str] = None,
                 calibration_flush: bool = True,
                 **extra: Any) -> Optional[RouterAudit]:
    """Record one routed decision (costs in SECONDS, stored in ms). Returns
    the audit, or None under a telemetry blackout. ``samples`` and
    ``log_path`` flow to `obs/calibration.ingest` (a no-op unless
    calibration is enabled); hot-path callers pass
    ``calibration_flush=False`` so the calibrator's state-file write is
    interval-throttled instead of per-decision."""
    if not conf.get_bool("delta.tpu.telemetry.enabled", True):
        return None
    predicted_ms = {k: float(v) * 1000.0 for k, v in predicted_s.items()}
    actual_ms = float(actual_s) * 1000.0
    chosen_pred = predicted_ms.get(decision)
    miss = any(v < actual_ms for k, v in predicted_ms.items() if k != decision)
    audit = RouterAudit(
        op=op, path=path, decision=decision, predicted_ms=predicted_ms,
        actual_ms=actual_ms, miss=miss, units=dict(units or {}),
        extra=dict(extra), timestamp_ms=int(time.time() * 1000),
    )
    keep = _keep()
    with _LOCK:
        global _AUDITS
        if _AUDITS.maxlen != keep:
            _AUDITS = deque(_AUDITS, maxlen=keep)
        _AUDITS.append(audit)
        _COUNTS["audits"] += 1
        if miss:
            _COUNTS["misses"] += 1
        rate = _COUNTS["misses"] / _COUNTS["audits"]
    telemetry.bump_counter("router.audits")
    if miss:
        telemetry.bump_counter("router.misses")
    telemetry.set_gauge("router.missRate", round(rate, 4))
    if chosen_pred is not None:
        telemetry.observe("router.predicted_ms", chosen_pred,
                          op=op, decision=decision)
    telemetry.observe("router.actual_ms", actual_ms, op=op, decision=decision)
    telemetry.record_event("delta.router.audit", audit.to_dict(), path=path)
    # workload journal: the audit outlives the in-memory ring, so routing
    # hindsight (miss rate over weeks, not minutes) feeds the advisor's
    # calibration recommendation (buffered; inert when journaling is off)
    if log_path is not None:
        from delta_tpu.obs import journal as journal_mod

        journal_mod.record_router(log_path, audit.to_dict())
    if samples:
        from delta_tpu.obs import calibration

        calibration.ingest(samples, log_path=log_path,
                           flush=calibration_flush)
    return audit


def last_audit() -> Optional[RouterAudit]:
    """The most recently recorded audit, if any — embedded into
    flight-recorder incidents so a failure shows what the router last
    decided, not just the span stack."""
    with _LOCK:
        return _AUDITS[-1] if _AUDITS else None


def recent_audits(limit: int = 32) -> List[Dict[str, Any]]:
    """The last ``limit`` audit records, oldest first, as JSON-able dicts."""
    with _LOCK:
        records = list(_AUDITS)
    if limit > 0:
        records = records[-limit:]
    return [a.to_dict() for a in records]


def audit_stats() -> Dict[str, Any]:
    """Totals since process start (or :func:`clear_audits`)."""
    with _LOCK:
        audits, misses = _COUNTS["audits"], _COUNTS["misses"]
    return {
        "audits": audits,
        "misses": misses,
        "missRate": round(misses / audits, 4) if audits else 0.0,
    }


def clear_audits() -> None:
    with _LOCK:
        _AUDITS.clear()
        _COUNTS["audits"] = _COUNTS["misses"] = 0
