"""Network LogStore over the conditional-PUT object-store dialect.

Covers the LogStore contract (atomic visibility, mutual exclusion via
``x-goog-if-generation-match: 0`` / ``If-None-Match: *``, consistent
listing), the retry/ambiguity policy under injected faults, and the OCC
commit conflict path end-to-end over HTTP — the multi-writer story the
reference delegates to HDFS rename (``storage/LogStore.scala:30-43``,
``LogStoreSuite.scala``).
"""
import threading
import time

import pytest

from tests.conftest import init_metadata

from delta_tpu.commands import operations as ops
from delta_tpu.log.deltalog import DeltaLog
from delta_tpu.protocol.actions import AddFile
from delta_tpu.storage.http_store import HttpObjectLogStore, RetryPolicy
from delta_tpu.storage.logstore import get_log_store
from delta_tpu.storage.object_store_emulator import ObjectStoreEmulator
from delta_tpu.utils import errors
from delta_tpu.utils.config import conf


@pytest.fixture(params=["gcs", "s3"])
def emu_store(request):
    with ObjectStoreEmulator() as emu:
        store = HttpObjectLogStore(
            emu.endpoint, dialect=request.param,
            retry=RetryPolicy(max_attempts=4, base_delay_s=0.01, timeout_s=5.0),
        )
        yield emu, store


LOG = "gs://bkt/tbl/_delta_log"


def _v(n: int) -> str:
    return f"{LOG}/{n:020d}.json"


# -- contract ---------------------------------------------------------------


def test_read_write_roundtrip(emu_store):
    _, store = emu_store
    store.write(_v(0), ["alpha", "beta"])
    assert store.read(_v(0)) == ["alpha", "beta"]
    assert store.exists(_v(0))
    assert not store.exists(_v(1))


def test_conditional_create_mutual_exclusion(emu_store):
    _, store = emu_store
    store.write(_v(0), ["first"])
    with pytest.raises(FileExistsError):
        store.write(_v(0), ["second"])
    assert store.read(_v(0)) == ["first"]
    store.write(_v(0), ["third"], overwrite=True)
    assert store.read(_v(0)) == ["third"]


def test_list_from_sorted_and_filtered(emu_store):
    _, store = emu_store
    for n in (2, 0, 1, 10):
        store.write(_v(n), [str(n)])
    # a deeper "subdirectory" object must not appear in the listing
    store.write(f"{LOG}/sub/dir.json", ["x"])
    names = [s.name for s in store.list_from(_v(1))]
    assert names == [f"{n:020d}.json" for n in (1, 2, 10)]


def test_list_from_missing_dir_raises(emu_store):
    _, store = emu_store
    with pytest.raises(FileNotFoundError):
        list(store.list_from("gs://bkt/nope/_delta_log/" + "0" * 20 + ".json"))


def test_read_missing_raises(emu_store):
    _, store = emu_store
    with pytest.raises(FileNotFoundError):
        store.read_bytes(_v(7))


def test_delete(emu_store):
    _, store = emu_store
    store.write(_v(0), ["x"])
    assert store.delete(_v(0))
    assert not store.delete(_v(0))
    assert not store.exists(_v(0))


def test_no_partial_write_visible(emu_store):
    _, store = emu_store
    assert store.is_partial_write_visible(_v(0)) is False


# -- races ------------------------------------------------------------------


def test_concurrent_create_exactly_one_winner(emu_store):
    emu, store = emu_store
    barrier = threading.Barrier(8)
    emu.before_put = lambda b, k: time.sleep(0.002)  # widen the race window
    results = []

    def writer(i):
        barrier.wait()
        try:
            store.write(_v(5), [f"writer-{i}"])
            results.append(("win", i))
        except FileExistsError:
            results.append(("lose", i))

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wins = [r for r in results if r[0] == "win"]
    assert len(wins) == 1, results
    assert store.read(_v(5)) == [f"writer-{wins[0][1]}"]


# -- fault injection --------------------------------------------------------


def test_retry_on_503(emu_store):
    emu, store = emu_store
    emu.fail_next(2, 503)
    store.write(_v(0), ["ok"])
    assert store.read(_v(0)) == ["ok"]


def test_retry_on_dropped_connection_read(emu_store):
    emu, store = emu_store
    store.write(_v(0), ["ok"])
    emu.fail_next(1, 0)  # sever the next connection mid-request
    assert store.read(_v(0)) == ["ok"]


def test_retries_exhausted_raises(emu_store):
    emu, store = emu_store
    emu.fail_next(100, 503)
    with pytest.raises(errors.DeltaIOError):
        store.read_bytes(_v(0))
    emu.fail_next(0)


def test_ambiguous_put_we_won(emu_store):
    """The store commits the PUT but the 200 is lost: the retried conditional
    PUT sees 412, reads the object back, finds its own bytes, and reports
    success — no spurious commit conflict."""
    emu, store = emu_store
    emu.drop_response_next_put()
    store.write(_v(3), ["mine"])  # must NOT raise
    assert store.read(_v(3)) == ["mine"]


def test_ambiguous_put_we_lost(emu_store):
    """First attempt is dropped *uncommitted*; a competing writer lands the
    object before the retry. Read-back shows foreign bytes → conflict."""
    emu, store = emu_store
    emu.fail_next(1, 0)  # drop attempt 0 before it commits
    fired = []

    def competitor(bucket, key):
        if not fired and key.endswith("3.json"):
            fired.append(True)
            with emu._mutex:
                emu._generation += 1
                emu._clock_ms += 1
                from delta_tpu.storage.object_store_emulator import _Object
                emu._objects[(bucket, key)] = _Object(
                    b"theirs\n", emu._generation, emu._clock_ms
                )

    emu.before_put = competitor
    with pytest.raises(FileExistsError):
        store.write(_v(3), ["mine"])
    assert store.read(_v(3)) == ["theirs"]


# -- registry ---------------------------------------------------------------


def test_cloud_scheme_without_endpoint_errors():
    with pytest.raises(errors.DeltaIOError, match="endpoint"):
        get_log_store("gs://bucket/table")


def test_cloud_scheme_with_endpoint_resolves():
    with ObjectStoreEmulator() as emu:
        with conf.set_temporarily(
            **{"delta.tpu.storage.objectStore.endpoint": emu.endpoint}
        ):
            store = get_log_store("gs://bucket/table")
            assert isinstance(store, HttpObjectLogStore)
            assert store.dialect == "gcs"
            s3 = get_log_store("s3://bucket/table")
            assert s3.dialect == "s3"


# -- OCC commits over the network store -------------------------------------


@pytest.fixture
def net_log():
    with ObjectStoreEmulator() as emu:
        store = HttpObjectLogStore(
            emu.endpoint, retry=RetryPolicy(max_attempts=4, base_delay_s=0.01,
                                            timeout_s=5.0),
        )
        DeltaLog.clear_cache()
        log = DeltaLog.for_table("gs://bkt/net_tbl", store=store)
        txn = log.start_transaction()
        txn.update_metadata(init_metadata())
        txn.commit([], ops.ManualUpdate())
        yield emu, log
        DeltaLog.clear_cache()


def _add(path):
    return AddFile(path, {}, 1, 1, True)


def test_commit_and_read_back_over_http(net_log):
    _, log = net_log
    v = log.start_transaction().commit([_add("f0")], ops.Write("Append"))
    assert v == 1
    snap = log.update()
    assert [a.path for a in snap.all_files] == ["f0"]


def test_concurrent_commit_retries_to_next_version(net_log):
    """Two blind appends race for the same version file: the loser's 412
    becomes a retry at v+1 (OptimisticTransaction.scala:672-674 semantics)."""
    _, log = net_log
    a = log.start_transaction()
    b = log.start_transaction()
    va = a.commit([_add("a")], ops.Write("Append"))
    vb = b.commit([_add("b")], ops.Write("Append"))
    assert sorted([va, vb]) == [1, 2]
    assert {x.path for x in log.update().all_files} == {"a", "b"}


def test_conflict_detected_over_http(net_log):
    """read-whole-table txn vs concurrent non-blind append → blocked."""
    _, log = net_log
    log.start_transaction().commit([_add("f0")], ops.Write("Append"))
    a = log.start_transaction()
    a.filter_files()  # reads the whole table
    b = log.start_transaction()
    b.filter_files()
    b.commit([_add("b1")], ops.Write("Append"))
    with pytest.raises(errors.ConcurrentAppendException):
        a.commit([_add("a1")], ops.Write("Append"))


def test_checkpoint_written_and_read_over_http(net_log):
    _, log = net_log
    for i in range(12):  # default checkpoint interval = 10
        log.start_transaction().commit([_add(f"f{i}")], ops.Write("Append"))
    from delta_tpu.log import checkpoints as ckpt_mod

    assert ckpt_mod.read_last_checkpoint(log.store, log.log_path) is not None
    DeltaLog.clear_cache()
    log2 = DeltaLog.for_table("gs://bkt/net_tbl", store=log.store)
    snap = log2.update()
    assert snap.version == 12
    assert len(list(snap.all_files)) == 12
