"""Fleet observability inspector — the CLI twin of ``/fleet`` and ``/slo``.

Two modes:

* **Remote** (``--url``): fetch a running engine's ``/fleet`` and ``/slo``
  routes (`delta_tpu/obs/server.py`) and pretty-print the ranked sweep,
  burn rates, and alerts — the operator's one-liner against a served
  process::

      python tools/fleet_dump.py --url http://127.0.0.1:8066
      python tools/fleet_dump.py --url http://127.0.0.1:8066 --slo
      python tools/fleet_dump.py --url http://127.0.0.1:8066 --json

* **In-process** (paths): open the given tables in THIS process, register
  them, and run the same fleet sweep locally — offline triage over tables
  on disk, no server required::

      python tools/fleet_dump.py /data/tbl1 /data/tbl2
      python tools/fleet_dump.py /data/tbl1 --sweep advisor --json

``--json`` prints the raw documents (pipe into ``jq``); the default output
is a compact ranked table (worst first).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fetch(url: str):
    with urllib.request.urlopen(url, timeout=30) as r:
        return json.loads(r.read().decode("utf-8"))


def _print_sweep(sweep) -> None:
    entries = (sweep or {}).get("entries", [])
    if not entries:
        print("  (no registered tables)")
        return
    for i, e in enumerate(entries, 1):
        if e.get("error"):
            print(f"  {i:>2}. {e['path']}  ERROR {e['error']}")
            continue
        remedies = ",".join(e.get("remedies") or []) or "-"
        print(f"  {i:>2}. [{e.get('severity', '?'):>8}] {e['path']} "
              f"(table={e.get('table')}) worst={e.get('worstDimension') or '-'} "
              f"crit={e.get('criticalDims', 0)} warn={e.get('warnDims', 0)} "
              f"score={e.get('topScore', 0)} remedies={remedies}")


def _print_slo(doc) -> None:
    print(f"SLO: enabled={doc.get('enabled')} firing={doc.get('firing')} "
          f"windows={doc.get('windows')}")
    for o in doc.get("objectives", []):
        print(f"  objective {o['name']}: {o['series']} <= {o['threshold']}"
              f"{' (per table)' if o.get('perTable') else ''}")
    alerts = doc.get("alerts", [])
    if not alerts:
        print("  no alerts")
    for a in alerts:
        state = "FIRING" if a.get("firing") else "cleared"
        print(f"  [{state}] {a['objective']} table={a.get('table') or '-'} "
              f"path={a.get('path') or '-'} burnFast={a.get('burnFast')} "
              f"burnSlow={a.get('burnSlow')} observed={a.get('observed')}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("tables", nargs="*",
                    help="table data paths to open + sweep in-process")
    ap.add_argument("--url", help="base URL of a running obs server "
                                  "(e.g. http://127.0.0.1:8066)")
    ap.add_argument("--sweep", choices=["doctor", "advisor"],
                    default="doctor", help="which fleet sweep to rank by")
    ap.add_argument("--slo", action="store_true",
                    help="only the SLO document (skip the fleet sweep)")
    ap.add_argument("--limit", type=int, default=None,
                    help="show only the worst N tables")
    ap.add_argument("--json", action="store_true",
                    help="print raw JSON documents instead of tables")
    args = ap.parse_args(argv)

    if args.url:
        base = args.url.rstrip("/")
        fleet_doc = None
        if not args.slo:
            route = f"{base}/fleet?sweep={args.sweep}"
            if args.limit is not None:
                route += f"&limit={args.limit}"
            fleet_doc = _fetch(route)
        slo_doc = _fetch(f"{base}/slo")
    else:
        if not args.tables:
            ap.error("give table paths or --url")
        from delta_tpu.log.deltalog import DeltaLog
        from delta_tpu.obs import fleet, slo as slo_mod, timeseries

        logs = [DeltaLog.for_table(p) for p in args.tables]  # registers
        timeseries.scrape_once()  # one scrape so /slo-style burns exist
        fleet_doc = None
        if not args.slo:
            report = (fleet.fleet_doctor() if args.sweep == "doctor"
                      else fleet.fleet_advise())
            fleet_doc = fleet.fleet_status()
            ranked = report.to_dict()
            if args.limit is not None:
                ranked["entries"] = ranked["entries"][:args.limit]
            fleet_doc["sweep"] = ranked
        slo_doc = slo_mod.status()
        del logs  # keep the handles alive through the sweep

    if args.json:
        doc = {"slo": slo_doc}
        if fleet_doc is not None:
            doc["fleet"] = fleet_doc
        print(json.dumps(doc, indent=1, default=str))
        return 0
    if fleet_doc is not None:
        print(f"fleet: {fleet_doc.get('tables', 0)} registered table(s); "
              f"sweep={args.sweep}")
        _print_sweep(fleet_doc.get("sweep"))
    _print_slo(slo_doc)
    return 0


if __name__ == "__main__":
    sys.exit(main())
