"""Group commit + incremental async checkpointing (ISSUE 9).

Covers: the group-commit coordinator (`txn/group_commit.py`) — concurrent
commits batched behind one tail read, intra-batch conflict checking,
external-race re-entry without per-member tail re-reads, crash-mid-batch
prefix durability; the `_check_and_retry` tail cache (one read per winning
commit across attempts AND across the reconcile read); the async
incremental checkpoint builder (`log/checkpointer.py`) — request
coalescing, incremental-vs-full result identity across the columnar and
dataclass read paths (DV + struct-stats lanes included), fallback seeding,
failure isolation; and the default-off byte-identity guarantee.
"""
import json
import threading
import time

import pyarrow as pa
import pytest

from delta_tpu.api.tables import DeltaTable
from delta_tpu.commands import operations as ops_mod
from delta_tpu.log import checkpointer
from delta_tpu.log import checkpoints as ck
from delta_tpu.log import columnar
from delta_tpu.log.checkpoints import CheckpointInstance
from delta_tpu.log.deltalog import DeltaLog
from delta_tpu.protocol import filenames
from delta_tpu.protocol.actions import AddFile, Metadata, RemoveFile, SetTransaction
from delta_tpu.schema.types import LongType, StructType
from delta_tpu.storage.faults import FaultPlan, SimulatedCrash
from delta_tpu.storage.logstore import MemoryLogStore
from delta_tpu.utils import errors, telemetry
from delta_tpu.utils.config import conf

GROUP_ON = {"delta.tpu.commit.group.enabled": True,
            "delta.tpu.commit.group.maxWaitMs": 200}


@pytest.fixture(autouse=True)
def _fresh():
    telemetry.reset_all()
    checkpointer.reset()
    yield
    telemetry.reset_all()
    checkpointer.reset()


def _schema_json():
    return StructType().add("id", LongType()).add("v", LongType()).to_json()


def _make_log(path) -> DeltaLog:
    log = DeltaLog.for_table(str(path))
    txn = log.start_transaction()
    txn.update_metadata(Metadata(schema_string=_schema_json()))
    txn.commit([], ops_mod.ManualUpdate())
    return log


def _add(name: str) -> AddFile:
    return AddFile(name, {}, 4096, 1, True,
                   stats='{"numRecords":8,"minValues":{"id":0},'
                         '"maxValues":{"id":7},"nullCount":{"id":0}}')


def _append(log: DeltaLog, name: str) -> int:
    txn = log.start_transaction()
    return txn.commit([_add(name)], ops_mod.Write("Append"))


# -- coordinator -------------------------------------------------------------


def test_concurrent_grouped_commits_all_land(tmp_path):
    """K barrier-released writers under grouping: every commit lands at a
    unique consecutive version, the snapshot sees every file, and at least
    one leader drained a real batch (>1 member) under the generous
    accumulation window."""
    log = _make_log(tmp_path / "t")
    K = 6
    versions = [None] * K
    barrier = threading.Barrier(K)

    def writer(w):
        barrier.wait()
        txn = log.start_transaction()
        versions[w] = txn.commit([_add(f"w{w}.parquet")], ops_mod.Write("Append"))

    with conf.set_temporarily(**GROUP_ON):
        threads = [threading.Thread(target=writer, args=(w,)) for w in range(K)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    assert sorted(versions) == list(range(1, K + 1))
    snap = log.update()
    assert snap.version == K
    assert len(snap.all_files) == K
    # batch evidence flowed into the stats events
    evs = [e for e in telemetry.recent_events("delta.commit.stats")
           if "batchSize" in e.data]
    assert len(evs) == K
    assert max(e.data["batchSize"] for e in evs) >= 2
    assert all(e.data["queueWaitMs"] >= 0 for e in evs)


def test_intra_batch_conflict_surfaces(tmp_path):
    """Two batchmates that would conflict had they raced ungrouped conflict
    inside the batch too: the remover lands, the reader of the removed file
    gets DeltaConcurrentModificationException — and its batchmate is
    unaffected."""
    log = _make_log(tmp_path / "t")
    _append(log, "f0.parquet")

    remover = log.start_transaction()
    reader = log.start_transaction()
    reader.filter_files()  # records the read of f0

    results = {}

    def run_remover():
        results["remover"] = remover.commit(
            [RemoveFile("f0.parquet", deletion_timestamp=1, data_change=True)],
            ops_mod.Delete([]))

    def run_reader():
        try:
            results["reader"] = reader.commit(
                [_add("g0.parquet")], ops_mod.Write("Append"))
        except errors.DeltaConcurrentModificationException as e:
            results["reader"] = e

    with conf.set_temporarily(**{"delta.tpu.commit.group.enabled": True,
                                 "delta.tpu.commit.group.maxWaitMs": 500}):
        t1 = threading.Thread(target=run_remover)
        t1.start()
        # deterministic queue order: the remover is enqueued (and leading)
        # before the reader joins its batch
        coord = log.group_coordinator
        for _ in range(500):
            with coord._cv:
                if coord._queue or results.get("remover") is not None:
                    break
            time.sleep(0.002)
        t2 = threading.Thread(target=run_reader)
        t2.start()
        t1.join()
        t2.join()

    assert results["remover"] == 2
    assert isinstance(results["reader"], errors.DeltaConcurrentModificationException)
    snap = log.update()
    assert snap.version == 2
    assert {f.path for f in snap.all_files} == set()
    assert telemetry.counters("commit")["commit.conflicts"] >= 1


def test_external_race_reenters_without_unwinding(tmp_path):
    """An external writer claiming the leader's target version mid-batch:
    the leader extends its tail snapshot by just the new commit and lands
    the member at the bumped version — one extra attempt, no per-member
    re-listing storm."""
    log = _make_log(tmp_path / "t")
    txn = log.start_transaction()
    orig_write = txn._write_commit
    fired = {}

    def racing_write(version, actions):
        if not fired.get("done"):
            fired["done"] = True
            # an external process wins exactly this version
            path = f"{log.log_path}/{filenames.delta_file(version)}"
            info = {"commitInfo": {"timestamp": 1, "operation": "WRITE",
                                   "operationParameters": {},
                                   "isBlindAppend": True, "txnId": "ext"}}
            add = {"add": {"path": "ext.parquet", "partitionValues": {},
                           "size": 1, "modificationTime": 1,
                           "dataChange": True}}
            log.store.write(path, [json.dumps(info), json.dumps(add)],
                            overwrite=False)
        return orig_write(version, actions)

    txn._write_commit = racing_write
    with conf.set_temporarily(**{"delta.tpu.commit.group.enabled": True,
                                 "delta.tpu.commit.group.maxWaitMs": 0}):
        version = txn.commit([_add("mine.parquet")], ops_mod.Write("Append"))

    assert version == 2  # bumped past the external winner at 1
    assert txn._group_meta["attempts"] == 2
    snap = log.update()
    assert {f.path for f in snap.all_files} == {"ext.parquet", "mine.parquet"}


def test_crash_mid_batch_leaves_durable_prefix(tmp_path, monkeypatch):
    """A process-death-class failure between batch members: the members
    already written stay durable AND resolve as committed (the coordinator
    knows their create landed — a false failure would invite a duplicate
    re-commit), every unfinished member observes the crash, and a
    recovered log sees exactly the prefix."""
    from delta_tpu.txn import group_commit as gc_mod

    log = _make_log(tmp_path / "t")
    K = 3
    results = [None] * K

    calls = {"n": 0}
    orig_fire = gc_mod.faults_mod.fire

    def crashing_fire(point, name=""):
        if point == "txn.groupLoop":
            calls["n"] += 1
            if calls["n"] == 3:
                # the leader dies AFTER members 1 and 2 created, BEFORE
                # member 3's create
                raise SimulatedCrash("txn.groupLoop")
        return orig_fire(point, name)

    monkeypatch.setattr(gc_mod.faults_mod, "fire", crashing_fire)

    def writer(w):
        txn = log.start_transaction()
        try:
            results[w] = txn.commit([_add(f"w{w}.parquet")],
                                    ops_mod.Write("Append"))
        except BaseException as e:  # noqa: BLE001 — SimulatedCrash expected
            results[w] = e

    with conf.set_temporarily(**{"delta.tpu.commit.group.enabled": True,
                                 "delta.tpu.commit.group.maxWaitMs": 1000}):
        coord = log.group_coordinator
        # deterministic single batch: writer 0 enqueues and leads (lingering
        # in its 1s accumulation window) while 1 and 2 join the queue
        threads = [threading.Thread(target=writer, args=(0,))]
        threads[0].start()
        for _ in range(1000):
            with coord._cv:
                if coord._leader_active:
                    break
            time.sleep(0.001)
        for w in (1, 2):
            t = threading.Thread(target=writer, args=(w,))
            t.start()
            threads.append(t)
        for t in threads:
            t.join()

    crashes = [r for r in results if isinstance(r, SimulatedCrash)]
    # writer 0 led: its create landed but the leader thread IS the crashed
    # context, so it re-raises (the ungrouped window, process-death
    # semantics). The committed NON-leader member resolves as success —
    # the coordinator knows its create landed, a false failure would
    # invite a duplicate re-commit — and the unfinished member crashes.
    assert len(crashes) == 2
    assert isinstance(results[0], SimulatedCrash)
    committed = [r for r in results[1:] if r == 2]
    assert len(committed) == 1  # whichever of writers 1/2 enqueued first
    # recovery: a fresh log sees exactly the durable prefix — two members'
    # files, written before the crash point
    DeltaLog.invalidate_cache(str(tmp_path / "t"))
    snap = DeltaLog(str(tmp_path / "t")).update()
    assert snap.version == 2
    assert len(snap.all_files) == 2


def test_group_off_never_constructs_coordinator(tmp_path):
    log = _make_log(tmp_path / "t")
    _append(log, "a.parquet")
    _append(log, "b.parquet")
    assert log._group_coordinator is None
    stats_evs = telemetry.recent_events("delta.commit.stats")
    assert all("batchSize" not in e.data for e in stats_evs)


def test_group_on_off_identical_log_bytes(tmp_path, monkeypatch):
    """With volatile inputs pinned (clock, commit token), the same
    single-writer workload produces byte-identical commit files with
    grouping on and off — the grouped path is a batching of the ungrouped
    write, not a different serialization."""
    import uuid as uuid_mod

    tokens = [f"{i:032x}" for i in range(100)]

    class _U:
        def __init__(self, h):
            self.hex = h

    def run(path, grouped):
        seq = iter(tokens)
        monkeypatch.setattr(
            "delta_tpu.txn.transaction.uuid.uuid4", lambda: _U(next(seq)))
        log = DeltaLog(str(path), clock=lambda: 1_700_000_000_000)
        txn = log.start_transaction()
        txn.update_metadata(Metadata(id="fixed-table-id",
                                     schema_string=_schema_json()))
        txn.commit([], ops_mod.ManualUpdate())
        overrides = {"delta.tpu.commit.group.enabled": grouped,
                     "delta.tpu.commit.group.maxWaitMs": 0}
        with conf.set_temporarily(**overrides):
            for i in range(4):
                _append(log, f"f{i}.parquet")
        out = []
        for v in range(0, 5):
            out.append(log.store.read(
                f"{log.log_path}/{filenames.delta_file(v)}"))
        return out

    assert run(tmp_path / "off", False) == run(tmp_path / "on", True)


# -- _check_and_retry tail cache ---------------------------------------------


class _CountingStore:
    """Delegating store wrapper tallying SUCCESSFUL read_iter opens per
    path (the base read_iter is a lazy generator: probe the first line
    eagerly so a miss — the retry loop's termination probe — is not
    counted as a read)."""

    def __init__(self, base):
        self._base = base
        self.reads = {}

    def read_iter(self, path):
        import itertools

        it = self._base.read_iter(path)
        try:
            first = next(it)
        except StopIteration:
            self.reads[path] = self.reads.get(path, 0) + 1
            return iter(())
        self.reads[path] = self.reads.get(path, 0) + 1
        return itertools.chain([first], it)

    def __getattr__(self, name):
        return getattr(self._base, name)


def test_retry_reads_each_winning_commit_once(tmp_path):
    """An N-attempt retry does one read per winning commit, not N: versions
    replayed by an earlier attempt are served from the per-txn tail cache
    when later attempts (and the reconcile read) revisit the window."""
    log = _make_log(tmp_path / "t")
    txn = log.start_transaction()  # read_version 0
    # two external winners land before our attempt
    _append(log, "x1.parquet")
    _append(log, "x2.parquet")

    counting = _CountingStore(log.store)
    log.store = counting
    try:
        orig_write = txn._write_commit
        raced = {}

        def race_once(version, actions):
            # attempt 3 loses too: a third winner sneaks in first (its own
            # commit + snapshot reads run unwrapped so the tally below is
            # exactly the txn-under-test's reads)
            if version == 3 and not raced.get("done"):
                raced["done"] = True
                log.store = counting._base
                try:
                    t2 = log.start_transaction()
                    t2.commit([_add("x3.parquet")], ops_mod.Write("Append"))
                finally:
                    log.store = counting
            return orig_write(version, actions)

        txn._write_commit = race_once
        version = txn.commit([_add("mine.parquet")], ops_mod.Write("Append"))
    finally:
        log.store = counting._base

    assert version == 4
    for v in (1, 2, 3):
        path = f"{log.log_path}/{filenames.delta_file(v)}"
        assert counting.reads.get(path, 0) == 1, (v, counting.reads)


def test_reconcile_read_seeds_retry_cache(tmp_path):
    """A lost ambiguous create reads version N once for reconciliation;
    the conflict replay that follows reuses those actions instead of
    re-reading the file."""
    log = _make_log(tmp_path / "t")
    txn = log.start_transaction()  # read_version 0

    counting = _CountingStore(log.store)
    log.store = counting
    try:
        orig_write = txn._write_commit
        state = {}

        def ambiguous_write(version, actions):
            if version == 1 and not state.get("done"):
                state["done"] = True
                # the external winner lands, then OUR create fails with an
                # indeterminate (transient-classified) error
                t2 = log.start_transaction()
                t2.commit([_add("theirs.parquet")], ops_mod.Write("Append"))
                raise ConnectionError("lost response")
            return orig_write(version, actions)

        txn._write_commit = ambiguous_write
        version = txn.commit([_add("mine.parquet")], ops_mod.Write("Append"))
    finally:
        log.store = counting._base

    assert version == 2
    assert getattr(txn, "_reconcile_outcome", None) is False
    # version 1 was read by store.read (reconcile); the conflict replay hit
    # the seeded cache, so read_iter never touched it
    path = f"{log.log_path}/{filenames.delta_file(1)}"
    assert counting.reads.get(path, 0) == 0


# -- incremental / async checkpointing ---------------------------------------


DV_PROPS = {"delta.tpu.enableDeletionVectors": "true"}


def _decoded_checkpoint(store, log_path, md):
    paths = CheckpointInstance(md.version, md.parts).paths(log_path)
    return ck.read_checkpoint_actions(store, paths), \
        columnar.decode_segment(store, paths, [])


def _action_key(a):
    return (type(a).__name__, getattr(a, "path", None),
            getattr(a, "app_id", None))


def test_incremental_checkpoint_result_identity(tmp_path):
    """The satellite identity bar: a checkpoint built incrementally from
    base M + tail-apply decodes to exactly the actions of a full
    reconstruction at the same version — dataclass AND columnar read
    paths, with DV descriptors and struct-stats lanes intact."""
    from delta_tpu.commands.write import WriteIntoDelta

    path = str(tmp_path / "t")

    def _rows(lo, n):
        return pa.table({"id": pa.array(range(lo, lo + n), pa.int64()),
                         "value": pa.array([f"v{i}" for i in range(n)])})

    t = DeltaTable.create(path, data=_rows(0, 40), configuration=DV_PROPS)
    log = t.delta_log
    WriteIntoDelta(log, "append", _rows(100, 20)).run()
    txn = log.start_transaction()
    txn.commit([SetTransaction("stream-app", 7, 123)], ops_mod.ManualUpdate())
    v_seed = log.update().version

    inc_on = {"delta.tpu.checkpoint.incremental": True}
    with conf.set_temporarily(**inc_on):
        checkpointer.build_checkpoint(log, v_seed)  # full build seeds the base
    assert telemetry.counters("checkpoint")[
        "checkpoint.incremental.fallback"] == 1
    assert checkpointer.base_version(path) == v_seed

    # tail past the base: a DV delete (add-with-DV + remove), another add,
    # and a whole-file remove — the lanes the incremental apply must carry
    t.delete("id < 5")
    before = {f.path for f in log.update().all_files}
    WriteIntoDelta(log, "append", _rows(200, 10)).run()
    third = next(iter({f.path for f in log.update().all_files} - before))
    txn = log.start_transaction()
    txn.commit([RemoveFile(third, deletion_timestamp=9, data_change=True)],
               ops_mod.Delete([]))
    v_n = log.update().version
    assert v_n > v_seed

    # reference: an INDEPENDENT full reconstruction of v_n (fresh DeltaLog,
    # decoded from the seed checkpoint + tail), checkpointed to a scratch
    # store BEFORE the incremental build can publish at v_n
    DeltaLog.invalidate_cache(path)
    ref_snap = DeltaLog(path).get_snapshot_at(v_n)
    ref_store = MemoryLogStore()
    # mirror DeltaLog.checkpoint's writer choice: columnar fast path, rows
    # fallback for the shapes it refuses (DVs force the rows path here)
    ref_md = ck.write_checkpoint_columnar(ref_store, "/ref/_delta_log",
                                          ref_snap, part_size=1_000_000)
    if ref_md is None:
        ref_md = ck.write_checkpoint(ref_store, "/ref/_delta_log", v_n,
                                     ref_snap.checkpoint_actions())
    ref_actions, ref_cols = _decoded_checkpoint(ref_store, "/ref/_delta_log",
                                                ref_md)

    with conf.set_temporarily(**inc_on):
        md = checkpointer.build_checkpoint(log, v_n)
    assert telemetry.counters("checkpoint")["checkpoint.incremental.built"] == 1
    assert checkpointer.base_version(path) == v_n
    inc_actions, inc_cols = _decoded_checkpoint(log.store, log.log_path, md)

    # dataclass read path: identical decoded actions (order-free)
    assert sorted(map(repr, sorted(inc_actions, key=_action_key))) == \
        sorted(map(repr, sorted(ref_actions, key=_action_key)))
    # DV lane really present
    dv_adds = [a for a in inc_actions
               if isinstance(a, AddFile) and a.deletion_vector is not None]
    assert dv_adds
    # columnar read path: same survivors, stats strings, struct-stats lanes
    inc_alive = inc_cols.winner_mask() & inc_cols.is_add
    ref_alive = ref_cols.winner_mask() & ref_cols.is_add
    assert sorted(inc_cols.paths_for(inc_alive)) == \
        sorted(ref_cols.paths_for(ref_alive))
    assert inc_cols.stats_parsed is not None
    assert ref_cols.stats_parsed is not None

    def _stats_by_path(cols, alive):
        paths = cols.paths_for(alive)
        sp = cols.stats_parsed.take(
            pa.array([i for i, m in enumerate(alive) if m])).to_pylist()
        return dict(zip(paths, map(str, sp)))

    assert _stats_by_path(inc_cols, inc_alive) == \
        _stats_by_path(ref_cols, ref_alive)

    # and the table reads back identically through the published checkpoint
    DeltaLog.invalidate_cache(path)
    back = DeltaTable.for_path(path).to_arrow().sort_by("id")
    assert back.column("id").to_pylist() == \
        list(range(5, 40)) + list(range(100, 120))


def test_incremental_chain_and_compaction_bound(tmp_path):
    """Consecutive incremental rounds keep building from the cached base;
    the dead-row compaction bound keeps the base from growing without
    bound (floor applies at these sizes, so rows just accumulate — the
    invariant under test is correctness across rounds)."""
    log = _make_log(tmp_path / "t")
    with conf.set_temporarily(**{"delta.tpu.checkpoint.incremental": True}):
        for r in range(3):
            for i in range(3):
                _append(log, f"r{r}-{i}.parquet")
            checkpointer.build_checkpoint(log, log.update().version)
    c = telemetry.counters("checkpoint")
    assert c["checkpoint.incremental.fallback"] == 1  # only the seed round
    assert c["checkpoint.incremental.built"] == 2
    DeltaLog.invalidate_cache(log.data_path)
    snap = DeltaLog(log.data_path).update()
    assert len(snap.all_files) == 9


def test_async_requests_coalesce_newest_wins(tmp_path, monkeypatch):
    monkeypatch.setattr(checkpointer, "_ensure_writer", lambda: None)
    log = _make_log(tmp_path / "t")
    for i in range(4):
        _append(log, f"f{i}.parquet")
    checkpointer.request_checkpoint(log, 2)
    checkpointer.request_checkpoint(log, 4)
    checkpointer.request_checkpoint(log, 3)  # stale: ignored
    assert checkpointer.pending_requests() == {log.data_path: 4}
    assert checkpointer.flush() == 1
    assert log.store.exists(
        f"{log.log_path}/{filenames.checkpoint_file_single(4)}")
    assert not log.store.exists(
        f"{log.log_path}/{filenames.checkpoint_file_single(2)}")


def test_async_interval_checkpoint_off_critical_path(tmp_path, monkeypatch):
    """With async on, the every-Nth-commit interval checkpoint is enqueued,
    not built inline: the committing writer returns before any checkpoint
    exists; a flush builds it."""
    monkeypatch.setattr(checkpointer, "_ensure_writer", lambda: None)
    log = _make_log(tmp_path / "t")
    with conf.set_temporarily(**{"delta.tpu.checkpoint.async": True}):
        # delta.checkpointInterval defaults to 10: v10 is the interval hit
        for i in range(10):
            _append(log, f"f{i}.parquet")
        ckpt = f"{log.log_path}/{filenames.checkpoint_file_single(10)}"
        assert not log.store.exists(ckpt)
        assert checkpointer.pending_requests() == {log.data_path: 10}
        checkpointer.flush()
        assert log.store.exists(ckpt)


def test_async_build_failure_isolated_and_recovers(tmp_path, monkeypatch):
    """A crash inside the async builder (injected at checkpoint.asyncBuild)
    never reaches a committer, drops the cached base, and the next build
    falls back to full reconstruction."""
    monkeypatch.setattr(checkpointer, "_ensure_writer", lambda: None)
    log = _make_log(tmp_path / "t")
    for i in range(3):
        _append(log, f"f{i}.parquet")
    with conf.set_temporarily(**{"delta.tpu.checkpoint.incremental": True}):
        checkpointer.build_checkpoint(log, 2)  # seeds the base
    assert checkpointer.base_version(log.data_path) == 2
    plan = FaultPlan(seed=3, script=[("checkpoint.asyncBuild",
                                      "crash_before_publish")])
    with conf.set_temporarily(**{"delta.tpu.faults.plan": plan,
                                 "delta.tpu.checkpoint.incremental": True}):
        checkpointer.request_checkpoint(log, 3)
        with pytest.raises(SimulatedCrash):
            checkpointer.flush()
        # the torn build forgot the base: no stale incremental state
        assert checkpointer.base_version(log.data_path) is None
    with conf.set_temporarily(**{"delta.tpu.checkpoint.incremental": True}):
        checkpointer.request_checkpoint(log, 3)
        assert checkpointer.flush() == 1
    assert telemetry.counters("checkpoint")[
        "checkpoint.incremental.fallback"] >= 2
    assert checkpointer.base_version(log.data_path) == 3
    assert log.store.exists(
        f"{log.log_path}/{filenames.checkpoint_file_single(3)}")


# -- observability / advisor -------------------------------------------------


def test_grouped_commit_journal_fields(tmp_path):
    """Journaled commit entries for grouped commits carry the measured
    batchSize/queueWaitMs so the advisor cites evidence, not inference."""
    from delta_tpu.obs import journal

    journal.reset()
    log = _make_log(tmp_path / "t")
    with conf.set_temporarily(**{"delta.tpu.commit.group.enabled": True,
                                 "delta.tpu.commit.group.maxWaitMs": 0}):
        _append(log, "a.parquet")
    journal.flush()
    commits = journal.read_entries(log.log_path, kinds=["commit"])
    grouped = [e for e in commits
               if (e.get("stats") or {}).get("batchSize") is not None]
    assert grouped
    st = grouped[-1]["stats"]
    assert st["batchSize"] == 1
    assert st["queueWaitMs"] >= 0
    journal.reset()


def test_advisor_contention_cites_group_evidence(tmp_path):
    """With grouped evidence in the journal, COMMIT_CONTENTION stops
    recommending the conf that is already on and cites the measured batch
    sizes and queue waits instead."""
    from delta_tpu.obs import journal
    from delta_tpu.obs.advisor import advise

    journal.reset()
    t = DeltaTable.create(str(tmp_path / "t"),
                          data=pa.table({"id": pa.array(range(5), pa.int64())}))
    log_path = t.delta_log.log_path
    for i in range(12):
        journal.record_commit(log_path, {
            "operation": "WRITE", "attempts": 3 if i % 2 else 1,
            "commitVersion": i, "batchSize": 4, "queueWaitMs": 1.5 + i,
        })
    rep = advise(str(tmp_path / "t"))
    cf = rep.facts["commits"]
    assert cf["groupedCommits"] == 12
    assert cf["meanBatchSize"] == 4.0
    assert cf["queueWaitP99Ms"] >= cf["queueWaitP50Ms"] >= 1.5
    [rec] = [r for r in rep.recommendations if r.kind == "COMMIT_CONTENTION"]
    assert rec.target == "delta.tpu.commit.group"
    assert rec.evidence["meanBatchSize"] == 4.0
    assert "maxBatch" in rec.action
    journal.reset()


def test_group_metrics_histograms_recorded(tmp_path):
    log = _make_log(tmp_path / "t")
    with conf.set_temporarily(**{"delta.tpu.commit.group.enabled": True,
                                 "delta.tpu.commit.group.maxWaitMs": 0}):
        _append(log, "a.parquet")
    names = {k[0] for k in telemetry.histograms("commit")}
    assert "commit.group.batchSize" in names
    assert "commit.queueWaitMs" in names


def test_doctor_stale_checkpoint_cites_async_conf(tmp_path):
    """The doctor's checkpoint dimension points at the async builder when
    the tail is long and async is off — and stops once it is on."""
    from delta_tpu.obs.doctor import doctor

    log = DeltaLog.for_table(str(tmp_path / "t"))
    txn = log.start_transaction()
    txn.update_metadata(Metadata(
        schema_string=_schema_json(),
        configuration={"delta.checkpointInterval": "1000"}))
    txn.commit([], ops_mod.ManualUpdate())
    for i in range(25):
        _append(log, f"f{i}.parquet")
    from delta_tpu.api.tables import DeltaTable as _DT

    t = _DT.for_path(log.data_path)
    ckpt = t.doctor().dimension("checkpoint")
    assert ckpt.severity != "ok"
    assert "delta.tpu.checkpoint.async" in ckpt.detail
    with conf.set_temporarily(**{"delta.tpu.checkpoint.async": True}):
        ckpt_on = t.doctor().dimension("checkpoint")
    assert "delta.tpu.checkpoint.async" not in ckpt_on.detail


def test_abandoned_waiter_removes_queued_entry(tmp_path):
    """A caller that observes a BaseException while its entry is still
    QUEUED (interrupt during the wait loop) removes the entry on the way
    out: a successor leader must never commit actions whose caller already
    saw failure — the app would retry and double-commit."""
    log = _make_log(tmp_path / "t")
    coord = log.group_coordinator
    txn = log.start_transaction()

    coord._leader_active = True  # park the caller in the wait loop

    def interrupting_wait(timeout=None):
        raise KeyboardInterrupt

    coord._cv.wait = interrupting_wait
    with pytest.raises(KeyboardInterrupt):
        coord.commit(txn, [_add("never.parquet")])
    assert coord._queue == []


# -- crash-safety narrowing (ISSUE 10 satellite): the daemon path ------------


def test_daemon_drain_crash_pierces_not_swallowed(tmp_path, monkeypatch):
    """Regression for the narrowed daemon-path handlers: a SimulatedCrash
    (process death) mid-batch must PIERCE the daemon drain — before the
    narrowing, ``_drain(raise_errors=False)`` swallowed BaseException and a
    "dead" writer kept draining the queue. An ordinary transient failure is
    still absorbed (the daemon survives IO flakiness)."""
    monkeypatch.setattr(checkpointer, "_ensure_writer", lambda: None)
    log = _make_log(tmp_path / "t")
    for i in range(3):
        _append(log, f"f{i}.parquet")
    plan = FaultPlan(seed=5, script=[("checkpoint.asyncBuild",
                                      "crash_before_publish")])
    with conf.set_temporarily(**{"delta.tpu.faults.plan": plan}):
        checkpointer.request_checkpoint(log, 3)
        with pytest.raises(SimulatedCrash):
            checkpointer._drain(raise_errors=False)  # the daemon's own path
    # a transient store error on the same path is absorbed, not raised
    plan2 = FaultPlan(seed=5, script=[("checkpoint.asyncBuild", "transient")])
    with conf.set_temporarily(**{"delta.tpu.faults.plan": plan2}):
        checkpointer.request_checkpoint(log, 3)
        assert checkpointer._drain(raise_errors=False) == 0
    # neither failure wedged the queue: a fresh request builds clean
    checkpointer.request_checkpoint(log, 3)
    assert checkpointer.flush() == 1
    assert log.store.exists(
        f"{log.log_path}/{filenames.checkpoint_file_single(3)}")


def test_daemon_thread_dies_on_crash_and_revives(tmp_path):
    """The delta-ckpt-async daemon thread now dies on a SimulatedCrash like
    the process it simulates; the next request revives a fresh writer — the
    crash-resume shape, at thread granularity."""
    log = _make_log(tmp_path / "t")
    for i in range(3):
        _append(log, f"f{i}.parquet")
    plan = FaultPlan(seed=7, script=[("checkpoint.asyncBuild",
                                      "crash_before_publish")])
    with conf.set_temporarily(**{"delta.tpu.faults.plan": plan}):
        checkpointer.request_checkpoint(log, 3)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            w = checkpointer._WRITER
            if w is not None and not w.is_alive() \
                    and not checkpointer.pending_requests():
                break
            time.sleep(0.02)
        w = checkpointer._WRITER
        assert w is not None and not w.is_alive(), \
            "the daemon must die on a simulated process death"
    # plan consumed; a new request spawns a fresh writer that completes
    checkpointer.request_checkpoint(log, 3)
    ckpt = f"{log.log_path}/{filenames.checkpoint_file_single(3)}"
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not log.store.exists(ckpt):
        time.sleep(0.02)
    assert log.store.exists(ckpt)
    assert checkpointer._WRITER.is_alive()
