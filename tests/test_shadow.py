"""Shadow optimizer (`delta_tpu/replay/`): journal→trace reconstruction with
the literal-sample reservoir, sandboxed what-if candidate scoring, the
advisor/autopilot closed loop (``shadowVerdict`` attachment, the
``requireShadow`` gate, the shadow-replay realized audit), time-compressed
SLO capacity replay, the ``/replay`` HTTP route, and the dump tool's
``--shadow`` view.
"""
import json
import os
import time
import urllib.parse

import pyarrow as pa
import pytest

from delta_tpu import autopilot
from delta_tpu.api.tables import DeltaTable
from delta_tpu.obs import journal
from delta_tpu.obs.advisor import advise
from delta_tpu.replay import shadow as shadow_mod
from delta_tpu.replay import (Candidate, TraceEvent, WorkloadTrace,
                              build_trace, capacity_replay, shadow_run,
                              zipf_hot_key_storm)
from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf


@pytest.fixture(autouse=True)
def _fresh_state():
    from delta_tpu.obs import slo, timeseries

    journal.reset()
    telemetry.reset_all()
    autopilot.reset()
    slo.reset()
    timeseries.reset()
    yield
    journal.reset()
    telemetry.clear_events()
    autopilot.reset()
    slo.reset()
    timeseries.reset()


def _ids(n):
    return pa.table({"id": pa.array(range(n), pa.int64()),
                     "v": pa.array(range(n), pa.int64())})


def _shadow_workload(path, v_scans=6, noise_scans=3, a_scans=4):
    """The acceptance layout: files clustered on ``id``/``a`` (file-level
    stats prune range scans), while ``v`` and ``noise`` span the full value
    domain in EVERY file — point scans on them never prune under the
    default coarse row groups, so the advisor recommends ZORDER for both.
    The ``v`` scans are selective (a ZORDER v rewrite genuinely wins); the
    ``noise`` scans match every row (a ZORDER noise rewrite gains nothing
    and destroys the ``a`` clustering — the deliberately-bad candidate)."""
    import numpy as np

    rng = np.random.RandomState(5)

    def _part(base, n=2000):
        return pa.table({
            "id": pa.array(range(base, base + n), pa.int64()),
            "a": pa.array(range(base, base + n), pa.int64()),
            "v": pa.array(rng.permutation(n).astype("int64")),
            "noise": pa.array(rng.permutation(n).astype("int64")),
        })

    # every scan keeps its own literal: the default 3-sample reservoir
    # would collapse later same-shape scans onto the first literal
    with conf.set_temporarily(**{"delta.tpu.journal.literalSamples": 16}):
        t = DeltaTable.create(path, data=_part(0))
        t.write(_part(2000), mode="append")
        t.write(_part(4000), mode="append")
        for i in range(v_scans):
            t.to_arrow(filters=[f"v = {i * 7}"])
        for _ in range(noise_scans):
            t.to_arrow(filters=["noise <= 1999"])  # matches every row
        for _ in range(a_scans):
            t.to_arrow(filters=["a < 100"])  # file-clustered range scan
    journal.flush()
    return t


# -- trace reconstruction ----------------------------------------------------


def test_trace_round_trip_rehydrates_reservoir_literals(tmp_table, tmp_path):
    t = _shadow_workload(tmp_table)
    trace = build_trace(t.delta_log)
    assert trace.source == "journal"
    assert trace.counts()["scan"] == 13
    assert trace.counts()["commit"] == 3
    scans = trace.scans()
    # every scan rehydrated to its EXACT concrete literal — no synthesis
    assert trace.synthesized_literals == 0
    assert [e.predicate for e in scans[:3]] == [
        "(v = 0)", "(v = 7)", "(v = 14)"]
    assert scans[0].fingerprint == "eq(v,?)"
    assert scans[0].payload["rowsOut"] == 3  # one hit per 2000-row file
    assert all(e.planning_ms >= 0 for e in scans)
    # serialize → load → identical trace
    p = str(tmp_path / "trace.json")
    trace.save(p)
    assert WorkloadTrace.load(p).to_dict() == trace.to_dict()
    assert telemetry.counters("replay.traces.built")["replay.traces.built"] == 1


def test_trace_sibling_samples_and_scan_limit(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(50))
    for i in range(5):
        t.to_arrow(filters=[f"v = {i}"])
    journal.flush()
    trace = build_trace(t.delta_log)
    scans = trace.scans()
    assert len(scans) == 5
    # scans past the 3-sample reservoir borrow a sibling literal recorded
    # under the SAME fingerprint key — executable, and NOT flagged synthetic
    assert scans[3].predicate == scans[0].predicate == "(v = 0)"
    assert trace.synthesized_literals == 0
    # limit keeps the NEWEST scans; non-scan events always survive
    bounded = build_trace(t.delta_log, limit=2)
    assert len(bounded.scans()) == 2
    assert bounded.counts()["commit"] == 1


def test_trace_synthesizes_literals_when_reservoir_disabled(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(100))
    with conf.set_temporarily(**{"delta.tpu.journal.literalSamples": 0}):
        t.to_arrow(filters=["v = 42"])
        t.to_arrow(filters=["v = 7"])
    journal.flush()
    trace = build_trace(t.delta_log)
    scans = trace.scans()
    # no literal survived anywhere: stats-guided synthesis fills in a
    # midpoint range predicate, flagged so scores discount the events
    assert trace.synthesized_literals == 2
    assert all(e.synthesized for e in scans)
    assert scans[0].predicate == "v <= 49"  # midpoint of [0, 99]
    c = telemetry.counters("replay.literals")
    assert c["replay.literals.synthesized"] == 2


# -- literal-sample reservoir (journal side) ---------------------------------


def test_literal_reservoir_first_k_then_redacts(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(50))
    for i in range(5):
        t.to_arrow(filters=[f"v = {i}"])
    journal.flush()
    scans = journal.read_entries(t.delta_log.log_path, kinds=["scan"])
    assert len(scans) == 5
    # first K=3 per fingerprint key carry the exact SQL
    assert [e.get("sample") for e in scans[:3]] == [
        "(v = 0)", "(v = 1)", "(v = 2)"]
    # past the bound: no sample AND the report predicate is redacted — the
    # reservoir is the ONLY place concrete literals persist
    for e in scans[3:]:
        assert "sample" not in e
        assert e["report"]["predicate"] is None
    c = telemetry.counters("journal.literalSamples")
    assert c["journal.literalSamples"] == 3


def test_literal_reservoir_is_per_fingerprint_key(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(50))
    for i in range(4):
        t.to_arrow(filters=[f"v = {i}"])
    for i in range(4):
        t.to_arrow(filters=[f"id > {i}"])
    journal.flush()
    scans = journal.read_entries(t.delta_log.log_path, kinds=["scan"])
    by_key = {}
    for e in scans:
        by_key.setdefault(e["fingerprint"]["key"], []).append(e)
    # each shape gets its own 3-sample budget
    for key in ("eq(v,?)", "gt(id,?)"):
        sampled = [e for e in by_key[key] if "sample" in e]
        assert len(sampled) == 3, key


def test_literal_reservoir_zero_redacts_everything(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(50))
    with conf.set_temporarily(**{"delta.tpu.journal.literalSamples": 0}):
        t.to_arrow(filters=["v = 9"])
    journal.flush()
    [e] = journal.read_entries(t.delta_log.log_path, kinds=["scan"])
    assert "sample" not in e
    assert e["report"]["predicate"] is None
    # the fingerprint (the abstract shape) still persists
    assert e["fingerprint"]["key"] == "eq(v,?)"


def test_literal_reservoir_size_bound_skips_oversized_sql(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(50))
    # >SAMPLE_MAX_SQL chars of conjuncts: too big to persist
    t.to_arrow(filters=[f"id < {10_000_000 + i}" for i in range(200)])
    t.to_arrow(filters=["v = 3"])
    journal.flush()
    scans = journal.read_entries(t.delta_log.log_path, kinds=["scan"])
    assert "sample" not in scans[0]
    assert scans[0]["report"]["predicate"] is None
    # the oversized predicate did not consume any key's budget
    assert scans[1]["sample"] == "(v = 3)"


def test_literal_reservoir_blackout_inert(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(50))
    with conf.set_temporarily(**{"delta.tpu.telemetry.enabled": False}):
        t.to_arrow(filters=["v = 99"])
    t.to_arrow(filters=["v = 1"])
    journal.flush()
    # the blackout scan journaled nothing; sampling resumes untouched after
    [e] = journal.read_entries(t.delta_log.log_path, kinds=["scan"])
    assert e["sample"] == "(v = 1)"


# -- shadow run: ranked measured scorecard -----------------------------------


def test_shadow_scorecard_ranks_zorder_candidate_first(tmp_table, tmp_path):
    t = _shadow_workload(tmp_table)
    sandbox_root = str(tmp_path / "sandboxes")
    os.makedirs(sandbox_root)
    # the deliberately-bad candidate: recoarsen the row groups — the
    # rewrite compacts everything into one giant group, losing the file
    # clustering the ``a < 100`` scan prunes on and gaining nothing
    cands = [Candidate("ROW_GROUP_ROWS", {"rows": 4_194_304}),
             Candidate("ZORDER", {"columns": ["v"]})]
    # the ZORDER rewrite gets fine-grained row groups; the baseline clone
    # keeps the table's coarse one-group-per-file layout
    with conf.set_temporarily(**{
            "delta.tpu.write.rowGroupRows": 64,
            "delta.tpu.replay.sandboxDir": sandbox_root}):
        card = shadow_run(t.delta_log, candidates=cands)
    # ranked: the genuinely-winning candidate first, with MEASURED deltas
    top = card.top
    assert top["candidate"]["label"] == "ZORDER:v"
    assert top["verdict"] == "confirmed"
    assert top["score"] > 0
    assert top["deltas"]["bytesSkipped"] > 0
    assert top["deltas"]["rowGroupsPruned"] > 0
    assert top["resultMismatch"] is False
    # replays returned identical results (rowsOut identity check held)
    assert top["metrics"]["rowsOut"] == card.baseline["rowsOut"]
    # the deliberately-bad candidate measures a LOSS and is refuted
    [bad] = [r for r in card.candidates
             if r["candidate"]["label"] == "ROW_GROUP_ROWS:4194304"]
    assert bad["verdict"] == "refuted"
    assert bad["score"] < 0
    # the loss is measured on the read side: the recoarsened table reads
    # bytes the baseline's file-tier pruning never touched
    assert bad["deltas"]["bytesRead"] > 0
    # journaled as a shadow entry, sandbox fully removed
    [e] = journal.read_entries(t.delta_log.log_path, kinds=["shadow"])
    assert e["scorecard"]["topCandidate"] == "ZORDER:v"
    assert os.listdir(sandbox_root) == []
    json.dumps(card.to_dict())  # JSON-able end to end
    c = telemetry.counters("shadow")
    assert c["shadow.runs"] == 1 and c["shadow.candidates"] == 2


def test_sandbox_cleanup_on_base_exception(tmp_table, tmp_path, monkeypatch):
    t = DeltaTable.create(tmp_table, data=_ids(50))
    sandbox_root = str(tmp_path / "sandboxes")
    os.makedirs(sandbox_root)

    def _boom(*a, **k):
        raise KeyboardInterrupt()

    monkeypatch.setattr(shadow_mod, "_replay_scans", _boom)
    trace = WorkloadTrace(path=tmp_table, built_at_ms=0, events=[
        TraceEvent(ts=1, kind="scan", predicate="v = 1")])
    with conf.set_temporarily(
            **{"delta.tpu.replay.sandboxDir": sandbox_root}):
        with pytest.raises(KeyboardInterrupt):
            shadow_run(t.delta_log, trace=trace, candidates=[])
    # BaseException mid-replay: no leaked clones
    assert os.listdir(sandbox_root) == []


# -- the closed loop: advise → gate → execute → realized audit ---------------


def test_shadow_closed_loop(tmp_table):
    t = _shadow_workload(tmp_table)
    # the advisor recommends ZORDER for BOTH never-pruned filter columns —
    # it cannot tell selective v from useless noise from stats alone
    pre = advise(tmp_table)
    pre_kinds = {(r.kind, r.target) for r in pre.recommendations}
    assert ("ZORDER", "v") in pre_kinds and ("ZORDER", "noise") in pre_kinds
    assert all(r.to_dict()["shadowVerdict"] == "untested"
               for r in pre.recommendations)

    # run 1: ZORDER v under fine row groups — the rewrite that wins
    with conf.set_temporarily(**{"delta.tpu.write.rowGroupRows": 64}):
        card = shadow_run(t.delta_log, candidates=[
            Candidate("ZORDER", {"columns": ["v"]})])
    assert card.top["candidate"]["label"] == "ZORDER:v"
    assert card.top["verdict"] == "confirmed"
    # run 2: ZORDER noise under the table's own coarse layout — clustering
    # on the non-selective column sacrifices the ``a`` file clustering for
    # zero gain; the measured verdict refutes the advisor's guess
    card2 = shadow_run(t.delta_log, candidates=[
        Candidate("ZORDER", {"columns": ["noise"]})])
    assert card2.top["verdict"] == "refuted"

    # 1) advise(): matching recs carry the measured verdicts
    rep = advise(tmp_table)
    recs = {(r.kind, r.target): r.to_dict() for r in rep.recommendations}
    zv = recs[("ZORDER", "v")]
    assert zv["shadowVerdict"] == "confirmed"
    assert zv["shadow"]["deltas"] == card.top["deltas"]
    assert zv["shadow"]["score"] == card.top["score"]
    zn = recs[("ZORDER", "noise")]
    assert zn["shadowVerdict"] == "refuted"
    assert rep.facts["shadow"]["runs"] == 2

    # 2) dry-run plan under requireShadow: the refuted action is suppressed
    # with the shadow evidence cited; the confirmed one passes the gate
    with conf.set_temporarily(**{
            "delta.tpu.autopilot.requireShadow": True,
            "delta.tpu.autopilot.maxActionsPerRun": 8}):
        dry = autopilot.run_once(tmp_table, force=True)
    assert "ZORDER:v" in dry.planned_keys
    assert "ZORDER:noise" not in dry.planned_keys
    filtered = {d["action"]: d for d in dry.shadow_filtered}
    assert filtered["ZORDER:noise"]["verdict"] == "refuted"
    assert "refuted by shadow run" in filtered["ZORDER:noise"]["reason"]
    assert filtered["ZORDER:noise"]["shadow"]["score"] == zn["shadow"]["score"]
    [planned_zv] = [a for a in dry.planned
                    if a["kind"] == "ZORDER" and a["target"] == "v"]
    assert planned_zv["evidence"]["shadow"]["verdict"] == "confirmed"

    # 3) execute: the realized rewrite improves with the SAME sign the
    # scorecard predicted, measured by replaying the scored trace against
    # the now-rewritten live table (auditSource=shadowReplay)
    with conf.set_temporarily(**{
            "delta.tpu.autopilot.dryRun": False,
            "delta.tpu.autopilot.requireShadow": True,
            "delta.tpu.autopilot.maxActionsPerRun": 8,
            "delta.tpu.autopilot.quietWindowMs": 50,
            "delta.tpu.write.rowGroupRows": 64}):
        time.sleep(0.1)
        run = autopilot.run_once(tmp_table, force=True)
    by_action = {o["action"]: o for o in run.outcomes}
    out = by_action["ZORDER:v"]
    assert out["status"] == "executed"
    audit = out["audit"]
    assert audit["auditSource"] == "shadowReplay"
    assert audit["verdict"] == "improved"
    assert audit["bytesSkippedDelta"] > 0
    assert (audit["realized"]["bytesSkipped"]
            > audit["shadowBaseline"]["bytesSkipped"])
    assert audit["shadowScore"] == card.top["score"]


def test_shadow_gate_defers_untested_rewrites(tmp_table):
    t = _shadow_workload(tmp_table)
    with conf.set_temporarily(**{
            "delta.tpu.autopilot.requireShadow": True,
            "delta.tpu.autopilot.maxActionsPerRun": 8}):
        dry = autopilot.run_once(tmp_table, force=True)
    # no shadow run exists: every rewrite-class action defers, with the
    # no-confirming-run reason cited in the report AND the journal ledger
    deferred = {d["action"]: d for d in dry.shadow_filtered}
    assert "ZORDER:v" in deferred
    assert deferred["ZORDER:v"]["verdict"] == "untested"
    assert "no confirming shadow run" in deferred["ZORDER:v"]["reason"]
    assert not any(k.startswith("ZORDER") for k in dry.planned_keys)
    journal.flush()
    ledger = journal.read_entries(t.delta_log.log_path, kinds=["autopilot"])
    assert any(e.get("phase") == "deferred"
               and (e.get("action") or {}).get("target") == "v"
               for e in ledger)


# -- capacity replay ---------------------------------------------------------


def test_capacity_replay_10x_fires_same_slo_objective(tmp_table):
    from delta_tpu.obs import slo, timeseries

    trace = zipf_hot_key_storm(path=tmp_table)
    overrides = {"delta.tpu.obs.slo.minObservations": 4}
    with conf.set_temporarily(**overrides):
        full = capacity_replay(trace, speed=1.0, now_ms=1_000_000_000_000)
    assert full["objectives"] == ["scanPlanningP99"]
    assert full["events"] == 120

    slo.reset()
    timeseries.reset()
    with conf.set_temporarily(**overrides):
        fast = capacity_replay(trace, speed=10.0, now_ms=2_000_000_000_000)
    # the compressed burn pre-fires the SAME objective in a tenth the time
    assert fast["objectives"] == full["objectives"]
    assert fast["simulatedMs"] == full["simulatedMs"] // 10
    assert fast["alerts"] and fast["alerts"][0]["firing"] is True
    assert fast["alerts"][0]["objective"] == "scanPlanningP99"
    c = telemetry.counters("replay.capacity")
    assert c["replay.capacity.runs"] == 2


def test_synthetic_scenarios_are_deterministic_and_serializable(tmp_path):
    from delta_tpu.replay import SCENARIOS

    for name, gen in SCENARIOS.items():
        a, b = gen(), gen()
        assert a.to_dict() == b.to_dict(), name
        assert a.source == f"synthetic:{name}"
        p = str(tmp_path / f"{name}.json")
        a.save(p)
        assert WorkloadTrace.load(p).to_dict() == a.to_dict()
    storm = SCENARIOS["zipfHotKeyStorm"]()
    assert any(e.payload.get("hotKey") for e in storm.scans())


# -- HTTP route + dump tool --------------------------------------------------


def test_replay_route_serves_scorecards_and_degrades_params(tmp_table):
    import http.client

    from delta_tpu.obs.server import ObsServer

    t = DeltaTable.create(tmp_table, data=_ids(20))
    journal.record_shadow(t.delta_log.log_path, {
        "ts": 123, "path": tmp_table, "trace": {}, "baseline": {},
        "candidates": [], "topCandidate": "ZORDER:v"})
    journal.flush()

    def _get(srv, route):
        c = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
        try:
            c.request("GET", route)
            r = c.getresponse()
            return r.status, json.loads(r.read())
        finally:
            c.close()

    srv = ObsServer(port=0)
    try:
        q = urllib.parse.quote(tmp_table)
        status, doc = _get(srv, f"/replay?path={q}")
        assert status == 200
        assert len(doc["shadowRuns"]) == 1
        assert doc["latest"]["topCandidate"] == "ZORDER:v"
        # malformed numeric params degrade to the default view, never 500
        status, doc2 = _get(srv, f"/replay?path={q}&limit=abc")
        assert status == 200 and doc2["latest"]["ts"] == 123
        status, err = _get(srv, "/replay")
        assert status == 400 and "path" in err["error"]
        status, err = _get(srv, "/nope")
        assert status == 404 and "/replay" in err["routes"]
    finally:
        srv.stop()


def test_journal_dump_shadow_views(tmp_table, capsys):
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from tools.journal_dump import main

    t = DeltaTable.create(tmp_table, data=_ids(20))
    journal.record_shadow(t.delta_log.log_path, {
        "ts": 5, "path": tmp_table, "trace": {"events": 3},
        "baseline": {"bytesSkipped": 0.0},
        "candidates": [
            {"candidate": {"kind": "ZORDER", "label": "ZORDER:v",
                           "params": {"columns": ["v"]}},
             "verdict": "confirmed", "score": 0.3,
             "deltas": {"bytesSkipped": 4096.0}}],
        "topCandidate": "ZORDER:v"})
    journal.flush()
    assert main([tmp_table, "--shadow"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["shadowRuns"] == 1
    assert doc["candidateVerdicts"] == {"confirmed": 1}
    [run] = doc["runs"]
    assert run["topCandidate"] == "ZORDER:v"
    assert run["candidates"][0]["deltas"]["bytesSkipped"] == 4096.0
    assert main([tmp_table, "--kind", "shadow"]) == 0
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    assert len(lines) == 1 and lines[0]["kind"] == "shadow"
