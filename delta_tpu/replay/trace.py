"""Trace reconstruction — journal segments → an ordered, replayable
:class:`WorkloadTrace`.

The journal persists scans as normalized predicate fingerprints
(``eq(v,?)``) plus a bounded literal-sample reservoir
(``delta.tpu.journal.literalSamples``, `obs/journal._stamp_sample`). This
module turns those segments back into something executable, rehydrating
each scan's concrete predicate in priority order:

1. the entry's own ``sample`` (reservoir hit — exact SQL),
2. the legacy un-redacted ``report["predicate"]`` (pre-reservoir segments),
3. a sibling sample recorded under the SAME fingerprint key (the workload
   shape is identical; only the literal differs),
4. stats-guided literal synthesis from the table's file-level min/max
   stats — flagged ``synthesized`` so shadow scores discount the event by
   ``delta.tpu.replay.literalDiscount`` (counter
   ``replay.literals.synthesized``).

Traces serialize to plain JSON (:meth:`WorkloadTrace.save` /
:meth:`WorkloadTrace.load`) — the synthetic scenario library
(`replay/scenarios`) emits the same format, so shadow runs, capacity
replays, torture, and bench all draw from one source.
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf

__all__ = ["TraceEvent", "WorkloadTrace", "build_trace"]

#: trace serialization format version (bump on incompatible change)
TRACE_FORMAT = 1

#: journal entry kinds that become trace events
_EVENT_KINDS = ("scan", "commit", "dml", "router")


@dataclass
class TraceEvent:
    """One replayable workload event (ordered by journal timestamp)."""

    ts: int
    kind: str  # scan | commit | dml | router
    #: concrete predicate SQL for scans (None = full-table scan)
    predicate: Optional[str] = None
    columns: Optional[List[str]] = None
    #: normalized fingerprint key (``eq(v,?)&lt(a,?)``-style) — the shape
    #: identity shadow candidates are matched on
    fingerprint: str = ""
    #: True when the literal came from stats-guided synthesis, not a
    #: recorded sample — scores discount these events
    synthesized: bool = False
    #: measured planning phase duration (capacity replay feeds this into
    #: the live ``delta.scan.planning.duration_ms`` histogram)
    planning_ms: float = 0.0
    #: kind-specific extras (commit outcome, dml op, router audit, scan
    #: skipping numbers) — carried for scoring context, not re-executed
    payload: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "ts": self.ts, "kind": self.kind, "predicate": self.predicate,
            "columns": list(self.columns) if self.columns is not None else None,
            "fingerprint": self.fingerprint, "synthesized": self.synthesized,
            "planningMs": self.planning_ms, "payload": dict(self.payload),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TraceEvent":
        return cls(
            ts=int(d.get("ts", 0)), kind=str(d.get("kind", "scan")),
            predicate=d.get("predicate"),
            columns=(list(d["columns"]) if d.get("columns") is not None
                     else None),
            fingerprint=str(d.get("fingerprint") or ""),
            synthesized=bool(d.get("synthesized", False)),
            planning_ms=float(d.get("planningMs", 0.0)),
            payload=dict(d.get("payload") or {}),
        )


@dataclass
class WorkloadTrace:
    """An ordered sequence of workload events for one table."""

    path: str
    built_at_ms: int
    events: List[TraceEvent] = field(default_factory=list)
    #: ``journal`` or ``synthetic:<scenario>``
    source: str = "journal"

    def scans(self) -> List[TraceEvent]:
        return [e for e in self.events if e.kind == "scan"]

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for e in self.events:
            out[e.kind] = out.get(e.kind, 0) + 1
        return out

    @property
    def synthesized_literals(self) -> int:
        return sum(1 for e in self.events if e.synthesized)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "format": TRACE_FORMAT, "path": self.path,
            "builtAtMs": self.built_at_ms, "source": self.source,
            "counts": self.counts(),
            "synthesizedLiterals": self.synthesized_literals,
            "events": [e.to_dict() for e in self.events],
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "WorkloadTrace":
        return cls(
            path=str(d.get("path") or ""),
            built_at_ms=int(d.get("builtAtMs", 0)),
            events=[TraceEvent.from_dict(e) for e in d.get("events") or ()],
            source=str(d.get("source") or "journal"),
        )

    def save(self, path: str) -> str:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_dict(), f, indent=1, sort_keys=True)
        return path

    @classmethod
    def load(cls, path: str) -> "WorkloadTrace":
        with open(path, "r", encoding="utf-8") as f:
            return cls.from_dict(json.load(f))


# ---------------------------------------------------------------------------
# Literal synthesis — stats-guided fallback for abstract fingerprints
# ---------------------------------------------------------------------------


def _column_ranges(snapshot) -> Dict[str, Tuple[Any, Any]]:
    """Per-column (min, max) over every live file's protocol stats —
    the raw material for synthesizing plausible literals."""
    ranges: Dict[str, Tuple[Any, Any]] = {}
    for add in snapshot.all_files:
        stats = add.stats_dict()
        if not stats:
            continue
        mins = stats.get("minValues") or {}
        maxs = stats.get("maxValues") or {}
        for col, lo in mins.items():
            hi = maxs.get(col)
            if lo is None or hi is None:
                continue
            key = col.lower()
            cur = ranges.get(key)
            if cur is None:
                ranges[key] = (lo, hi)
            else:
                try:
                    ranges[key] = (min(cur[0], lo), max(cur[1], hi))
                except TypeError:
                    pass  # mixed-type stats: keep the first sighting
    return ranges


def _sql_literal(value: Any) -> str:
    if isinstance(value, bool):
        return "TRUE" if value else "FALSE"
    if isinstance(value, (int, float)):
        return repr(value)
    return "'" + str(value).replace("'", "''") + "'"


def _synthesize_predicate(fingerprint: Dict[str, Any],
                          ranges: Dict[str, Tuple[Any, Any]]
                          ) -> Optional[str]:
    """Build an executable stand-in predicate for an abstracted fingerprint:
    one ``col <= <midpoint>`` conjunct per prunable column with known stats
    (numeric midpoint halves the range; strings fall back to ``<= min``,
    the most selective sound choice). Returns None when no referenced
    column has usable stats — the event replays as a full-table scan."""
    conjuncts: List[str] = []
    cols = (fingerprint.get("prunableColumns")
            or fingerprint.get("columns") or [])
    for col in cols:
        rng = ranges.get(col.lower())
        if rng is None:
            continue
        lo, hi = rng
        if isinstance(lo, bool) or isinstance(hi, bool):
            target: Any = lo
        elif isinstance(lo, (int, float)) and isinstance(hi, (int, float)):
            target = (lo + hi) / 2.0
            if isinstance(lo, int) and isinstance(hi, int):
                target = int(target)
        else:
            target = lo
        conjuncts.append(f"{col} <= {_sql_literal(target)}")
    return " AND ".join(conjuncts) if conjuncts else None


# ---------------------------------------------------------------------------
# build_trace
# ---------------------------------------------------------------------------


def _resolve_log(table: Any):
    """Accept a path, a DeltaTable, or a DeltaLog."""
    from delta_tpu.log.deltalog import DeltaLog

    if isinstance(table, DeltaLog):
        return table
    log = getattr(table, "delta_log", None)
    if log is not None:
        return log
    return DeltaLog.for_table(os.fspath(table))


def build_trace(table: Any, limit: Optional[int] = None,
                before_ts: Optional[int] = None) -> WorkloadTrace:
    """Reconstruct a table's :class:`WorkloadTrace` from its journal.

    ``limit`` bounds the number of SCAN events kept (newest win; default
    ``delta.tpu.replay.maxScans``); non-scan events are always kept — they
    cost nothing to carry and capacity replay wants the full timeline.
    ``before_ts`` drops events at/after that journal timestamp — the
    realized-audit path uses it to replay exactly the workload a shadow
    scorecard was scored on."""
    import time as _time

    from delta_tpu.obs import journal

    delta_log = _resolve_log(table)
    journal.flush(delta_log.log_path)
    entries = journal.read_entries(delta_log.log_path, kinds=_EVENT_KINDS)
    if before_ts is not None:
        entries = [e for e in entries if int(e.get("ts", 0)) < before_ts]

    # pass 1: collect reservoir samples per fingerprint key so sampled
    # entries can donate literals to same-shape entries past the bound
    samples_by_key: Dict[str, str] = {}
    for e in entries:
        if e.get("kind") != "scan":
            continue
        key = (e.get("fingerprint") or {}).get("key")
        sample = e.get("sample")
        if key and sample and key not in samples_by_key:
            samples_by_key[key] = sample

    ranges: Optional[Dict[str, Tuple[Any, Any]]] = None  # built lazily
    events: List[TraceEvent] = []
    synthesized = 0
    for e in entries:
        ts = int(e.get("ts", 0))
        kind = e.get("kind")
        if kind != "scan":
            payload = {k: v for k, v in e.items()
                       if k not in ("kind", "ts") and not k.startswith("_")}
            events.append(TraceEvent(ts=ts, kind=str(kind), payload=payload))
            continue
        report = e.get("report") or {}
        fp = e.get("fingerprint") or {}
        key = str(fp.get("key") or "")
        predicate: Optional[str] = None
        synth = False
        had_predicate = bool(key) or report.get("predicate") is not None
        if had_predicate:
            predicate = (e.get("sample") or report.get("predicate")
                         or samples_by_key.get(key))
            if predicate is None:
                if ranges is None:
                    ranges = _column_ranges(delta_log.update())
                predicate = _synthesize_predicate(fp, ranges)
                if predicate is not None:
                    synth = True
                    synthesized += 1
        phase = report.get("phaseMs") or {}
        events.append(TraceEvent(
            ts=ts, kind="scan", predicate=predicate,
            columns=report.get("columns"), fingerprint=key,
            synthesized=synth,
            planning_ms=float(phase.get("planning", 0) or 0),
            payload={
                "bytesRead": report.get("bytesRead", 0),
                "bytesSkipped": report.get("bytesSkipped", 0),
                "rowsOut": report.get("rowsOut", 0),
            },
        ))

    max_scans = limit if limit is not None else conf.get_int(
        "delta.tpu.replay.maxScans", 256)
    scan_idx = [i for i, ev in enumerate(events) if ev.kind == "scan"]
    if max_scans is not None and len(scan_idx) > max_scans:
        drop = set(scan_idx[:len(scan_idx) - max_scans])
        events = [ev for i, ev in enumerate(events) if i not in drop]

    telemetry.bump_counter("replay.traces.built")
    if synthesized:
        telemetry.bump_counter("replay.literals.synthesized", by=synthesized)
    return WorkloadTrace(
        path=delta_log.data_path, built_at_ms=int(_time.time() * 1000),
        events=events, source="journal",
    )
