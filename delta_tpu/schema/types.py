"""Schema type system for delta-tpu.

A minimal, self-contained implementation of the Spark-SQL JSON schema format that
Delta's ``Metadata.schemaString`` uses (reference: ``PROTOCOL.md`` "Schema
Serialization Format"; consumed in ``actions/actions.scala:348-393``). We keep the
serialized form byte-compatible so tables written by the reference can be read and
vice versa, but the in-memory representation is our own and maps onto pyarrow (host
columnar) and numpy/JAX dtypes (device columnar).
"""
from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


__all__ = [
    "DataType",
    "AtomicType",
    "ArrayType",
    "MapType",
    "StructField",
    "StructType",
    "parse_data_type",
    "schema_from_json",
    "BooleanType",
    "ByteType",
    "ShortType",
    "IntegerType",
    "LongType",
    "FloatType",
    "DoubleType",
    "StringType",
    "BinaryType",
    "DateType",
    "TimestampType",
    "DecimalType",
    "NullType",
    "CharType",
    "VarcharType",
]


class DataType:
    """Base of the type hierarchy."""

    #: Spark-SQL JSON name, e.g. "integer"
    name: str = ""

    def json_value(self) -> Any:
        return self.name

    def to_json(self) -> str:
        return json.dumps(self.json_value(), separators=(",", ":"))

    def simple_string(self) -> str:
        return self.name

    def __eq__(self, other: Any) -> bool:
        return type(self) is type(other) and self.__dict__ == other.__dict__

    def __hash__(self) -> int:
        return hash((type(self).__name__, tuple(sorted(self.__dict__.items(), key=lambda kv: kv[0]))))

    def __repr__(self) -> str:
        return f"{type(self).__name__}()"


class AtomicType(DataType):
    pass


class NullType(AtomicType):
    name = "null"


class BooleanType(AtomicType):
    name = "boolean"


class ByteType(AtomicType):
    name = "byte"


class ShortType(AtomicType):
    name = "short"


class IntegerType(AtomicType):
    name = "integer"


class LongType(AtomicType):
    name = "long"


class FloatType(AtomicType):
    name = "float"


class DoubleType(AtomicType):
    name = "double"


class StringType(AtomicType):
    name = "string"


class CharType(AtomicType):
    """Fixed-length character type (`CharVarcharUtils.scala`). Stored in
    table metadata as STRING plus the `__CHAR_VARCHAR_TYPE_STRING` field
    metadata (the reference's wire form); values are space-padded to
    ``length`` on write and length-enforced."""

    def __init__(self, length: int):
        if length < 1:
            raise ValueError("char length must be >= 1")
        self.length = length

    @property
    def name(self) -> str:  # type: ignore[override]
        return f"char({self.length})"

    def __repr__(self) -> str:
        return f"CharType({self.length})"


class VarcharType(AtomicType):
    """Bounded-length character type: stored as STRING + field metadata;
    writes longer than ``length`` characters are rejected."""

    def __init__(self, length: int):
        if length < 1:
            raise ValueError("varchar length must be >= 1")
        self.length = length

    @property
    def name(self) -> str:  # type: ignore[override]
        return f"varchar({self.length})"

    def __repr__(self) -> str:
        return f"VarcharType({self.length})"


class BinaryType(AtomicType):
    name = "binary"


class DateType(AtomicType):
    name = "date"


class TimestampType(AtomicType):
    name = "timestamp"


class DecimalType(AtomicType):
    def __init__(self, precision: int = 10, scale: int = 0):
        self.precision = precision
        self.scale = scale

    @property
    def name(self) -> str:  # type: ignore[override]
        return f"decimal({self.precision},{self.scale})"

    def __repr__(self) -> str:
        return f"DecimalType({self.precision},{self.scale})"


class ArrayType(DataType):
    def __init__(self, element_type: DataType, contains_null: bool = True):
        self.element_type = element_type
        self.contains_null = contains_null

    def json_value(self) -> Any:
        return {
            "type": "array",
            "elementType": self.element_type.json_value(),
            "containsNull": self.contains_null,
        }

    def simple_string(self) -> str:
        return f"array<{self.element_type.simple_string()}>"

    def __repr__(self) -> str:
        return f"ArrayType({self.element_type!r}, {self.contains_null})"


class MapType(DataType):
    def __init__(self, key_type: DataType, value_type: DataType, value_contains_null: bool = True):
        self.key_type = key_type
        self.value_type = value_type
        self.value_contains_null = value_contains_null

    def json_value(self) -> Any:
        return {
            "type": "map",
            "keyType": self.key_type.json_value(),
            "valueType": self.value_type.json_value(),
            "valueContainsNull": self.value_contains_null,
        }

    def simple_string(self) -> str:
        return f"map<{self.key_type.simple_string()},{self.value_type.simple_string()}>"

    def __repr__(self) -> str:
        return f"MapType({self.key_type!r}, {self.value_type!r}, {self.value_contains_null})"


@dataclass
class StructField:
    name: str
    data_type: DataType
    nullable: bool = True
    metadata: Dict[str, Any] = field(default_factory=dict)

    def json_value(self) -> Any:
        return {
            "name": self.name,
            "type": self.data_type.json_value(),
            "nullable": self.nullable,
            "metadata": self.metadata,
        }


class StructType(DataType):
    def __init__(self, fields: Optional[List[StructField]] = None):
        self.fields: List[StructField] = list(fields or [])

    def json_value(self) -> Any:
        return {"type": "struct", "fields": [f.json_value() for f in self.fields]}

    def simple_string(self) -> str:
        inner = ",".join(f"{f.name}:{f.data_type.simple_string()}" for f in self.fields)
        return f"struct<{inner}>"

    def add(self, name: str, data_type: DataType, nullable: bool = True,
            metadata: Optional[Dict[str, Any]] = None) -> "StructType":
        self.fields.append(StructField(name, data_type, nullable, dict(metadata or {})))
        return self

    def add_field(self, field: "StructField") -> "StructType":
        self.fields.append(field)
        return self

    @property
    def field_names(self) -> List[str]:
        return [f.name for f in self.fields]

    def __getitem__(self, name: str) -> StructField:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(name)

    def __contains__(self, name: str) -> bool:
        return any(f.name == name for f in self.fields)

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, StructType) and self.fields == other.fields

    def __hash__(self) -> int:
        return hash(self.to_json())

    def __repr__(self) -> str:
        return f"StructType({self.fields!r})"


_ATOMIC_TYPES: Dict[str, DataType] = {
    t.name: t()
    for t in (
        NullType,
        BooleanType,
        ByteType,
        ShortType,
        IntegerType,
        LongType,
        FloatType,
        DoubleType,
        StringType,
        BinaryType,
        DateType,
        TimestampType,
    )
}
# Spark accepts a few aliases in schema JSON.
_ATOMIC_ALIASES = {
    "int": "integer",
    "bigint": "long",
    "smallint": "short",
    "tinyint": "byte",
}

_DECIMAL_RE = re.compile(r"decimal\(\s*(\d+)\s*,\s*(-?\d+)\s*\)")
_CHAR_RE = re.compile(r"char\(\s*(\d+)\s*\)")
_VARCHAR_RE = re.compile(r"varchar\(\s*(\d+)\s*\)")


def parse_data_type(obj: Any) -> DataType:
    """Parse the JSON value form of a data type (string or nested dict)."""
    if isinstance(obj, str):
        s = _ATOMIC_ALIASES.get(obj, obj)
        if s in _ATOMIC_TYPES:
            return _ATOMIC_TYPES[s]
        m = _DECIMAL_RE.fullmatch(s)
        if m:
            return DecimalType(int(m.group(1)), int(m.group(2)))
        if s == "decimal":
            return DecimalType(10, 0)
        m = _CHAR_RE.fullmatch(s)
        if m:
            return CharType(int(m.group(1)))
        m = _VARCHAR_RE.fullmatch(s)
        if m:
            return VarcharType(int(m.group(1)))
        raise ValueError(f"Unsupported data type: {obj!r}")
    if isinstance(obj, dict):
        t = obj.get("type")
        if t == "struct":
            return StructType(
                [
                    StructField(
                        f["name"],
                        parse_data_type(f["type"]),
                        bool(f.get("nullable", True)),
                        dict(f.get("metadata") or {}),
                    )
                    for f in obj.get("fields", [])
                ]
            )
        if t == "array":
            return ArrayType(parse_data_type(obj["elementType"]), bool(obj.get("containsNull", True)))
        if t == "map":
            return MapType(
                parse_data_type(obj["keyType"]),
                parse_data_type(obj["valueType"]),
                bool(obj.get("valueContainsNull", True)),
            )
        if t == "udt":  # not supported; treat underlying sql type if present
            if "sqlType" in obj:
                return parse_data_type(obj["sqlType"])
    raise ValueError(f"Unsupported data type JSON: {obj!r}")


def schema_from_json(s: str) -> StructType:
    dt = parse_data_type(json.loads(s))
    if not isinstance(dt, StructType):
        raise ValueError("schema JSON must be a struct type")
    return dt


# ---------------------------------------------------------------------------
# pyarrow interop
# ---------------------------------------------------------------------------

def to_arrow_type(dt: DataType):
    import pyarrow as pa

    if isinstance(dt, BooleanType):
        return pa.bool_()
    if isinstance(dt, ByteType):
        return pa.int8()
    if isinstance(dt, ShortType):
        return pa.int16()
    if isinstance(dt, IntegerType):
        return pa.int32()
    if isinstance(dt, LongType):
        return pa.int64()
    if isinstance(dt, FloatType):
        return pa.float32()
    if isinstance(dt, DoubleType):
        return pa.float64()
    if isinstance(dt, (StringType, CharType, VarcharType)):
        return pa.string()
    if isinstance(dt, BinaryType):
        return pa.binary()
    if isinstance(dt, DateType):
        return pa.date32()
    if isinstance(dt, TimestampType):
        # Spark timestamps are microsecond-precision UTC-normalized.
        return pa.timestamp("us")
    if isinstance(dt, DecimalType):
        return pa.decimal128(dt.precision, dt.scale)
    if isinstance(dt, NullType):
        return pa.null()
    if isinstance(dt, ArrayType):
        return pa.list_(to_arrow_type(dt.element_type))
    if isinstance(dt, MapType):
        return pa.map_(to_arrow_type(dt.key_type), to_arrow_type(dt.value_type))
    if isinstance(dt, StructType):
        return pa.struct([(f.name, to_arrow_type(f.data_type)) for f in dt.fields])
    raise ValueError(f"No arrow mapping for {dt!r}")


def to_arrow_schema(schema: StructType):
    import pyarrow as pa

    return pa.schema([pa.field(f.name, to_arrow_type(f.data_type), f.nullable) for f in schema.fields])
