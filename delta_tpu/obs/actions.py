"""Shared maintenance-action catalog — one machine-readable Action model.

The doctor (`obs/doctor`) names remedies and the advisor (`obs/advisor`)
names recommended actions; until this module they did it as free-form
strings, which meant any consumer (dashboards, and now the autopilot
scheduler in `delta_tpu/autopilot/`) had to string-match two surfaces that
could silently drift. This catalog closes that: every remedy either surface
emits is an :class:`ActionSpec` here — validated at emit time exactly the
way ``metric_names.health_gauge`` validates gauge names — and the autopilot
consumes :class:`MaintenanceAction` objects whose ``kind`` is a catalog
key, never a parsed string.

``executable`` marks the actions the autopilot may run unattended: layout
and metadata maintenance whose failure paths are torture-tested (OPTIMIZE /
ZORDER / CHECKPOINT / VACUUM / PURGE) plus two process-local knob turns
(EVICT, RECALIBRATE). REPARTITION and TUNE stay human decisions — a
partition-scheme or conf change is a policy choice, not maintenance.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

__all__ = ["ActionSpec", "MaintenanceAction", "CATALOG", "CATALOG_REF",
           "RECOMMENDATION_ACTIONS", "COOLDOWN_PHASES", "spec",
           "remedy_name", "executable_kinds", "action_key",
           "attempts_in_cooldown"]

#: Stable dotted reference both report ``to_dict`` outputs cite, so a JSON
#: consumer can find the catalog without guessing.
CATALOG_REF = "delta_tpu.obs.actions.CATALOG"


@dataclass(frozen=True)
class ActionSpec:
    """One maintenance action the engine knows how to talk about."""

    name: str
    executable: bool    # the autopilot may run it unattended
    mutates_table: bool  # writes to the table dir / log (vs process-local)
    description: str


CATALOG: Dict[str, ActionSpec] = {s.name: s for s in (
    ActionSpec("OPTIMIZE", True, True,
               "bin-pack small files per partition into compaction targets"),
    ActionSpec("ZORDER", True, True,
               "re-sort selected files by the Morton key of hot filter "
               "columns so min/max stats become selective"),
    ActionSpec("CHECKPOINT", True, True,
               "write a checkpoint so cold snapshot builds stop replaying "
               "the log tail"),
    ActionSpec("VACUUM", True, True,
               "delete unreferenced data files past the retention horizon"),
    ActionSpec("PURGE", True, True,
               "rewrite files carrying deletion vectors, materializing the "
               "soft deletes"),
    ActionSpec("EVICT", True, False,
               "apply HBM soft-budget pressure to the device-resident "
               "caches (key cache / state cache LRU)"),
    ActionSpec("RECALIBRATE", True, False,
               "re-apply the persisted router calibration state to the "
               "link cost constants"),
    ActionSpec("REPARTITION", False, True,
               "change the table's partition scheme (human decision)"),
    ActionSpec("TUNE", False, False,
               "session/table conf change (human decision)"),
)}


#: Advisor ``Recommendation.kind`` → catalog action executing (or citing) it.
RECOMMENDATION_ACTIONS: Dict[str, str] = {
    "ZORDER": "ZORDER",
    "PARTITION": "REPARTITION",
    "ROW_GROUP_SIZE": "OPTIMIZE",
    "CHECKPOINT_INTERVAL": "CHECKPOINT",
    "COMMIT_CONTENTION": "TUNE",
    "CALIBRATION": "RECALIBRATE",
    "HBM_BUDGET": "TUNE",
}


def spec(name: str) -> ActionSpec:
    """The catalog entry for ``name`` — raises on an unknown action, so a
    typo'd remedy cannot ship (the no-string-matching guarantee)."""
    try:
        return CATALOG[name]
    except KeyError:
        raise ValueError(f"action {name!r} is not registered in "
                         "delta_tpu/obs/actions.py") from None


def remedy_name(name: str) -> str:
    """Catalog-checked remedy string for doctor/advisor emit sites."""
    return spec(name).name


def executable_kinds() -> tuple:
    return tuple(sorted(n for n, s in CATALOG.items() if s.executable))


#: Action-ledger phases that arm a cooldown — everything that ATTEMPTED
#: the action. A crash between "started" and its terminal entry must still
#: cool down (that is exactly the crash-loop the guardrail exists for), so
#: "started" is in; "planned"/"deferred"/"skipped" never ran and are not.
#: One definition, shared by the autopilot planner (re-plan filtering) and
#: the advisor (suppression of executed recommendations) so the two
#: surfaces can never drift.
COOLDOWN_PHASES = frozenset(
    {"started", "executed", "failed", "interrupted", "abortedContention"})


def action_key(action: Dict[str, Any]) -> Optional[str]:
    """The cooldown/dedup identity of a ledger entry's ``action`` payload —
    the dict twin of :attr:`MaintenanceAction.key`; None when malformed."""
    kind = action.get("kind")
    if not kind:
        return None
    target = action.get("target")
    return f"{kind}:{target}" if target else kind


def attempts_in_cooldown(entries: List[Dict[str, Any]], now_ms: int,
                         cooldown_ms: int,
                         state: Optional[Dict[str, Dict[str, Any]]] = None
                         ) -> Dict[str, Dict[str, Any]]:
    """Action keys whose last ATTEMPT (any :data:`COOLDOWN_PHASES` ledger
    entry) falls inside the cooldown window, mapped to the arming entry.
    Newest ``ts`` wins; on a tie the terminal entry (audit attached)
    outranks its own ``started`` marker. ``state`` merges the sweep-proof
    sidecar (`obs/journal.attempt_state`) so a ledger segment evicted by
    the journal sweep cannot un-arm a cooldown — both the autopilot
    planner and the advisor's suppression pass it."""
    out: Dict[str, Dict[str, Any]] = {}
    for e in entries:
        if e.get("phase") not in COOLDOWN_PHASES:
            continue
        key = action_key(e.get("action") or {})
        if key is None:
            continue
        ts = int(e.get("ts") or 0)
        if now_ms - ts > cooldown_ms:
            continue
        prev = out.get(key)
        prev_ts = int(prev.get("ts") or 0) if prev is not None else -1
        if prev is None or ts > prev_ts or (ts == prev_ts and e.get("audit")):
            out[key] = e
    for key, st in (state or {}).items():
        ts = int(st.get("ts") or 0)
        if (st.get("phase") in COOLDOWN_PHASES
                and now_ms - ts <= cooldown_ms
                and ts > int((out.get(key) or {}).get("ts") or 0)):
            kind, _, target = key.partition(":")
            out[key] = {"phase": st["phase"], "ts": ts,
                        "action": {"kind": kind, "target": target},
                        "source": "stateFile"}
    return out


@dataclass
class MaintenanceAction:
    """One planned/executed unit of maintenance, shared between the
    planner, the executor, and the persistent action ledger (journal
    entries of kind ``autopilot``)."""

    kind: str                      # CATALOG key
    table_path: str
    target: str = ""               # column list / conf key; "" = the table
    params: Dict[str, Any] = field(default_factory=dict)
    source: str = ""               # "doctor:<dimension>" | "advisor:<kind>"
    priority: float = 0.0          # higher = execute first
    evidence: Dict[str, Any] = field(default_factory=dict)
    #: metric values the source cited — the audit compares these (and the
    #: re-measured before values) against the post-action measurement
    predicted: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self) -> None:
        spec(self.kind)  # unknown kinds never enter the pipeline

    @property
    def key(self) -> str:
        """Cooldown/dedup identity: the action kind plus its target."""
        return f"{self.kind}:{self.target}" if self.target else self.kind

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "table": self.table_path,
            "target": self.target,
            "params": dict(self.params),
            "source": self.source,
            "priority": round(self.priority, 3),
            "evidence": dict(self.evidence),
            "predicted": dict(self.predicted),
            "catalog": CATALOG_REF,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> Optional["MaintenanceAction"]:
        """Rebuild from a ledger entry; None on malformed/unknown input —
        an old or torn ledger line must not poison planning."""
        try:
            return cls(
                kind=d["kind"], table_path=d.get("table", ""),
                target=d.get("target", "") or "",
                params=dict(d.get("params") or {}),
                source=d.get("source", ""),
                priority=float(d.get("priority") or 0.0),
                evidence=dict(d.get("evidence") or {}),
                predicted=dict(d.get("predicted") or {}),
            )
        except (KeyError, TypeError, ValueError):
            return None
