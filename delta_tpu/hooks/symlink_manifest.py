"""GENERATE symlink_format_manifest — Presto/Athena compatibility manifests.

Mirrors `hooks/GenerateSymlinkManifest.scala:41-374`: writes
``_symlink_format_manifest/[<partition-path>/]manifest`` files, each listing
the absolute URIs of the table's current data files for that partition.
Two modes:
* **full** (`:165`) — regenerate every partition's manifest, drop manifests
  of vanished partitions (the GENERATE command);
* **incremental** (`:80`) — post-commit hook (enabled by table property
  ``delta.compatibility.symlinkFormatManifest.enabled``) that rewrites only
  partitions touched by the commit.
"""
from __future__ import annotations

import os
import shutil
import urllib.parse
from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Set

from delta_tpu.exec.write import partition_path
from delta_tpu.protocol.actions import Action, AddFile, RemoveFile
from delta_tpu.utils.config import DeltaConfigs

__all__ = ["MANIFEST_DIR", "generate_full_manifest", "SymlinkManifestHook"]

MANIFEST_DIR = "_symlink_format_manifest"


def _partition_dir(pv: Dict[str, Optional[str]], part_cols) -> str:
    return partition_path(pv or {}, part_cols)


def _write_manifest(data_path: str, rel_dir: str, files: Iterable[AddFile]) -> None:
    out_dir = os.path.join(data_path, MANIFEST_DIR, rel_dir.replace("/", os.sep))
    os.makedirs(out_dir, exist_ok=True)
    lines = []
    for f in sorted(files, key=lambda a: a.path):
        abs_p = os.path.join(data_path, urllib.parse.unquote(f.path).replace("/", os.sep))
        lines.append("file:" + urllib.parse.quote(os.path.abspath(abs_p)))
    with open(os.path.join(out_dir, "manifest"), "w") as fh:
        fh.write("\n".join(lines) + ("\n" if lines else ""))


def generate_full_manifest(delta_log) -> int:
    """Regenerate all manifests; returns the number written (`:165`)."""
    snapshot = delta_log.update()
    part_cols = snapshot.metadata.partition_columns
    by_dir: Dict[str, List[AddFile]] = defaultdict(list)
    for f in snapshot.all_files:
        by_dir[_partition_dir(f.partition_values, part_cols)].append(f)

    manifest_root = os.path.join(delta_log.data_path, MANIFEST_DIR)
    if os.path.isdir(manifest_root):
        shutil.rmtree(manifest_root)
    for rel_dir, files in by_dir.items():
        _write_manifest(delta_log.data_path, rel_dir, files)
    return len(by_dir)


class SymlinkManifestHook:
    """Post-commit hook: incremental manifest update (`:80`).
    Registered automatically by the transaction when the table property
    ``delta.compatibility.symlinkFormatManifest.enabled`` is set."""

    name = "Generate Symlink Format Manifest"

    def __eq__(self, other) -> bool:  # dedupe in the hook registry
        return type(other) is type(self)

    def __hash__(self) -> int:
        return hash(type(self))

    def run(self, txn, committed_version: int, snapshot) -> None:
        metadata = txn.metadata
        if not DeltaConfigs.SYMLINK_FORMAT_MANIFEST_ENABLED.from_metadata(metadata):
            return
        part_cols = metadata.partition_columns
        committed_actions: List[Action] = []
        for v, actions in txn.delta_log.get_changes(committed_version):
            if v == committed_version:
                committed_actions = actions
            break
        touched: Set[str] = set()
        for a in committed_actions:
            if isinstance(a, (AddFile, RemoveFile)):
                touched.add(_partition_dir(a.partition_values or {}, part_cols))
        if not touched:
            return
        by_dir: Dict[str, List[AddFile]] = defaultdict(list)
        for f in snapshot.all_files:
            by_dir[_partition_dir(f.partition_values, part_cols)].append(f)
        for rel_dir in touched:
            files = by_dir.get(rel_dir)
            if files:
                _write_manifest(txn.delta_log.data_path, rel_dir, files)
            else:
                # partition vanished: remove its manifest dir
                gone = os.path.join(
                    txn.delta_log.data_path, MANIFEST_DIR, rel_dir.replace("/", os.sep)
                )
                if os.path.isdir(gone):
                    shutil.rmtree(gone, ignore_errors=True)
