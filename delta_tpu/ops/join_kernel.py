"""Device equi-join for MERGE — the north star's centerpiece.

The reference runs MERGE phase 1 (findTouchedFiles) as a Spark inner join
source×target with a row-id/file-name UDF (`commands/MergeIntoCommand.scala:310-389`)
and phase 2 as an outer join + row-at-a-time clause interpreter (`:456-561`).
Here the join itself is a device kernel; clause application stays columnar
Arrow on the host (`commands/merge.py`).

Shape of the kernel (TPU-first, not a shuffle translation):

  An upsert MERGE is a small-source × large-target join, so instead of
  hash-partitioning both sides over the mesh (an all-to-all whose per-shard
  capacities are data-dependent — dynamic shapes XLA can't tile), the
  *target* keys stay sharded where they are and the *source* keys are
  `all_gather`ed over ICI (tiled, one collective). Each shard then runs a
  static-shaped sort-merge probe:

      sort source by (key, invalid)          # valid rows first in a key run
      lo/hi = searchsorted(target slab keys) # bitonic-sort-backed on TPU
      count = valid-prefix-sum[hi] - [lo]    # exact per-target match count
      first = source-perm[lo]                # first matching source row

  and the per-source matched flags (needed for NOT MATCHED inserts and the
  reference's insert-only left-anti fast path, `:397-450`) come from the
  reverse probe reduced with `psum` over ICI.

Exactness: keys are int64 *values* (no hashing), so there are no false
matches; NULL keys never join (validity masks, SQL semantics). Non-integer
or multi-column join keys stay on the host Arrow hash join.

The per-target output is (match count, first matching source row). This is
lossless for MERGE because a target row matching >1 source rows is an error
(`:351-365`) except when duplicates are harmless (single unconditional
DELETE, insert-only) — in which case any one match carries the decision.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import numpy as np

__all__ = ["JoinResult", "inner_join"]


class JoinResult(NamedTuple):
    """Per-row join outcome (host numpy, unpadded).

    Outputs are packed to minimize device→host transfer (the dominant cost
    on PCIe- or tunnel-attached chips): one int32 per target row instead of
    separate count/index arrays, and the multi-match signal reduced to a
    scalar on device."""

    t_first_s: np.ndarray  # int32 per target row: first matching source row, -1 = no match
    s_matched: np.ndarray  # bool per source row: has at least one target match
    any_multi: bool  # some target row matched more than one source row

    @property
    def t_matched(self) -> np.ndarray:
        return self.t_first_s >= 0


def _next_pow2(n: int) -> int:
    p = 8
    while p < n:
        p *= 2
    return p


def _sorted_probe(jnp, jax, probe_keys, probe_valid, base_key, base_invalid):
    """count of valid base rows whose key equals each probe key, plus the
    position of the first such row in the (key, invalid)-sorted base."""
    m = base_key.shape[0]
    perm = jnp.arange(m, dtype=jnp.int32)
    k_sorted, inv_sorted, perm_sorted = jax.lax.sort(
        (base_key, base_invalid, perm), num_keys=2
    )
    valid_sorted = (inv_sorted == 0).astype(jnp.int32)
    cum = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(valid_sorted, dtype=jnp.int32)])
    lo = jnp.searchsorted(k_sorted, probe_keys, side="left", method="sort")
    hi = jnp.searchsorted(k_sorted, probe_keys, side="right", method="sort")
    count = jnp.where(probe_valid, cum[hi] - cum[lo], 0)
    first = perm_sorted[jnp.clip(lo, 0, m - 1)]
    return count, first


@functools.lru_cache(maxsize=None)
def _single_device_kernel_cached():
    import jax

    return _single_device_kernel(jax)


def _single_device_kernel(jax):
    import jax.numpy as jnp

    @jax.jit
    def kernel(t_key, t_invalid, s_key, s_invalid):
        t_valid = t_invalid == 0
        s_valid = s_invalid == 0
        count, first = _sorted_probe(jnp, jax, t_key, t_valid, s_key, s_invalid)
        s_count, _ = _sorted_probe(jnp, jax, s_key, s_valid, t_key, t_invalid)
        packed = jnp.where(count > 0, first, -1)
        return packed, s_count > 0, jnp.any(count > 1)

    return kernel


@functools.lru_cache(maxsize=None)
def _sharded_kernel_cached(mesh, axis):
    import jax

    return _sharded_kernel(jax, mesh, axis)


def _sharded_kernel(jax, mesh, axis):
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis)),
        out_specs=(P(axis), P(), P()),
    )
    def kernel(t_key, t_invalid, s_key, s_invalid):
        # slabs arrive stacked (1, cap); source is gathered over ICI so every
        # shard probes the full (padded) source in original order
        tk, ti = t_key[0], t_invalid[0]
        s_full_key = jax.lax.all_gather(s_key[0], axis, tiled=True)
        s_full_inv = jax.lax.all_gather(s_invalid[0], axis, tiled=True)
        t_valid = ti == 0
        s_valid = s_full_inv == 0
        count, first = _sorted_probe(jnp, jax, tk, t_valid, s_full_key, s_full_inv)
        packed = jnp.where(count > 0, first, -1)
        # reverse probe: this shard's target slab vs the full source; a source
        # row is matched iff any shard finds a hit → psum over ICI
        s_count, _ = _sorted_probe(jnp, jax, s_full_key, s_valid, tk, ti)
        s_hits = jax.lax.psum(jnp.minimum(s_count, 1), axis)
        multi = jax.lax.psum(jnp.any(count > 1).astype(jnp.int32), axis)
        return packed[None], s_hits > 0, multi > 0

    return jax.jit(kernel)


def _pad(col: np.ndarray, cap: int, fill) -> np.ndarray:
    out = np.full(cap, fill, dtype=col.dtype)
    out[: len(col)] = col
    return out


def inner_join(
    t_keys: np.ndarray,
    t_valid: np.ndarray,
    s_keys: np.ndarray,
    s_valid: np.ndarray,
    mesh=None,
) -> JoinResult:
    """Join int64 target keys against int64 source keys on device.

    ``mesh`` is a 1-D `jax.sharding.Mesh` (target sharded contiguously,
    source gathered); None runs the single-device kernel. Rows with
    ``valid == False`` (SQL NULL keys, padding) never match. Keys are
    narrowed to int32 when both sides' values fit — halves the host→device
    transfer, which dominates on remote-attached chips.
    """
    import jax

    n, m = len(t_keys), len(s_keys)
    if n == 0 or m == 0:
        return JoinResult(np.full(n, -1, np.int32), np.zeros(m, bool), False)

    t_key64 = np.ascontiguousarray(t_keys, np.int64)
    s_key64 = np.ascontiguousarray(s_keys, np.int64)
    t_ok = np.asarray(t_valid, bool)
    s_ok = np.asarray(s_valid, bool)
    t_inv = (~t_ok).astype(np.int32)
    s_inv = (~s_ok).astype(np.int32)

    # narrow to int32 when exact (valid keys only; invalid rows never match);
    # where= reductions avoid materializing boolean-indexed copies
    kdtype = np.int64
    i32 = np.iinfo(np.int32)
    if (
        np.min(t_key64, where=t_ok, initial=0) >= i32.min
        and np.max(t_key64, where=t_ok, initial=0) <= i32.max
        and np.min(s_key64, where=s_ok, initial=0) >= i32.min
        and np.max(s_key64, where=s_ok, initial=0) <= i32.max
    ):
        kdtype = np.int32
        t_key64 = np.where(t_ok, t_key64, 0).astype(np.int32)
        s_key64 = np.where(s_ok, s_key64, 0).astype(np.int32)

    if mesh is None or mesh.devices.size == 1:
        cap_t, cap_s = _next_pow2(n), _next_pow2(m)
        kernel = _single_device_kernel_cached()
        with jax.enable_x64():
            packed, s_matched, multi = kernel(
                _pad(t_key64, cap_t, kdtype(0)), _pad(t_inv, cap_t, 1),
                _pad(s_key64, cap_s, kdtype(0)), _pad(s_inv, cap_s, 1),
            )
        return JoinResult(
            np.asarray(packed)[:n], np.asarray(s_matched)[:m], bool(multi)
        )

    from delta_tpu.parallel.mesh import STATE_AXIS, shard_count

    p = shard_count(mesh)
    cap_t = _next_pow2((n + p - 1) // p) * p
    cap_s = _next_pow2((m + p - 1) // p) * p
    kernel = _sharded_kernel_cached(mesh, STATE_AXIS)
    with jax.enable_x64():
        packed, s_matched, multi = kernel(
            _pad(t_key64, cap_t, kdtype(0)).reshape(p, -1),
            _pad(t_inv, cap_t, 1).reshape(p, -1),
            _pad(s_key64, cap_s, kdtype(0)).reshape(p, -1),
            _pad(s_inv, cap_s, 1).reshape(p, -1),
        )
    return JoinResult(
        np.asarray(packed).reshape(-1)[:n], np.asarray(s_matched)[:m], bool(multi)
    )
