"""Error taxonomy, mirroring the reference's user-facing error factory
(``DeltaErrors.scala``) and the public concurrency exception hierarchy
(``io/delta/exceptions/DeltaConcurrentExceptions.scala``, also surfaced to
Python in the reference via ``python/delta/exceptions.py``)."""
from __future__ import annotations

from typing import Iterable, Optional

__all__ = [
    "DeltaError",
    "DeltaAnalysisError",
    "DeltaIllegalArgumentError",
    "DeltaIllegalStateError",
    "DeltaFileNotFoundError",
    "DeltaIOError",
    "DeltaUnsupportedOperationError",
    "MetadataChangedException",
    "ProtocolChangedException",
    "ConcurrentWriteException",
    "ConcurrentAppendException",
    "ConcurrentDeleteReadException",
    "ConcurrentDeleteDeleteException",
    "ConcurrentTransactionException",
    "DeltaConcurrentModificationException",
    "InvariantViolationError",
    "SchemaMismatchError",
    "ProtocolError",
    "VersionNotFoundError",
    "TimestampEarlierThanCommitRetentionError",
    "TemporallyUnstableInputError",
]


class DeltaError(Exception):
    """Base for all delta-tpu errors."""


class DeltaAnalysisError(DeltaError):
    pass


class DeltaIllegalArgumentError(DeltaError, ValueError):
    pass


class DeltaIllegalStateError(DeltaError, RuntimeError):
    pass


class DeltaFileNotFoundError(DeltaError, FileNotFoundError):
    pass


class DeltaIOError(DeltaError, IOError):
    pass


class DeltaUnsupportedOperationError(DeltaError, NotImplementedError):
    pass


class InvariantViolationError(DeltaError):
    """Row-level constraint / NOT NULL violation
    (``schema/InvariantViolationException.scala``)."""


class DeltaParseError(DeltaAnalysisError):
    """SQL statement failed to tokenize or parse (≈ Spark ParseException)."""


class SchemaMismatchError(DeltaAnalysisError):
    """Write schema incompatible with table schema
    (``DeltaErrors.failedToMergeFields`` etc.)."""


class ProtocolError(DeltaError):
    """Table requires a newer reader/writer than this client
    (``DeltaErrors.InvalidProtocolVersionException``)."""


class VersionNotFoundError(DeltaAnalysisError):
    def __init__(self, user_version: int, earliest: int, latest: int):
        super().__init__(
            f"Cannot time travel Delta table to version {user_version}. "
            f"Available versions: [{earliest}, {latest}]."
        )
        self.user_version = user_version
        self.earliest = earliest
        self.latest = latest


class TimestampEarlierThanCommitRetentionError(DeltaAnalysisError):
    pass


class TemporallyUnstableInputError(DeltaAnalysisError):
    """Requested timestamp is after the latest commit timestamp."""

    def __init__(self, user_ts, commit_ts, latest_version: int):
        super().__init__(
            f"The provided timestamp ({user_ts}) is after the latest version "
            f"available to this table ({commit_ts}, version {latest_version})."
        )
        self.commit_ts = commit_ts
        self.latest_version = latest_version


# ---------------------------------------------------------------------------
# Concurrency exceptions (conflict-checker verdicts) — names match
# io/delta/exceptions/DeltaConcurrentExceptions.scala so users can map 1:1.
# ---------------------------------------------------------------------------

class DeltaConcurrentModificationException(DeltaError):
    """Base of the OCC conflict hierarchy."""

    def __init__(self, message: str, conflicting_commit: Optional[dict] = None):
        super().__init__(message)
        self.conflicting_commit = conflicting_commit


class ConcurrentWriteException(DeltaConcurrentModificationException):
    """A concurrent transaction wrote new data the current transaction read
    (or the commit file appeared non-atomically)."""


class MetadataChangedException(DeltaConcurrentModificationException):
    """The table metadata changed since the transaction's snapshot."""


class ProtocolChangedException(DeltaConcurrentModificationException):
    """The protocol version changed since the transaction's snapshot."""


class ConcurrentAppendException(DeltaConcurrentModificationException):
    """Files were added by a concurrent commit in a region this txn read."""


class ConcurrentDeleteReadException(DeltaConcurrentModificationException):
    """A concurrent commit deleted a file this transaction read."""


class ConcurrentDeleteDeleteException(DeltaConcurrentModificationException):
    """A concurrent commit deleted a file this transaction also deletes."""


class ConcurrentTransactionException(DeltaConcurrentModificationException):
    """Overlapping SetTransaction appId with a concurrent commit."""


def concurrent_modification(kind: str, message: str, commit: Optional[dict] = None):
    cls = {
        "write": ConcurrentWriteException,
        "metadata": MetadataChangedException,
        "protocol": ProtocolChangedException,
        "append": ConcurrentAppendException,
        "deleteRead": ConcurrentDeleteReadException,
        "deleteDelete": ConcurrentDeleteDeleteException,
        "txn": ConcurrentTransactionException,
    }[kind]
    return cls(message, commit)


def versions_not_contiguous(versions: Iterable[int]) -> DeltaIllegalStateError:
    return DeltaIllegalStateError(
        f"Versions ({list(versions)}) are not contiguous. This can happen when "
        "files have been manually deleted from the transaction log."
    )
