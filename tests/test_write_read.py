"""End-to-end slice: create → append → scan → overwrite → time travel.

The behavioral spec is the reference's write/read call stacks (SURVEY §3.1,
§3.2) and `examples/python/quickstart.py` up to the DML steps.
"""
import json
import os

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from delta_tpu import DeltaLog
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.exec.scan import scan_files, scan_to_table
from delta_tpu.schema.constraints import CONSTRAINT_PROP_PREFIX
from delta_tpu.utils.errors import (
    DeltaAnalysisError,
    InvariantViolationError,
    SchemaMismatchError,
)


def write(log, data, mode="append", **kw):
    return WriteIntoDelta(log, mode, data, **kw).run()


def read_ids(log, filters=()):
    t = scan_to_table(log.update(), filters)
    return sorted(t.column("id").to_pylist())


def test_quickstart_create_read_overwrite(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": list(range(5))})
    assert read_ids(log) == [0, 1, 2, 3, 4]
    # overwrite with 5..10
    write(log, {"id": list(range(5, 10))}, mode="overwrite")
    assert read_ids(log) == [5, 6, 7, 8, 9]
    # time travel back to v0
    v0 = log.get_snapshot_at(0)
    assert sorted(scan_to_table(v0).column("id").to_pylist()) == [0, 1, 2, 3, 4]


def test_append_accumulates(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2]})
    write(log, {"id": [3]})
    assert read_ids(log) == [1, 2, 3]
    assert log.snapshot.version == 1


def test_error_mode_and_ignore(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1]})
    with pytest.raises(DeltaAnalysisError):
        write(log, {"id": [2]}, mode="error")
    write(log, {"id": [2]}, mode="ignore")  # no-op
    assert read_ids(log) == [1]


def test_partitioned_write_layout_and_pruning(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    data = {
        "id": [1, 2, 3, 4],
        "country": ["us", "us", "fr", "fr"],
    }
    write(log, data, partition_columns=["country"])
    snap = log.update()
    files = snap.all_files
    assert len(files) == 2
    assert all(f.path.startswith("country=") for f in files)
    # physical file must NOT contain the partition column. Read via
    # ParquetFile: pq.read_table on a path under `country=fr/` re-infers a
    # hive partition column on some pyarrow versions, masking the check.
    raw = pq.ParquetFile(os.path.join(tmp_table, files[0].path)).read()
    assert "country" not in raw.column_names
    # partition pruning reads one file
    scan = scan_files(snap, ["country = 'us'"])
    assert len(scan.files) == 1
    t = scan_to_table(snap, ["country = 'us'"])
    assert sorted(t.column("id").to_pylist()) == [1, 2]
    assert set(t.column("country").to_pylist()) == {"us"}


def test_stats_skipping_on_read(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2, 3]})
    write(log, {"id": [100, 200, 300]})
    snap = log.update()
    scan = scan_files(snap, ["id > 50"])
    assert scan.total.files == 2
    assert scan.scanned.files == 1  # min/max skipping pruned the first file
    assert sorted(scan_to_table(snap, ["id > 50"]).column("id").to_pylist()) == [100, 200, 300]


def test_stats_written_per_file(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [5, 1, 9], "name": ["c", "a", "b"]})
    f = log.update().all_files[0]
    st = json.loads(f.stats)
    assert st["numRecords"] == 3
    assert st["minValues"] == {"id": 1, "name": "a"}
    assert st["maxValues"] == {"id": 9, "name": "c"}
    assert st["nullCount"] == {"id": 0, "name": 0}


def test_schema_enforcement_rejects_extra_column(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1]})
    with pytest.raises((SchemaMismatchError, DeltaAnalysisError)):
        write(log, {"id": [2], "extra": ["x"]})


def test_merge_schema_evolution(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1]})
    write(log, {"id": [2], "extra": ["x"]}, merge_schema=True)
    snap = log.update()
    assert [f.name for f in snap.metadata.schema.fields] == ["id", "extra"]
    t = scan_to_table(snap)
    by_id = dict(zip(t.column("id").to_pylist(), t.column("extra").to_pylist()))
    assert by_id == {1: None, 2: "x"}


def test_overwrite_schema(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1]})
    with pytest.raises(DeltaAnalysisError):
        write(log, {"other": [1.5]}, overwrite_schema=True)  # append mode
    write(log, {"other": [1.5]}, mode="overwrite", overwrite_schema=True)
    snap = log.update()
    assert [f.name for f in snap.metadata.schema.fields] == ["other"]


def test_replace_where(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(
        log,
        {"id": [1, 2, 3, 4], "country": ["us", "us", "fr", "fr"]},
        partition_columns=["country"],
    )
    write(
        log,
        {"id": [20, 21], "country": ["us", "us"]},
        mode="overwrite",
        replace_where="country = 'us'",
    )
    assert read_ids(log) == [3, 4, 20, 21]
    # writing a row outside the predicate fails
    with pytest.raises(DeltaAnalysisError):
        write(
            log,
            {"id": [9], "country": ["de"]},
            mode="overwrite",
            replace_where="country = 'us'",
        )
    # data-column predicate is rejected (partition-only, like the reference)
    with pytest.raises(DeltaAnalysisError):
        write(
            log,
            {"id": [9], "country": ["us"]},
            mode="overwrite",
            replace_where="id > 0",
        )


def test_rearrange_only_sets_datachange_false(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2]})
    write(log, {"id": [1, 2]}, mode="overwrite", rearrange_only=True)
    changes = list(log.get_changes(1))
    _, actions = changes[0]
    from delta_tpu.protocol.actions import AddFile, RemoveFile

    for a in actions:
        if isinstance(a, (AddFile, RemoveFile)):
            assert a.data_change is False


def test_check_constraint_enforced_on_write(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(
        log,
        {"id": [1, 2]},
        configuration={CONSTRAINT_PROP_PREFIX + "idpositive": "id > 0"},
    )
    with pytest.raises(InvariantViolationError):
        write(log, {"id": [-5]})
    assert read_ids(log) == [1, 2]


def test_null_partition_value_roundtrip(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(
        log,
        {"id": [1, 2], "p": ["a", None]},
        partition_columns=["p"],
    )
    snap = log.update()
    paths = sorted(f.path for f in snap.all_files)
    assert any("__HIVE_DEFAULT_PARTITION__" in p for p in paths)
    t = scan_to_table(snap)
    assert sorted(t.column("id").to_pylist()) == [1, 2]
    got = dict(zip(t.column("id").to_pylist(), t.column("p").to_pylist()))
    assert got == {1: "a", 2: None}


def test_special_chars_in_partition_values(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(
        log,
        {"id": [1], "p": ["a/b c=d"]},
        partition_columns=["p"],
    )
    snap = log.update()
    t = scan_to_table(snap, ["p = 'a/b c=d'"])
    assert t.column("id").to_pylist() == [1]


def test_checkpoint_after_writes_and_reload(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    for i in range(12):  # crosses the checkpoint interval (10)
        write(log, {"id": [i]})
    assert os.path.exists(os.path.join(tmp_table, "_delta_log", "_last_checkpoint"))
    DeltaLog.clear_cache()
    log2 = DeltaLog.for_table(tmp_table)
    assert read_ids(log2) == list(range(12))


def test_large_batch_splits_files(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    from delta_tpu.exec.write import write_files
    from delta_tpu.protocol.actions import Metadata
    from delta_tpu.schema.arrow_interop import schema_from_arrow

    t = pa.table({"id": list(range(100))})
    meta = Metadata(schema_string=schema_from_arrow(t.schema).to_json(), partition_columns=[])
    adds = write_files(tmp_table, t, meta, target_file_rows=30)
    assert len(adds) == 4
    assert sum(json.loads(a.stats)["numRecords"] for a in adds) == 100


def test_projection_with_filter_on_unprojected_column(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2], "country": ["us", "fr"]})
    t = scan_to_table(log.update(), ["country = 'us'"], columns=["id"])
    assert t.column_names == ["id"]
    assert t.column("id").to_pylist() == [1]


def test_partition_only_projection(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2], "c": ["a", "b"]}, partition_columns=["c"])
    t = scan_to_table(log.update(), columns=["c"])
    assert sorted(t.column("c").to_pylist()) == ["a", "b"]


def test_nan_partition_value_not_lost(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2, 3], "p": [1.5, float("nan"), None]},
          partition_columns=["p"])
    t = scan_to_table(log.update())
    assert sorted(t.column("id").to_pylist()) == [1, 2, 3]


def test_numeric_partition_with_data_predicate(tmp_table):
    # regression: device path must not compare partition dictionary codes
    log = DeltaLog.for_table(tmp_table)
    write(log, {"id": [1, 2, 3, 4], "year": [2020, 2020, 2021, 2021]},
          partition_columns=["year"])
    t = scan_to_table(log.update(), ["year = 2021 OR id > 100"])
    assert sorted(t.column("id").to_pylist()) == [3, 4]


def test_timestamp_max_stats_round_up(tmp_table):
    import datetime as dt

    log = DeltaLog.for_table(tmp_table)
    ts = dt.datetime(2026, 1, 1, 12, 0, 0, 999)  # sub-millisecond max
    write(log, {"ts": pa.array([ts], pa.timestamp("us"))})
    f = log.update().all_files[0]
    st = json.loads(f.stats)
    # max must round UP to the next ms, min floors
    assert st["maxValues"]["ts"] == "2026-01-01T12:00:00.001Z"
    assert st["minValues"]["ts"] == "2026-01-01T12:00:00.000Z"


def test_many_partitions_write(tmp_table):
    log = DeltaLog.for_table(tmp_table)
    n = 500
    write(log, {"id": list(range(n)), "p": [str(i % 50) for i in range(n)]},
          partition_columns=["p"])
    snap = log.update()
    assert len(snap.all_files) == 50
    assert read_ids(log) == list(range(n))
