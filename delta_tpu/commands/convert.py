"""CONVERT TO DELTA — in-place conversion of a Parquet directory.

Mirrors `commands/ConvertToDeltaCommand.scala:73-655`: list every data file,
merge the Parquet footers into one schema, parse partition values from the
hive-style directory names against the user-provided partition schema
(required when the table is partitioned, like the reference's
``CONVERT TO DELTA t PARTITIONED BY (...)``), synthesize `AddFile`s, and
write everything in a single commit (version 0). Already-delta tables are a
no-op; collecting stats during convert is optional (the reference collects
none).
"""
from __future__ import annotations

import os
import urllib.parse
from typing import Dict, List, Optional, Tuple

import pyarrow.parquet as pq

from delta_tpu.commands import operations as ops
from delta_tpu.exec.write import unescape_partition_value
from delta_tpu.protocol.actions import Action, AddFile, Metadata
from delta_tpu.schema.arrow_interop import schema_from_arrow
from delta_tpu.schema.types import StructType
from delta_tpu.utils.errors import DeltaAnalysisError, DeltaFileNotFoundError
from delta_tpu.utils import errors

__all__ = ["ConvertToDeltaCommand"]


class ConvertToDeltaCommand:
    def __init__(
        self,
        delta_log,
        partition_schema: Optional[StructType] = None,
        collect_stats: bool = False,
    ):
        self.delta_log = delta_log
        self.partition_schema = partition_schema
        self.collect_stats = collect_stats

    def _list_parquet_files(self) -> List[Tuple[str, int, int]]:
        """(rel_path, size, mtime_ms) for every data file under the table."""
        base = self.delta_log.data_path
        out = []
        for root, dirs, files in os.walk(base):
            dirs[:] = [
                d for d in dirs
                if not ((d.startswith("_") or d.startswith(".")) and "=" not in d)
            ]
            for name in sorted(files):
                if name.startswith("_") or name.startswith("."):
                    continue
                if not name.endswith(".parquet"):
                    continue
                abs_p = os.path.join(root, name)
                st = os.stat(abs_p)
                rel = os.path.relpath(abs_p, base).replace(os.sep, "/")
                out.append((rel, st.st_size, int(st.st_mtime * 1000)))
        return out

    def _partition_values(self, rel: str) -> Dict[str, Optional[str]]:
        """Parse ``col=value`` path segments (`createDeltaActions :286`)."""
        parts = rel.split("/")[:-1]
        values: Dict[str, Optional[str]] = {}
        for seg in parts:
            if "=" not in seg:
                raise errors.partition_path_segment_invalid(seg, rel)
            k, _, v = seg.partition("=")
            values[k] = unescape_partition_value(v)
        expected = [f.name for f in (self.partition_schema.fields if self.partition_schema else [])]
        if sorted(values) != sorted(expected):
            raise errors.partition_path_mismatch(rel, values, expected)
        return values

    def run(self) -> int:
        log = self.delta_log
        if log.table_exists:
            return log.snapshot.version  # already delta: no-op

        files = self._list_parquet_files()
        if not files:
            raise DeltaFileNotFoundError(
                f"No parquet files found in {log.data_path} to convert"
            )

        # merge footers into one schema (performConvert :314-365)
        merged = None
        for rel, _, _ in files:
            abs_p = os.path.join(log.data_path, rel.replace("/", os.sep))
            s = pq.ParquetFile(abs_p).schema_arrow
            merged = s if merged is None else _merge_arrow(merged, s)
        data_schema = schema_from_arrow(merged)

        part_fields = list(self.partition_schema.fields) if self.partition_schema else []
        full = StructType(list(data_schema.fields) + part_fields)
        metadata = Metadata(
            schema_string=full.to_json(),
            partition_columns=[f.name for f in part_fields],
        )

        adds: List[Action] = []
        for rel, size, mtime in files:
            pv = self._partition_values(rel)
            adds.append(
                AddFile(
                    path=urllib.parse.quote(rel, safe="/:@!$&'()*+,;=-._~"),
                    partition_values=pv,
                    size=size,
                    modification_time=mtime,
                    data_change=True,
                    stats=self._stats_for(rel) if self.collect_stats else None,
                )
            )

        def body(txn):
            txn.update_metadata(metadata)
            op = ops.Convert(
                num_files=len(adds),
                partition_by=[f.name for f in part_fields],
            )
            return txn.commit(adds, op)

        return log.with_new_transaction(body)

    def _stats_for(self, rel: str) -> str:
        from delta_tpu.exec.parquet import stats_json

        abs_p = os.path.join(self.delta_log.data_path, rel.replace("/", os.sep))
        return stats_json(pq.read_table(abs_p))


def _merge_arrow(a, b):
    import pyarrow as pa

    names = list(a.names)
    fields = {f.name: f for f in a}
    for f in b:
        if f.name not in fields:
            names.append(f.name)
            fields[f.name] = f
        elif fields[f.name].type != f.type:
            # widen to the later file's type when types differ numerically
            if pa.types.is_integer(fields[f.name].type) and pa.types.is_floating(f.type):
                fields[f.name] = f
    return pa.schema([fields[n] for n in names])
