"""Change Data Feed: per-commit row-level change capture.

The reference at 0.9 carries the ``cdc`` action in its protocol
(``actions/actions.scala:328-341``) but blocks writing it
(``actions.scala:151-156``); modern Delta ships the full feature. This
module implements it end to end:

* **Write side** — DML on tables with ``delta.enableChangeDataFeed=true``
  stages change rows (``_change_type`` ∈ insert / delete /
  update_preimage / update_postimage) that commit as Parquet files under
  ``_change_data/`` logged with ``AddCDCFile`` actions (``dataChange=false``
  so they never affect table state replay).
* **Read side** — :func:`read_changes` returns the changes between two
  versions with ``_change_type`` / ``_commit_version`` /
  ``_commit_timestamp`` columns. Commits without CDC files are
  reconstructed from their file actions: dataChange adds → inserts,
  dataChange removes of dropped files → deletes (read through the
  tombstone's deletion vector), and deletion-vector re-adds → deletes of
  the newly-marked positions (old-DV/new-DV diff).
"""
from __future__ import annotations

import os
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from delta_tpu.protocol.actions import AddCDCFile, AddFile, RemoveFile
from delta_tpu.utils import errors

__all__ = [
    "CHANGE_TYPE_COL",
    "COMMIT_VERSION_COL",
    "COMMIT_TIMESTAMP_COL",
    "CDC_DIR",
    "write_change_data",
    "read_changes",
]

CHANGE_TYPE_COL = "_change_type"
COMMIT_VERSION_COL = "_commit_version"
COMMIT_TIMESTAMP_COL = "_commit_timestamp"
CDC_DIR = "_change_data"


def cdf_enabled(metadata) -> bool:
    from delta_tpu.utils.config import DeltaConfigs

    return bool(DeltaConfigs.CHANGE_DATA_FEED.from_metadata(metadata))


def write_change_data(
    data_path: str,
    blocks: Sequence[Tuple[str, pa.Table]],
    metadata,
) -> List[AddCDCFile]:
    """Write change blocks (``(change_type, rows)``) as one CDC Parquet file.

    Rows are stored with every table column (partition columns included —
    unlike data files, CDC files are self-contained) plus ``_change_type``.
    """
    from delta_tpu.exec.parquet import write_parquet_file

    target_cols = [f.name for f in metadata.schema.fields]
    parts: List[pa.Table] = []
    for change_type, rows in blocks:
        if rows is None or rows.num_rows == 0:
            continue
        t = rows.select([c for c in target_cols if c in rows.column_names])
        t = t.append_column(
            CHANGE_TYPE_COL, pa.array([change_type] * t.num_rows, pa.string())
        )
        parts.append(t)
    if not parts:
        return []
    out = pa.concat_tables(parts, promote_options="permissive")
    rel = f"{CDC_DIR}/cdc-{uuid.uuid4()}.c000.snappy.parquet"
    abs_path = os.path.join(data_path, CDC_DIR, os.path.basename(rel))
    size, _ = write_parquet_file(out, abs_path)
    return [AddCDCFile(path=rel, partition_values={}, size=size)]


def _read_file_rows(
    data_path: str, add_like, metadata, dv_dict=None
) -> pa.Table:
    """Read a data file's rows as they were live under ``dv_dict``."""
    from delta_tpu.exec.scan import read_files_as_table

    add = AddFile(
        path=add_like.path,
        partition_values=dict(add_like.partition_values or {}),
        size=add_like.size or 0,
        deletion_vector=dv_dict,
    )
    [t] = read_files_as_table(data_path, [add], metadata, per_file=True)
    return t


def _dv_positions(data_path: str, dv_dict) -> np.ndarray:
    from delta_tpu.protocol import deletion_vectors as dv_mod

    if not dv_dict:
        return np.array([], np.uint32)
    return dv_mod.read_deletion_vector(
        dv_mod.DeletionVectorDescriptor.from_dict(dv_dict), data_path
    )


def read_changes(
    delta_log,
    starting_version: int,
    ending_version: Optional[int] = None,
) -> pa.Table:
    """The table's change feed for versions [starting, ending] (inclusive)."""
    snapshot = delta_log.update()
    if ending_version is None:
        ending_version = snapshot.version
    if starting_version > snapshot.version:
        raise errors.cdf_start_after_latest(starting_version, snapshot.version)
    if starting_version > ending_version:
        raise errors.cdf_start_after_end(starting_version, ending_version)
    # data-loss guard: silently skipping retention-cleaned commits would
    # hide their deletes/updates from the consumer
    earliest = delta_log.history.get_earliest_delta_file()
    if starting_version < earliest:
        raise errors.cdf_start_unavailable(starting_version, earliest)
    metadata = snapshot.metadata
    target_cols = [f.name for f in metadata.schema.fields]
    commits = {
        c.version: c.timestamp
        for c in delta_log.history.get_commits(starting_version, ending_version)
    }

    out_parts: List[pa.Table] = []

    def emit(rows: pa.Table, change_type: Optional[str], version: int):
        if rows.num_rows == 0:
            return
        keep = [c for c in rows.column_names
                if c in target_cols or c == CHANGE_TYPE_COL]
        t = rows.select(keep)
        if change_type is not None:
            t = t.append_column(
                CHANGE_TYPE_COL, pa.array([change_type] * t.num_rows, pa.string())
            )
        t = t.append_column(
            COMMIT_VERSION_COL, pa.array([version] * t.num_rows, pa.int64())
        )
        t = t.append_column(
            COMMIT_TIMESTAMP_COL,
            pa.array([commits.get(version, 0)] * t.num_rows, pa.int64()),
        )
        out_parts.append(t)

    for version, actions in delta_log.get_changes(starting_version):
        if version > ending_version:
            break
        cdc_files = [a for a in actions if isinstance(a, AddCDCFile)]
        if cdc_files:
            from delta_tpu.exec.parquet import read_parquet_files

            abs_paths = [
                os.path.join(delta_log.data_path, c.path.replace("/", os.sep))
                for c in cdc_files
            ]
            for t in read_parquet_files(abs_paths):
                emit(t, None, version)
            continue
        # reconstruction: no CDC files in this commit
        adds: Dict[str, AddFile] = {
            a.path: a for a in actions
            if isinstance(a, AddFile) and a.data_change
        }
        removes: Dict[str, RemoveFile] = {
            a.path: a for a in actions
            if isinstance(a, RemoveFile) and a.data_change
        }
        for path, add in adds.items():
            rm = removes.get(path)
            if rm is not None:
                # deletion-vector re-add: the change is the newly-marked rows
                from delta_tpu.commands.dml_common import POSITION_COL
                from delta_tpu.exec.scan import read_files_as_table

                old = _dv_positions(delta_log.data_path, rm.deletion_vector)
                new = _dv_positions(delta_log.data_path, add.deletion_vector)
                newly = np.setdiff1d(new, old)
                if newly.size == 0:
                    continue
                bare = AddFile(path=add.path,
                               partition_values=dict(add.partition_values or {}),
                               size=add.size)
                # the newly-marked positions are known before any decode:
                # read only the row groups containing them (positions stay
                # physical, so the isin selection below is unchanged)
                [t] = read_files_as_table(
                    delta_log.data_path, [bare], metadata, per_file=True,
                    position_column=POSITION_COL,
                    positions_of_interest=[newly],
                )
                sel = np.isin(
                    t.column(POSITION_COL).to_numpy(zero_copy_only=False), newly
                )
                emit(t.filter(pa.array(sel)), "delete", version)
            else:
                emit(
                    _read_file_rows(delta_log.data_path, add, metadata,
                                    dv_dict=add.deletion_vector),
                    "insert", version,
                )
        for path, rm in removes.items():
            if path in adds:
                continue  # handled as DV diff above
            rows = _read_file_rows(
                delta_log.data_path, rm, metadata, dv_dict=rm.deletion_vector
            )
            emit(rows, "delete", version)

    if not out_parts:
        schema = pa.schema(
            [pa.field(CHANGE_TYPE_COL, pa.string()),
             pa.field(COMMIT_VERSION_COL, pa.int64()),
             pa.field(COMMIT_TIMESTAMP_COL, pa.int64())]
        )
        return schema.empty_table()
    return pa.concat_tables(out_parts, promote_options="permissive")
