"""DeltaLog: the per-table handle composing the whole log stack.

Reference: ``DeltaLog.scala:59-548``. Composes snapshot management,
checkpointing, metadata cleanup, checksum, transactions, and log tailing
behind one object, with a per-resolved-path singleton cache.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

from delta_tpu.log import checkpoints as ckpt_mod
from delta_tpu.log import checksum as crc_mod
from delta_tpu.log import snapshot_management as sm
from delta_tpu.log.snapshot import InitialSnapshot, Snapshot
from delta_tpu.protocol import filenames
from delta_tpu.protocol.actions import (
    READER_VERSION,
    SUPPORTED_READER_FEATURES,
    SUPPORTED_READER_VERSION,
    SUPPORTED_WRITER_FEATURES,
    SUPPORTED_WRITER_VERSION,
    WRITER_VERSION,
    Action,
    Protocol,
    actions_from_lines,
)
from delta_tpu.storage import faults as faults_mod
from delta_tpu.storage.logstore import LogStore, get_log_store
from delta_tpu.utils.config import DeltaConfigs, conf
from delta_tpu.utils import errors as errors_mod
from delta_tpu.utils.errors import (
    DeltaIllegalStateError,
    ProtocolError,
    versions_not_contiguous,
)

logger = logging.getLogger(__name__)

__all__ = ["DeltaLog", "extract_path_time_travel"]

# path-embedded time travel (`DeltaTimeTravelSpec.scala:137` /
# `DeltaTableUtils.extractIfPathContainsTimeTravel`): `/t@v123` pins a
# version, `/t@yyyyMMddHHmmssSSS` (17 digits) pins a timestamp
import re as _re

_TT_SUFFIX = _re.compile(r"^(?P<base>.+)@(?:[vV](?P<ver>\d+)|(?P<ts>\d{17}))$")


def extract_path_time_travel(path: str):
    """(base_path, version, timestamp_ms) when ``path`` carries an embedded
    time-travel suffix, else None. Callers apply it only when the literal
    path is NOT itself a Delta table (a directory literally named ``t@v1``
    wins, matching the reference's resolution order).

    DEVIATION (documented, PARITY.md): the ``@yyyyMMddHHmmssSSS`` timestamp
    form is interpreted as **UTC**, not the session timezone. The reference
    parses it with a session-zone ``SimpleDateFormat``
    (`DeltaTimeTravelSpec.scala:137`), so the same literal can pin a
    different version per client zone; this engine has no session timezone
    and deliberately resolves the digits as a UTC wall clock — the same
    path string selects the same version everywhere. Use an explicit
    ``@v<N>`` pin when cross-engine reproducibility against a non-UTC
    reference session matters."""
    m = _TT_SUFFIX.match(path.rstrip("/"))
    if not m:
        return None
    base = m.group("base")
    if m.group("ver") is not None:
        return base, int(m.group("ver")), None
    import datetime as _dt

    s = m.group("ts")
    try:
        d = _dt.datetime.strptime(s[:14], "%Y%m%d%H%M%S").replace(
            tzinfo=_dt.timezone.utc)
    except ValueError:
        return None
    ts_ms = int(d.timestamp() * 1000) + int(s[14:])
    return base, None, ts_ms


class DeltaLog:
    _cache: Dict[str, "DeltaLog"] = {}
    _cache_lock = threading.Lock()

    def __init__(self, data_path: str, store: Optional[LogStore] = None, clock=None):
        self.data_path = data_path.rstrip("/")
        self.log_path = f"{self.data_path}/_delta_log"
        # Store stack, inside out: base -> fault injector (ONLY when
        # `delta.tpu.faults.plan` is set — no wrapper, no overhead
        # otherwise) -> transient-retry layer for idempotent ops. The retry
        # layer sits on top so injected transients are actually retried.
        base_store = store or get_log_store(self.data_path)
        self._base_store = base_store
        wrapped = faults_mod.maybe_wrap(base_store)
        if conf.get_bool("delta.tpu.storage.retry.enabled", True):
            from delta_tpu.storage.retrying import RetryingLogStore

            wrapped = RetryingLogStore(wrapped)
        self.store = wrapped
        # Single in-process commit lock (DeltaLog.scala:84). Cross-process
        # exclusion comes from the LogStore's atomic create.
        self.lock = threading.RLock()
        self.clock = clock or (lambda: int(time.time() * 1000))
        self._snapshot: Optional[Snapshot] = None
        self._group_coordinator = None  # lazily built (txn/group_commit)
        self._last_update_ms: int = 0
        self._update_lock = threading.Lock()
        # monotonic instant the most recent COMPLETED listing began —
        # drives update coalescing in _do_update (a waiter adopts a result
        # whose listing started after the waiter arrived)
        self._last_listing_start: float = float("-inf")
        self._refresh_future = None  # in-flight async stale-ok refresh
        self._refresh_lock = threading.Lock()
        # checkpoint versions that failed to decode (Snapshot._columnar
        # recovery): listings skip them so update()'s early-exit holds
        self._corrupt_checkpoints: frozenset = frozenset()
        self._initialize()
        # fleet registry (obs/fleet): weakref'd — the registry never keeps
        # a table alive — and inert under a telemetry blackout
        from delta_tpu.obs import fleet as fleet_mod

        fleet_mod.register(self)

    @property
    def corrupt_checkpoints(self) -> frozenset:
        return self._corrupt_checkpoints

    def mark_corrupt_checkpoint(self, version: int) -> frozenset:
        """Memoize a checkpoint that failed to decode; returns the set."""
        self._corrupt_checkpoints = self._corrupt_checkpoints | {version}
        return self._corrupt_checkpoints

    # -- singleton cache (DeltaLog.scala:373-387) -----------------------

    def _store_stack_current(self) -> bool:
        """Does this instance's (construction-time) store wrapping still
        match the session conf? A later `delta.tpu.faults.plan` install or
        retry-layer toggle must not be silently ignored by cache hits."""
        from delta_tpu.storage.retrying import RetryingLogStore

        retry_on = conf.get_bool("delta.tpu.storage.retry.enabled", True)
        inner = self.store
        has_retry = isinstance(inner, RetryingLogStore)
        if has_retry:
            inner = inner.base
        has_faults = isinstance(inner, faults_mod.FaultInjectingLogStore)
        plan = faults_mod.plan_from_conf()
        return has_retry == retry_on and (
            (inner.plan is plan) if has_faults else (plan is None)
        )

    @classmethod
    def for_table(cls, data_path: str, store: Optional[LogStore] = None, clock=None) -> "DeltaLog":
        key = data_path.rstrip("/")
        with cls._cache_lock:
            dl = cls._cache.get(key)
            if (dl is None or clock is not None
                    or (store is not None and dl._base_store is not store)
                    or not dl._store_stack_current()):
                dl = DeltaLog(key, store=store or (dl._base_store if dl else None),
                              clock=clock)
                cls._cache[key] = dl
            return dl

    @classmethod
    def clear_cache(cls) -> None:
        with cls._cache_lock:
            cls._cache.clear()

    @classmethod
    def invalidate_cache(cls, data_path: str) -> None:
        with cls._cache_lock:
            cls._cache.pop(data_path.rstrip("/"), None)

    # -- snapshots -------------------------------------------------------

    def _initialize(self) -> None:
        self.update()

    @property
    def unsafe_volatile_snapshot(self) -> Optional[Snapshot]:
        return self._snapshot

    @property
    def snapshot(self) -> Snapshot:
        s = self._snapshot
        if s is None:
            s = self.update()
        return s

    def _trigger_async_refresh(self) -> None:
        """Kick one background re-list+install for this log (at most one in
        flight); readers keep serving the stale snapshot meanwhile. Daemon
        threads (not an executor pool): a refresh hung on an unreachable
        store must never block interpreter exit — the analogue of the
        reference's snapshot-update pool (``SnapshotManagement.scala:251-263``)."""
        import concurrent.futures

        with self._refresh_lock:
            f = self._refresh_future
            if f is not None and not f.done():
                return
            fut: concurrent.futures.Future = concurrent.futures.Future()
            self._refresh_future = fut

            def work():
                try:
                    fut.set_result(self._do_update())
                except BaseException as e:
                    logger.warning("async snapshot refresh failed for %s",
                                   self.data_path, exc_info=True)
                    fut.set_exception(e)

            threading.Thread(
                target=work, daemon=True, name="delta-state-update"
            ).start()

    def update(self, stale_ok: bool = False) -> Snapshot:
        """Re-list the log and install a new Snapshot if the segment changed
        (``SnapshotManagement.scala:244-330``). With ``stale_ok`` and a
        fresh-enough snapshot, return the current one immediately and refresh
        in the background (the reference's async stale-ok path,
        ``:251-263,375-380``); past the staleness bound the update is
        synchronous again."""
        if stale_ok:
            limit = (conf.get("delta.tpu.snapshot.stalenessLimitMs")
                     or conf.get("delta.tpu.stalenessLimitMs"))
            if (
                limit
                and self._snapshot is not None
                and self.clock() - self._last_update_ms < int(limit)
            ):
                self._trigger_async_refresh()
                return self._snapshot
        return self._do_update()

    def _do_update(self) -> Snapshot:
        from delta_tpu.obs import fleet as fleet_mod
        from delta_tpu.utils import telemetry

        # re-offer this handle to the fleet registry: a table constructed
        # under a telemetry blackout that later lifted must not stay
        # invisible for the life of the process (a lock-free dict probe
        # when already registered, a conf check when still dark)
        fleet_mod.register(self)

        t_arrive = time.monotonic()
        with self._update_lock, telemetry.record_operation(
            "delta.log.update", path=self.data_path
        ) as uev:
            # COALESCE a listing convoy: if the lock-holder ahead of us
            # completed a listing that STARTED after we arrived, its result
            # reflects every commit durable before our call — re-listing
            # would tell us nothing newer than another racer could. Under K
            # contending writers this collapses K queued listings into one.
            # Sequential semantics are untouched: a listing started BEFORE
            # our arrival never satisfies the check, so update() after a
            # commit always re-lists.
            if (
                self._snapshot is not None
                and self._last_listing_start >= t_arrive
            ):
                uev.data["result"] = "coalesced"
                telemetry.bump_counter("log.update.coalesced")
                return self._snapshot
            # published only when the listing COMPLETES (both return paths
            # below) — a failed listing must not let waiters adopt a result
            # staler than the check promises
            listing_start = time.monotonic()
            previous = self._snapshot
            start_ckpt = None
            last = ckpt_mod.read_last_checkpoint(self.store, self.log_path)
            if last is not None:
                start_ckpt = last.version
            segment = sm.get_log_segment_for_version(
                self.store, self.log_path, start_checkpoint=start_ckpt,
                excluded_checkpoints=self.corrupt_checkpoints,
            )
            if segment is None:
                snap: Snapshot = InitialSnapshot(self)
            elif previous is not None and previous.segment == segment:
                self._last_update_ms = self.clock()
                self._last_listing_start = listing_start
                uev.data["result"] = "unchanged"
                telemetry.bump_counter("log.update.unchanged")
                return previous
            else:
                snap = Snapshot(self, segment.version, segment)
                # Table-id drift detection (SnapshotManagement.scala:305-315) is
                # done lazily — only when the previous snapshot's state was
                # already materialized, so update() never forces a full replay.
                if (
                    previous is not None
                    and previous.version >= 0
                    and "_columnar" in previous.__dict__
                    and "metadata" in previous.__dict__
                ):
                    prev_id = previous.metadata.id
                    new_id = snap.metadata.id
                    if prev_id != new_id:
                        logger.warning(
                            "Change in the table id detected for %s: was %s, now %s",
                            self.data_path, prev_id, new_id,
                        )
            self._snapshot = snap
            self._last_update_ms = self.clock()
            self._last_listing_start = listing_start
            uev.data.update(result="installed", version=snap.version)
            telemetry.bump_counter("log.update.installed")
            return snap

    def get_snapshot_at(self, version: int) -> Snapshot:
        return sm.get_snapshot_at(self, version)

    def snapshot_for(self, version: Optional[int] = None,
                     timestamp=None, stale_ok: bool = False) -> Snapshot:
        """One shared time-travel resolution for every surface that takes
        version/timestamp options (reads, RESTORE, CLONE): at most one
        selector; timestamp = epoch ms or ISO-8601; none = latest.

        ``stale_ok`` (reads only): "latest" may be served from the staleness
        window with a background refresh. Copy-like surfaces (CLONE,
        RESTORE) must not pass it — they'd silently copy an old version."""
        if version is not None and timestamp is not None:
            raise errors_mod.DeltaAnalysisError(
                "Cannot specify both version and timestamp"
            )
        if version is not None:
            return self.get_snapshot_at(int(version))
        if timestamp is not None:
            from delta_tpu.utils.timeparse import timestamp_option_to_ms

            commit = self.history.get_active_commit_at_time(
                timestamp_option_to_ms(timestamp), can_return_last_commit=True
            )
            return self.get_snapshot_at(commit.version)
        return self.update(stale_ok=stale_ok)

    @property
    def table_exists(self) -> bool:
        return self.snapshot.version >= 0

    # -- transactions ----------------------------------------------------

    def start_transaction(self):
        from delta_tpu.txn.transaction import OptimisticTransaction

        self.update()
        return OptimisticTransaction(self)

    @property
    def group_coordinator(self):
        """This log's group-commit coordinator (``txn/group_commit``),
        created on first use — a table never grouped pays nothing."""
        gc = self._group_coordinator
        if gc is None:
            with self.lock:
                if self._group_coordinator is None:
                    from delta_tpu.txn.group_commit import GroupCommitCoordinator

                    self._group_coordinator = GroupCommitCoordinator(self)
                gc = self._group_coordinator
        return gc

    def with_new_transaction(self, thunk):
        """Run ``thunk(txn)`` with the active-transaction ambient set
        (``DeltaLog.scala:183-191``)."""
        from delta_tpu.txn.transaction import OptimisticTransaction

        txn = self.start_transaction()
        token = OptimisticTransaction.set_active(txn)
        try:
            return thunk(txn)
        finally:
            OptimisticTransaction.clear_active(token)

    # -- log tailing (DeltaLog.scala:222-238) ----------------------------

    def get_changes(
        self, start_version: int, fail_on_data_loss: bool = False
    ) -> Iterator[Tuple[int, List[Action]]]:
        """Yield (version, actions) for every commit >= start_version."""
        prefix = f"{self.log_path}/{filenames.check_version_prefix(start_version)}"
        last_seen: Optional[int] = None
        try:
            statuses = list(self.store.list_from(prefix))
        except FileNotFoundError:
            statuses = []
        for fs in statuses:
            if not filenames.is_delta_file(fs.name):
                continue
            v = filenames.delta_version(fs.name)
            if fail_on_data_loss and last_seen is None and v > start_version:
                raise DeltaIllegalStateError(
                    f"Events were deleted: expected version {start_version}, first available {v}"
                )
            if last_seen is not None and v > last_seen + 1:
                raise versions_not_contiguous([last_seen, v])
            last_seen = v
            yield v, actions_from_lines(self.store.read_iter(fs.path))

    # -- protocol gating (DeltaLog.scala:248-275) ------------------------

    def assert_protocol_read(self, protocol: Protocol) -> None:
        """Reader gate, feature-aware: legacy versions we implement (1) pass;
        version 2 (column mapping) is refused; version 3 (table features)
        passes only when every listed readerFeature is supported — a missing
        list at version 3 is spec-invalid and also refused."""
        if protocol is None:
            return
        v = protocol.min_reader_version
        ok = v <= READER_VERSION or (
            v == SUPPORTED_READER_VERSION
            and protocol.reader_features is not None
            and set(protocol.reader_features) <= SUPPORTED_READER_FEATURES
        )
        if not ok:
            raise errors_mod.invalid_protocol_version(
                SUPPORTED_READER_VERSION, SUPPORTED_WRITER_VERSION,
                v, protocol.min_writer_version or 0,
            )

    def assert_protocol_write(self, protocol: Protocol, log_upgrade_message: bool = True) -> None:
        """Writer gate: legacy versions up to 4 (invariants/constraints/
        generated columns — all implemented) pass; 5/6 (column mapping,
        identity columns) are refused; 7 (table features) passes only when
        every listed writerFeature is supported."""
        if protocol is None:
            return
        v = protocol.min_writer_version
        ok = v <= WRITER_VERSION or (
            v == SUPPORTED_WRITER_VERSION
            and protocol.writer_features is not None
            and set(protocol.writer_features) <= SUPPORTED_WRITER_FEATURES
        )
        if not ok:
            raise errors_mod.invalid_protocol_version(
                SUPPORTED_READER_VERSION, SUPPORTED_WRITER_VERSION,
                protocol.min_reader_version or 0, v,
            )

    def upgrade_protocol(self, new_protocol: Protocol) -> None:
        """Explicit protocol upgrade (DeltaLog.scala:198-216)."""
        snap = self.update()
        current = snap.protocol
        if (
            current.min_reader_version >= new_protocol.min_reader_version
            and current.min_writer_version >= new_protocol.min_writer_version
        ):
            logger.info("Table already at protocol %s; skipping upgrade", current)
            return
        if (
            new_protocol.min_reader_version < current.min_reader_version
            or new_protocol.min_writer_version < current.min_writer_version
        ):
            raise ProtocolError(
                f"Protocol version cannot be downgraded from {current} to {new_protocol}"
            )
        from delta_tpu.txn.transaction import OptimisticTransaction
        from delta_tpu.commands.operations import UpgradeProtocol

        txn = self.start_transaction()
        txn.new_protocol = new_protocol
        txn.commit([], UpgradeProtocol(new_protocol))

    # -- checkpointing ---------------------------------------------------

    def checkpoint(self, snapshot: Optional[Snapshot] = None) -> ckpt_mod.CheckpointMetaData:
        """Write a checkpoint of ``snapshot`` (default: current) and update
        ``_last_checkpoint`` (``Checkpoints.scala:221-260``)."""
        from delta_tpu.utils import telemetry

        snap = snapshot or self.update()
        if snap.version < 0:
            raise DeltaIllegalStateError("Cannot checkpoint an uninitialized table")
        part_size = conf.get("delta.tpu.checkpointPartSize")
        with telemetry.record_operation(
            "delta.checkpoint", path=self.data_path
        ) as cev:
            # columnar fast path: AddFiles stream from the SoA columns without
            # dataclass materialization (None = unsupported shape)
            md = ckpt_mod.write_checkpoint_columnar(
                self.store, self.log_path, snap, part_size=part_size or 1_000_000
            )
            writer = "columnar"
            if md is None:
                actions = snap.checkpoint_actions()
                md = ckpt_mod.write_checkpoint(
                    self.store, self.log_path, snap.version, actions,
                    part_size=part_size,
                )
                writer = "rows"
            cev.data.update(version=md.version, numActions=md.size,
                            parts=md.parts or 1, writer=writer)
            telemetry.bump_counter("checkpoint.written")
            self.cleanup_expired_logs(snap)
        if cev.duration_ms is not None:  # unmeasured (telemetry disabled)
            telemetry.observe("delta.checkpoint.duration_ms", cev.duration_ms,
                              path=self.data_path)
        return md

    def cleanup_expired_logs(self, snapshot: Snapshot) -> None:
        from delta_tpu.log.cleanup import cleanup_expired_logs

        try:
            if DeltaConfigs.ENABLE_EXPIRED_LOG_CLEANUP.from_metadata(snapshot.metadata):
                cleanup_expired_logs(self, snapshot)
        except Exception:  # noqa: BLE001 — cleanup must not fail commits
            logger.warning("Metadata cleanup failed", exc_info=True)

    # -- post-commit hook from transactions ------------------------------

    def update_after_commit(self, committed_version: int, new_snapshot_hint: Optional[Snapshot] = None) -> Snapshot:
        snap = self.update()
        if snap.version < committed_version:
            raise DeltaIllegalStateError(
                f"The committed version is {committed_version} but the current version is {snap.version}"
            )
        return snap

    def write_checksum_for(self, snapshot: Snapshot) -> None:
        crc_mod.write_checksum(
            self.store, self.log_path, snapshot.version, crc_mod.VersionChecksum.of_snapshot(snapshot)
        )

    # -- history ---------------------------------------------------------

    @property
    def history(self):
        from delta_tpu.log.history import DeltaHistoryManager

        return DeltaHistoryManager(self)

    def __repr__(self) -> str:
        return f"DeltaLog({self.data_path!r}, v={self._snapshot.version if self._snapshot else '?'})"
