"""Real 2-process DCN integration (VERDICT r3 item 3): two OS processes in a
`jax.distributed` CPU cluster drive multi-host scan, distributed checkpoint
part writing, and fragment-exchanged CONVERT against one shared table dir —
plus a unit check that vacuum's delete fan-out composes with the same
partitioner. No mocks: real subprocesses, real coordination service."""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from delta_tpu import DeltaLog
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.parallel.distributed import host_partition, host_shard_indices

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_cluster_scan_checkpoint_convert(tmp_path):
    table = str(tmp_path / "table")
    log = DeltaLog.for_table(table)
    for i in range(6):
        WriteIntoDelta(log, "append", pa.table({
            "id": np.arange(i * 10, (i + 1) * 10, dtype=np.int64),
            "v": np.random.rand(10),
        })).run()

    convert_dir = str(tmp_path / "plain")
    os.makedirs(convert_dir)
    for i in range(5):
        pq.write_table(
            pa.table({"a": np.arange(i * 4, (i + 1) * 4, dtype=np.int64)}),
            os.path.join(convert_dir, f"part-{i}.parquet"),
        )

    out_dir = str(tmp_path / "out")
    os.makedirs(out_dir)
    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)  # the virtual 8-device mesh is for in-proc tests
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", "multihost_worker.py"),
             str(i), "2", str(port), table, convert_dir, out_dir],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=150) for p in procs]
    for p, (so, se) in zip(procs, outs):
        assert p.returncode == 0, se.decode()[-3000:]

    results = []
    for i in range(2):
        with open(os.path.join(out_dir, f"result-{i}.json")) as f:
            results.append(json.load(f))

    # scan: the two hosts' partitions tile the table exactly
    assert all(r["count"] == 2 for r in results)
    assert results[0]["full_rows"] == 60
    assert results[0]["scan_rows"] + results[1]["scan_rows"] == 60
    ids = sorted(results[0]["scan_ids"] + results[1]["scan_ids"])
    assert ids == list(range(60))

    # checkpoint: all 4 parts exist, _last_checkpoint published once,
    # and a cold reader reconstructs from it
    from delta_tpu.log import checkpoints as ckpt_mod

    last = ckpt_mod.read_last_checkpoint(log.store, log.log_path)
    assert last is not None and last.parts == 4
    DeltaLog.clear_cache()
    snap = DeltaLog.for_table(table).update()
    assert snap.num_of_files == 6
    assert snap.segment.checkpoint_version == last.version

    # convert: both processes agree on the committed version; all files in
    assert results[0]["convert_version"] == results[1]["convert_version"]
    assert all(r["convert_files"] == 5 for r in results)
    DeltaLog.clear_cache()
    csnap = DeltaLog.for_table(convert_dir).update()
    t = sorted(
        __import__("delta_tpu.exec.scan", fromlist=["scan_to_table"])
        .scan_to_table(csnap).column("a").to_pylist()
    )
    assert t == list(range(20))


def test_vacuum_composes_with_scan_partitioning():
    """The same strided partitioner drives vacuum's delete fan-out and the
    distributed scan: for any (index, count) the slices tile the work list
    without overlap — the composition property the multi-host paths rely on."""
    items = [f"f{i}" for i in range(13)]
    for count in (1, 2, 3, 5):
        seen = []
        for index in range(count):
            seen += host_partition(items, index, count)
        assert sorted(seen) == sorted(items)
        # disjointness
        assert len(seen) == len(set(seen))
        for index in range(count):
            idx = host_shard_indices(len(items), index, count)
            assert idx == list(range(index, len(items), count))


def test_convert_fragment_exchange_empty_slice_and_token(tmp_path):
    """A host with an empty file slice publishes a schema-less fragment
    (fewer files than processes must not crash), and fragments are
    namespaced by a listing hash so a retry after the data changed cannot
    consume stale ones."""
    from delta_tpu.commands.convert import ConvertToDeltaCommand

    d = str(tmp_path / "plain")
    os.makedirs(d)
    pq.write_table(pa.table({"a": np.arange(3, dtype=np.int64)}),
                   os.path.join(d, "only.parquet"))
    log = DeltaLog.for_table(d)
    cmd = ConvertToDeltaCommand(log, collect_stats=True, distribute=True)
    files = cmd._list_parquet_files()
    assert len(files) == 1
    # "proc 1" has the empty slice: publish its (schema-less) fragment
    m1, f1 = cmd._exchange_fragments(1, 2, None, [], files)
    assert m1 is None and f1 == []
    # "proc 0" computed the file and gathers both fragments
    abs_p = os.path.join(d, files[0][0])
    schema = pq.ParquetFile(abs_p).schema_arrow
    adds0 = [{"i": 0, "rel": files[0][0], "size": files[0][1],
              "mtime": files[0][2], "stats": None}]
    merged, all_adds = cmd._exchange_fragments(0, 2, schema, adds0, files)
    assert merged is not None and len(all_adds) == 1
    # token changes when the listing changes (stale fragments unreachable)
    t1 = cmd._listing_token(files)
    pq.write_table(pa.table({"a": np.arange(2, dtype=np.int64)}),
                   os.path.join(d, "second.parquet"))
    t2 = cmd._listing_token(cmd._list_parquet_files())
    assert t1 != t2
