"""Device/host MERGE result-identity matrix (ISSUE 6 satellite).

The fused device path — both residency variants: the cold slab pipeline
(`MergeIntoCommand._launch_slab_pipeline` + `ops/key_cache.SlabBuilder`)
and the HBM cache hit (`ops/key_cache.KeyCache`) — must be row-identical
to the host Arrow hash join across the semantic corners: matched /
not-matched / insert-only / multi-match error / NULL-key sentinels /
composite packed keys, deletion vectors included. Every scenario runs the
same merge on two copies of a seeded table, fused-forced vs host-pinned,
and compares the full sorted row sets.
"""
import shutil

import numpy as np
import pyarrow as pa
import pytest

from delta_tpu import DeltaLog
from delta_tpu.commands.merge import MergeClause, MergeIntoCommand
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.expr import ir
from delta_tpu.ops.key_cache import KeyCache
from delta_tpu.utils.config import conf
from delta_tpu.utils.errors import DeltaUnsupportedOperationError


@pytest.fixture(autouse=True)
def _fresh_cache():
    KeyCache.reset()
    yield
    KeyCache.reset()


@pytest.fixture(params=["cold", "hit"])
def fused(request):
    """Which fused-device residency variant the scenario forces: 'cold'
    (no cached entry — the slab pipeline builds + registers inline) or
    'hit' (the key lane is pre-built, the merge probes the cache)."""
    return request.param


UP = MergeClause("update", assignments=None)
INS = MergeClause("insert", assignments=None)
DEL = MergeClause("delete")
ALIAS = dict(source_alias="s", target_alias="t")


def _seed_table(path, *, composite=False, with_null_target=False, files=3):
    """Multi-file target with negative + positive int64 keys and payload
    columns; optionally a second key component / NULL target keys."""
    log = DeltaLog.for_table(str(path))
    rng = np.random.RandomState(11)
    per = 40
    for i in range(files):
        lo = -40 + i * per
        keys = np.arange(lo, lo + per, dtype=np.int64)
        k = pa.array(keys)
        if with_null_target and i == 1:
            py = keys.tolist()
            py[3] = None  # one NULL target key per middle file
            k = pa.array(py, pa.int64())
        cols = {
            "k": k,
            "v": pa.array(rng.rand(per)),
            "tag": pa.array([f"r{j}" for j in keys]),
        }
        if composite:
            cols["k2"] = pa.array((keys % 7).astype(np.int64))
        WriteIntoDelta(log, "append", pa.table(cols)).run()
    return log


def _rows(log, keys=("k",)):
    from delta_tpu.exec.scan import scan_to_table

    t = scan_to_table(log.update())
    return sorted(t.to_pylist(), key=lambda r: tuple(
        (r[c] is None, r[c]) for c in list(keys) + ["tag", "v"]))


def _run(log, source, cond, matched, not_matched, mode):
    with conf.set_temporarily(**{
        "delta.tpu.merge.devicePath.mode": mode,
        "delta.tpu.deletionVectors.enabled": True,
        "delta.tpu.merge.keyCache.enabled": mode != "off",
    }):
        cmd = MergeIntoCommand(log, source, cond, matched, not_matched,
                               **ALIAS)
        cmd.run()
    return cmd


def _prebuild(log, cond, target_cols, source_cols):
    """Build the table's resident key lane using the merge's own resolved
    key signature (what the background build would have produced)."""
    probe = MergeIntoCommand(log, pa.table({c: pa.array([], pa.int64())
                                            for c in source_cols}),
                             cond, [UP], [INS], **ALIAS)
    resolved = probe._resolve(probe.condition, target_cols, source_cols)
    equi, _ = probe._split_equi_keys(resolved)
    t_exprs = [t for t, _ in equi]
    sig = MergeIntoCommand._key_signature(t_exprs)
    key_cols = [c for c in target_cols
                if c.lower() in {r.lower() for t, _ in equi
                                 for r in ir.references(t)}]
    e = KeyCache.instance().get(log.update(), sig, key_cols, t_exprs)
    assert e is not None
    return e


def _identity_case(tmp_path, fused, source, cond, matched, not_matched,
                   *, composite=False, with_null_target=False,
                   expect_path=None, keys=("k",)):
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    log_a = _seed_table(a, composite=composite,
                        with_null_target=with_null_target)
    shutil.copytree(a, b)
    log_b = DeltaLog.for_table(b)
    tcols = [f.name for f in log_a.update().metadata.schema.fields]
    scols = source.column_names
    if fused == "hit":
        _prebuild(log_a, cond, tcols, scols)
    cmd_a = _run(log_a, source, cond, matched, not_matched, "force")
    cmd_b = _run(log_b, source, cond, matched, not_matched, "off")
    assert cmd_a._device_join is not None, "fused path did not engage"
    assert cmd_a._join_path == (
        expect_path or ("resident" if fused == "hit" else "device-cold"))
    assert cmd_b._device_join is None
    for k in sorted(set(cmd_a.metrics) & set(cmd_b.metrics)):
        if k.endswith("TimeMs"):
            continue  # wall-clock differs by construction
        assert cmd_a.metrics[k] == cmd_b.metrics[k], k
    assert _rows(log_a, keys) == _rows(log_b, keys)
    return cmd_a, cmd_b


# -- the matrix -------------------------------------------------------------


def _upsert_source():
    rng = np.random.RandomState(3)
    keys = np.concatenate([
        np.arange(-10, 20, 3, dtype=np.int64),        # hits incl. negatives
        np.arange(500, 520, dtype=np.int64),          # misses -> inserts
    ])
    return pa.table({
        "k": pa.array(keys),
        "v": pa.array(rng.rand(len(keys))),
        "tag": pa.array([f"s{i}" for i in range(len(keys))]),
    })


def test_matched_and_not_matched_upsert(tmp_path, fused):
    """The headline shape: star upsert, hits + misses, DV mode."""
    cmd_a, _ = _identity_case(
        tmp_path, fused, _upsert_source(), "t.k = s.k", [UP], [INS])
    assert cmd_a.metrics["numTargetRowsUpdated"] == 10
    assert cmd_a.metrics["numTargetRowsInserted"] == 20


def test_matched_only_with_clause_conditions(tmp_path, fused):
    """UPDATE/DELETE with conditions referencing both sides; no inserts."""
    src = _upsert_source()
    _identity_case(
        tmp_path, fused, src, "t.k = s.k",
        [MergeClause("update", condition="s.v >= 0.5", assignments=None),
         MergeClause("delete")],
        [])


def test_insert_only_duplicate_sources(tmp_path, fused):
    """Insert-only fast path: duplicate source keys are legal (left-anti),
    and the fused probe fetches only the head (no pair download)."""
    keys = np.array([5, 5, 700, 700, 701, -3], np.int64)
    src = pa.table({
        "k": pa.array(keys),
        "v": pa.array(np.linspace(0, 1, len(keys))),
        "tag": pa.array([f"d{i}" for i in range(len(keys))]),
    })
    cmd_a, _ = _identity_case(
        tmp_path, fused, src, "t.k = s.k", [], [INS])
    # 5 and -3 exist; one insert per miss ROW (700, 700, 701)
    assert cmd_a.metrics["numTargetRowsInserted"] == 3


def test_null_source_and_target_keys_sentinel(tmp_path, fused):
    """SQL NULL semantics under sentinel encoding: NULL source keys never
    match (they insert), NULL target keys never match (they stay)."""
    src = pa.table({
        "k": pa.array([7, None, None, 900], pa.int64()),
        "v": pa.array([0.1, 0.2, 0.3, 0.4]),
        "tag": pa.array(["n0", "n1", "n2", "n3"]),
    })
    cmd_a, _ = _identity_case(
        tmp_path, fused, src, "t.k = s.k", [UP], [INS],
        with_null_target=True)
    assert cmd_a.metrics["numTargetRowsUpdated"] == 1   # only k=7
    assert cmd_a.metrics["numTargetRowsInserted"] == 3  # 2 NULLs + 900


def test_composite_packed_keys(tmp_path, fused):
    """Two-component equi keys pack into one int64 lane (hi<<32|lo) with
    negative components; identity incl. per-component NULLs."""
    keys = np.array([-5, 2, 9, 9, 333], np.int64)
    src = pa.table({
        "k": pa.array(keys),
        "k2": pa.array([(-5) % 7, 2 % 7, 9 % 7, 6, 1], pa.int64()),
        "v": pa.array(np.linspace(0, 1, len(keys))),
        "tag": pa.array([f"c{i}" for i in range(len(keys))]),
    })
    cmd_a, _ = _identity_case(
        tmp_path, fused, src, "t.k = s.k AND t.k2 = s.k2", [UP], [INS],
        composite=True, keys=("k", "k2"))
    # (9, 6) and (333, 1) miss; (-5), (2), (9 % 7) hit
    assert cmd_a.metrics["numTargetRowsUpdated"] == 3
    assert cmd_a.metrics["numTargetRowsInserted"] == 2


def test_multi_match_error_parity(tmp_path, fused):
    """Duplicate source matches for one target row must raise on BOTH
    executors (reference `MergeIntoCommand.scala:351-365`)."""
    src = pa.table({
        "k": pa.array([4, 4], pa.int64()),
        "v": pa.array([1.0, 2.0]),
        "tag": pa.array(["m0", "m1"]),
    })
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    log_a = _seed_table(a)
    shutil.copytree(a, b)
    log_b = DeltaLog.for_table(b)
    if fused == "hit":
        _prebuild(log_a, "t.k = s.k", ["k", "v", "tag"], src.column_names)
    with pytest.raises(DeltaUnsupportedOperationError, match="multiple source"):
        _run(log_a, src, "t.k = s.k", [UP], [INS], "force")
    with pytest.raises(DeltaUnsupportedOperationError, match="multiple source"):
        _run(log_b, src, "t.k = s.k", [UP], [INS], "off")
    # single unconditional DELETE legally multi-matches on both
    cmd_a = _run(log_a, src, "t.k = s.k", [DEL], [], "force")
    cmd_b = _run(log_b, src, "t.k = s.k", [DEL], [], "off")
    assert cmd_a.metrics["numTargetRowsDeleted"] == 1
    assert cmd_b.metrics["numTargetRowsDeleted"] == 1
    assert _rows(log_a) == _rows(log_b)


def test_second_round_over_deletion_vectors(tmp_path, fused):
    """Round 2 merges into the DV-carrying files round 1 produced: the cold
    slab build must scatter DV-filtered decodes into physical layout, the
    hit path must advance through the DV diff."""
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    log_a = _seed_table(a)
    shutil.copytree(a, b)
    log_b = DeltaLog.for_table(b)
    if fused == "hit":
        _prebuild(log_a, "t.k = s.k", ["k", "v", "tag"], ["k", "v", "tag"])
    src1 = _upsert_source()
    _run(log_a, src1, "t.k = s.k", [UP], [INS], "force")
    _run(log_b, src1, "t.k = s.k", [UP], [INS], "off")
    if fused == "cold":
        KeyCache.reset()  # round 2 cold-builds over DV'd files
    src2 = pa.table({
        "k": pa.array([-10, 2, 505, 999], pa.int64()),
        "v": pa.array([9.0, 8.0, 7.0, 6.0]),
        "tag": pa.array(["z0", "z1", "z2", "z3"]),
    })
    cmd_a = _run(log_a, src2, "t.k = s.k", [UP], [INS], "force")
    cmd_b = _run(log_b, src2, "t.k = s.k", [UP], [INS], "off")
    assert cmd_a._device_join is not None
    assert cmd_a.metrics["numTargetRowsUpdated"] == 3  # -10, 2, 505
    assert cmd_a.metrics["numTargetRowsInserted"] == 1
    assert cmd_b.metrics["numTargetRowsUpdated"] == 3
    assert _rows(log_a) == _rows(log_b)


def test_post_optimize_merge_parity(tmp_path, fused):
    """ISSUE 6 small-fix regression: OPTIMIZE between merges bumps the
    key-cache epoch; the next fused merge must rebuild (never probe the
    pre-rewrite slab) and stay row-identical to the host."""
    from delta_tpu.commands.optimize import OptimizeCommand

    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    log_a = _seed_table(a)
    shutil.copytree(a, b)
    log_b = DeltaLog.for_table(b)
    _prebuild(log_a, "t.k = s.k", ["k", "v", "tag"], ["k", "v", "tag"])
    OptimizeCommand(log_a, min_file_size=1 << 30).run()
    OptimizeCommand(log_b, min_file_size=1 << 30).run()
    assert KeyCache.instance().peek(log_a.log_path,
                                    "[\"Column('k')\"]") is None \
        or not KeyCache.instance()._entries, \
        "epoch bump must drop the pre-rewrite entry"
    if fused == "hit":
        _prebuild(log_a, "t.k = s.k", ["k", "v", "tag"], ["k", "v", "tag"])
    src = _upsert_source()
    cmd_a = _run(log_a, src, "t.k = s.k", [UP], [INS], "force")
    cmd_b = _run(log_b, src, "t.k = s.k", [UP], [INS], "off")
    assert cmd_a._device_join is not None
    assert _rows(log_a) == _rows(log_b)
