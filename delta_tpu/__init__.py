"""delta-tpu: a TPU-native lakehouse framework.

Same capabilities as Delta Lake (reference mounted at ``/root/reference``):
an ACID transaction log over Parquet with optimistic concurrency, snapshot
isolation, time travel, schema enforcement/evolution, constraints, streaming
source/sink, and MERGE/UPDATE/DELETE/VACUUM — with the data plane rebuilt
for TPUs on JAX/XLA (sharded log replay, device-evaluated data skipping,
columnar MERGE kernels) instead of Spark. The on-disk transaction-log format
is byte-compatible with the Delta protocol.
"""

__version__ = "0.1.0"

from delta_tpu.log.deltalog import DeltaLog  # noqa: F401
from delta_tpu.utils.config import conf  # noqa: F401


def __getattr__(name):
    # Lazy top-level conveniences: `from delta_tpu import DeltaTable`
    # without paying the command/executor module imports at package-import
    # time. (The log kernel itself — and its pyarrow dependency — loads
    # eagerly via DeltaLog above; this defers only the data-plane glue.)
    if name == "DeltaTable":
        from delta_tpu.api.tables import DeltaTable

        return DeltaTable
    if name == "execute_sql":
        from delta_tpu.sql.parser import execute_sql

        return execute_sql
    if name == "obs":
        # `delta_tpu.obs` — operator surface (doctor, scan reports, HTTP
        # endpoint, flight recorder); lazy like the data-plane glue
        import delta_tpu.obs as obs

        return obs
    raise AttributeError(f"module 'delta_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(__all__))


__all__ = ["DeltaLog", "DeltaTable", "conf", "execute_sql", "obs",
           "__version__"]
