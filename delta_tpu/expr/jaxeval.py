"""Compile expressions to ``jnp`` ops over device-resident columns.

TPU columns are SoA pairs ``(values, valid)``: a numeric/bool lane array plus a
boolean validity mask (NULL = invalid lane). Strings never reach the device as
bytes — the host dictionary-encodes them (``ops/state_export.py``) and the
device compares int32 codes; that keeps everything MXU/VPU-friendly and
static-shaped.

Three-valued logic is carried explicitly through the mask, matching
:mod:`delta_tpu.expr.ir` row semantics (Kleene AND/OR, NULL-propagating
comparisons). Replaces the role Catalyst codegen plays in the reference
(``constraints/CheckDeltaInvariant.scala``, ``MergeIntoCommand.scala:702-752``)
with XLA-fused vector code.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

from delta_tpu.expr import ir
from delta_tpu.utils.errors import DeltaAnalysisError

__all__ = ["DeviceColumn", "compile_expr", "NotDeviceCompilable",
           "ResidualPlan", "compile_residual", "STR_CODE_ABSENT"]


class NotDeviceCompilable(DeltaAnalysisError):
    """Raised when an expression cannot be lowered to device ops
    (caller falls back to the host vectorized/row evaluators)."""


class DeviceColumn(NamedTuple):
    """One SoA column: lane values + validity mask (True = non-NULL)."""

    values: Any  # jnp array
    valid: Any  # jnp bool array

    @staticmethod
    def of(values, valid=None) -> "DeviceColumn":
        values = jnp.asarray(values)
        if valid is None:
            valid = jnp.ones(values.shape, dtype=bool)
        return DeviceColumn(values, jnp.asarray(valid, dtype=bool))


Env = Dict[str, DeviceColumn]
_Compiled = Callable[[Env], DeviceColumn]


def _lit(e: ir.Literal) -> _Compiled:
    v = e.value
    if v is None:
        return lambda env: DeviceColumn(jnp.zeros((), jnp.float32), jnp.zeros((), bool))
    # Keep literals as numpy until trace time: wide dtypes (int64/float64)
    # only take effect inside the kernel's jax.enable_x64() scope.
    if isinstance(v, bool):
        arr = np.asarray(v)
    elif isinstance(v, int):
        if not (-(2**63) <= v < 2**63):
            raise NotDeviceCompilable(f"integer literal {v} exceeds int64")
        arr = np.asarray(v, np.int64 if not (-(2**31) <= v < 2**31) else np.int32)
    elif isinstance(v, float):
        arr = np.asarray(v, np.float64)
    else:
        raise NotDeviceCompilable(f"literal {v!r} has no device representation")
    return lambda env: DeviceColumn(jnp.asarray(arr), jnp.ones((), bool))


def _col(e: ir.Column) -> _Compiled:
    name = e.name

    def run(env: Env) -> DeviceColumn:
        c = env.get(name) or env.get(name.lower())
        if c is None:
            raise NotDeviceCompilable(f"column {name!r} not bound in device env")
        return c

    return run


def _binop(e, fn) -> _Compiled:
    lf, rf = compile_expr(e.left), compile_expr(e.right)

    def run(env: Env) -> DeviceColumn:
        l, r = lf(env), rf(env)
        return DeviceColumn(fn(l.values, r.values), l.valid & r.valid)

    return run


def _kleene_and(e: ir.And) -> _Compiled:
    lf, rf = compile_expr(e.left), compile_expr(e.right)

    def run(env: Env) -> DeviceColumn:
        l, r = lf(env), rf(env)
        lt = l.values.astype(bool) & l.valid  # definitely TRUE
        rt = r.values.astype(bool) & r.valid
        lF = ~l.values.astype(bool) & l.valid  # definitely FALSE
        rF = ~r.values.astype(bool) & r.valid
        value = lt & rt
        valid = value | lF | rF
        return DeviceColumn(value, valid)

    return run


def _kleene_or(e: ir.Or) -> _Compiled:
    lf, rf = compile_expr(e.left), compile_expr(e.right)

    def run(env: Env) -> DeviceColumn:
        l, r = lf(env), rf(env)
        lv = l.values.astype(bool) & l.valid
        rv = r.values.astype(bool) & r.valid
        value = lv | rv
        valid = (l.valid & r.valid) | lv | rv
        return DeviceColumn(value, valid)

    return run


def _div(e: ir.Div) -> _Compiled:
    lf, rf = compile_expr(e.left), compile_expr(e.right)

    def run(env: Env) -> DeviceColumn:
        l, r = lf(env), rf(env)
        rnz = r.values != 0
        lv = l.values.astype(jnp.float64)
        rv = jnp.where(rnz, r.values, 1).astype(jnp.float64)
        return DeviceColumn(lv / rv, l.valid & r.valid & rnz)

    return run


_CMP = {
    ir.Eq: lambda a, b: a == b,
    ir.Ne: lambda a, b: a != b,
    ir.Lt: lambda a, b: a < b,
    ir.Le: lambda a, b: a <= b,
    ir.Gt: lambda a, b: a > b,
    ir.Ge: lambda a, b: a >= b,
    ir.Add: lambda a, b: a + b,
    ir.Sub: lambda a, b: a - b,
    ir.Mul: lambda a, b: a * b,
}


def compile_expr(e: ir.Expression) -> _Compiled:
    """Lower an expression tree to a function over a device-column env.

    Raises :class:`NotDeviceCompilable` for string ops / casts / functions
    that belong on the host.
    """
    t = type(e)
    if t is ir.Literal:
        return _lit(e)
    if t is ir.Column:
        return _col(e)
    if t is ir.Alias:
        return compile_expr(e.child)
    if t in _CMP:
        return _binop(e, _CMP[t])
    if t is ir.And:
        return _kleene_and(e)
    if t is ir.Or:
        return _kleene_or(e)
    if t is ir.Div:
        return _div(e)
    if t is ir.Not:
        cf = compile_expr(e.child)
        return lambda env: (lambda c: DeviceColumn(~c.values.astype(bool), c.valid))(cf(env))
    if t is ir.Neg:
        cf = compile_expr(e.child)
        return lambda env: (lambda c: DeviceColumn(-c.values, c.valid))(cf(env))
    if t is ir.IsNull:
        cf = compile_expr(e.child)
        return lambda env: (lambda c: DeviceColumn(~c.valid, jnp.ones_like(c.valid)))(cf(env))
    if t is ir.IsNotNull:
        cf = compile_expr(e.child)
        return lambda env: (lambda c: DeviceColumn(c.valid, jnp.ones_like(c.valid)))(cf(env))
    if t is ir.NullSafeEq:
        lf, rf = compile_expr(e.left), compile_expr(e.right)

        def run_nse(env: Env) -> DeviceColumn:
            l, r = lf(env), rf(env)
            eq = (l.values == r.values) & l.valid & r.valid
            both_null = ~l.valid & ~r.valid
            return DeviceColumn(eq | both_null, jnp.ones_like(eq))

        return run_nse
    if t is ir.In:
        vf = compile_expr(e.value)
        opts = [compile_expr(o) for o in e.options]

        def run_in(env: Env) -> DeviceColumn:
            v = vf(env)
            hit = jnp.zeros(jnp.shape(v.values), bool)
            any_null_opt = jnp.zeros((), bool)
            for of in opts:
                o = of(env)
                hit = hit | ((v.values == o.values) & o.valid)
                any_null_opt = any_null_opt | ~jnp.all(o.valid)
            valid = v.valid & (hit | ~any_null_opt)
            return DeviceColumn(hit, valid)

        return run_in
    if t is ir.Coalesce:
        fns = [compile_expr(c) for c in e.children]

        def run_coalesce(env: Env) -> DeviceColumn:
            cols = [f(env) for f in fns]
            out = cols[-1]
            for c in reversed(cols[:-1]):
                out = DeviceColumn(
                    jnp.where(c.valid, c.values, out.values), c.valid | out.valid
                )
            return out

        return run_coalesce
    if t is ir.CaseWhen:
        conds = [compile_expr(e.children[2 * i]) for i in range(e.n_branches)]
        vals = [compile_expr(e.children[2 * i + 1]) for i in range(e.n_branches)]
        default = compile_expr(e.children[-1])

        def run_case(env: Env) -> DeviceColumn:
            out = default(env)
            for cf, vf2 in zip(reversed(conds), reversed(vals)):
                c, v = cf(env), vf2(env)
                fire = c.values.astype(bool) & c.valid
                out = DeviceColumn(
                    jnp.where(fire, v.values, out.values),
                    jnp.where(fire, v.valid, out.valid),
                )
            return out

        return run_case
    if t is ir.Cast:
        cf = compile_expr(e.child)
        name = e.data_type.name if not hasattr(e.data_type, "precision") else "decimal"
        if name in ("byte", "short", "integer"):
            dtype: Any = jnp.int32
        elif name == "long":
            dtype = jnp.int64
        elif name in ("float", "double", "decimal"):
            # host row-eval casts produce python doubles; match that width
            dtype = jnp.float64
        elif name == "boolean":
            dtype = bool
        else:
            raise NotDeviceCompilable(f"cast to {name} not device-representable")
        return lambda env: (lambda c: DeviceColumn(c.values.astype(dtype), c.valid))(cf(env))
    if t is ir.Func and e.name in ("abs", "floor", "ceil", "exp", "sqrt"):
        cf = compile_expr(e.children[0])
        if e.name == "sqrt":
            # Spark: NULL outside the domain (the row evaluator's contract)
            return lambda env: (lambda c: DeviceColumn(
                jnp.sqrt(jnp.maximum(c.values.astype(jnp.float64), 0.0)),
                c.valid & (c.values >= 0)))(cf(env))
        fn = {"abs": jnp.abs, "floor": jnp.floor, "ceil": jnp.ceil,
              "exp": lambda v: jnp.exp(v.astype(jnp.float64))}[e.name]
        return lambda env: (lambda c: DeviceColumn(fn(c.values), c.valid))(cf(env))
    if t is ir.Func and e.name == "log" and len(e.children) == 1:
        cf = compile_expr(e.children[0])
        return lambda env: (lambda c: DeviceColumn(
            jnp.log(jnp.maximum(c.values.astype(jnp.float64), 1e-300)),
            c.valid & (c.values > 0)))(cf(env))
    if t is ir.Func and e.name in ("pow", "power") and len(e.children) == 2:
        cx = compile_expr(e.children[0])
        cy = compile_expr(e.children[1])
        return lambda env: (lambda a, b: DeviceColumn(
            jnp.power(a.values.astype(jnp.float64), b.values.astype(jnp.float64)),
            a.valid & b.valid))(cx(env), cy(env))
    if t is ir.Func and e.name in ("date_add", "date_sub") and len(e.children) == 2:
        # date lanes are epoch days on device
        cd = compile_expr(e.children[0])
        cn = compile_expr(e.children[1])
        sign = 1 if e.name == "date_add" else -1
        return lambda env: (lambda d, n: DeviceColumn(
            d.values + sign * n.values.astype(d.values.dtype),
            d.valid & n.valid))(cd(env), cn(env))
    if t is ir.Func and e.name == "datediff" and len(e.children) == 2:
        ca = compile_expr(e.children[0])
        cb = compile_expr(e.children[1])
        return lambda env: (lambda a, b: DeviceColumn(
            a.values - b.values, a.valid & b.valid))(ca(env), cb(env))
    if t is ir.Func and e.name in ("minute", "second") and len(e.children) == 1:
        ct = compile_expr(e.children[0])
        div = 60_000_000 if e.name == "minute" else 1_000_000
        return lambda env: (lambda c: DeviceColumn(
            (c.values // div) % 60, c.valid))(ct(env))
    if t is ir.Func and e.name == "hour" and len(e.children) == 1:
        # timestamp lanes are epoch microseconds (naive UTC)
        ct = compile_expr(e.children[0])
        return lambda env: (lambda c: DeviceColumn(
            (c.values // 3_600_000_000) % 24, c.valid))(ct(env))
    if t is ir.Func and e.name == "__ts_days" and len(e.children) == 1:
        # compile_residual's unit bridge: epoch-µs timestamp lane → epoch
        # days, so the calendar kernels below serve both temporal lanes
        ct = compile_expr(e.children[0])
        return lambda env: (lambda c: DeviceColumn(
            jnp.floor_divide(c.values, 86_400_000_000), c.valid))(ct(env))
    if t is ir.Func and e.name in ("__year_days", "__month_days",
                                   "__day_days") and len(e.children) == 1:
        ct = compile_expr(e.children[0])
        idx = ("__year_days", "__month_days", "__day_days").index(e.name)

        def run_civil(env: Env, _ct=ct, _idx=idx) -> DeviceColumn:
            # civil-from-days (Hinnant): exact for every date32 value; all
            # intermediate operands are non-negative after the era shift,
            # so jnp floor division matches the reference arithmetic
            c = _ct(env)
            z = c.values.astype(jnp.int64) + 719468
            era = jnp.floor_divide(z, 146097)
            doe = z - era * 146097
            yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
            doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
            mp = (5 * doy + 2) // 153
            day = doy - (153 * mp + 2) // 5 + 1
            month = jnp.where(mp < 10, mp + 3, mp - 9)
            year = yoe + era * 400 + (month <= 2)
            return DeviceColumn((year, month, day)[_idx], c.valid)

        return run_civil
    raise NotDeviceCompilable(f"{type(e).__name__} has no device lowering: {e.sql()}")


def columns_from_numpy(data: Dict[str, np.ndarray], masks: Optional[Dict[str, np.ndarray]] = None) -> Env:
    """Build a device env from host numpy columns (tests / small paths)."""
    masks = masks or {}
    return {k: DeviceColumn.of(v, masks.get(k)) for k, v in data.items()}


# -- residual-predicate lowering (the device scan path) ----------------------

#: dictionary code bound to a string literal ABSENT from a file's
#: dictionary — real codes are >= 0, so equality never fires against it
#: and inequality fires for every non-NULL row, exactly the host verdicts.
STR_CODE_ABSENT = -2

_STRLIT_PREFIX = "__strlit"
_CMP_TYPES = (ir.Eq, ir.Ne, ir.Lt, ir.Le, ir.Gt, ir.Ge)
_CMP_FLIP = {ir.Lt: ir.Gt, ir.Le: ir.Ge, ir.Gt: ir.Lt, ir.Ge: ir.Le}


class ResidualPlan(NamedTuple):
    """A residual predicate lowered for the device scan path
    (``ops/column_cache``): the rewritten expression (string literals have
    become placeholder columns over dictionary codes, temporal literals
    epoch ints — hashable, so it doubles as the jit-cache key), the data
    columns the device env must bind as lanes, the partition columns bound
    as per-file scalars, and the string-literal bindings the caller resolves
    per file against that file's dictionary (absent value →
    :data:`STR_CODE_ABSENT`)."""

    expr: ir.Expression
    refs: frozenset        # data columns needed as lanes (lower-cased)
    part_refs: frozenset   # partition columns bound as per-file scalars
    str_binds: tuple       # ((placeholder, column_lower, literal_value), ...)


def compile_residual(e: ir.Expression, types: Dict[str, Any],
                     partition_names=()) -> ResidualPlan:
    """Rewrite + gate a residual predicate so :func:`compile_expr` can run
    it over decoded file lanes:

    * string equality / ``IN`` against literals lowers to int32
      dictionary-code compares via per-file placeholder columns — string
      ORDER comparisons do not lower (codes are unordered);
    * date/timestamp literals (ISO strings or datetime objects) become the
      lane encodings (epoch days / epoch microseconds), and
      ``year``/``month``/``day``/``to_date``/``hour`` over temporal columns
      lower to the device calendar kernels;
    * decimal columns, string partition references, and mixed
      date-vs-timestamp compares raise :class:`NotDeviceCompilable` — the
      caller falls back to the Arrow path.

    ``types`` maps lower-cased column names to declared
    :class:`~delta_tpu.schema.types.DataType`; ``partition_names`` marks the
    columns bound as per-file scalars instead of lanes.
    """
    import datetime as _dt

    from delta_tpu.schema.types import (DateType, DecimalType, StringType,
                                        TimestampType)

    parts = frozenset(c.lower() for c in partition_names)
    binds: list = []
    refs: set = set()
    part_refs: set = set()

    def _ctype(x):
        while isinstance(x, ir.Alias):
            x = x.child
        if isinstance(x, ir.Column):
            return types.get(x.name.lower())
        if isinstance(x, ir.Func) and x.name == "to_date" and len(x.children) == 1:
            ct = _ctype(x.children[0])
            return DateType() if isinstance(ct, (DateType, TimestampType)) else None
        return None

    def _note(c: ir.Column) -> ir.Column:
        n = c.name.lower()
        if isinstance(types.get(n), DecimalType):
            raise NotDeviceCompilable(
                f"decimal column {c.name!r} stays on host (exact arithmetic)")
        if n in parts:
            if isinstance(types.get(n), StringType):
                raise NotDeviceCompilable(
                    f"string partition column {c.name!r} has no device codes")
            part_refs.add(n)
        else:
            refs.add(n)
        return ir.Column(n)

    def _temporal_lit(lit: ir.Literal, dt) -> ir.Literal:
        v = lit.value
        if v is None:
            return lit
        if isinstance(v, str):
            from delta_tpu.utils.timeparse import iso_to_date, iso_to_naive_utc

            try:
                v = (iso_to_date(v) if isinstance(dt, DateType)
                     else iso_to_naive_utc(v))
            except ValueError:
                raise NotDeviceCompilable(
                    f"unparseable temporal literal {lit.value!r}") from None
        if isinstance(dt, TimestampType) and isinstance(v, _dt.date) \
                and not isinstance(v, _dt.datetime):
            v = _dt.datetime.combine(v, _dt.time())  # midnight, like Spark
        if isinstance(v, _dt.datetime):
            if not isinstance(dt, TimestampType):
                raise NotDeviceCompilable("timestamp literal vs date lane")
            if v.tzinfo is None:
                v = v.replace(tzinfo=_dt.timezone.utc)  # naive IS UTC here
            return ir.Literal(int(v.timestamp() * 1_000_000))
        if isinstance(v, _dt.date):
            return ir.Literal((v - _dt.date(1970, 1, 1)).days)
        raise NotDeviceCompilable(
            f"literal {v!r} does not coerce to a temporal lane")

    def _strip(x):
        while isinstance(x, ir.Alias):
            x = x.child
        return x

    def _ifunc(name: str, child: ir.Expression) -> ir.Func:
        # internal lowering-only node (__ts_days / __{year,month,day}_days):
        # built via the clone idiom because ir.Func validates public names,
        # and these never reach host eval — compile_expr consumes them
        f = object.__new__(ir.Func)
        f.name = name
        f.children = (child,)
        return f

    def rw(x: ir.Expression) -> ir.Expression:
        t = type(x)
        if t is ir.Alias:
            return rw(x.child)
        if t is ir.Column:
            return _note(x)
        if t is ir.Literal:
            v = x.value
            if isinstance(v, str):
                # a string literal outside a code compare has no device form
                raise NotDeviceCompilable(
                    f"string literal {v!r} outside a dictionary-code compare")
            if isinstance(v, _dt.datetime):
                if v.tzinfo is None:
                    v = v.replace(tzinfo=_dt.timezone.utc)
                return ir.Literal(int(v.timestamp() * 1_000_000))
            if isinstance(v, _dt.date):
                return ir.Literal((v - _dt.date(1970, 1, 1)).days)
            return x
        if t in _CMP_TYPES or t is ir.NullSafeEq:
            l, r = x.left, x.right
            if isinstance(l, ir.Literal) and not isinstance(r, ir.Literal):
                l, r = r, l
                t = _CMP_FLIP.get(t, t)
            lt_, rt_ = _ctype(l), _ctype(r)
            if isinstance(lt_, (DateType, TimestampType)) \
                    and isinstance(rt_, (DateType, TimestampType)):
                if type(lt_) is not type(rt_):
                    raise NotDeviceCompilable(
                        "mixed date/timestamp compare (lane units differ)")
                return t(rw(l), rw(r))
            if isinstance(lt_, (DateType, TimestampType)) and isinstance(r, ir.Literal):
                return t(rw(l), _temporal_lit(r, lt_))
            stringy = (isinstance(lt_, StringType) or isinstance(rt_, StringType)
                       or isinstance(getattr(_strip(l), "value", None), str)
                       or isinstance(getattr(_strip(r), "value", None), str))
            if stringy:
                col, lit = _strip(l), _strip(r)
                if t in (ir.Eq, ir.Ne, ir.NullSafeEq) \
                        and isinstance(lt_, StringType) \
                        and isinstance(col, ir.Column) \
                        and isinstance(lit, ir.Literal) \
                        and (lit.value is None or isinstance(lit.value, str)):
                    if lit.value is None:
                        return t(_note(col), ir.Literal(None))
                    ph = f"{_STRLIT_PREFIX}{len(binds)}"
                    binds.append((ph, col.name.lower(), lit.value))
                    return t(_note(col), ir.Column(ph))
                raise NotDeviceCompilable(
                    f"string comparison stays on host: {x.sql()}")
            return t(rw(l), rw(r))
        if t is ir.In:
            v = _strip(x.value)
            vt = _ctype(v)
            opts = list(x.options)
            if isinstance(vt, StringType):
                if not isinstance(v, ir.Column):
                    raise NotDeviceCompilable("string IN over a non-column")
                new_opts = []
                for o in opts:
                    o = _strip(o)
                    if not isinstance(o, ir.Literal):
                        raise NotDeviceCompilable(
                            "string IN option is not a literal")
                    if o.value is None:
                        new_opts.append(o)  # NULL option: Kleene semantics
                        continue
                    if not isinstance(o.value, str):
                        raise NotDeviceCompilable(
                            f"non-string option {o.value!r} in string IN")
                    ph = f"{_STRLIT_PREFIX}{len(binds)}"
                    binds.append((ph, v.name.lower(), o.value))
                    new_opts.append(ir.Column(ph))
                return ir.In(_note(v), new_opts)
            if isinstance(vt, (DateType, TimestampType)):
                new_opts = [o if (isinstance(_strip(o), ir.Literal)
                                  and _strip(o).value is None)
                            else _temporal_lit(_strip(o), vt)
                            if isinstance(_strip(o), ir.Literal) else rw(o)
                            for o in opts]
                return ir.In(rw(x.value), new_opts)
            return ir.In(rw(x.value), [rw(o) for o in opts])
        if t is ir.Func and x.name in ("year", "month", "day") \
                and len(x.children) == 1:
            ct = _ctype(x.children[0])
            child = rw(x.children[0])
            if isinstance(ct, TimestampType):
                child = _ifunc("__ts_days", child)
            elif not isinstance(ct, DateType):
                raise NotDeviceCompilable(
                    f"{x.name}() over a non-temporal lane")
            return _ifunc(f"__{x.name}_days", child)
        if t is ir.Func and x.name == "to_date" and len(x.children) == 1:
            ct = _ctype(x.children[0])
            if isinstance(ct, TimestampType):
                return _ifunc("__ts_days", rw(x.children[0]))
            if isinstance(ct, DateType):
                return rw(x.children[0])
            raise NotDeviceCompilable("to_date over a non-temporal lane")
        if t is ir.Func and x.name == "hour" and len(x.children) == 1:
            if not isinstance(_ctype(x.children[0]), TimestampType):
                raise NotDeviceCompilable("hour() needs a timestamp lane")
            return ir.Func("hour", [rw(x.children[0])])
        # generic rebuild (And/Or/Not/arith/null tests/Coalesce/CaseWhen/
        # Cast/other Funcs) — unsupported shapes surface from compile_expr
        new_children = tuple(rw(c) for c in x.children)
        if new_children == x.children:
            return x
        clone = object.__new__(t)
        clone.__dict__.update(x.__dict__)
        clone.children = new_children
        return clone

    out = rw(e)
    compile_expr(out)  # validate the lowering NOW — routers price after this
    return ResidualPlan(out, frozenset(refs), frozenset(part_refs),
                        tuple(binds))
