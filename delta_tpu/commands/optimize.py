"""OPTIMIZE — compaction and Z-ORDER clustering.

The reference ships no OPTIMIZE command in this version (Z-order tags exist
in the format only, `actions/actions.scala:270-291`); the rebuild provides
both modes because the perf baseline measures them:

* **compaction**: bin-pack small files per partition up to a target size and
  rewrite them as one file;
* **Z-ORDER BY (cols)**: re-sort the selected partitions by the on-device
  Morton key (`ops/zorder.py`) and re-split, giving compact per-file min/max
  boxes for data skipping.

Both commit as rearrange-only transactions (`dataChange=False`), so
concurrent appends don't conflict and streams ignore the rewrite — the same
reason `WriteIntoDelta.scala:129-131` flips dataChange for rearrangeOnly.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple, Union

import pyarrow as pa

from delta_tpu.commands import operations as ops
from delta_tpu.commands.dml_common import Timer
from delta_tpu.exec import write as write_exec
from delta_tpu.exec.scan import read_files_as_table
from delta_tpu.expr import ir
from delta_tpu.expr import partition as partition_expr
from delta_tpu.expr.parser import parse_predicate
from delta_tpu.ops.zorder import morton_order
from delta_tpu.protocol.actions import Action, AddFile
from delta_tpu.utils.errors import DeltaAnalysisError
from delta_tpu.utils import errors

__all__ = ["OptimizeCommand", "OptimizeBudgetExceeded"]

DEFAULT_MIN_FILE_SIZE = 256 * 1024 * 1024  # files below this are compactable
DEFAULT_TARGET_ROWS = 1 << 22


class OptimizeBudgetExceeded(errors.DeltaError):
    """The selected rewrite set exceeds ``max_rewrite_bytes``. Raised
    BEFORE any data is read or written — the cost-capped invocation path
    (`delta_tpu/autopilot`) turns this into a journaled SKIPPED outcome
    instead of an over-budget background rewrite."""

    def __init__(self, est_bytes: int, cap_bytes: int, files: int):
        super().__init__(
            f"OPTIMIZE would rewrite {est_bytes} bytes across {files} "
            f"files, over the {cap_bytes}-byte budget")
        self.est_bytes = est_bytes
        self.cap_bytes = cap_bytes
        self.files = files


class OptimizeCommand:
    def __init__(
        self,
        delta_log,
        predicate: Optional[Union[str, ir.Expression]] = None,
        z_order_by: Sequence[str] = (),
        min_file_size: int = DEFAULT_MIN_FILE_SIZE,
        target_rows: int = DEFAULT_TARGET_ROWS,
        purge: bool = False,
        max_rewrite_bytes: Optional[int] = None,
        workers: Optional[int] = None,
        distribute: bool = False,
        on_failure: str = "raise",
    ):
        if on_failure not in ("raise", "quarantine"):
            raise ValueError(
                f"on_failure must be 'raise' or 'quarantine', got {on_failure!r}")
        self.delta_log = delta_log
        self.predicate = (
            parse_predicate(predicate) if isinstance(predicate, str) else predicate
        )
        self.z_order_by = list(z_order_by)
        self.min_file_size = min_file_size
        self.target_rows = target_rows
        # purge mode (modern Delta's REORG TABLE ... APPLY (PURGE)): rewrite
        # exactly the files carrying deletion vectors, materializing the
        # deletes and dropping the DVs — size-based selection is bypassed
        self.purge = purge
        # cost cap (programmatic maintenance path): the total size of the
        # files selected for rewrite is bounded up front — an over-budget
        # job raises OptimizeBudgetExceeded before any IO
        self.max_rewrite_bytes = max_rewrite_bytes
        # sharded execution (parallel/executor): bin-pack groups rewrite on
        # `workers` LPT-seeded work-stealing workers (None = the
        # delta.tpu.distributed.optimize.workers conf, default 1 —
        # sequential, byte-identical to the classic loop). `distribute`
        # additionally splits the groups across jax.distributed hosts
        # (byte-weighted LPT); each host commits its disjoint rearrange-only
        # slice, funneled through the group-commit coordinator.
        self.workers = workers
        self.distribute = distribute
        # item-failure policy for the sharded executor: "raise" aborts the
        # job on the first exhausted group (classic semantics); "quarantine"
        # completes the commit WITHOUT the failed groups' rewrites — their
        # files stay exactly as planned-around, reported in shard_report
        self.on_failure = on_failure
        # the last run's executor evidence (per-worker timings, steals,
        # skew) — the sharded-scan bench and the MULTICHIP artifact read it
        self.shard_report = None
        # multihost crash evidence: this host's lease (heartbeated during
        # the rewrite, cleared after commit) and, on the coordinator, the
        # post-commit orphan-recovery context (parallel/leases.py)
        self._lease_path: Optional[str] = None
        self._recover_info: Optional[Dict] = None
        self.metrics: Dict[str, int] = {}

    def _resolve_workers(self) -> int:
        if self.workers is not None:
            return max(int(self.workers), 1)
        from delta_tpu.utils.config import conf

        got = conf.get("delta.tpu.distributed.optimize.workers")
        return max(int(got), 1) if got is not None else 1

    def run(self) -> int:
        from delta_tpu.utils.telemetry import record_operation

        with record_operation("delta.dml.optimize", path=self.delta_log.data_path):
            version = self.delta_log.with_new_transaction(self._body)
            if self._recover_info is not None:
                # coordinator fan-in: after our own slice committed, wait
                # for peer hosts' leases to clear and recover any orphans
                # (needs fresh transactions — cannot run inside _body's)
                self._recover_orphan_slices()
            return version

    def _recover_orphan_slices(self) -> int:
        """Coordinator-side orphaned-slice recovery: poll peer leases for
        this job until each clears (host committed and released) or its
        heartbeat expires past the ttl (host died). An expired lease is
        reconciled against the log by its recorded ``commitInfo.txnId`` —
        present means only the *clear* was lost; absent means the slice's
        work is re-planned from a fresh snapshot restricted to its recorded
        group keys and re-executed locally. Returns recovered slice count.

        The wait is bounded: with no peer lease in sight the coordinator
        only lingers ``delta.tpu.distributed.lease.settleMs`` (a peer that
        died before even publishing its lease lost no committed data — its
        partitions are merely left uncompacted for the next OPTIMIZE), and
        a wedged-but-heartbeating peer stops blocking fan-in after 10×ttl.
        """
        import time as _time

        from delta_tpu.parallel import leases
        from delta_tpu.utils.config import conf

        info = self._recover_info
        self._recover_info = None
        log_path = self.delta_log.log_path
        if info is None or not leases.enabled(log_path):
            return 0
        ttl_s = leases.lease_ttl_s()
        try:
            settle_s = max(float(conf.get(
                "delta.tpu.distributed.lease.settleMs", 250)), 0.0) / 1000.0
        except (TypeError, ValueError):
            settle_s = 0.25
        poll_s = max(min(ttl_s / 4.0, 0.25), 0.005)
        start = _time.monotonic()
        hard_deadline = start + max(10.0 * ttl_s, settle_s)
        recovered = 0
        own = self._lease_path
        while True:
            now = _time.time()
            all_leases = [(p, body, mtime)
                          for p, body, mtime in leases.read_leases(log_path)
                          if p != own]
            # an EXPIRED lease is an orphan whatever job wrote it — the
            # lease is self-describing (txnId + group keys + readVersion),
            # and hosts that planned across an interleaving commit carry
            # different job ids for the same fan-out. Only same-job live
            # peers gate the fan-in wait, though: another job's live lease
            # is that job's coordinator's problem.
            orphans = [(p, body) for p, body, mtime in all_leases
                       if now - mtime > ttl_s]
            live = [p for p, body, mtime in all_leases
                    if now - mtime <= ttl_s
                    and body.get("job") == info["job"]]
            seen_peer = any(body.get("job") == info["job"]
                            for _p, body, _m in all_leases)
            for path, body in orphans:
                recovered += self._recover_one_slice(path, body, info)
            if not live and (seen_peer or orphans or
                             _time.monotonic() - start >= settle_s):
                break
            if _time.monotonic() >= hard_deadline:
                break
            _time.sleep(poll_s)
        return recovered

    def _recover_one_slice(self, lease_path: str, body: Dict,
                           info: Dict) -> int:
        """Reconcile or re-execute one orphaned slice; returns 1 when its
        work had to be (and was) re-executed. Exactly-once per group:
        either the dead host's commit is found by token, or the restricted
        replan sees its partitions' current files — never both rewrites."""
        from delta_tpu.obs import journal
        from delta_tpu.parallel import leases
        from delta_tpu.utils import telemetry

        log_path = self.delta_log.log_path
        token = body.get("txnId")
        with telemetry.record_operation("delta.dist.sliceRecovery", {
            "job": str(body.get("job")), "proc": body.get("proc"),
        }) as ev:
            try:
                since = int(body.get("readVersion", info["readVersion"]))
            except (TypeError, ValueError):
                since = int(info["readVersion"])
            if token and self._txn_landed(str(token), since):
                # the host committed; only its lease clear was lost
                ev.data["outcome"] = "reconciled"
                leases.clear_lease(lease_path)
                journal.record_dist(log_path, {
                    "event": "dist.sliceReconciled",
                    "proc": body.get("proc"), "job": body.get("job"),
                })
                return 0
            keys = {tuple(tuple(kv) for kv in key)
                    for key in (body.get("groupKeys") or [])}

            def _recover_body(txn):
                groups = self._plan_groups(txn, restrict_keys=keys)
                if not groups:
                    return 0  # nothing re-plannable: no commit at all
                removes: List[Action] = []
                adds: List[Action] = []
                for _key, group in groups:
                    new_adds, new_removes = self._rewrite_group(
                        group, txn.metadata)
                    adds.extend(new_adds)
                    removes.extend(new_removes)
                op = (ops.Reorg(predicate=[]) if self.purge else
                      ops.Optimize(predicate=[],
                                   z_order_by=self.z_order_by or None))
                txn.commit(removes + adds, op)
                return len(groups)

            self.delta_log.update()  # replan from the freshest snapshot
            n_groups = self.delta_log.with_new_transaction(_recover_body)
            ev.data["outcome"] = "recovered" if n_groups else "noop"
            ev.data["groups"] = n_groups
            leases.clear_lease(lease_path)
            journal.record_dist(log_path, {
                "event": "dist.sliceRecovered",
                "proc": body.get("proc"), "job": body.get("job"),
                "groups": n_groups,
            })
            if n_groups:
                telemetry.bump_counter("dist.slice.recovered")
            return 1 if n_groups else 0

    def _txn_landed(self, token: str, since_version: int) -> bool:
        """Did a commit carrying ``commitInfo.txnId == token`` land after
        ``since_version``? Scans the log tail file-by-file — the same
        token comparison ``_reconcile_ambiguous_commit`` does for one
        version, widened to the window a dead peer could have written."""
        import json as _json

        from delta_tpu.protocol import filenames

        self.delta_log.update()
        current = self.delta_log.snapshot.version
        for v in range(since_version + 1, current + 1):
            path = f"{self.delta_log.log_path}/{filenames.delta_file(v)}"
            try:
                lines = self.delta_log.store.read(path)
            except FileNotFoundError:
                continue
            if not lines:
                continue
            try:
                got = (_json.loads(lines[0]).get("commitInfo")
                       or {}).get("txnId")
            except (ValueError, AttributeError):
                continue
            if got == token:
                return True
        return False

    def _plan_groups(self, txn, restrict_keys=None
                     ) -> List[Tuple[Tuple, List[AddFile]]]:
        """Metadata-only rewrite planning: the selected files per partition
        key, in deterministic key order. ``restrict_keys`` (a set of
        partition-key tuples) replans only those partitions — the orphan
        slice recovery path, where it makes re-execution idempotent: a
        partition the dead host already compacted yields fewer than two
        small files and drops out of the plan."""
        # filter_files evaluates the partition predicate exactly
        candidates = txn.filter_files(
            [self.predicate] if self.predicate is not None else None
        )

        by_partition: Dict[Tuple, List[AddFile]] = defaultdict(list)
        for f in candidates:
            key = tuple(sorted((f.partition_values or {}).items()))
            if restrict_keys is not None and key not in restrict_keys:
                continue
            by_partition[key].append(f)

        groups: List[Tuple[Tuple, List[AddFile]]] = []
        # None-safe ordering: null partition values sort first
        for key, files in sorted(
            by_partition.items(),
            key=lambda kv: [(c, v is not None, v or "") for c, v in kv[0]],
        ):
            if self.z_order_by:
                group = files  # Z-order rewrites every selected file
            elif self.purge:
                group = [f for f in files if f.deletion_vector is not None]
                if not group:
                    continue
            else:
                group = [f for f in files if (f.size or 0) < self.min_file_size]
                if len(group) < 2:
                    continue  # nothing to compact
            groups.append((key, group))
        return groups

    def _rewrite_group(self, group: List[AddFile], metadata):
        """Read, (optionally) re-sort, and rewrite one bin-packed group;
        returns ``(new_adds, removes)``. Runs on executor worker threads —
        each call heartbeats this host's lease so the coordinator sees the
        slice as live for as long as it is making progress."""
        from delta_tpu.parallel import leases

        leases.heartbeat_lease(self._lease_path)
        table = read_files_as_table(
            self.delta_log.data_path, group, metadata
        )
        if self.z_order_by:
            cols = [
                np_col(table, c) for c in self.z_order_by
            ]
            perm = morton_order(cols)
            table = table.take(pa.array(perm))
        new_adds = write_exec.write_files(
            self.delta_log.data_path,
            table,
            metadata,
            data_change=False,
            target_file_rows=self.target_rows,
        )
        return new_adds, [f.remove(data_change=False) for f in group]

    def _body(self, txn) -> int:
        metadata = txn.metadata
        pcols = metadata.partition_columns
        if self.predicate is not None:
            conjuncts = ir.split_conjuncts(self.predicate)
            if not all(partition_expr.is_partition_predicate(c, pcols) for c in conjuncts):
                raise DeltaAnalysisError(
                    "OPTIMIZE predicate must reference only partition columns"
                )
        for c in self.z_order_by:
            names = [f.name.lower() for f in metadata.schema.fields]
            if c.lower() not in names:
                raise errors.zorder_column_not_in_schema(c)
            if c.lower() in [p.lower() for p in pcols]:
                raise errors.zorder_on_partition_column(c)

        timer = Timer()
        # plan first (selection is metadata-only), so the cost cap can
        # abort an over-budget job before ANY file is read or written
        groups = self._plan_groups(txn)
        if self.max_rewrite_bytes is not None:
            est = sum(f.size or 0 for _, g in groups for f in g)
            if est > self.max_rewrite_bytes:
                raise OptimizeBudgetExceeded(
                    est, self.max_rewrite_bytes,
                    sum(len(g) for _, g in groups))

        # multi-host mode: every host plans the SAME group list from the
        # same snapshot, then takes its disjoint byte-weighted LPT slice —
        # deterministic, no scheduler RPC. Each host commits only its own
        # rearranged files, so the per-host transactions are disjoint
        # rearrange-only commits that cannot conflict.
        fan_in = False
        slice_info = None
        if self.distribute:
            from delta_tpu.parallel.distributed import (
                host_shard_indices, process_info)

            proc, n_procs = process_info()
            if n_procs > 1:
                gsizes = [sum(f.size or 0 for f in g) for _k, g in groups]
                mine = host_shard_indices(
                    len(groups), proc, n_procs, sizes=gsizes)
                groups = [groups[i] for i in mine]
                # this host's slice of the groups, as a span: the stitched
                # trace shows one delta.dist.hostSlice lane per process
                slice_info = {
                    "proc": proc, "nProcs": n_procs, "groups": len(groups),
                    "sliceBytes": sum(
                        f.size or 0 for _k, g in groups for f in g),
                }
                # narrow the recorded read set to THIS host's slice: the
                # commit's validity depends only on its own files surviving
                # (the reference's OPTIMIZE pins its read files the same
                # way), so a peer host's rearrange-only removes must not
                # fail us with a delete-read conflict
                keep = {f.path for _k, g in groups for f in g}
                for p in [p for p in txn.read_files if p not in keep]:
                    del txn.read_files[p]
                from delta_tpu.utils.config import conf

                fan_in = conf.get_bool(
                    "delta.tpu.distributed.singleWriterFanIn", True)

                # publish this host's lease BEFORE executing: the slice id,
                # its bin-packed group keys, and the txnId its commit will
                # carry — everything the coordinator needs to reconcile or
                # re-execute the slice if this host dies past this point
                from delta_tpu.parallel import leases

                job_id = f"optimize@{txn.read_version}"
                token = leases.new_token()
                txn.preset_txn_id = token
                self._lease_path = leases.write_lease(
                    self.delta_log.log_path, job_id, proc, {
                        "txnId": token,
                        "nProcs": n_procs,
                        "readVersion": txn.read_version,
                        "groupKeys": [[list(kv) for kv in key]
                                      for key, _g in groups],
                    })
                if proc == 0:
                    # the coordinator owns post-commit orphan recovery
                    # (run() — it needs its own transaction)
                    self._recover_info = {
                        "job": job_id, "proc": proc,
                        "readVersion": txn.read_version,
                    }

        removes: List[Action] = []
        adds: List[Action] = []
        rewritten_bytes = 0
        quarantined_groups = 0

        if groups:
            import contextlib

            from delta_tpu.parallel.executor import run_sharded
            from delta_tpu.utils import telemetry

            telemetry.bump_counter("dist.optimize.groups", len(groups))
            slice_span = (
                telemetry.record_operation("delta.dist.hostSlice", slice_info)
                if slice_info is not None else contextlib.nullcontext())
            with slice_span:
                report = run_sharded(
                    [g for _k, g in groups],
                    lambda g: self._rewrite_group(g, metadata),
                    sizes=[sum(f.size or 0 for f in g) for _k, g in groups],
                    workers=self._resolve_workers(),
                    label="optimize",
                    on_failure=self.on_failure,
                )
            self.shard_report = report
            # results are index-ordered, so adds/removes land in the exact
            # order the classic sequential loop produced them; a quarantined
            # group's slot is None — its files are simply not rewritten
            # this run (left exactly as planned-around, reported below)
            for (_key, group), pair in zip(groups, report.results):
                if pair is None:
                    quarantined_groups += 1
                    continue
                new_adds, new_removes = pair
                adds.extend(new_adds)
                removes.extend(new_removes)
                rewritten_bytes += sum(f.size or 0 for f in group)
            if report.quarantined:
                from delta_tpu.obs import journal

                journal.record_dist(self.delta_log.log_path, {
                    "event": "dist.quarantine", "op": "optimize",
                    "items": [q.to_dict() for q in report.quarantined],
                })

        self.metrics.update(
            numRemovedFiles=len(removes),
            numAddedFiles=len(adds),
            numRemovedBytes=rewritten_bytes,
            numAddedBytes=sum(a.size or 0 for a in adds
                              if isinstance(a, AddFile)),
            numQuarantinedGroups=quarantined_groups,
            timeMs=timer.lap_ms(),
        )
        txn.report_metrics(**self.metrics)
        pred_sql = [self.predicate.sql()] if self.predicate is not None else []
        if self.purge:
            op = ops.Reorg(predicate=pred_sql)
        else:
            op = ops.Optimize(
                predicate=pred_sql, z_order_by=self.z_order_by or None,
            )
        if fan_in:
            # single-writer fan-in: every host's commit funnels through the
            # group-commit coordinator (PR 9), so the log sees one ordered
            # writer instead of n_procs racing _do_commit_retry loops
            from delta_tpu.utils.config import conf
            from delta_tpu.utils import telemetry

            telemetry.bump_counter("dist.commit.fanin")
            with telemetry.record_operation(
                "delta.dist.commit.fanIn",
                {"adds": len(adds), "removes": len(removes)},
            ):
                with conf.set_temporarily(
                    **{"delta.tpu.commit.group.enabled": True}
                ):
                    version = txn.commit(removes + adds, op)
        else:
            version = txn.commit(removes + adds, op)
        # commit is durable: release this host's lease — a crash between
        # the commit and here leaves an orphan whose txnId reconciles to
        # already-committed (cleanup, not re-execution)
        if self._lease_path is not None:
            from delta_tpu.parallel import leases

            leases.clear_lease(self._lease_path)
            self._lease_path = None
        # file rewrite: bump the resident key-cache epoch so a stale HBM
        # slab can never serve a post-OPTIMIZE MERGE (ops/key_cache.py)
        if removes or adds:
            from delta_tpu.ops.column_cache import ColumnCache
            from delta_tpu.ops.key_cache import KeyCache

            KeyCache.instance().bump_epoch(self.delta_log.log_path)
            ColumnCache.instance().bump_epoch(self.delta_log.log_path)
        # feed the table-health doctor: maintenance recency as gauges, work
        # done as counters (obs/metric_names.py catalog)
        from delta_tpu.utils import telemetry

        telemetry.set_gauge("table.maintenance.lastOptimizeVersion", version,
                            path=self.delta_log.data_path)
        if removes:
            telemetry.bump_counter("maintenance.optimize.filesCompacted",
                                   len(removes))
        if adds:
            telemetry.bump_counter("maintenance.optimize.filesWritten",
                                   len(adds))
        return version


def np_col(table: pa.Table, name: str):
    """Column as numpy for ranking; NULLs substitute the column minimum so
    rank_u16's argsort stays total (NULLs cluster with the smallest value)."""
    import pyarrow.compute as pc

    col = None
    for c in table.column_names:
        if c.lower() == name.lower():
            col = table.column(c)
            break
    if col.null_count == len(col):
        # all-null: every rank is equal, contribute a constant dimension
        import numpy as np

        return np.zeros(len(col), np.int64)
    if col.null_count:
        col = pc.fill_null(col, pc.min(col))
    return col.to_numpy(zero_copy_only=False)
