"""Self-calibrating cost model (ISSUE 7 tentpole): the router audit ledger
(predicted vs actual per routed decision), the EWMA calibrator feeding the
`parallel/link.py` constants (persisted state round-trip), the device-memory
ledger + doctor pressure dimension, cross-thread trace propagation of the
staged MERGE pipeline, and the blackout guarantee over all of it.
"""
import json

import numpy as np
import pyarrow as pa
import pytest

from delta_tpu import DeltaLog
from delta_tpu.commands.merge import MergeClause, MergeIntoCommand
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.obs import calibration, hbm_ledger, router_audit
from delta_tpu.ops.key_cache import KeyCache
from delta_tpu.parallel import link
from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf

UP = MergeClause("update", assignments=None)
INS = MergeClause("insert", assignments=None)
ALIAS = dict(source_alias="s", target_alias="t")


@pytest.fixture(autouse=True)
def _fresh_state():
    import gc

    def fresh():
        telemetry.reset_all()
        router_audit.clear_audits()
        calibration.reset()
        KeyCache.reset()
        # run dropped entries' hbm finalizers NOW, then zero the ledger, so
        # stale finalizers can't fire mid-test and skew equality asserts
        gc.collect()
        hbm_ledger.reset()

    fresh()
    yield
    fresh()


def _seed(path, files=2, per=50):
    log = DeltaLog.for_table(str(path))
    rng = np.random.RandomState(5)
    for i in range(files):
        keys = np.arange(i * per, (i + 1) * per, dtype=np.int64)
        WriteIntoDelta(log, "append", pa.table({
            "k": pa.array(keys),
            "v": pa.array(rng.rand(per)),
        })).run()
    return log


def _source(n=30, hit_lo=10):
    rng = np.random.RandomState(9)
    keys = np.concatenate([
        np.arange(hit_lo, hit_lo + n // 2, dtype=np.int64),
        np.arange(10_000, 10_000 + n - n // 2, dtype=np.int64),
    ])
    return pa.table({"k": pa.array(keys), "v": pa.array(rng.rand(len(keys)))})


def _merge(log, mode, source=None):
    with conf.set_temporarily(**{
        "delta.tpu.merge.devicePath.mode": mode,
        "delta.tpu.deletionVectors.enabled": True,
        "delta.tpu.merge.keyCache.enabled": mode != "off",
    }):
        cmd = MergeIntoCommand(log, source if source is not None
                               else _source(), "t.k = s.k", [UP], [INS],
                               **ALIAS)
        cmd.run()
    return cmd


# -- ledger unit behavior ----------------------------------------------------


def test_record_audit_miss_logic_and_stats():
    a = router_audit.record_audit(
        "merge.join", "/t", "host", {"host": 0.010, "device": 0.002}, 0.005,
        units={"targetRows": 10},
    )
    assert a is not None and a.miss  # device predicted 2ms, host ran 5ms
    b = router_audit.record_audit(
        "merge.join", "/t", "host", {"host": 0.010, "device": 0.050}, 0.005,
    )
    assert not b.miss
    stats = router_audit.audit_stats()
    assert stats == {"audits": 2, "misses": 1, "missRate": 0.5}
    g = telemetry.gauges("router.missRate")
    assert g[("router.missRate", ())] == 0.5
    assert telemetry.counters("router.audits") == {"router.audits": 2}
    assert telemetry.counters("router.misses") == {"router.misses": 1}
    recent = router_audit.recent_audits()
    assert [r["miss"] for r in recent] == [True, False]
    json.dumps(recent)
    # predicted/actual histograms populated under catalog-registered names
    h = telemetry.histograms("router.predicted_ms")
    assert sum(v.count for v in h.values()) == 2
    h = telemetry.histograms("router.actual_ms")
    assert sum(v.count for v in h.values()) == 2


def test_record_audit_no_alternative_never_misses():
    a = router_audit.record_audit(
        "merge.join", "/t", "host", {"host": 0.001}, 99.0)
    assert a is not None and not a.miss


def test_audit_ring_bounded_by_conf():
    with conf.set_temporarily(**{"delta.tpu.router.auditKeep": 4}):
        for i in range(10):
            router_audit.record_audit("merge.join", "/t", "host",
                                      {"host": 1.0}, 0.5, seq=i)
        recent = router_audit.recent_audits(limit=100)
    assert len(recent) == 4
    assert [r["extra"]["seq"] for r in recent] == [6, 7, 8, 9]
    assert router_audit.audit_stats()["audits"] == 10  # counts keep totals


# -- merge audits: predicted vs actual on both forced routes -----------------


def test_host_forced_merge_produces_populated_audit(tmp_path):
    log = _seed(tmp_path / "thost")
    cmd = _merge(log, "off")
    assert cmd._join_path == "host"
    [rec] = [r for r in router_audit.recent_audits() if r["op"] == "merge.join"]
    assert rec["decision"] == "host"
    assert rec["predictedMs"]["host"] > 0
    assert rec["actualMs"] > 0
    assert rec["units"]["targetRows"] == 100
    assert rec["units"]["sourceRows"] == 30
    assert "join_ms" in rec["extra"]["phases"]
    # host-only (device structurally off): no hindsight miss possible
    assert "device" not in rec["predictedMs"] or rec["predictedMs"]["device"] > 0


def test_device_forced_merge_produces_populated_audit(tmp_path):
    log = _seed(tmp_path / "tdev")
    cmd = _merge(log, "force")
    assert cmd._device_join is not None
    assert cmd._join_path in ("device-cold", "resident")
    [rec] = [r for r in router_audit.recent_audits() if r["op"] == "merge.join"]
    assert rec["decision"] == cmd._join_path
    assert rec["predictedMs"]["host"] > 0
    assert rec["predictedMs"][cmd._join_path] > 0
    assert rec["actualMs"] > 0
    h = telemetry.histograms("router.actual_ms")
    assert sum(v.count for v in h.values()) == 1


def test_scan_plan_batch_produces_audit(tmp_path):
    from delta_tpu.exec.scan import plan_scans

    log = _seed(tmp_path / "tplan", files=3)
    snap = log.update()
    with conf.set_temporarily(**{
        "delta.tpu.link.uploadMBps": 100, "delta.tpu.link.downloadMBps": 100,
    }):
        # AUTO mode (the default): the router made a priceable decision
        plans = plan_scans(snap, [["k >= 0 AND k <= 10"]], k=16)
    assert plans[0].count >= 1
    recs = [r for r in router_audit.recent_audits() if r["op"] == "scan.plan"]
    assert recs, "scan planning must audit its device/host pick"
    assert recs[-1]["decision"] in ("device", "host-resident")
    assert set(recs[-1]["predictedMs"]) == {"device", "host-resident"}
    assert recs[-1]["units"]["cells"] > 0
    # pinned modes made no priceable decision: no audit, no link probe
    router_audit.clear_audits()
    with conf.set_temporarily(**{
        "delta.tpu.stateCache.devicePlan.mode": "off",
    }):
        plan_scans(snap, [["k >= 0 AND k <= 10"]], k=16)
    assert [r for r in router_audit.recent_audits()
            if r["op"] == "scan.plan"] == []


# -- calibration: synthetic convergence + persistence ------------------------


def test_calibrator_ewma_converges_from_synthetic_samples(tmp_path):
    state_file = str(tmp_path / "cal.json")
    default = link.HOST_JOIN_S_PER_ROW
    target_rate = default * 10  # this hardware is 10x slower than the bench
    with conf.set_temporarily(**{
        "delta.tpu.router.calibration.enabled": True,
        "delta.tpu.router.calibration.statePath": state_file,
        "delta.tpu.router.calibration.alpha": 0.5,
        "delta.tpu.router.calibration.minSamples": 3,
    }):
        # below minSamples: no override installed yet
        for _ in range(2):
            calibration.ingest([("HOST_JOIN_S_PER_ROW", 1_000_000,
                                 target_rate * 1_000_000)])
        assert link.calibrated_constants() == {}
        assert link.constant("HOST_JOIN_S_PER_ROW") == default
        for _ in range(8):
            calibration.ingest([("HOST_JOIN_S_PER_ROW", 1_000_000,
                                 target_rate * 1_000_000)])
        got = link.constant("HOST_JOIN_S_PER_ROW")
        # EWMA over identical samples converges onto the sample rate
        assert got == pytest.approx(target_rate, rel=0.01)
        assert telemetry.counters("router.calibration.updates")[
            "router.calibration.updates"] == 10
        # gauge published under the catalog name, labeled by constant
        g = telemetry.gauges("router.calibration")
        assert g[("router.calibration",
                  (("constant", "HOST_JOIN_S_PER_ROW"),))] == got

        # state file round-trips into a fresh process (reset = fresh state)
        calibration.reset()
        assert link.constant("HOST_JOIN_S_PER_ROW") == default
        state = calibration.apply_state()
        assert state["HOST_JOIN_S_PER_ROW"]["samples"] == 10
        assert link.constant("HOST_JOIN_S_PER_ROW") == pytest.approx(
            target_rate, rel=0.01)


def test_calibrator_rejects_garbage_samples(tmp_path):
    with conf.set_temporarily(**{
        "delta.tpu.router.calibration.enabled": True,
        "delta.tpu.router.calibration.statePath": str(tmp_path / "c.json"),
    }):
        assert calibration.ingest([("NOT_A_CONSTANT", 10, 1.0)]) is None
        assert calibration.ingest([("HOST_JOIN_S_PER_ROW", 0, 1.0)]) is None
        assert calibration.ingest([("HOST_JOIN_S_PER_ROW", 10, -1.0)]) is None
    assert link.calibrated_constants() == {}


def test_calibration_hot_path_flush_is_throttled(tmp_path):
    """flush=False (the per-query scan-planner path) defers the state-file
    write to the flush interval; merge-path ingests and apply_state flush
    deferred state, so nothing is ever lost across a routed merge."""
    state_file = str(tmp_path / "hot.json")
    key = "HOST_PRUNE_S_PER_CELL"
    with conf.set_temporarily(**{
        "delta.tpu.router.calibration.enabled": True,
        "delta.tpu.router.calibration.statePath": state_file,
        "delta.tpu.router.calibration.flushIntervalMs": 60_000,
    }):
        # first hot-path ingest persists (nothing saved yet this process)
        calibration.ingest([(key, 100, 1.0)], flush=False)
        assert calibration.load_state(state_file)[key]["samples"] == 1
        # within the interval: deferred — file unchanged, memory advances
        for _ in range(5):
            calibration.ingest([(key, 100, 1.0)], flush=False)
        assert calibration.load_state(state_file)[key]["samples"] == 1
        assert calibration.current_state()[key]["samples"] == 6
        # a flushing ingest (the merge path) writes the deferred state
        calibration.ingest([(key, 100, 1.0)])
        assert calibration.load_state(state_file)[key]["samples"] == 7
        # apply_state (merge start) also flushes dirty deferred state
        calibration.ingest([(key, 100, 1.0)], flush=False)
        assert calibration.load_state(state_file)[key]["samples"] == 7
        calibration.apply_state()
        assert calibration.load_state(state_file)[key]["samples"] == 8


def test_calibration_disabled_is_inert(tmp_path):
    state_file = tmp_path / "never.json"
    with conf.set_temporarily(**{
        "delta.tpu.router.calibration.statePath": str(state_file),
    }):
        assert calibration.ingest(
            [("HOST_JOIN_S_PER_ROW", 100, 1.0)]) is None
    assert not state_file.exists()
    assert link.calibrated_constants() == {}


def test_host_merge_calibrates_and_round_trips_across_fresh_deltalog(tmp_path):
    """Acceptance: with calibration enabled, a real MERGE's measured samples
    move a link constant, the state persists under the table's log dir, and
    a FRESH DeltaLog (new process simulation: caches cleared, calibration
    state reset) re-applies it before routing."""
    log = _seed(tmp_path / "tcal")
    default = link.HOST_JOIN_S_PER_ROW
    with conf.set_temporarily(**{
        "delta.tpu.router.calibration.enabled": True,
        "delta.tpu.router.calibration.minSamples": 1,
    }):
        _merge(log, "off")
        moved = link.calibrated_constants()
        assert "HOST_JOIN_S_PER_ROW" in moved
        assert moved["HOST_JOIN_S_PER_ROW"] != default
        state_file = calibration.state_path(log.log_path)
        assert state_file is not None
        persisted = calibration.load_state(state_file)
        assert persisted["HOST_JOIN_S_PER_ROW"]["value"] == pytest.approx(
            moved["HOST_JOIN_S_PER_ROW"])

        # fresh process: no in-memory state, no installed overrides
        calibration.reset()
        DeltaLog.clear_cache()
        assert link.calibrated_constants() == {}
        fresh = DeltaLog.for_table(str(tmp_path / "tcal"))
        _merge(fresh, "off", source=_source(20))
        # the merge loaded the persisted state before routing
        assert "HOST_JOIN_S_PER_ROW" in link.calibrated_constants()


# -- cross-thread trace propagation (acceptance) -----------------------------


def test_cold_device_merge_trace_has_no_orphan_worker_spans(tmp_path):
    """export_chrome_trace of a cold fused MERGE shows decode, upload, and
    probe spans parented (transitively) under `delta.dml.merge`, on thread
    lanes other than the command's, with zero orphan roots from pooled
    workers."""
    log = _seed(tmp_path / "ttrace", files=3)
    telemetry.reset_all()
    cmd = _merge(log, "force")
    assert cmd._join_path == "device-cold"
    trace = telemetry.export_chrome_trace()
    rows = [r for r in trace["traceEvents"] if r.get("ph") == "X"]
    by_id = {r["args"]["spanId"]: r for r in rows if "spanId" in r["args"]}
    [merge_row] = [r for r in rows if r["name"] == "delta.dml.merge"]

    def under_merge(row):
        seen = set()
        while True:
            pid = row["args"].get("parentId")
            if pid is None or pid in seen or pid not in by_id:
                return False
            if pid == merge_row["args"]["spanId"]:
                return True
            seen.add(pid)
            row = by_id[pid]

    for name in ("delta.scan.decode", "delta.merge.slabUpload",
                 "delta.merge.deviceProbe"):
        spans = [r for r in rows if r["name"] == name]
        assert spans, f"{name} spans missing from the cold-merge trace"
        assert all(under_merge(r) for r in spans), f"{name} span orphaned"
    # decode + upload + probe ran on worker lanes, not the command thread
    worker_tids = {r["tid"] for r in rows
                   if r["name"] in ("delta.scan.decode",
                                    "delta.merge.slabUpload",
                                    "delta.merge.deviceProbe")}
    assert worker_tids - {merge_row["tid"]}, "no worker thread lanes in trace"
    # zero orphan roots from pooled workers: every span on a non-command
    # thread has a parent chain
    for r in rows:
        if r["tid"] != merge_row["tid"] and "spanId" in r["args"]:
            assert r["args"].get("parentId") is not None, (
                f"orphan worker span {r['name']}")


# -- device-memory ledger + doctor pressure ----------------------------------


def test_hbm_ledger_tracks_key_cache_residency(tmp_path):
    hbm_ledger.reset()
    log = _seed(tmp_path / "thbm")
    cmd = _merge(log, "force")  # cold slab pipeline registers in KeyCache
    assert cmd._device_join is not None
    t = hbm_ledger.totals()
    assert t["keyCache"] > 0
    g = telemetry.gauges("device.hbm.keyCacheBytes")
    assert g[("device.hbm.keyCacheBytes", ())] == t["keyCache"]
    # scratch is transient: released once the probe thread finished
    assert t["scratch"] == 0
    # dropping the entries returns every byte
    KeyCache.instance().bump_epoch(log.log_path)
    assert hbm_ledger.totals()["keyCache"] == 0


def test_hbm_ledger_tracks_state_cache(tmp_path):
    from delta_tpu.ops.state_cache import DeviceStateCache

    hbm_ledger.reset()
    DeviceStateCache.reset()
    log = _seed(tmp_path / "tsc")
    entry = DeviceStateCache.instance().get(log.update())
    assert entry is not None
    entry.ensure_resident()
    assert hbm_ledger.totals()["stateCache"] == entry.device_bytes
    entry.drop_device()
    assert hbm_ledger.totals()["stateCache"] == 0
    DeviceStateCache.reset()


def test_doctor_device_dimension_reports_pressure(tmp_path):
    from delta_tpu.obs.doctor import doctor

    hbm_ledger.reset()
    log = _seed(tmp_path / "tdoc")
    dim = doctor(log).dimension("device")
    assert dim.severity == "ok"  # no budget set
    hbm_ledger.adjust("keyCache", 900)
    with conf.set_temporarily(**{"delta.tpu.device.hbmBudgetBytes": 1000}):
        dim = doctor(log).dimension("device")
        assert dim.severity == "warn" and dim.remedy == "EVICT"
        assert dim.metrics["pressure"] == 0.9
        hbm_ledger.adjust("scratch", 200)
        dim = doctor(log).dimension("device")
        assert dim.severity == "critical" and dim.remedy == "EVICT"
    g = telemetry.gauges("table.health.device.pressure")
    assert g, "doctor must publish the device pressure gauge"
    hbm_ledger.reset()


# -- /router HTTP route + /metrics exposition --------------------------------


def test_router_route_and_metrics_exposition(tmp_path):
    import http.client

    from delta_tpu.obs.server import ObsServer

    log = _seed(tmp_path / "tsrv")
    _merge(log, "off")
    srv = ObsServer(port=0)
    try:
        host, port = srv.address

        def get(path):
            c = http.client.HTTPConnection(host, port, timeout=10)
            c.request("GET", path)
            r = c.getresponse()
            body = r.read().decode()
            c.close()
            return r.status, body

        status, body = get("/router?limit=8")
        assert status == 200
        payload = json.loads(body)
        assert payload["stats"]["audits"] >= 1
        assert payload["audits"][-1]["op"] == "merge.join"
        assert "calibration" in payload
        status, text = get("/metrics")
        assert status == 200
        assert "router_missRate" in text
        assert "router_actual_ms" in text
        # the doctor's device gauges flow into the same exposition
        from delta_tpu.obs.doctor import doctor

        doctor(log)
        _, text = get("/metrics")
        assert "table_health_device_hbmBytes" in text
    finally:
        srv.stop()


def test_bench_snapshot_carries_router_and_hbm_gauges(tmp_path):
    log = _seed(tmp_path / "tsnap")
    _merge(log, "off")
    snap = telemetry.bench_snapshot(include=("router", "device.hbm"))
    assert "router.audits" in snap["counters"]
    assert any(k.startswith("router.missRate") for k in snap["gauges"])
    assert any(k.startswith("router.actual_ms")
               for k in snap["histograms"])


# -- blackout: zero overhead end to end --------------------------------------


def test_blackout_no_audits_no_calibration_no_hbm_gauges(tmp_path):
    state_file = tmp_path / "dark.json"
    hbm_ledger.reset()
    with conf.set_temporarily(**{
        "delta.tpu.telemetry.enabled": False,
        "delta.tpu.router.calibration.enabled": True,
        "delta.tpu.router.calibration.statePath": str(state_file),
    }):
        log = _seed(tmp_path / "tdark")
        _merge(log, "off")
        assert router_audit.recent_audits() == []
        assert router_audit.audit_stats()["audits"] == 0
        assert not state_file.exists()
        assert link.calibrated_constants() == {}
        assert telemetry.gauges("router") == {}
        assert telemetry.gauges("device.hbm") == {}
        assert telemetry.histograms("router") == {}
