"""DESCRIBE DETAIL / DESCRIBE HISTORY.

Mirrors `commands/DescribeDeltaDetailsCommand.scala` (one detail row with
format/id/location/times/partitioning/counts/properties/protocol) and
`commands/DescribeDeltaHistoryCommand.scala` (CommitInfo rows, newest
first, via the history manager).
"""
from __future__ import annotations

import datetime as _dt
from typing import Any, Dict, List, Optional

__all__ = ["describe_detail", "describe_history"]


def describe_detail(delta_log) -> Dict[str, Any]:
    from delta_tpu.utils.telemetry import record_operation

    with record_operation("delta.utility.describeDetail",
                          path=delta_log.data_path):
        return _describe_detail_impl(delta_log)


def _describe_detail_impl(delta_log) -> Dict[str, Any]:
    snapshot = delta_log.update()
    meta = snapshot.metadata
    created = meta.created_time
    out = {
        "format": "delta",
        "id": meta.id,
        "name": meta.name,
        "description": meta.description,
        "location": delta_log.data_path,
        "createdAt": _ts(created),
        "lastModified": _ts(snapshot.timestamp),
        "partitionColumns": list(meta.partition_columns),
        "numFiles": snapshot.num_of_files,
        "sizeInBytes": snapshot.size_in_bytes,
        "properties": dict(meta.configuration or {}),
        "minReaderVersion": snapshot.protocol.min_reader_version,
        "minWriterVersion": snapshot.protocol.min_writer_version,
    }
    # health columns (beyond the reference's DESCRIBE DETAIL): the doctor's
    # per-dimension verdicts inline, so one detail row answers "is this
    # table in debt" without a second call. Gauges stay untouched — a
    # read-only metadata query must not restamp the table.health.* series
    # an operator dashboard scrapes.
    from delta_tpu.obs.doctor import doctor

    report = doctor(delta_log, snapshot=snapshot, publish_gauges=False)
    out.update({
        "healthSeverity": report.severity,
        "healthRemedies": report.remedies(),
        "health": {d.name: d.severity for d in report.dimensions},
        "numCommitsSinceCheckpoint":
            report.dimension("checkpoint").metrics["commitsSince"],
        "numSmallFiles": report.dimension("smallFiles").metrics["count"],
        "numDeletionVectorFiles": report.dimension("dv").metrics["files"],
        "numDeletedRows": report.dimension("dv").metrics["deletedRows"],
        "statsCoveragePct": report.dimension("stats").metrics["coveragePct"],
        "numTombstones": report.dimension("tombstones").metrics["count"],
    })
    return out


def describe_history(delta_log, limit: Optional[int] = None) -> List[Dict[str, Any]]:
    from delta_tpu.utils.telemetry import record_operation

    with record_operation("delta.utility.describeHistory",
                          path=delta_log.data_path):
        commits = delta_log.history.get_history(limit)
        out = []
        for ci in commits:
            d = ci.to_dict()
            out.append(d)
        return out


def _ts(ms: Optional[int]):
    if ms is None:
        return None
    return _dt.datetime.fromtimestamp(ms / 1000, _dt.timezone.utc)
