"""Scan executor: snapshot + predicate → Arrow table.

The read side of the engine, replacing Spark's `FileSourceScanExec` over the
`TahoeFileIndex` (`files/TahoeFileIndex.scala:58-81`, SURVEY §3.2): prune the
file list on device (`ops/pruning.files_for_scan` — partition + min/max
skipping), decode the surviving Parquet with Arrow, materialize partition
columns from `partitionValues` (data files don't store them), and apply the
residual predicate with the vectorized evaluator.
"""
from __future__ import annotations

import os
import urllib.parse
from typing import Optional, Sequence, Union

import pyarrow as pa

from delta_tpu.expr import ir
from delta_tpu.expr.parser import parse_predicate
from delta_tpu.expr.partition import typed_partition_row
from delta_tpu.expr.vectorized import arrow_type_for, filter_table
from delta_tpu.ops import pruning
from delta_tpu.protocol.actions import AddFile
from delta_tpu.schema.types import StructType
from delta_tpu.utils.config import conf

__all__ = ["scan_files", "read_files_as_table", "scan_to_table", "plan_scans", "QueryPlan"]


def _abs_data_path(data_path: str, file_path: str) -> str:
    if "://" in file_path or os.path.isabs(file_path):
        return urllib.parse.unquote(file_path)
    return os.path.join(data_path, urllib.parse.unquote(file_path).replace("/", os.sep))


def read_files_as_table(
    data_path: str,
    files: Sequence[AddFile],
    metadata,
    columns: Optional[Sequence[str]] = None,
    per_file: bool = False,
    position_column: Optional[str] = None,
    distribute: bool = False,
    predicate=None,
    positions_of_interest: Optional[Sequence] = None,
    late_materialize: bool = True,
    file_ready=None,
    device_masks=None,
):
    """Decode AddFiles to one Arrow table, materializing partition columns.

    Files decode in parallel on a thread pool (Arrow's Parquet reader drops
    the GIL) — the host fan-out the reference gets from Spark executors
    (`files/TahoeFileIndex.scala:58-81`). ``per_file=True`` returns the list
    of per-file tables (same order as ``files``) instead of one concat.
    ``distribute=True`` restricts the decode to THIS host's deterministic
    slice of the file list (`parallel/distributed.host_partition`) — the
    multi-host scan shape where each process consumes its partition; on a
    single host it is the identity.

    ``predicate`` (an `expr/ir` expression) turns on the second pruning
    tier (`exec/rowgroups`): row groups whose footer stats definitely
    cannot match skip decode entirely, and of the survivors, predicate
    columns decode FIRST — remaining projected columns decode only for
    row groups with at least one possibly-matching row (late
    materialization). Rows within surviving row groups are NOT filtered;
    callers apply the residual predicate exactly as before, so the result
    is identical to a full decode. Callers must only pass ``predicate``
    when rows outside it are never needed (scans re-filter; DML may pass
    it only when it doesn't rewrite untouched rows — deletion-vector
    mode). ``positions_of_interest`` (per-file physical row positions,
    aligned with ``files``; entries may be None) additionally restricts
    decode to row groups containing those positions — the CDF DV-diff
    shape. Both are gated by ``delta.tpu.read.rowGroupSkipping``.

    Rows marked in a file's deletion vector are dropped. When
    ``position_column`` is given, each row carries its PHYSICAL position in
    the file as written (int64) — DML needs physical positions to extend a
    file's deletion vector; positions stay physical under row-group
    skipping (offset by the row counts of skipped groups).

    ``file_ready(index, add, table)`` is invoked from the decode pool as
    each file's table completes (decode-completion order, not list order) —
    the hook the MERGE fused pipeline uses to stream key lanes onto the
    device while the remaining files still decode. The callback must not
    raise; an exception from it fails the whole read.

    ``device_masks`` ({add.path → bool ndarray over the file's physical
    rows}, from `ops/column_cache.device_residual_masks`) switches masked
    files to the device residual path: row groups whose mask slice is
    all-False skip decode, surviving groups decode in one read with NO
    host predicate evaluation, and — unlike the contract above — rows
    within surviving groups ARE filtered to the mask. Only callers that
    re-apply the residual over the result may pass it (``scan_to_table``
    does); a file whose mask doesn't line up with its footer falls back to
    the host path.
    """
    from delta_tpu.utils import telemetry

    if distribute:
        if positions_of_interest is not None:
            raise ValueError(
                "positions_of_interest cannot be combined with distribute"
            )
        from delta_tpu.parallel.distributed import host_partition

        # byte-weighted LPT: the strided count-based split hands one host
        # the hot shard's bytes on a zipf-skewed file list; sizes are on
        # every AddFile, so the balanced assignment is free and RPC-less
        files = list(files)
        files = host_partition(files, sizes=[f.size or 0 for f in files])
    total_bytes = sum(f.size or 0 for f in files)
    telemetry.bump_counter("scan.files.read", len(files))
    telemetry.bump_counter("scan.bytes.read", total_bytes)
    from delta_tpu.obs import scan_report as scan_report_mod

    scan_report_mod.contribute(bytes_read=total_bytes)
    schema: StructType = metadata.schema
    part_cols = list(metadata.partition_columns)
    part_schema = metadata.partition_schema
    out_names = columns if columns is not None else [f.name for f in schema.fields]
    data_cols = [c for c in out_names if c not in part_cols]

    arrow_fields = [
        pa.field(f.name, arrow_type_for(f.data_type), f.nullable)
        for f in schema.fields
        if f.name in out_names
    ]
    empty = pa.schema(arrow_fields).empty_table()
    if not files:
        return [] if per_file else empty

    import pyarrow.parquet as pq

    rg_skipping = conf.get_bool("delta.tpu.read.rowGroupSkipping", True)
    pred_refs = (
        frozenset(r.lower() for r in ir.references(predicate))
        if predicate is not None
        else frozenset()
    )
    if predicate is not None:
        from delta_tpu.expr.synthesis import schema_types

        # arms predicate synthesis in the row-group planner (the shared
        # skipping rewrite needs declared column types to gate its rules)
        pred_types = schema_types(metadata)
    else:
        pred_types = None
    pred_rewrites = None
    pcols_lower = frozenset(c.lower() for c in part_cols)
    if pred_types is not None:
        from delta_tpu.ops.pruning import conjunct_rewrites

        # scan-constant: computed ONCE here, not per file in the decode pool
        pred_rewrites = conjunct_rewrites([predicate], pcols_lower,
                                          pred_types)
    pos_hints = list(positions_of_interest) if positions_of_interest else None
    # per-file (rgTotal, rgPruned, rgLateSkipped, bytesSkippedPlanned,
    # bytesLateSkipped, planFired, rgDeviceSkipped, bytesDeviceSkipped,
    # bytesDeviceSurvivor) — summed into counters/span attributes after the
    # pool drains
    rg_stats: List[tuple] = []

    def _dummy(n: int) -> pa.Table:
        # no stored columns requested (partition-only projection, or all
        # requested columns post-date this file): carry just the row
        # count — the dummy column is dropped by the final select
        return pa.table({"__dummy": pa.nulls(n)})

    def _mask_table(t1: pa.Table, add: AddFile) -> pa.Table:
        """Attach everything the predicate may reference beyond the decoded
        predicate columns: typed partition constants and nulls for columns
        this file predates — mirroring the final table the residual filter
        sees, so the late-materialization verdict can never diverge."""
        mt = t1
        for f in schema.fields:
            if f.name.lower() not in pred_refs:
                continue
            if f.name in mt.column_names or f.name in part_cols:
                continue
            at = arrow_type_for(f.data_type)
            mt = mt.append_column(pa.field(f.name, at, True), pa.nulls(mt.num_rows, at))
        if part_cols:
            typed = typed_partition_row(add, part_schema)
            for c in part_cols:
                if c.lower() not in pred_refs or c in mt.column_names:
                    continue
                f = part_schema[c]
                at = arrow_type_for(f.data_type)
                v = typed.get(c)
                arr = (
                    pa.nulls(mt.num_rows, at)
                    if v is None
                    else pa.array([v] * mt.num_rows, type=at)
                )
                mt = mt.append_column(pa.field(c, at, f.nullable), arr)
        return mt

    def _decode_pruned(abs_path, meta, keep_idx, add, need_positions):
        """Decode only ``keep_idx`` row groups (late-materializing around
        the predicate columns); returns (table, physical_positions | None,
        late_skipped_groups, late_skipped_bytes)."""
        import numpy as np

        from delta_tpu.exec import rowgroups

        offsets = rowgroups.row_group_offsets(meta)
        late_skipped = 0
        late_bytes = 0
        if not keep_idx:
            t = _dummy(0)
            pos = np.empty(0, dtype=np.int64) if need_positions else None
            return t, pos, 0, 0
        pf = pq.ParquetFile(abs_path, memory_map=True, metadata=meta)
        present = set(pf.schema_arrow.names)
        file_cols = [c for c in data_cols if c in present]
        if not file_cols:
            t = _dummy(int(sum(meta.row_group(i).num_rows for i in keep_idx)))
        else:
            pred_cols = [c for c in file_cols if c.lower() in pred_refs]
            rest_cols = [c for c in file_cols if c not in pred_cols]
            # a predicate column STORED in the file but outside the
            # projection would mask as all-null and late-skip groups that
            # genuinely match — late materialization needs every stored
            # predicate column in the decode set
            refs_covered = not (
                pred_refs
                & {c.lower() for c in present}
                - {c.lower() for c in file_cols}
            )
            t = None
            if late_materialize and refs_covered \
                    and predicate is not None and pred_cols and rest_cols:
                t1 = pf.read_row_groups(keep_idx, columns=pred_cols)
                try:
                    from delta_tpu.expr.vectorized import boolean_mask

                    mask = boolean_mask(
                        predicate, _mask_table(t1, add)
                    ).to_numpy(zero_copy_only=False)
                except Exception:
                    mask = None  # unevaluable here: keep every group
                if mask is not None:
                    survivors, slices = [], []
                    start = 0
                    for i in keep_idx:
                        n_i = meta.row_group(i).num_rows
                        if mask[start:start + n_i].any():
                            survivors.append(i)
                            slices.append((start, n_i))
                        else:
                            late_skipped += 1
                            rg = meta.row_group(i)
                            by_name = {
                                rg.column(j).path_in_schema: j
                                for j in range(rg.num_columns)
                            }
                            late_bytes += sum(
                                rg.column(by_name[c]).total_uncompressed_size
                                for c in rest_cols
                                if c in by_name
                            )
                        start += n_i
                    if late_skipped:
                        t1 = (
                            pa.concat_tables([t1.slice(s, n) for s, n in slices])
                            if slices
                            else t1.slice(0, 0)
                        )
                        keep_idx = survivors
                if keep_idx and rest_cols:
                    t2 = pf.read_row_groups(keep_idx, columns=rest_cols)
                    cols = {c: t1.column(c) for c in t1.column_names}
                    cols.update({c: t2.column(c) for c in t2.column_names})
                    t = pa.table([cols[c] for c in file_cols], names=file_cols)
                elif keep_idx:
                    t = t1
                else:
                    t = pf.schema_arrow.empty_table().select(file_cols)
            if t is None:
                t = pf.read_row_groups(keep_idx, columns=file_cols)
        pos = None
        if need_positions:
            pos = (
                np.concatenate(
                    [np.arange(offsets[i], offsets[i + 1]) for i in keep_idx]
                ).astype(np.int64)
                if keep_idx
                else np.empty(0, dtype=np.int64)
            )
        return t, pos, late_skipped, late_bytes

    def _decode_device_masked(abs_path, meta, keep_idx, add, need_positions,
                              dev_mask):
        """The device residual path's survivor fetch: drop row groups whose
        device mask slice is all-False, decode the survivors' projected
        columns in ONE read (no host predicate evaluation), and filter rows
        to the mask. The caller re-applies the residual over the result
        (``scan_to_table``), so an over-keep can never leak; an under-keep
        cannot happen because the mask is the exact Kleene-TRUE set of the
        same predicate. Returns None when the mask doesn't line up with the
        footer (→ host path), else (table, positions | None,
        (device_skipped_groups, device_skipped_bytes, survivor_bytes))."""
        import numpy as np

        from delta_tpu.exec import rowgroups

        offsets = rowgroups.row_group_offsets(meta)
        if len(dev_mask) != offsets[-1]:
            return None
        survivors = []
        dev_skipped = dev_bytes = surv_bytes = 0
        for i in keep_idx:
            if dev_mask[offsets[i]:offsets[i + 1]].any():
                survivors.append(i)
                surv_bytes += meta.row_group(i).total_byte_size
            else:
                dev_skipped += 1
                dev_bytes += meta.row_group(i).total_byte_size
        pf = pq.ParquetFile(abs_path, memory_map=True, metadata=meta)
        present = set(pf.schema_arrow.names)
        file_cols = [c for c in data_cols if c in present]
        if not survivors:
            t = (pf.schema_arrow.empty_table().select(file_cols)
                 if file_cols else _dummy(0))
            pos = np.empty(0, dtype=np.int64) if need_positions else None
            return t, pos, (dev_skipped, dev_bytes, 0)
        if file_cols:
            t = pf.read_row_groups(survivors, columns=file_cols)
        else:
            t = _dummy(int(sum(meta.row_group(i).num_rows
                               for i in survivors)))
        keep = np.concatenate(
            [dev_mask[offsets[i]:offsets[i + 1]] for i in survivors])
        t = t.filter(pa.array(keep))
        pos = None
        if need_positions:
            phys = np.concatenate(
                [np.arange(offsets[i], offsets[i + 1]) for i in survivors])
            pos = phys[keep].astype(np.int64)
        return t, pos, (dev_skipped, dev_bytes, surv_bytes)

    def read_one(job) -> pa.Table:
        fidx, add, pos_hint = job
        abs_path = _abs_data_path(data_path, add.path)
        import numpy as np

        need_positions = (
            add.deletion_vector is not None or position_column is not None
        )
        t = None
        positions = None
        meta = None
        if rg_skipping and (predicate is not None or pos_hint is not None):
            from delta_tpu.exec import rowgroups

            try:
                meta = rowgroups.read_footer(abs_path)
            except Exception:
                meta = None
        if meta is not None and meta.num_row_groups > 0:
            n_rg = meta.num_row_groups
            keep_idx = list(range(n_rg))
            skipped_bytes = 0
            plan_fired: list = []
            if predicate is not None and n_rg > 1:
                part_row = (
                    typed_partition_row(add, part_schema) if part_cols else None
                )
                plan = rowgroups.plan_row_groups(
                    meta, predicate, part_row, pcols_lower, pred_types,
                    rewrites=pred_rewrites,
                )
                keep_idx, skipped_bytes = plan.keep, plan.skipped_bytes
                plan_fired = plan.fired
            if pos_hint is not None:
                wanted = rowgroups.row_groups_for_positions(meta, pos_hint)
                for i in keep_idx:
                    if i not in wanted:
                        skipped_bytes += meta.row_group(i).total_byte_size
                keep_idx = [i for i in keep_idx if i in wanted]
            pruned = n_rg - len(keep_idx)
            late_capable = (
                late_materialize and predicate is not None
                and keep_idx and pred_refs and n_rg > 1
            )
            dev_mask = device_masks.get(add.path) if device_masks else None
            if dev_mask is not None:
                res = _decode_device_masked(
                    abs_path, meta, keep_idx, add, need_positions, dev_mask
                )
                if res is not None:
                    t, positions, dstats = res
                    rg_stats.append(
                        (n_rg, pruned, 0, skipped_bytes, 0, plan_fired)
                        + dstats
                    )
            if t is None and (pruned or late_capable):
                t, positions, late_n, late_bytes = _decode_pruned(
                    abs_path, meta, keep_idx, add, need_positions
                )
                rg_stats.append(
                    (n_rg, pruned, late_n, skipped_bytes, late_bytes,
                     plan_fired, 0, 0, 0)
                )
            elif t is None:
                rg_stats.append((n_rg, 0, 0, 0, 0, (), 0, 0, 0))
        if t is None:
            # full decode — the seed path; reuse the already-parsed footer
            # when the planner fetched one.
            # memory_map: decoded columns reference page-cache pages
            # instead of round-tripping file bytes through the Arrow
            # memory pool — on single-core hosts the pool churn costs
            # more than the decode
            pf = pq.ParquetFile(abs_path, memory_map=True, metadata=meta)
            # project to the columns this file actually has (files written
            # before a schema evolution lack the newer columns — read
            # fills them w/ null)
            present = set(pf.schema_arrow.names)
            file_cols = [c for c in data_cols if c in present]
            if file_cols:
                t = pf.read(columns=file_cols)
            else:
                t = _dummy(pf.metadata.num_rows)

        if add.deletion_vector is not None:
            from delta_tpu.protocol.deletion_vectors import (
                DeletionVectorDescriptor,
                read_deletion_vector,
            )

            dv_rows = read_deletion_vector(
                DeletionVectorDescriptor.from_dict(add.deletion_vector), data_path
            )
            if positions is None:
                keep = np.ones(t.num_rows, dtype=bool)
                keep[dv_rows] = False
                positions = np.flatnonzero(keep)
            else:
                # pruned decode: positions are physical but sparse — map
                # the DV through membership, not direct indexing
                keep = ~np.isin(positions, dv_rows)
                positions = positions[keep]
            t = t.filter(pa.array(keep))
        elif position_column is not None and positions is None:
            positions = np.arange(t.num_rows, dtype=np.int64)
        for f in schema.fields:
            if f.name in data_cols and f.name not in t.column_names:
                at = arrow_type_for(f.data_type)
                t = t.append_column(pa.field(f.name, at, True), pa.nulls(t.num_rows, at))
        if part_cols:
            typed = typed_partition_row(add, part_schema)
            for c in part_cols:
                if c not in out_names:
                    continue
                f = part_schema[c]
                at = arrow_type_for(f.data_type)
                v = typed.get(c)
                arr = (
                    pa.nulls(t.num_rows, at)
                    if v is None
                    else pa.array([v] * t.num_rows, type=at)
                )
                t = t.append_column(pa.field(c, at, f.nullable), arr)
        # column order = requested order
        t = t.select([c for c in out_names if c in t.column_names])
        # Cast columns up to the declared table type: files written before an
        # ALTER ... CHANGE COLUMN widen carry the old narrower type.
        declared = {f.name: arrow_type_for(f.data_type) for f in schema.fields}
        for i, name in enumerate(t.column_names):
            want = declared.get(name)
            col = t.column(i)
            if want is not None and col.type != want:
                t = t.set_column(i, pa.field(name, want, True), col.cast(want))
        if position_column is not None:
            t = t.append_column(
                position_column, pa.array(positions, pa.int64())
            )
        if file_ready is not None:
            file_ready(fidx, add, t)
        return t

    if pos_hints is not None and len(pos_hints) != len(files):
        raise ValueError(
            f"positions_of_interest has {len(pos_hints)} entries "
            f"for {len(files)} files"
        )
    jobs = [(i, add, hint) for i, (add, hint) in enumerate(
        zip(files, pos_hints if pos_hints else [None] * len(files)))]
    def decode_one(job):
        # one span per file decode: with the span context propagated into
        # the pool workers these parent under `delta.scan.read` (and the
        # enclosing command span) on each worker's own trace lane — the
        # decode half of the decode/compute overlap, visible in
        # export_chrome_trace instead of orphaned
        with telemetry.record_operation(
            "delta.scan.decode", {"file": job[1].path}
        ):
            return read_one(job)

    with telemetry.record_operation(
        "delta.scan.read", {"numFiles": len(files)}
    ) as rev:
        if len(jobs) == 1:
            pieces = [decode_one(jobs[0])]
        else:
            from concurrent.futures import ThreadPoolExecutor

            workers = min(len(jobs), os.cpu_count() or 4)
            with ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="delta-scan-decode"
            ) as pool:
                pieces = list(pool.map(telemetry.propagated(decode_one), jobs))
        if rg_stats:
            rg_total = sum(s[0] for s in rg_stats)
            rg_pruned = sum(s[1] for s in rg_stats)
            rg_late = sum(s[2] for s in rg_stats)
            planned_bytes = sum(s[3] for s in rg_stats)
            rg_device = sum(s[6] for s in rg_stats)
            device_bytes = sum(s[7] for s in rg_stats)
            device_survivor = sum(s[8] for s in rg_stats)
            bytes_skipped = (planned_bytes + sum(s[4] for s in rg_stats)
                             + device_bytes)
            telemetry.bump_counter("scan.rowgroups.total", rg_total)
            if rg_pruned:
                telemetry.bump_counter("scan.rowgroups.pruned", rg_pruned)
            if rg_late:
                telemetry.bump_counter("scan.rowgroups.lateSkipped", rg_late)
            if rg_device:
                telemetry.bump_counter("scan.rowgroups.deviceSkipped",
                                       rg_device)
            if bytes_skipped:
                telemetry.bump_counter("scan.bytes.skipped", bytes_skipped)
            if device_bytes:
                telemetry.bump_counter("scan.bytes.deviceSkipped",
                                       device_bytes)
            if device_survivor:
                # survivor-group bytes the device path sent to host decode —
                # the host-decoded remainder of masked files, counted apart
                # from plain host reads so the bench can split the two
                telemetry.bump_counter("scan.bytes.deviceSurvivor",
                                       device_survivor)
            rev.data.update(
                rowGroupsTotal=rg_total, rowGroupsPruned=rg_pruned,
                rowGroupsLateSkipped=rg_late, bytesSkipped=bytes_skipped,
                rowGroupsDeviceSkipped=rg_device,
            )
            # the in-flight per-query ScanReport (obs/scan_report) gets the
            # SAME sums that fed the counters — report/counter parity by
            # construction
            from delta_tpu.obs import scan_report as scan_report_mod

            scan_report_mod.contribute(
                row_groups_total=rg_total, row_groups_pruned=rg_pruned,
                row_groups_late_skipped=rg_late, bytes_skipped=bytes_skipped,
                bytes_skipped_planned=planned_bytes,
                row_groups_device_skipped=rg_device,
                bytes_device_skipped=device_bytes,
                bytes_device_survivor=device_survivor,
            )
            # fired-rewrite attribution: each synthesized conjunct that
            # excluded a row group records ONCE per scan (the per-file
            # planner reports per file; the report layer dedupes against
            # the file tier too)
            seen_fired = set()
            for s in rg_stats:
                for fe in s[5]:
                    key = (fe["family"], fe["conjunct"])
                    if key in seen_fired:
                        continue
                    seen_fired.add(key)
                    scan_report_mod.record_rewrite_fired(
                        fe["family"], fe["conjunct"], fe["rewrite"])
        if per_file:
            return pieces
        return pa.concat_tables(pieces, promote_options="permissive")


def scan_files(snapshot, filters: Sequence[Union[str, ir.Expression]] = ()) -> pruning.DeltaScan:
    exprs = [parse_predicate(f) if isinstance(f, str) else f for f in filters]
    return pruning.files_for_scan(snapshot, exprs)


from dataclasses import dataclass
from typing import List


@dataclass
class QueryPlan:
    """One query's pruned file list from :func:`plan_scans`. ``overflow``
    marks a query whose match set exceeded K (``paths`` holds the first K;
    ``count`` stays exact); ``via`` records which engine produced it
    ('device', 'host-resident', or 'scan' for the per-query fallback)."""

    paths: List[str]
    count: int
    overflow: bool = False
    via: str = "scan"


def plan_scans(
    snapshot,
    queries: Sequence[Sequence[Union[str, ir.Expression]]],
    k: int = 256,
) -> List[QueryPlan]:
    """Plan a *batch* of queries against one snapshot — the serving shape of
    a query router / BI dashboard (N concurrent point lookups) or MERGE's
    per-partition file probing.

    With the table's scan lanes HBM-resident (`ops/state_cache`), the whole
    batch is ONE device dispatch and one (N, K) download; the link cost model
    (`parallel/link`) decides device vs the host float64 mirrors per batch.
    Queries whose predicates don't lower to per-column ranges (ORs, null
    tests, strings) fall back to :func:`scan_files` individually."""
    import numpy as np

    from delta_tpu.ops.state_cache import DeviceStateCache, extract_range_union
    from delta_tpu.utils.telemetry import bump_counter

    parsed = [
        [parse_predicate(f) if isinstance(f, str) else f for f in q]
        for q in queries
    ]
    out: List[Optional[QueryPlan]] = [None] * len(queries)
    entry = DeviceStateCache.instance().get(snapshot)
    range_ix, term_lists = [], []
    if entry is not None:
        from delta_tpu.expr.synthesis import schema_types

        pcols = frozenset(c.lower() for c in snapshot.metadata.partition_columns)
        types = schema_types(snapshot.metadata)
        for i, exprs in enumerate(parsed):
            if not exprs:
                continue
            rewritten = pruning.skipping_predicate(ir.and_all(list(exprs)),
                                                   pcols, types)
            terms = extract_range_union(rewritten, entry.columns,
                                        entry.part_info,
                                        str_lanes=entry.str_lanes)
            if terms:
                range_ix.append(i)
                term_lists.append(terms)
            else:
                bump_counter("stateCache.plan.fallback.lowering")
    else:
        bump_counter("stateCache.plan.fallback.noentry", len(queries))
    if term_lists:
        # OR queries lower to several boxes; their row sets union after the
        # plan, so THEIR boxes ask for complete row sets — but only theirs:
        # per-range k keeps the single-term queries sharing the dispatch on
        # small plans instead of dragging the whole batch to num_rows
        flat, flat_ks = [], []
        full_k = max(entry.num_rows, 1)
        for terms in term_lists:
            flat.extend(terms)
            flat_ks.extend([k if len(terms) == 1 else full_k] * len(terms))
        plans = entry.plan_ranges(
            flat, k=flat_ks, expected_version=snapshot.version
        )
        if plans is not None:  # None: entry advanced past our snapshot
            bump_counter("stateCache.plan.resident", len(term_lists))
            pos = 0
            for i, terms in zip(range_ix, term_lists):
                chunk = plans[pos:pos + len(terms)]
                pos += len(terms)
                if len(chunk) == 1:
                    rows, count = chunk[0].rows, chunk[0].count
                else:
                    rows = np.unique(np.concatenate([p.rows for p in chunk]))
                    count = len(rows)
                over = count > k or chunk[0].overflow
                out[i] = QueryPlan(
                    paths=[entry.paths[r] for r in rows[:k]],
                    count=count, overflow=over, via=chunk[0].via,
                )
        else:
            bump_counter("stateCache.plan.fallback.version", len(term_lists))
    for i, exprs in enumerate(parsed):
        if out[i] is None:
            scan = pruning.files_for_scan(snapshot, exprs)
            out[i] = QueryPlan(
                paths=[f.path for f in scan.files], count=len(scan.files)
            )
    return out  # type: ignore[return-value]


def scan_to_table(
    snapshot,
    filters: Sequence[Union[str, ir.Expression]] = (),
    columns: Optional[Sequence[str]] = None,
    distribute: bool = False,
) -> pa.Table:
    """Full read path: prune → decode (projection ∪ filter columns) →
    residual filter → project. ``distribute=True``: this host decodes only
    its partition of the pruned file list (multi-host scan).

    Each call records a per-query :class:`delta_tpu.obs.scan_report.ScanReport`
    (files/row-groups considered vs pruned, bytes, phase durations),
    retrievable via ``obs.last_scan_report()`` and attached to the
    ``delta.scan`` span — skipped entirely under a telemetry blackout."""
    import time as _time

    from delta_tpu.obs import scan_report as scan_report_mod
    from delta_tpu.utils import telemetry

    track = conf.get_bool("delta.tpu.telemetry.enabled", True)
    token = (scan_report_mod.start_report(snapshot.delta_log.data_path,
                                          snapshot.version)
             if track else None)
    scan_ok = False
    try:
        with telemetry.record_operation(
            "delta.scan", path=snapshot.delta_log.data_path
        ) as sev:
            t0 = _time.perf_counter_ns()
            exprs = [parse_predicate(f) if isinstance(f, str) else f for f in filters]
            scan = pruning.files_for_scan(snapshot, exprs)
            t1 = _time.perf_counter_ns()
            data_path = snapshot.delta_log.data_path
            residual = scan.partition_filters + scan.data_filters
            read_cols = columns
            if columns is not None and residual:
                # read filter-referenced columns too; project back after filtering
                needed = set(columns)
                for e in residual:
                    needed.update(ir.references(e))
                read_cols = [c for c in [f.name for f in snapshot.metadata.schema.fields]
                             if c in needed]
            # third tier, when the router prices it: the device residual
            # path (ops/column_cache) computes per-file survivor masks from
            # HBM-resident lanes in one jitted pass; None = host path
            device_masks = None
            if residual and scan.files:
                from delta_tpu.ops import column_cache

                if column_cache.column_cache_enabled():
                    device_masks = column_cache.device_residual_masks(
                        snapshot, scan.files, ir.and_all(residual))
            # the residual predicate rides into the decode: footer row-group
            # stats prune inside each file (second tier), and the residual
            # filter below re-applies the exact semantics over the survivors
            table = read_files_as_table(data_path, scan.files, snapshot.metadata,
                                        read_cols, distribute=distribute,
                                        predicate=(ir.and_all(residual)
                                                   if residual else None),
                                        device_masks=device_masks)
            t2 = _time.perf_counter_ns()
            if residual and table.num_rows:
                table = filter_table(table, ir.and_all(residual))
            if columns is not None and read_cols != list(columns):
                table = table.select([c for c in columns if c in table.column_names])
            t3 = _time.perf_counter_ns()
            sev.data.update(
                filesScanned=len(scan.files), rowsOut=table.num_rows,
                bytesScanned=scan.scanned.bytes_compressed,
            )
            if token is not None:
                rep = scan_report_mod.current_report()
                if rep is not None:
                    rep.predicate = (ir.and_all(residual).sql()
                                     if residual else None)
                    rep.columns = list(columns) if columns is not None else None
                    rep.files_total = scan.total.files or 0
                    rep.files_after_partition = scan.partition.files or 0
                    rep.files_scanned = len(scan.files)
                    rep.rows_out = table.num_rows
                    rep.phase_ms = {
                        "planning": (t1 - t0) // 1_000_000,
                        "read": (t2 - t1) // 1_000_000,
                        "filter": (t3 - t2) // 1_000_000,
                    }
                    rep_dict = rep.to_dict()
                    sev.data["scanReport"] = rep_dict
                    # workload journal: the same report dict plus the
                    # normalized predicate fingerprint (computed on the
                    # journal writer thread) persists to
                    # <table>/_delta_log/_journal so the layout advisor can
                    # aggregate across processes (buffered; inert when the
                    # journal or telemetry is disabled)
                    from delta_tpu.obs import journal as journal_mod

                    from delta_tpu.expr.synthesis import schema_types

                    # resolve the synthesis conf NOW: the fingerprint is
                    # computed deferred on the journal writer thread, and
                    # the process conf may sit in a different window by
                    # flush time (types=None = synthesis was off)
                    fp_types = (
                        schema_types(snapshot.metadata)
                        if conf.get_bool(
                            "delta.tpu.read.predicateSynthesis", True)
                        else None)
                    journal_mod.record_scan(
                        snapshot.delta_log.log_path, report_dict=rep_dict,
                        predicate=(ir.and_all(residual) if residual else None),
                        partition_cols=snapshot.metadata.partition_columns,
                        types=fp_types,
                    )
            scan_ok = True
            return table
    finally:
        if token is not None:
            scan_report_mod.finish_report(token, completed=scan_ok)
