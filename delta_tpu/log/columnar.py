"""Columnar log-segment decode: Parquet/JSON actions → SoA columns, no
per-action Python objects.

The reference reconstructs state by decoding every action into a JVM object
and replaying per partition (``Snapshot.scala:88-111``,
``actions/InMemoryLogReplay.scala:43-65``).  A columnar engine cannot afford
an object per action on its hottest path: here the whole segment — checkpoint
Parquet parts and delta JSON commits — is decoded *directly* to Arrow/numpy
columns in C++ (pyarrow's multithreaded JSON/Parquet readers), the
last-writer-wins winner is computed vectorially (host numpy or the device
kernel in ``delta_tpu.ops.replay_kernel``), and :class:`AddFile` /
:class:`RemoveFile` dataclasses are materialized **lazily**, only for the
rows a caller actually touches.

Layout invariant: rows are in global replay order (checkpoint parts first,
then deltas ascending by version, line order within a commit), so *row index
is the replay sequence number* — last row of a path run wins.  No explicit
seq column ever needs to ship to the device.

Non-file actions (Protocol / Metadata / SetTransaction) are rare; they are
materialized eagerly (they drive schema/config decisions) via a cheap
key-substring scan over non-file rows.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from delta_tpu.protocol.actions import (
    Action,
    Metadata,
    Protocol,
    SetTransaction,
    action_from_json,
)
from delta_tpu.storage.logstore import LogStore
from delta_tpu.utils.arrow import one_chunk
from delta_tpu.utils.errors import DeltaIllegalStateError

__all__ = ["SegmentColumns", "decode_segment", "decode_json_commits",
           "decode_checkpoint_parts", "extend_segment_columns"]


def _json_schema() -> pa.Schema:
    """Explicit schema for the batched JSON reader.

    Map-typed fields (partitionValues/tags/configuration) are excluded — the
    Arrow JSON reader cannot parse JSON objects into map columns — so they are
    recovered lazily from the raw line when a row is materialized. Everything
    the replay and scan planner need (path identity, size, timestamps, stats
    JSON) parses straight to columns.
    """
    add_t = pa.struct(
        [
            ("path", pa.string()),
            ("size", pa.int64()),
            ("modificationTime", pa.int64()),
            ("dataChange", pa.bool_()),
            ("stats", pa.string()),
        ]
    )
    rem_t = pa.struct(
        [
            ("path", pa.string()),
            ("deletionTimestamp", pa.int64()),
            ("dataChange", pa.bool_()),
            ("extendedFileMetadata", pa.bool_()),
            ("size", pa.int64()),
        ]
    )
    return pa.schema([("add", add_t), ("remove", rem_t)])


# Key substrings that mark a non-file line as state-relevant. commitInfo and
# cdc rows are skipped without a JSON parse (state replay ignores them,
# InMemoryLogReplay.scala:62-64).
_OTHER_KEYS = (b'"metaData"', b'"protocol"', b'"txn"')


@dataclass
class _Batch:
    """One decoded source: a run of delta-JSON commits or a checkpoint part."""

    kind: str  # "json" | "ckpt"
    row_offset: int  # first global row index of this batch's file actions
    num_rows: int
    # json batches: per-line bytes (row i of the parsed table == lines[i])
    lines: Optional[List[bytes]] = None
    line_index: Optional[np.ndarray] = None  # file-action row -> line number
    # ckpt batches: the Arrow table (map columns intact) + per-row source row
    table: Optional[pa.Table] = None
    table_index: Optional[np.ndarray] = None  # file-action row -> table row

    def partition_strings(
        self, local_rows: np.ndarray, part_cols: Sequence[str]
    ) -> Optional[Dict[str, pa.Array]]:
        """Partition-value strings for batch-local file-action rows (adds;
        remove rows yield nulls). Checkpoint batches answer vectorized from
        the retained table's ``add.partitionValues`` map; JSON batches parse
        their lines (commit tails are short)."""
        import json as _json

        if self.kind == "json":
            assert self.lines is not None and self.line_index is not None
            cols: Dict[str, List[Optional[str]]] = {c: [] for c in part_cols}
            for r in local_rows:
                try:
                    d = _json.loads(self.lines[self.line_index[r]])
                except Exception:
                    return None
                pv = (d.get("add") or {}).get("partitionValues")
                if pv is None and "add" in d:
                    return None  # an add without the mandatory map
                pv = pv or {}
                for c in part_cols:
                    v = pv.get(c)
                    cols[c].append(v if isinstance(v, str) else None)
            return {c: pa.array(v, pa.string()) for c, v in cols.items()}
        assert self.table is not None and self.table_index is not None
        if "add" not in self.table.column_names:
            return None
        add = self.table.column("add")
        sel = pa.array(self.table_index[local_rows])
        got = self._part_strings_from_map(add, sel, part_cols)
        if got is None:
            got = self._part_strings_from_parsed(add, sel, part_cols)
        return got

    @staticmethod
    def _part_strings_from_map(add, sel, part_cols) -> Optional[Dict[str, pa.Array]]:
        add_t = add.type
        if not any(add_t.field(i).name == "partitionValues"
                   for i in range(add_t.num_fields)):
            return None
        pv = pc.struct_field(add, "partitionValues")
        if not pa.types.is_map(pv.type):
            return None
        pv = pv.take(sel)
        out: Dict[str, pa.Array] = {}
        for c in part_cols:
            try:
                vals = pc.map_lookup(pv, query_key=c, occurrence="first")
            except Exception:
                return None
            out[c] = one_chunk(vals).cast(pa.string())
        return out

    @staticmethod
    def _part_strings_from_parsed(add, sel, part_cols) -> Optional[Dict[str, pa.Array]]:
        """Fallback for checkpoints that carry only the typed
        ``partitionValues_parsed`` struct (no raw map): render each typed
        leaf back to a string. The rendering is Arrow's canonical cast, so
        every batch of such a checkpoint encodes a value the same way —
        dictionary codes stay consistent within the segment."""
        add_t = add.type
        if not any(add_t.field(i).name == "partitionValues_parsed"
                   for i in range(add_t.num_fields)):
            return None
        pv = pc.struct_field(add, "partitionValues_parsed")
        if not pa.types.is_struct(pv.type):
            return None
        fields = {pv.type.field(i).name for i in range(pv.type.num_fields)}
        if not set(part_cols) <= fields:
            return None
        pv = pv.take(sel)
        out: Dict[str, pa.Array] = {}
        for c in part_cols:
            try:
                vals = pc.struct_field(pv, c).cast(pa.string())
            except Exception:
                return None
            out[c] = one_chunk(vals)
        return out

    def materialize(self, local_rows: np.ndarray) -> List[Action]:
        """Build Add/RemoveFile dataclasses for batch-local file-action rows."""
        out: List[Action] = []
        if self.kind == "json":
            assert self.lines is not None and self.line_index is not None
            for r in local_rows:
                a = action_from_json(self.lines[self.line_index[r]].decode("utf-8"))
                assert a is not None
                out.append(a)
            return out
        assert self.table is not None and self.table_index is not None
        rows = self.table.take(pa.array(self.table_index[local_rows]))
        add_col = rows.column("add").to_pylist() if "add" in rows.column_names else [None] * len(rows)
        rem_col = rows.column("remove").to_pylist() if "remove" in rows.column_names else [None] * len(rows)
        from delta_tpu.log.checkpoints import _row_to_action

        for a_d, r_d in zip(add_col, rem_col):
            if a_d is not None:
                out.append(_row_to_action("add", a_d))
            else:
                out.append(_row_to_action("remove", r_d))
        return out


@dataclass
class SegmentColumns:
    """A log segment's file actions as replay-ordered SoA columns.

    ``path_id`` indexes ``path_dict`` (canonicalized paths, dictionary
    encoded); row order is the replay order, so winner-per-path is "last row
    of each path_id run".  ``stats`` is the raw per-row stats JSON string
    column (null for removes / stats-less adds) — scan planning parses it in
    batch without touching dataclasses.
    """

    path_dict: pa.Array  # string array: path_id -> canonical path
    path_id: np.ndarray  # int32
    is_add: np.ndarray  # bool
    size: np.ndarray  # int64 (0 where absent)
    modification_time: np.ndarray  # int64 (adds; 0 elsewhere)
    deletion_timestamp: np.ndarray  # int64 (removes; 0 elsewhere)
    stats: Optional[pa.ChunkedArray]  # string, aligned with rows (may be None)
    other_actions: List[Action]  # Protocol/Metadata/SetTransaction, replay order
    batches: List[_Batch] = field(default_factory=list)
    # checkpoint `add.stats_parsed` struct column, aligned with rows: typed
    # per-file stats (numRecords/minValues/maxValues/nullCount) for the
    # zero-JSON state export; null on rows whose source batch lacks the
    # column (JSON commit tails, pre-struct checkpoints). None when no batch
    # carries it (or batch types disagree).
    stats_parsed: Optional[pa.ChunkedArray] = None

    @property
    def num_rows(self) -> int:
        return len(self.path_id)

    @property
    def num_paths(self) -> int:
        return len(self.path_dict)

    # -- replay -----------------------------------------------------------

    def winner_mask(self) -> np.ndarray:
        """Last-action-per-path mask, host path: one vectorized scatter —
        later rows overwrite earlier ones, which *is* last-writer-wins."""
        last = np.full(self.num_paths, -1, np.int64)
        last[self.path_id] = np.arange(self.num_rows)
        mask = np.zeros(self.num_rows, bool)
        live = last[last >= 0]
        mask[live] = True
        return mask

    def replay(
        self, min_retention_ts: int = 0, winner: Optional[np.ndarray] = None
    ) -> Tuple[np.ndarray, np.ndarray]:
        """(alive_adds, retained_tombstones) boolean row masks. Callers that
        cached a winner mask (or computed it on device) pass it in."""
        w = self.winner_mask() if winner is None else winner
        alive = w & self.is_add
        tomb = w & ~self.is_add & (self.deletion_timestamp > min_retention_ts)
        return alive, tomb

    # -- lazy materialization --------------------------------------------

    def materialize(self, mask_or_rows) -> List[Action]:
        """AddFile/RemoveFile dataclasses for the selected rows, in row order.

        Accepts a boolean row mask or an array of row indices. Only the rows
        selected are decoded (``VERDICT`` round 2: the dataclass view is for
        the rows a caller touches, never the whole log).
        """
        rows = np.asarray(mask_or_rows)
        if rows.dtype == bool:
            rows = np.nonzero(rows)[0]
        out: List[Action] = []
        offsets = np.array([b.row_offset for b in self.batches], np.int64)
        which = np.searchsorted(offsets, rows, side="right") - 1
        ordered_rows: List[int] = []
        for bi in np.unique(which):
            batch = self.batches[bi]
            sel = rows[which == bi]
            ordered_rows.extend(sel.tolist())
            out.extend(batch.materialize(sel - batch.row_offset))
        # Replay identity is the *canonical* path; rewrite materialized
        # actions whose as-written path differs (log/replay.canonicalize_path)
        canon = self.paths_for(np.asarray(ordered_rows, np.int64)) if out else []
        from dataclasses import replace as _dc_replace

        for i, (a, cp) in enumerate(zip(out, canon)):
            if a.path != cp:
                out[i] = _dc_replace(a, path=cp)
        return out

    def paths_for(self, rows: np.ndarray) -> List[str]:
        """Canonical paths for the given *row* indices."""
        return self.path_dict.take(pa.array(self.path_id[rows], pa.int64())).to_pylist()

    def partition_strings(
        self, rows: np.ndarray, part_cols: Sequence[str]
    ) -> Optional[Dict[str, pa.Array]]:
        """Raw partition-value strings for the given *row* indices, one
        string array per partition column (null = value absent/null).

        Checkpoint batches serve this vectorized from their retained Arrow
        table (``add.partitionValues`` map via ``pc.map_lookup``, or the
        typed ``partitionValues_parsed`` struct when present); JSON batches
        parse their (few) tail lines individually. None when any covering
        batch can't produce the columns — callers fall back to the
        dataclass path."""
        rows = np.asarray(rows, np.int64)
        if not len(rows):
            return {c: pa.array([], pa.string()) for c in part_cols}
        out_chunks: Dict[str, List[pa.Array]] = {c: [] for c in part_cols}
        offsets = np.array([b.row_offset for b in self.batches], np.int64)
        which = np.searchsorted(offsets, rows, side="right") - 1
        order = np.argsort(which, kind="stable")
        if not (rows[order] == rows).all():
            # callers pass replay-ordered rows; batches are replay-ordered
            # too, so a reordering here would desync the output alignment
            return None
        for bi in np.unique(which):
            batch = self.batches[bi]
            local = rows[which == bi] - batch.row_offset
            got = batch.partition_strings(local, part_cols)
            if got is None:
                return None
            for c in part_cols:
                out_chunks[c].append(got[c])
        result: Dict[str, pa.Array] = {}
        for c in part_cols:
            arr = (out_chunks[c][0] if len(out_chunks[c]) == 1
                   else pa.concat_arrays([a.combine_chunks() if
                                          isinstance(a, pa.ChunkedArray) else a
                                          for a in out_chunks[c]]))
            if isinstance(arr, pa.ChunkedArray):
                arr = arr.combine_chunks()
            result[c] = arr
        return result


def extend_segment_columns(base: SegmentColumns,
                           tail: SegmentColumns) -> SegmentColumns:
    """Append ``tail``'s rows (a decoded run of newer delta commits) after
    ``base``'s — the columnar tail-apply behind incremental checkpoints
    (``log/checkpointer``). Row order is base-then-tail, which preserves
    the replay-order invariant (row index = replay sequence), so
    ``winner_mask``/``replay`` over the result equal a fresh
    :func:`decode_segment` of the concatenated sources; the path dictionary
    is ``base``'s with the tail's unseen entries appended (first-appearance
    order preserved) — O(tail), the base rows are never re-hashed.
    Neither input is mutated (``base`` may be a long-lived cached state)."""
    if tail.num_rows == 0 and not tail.other_actions:
        return base
    n_base, n_tail = base.num_rows, tail.num_rows
    total = n_base + n_tail
    other = list(base.other_actions) + list(tail.other_actions)
    if total == 0:
        return SegmentColumns(
            path_dict=pa.array([], pa.string()),
            path_id=np.empty(0, np.int32),
            is_add=np.empty(0, bool),
            size=np.empty(0, np.int64),
            modification_time=np.empty(0, np.int64),
            deletion_timestamp=np.empty(0, np.int64),
            stats=None,
            other_actions=other,
            batches=[],
        )

    # Path dictionary: keep base's intact and map only the tail's entries
    # into it (unseen entries append, preserving first-appearance order —
    # decode_segment dictionaries are dictionary_encode products, so both
    # inputs are first-appearance ordered and the merge equals a fresh
    # decode's dictionary). O(tail), never re-hashing the base rows: the
    # incremental checkpoint build stays O(delta) on a large table.
    if n_tail:
        idx = pc.index_in(tail.path_dict, value_set=base.path_dict)
        mapped = idx.fill_null(-1).to_numpy(zero_copy_only=False).astype(
            np.int64, copy=False)
        unseen = mapped < 0
        n_new = int(unseen.sum())
        if n_new:
            mapped[unseen] = len(base.path_dict) + np.arange(n_new)
            path_dict = pa.concat_arrays([
                base.path_dict,
                one_chunk(tail.path_dict.filter(pa.array(unseen)))])
        else:
            path_dict = base.path_dict
        tail_ids = mapped[tail.path_id].astype(np.int32, copy=False)
    else:
        path_dict = base.path_dict
        tail_ids = np.empty(0, np.int32)

    # batches are shallow-copied with shifted offsets: the decoded tables /
    # line buffers are immutable and shared, only the placement changes
    batches = list(base.batches)
    for b in tail.batches:
        batches.append(_Batch(
            kind=b.kind, row_offset=b.row_offset + n_base,
            num_rows=b.num_rows, lines=b.lines, line_index=b.line_index,
            table=b.table, table_index=b.table_index,
        ))

    def _np_concat(a, b):
        return np.concatenate([a, b])

    def _str_chunks(ca, n: int):
        if n == 0:
            return []
        if ca is None:
            return [pa.nulls(n, pa.string())]
        return list(ca.chunks) if isinstance(ca, pa.ChunkedArray) else [ca]

    stats_chunks = _str_chunks(base.stats, n_base) + _str_chunks(tail.stats, n_tail)
    stats = pa.chunked_array(stats_chunks, type=pa.string()) if stats_chunks else None

    # stats_parsed: rows from the side lacking the struct column contribute
    # typed nulls (same alignment rule as decode_segment); disagreeing
    # struct types disable the column
    sp = None
    sp_types = {c.type for c in (base.stats_parsed, tail.stats_parsed)
                if c is not None}
    if len(sp_types) == 1:
        sp_t = next(iter(sp_types))

        def _sp_chunks(ca, n: int):
            if n == 0:
                return []
            if ca is None:
                return [pa.nulls(n, sp_t)]
            return list(ca.chunks) if isinstance(ca, pa.ChunkedArray) else [ca]

        chunks = _sp_chunks(base.stats_parsed, n_base) + _sp_chunks(
            tail.stats_parsed, n_tail)
        if chunks:
            sp = pa.chunked_array(chunks, type=sp_t)

    return SegmentColumns(
        path_dict=path_dict,
        path_id=_np_concat(base.path_id, tail_ids).astype(
            np.int32, copy=False),
        is_add=_np_concat(base.is_add, tail.is_add),
        size=_np_concat(base.size, tail.size),
        modification_time=_np_concat(base.modification_time,
                                     tail.modification_time),
        deletion_timestamp=_np_concat(base.deletion_timestamp,
                                      tail.deletion_timestamp),
        stats=stats,
        other_actions=other,
        batches=batches,
        stats_parsed=sp,
    )


def _canonicalize(paths, out_of_line: bool) -> pa.Array:
    """Vectorized path canonicalization (see ``log/replay.canonicalize_path``):
    strip redundant "./" prefixes; leave everything else exact."""
    if out_of_line and bool(pc.any(pc.starts_with(paths, "./")).as_py() or False):
        paths = pc.replace_substring_regex(paths, r"^(\./)+", "")
    return paths


def _extract_file_columns(table: pa.Table):
    """Shared add/remove struct → flat columns extraction (C++ end to end)."""
    names = table.column_names
    n = table.num_rows
    null_s = pa.nulls(n, pa.string())
    null_i = pa.nulls(n, pa.int64())

    def _field(struct_col, name, fallback):
        struct_type = struct_col.type
        if any(struct_type.field(i).name == name for i in range(struct_type.num_fields)):
            return pc.struct_field(struct_col, name)
        return fallback

    if "add" in names:
        add = table.column("add")
        a_path = pc.struct_field(add, "path")
        a_size = _field(add, "size", null_i)
        a_mtime = _field(add, "modificationTime", null_i)
        a_stats = _field(add, "stats", null_s)
    else:
        a_path, a_size, a_mtime, a_stats = null_s, null_i, null_i, null_s
    if "remove" in names:
        rem = table.column("remove")
        r_path = pc.struct_field(rem, "path")
        r_size = _field(rem, "size", null_i)
        r_dts = _field(rem, "deletionTimestamp", null_i)
    else:
        r_path, r_size, r_dts = null_s, null_i, null_i
    return a_path, a_size, a_mtime, a_stats, r_path, r_size, r_dts


def decode_checkpoint_parts(store: LogStore, paths: Sequence[str]) -> List[pa.Table]:
    """Read checkpoint part files into Arrow tables (no row materialization).

    Parts fetch and decode concurrently (the writer already writes them
    that way): both the store read and Arrow's Parquet decode drop the GIL,
    so a multi-part checkpoint decodes at aggregate disk/codec bandwidth
    instead of summing per-part latencies. Order is preserved — part order
    is replay order."""
    import pyarrow.parquet as pq

    def _one(p: str) -> pa.Table:
        return pq.read_table(pa.BufferReader(store.read_bytes(p)))

    if len(paths) <= 1:
        return [_one(p) for p in paths]
    from concurrent.futures import ThreadPoolExecutor

    from delta_tpu.utils import telemetry

    with ThreadPoolExecutor(max_workers=min(len(paths), 16),
                            thread_name_prefix="delta-ckpt-decode") as ex:
        # span-context propagation: the store-read counters/events these
        # workers emit parent under the enclosing snapshot/checkpoint span
        return list(ex.map(telemetry.propagated(_one), paths))


def decode_json_commits(
    buffers: Sequence[bytes],
) -> Tuple[pa.Table, List[bytes]]:
    """Batched parse of newline-delimited commit JSON.

    Returns (parsed table, line list) with the invariant row i == lines[i]:
    empty lines are dropped *before* the parse so the Arrow reader's rows stay
    aligned with the retained lines. The parse runs once over the
    concatenation of all commit files and never builds a Python object.
    """
    import pyarrow.json as pajson

    lines: List[bytes] = []
    for b in buffers:
        for ln in b.split(b"\n"):
            ln = ln.strip(b"\r")
            if ln.strip():
                lines.append(ln)
    raw = b"\n".join(lines) + b"\n" if lines else b""
    if not lines:
        return pa.table({}), lines
    table = pajson.read_json(
        pa.BufferReader(raw),
        read_options=pajson.ReadOptions(use_threads=True, block_size=4 << 20),
        parse_options=pajson.ParseOptions(
            explicit_schema=_json_schema(), unexpected_field_behavior="ignore"
        ),
    )
    if table.num_rows != len(lines):  # pragma: no cover - alignment guard
        raise DeltaIllegalStateError(
            f"JSON batch decode row/line mismatch: {table.num_rows} rows vs "
            f"{len(lines)} lines"
        )
    return table, lines


def _other_actions_from_json(lines: List[bytes], nonfile_lines: np.ndarray) -> List[Action]:
    """Materialize Protocol/Metadata/SetTransaction from non-file lines.

    ``nonfile_lines`` are line numbers whose row had neither add nor remove —
    commitInfo, cdc, or state actions. A substring scan keeps JSON parsing to
    the (rare) state-action lines; false positives (e.g. '"txn"' inside a
    commitInfo string) are filtered after a real parse.
    """
    out: List[Action] = []
    for ln in nonfile_lines:
        line = lines[ln]
        if not any(k in line for k in _OTHER_KEYS):
            continue
        a = action_from_json(line.decode("utf-8"))
        if isinstance(a, (Protocol, Metadata, SetTransaction)):
            out.append(a)
    return out


def decode_segment(
    store: LogStore,
    checkpoint_paths: Sequence[str],
    delta_paths: Sequence[str],
) -> SegmentColumns:
    """Decode a whole LogSegment (checkpoint parts + ordered delta files) to
    :class:`SegmentColumns`. Replaces the object-per-action read path of
    ``Snapshot.scala:88-111`` with three C++ passes: parse, extract, encode."""
    batches: List[_Batch] = []
    path_chunks: List[pa.Array] = []
    col_chunks: List[Tuple[np.ndarray, ...]] = []  # is_add, size, mtime, dts
    stats_chunks: List[pa.Array] = []
    sp_chunks: List[Tuple[Optional[pa.Array], int]] = []  # (stats_parsed, n)
    other: List[Action] = []
    row_offset = 0

    def _ingest(table: pa.Table, batch: _Batch, lines: Optional[List[bytes]]):
        nonlocal row_offset
        a_path, a_size, a_mtime, a_stats, r_path, r_size, r_dts = _extract_file_columns(table)
        is_add_arr = pc.is_valid(a_path)
        is_rem_arr = pc.is_valid(r_path)
        file_mask = pc.or_(is_add_arr, is_rem_arr)
        n_files = int(pc.sum(file_mask).as_py() or 0)
        all_rows = np.arange(table.num_rows, dtype=np.int64)
        file_rows = all_rows[file_mask.to_numpy(zero_copy_only=False)]
        if lines is not None:
            nonfile = all_rows[~file_mask.to_numpy(zero_copy_only=False)]
            other.extend(_other_actions_from_json(lines, nonfile))
            batch.line_index = file_rows
        else:
            # checkpoint: non-file rows are protocol/metaData/txn struct rows
            for name, kinds in (("protocol", Protocol), ("metaData", Metadata), ("txn", SetTransaction)):
                if name not in table.column_names:
                    continue
                col = table.column(name)
                valid = pc.is_valid(col).to_numpy(zero_copy_only=False)
                if valid.any():
                    from delta_tpu.log.checkpoints import _row_to_action

                    for d in col.filter(pa.array(valid)).to_pylist():
                        a = _row_to_action(name, d)
                        if a is not None:
                            other.append(a)
            batch.table_index = file_rows
        if n_files == 0:
            return
        sel = pa.array(file_rows)
        path = pc.coalesce(a_path, r_path).take(sel)
        path = _canonicalize(path, out_of_line=True)
        path_chunks.append(path.combine_chunks() if isinstance(path, pa.ChunkedArray) else path)
        take_np = lambda col, fill: np.asarray(
            col.take(sel).fill_null(fill).to_numpy(zero_copy_only=False)
        )
        col_chunks.append(
            (
                is_add_arr.take(sel).to_numpy(zero_copy_only=False),
                take_np(pc.coalesce(a_size, r_size), 0).astype(np.int64, copy=False),
                take_np(a_mtime, 0).astype(np.int64, copy=False),
                take_np(r_dts, 0).astype(np.int64, copy=False),
            )
        )
        stats_chunks.append(one_chunk(a_stats.take(sel)))
        sp = None
        if lines is None and "add" in table.column_names:
            add_t = table.column("add").type
            if any(add_t.field(i).name == "stats_parsed"
                   for i in range(add_t.num_fields)):
                sp = one_chunk(
                    pc.struct_field(table.column("add"), "stats_parsed").take(sel))
        sp_chunks.append((sp, n_files))
        batch.row_offset = row_offset
        batch.num_rows = n_files
        row_offset += n_files
        batches.append(batch)

    if checkpoint_paths:
        for p, table in zip(checkpoint_paths, decode_checkpoint_parts(store, checkpoint_paths)):
            _ingest(table, _Batch(kind="ckpt", row_offset=0, num_rows=0, table=table), lines=None)

    if delta_paths:
        buffers = [store.read_bytes(p) for p in delta_paths]
        table, lines = decode_json_commits(buffers)
        if lines:
            _ingest(table, _Batch(kind="json", row_offset=0, num_rows=0, lines=lines), lines=lines)

    if not path_chunks:
        return SegmentColumns(
            path_dict=pa.array([], pa.string()),
            path_id=np.empty(0, np.int32),
            is_add=np.empty(0, bool),
            size=np.empty(0, np.int64),
            modification_time=np.empty(0, np.int64),
            deletion_timestamp=np.empty(0, np.int64),
            stats=None,
            other_actions=other,
            batches=batches,
        )

    all_paths = pa.chunked_array(path_chunks).combine_chunks()
    enc = pc.dictionary_encode(all_paths)
    if isinstance(enc, pa.ChunkedArray):
        enc = enc.combine_chunks()
    path_id = enc.indices.to_numpy(zero_copy_only=False).astype(np.int32, copy=False)
    # align stats_parsed across batches: batches without the column (JSON
    # tails, pre-struct checkpoints) contribute typed nulls; disagreeing
    # struct types (shouldn't happen within one segment) disable the column
    sp_types = {c.type for c, _n in sp_chunks if c is not None}
    stats_parsed = None
    if len(sp_types) == 1:
        sp_t = next(iter(sp_types))
        stats_parsed = pa.chunked_array(
            [c if c is not None else pa.nulls(k, sp_t) for c, k in sp_chunks],
            type=sp_t,
        )
    return SegmentColumns(
        path_dict=enc.dictionary,
        path_id=path_id,
        is_add=np.concatenate([c[0] for c in col_chunks]),
        size=np.concatenate([c[1] for c in col_chunks]),
        modification_time=np.concatenate([c[2] for c in col_chunks]),
        deletion_timestamp=np.concatenate([c[3] for c in col_chunks]),
        stats=pa.chunked_array(stats_chunks),
        other_actions=other,
        batches=batches,
        stats_parsed=stats_parsed,
    )
