"""In-process object-store emulator speaking the conditional-PUT dialect.

Serves the HTTP surface :class:`delta_tpu.storage.http_store.HttpObjectLogStore`
expects — path-style ``/{bucket}/{key}`` objects with GCS
(``x-goog-if-generation-match``) and S3 (``If-None-Match: *``) conditional
creates, prefix listing, and per-object generation numbers — plus the
fault-injection hooks the reference exercises through fake Hadoop
filesystems (``LogStoreSuite.scala:293-339``):

* ``fail_next(n, status)`` — fail the next *n* requests with an HTTP status
  (or, with ``status=0``, drop the connection mid-response);
* ``drop_response_next_put()`` — **commit** the next PUT server-side but
  sever the connection before the client sees the response: the
  lost-200 ambiguity a real store can produce;
* ``before_put`` — callback run under no lock before the conditional check,
  to widen race windows deterministically.

Concurrency: one server-wide mutex around each object mutation makes the
conditional PUT check-and-set atomic, which is exactly the guarantee GCS
generation-match gives per object.
"""
from __future__ import annotations

import json
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple

__all__ = ["ObjectStoreEmulator"]


class _Object:
    __slots__ = ("data", "generation", "updated_ms")

    def __init__(self, data: bytes, generation: int, updated_ms: int):
        self.data = data
        self.generation = generation
        self.updated_ms = updated_ms


class ObjectStoreEmulator:
    """A threaded HTTP object store bound to 127.0.0.1:<free port>."""

    def __init__(self):
        self._objects: Dict[Tuple[str, str], _Object] = {}
        self._mutex = threading.Lock()
        self._generation = 0
        self._clock_ms = 0
        self.request_count = 0
        # fault injection
        self._fail_budget = 0
        self._fail_status = 503
        self._drop_next_put = False
        self.before_put: Optional[Callable[[str, str], None]] = None

        emulator = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # silence request logging in tests
                pass

            def _split(self) -> Tuple[str, str, dict]:
                parsed = urllib.parse.urlparse(self.path)
                parts = parsed.path.lstrip("/").split("/", 1)
                bucket = parts[0]
                key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
                query = urllib.parse.parse_qs(parsed.query, keep_blank_values=True)
                return bucket, key, query

            def _respond(self, status: int, body: bytes = b"",
                         content_type: str = "application/octet-stream") -> None:
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if self.command != "HEAD":
                    self.wfile.write(body)

            def _maybe_fail(self) -> bool:
                with emulator._mutex:
                    emulator.request_count += 1
                    if emulator._fail_budget > 0:
                        emulator._fail_budget -= 1
                        status = emulator._fail_status
                    else:
                        return False
                if status == 0:
                    # drop the connection without any response
                    self.close_connection = True
                    self.connection.close()
                    return True
                self._respond(status, b"injected failure")
                return True

            def do_GET(self):
                if self._maybe_fail():
                    return
                bucket, key, query = self._split()
                if not key and ("list" in query or "prefix" in query):
                    prefix = query.get("prefix", [""])[0]
                    start_after = query.get("start-after-name", [""])[0]
                    with emulator._mutex:
                        objs = [
                            {"name": k, "size": len(o.data), "updated": o.updated_ms,
                             "generation": o.generation}
                            for (b, k), o in emulator._objects.items()
                            if b == bucket and k.startswith(prefix)
                            and k[len(prefix):] >= start_after
                        ]
                        prefix_exists = any(
                            b == bucket and k.startswith(prefix)
                            for (b, k) in emulator._objects
                        )
                    body = json.dumps({"objects": sorted(objs, key=lambda o: o["name"]),
                                       "prefix_exists": prefix_exists})
                    self._respond(200, body.encode(), "application/json")
                    return
                with emulator._mutex:
                    obj = emulator._objects.get((bucket, key))
                if obj is None:
                    self._respond(404)
                else:
                    self._respond(200, obj.data)

            def do_HEAD(self):
                if self._maybe_fail():
                    return
                bucket, key, _ = self._split()
                with emulator._mutex:
                    obj = emulator._objects.get((bucket, key))
                self._respond(404 if obj is None else 200)

            def do_PUT(self):
                if self._maybe_fail():
                    return
                bucket, key, _ = self._split()
                length = int(self.headers.get("Content-Length", 0))
                data = self.rfile.read(length)
                gen_match = self.headers.get("x-goog-if-generation-match")
                if_none_match = self.headers.get("If-None-Match")
                conditional = gen_match == "0" or if_none_match == "*"
                if emulator.before_put is not None:
                    emulator.before_put(bucket, key)
                with emulator._mutex:
                    exists = (bucket, key) in emulator._objects
                    if conditional and exists:
                        committed = False
                        status = 412
                    else:
                        emulator._generation += 1
                        # real wall-clock mtimes (retention/cleanup logic
                        # compares them to now), kept strictly increasing
                        emulator._clock_ms = max(
                            int(time.time() * 1000), emulator._clock_ms + 1
                        )
                        emulator._objects[(bucket, key)] = _Object(
                            data, emulator._generation, emulator._clock_ms
                        )
                        committed = True
                        status = 200
                    drop = emulator._drop_next_put and committed
                    if drop:
                        emulator._drop_next_put = False
                if drop:
                    self.close_connection = True
                    self.connection.close()
                    return
                self._respond(status)

            def do_DELETE(self):
                if self._maybe_fail():
                    return
                bucket, key, _ = self._split()
                with emulator._mutex:
                    existed = emulator._objects.pop((bucket, key), None) is not None
                self._respond(204 if existed else 404)

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name="delta-object-store-http")

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "ObjectStoreEmulator":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def __enter__(self) -> "ObjectStoreEmulator":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def endpoint(self) -> str:
        host, port = self._server.server_address
        return f"http://{host}:{port}"

    # -- fault injection ---------------------------------------------------

    def fail_next(self, n: int, status: int = 503) -> None:
        with self._mutex:
            self._fail_budget = n
            self._fail_status = status

    def drop_response_next_put(self) -> None:
        with self._mutex:
            self._drop_next_put = True

    # -- inspection --------------------------------------------------------

    def object_count(self) -> int:
        with self._mutex:
            return len(self._objects)

    def get_object(self, bucket: str, key: str) -> Optional[bytes]:
        with self._mutex:
            obj = self._objects.get((bucket, key))
            return None if obj is None else obj.data
