"""ALTER TABLE commands — properties, columns, constraints.

Mirrors `commands/alterDeltaTableCommands.scala:68-578`: SET/UNSET
TBLPROPERTIES, ADD COLUMNS, CHANGE COLUMN (comment/nullability/type per the
`can_change_data_type` rules), ADD/DROP CONSTRAINT. Each is one metadata-only
transaction.
"""
from __future__ import annotations

from dataclasses import replace
from typing import Dict, List, Optional, Sequence

from delta_tpu.commands import operations as ops
from delta_tpu.expr.parser import parse_predicate
from delta_tpu.expr.vectorized import boolean_mask
from delta_tpu.schema import schema_utils
from delta_tpu.schema.constraints import CONSTRAINT_PROP_PREFIX
from delta_tpu.schema.types import StructField, StructType
from delta_tpu.utils.errors import DeltaAnalysisError

__all__ = [
    "set_table_properties",
    "unset_table_properties",
    "add_columns",
    "change_column",
    "add_constraint",
    "drop_constraint",
]


def set_table_properties(delta_log, properties: Dict[str, str]) -> int:
    def body(txn):
        meta = txn.metadata
        cfg = dict(meta.configuration or {})
        cfg.update({k: str(v) for k, v in properties.items()})
        txn.update_metadata(replace(meta, configuration=cfg))
        return txn.commit([], ops.SetTableProperties(properties))

    return delta_log.with_new_transaction(body)


def unset_table_properties(delta_log, keys: Sequence[str], if_exists: bool = False) -> int:
    def body(txn):
        meta = txn.metadata
        cfg = dict(meta.configuration or {})
        norm = {k.lower(): k for k in cfg}
        for k in keys:
            actual = norm.get(k.lower())
            if actual is None:
                if not if_exists:
                    raise DeltaAnalysisError(
                        f"Attempted to unset non-existent property {k!r}"
                    )
                continue
            del cfg[actual]
        txn.update_metadata(replace(meta, configuration=cfg))
        return txn.commit([], ops.UnsetTableProperties(list(keys), if_exists))

    return delta_log.with_new_transaction(body)


def add_columns(delta_log, new_fields: Sequence[StructField]) -> int:
    """ADD COLUMNS — appended at the end (`:163`); new columns must be
    nullable (existing files have no values for them)."""

    def body(txn):
        meta = txn.metadata
        schema = meta.schema
        for f in new_fields:
            if not f.nullable:
                raise DeltaAnalysisError(
                    f"ADD COLUMNS requires nullable columns, {f.name} is NOT NULL"
                )
            if f.name in schema:
                raise DeltaAnalysisError(f"Column {f.name} already exists")
            schema = schema_utils.add_column(schema, f)
        txn.update_metadata(replace(meta, schema_string=schema.to_json()))
        op = ops.AddColumns(
            [{"column": f.json_value()} for f in new_fields]
        )
        return txn.commit([], op)

    return delta_log.with_new_transaction(body)


def change_column(
    delta_log,
    name: str,
    new_type=None,
    nullable: Optional[bool] = None,
    comment: Optional[str] = None,
) -> int:
    """CHANGE COLUMN (`:251`): widen type (int→long etc.), relax nullability
    (never tighten — existing data may violate it), set a comment."""

    def body(txn):
        meta = txn.metadata
        schema = meta.schema
        field = schema_utils.find_field(schema, name)
        if field is None:
            raise DeltaAnalysisError(f"Column {name!r} not found")
        new_field = field
        if new_type is not None and new_type != field.data_type:
            if not schema_utils.can_change_data_type(field.data_type, new_type):
                raise DeltaAnalysisError(
                    f"Cannot change column {name} from "
                    f"{field.data_type.simple_string()} to {new_type.simple_string()}"
                )
            new_field = replace(new_field, data_type=new_type)
        if nullable is not None:
            if not nullable and field.nullable:
                raise DeltaAnalysisError(
                    f"Cannot change nullable column {name} to NOT NULL"
                )
            new_field = replace(new_field, nullable=nullable)
        if comment is not None:
            md = dict(new_field.metadata or {})
            md["comment"] = comment
            new_field = replace(new_field, metadata=md)
        fields = [
            new_field if f.name.lower() == field.name.lower() else f
            for f in schema.fields
        ]
        txn.update_metadata(replace(meta, schema_string=StructType(fields).to_json()))
        op = ops.ChangeColumn(name, new_field.json_value())
        return txn.commit([], op)

    return delta_log.with_new_transaction(body)


def add_constraint(delta_log, name: str, expr_sql: str) -> int:
    """ADD CONSTRAINT (`:519`): validates existing rows satisfy the check
    before committing, like the reference (which runs a full scan)."""
    import pyarrow.compute as pc

    from delta_tpu.exec.scan import scan_to_table

    key = CONSTRAINT_PROP_PREFIX + name.lower()

    def body(txn):
        meta = txn.metadata
        cfg = dict(meta.configuration or {})
        if any(k.lower() == key for k in cfg):
            raise DeltaAnalysisError(f"Constraint '{name}' already exists")
        expr = parse_predicate(expr_sql)
        existing = scan_to_table(txn.snapshot)
        if existing.num_rows:
            ok = boolean_mask(expr, existing)
            bad = (pc.sum(pc.invert(ok)).as_py() or 0)
            if bad:
                raise DeltaAnalysisError(
                    f"{bad} rows in the table violate the new CHECK constraint "
                    f"{expr_sql!r}"
                )
        txn.read_whole_table()
        cfg[key] = expr_sql
        txn.update_metadata(replace(meta, configuration=cfg))
        return txn.commit([], ops.AddConstraint(name, expr_sql))

    return delta_log.with_new_transaction(body)


def drop_constraint(delta_log, name: str, if_exists: bool = True) -> int:
    key = CONSTRAINT_PROP_PREFIX + name.lower()

    def body(txn):
        meta = txn.metadata
        cfg = dict(meta.configuration or {})
        actual = next((k for k in cfg if k.lower() == key), None)
        if actual is None:
            if if_exists:
                return txn.commit([], ops.DropConstraint(name, None))
            raise DeltaAnalysisError(f"Constraint '{name}' does not exist")
        expr = cfg.pop(actual)
        txn.update_metadata(replace(meta, configuration=cfg))
        return txn.commit([], ops.DropConstraint(name, expr))

    return delta_log.with_new_transaction(body)
