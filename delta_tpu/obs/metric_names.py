"""Single catalog of every observability metric name and public entry point.

The AST lint in ``tests/test_telemetry.py`` enforces that (a) every string
constant passed to ``set_gauge`` anywhere in ``delta_tpu/`` appears in
:data:`GAUGES`, (b) every counter bumped from ``delta_tpu/obs/`` (and the
maintenance/conflict counters wired for the doctor) appears in
:data:`COUNTERS`, (c) the INVERSE pass — every constant-string
``bump_counter`` / ``observe`` call site engine-wide resolves to
:data:`COUNTERS` ∪ :data:`ENGINE_COUNTERS` / :data:`HISTOGRAMS` — so no
metric can ship un-cataloged, and (d) each ``obs/`` module's ``__all__``
matches :data:`PUBLIC_API` — so dashboards and the doctor never chase
stringly-typed drift: a renamed gauge fails the suite, not a Grafana panel.

``table.health.*`` gauges are emitted by :func:`delta_tpu.obs.doctor.doctor`
(labeled by table path) and validated against this catalog at publish time.
"""
from __future__ import annotations

__all__ = ["GAUGES", "COUNTERS", "ENGINE_COUNTERS", "HISTOGRAMS",
           "PUBLIC_API", "DESCRIPTIONS", "health_gauge"]

#: Every labeled gauge the engine publishes.
GAUGES = frozenset({
    # -- doctor: table-health gauges (obs/doctor.py, label: path) --------
    "table.health.severity",
    "table.health.files.count",
    "table.health.files.bytes",
    "table.health.checkpoint.commitsSince",
    "table.health.checkpoint.tailBytes",
    "table.health.checkpoint.tailFiles",
    "table.health.smallFiles.count",
    "table.health.smallFiles.bytes",
    "table.health.smallFiles.estReduction",
    "table.health.dv.files",
    "table.health.dv.deletedRows",
    "table.health.dv.deletedPct",
    "table.health.dv.filesPastPurge",
    "table.health.stats.coveragePct",
    "table.health.stats.parsedPct",
    "table.health.partition.count",
    "table.health.partition.gini",
    "table.health.tombstones.count",
    "table.health.tombstones.bytes",
    "table.health.protocol.minReader",
    "table.health.protocol.minWriter",
    # -- doctor: distributed-execution supervision (obs/doctor
    #    ._dim_distributed, process-wide counters) ----------------------
    "table.health.distributed.itemsRetried",
    "table.health.distributed.itemsQuarantined",
    "table.health.distributed.itemsSpeculated",
    "table.health.distributed.speculationWins",
    "table.health.distributed.slicesRecovered",
    "table.health.distributed.degraded",
    # -- doctor: device residency pressure (obs/doctor._dim_device) ------
    "table.health.device.hbmBytes",
    "table.health.device.keyCacheBytes",
    "table.health.device.stateCacheBytes",
    "table.health.device.scratchBytes",
    "table.health.device.budgetBytes",
    "table.health.device.pressure",
    "table.health.device.worstDevice",
    "table.health.device.worstDeviceBytes",
    "table.health.device.worstDevicePressure",
    # -- device-memory ledger (obs/hbm_ledger, process-wide) -------------
    "device.hbm.keyCacheBytes",
    "device.hbm.stateCacheBytes",
    "device.hbm.scratchBytes",
    "device.hbm.columnCacheBytes",
    # -- router audit + calibration (obs/router_audit, obs/calibration) --
    "router.missRate",
    "router.calibration",        # label: constant
    # -- streaming consumer lag (streaming/source.py, label: path) -------
    "streaming.source.backlogFiles",
    "streaming.source.backlogBytes",
    "streaming.source.lastBatchVersionLag",
    # -- maintenance recency (commands/optimize.py, vacuum.py) -----------
    "table.maintenance.lastOptimizeVersion",
    "table.maintenance.lastVacuumTimestamp",
    # -- static analysis (analysis/__init__.publish_metrics, label: rule) -
    "analysis.findings",
    # -- autopilot maintenance scheduler (delta_tpu/autopilot, label: path)
    "autopilot.lastRunTimestamp",
    # -- fleet observability plane (obs/fleet, obs/timeseries, obs/slo) ---
    "fleet.tables",               # live registered DeltaLogs
    "obs.scrape.series",          # series held in the scrape rings
    "slo.burnRate",               # labels: objective, table, window
    "slo.alerts",                 # alerts currently firing
    # -- shadow optimizer (delta_tpu/replay, label: path) -----------------
    "shadow.topScore",            # best candidate score of the last run
    # -- resident key cache per-table residency (ops/key_cache, label: table)
    "keyCache.residentBytes",
    # -- scan column cache per-table residency (ops/column_cache, label: table)
    "columnCache.residentBytes",
})

#: Counters introduced by the obs layer and its doctor feeds.
COUNTERS = frozenset({
    "obs.incidents.written",
    "obs.server.requests",
    # -- distributed-trace spool (obs/trace_store) ------------------------
    "trace.spansSpooled",         # spans appended to the JSONL spool
    "trace.spansDropped",         # spans dropped by the byte cap / IO error
    "commit.conflicts",
    "maintenance.optimize.filesCompacted",
    "maintenance.optimize.filesWritten",
    "maintenance.vacuum.filesDeleted",
    "maintenance.vacuum.bytesReclaimed",
    # -- robustness layer (utils/retries, storage/faults, txn) -----------
    "storage.retry.attempts",     # one per backoff sleep, any store
    "storage.retry.exhausted",    # gave up: surfaced to the caller
    "faults.injected",            # deterministic fault injector fired
    "commit.reconciled",          # ambiguous commit resolved via txnId
    # -- predicate pushdown synthesis (obs/scan_report.record_rewrite_fired)
    "scan.rewrites.fired",        # synthesized rewrite excluded data in a scan
    # -- device MERGE router + resident key cache (commands/merge.py,
    #    ops/key_cache.py) — `auto_used_device` made observable on
    #    production tables via /metrics and flight-recorder incidents
    "merge.device.engaged",       # a device join produced this merge's pairs
    "merge.device.declined",      # link cost model chose the host
    "merge.device.cacheHit",      # engaged from an HBM-resident key lane
    "merge.keyCache.builds",      # cold key-lane builds (inline or bg)
    "merge.keyCache.advances",    # incremental log-tail applications
    "merge.keyCache.invalidations",  # entries dropped by a rewrite epoch bump
    # -- router audit ledger + calibrator (obs/router_audit, obs/calibration)
    "router.audits",              # one per routed decision recorded
    "router.misses",              # hindsight: rejected route predicted faster
    "router.calibration.updates",  # EWMA samples folded into the state
    # -- workload journal + layout advisor (obs/journal, obs/advisor) -----
    "journal.entries",            # entries written to journal segments
    "journal.bytes.written",      # JSONL bytes appended
    "journal.segments.written",   # segment files opened
    "journal.segments.swept",     # segments deleted by the size/age sweep
    "journal.entriesDropped",     # buffer cap hit or unwritable directory
    "journal.literalSamples",     # reservoir-sampled concrete predicates
    "advisor.runs",               # advise() invocations
    "advisor.recommendations",    # recommendations emitted across runs
    # -- autopilot maintenance scheduler (delta_tpu/autopilot) ------------
    "autopilot.runs",             # run_once passes (daemon ticks + manual)
    "autopilot.actions.planned",  # actions surviving cooldown into a plan
    "autopilot.actions.executed",  # actions that ran to completion
    "autopilot.actions.skipped",  # cost cap / run budget aborts
    "autopilot.actions.deferred",  # not-quiet / backoff / busy deferrals
    "autopilot.actions.failed",   # genuine execution failures
    "autopilot.contentionAborts",  # maintenance commits that lost to
                                   # foreground writers and backed off
    # -- fleet observability plane (obs/fleet, obs/timeseries, obs/slo) ---
    "obs.server.clientAborts",    # responses cut short by a client hangup
    "obs.scrape.ticks",           # scraper passes over the registry
    "fleet.sweeps",               # fleet_doctor/fleet_advise sweeps run
    "slo.evaluations",            # SLO evaluation passes
    "slo.alerts.fired",           # alerts that crossed both burn windows
    "slo.alerts.cleared",         # alerts cleared by fast-window recovery
    # -- workload replay + shadow optimizer (delta_tpu/replay) ------------
    "replay.traces.built",        # WorkloadTraces reconstructed from journals
    "replay.scans.replayed",      # trace scans re-executed in replays
    "replay.literals.synthesized",  # predicates rebuilt from file stats
    "replay.capacity.runs",       # time-compressed SLO capacity replays
    "shadow.runs",                # shadow_run scorecards produced
    "shadow.candidates",          # candidate configurations scored
})

#: Every OTHER counter the engine bumps by constant name — the inverse lint
#: (tests/test_telemetry.py) fails on any ``bump_counter`` call site whose
#: name is in neither this set nor :data:`COUNTERS`. Dynamic families
#: (``logstore.{op}.calls``/``.bytes``) are f-strings and out of lint scope.
ENGINE_COUNTERS = frozenset({
    "checkpoint.parts",
    "checkpoint.actions",
    "checkpoint.written",
    "checkpoint.incremental.built",
    "checkpoint.incremental.fallback",
    "commit.total",
    "commit.retries",
    "convert.stats.fromFooter",
    "convert.stats.fromDecode",
    "footerCache.hits",
    "footerCache.misses",
    "footerCache.evictions",
    "log.update.coalesced",
    "log.update.installed",
    "log.update.unchanged",
    "parquet.files.written",
    "parquet.bytes.written",
    "parquet.rows.written",
    "scan.files.read",
    "scan.bytes.read",
    "scan.bytes.skipped",
    "scan.bytes.deviceSkipped",
    "scan.bytes.deviceSurvivor",
    "scan.rowgroups.total",
    "scan.rowgroups.pruned",
    "scan.rowgroups.lateSkipped",
    "scan.rowgroups.deviceSkipped",
    "scan.device.engaged",
    "scan.device.declined",
    "scan.device.fallback",
    "columnCache.hits",
    "columnCache.misses",
    "columnCache.evictions",
    "columnCache.invalidations",
    "scan.rewrites.synthesized",
    "scan.rewrites.unknown",
    "stateCache.builds",
    "stateCache.plan.resident",
    "stateCache.plan.fallback.lowering",
    "stateCache.plan.fallback.noentry",
    "stateCache.plan.fallback.version",
    "stateCache.scan.resident",
    "stateCache.scan.fallback.lowering",
    "stateCache.scan.fallback.noentry",
    "stateCache.scan.fallback.version",
    "stateExport.statsLanes.struct",
    "stateExport.statsLanes.json",
    "stateExport.statsLanes.mixed",
    "stateExport.statsLanes.us",
    "streaming.sink.batches",
    # -- distributed executor + sharded planning (parallel/executor,
    #    parallel/distributed, ops/state_cache sharded plan) --------------
    "dist.jobs",                  # sharded jobs launched (run_sharded calls)
    "dist.items",                 # work items executed across all jobs
    "dist.steals",                # items stolen from another worker's deque
    "dist.plan.sharded",          # plan batches served by the shard_map kernel
    "dist.merge.filesProbed",     # candidate files probed by the distributed
                                  # MERGE touched-files pass
    "dist.optimize.groups",       # OPTIMIZE bin-pack groups rewritten by
                                  # sharded workers
    "dist.commit.fanin",          # distributed-job commits funneled through
                                  # the group-commit coordinator
    # -- distributed-execution supervision (parallel/executor item retry +
    #    quarantine, heartbeat speculation; parallel/leases slice recovery;
    #    the graceful-degradation ladder) -------------------------------
    "dist.items.retried",         # transient item attempts retried in place
    "dist.items.quarantined",     # poison items quarantined off a job
    "dist.items.speculated",      # stuck items speculatively re-dispatched
    "dist.speculation.wins",      # speculative attempts that won the race
    "dist.slice.recovered",       # orphaned host slices re-executed by the
                                  # coordinator after lease expiry
    "dist.lease.swept",           # expired _dist/ lease files swept
    "dist.degraded.pool",         # sharded jobs degraded to inline execution
    "dist.degraded.plan",         # shard_map plans degraded to the host pass
    "dist.degraded.probe",        # MERGE probes degraded to the all-files
                                  # superset
    "dist.degraded.lease",        # slices run uncovered after lease-write
                                  # failure
})

#: Every histogram observed by constant name (``telemetry.observe``).
HISTOGRAMS = frozenset({
    "commit.group.batchSize",
    "commit.queueWaitMs",
    "delta.checkpoint.duration_ms",
    "delta.commit.duration_ms",
    "delta.scan.planning.duration_ms",
    "delta.streaming.sink.batch_ms",
    "delta.streaming.source.batch_ms",
    "dist.item.duration_ms",
    "journal.flushKb",
    "router.predicted_ms",
    "router.actual_ms",
})

#: Public surface of each obs module, lint-matched against its ``__all__``.
PUBLIC_API = {
    "doctor": ("HealthDimension", "TableHealthReport", "doctor",
               "SEVERITY_RANK"),
    "scan_report": ("ScanReport", "last_scan_report", "clear_last_report",
                    "start_report", "current_report", "contribute",
                    "record_rewrite_fired", "finish_report"),
    "server": ("ObsServer", "start_server", "stop_server"),
    "flight_recorder": ("install", "uninstall", "record_incident",
                        "incident_files"),
    "metric_names": ("GAUGES", "COUNTERS", "ENGINE_COUNTERS", "HISTOGRAMS",
                     "PUBLIC_API", "DESCRIPTIONS", "health_gauge"),
    "router_audit": ("RouterAudit", "record_audit", "recent_audits",
                     "clear_audits", "audit_stats", "last_audit"),
    "calibration": ("enabled", "ingest", "state_path", "load_state",
                    "save_state", "apply_state", "current_state", "reset"),
    "hbm_ledger": ("Account", "adjust", "totals", "budget_bytes",
                   "device_totals", "worst_device",
                   "key_cache_allowance", "column_cache_allowance",
                   "over_budget", "maybe_relieve", "reset"),
    "journal": ("enabled", "journal_dir", "predicate_fingerprint",
                "record_scan", "record_commit", "record_dml",
                "record_router", "record_autopilot", "record_shadow",
                "record_dist", "attempt_state", "record_attempt", "flush",
                "read_entries", "sweep", "live_writer_spared", "reset"),
    "advisor": ("Recommendation", "AdvisorReport", "advise"),
    "actions": ("ActionSpec", "MaintenanceAction", "CATALOG", "CATALOG_REF",
                "RECOMMENDATION_ACTIONS", "COOLDOWN_PHASES", "spec",
                "remedy_name", "executable_kinds", "action_key",
                "attempts_in_cooldown"),
    "fleet": ("enabled", "register", "unregister", "live_tables",
              "table_label", "label_path", "fleet_doctor", "fleet_advise",
              "fleet_status", "FleetEntry", "FleetReport", "reset"),
    "timeseries": ("Scraper", "start_scraper", "stop_scraper", "scrape_once",
                   "scrape_count", "counter_window", "quantile_window",
                   "histogram_labels", "series_snapshot", "reset"),
    "slo": ("SloObjective", "SloAlert", "SloBreach", "objectives",
            "evaluate", "active_alerts", "priority_boost", "firing_count",
            "status", "reset"),
    "trace_store": ("install", "uninstall", "read_spools", "recent_traces",
                    "stitch_trace", "analyze_trace", "reset"),
}


#: One-line description per catalog entry, emitted as ``# HELP`` lines in
#: the Prometheus exposition (``telemetry.prometheus_text``) so scrapers
#: classify and document every series. The lint in ``tests/test_telemetry``
#: requires a non-empty description for EVERY catalog name — a new metric
#: cannot ship undocumented.
DESCRIPTIONS = {
    # gauges — doctor
    "table.health.severity": "Worst doctor dimension severity (0 ok, 1 warn, 2 critical).",
    "table.health.files.count": "Live data files in the current snapshot.",
    "table.health.files.bytes": "Live data bytes in the current snapshot.",
    "table.health.checkpoint.commitsSince": "Commits replayed after the last checkpoint on a cold build.",
    "table.health.checkpoint.tailBytes": "Log-tail bytes re-read per snapshot update.",
    "table.health.checkpoint.tailFiles": "Log-tail commit files after the last checkpoint.",
    "table.health.smallFiles.count": "Files below the OPTIMIZE compaction floor.",
    "table.health.smallFiles.bytes": "Bytes held in small files.",
    "table.health.smallFiles.estReduction": "Estimated file-count reduction OPTIMIZE would achieve.",
    "table.health.dv.files": "Files carrying deletion vectors.",
    "table.health.dv.deletedRows": "Rows soft-deleted via deletion vectors.",
    "table.health.dv.deletedPct": "Soft-deleted fraction of the table's physical rows.",
    "table.health.dv.filesPastPurge": "Files past the per-file PURGE threshold.",
    "table.health.stats.coveragePct": "Fraction of files carrying min/max stats.",
    "table.health.stats.parsedPct": "Fraction of files whose stats parse cleanly.",
    "table.health.partition.count": "Distinct partitions in the snapshot.",
    "table.health.partition.gini": "Byte-skew Gini coefficient across partitions.",
    "table.health.tombstones.count": "Removed files awaiting retention expiry.",
    "table.health.tombstones.bytes": "Bytes held by tombstoned files.",
    "table.health.protocol.minReader": "Table protocol minimum reader version.",
    "table.health.protocol.minWriter": "Table protocol minimum writer version.",
    "table.health.device.hbmBytes": "Device-resident bytes attributed while diagnosing this table.",
    "table.health.device.keyCacheBytes": "Key-cache slab bytes resident on device.",
    "table.health.device.stateCacheBytes": "State-cache lane bytes resident on device.",
    "table.health.device.scratchBytes": "Transient probe-scratch bytes resident on device.",
    "table.health.device.budgetBytes": "Configured soft HBM budget (0 = unlimited).",
    "table.health.device.pressure": "Resident bytes over the soft budget (fraction).",
    "table.health.device.worstDevice": "Index of the most-loaded device in the per-device HBM breakdown.",
    "table.health.device.worstDeviceBytes": "Resident bytes on the most-loaded device.",
    "table.health.device.worstDevicePressure": "Worst device's bytes over its fair share of the soft budget.",
    # gauges — device ledger / router / streaming / maintenance
    "device.hbm.keyCacheBytes": "Process-wide key-cache bytes resident on device.",
    "device.hbm.stateCacheBytes": "Process-wide state-cache bytes resident on device.",
    "device.hbm.scratchBytes": "Process-wide transient scratch bytes resident on device.",
    "device.hbm.columnCacheBytes": "Process-wide scan column-cache lane bytes resident on device.",
    "columnCache.residentBytes": "HBM-resident scan column-lane bytes per table.",
    "router.missRate": "Fraction of routed decisions where a rejected route predicted faster.",
    "router.calibration": "Installed calibrated value per link constant.",
    "streaming.source.backlogFiles": "Committed files not yet served to the streaming consumer.",
    "streaming.source.backlogBytes": "Committed bytes not yet served to the streaming consumer.",
    "streaming.source.lastBatchVersionLag": "Table versions between the last served batch and the head.",
    "table.maintenance.lastOptimizeVersion": "Table version written by the last OPTIMIZE.",
    "table.maintenance.lastVacuumTimestamp": "Wall-clock ms of the last VACUUM.",
    "analysis.findings": "Non-baselined static-analysis findings per rule (tools/analyze.py).",
    "fleet.tables": "DeltaLog handles registered in the process-wide fleet registry.",
    "obs.scrape.series": "Distinct series retained in the obs scraper's in-memory rings.",
    "slo.burnRate": "Observed-over-objective burn rate per objective/table/window.",
    "slo.alerts": "SLO alerts currently firing.",
    "shadow.topScore": "Best candidate score of the table's latest shadow run.",
    "keyCache.residentBytes": "HBM-resident key-cache slab bytes per table.",
    # counters — obs layer
    "obs.incidents.written": "Flight-recorder incident files written.",
    "obs.server.requests": "HTTP requests served by the obs endpoint.",
    "trace.spansSpooled": "Sampled spans appended to the distributed-trace JSONL spool.",
    "trace.spansDropped": "Sampled spans dropped by the spool byte cap or an IO error.",
    "commit.conflicts": "Commits aborted on a genuine logical conflict.",
    "maintenance.optimize.filesCompacted": "Files removed by OPTIMIZE compaction.",
    "maintenance.optimize.filesWritten": "Files written by OPTIMIZE compaction.",
    "maintenance.vacuum.filesDeleted": "Unreferenced files deleted by VACUUM.",
    "maintenance.vacuum.bytesReclaimed": "Bytes reclaimed by VACUUM.",
    "storage.retry.attempts": "Transient-failure retry sleeps across all stores.",
    "storage.retry.exhausted": "Retry policies that gave up and surfaced the error.",
    "faults.injected": "Deterministic fault-injector activations.",
    "commit.reconciled": "Ambiguous commit outcomes resolved via the txnId token.",
    "merge.device.engaged": "MERGEs whose join pairs came from a device join.",
    "merge.device.declined": "MERGEs where the cost model chose the host join.",
    "merge.device.cacheHit": "Device MERGEs served from an HBM-resident key lane.",
    "merge.keyCache.builds": "Cold resident key-lane builds.",
    "merge.keyCache.advances": "Incremental log-tail applications to a key lane.",
    "merge.keyCache.invalidations": "Key-cache entries dropped by a rewrite epoch bump.",
    "router.audits": "Routed decisions recorded in the audit ledger.",
    "router.misses": "Audits where a rejected route's prediction beat the actual.",
    "router.calibration.updates": "EWMA samples folded into the calibration state.",
    "journal.entries": "Workload-journal entries written to segments.",
    "journal.bytes.written": "JSONL bytes appended to journal segments.",
    "journal.segments.written": "Journal segment files opened.",
    "journal.segments.swept": "Journal segments deleted by the size/age sweep.",
    "journal.entriesDropped": "Journal entries dropped (buffer cap or unwritable dir).",
    "journal.literalSamples": "Concrete predicate SQLs persisted by the literal-sample reservoir.",
    "advisor.runs": "Layout-advisor invocations.",
    "advisor.recommendations": "Recommendations emitted by the advisor.",
    "autopilot.lastRunTimestamp": "Wall-clock ms of the last autopilot pass over the table.",
    "autopilot.runs": "Autopilot maintenance passes (daemon ticks + manual run_once).",
    "autopilot.actions.planned": "Maintenance actions planned past the cooldown filter.",
    "autopilot.actions.executed": "Maintenance actions executed to completion.",
    "autopilot.actions.skipped": "Maintenance actions aborted by a cost cap or run budget.",
    "autopilot.actions.deferred": "Maintenance actions deferred (window not quiet, backoff, or busy).",
    "autopilot.actions.failed": "Maintenance actions that failed outright.",
    "autopilot.contentionAborts": "Maintenance commits that lost to foreground writers and backed off.",
    "obs.server.clientAborts": "HTTP responses cut short by a client disconnect (BrokenPipe/ConnectionReset).",
    "obs.scrape.ticks": "Scraper passes snapshotting the metrics registry into rings.",
    "fleet.sweeps": "Fleet-wide doctor/advisor sweeps over the table registry.",
    "slo.evaluations": "SLO burn-rate evaluation passes.",
    "slo.alerts.fired": "SLO alerts fired (both burn windows crossed 1.0).",
    "slo.alerts.cleared": "SLO alerts cleared by fast-window recovery below the hysteresis ratio.",
    "replay.traces.built": "WorkloadTraces reconstructed from table journals.",
    "replay.scans.replayed": "Trace scan events re-executed through the real scan path.",
    "replay.literals.synthesized": "Scan predicates rehydrated via stats-guided literal synthesis.",
    "replay.capacity.runs": "Time-compressed capacity replays against the SLO plane.",
    "shadow.runs": "Shadow-optimizer what-if runs completed.",
    "shadow.candidates": "Candidate configurations scored across shadow runs.",
    # counters — engine
    "checkpoint.parts": "Checkpoint part files written.",
    "checkpoint.actions": "Actions serialized into checkpoints.",
    "checkpoint.written": "Checkpoints completed.",
    "checkpoint.incremental.built": "Checkpoints built incrementally from a cached base plus tail.",
    "checkpoint.incremental.fallback": "Incremental checkpoint builds that fell back to full reconstruction.",
    "commit.total": "Commits attempted through the transaction pipeline.",
    "commit.retries": "Extra commit attempts after lost races.",
    "convert.stats.fromFooter": "CONVERT stats derived from Parquet footers.",
    "convert.stats.fromDecode": "CONVERT stats derived via full decode fallback.",
    "footerCache.hits": "Parquet footer cache hits.",
    "footerCache.misses": "Parquet footer cache misses (footer parsed).",
    "footerCache.evictions": "Parquet footers evicted by the LRU bound.",
    "log.update.coalesced": "Log updates served by a concurrent racer's just-completed listing.",
    "log.update.installed": "Log updates that installed a newer snapshot.",
    "log.update.unchanged": "Log updates that found no new commits.",
    "parquet.files.written": "Parquet data files written.",
    "parquet.bytes.written": "Parquet bytes written.",
    "parquet.rows.written": "Rows written to Parquet files.",
    "scan.files.read": "Data files decoded by scans.",
    "scan.bytes.read": "Compressed bytes of files decoded by scans.",
    "scan.bytes.skipped": "Uncompressed bytes skipped by row-group pruning.",
    "scan.bytes.deviceSkipped": "Uncompressed bytes skipped by all-False device residual masks.",
    "scan.bytes.deviceSurvivor": "Survivor row-group bytes host-decoded on the device residual path.",
    "scan.rowgroups.total": "Row groups considered by the second pruning tier.",
    "scan.rowgroups.pruned": "Row groups skipped via footer stats.",
    "scan.rowgroups.lateSkipped": "Row groups skipped by late materialization.",
    "scan.rowgroups.deviceSkipped": "Row groups skipped by all-False device residual masks.",
    "scan.device.engaged": "Scans whose residual mask was computed on device.",
    "scan.device.declined": "Scans where the cost model kept the residual on host.",
    "scan.device.fallback": "Device residual attempts that fell back to the host path.",
    "columnCache.hits": "Scan column-cache lane hits (file, column resident).",
    "columnCache.misses": "Scan column-cache lane misses (cold decode).",
    "columnCache.evictions": "Scan column-cache lanes evicted by the LRU bound.",
    "columnCache.invalidations": "Scan column-cache lanes dropped by a rewrite epoch bump.",
    "scan.rewrites.synthesized": "Conjuncts lowered to stats bounds only via predicate synthesis.",
    "scan.rewrites.fired": "Synthesized rewrites that excluded files or row groups in a scan.",
    "scan.rewrites.unknown": "Conjuncts predicate synthesis still could not lower (kept residual).",
    "stateCache.builds": "Device state-cache lane builds.",
    "stateCache.plan.resident": "Scan plans served from resident lanes.",
    "stateCache.plan.fallback.lowering": "Scan plans that could not lower to ranges.",
    "stateCache.plan.fallback.noentry": "Scan plans with no resident entry.",
    "stateCache.plan.fallback.version": "Scan plans whose entry advanced past the snapshot.",
    "stateCache.scan.resident": "File prunes served from resident lanes.",
    "stateCache.scan.fallback.lowering": "File prunes that could not lower to ranges.",
    "stateCache.scan.fallback.noentry": "File prunes with no resident entry.",
    "stateCache.scan.fallback.version": "File prunes whose entry advanced past the snapshot.",
    "stateExport.statsLanes.struct": "Checkpoint rows decoded from typed struct stats.",
    "stateExport.statsLanes.json": "Checkpoint rows decoded via per-row JSON stats.",
    "stateExport.statsLanes.mixed": "Checkpoint segments mixing struct and JSON stats.",
    "stateExport.statsLanes.us": "Checkpoint stats decoded with microsecond timestamps.",
    "streaming.sink.batches": "Micro-batches written by the streaming sink.",
    # histograms
    "delta.scan.planning.duration_ms": "Scan-planning (file pruning) latency per table (ms).",
    "journal.flushKb": "JSONL KiB per journal flush batch, labeled per table.",
    "commit.group.batchSize": "Transactions written per group-commit batch.",
    "commit.queueWaitMs": "Time a grouped commit waited in the coordinator queue (ms).",
    "delta.checkpoint.duration_ms": "Checkpoint write latency (ms).",
    "delta.commit.duration_ms": "Commit pipeline latency (ms).",
    "delta.streaming.sink.batch_ms": "Streaming sink addBatch latency (ms).",
    "delta.streaming.source.batch_ms": "Streaming source getBatch latency (ms).",
    "router.predicted_ms": "Router-predicted cost of the chosen route (ms).",
    "router.actual_ms": "Measured cost of the chosen route (ms).",
    # distributed executor + sharded planning
    "dist.jobs": "Sharded work-item jobs launched by the distributed executor.",
    "dist.items": "Work items executed across all sharded jobs.",
    "dist.steals": "Work items stolen from another worker's deque (skew relief).",
    "dist.plan.sharded": "Scan-plan batches served by the shard_map pruning kernel.",
    "dist.merge.filesProbed": "Candidate files probed by the distributed MERGE touched-files pass.",
    "dist.optimize.groups": "OPTIMIZE bin-pack groups rewritten by sharded workers.",
    "dist.commit.fanin": "Distributed-job commits funneled through the group-commit coordinator.",
    "dist.items.retried": "Transient work-item attempts retried in place by the executor.",
    "dist.items.quarantined": "Poison work items quarantined off a sharded job.",
    "dist.items.speculated": "Stuck work items speculatively re-dispatched by the supervisor.",
    "dist.speculation.wins": "Speculative re-dispatches that beat the original attempt.",
    "dist.slice.recovered": "Orphaned host slices re-executed after lease expiry.",
    "dist.lease.swept": "Expired distributed-lease files swept from _delta_log/_dist.",
    "dist.degraded.pool": "Sharded jobs that degraded to inline execution after pool failure.",
    "dist.degraded.plan": "shard_map scan plans that degraded to the host fine pass.",
    "dist.degraded.probe": "Distributed MERGE probes that degraded to the all-files superset.",
    "dist.degraded.lease": "Distributed slices run uncovered after a lease-write failure.",
    "dist.item.duration_ms": "Per-work-item wall clock inside the distributed executor (ms).",
    # doctor distributed-supervision dimension (process-wide)
    "table.health.distributed.itemsRetried": "Transient item retries seen by this process's sharded jobs.",
    "table.health.distributed.itemsQuarantined": "Poison items quarantined by this process's sharded jobs.",
    "table.health.distributed.itemsSpeculated": "Stuck items speculatively re-dispatched in this process.",
    "table.health.distributed.speculationWins": "Speculative re-dispatches that won in this process.",
    "table.health.distributed.slicesRecovered": "Orphaned distributed slices recovered by this process.",
    "table.health.distributed.degraded": "Degradation-ladder rungs taken (pool+plan+probe+lease) in this process.",
}


def health_gauge(dimension: str, metric: str) -> str:
    """The catalog-checked gauge name for a doctor metric — raises on a name
    that is not registered, so a new metric cannot ship un-cataloged."""
    name = f"table.health.{dimension}.{metric}"
    if name not in GAUGES:
        raise ValueError(f"gauge {name!r} is not registered in "
                         "delta_tpu/obs/metric_names.py")
    return name
