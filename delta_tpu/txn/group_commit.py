"""Group-commit coordinator: batch concurrent commits into one tail pass.

Ungrouped, each of K concurrent writers pays its own read-tail →
conflict-check → CAS cycle against the log (``doCommitRetryIteratively``,
``OptimisticTransaction.scala:610-642``, mirrored by
``txn/transaction._do_commit_retry``): under contention that costs O(K²)
tail reads plus a retry storm, all serialized on the in-process commit
lock. This module amortizes the cycle: concurrent ``commit()`` calls on one
:class:`~delta_tpu.log.deltalog.DeltaLog` enqueue their **prepared** action
lists; the first enqueuer becomes the *leader*, lingers briefly
(``delta.tpu.commit.group.maxWaitMs``) for the queue to fill, then drains a
batch (``delta.tpu.commit.group.maxBatch``) and, holding the commit lock:

1. reads the log tail **once** — every winning commit between the oldest
   member's read version and the head, each file fetched exactly once into
   a shared tail snapshot;
2. conflict-checks each member against that snapshot *and against the
   batchmates already assigned earlier versions* (the same
   ``txn/conflicts.check_for_conflicts`` matrix — intra-batch conflicts
   surface exactly as they would have had the members raced ungrouped);
3. writes surviving members as **consecutive versions** in one pass — each
   still an atomic create-if-absent, so cross-process exclusion is
   unchanged; per-member ``commitInfo.txnId`` tokens reconcile ambiguous
   creates exactly as in the ungrouped path.

Losers of an *external* race (another process claimed a version mid-batch)
do not each re-read the tail: the leader extends its tail snapshot by just
the new commits and re-attempts the remaining members at bumped versions.

Failure semantics: a member whose conflict check fails gets that exception
(its batchmates are unaffected); an ordinary per-member write failure is
that member's alone; a ``BaseException`` (:class:`SimulatedCrash`,
KeyboardInterrupt — process-death class) aborts the whole batch: the
prefix already written is durable, members whose create landed resolve as
committed (the coordinator knows — a false failure would invite a
duplicate re-commit from a caller surviving the interrupt), and every
unfinished member observes the crash — the crash-between-batch-members
case the torture harness replays. The fault injector draws at ``txn.groupLoop`` once per member
before its create.

Default off (``delta.tpu.commit.group.enabled``); with it off,
``transaction.commit`` never constructs a coordinator and the commit path
is byte-identical to the ungrouped engine.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from delta_tpu.protocol import filenames
from delta_tpu.protocol.actions import Action, actions_from_lines
from delta_tpu.storage import faults as faults_mod
from delta_tpu.txn import conflicts as conflicts_mod
from delta_tpu.txn import transaction as transaction_mod
from delta_tpu.utils.config import conf
from delta_tpu.utils import errors
from delta_tpu.utils import retries as retries_mod
from delta_tpu.utils import telemetry

__all__ = ["GroupCommitCoordinator", "group_commit_enabled"]


def group_commit_enabled() -> bool:
    return conf.get_bool("delta.tpu.commit.group.enabled", False)


@dataclass
class _Pending:
    """One queued transaction: the prepared full action list (CommitInfo
    first — blind-append detection and the txnId token are already baked
    in) plus the slots the leader fills."""

    txn: Any
    actions: List[Action]
    enqueued: float = field(default_factory=time.monotonic)
    done: bool = False
    version: Optional[int] = None
    exc: Optional[BaseException] = None
    batch_size: int = 0
    queue_wait_ms: float = 0.0
    attempts: int = 1
    conflict_check_ms: float = 0.0


class GroupCommitCoordinator:
    """Per-DeltaLog queue + leader election. Thread-safe; one instance per
    :class:`DeltaLog` (lazily created, see ``DeltaLog.group_coordinator``)."""

    #: persistent tail entries kept after a batch (commit files are
    #: immutable, so entries never go stale; bound keeps memory O(1))
    _TAIL_KEEP = 512

    def __init__(self, delta_log):
        self.delta_log = delta_log
        self._cv = threading.Condition()
        self._queue: List[_Pending] = []
        self._leader_active = False
        #: version -> decoded actions, SHARED ACROSS BATCHES: members' read
        #: versions lag by about a round, so successive batches' windows
        #: overlap heavily — without this each batch re-reads ~K files the
        #: previous batch already fetched. Only the (single) leader touches
        #: it, under the commit lock.
        self._tail: Dict[int, List[Action]] = {}

    # -- public ----------------------------------------------------------

    def commit(self, txn, actions: List[Action]) -> int:
        """Enqueue ``txn``'s prepared actions and block until a leader (
        possibly this thread) resolves them; returns the committed version
        or raises the member's failure."""
        p = _Pending(txn=txn, actions=list(actions))
        with self._cv:
            self._queue.append(p)
            self._cv.notify_all()
        try:
            while True:
                with self._cv:
                    if p.done:
                        break
                    if self._leader_active:
                        # a crashed leader marks its whole in-flight batch
                        # done; entries it never drained are re-led by the
                        # next volunteer (possibly this thread, next
                        # iteration)
                        self._cv.wait(0.05)
                        continue
                    self._leader_active = True
                try:
                    self._lead(p)
                finally:
                    with self._cv:
                        self._leader_active = False
                        self._cv.notify_all()
        except BaseException:
            # the caller is abandoning (KeyboardInterrupt while waiting or
            # leading): an entry still in the queue must NOT be committed
            # by a successor leader after the caller observed failure — the
            # app would retry and double-commit. An entry already drained
            # into a leader's in-flight batch stays: its outcome is
            # genuinely ambiguous, exactly like any interrupted commit
            # (per-txn txnId reconciliation covers a retry).
            with self._cv:
                if not p.done:
                    try:
                        self._queue.remove(p)
                    except ValueError:
                        pass
            raise
        if p.exc is not None:
            raise p.exc
        assert p.version is not None
        return p.version

    # -- leader ----------------------------------------------------------

    def _max_batch(self) -> int:
        try:
            n = int(conf.get("delta.tpu.commit.group.maxBatch", 32))
        except (TypeError, ValueError):
            n = 32
        return max(n, 1)

    def _max_wait_s(self) -> float:
        try:
            ms = float(conf.get("delta.tpu.commit.group.maxWaitMs", 2))
        except (TypeError, ValueError):
            ms = 2.0
        return max(ms, 0.0) / 1000.0

    def _lead(self, p: _Pending) -> None:
        """Drain batches until the CALLER's own entry resolves, then hand
        leadership off (a waiting member volunteers the moment
        ``_leader_active`` clears). Draining until the queue is empty
        instead would pin the first volunteer serving everyone else's
        batches under sustained traffic — its own commit latency balloons
        to the whole burst's duration."""
        max_batch = self._max_batch()
        deadline = time.monotonic() + self._max_wait_s()
        with self._cv:
            # accumulation window: give racing writers a moment to join
            while len(self._queue) < max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(remaining)
        while not p.done:
            batch: List[_Pending] = []
            try:
                with self._cv:
                    batch = self._queue[:max_batch]
                    del self._queue[: len(batch)]
                if not batch:
                    return
                self._run_batch(batch)
            except BaseException as e:
                # process-death class (SimulatedCrash, KeyboardInterrupt):
                # handled HERE, around the whole drain+run window, so an
                # interrupt landing between the drain and _run_batch's
                # body cannot strand drained members unresolved (their
                # callers would spin forever). Members whose create
                # already landed resolve as COMMITTED — the coordinator
                # knows they succeeded, and reporting them failed would
                # invite a duplicate re-commit from a caller that survives
                # the interrupt; every unfinished member observes the
                # crash. The LEADER's own thread still re-raises — it is
                # the crashed context (exactly the ungrouped window).
                for q in batch:
                    if not q.done:
                        if q.version is None:
                            q.exc = e
                        q.done = True
                raise
            finally:
                if batch:
                    with self._cv:
                        self._cv.notify_all()

    def _run_batch(self, batch: List[_Pending]) -> None:
        dl = self.delta_log
        t_lead = time.monotonic()
        for p in batch:
            p.batch_size = len(batch)
            p.queue_wait_ms = (t_lead - p.enqueued) * 1000.0
        telemetry.observe("commit.group.batchSize", len(batch),
                          path=dl.data_path)
        with dl.lock:
            # ONE tail read for the whole batch: every winning commit
            # since the oldest member's snapshot, each file fetched
            # once — across batches too (persistent cache)
            tail = self._tail
            min_read = min(p.txn.read_version for p in batch)
            attempt = self._load_tail(tail, min_read + 1)
            attempt = max(attempt,
                          max(p.txn.read_version for p in batch) + 1)
            for p in batch:
                try:
                    attempt = self._commit_member(p, attempt, tail) + 1
                # delta-lint: ignore[crash-except] -- member-scoped by design; a
                # SimulatedCrash (BaseException) pierces to _lead's batch resolver
                except Exception as e:  # noqa: BLE001 — member-scoped
                    p.exc = e
            if len(tail) > self._TAIL_KEEP:
                for v in sorted(tail)[: len(tail) - self._TAIL_KEEP]:
                    del tail[v]
        # ONE snapshot install for the whole batch, BEFORE the members
        # wake: their _post_commit reuses it instead of K re-listings.
        # A LISTING install, deliberately not a segment extension (the
        # reference's postCommitSnapshot): the listing rebases the
        # segment onto the freshest async-written checkpoint, and a
        # measured attempt at extension showed the longer synthetic
        # tail costs more in state materialization than the listing
        # saves
        try:
            dl.update()
        except Exception:  # noqa: BLE001 — members re-list themselves
            pass
        for p in batch:
            p.done = True
        with self._cv:
            self._cv.notify_all()

    def _load_tail(self, tail: Dict[int, List[Action]],
                   from_version: int) -> int:
        """Extend ``tail`` with every commit >= ``from_version``; returns
        the next free version. One listing bounds the window; each commit
        file is read at most once across the batch (and across re-loads
        after an external race); a read-probe past the listed head guards
        against lagged listings."""
        dl = self.delta_log
        head = from_version - 1
        prefix = f"{dl.log_path}/{filenames.check_version_prefix(from_version)}"
        try:
            # delta-lint: ignore[lock-blocking] -- deliberate: ONE tail listing
            # under the commit lock replaces K per-writer listings (PR 9 design)
            for fs in dl.store.list_from(prefix):
                if filenames.is_delta_file(fs.name):
                    head = max(head, filenames.delta_version(fs.name))
        except FileNotFoundError:
            pass
        v = from_version
        while True:
            if v not in tail:
                path = f"{dl.log_path}/{filenames.delta_file(v)}"
                try:
                    # delta-lint: ignore[lock-blocking] -- deliberate: the shared
                    # tail snapshot is read once under the lock for the batch
                    tail[v] = actions_from_lines(dl.store.read_iter(path))
                except FileNotFoundError:
                    # end of tail — or a listed-but-unreadable mid-window
                    # hole (listing/read disagreement): either way stop
                    # here; if the hole was real, the member's create at v
                    # collides and _winning's direct read resolves it
                    return v
            v += 1
            if v > head:
                # beyond the listing: keep probing (listing may lag writes)
                path = f"{dl.log_path}/{filenames.delta_file(v)}"
                if v in tail:
                    head = v
                    continue
                try:
                    # delta-lint: ignore[lock-blocking] -- deliberate: probing
                    # past a lagged listing is part of the one shared tail read
                    tail[v] = actions_from_lines(dl.store.read_iter(path))
                    head = v
                    v += 1
                except FileNotFoundError:
                    return v

    def _commit_member(self, p: _Pending, attempt: int,
                       tail: Dict[int, List[Action]]) -> int:
        """Conflict-check and write one member at ``attempt`` (bumping past
        external race winners); returns the version it landed at. On a
        logical conflict the member's exception propagates (counted and
        journaled exactly like the ungrouped retry path). The member's
        actions join ``tail`` so later batchmates conflict-check against
        them — the intra-batch check."""
        txn = p.txn
        dl = self.delta_log
        # honors the member's maintenance attempts cap (stamped on the txn
        # at commit() — the leader thread's own contextvar is irrelevant)
        max_attempts = transaction_mod.effective_max_commit_attempts(txn)

        def _winning(v: int) -> List[Action]:
            # normally served from the shared snapshot; a version _load_tail
            # could list but not read (listing/read disagreement, or cleanup
            # expiring a very old window) is fetched directly — and if it is
            # genuinely unreadable the member fails as an ordinary conflict,
            # never an opaque KeyError
            actions = tail.get(v)
            if actions is None:
                path = f"{dl.log_path}/{filenames.delta_file(v)}"
                try:
                    # delta-lint: ignore[lock-blocking] -- deliberate: rare
                    # listing/read disagreement fill of the shared tail snapshot
                    actions = actions_from_lines(dl.store.read_iter(path))
                except FileNotFoundError:
                    raise errors.concurrent_write_exception()
                tail[v] = actions
            return actions

        def _check_window(lo: int, hi: int) -> None:
            # keep the txn's attempt count current BEFORE checking: a
            # conflict abort journals stats.attempts via
            # _note_logical_conflict, and the advisor's contention evidence
            # must see the real grouped retry count, not the initial 1
            txn.stats.attempts = p.attempts
            t0 = time.monotonic()
            try:
                for v in range(lo, hi):
                    try:
                        conflicts_mod.check_for_conflicts(txn, v, _winning(v))
                    except errors.DeltaConcurrentModificationException:
                        txn._note_logical_conflict(v)
                        raise
            finally:
                p.conflict_check_ms += (time.monotonic() - t0) * 1000.0

        _check_window(txn.read_version + 1, attempt)
        while True:
            if p.attempts > max_attempts:
                # same bound as the ungrouped loop — the leader must not
                # spin forever holding the commit lock
                raise transaction_mod.max_attempts_exceeded(p.attempts)
            # fault point: the leader's write loop, once per member, before
            # the create — a crash here dies between batch members
            faults_mod.fire("txn.groupLoop", filenames.delta_file(attempt))
            try:
                txn._write_commit(attempt, p.actions)
            except FileExistsError:
                # external writer claimed this version: extend the tail by
                # just the new commits, re-check, re-attempt — the batch
                # re-enters at bumped versions instead of unwinding to K
                # independent tail re-reads
                p.attempts += 1
                nxt = self._load_tail(tail, attempt)
                if nxt == attempt:
                    raise errors.concurrent_write_exception()
                _check_window(attempt, nxt)
                attempt = nxt
                continue
            except Exception as e:  # noqa: BLE001 — classified below
                if not retries_mod.is_transient(e):
                    raise
                outcome = txn._reconcile_ambiguous_commit(attempt, e)
                if outcome is True:
                    break
                if outcome is False:
                    p.attempts += 1
                    # the reconcile read already fetched and decoded the
                    # winner at `attempt` (it seeds the txn's tail cache):
                    # reuse it instead of a second store read
                    cached = getattr(txn, "_tail_cache", None)
                    if cached and attempt in cached:
                        tail.setdefault(attempt, cached[attempt])
                    nxt = self._load_tail(tail, attempt)
                    _check_window(attempt, max(nxt, attempt + 1))
                    attempt = max(nxt, attempt + 1)
                    continue
                # delta-lint: ignore[lock-blocking] -- same backoff the ungrouped
                # path sleeps under this lock; only transient-ambiguous retries
                time.sleep(transaction_mod.commit_backoff_s(p.attempts))
                p.attempts += 1
                continue
            else:
                break
        tail[attempt] = list(p.actions)
        p.version = attempt
        txn._group_meta = {
            "batchSize": p.batch_size,
            "queueWaitMs": p.queue_wait_ms,
            "attempts": p.attempts,
            "conflictCheckMs": p.conflict_check_ms,
        }
        return attempt
