"""The distributed-trace plane: trace identity (128-bit trace ids, namespaced
span ids, the traceparent-shaped wire carrier), head sampling and its forced
paths (errors, SLO burn windows), the JSONL span spool + cross-process
collector (``obs/trace_store``), straggler/critical-path analysis, the
``/traces`` routes, and the flight-recorder exemplar link. The end-to-end
2-process stitch lives in ``test_multihost.py``; these are the unit
contracts it stands on.
"""
import http.client
import json
import os
import threading

import pytest

from delta_tpu.obs import trace_store
from delta_tpu.obs.server import ObsServer
from delta_tpu.parallel.executor import run_sharded
from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf


@pytest.fixture(autouse=True)
def _fresh():
    telemetry.clear_events()
    yield
    telemetry.clear_events()
    trace_store.reset()


def _get(srv, route):
    c = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
    try:
        c.request("GET", route)
        r = c.getresponse()
        return r.status, r.read()
    finally:
        c.close()


# -- trace identity ----------------------------------------------------------


def test_root_span_mints_trace_id_children_inherit():
    with telemetry.record_operation("delta.test.root") as root:
        assert telemetry.current_trace_id() == root.trace_id
        with telemetry.record_operation("delta.test.child") as child:
            pass
        telemetry.record_event("delta.test.mark")
    [mark] = telemetry.recent_events("delta.test.mark")
    assert len(root.trace_id) == 32
    int(root.trace_id, 16)  # hex
    assert child.trace_id == root.trace_id
    assert mark.trace_id == root.trace_id
    # the trace ends with its root: sequential roots are distinct traces
    assert telemetry.current_trace_id() is None
    with telemetry.record_operation("delta.test.root2") as root2:
        pass
    assert root2.trace_id != root.trace_id


def test_span_ids_share_the_process_namespace():
    with telemetry.record_operation("delta.test.a") as a:
        pass
    with telemetry.record_operation("delta.test.b") as b:
        pass
    assert a.span_id != b.span_id
    # high word = the per-process random namespace, low word = the counter —
    # two hosts' spools cannot collide when stitched
    assert a.span_id >> 32 == b.span_id >> 32 == telemetry._SPAN_NS >> 32


def test_wire_carrier_round_trip():
    with telemetry.record_operation("delta.test.coord") as root:
        wire = telemetry.span_context(wire=True)
    assert wire == "00-%s-%016x-01" % (root.trace_id, root.span_id)
    with telemetry.adopt_span_context(wire):
        assert telemetry.current_trace_id() == root.trace_id
        with telemetry.record_operation("delta.test.remote") as child:
            pass
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    assert telemetry.current_trace_id() is None
    # no active trace → nothing to put on the wire
    assert telemetry.span_context(wire=True) is None
    with pytest.raises(ValueError):
        with telemetry.adopt_span_context("not-a-traceparent"):
            pass


def test_thread_carrier_keeps_trace_and_parent():
    # pool threads do not inherit contextvars: the carrier must hand over
    # both the span chain (legacy tuple contract) and the trace state
    assert telemetry.span_context() == ()
    out = {}

    def work(carrier):
        with telemetry.adopt_span_context(carrier):
            with telemetry.record_operation("delta.test.pooled") as ev:
                out["ev"] = ev

    with telemetry.record_operation("delta.test.submit") as root:
        carrier = telemetry.span_context()
        assert carrier == (root.span_id,)
        t = threading.Thread(target=work, args=(carrier,))
        t.start()
        t.join()
    assert out["ev"].parent_id == root.span_id
    assert out["ev"].trace_id == root.trace_id


# -- sampling + spool --------------------------------------------------------


def test_spool_stitch_and_index_round_trip(tmp_path):
    spool = str(tmp_path / "spool")
    before = telemetry.counters().get("trace.spansSpooled", 0)
    with conf.set_temporarily(**{"delta.tpu.trace.dir": spool,
                                 "delta.tpu.trace.sampleRate": 1.0}):
        with telemetry.record_operation("delta.test.parent", path="/t") as root:
            telemetry.record_event("delta.test.mark", {"n": 1})
            with telemetry.record_operation("delta.test.child"):
                pass
    trace_store.reset()
    assert telemetry.counters()["trace.spansSpooled"] - before >= 3

    rows = trace_store.read_spools(spool, root.trace_id)
    by_op = {r["op"]: r for r in rows}
    assert set(by_op) == {"delta.test.parent", "delta.test.mark",
                          "delta.test.child"}
    assert {r["traceId"] for r in rows} == {root.trace_id}
    assert by_op["delta.test.parent"]["parentId"] is None
    assert by_op["delta.test.child"]["parentId"] == root.span_id
    # instants spool too (no span id, no duration), parented in place
    assert by_op["delta.test.mark"]["spanId"] is None
    assert by_op["delta.test.mark"]["durUs"] is None
    assert by_op["delta.test.mark"]["parentId"] == root.span_id

    trace = trace_store.stitch_trace(spool, root.trace_id)
    spans = [r for r in trace["traceEvents"] if r.get("cat") == "delta"]
    assert len(spans) == len(rows) == 3
    phases = {r["name"]: r["ph"] for r in spans}
    assert phases["delta.test.parent"] == "X"
    assert phases["delta.test.mark"] == "i"
    assert all(r["args"]["traceId"] == root.trace_id for r in spans)
    meta = {r["name"] for r in trace["traceEvents"]} - {s["name"] for s in spans}
    assert {"process_name", "thread_name"} <= meta
    assert trace_store.stitch_trace(spool, "f" * 32) is None

    [row] = trace_store.recent_traces(spool)
    assert row["traceId"] == root.trace_id
    assert row["rootOp"] == "delta.test.parent"
    assert row["spans"] == 3 and row["processes"] == 1 and row["errors"] == 0


def test_sample_rate_zero_is_inert_and_errors_force_sample(tmp_path):
    spool = str(tmp_path / "spool")
    with conf.set_temporarily(**{"delta.tpu.trace.dir": spool,
                                 "delta.tpu.trace.sampleRate": 0.0}):
        with telemetry.record_operation("delta.test.quiet"):
            telemetry.record_event("delta.test.quiet.mark")
        # unsampled: the sink never ran, the spool dir was never created
        assert not os.path.exists(spool)
        with pytest.raises(ValueError):
            with telemetry.record_operation("delta.test.outer"):
                with telemetry.record_operation("delta.test.boom") as boom:
                    raise ValueError("kapow")
        rows = trace_store.read_spools(spool)
    trace_store.reset()
    # the error force-sampled the WHOLE trace: both spans spooled
    assert {r["op"] for r in rows} == {"delta.test.boom", "delta.test.outer"}
    assert {r["traceId"] for r in rows} == {boom.trace_id}
    [err_row] = [r for r in rows if r["op"] == "delta.test.boom"]
    assert "kapow" in err_row["error"]
    assert telemetry.last_sampled_trace_id() == boom.trace_id


def test_slo_burn_window_forces_sampling(tmp_path):
    from delta_tpu.obs import slo

    spool = str(tmp_path / "spool")
    alert = slo.SloAlert(objective="test.burn", table="", path=None,
                         fired_at_ms=0, burn_fast=2.0, burn_slow=2.0,
                         threshold=1.0, observed=2.0)
    with slo._LOCK:
        slo._ALERTS[alert.key] = alert
    try:
        assert slo.firing_count() == 1
        with conf.set_temporarily(**{"delta.tpu.trace.dir": spool,
                                     "delta.tpu.trace.sampleRate": 0.0}):
            with telemetry.record_operation("delta.test.burning") as ev:
                pass
            rows = trace_store.read_spools(spool)
    finally:
        with slo._LOCK:
            slo._ALERTS.pop(alert.key, None)
        trace_store.reset()
    # rate 0, no error — but the burn window forced an exemplar trace
    assert [r["op"] for r in rows] == ["delta.test.burning"]
    assert rows[0]["traceId"] == ev.trace_id


def test_spool_byte_cap_drops_instead_of_filling_disk(tmp_path):
    spool = str(tmp_path / "spool")
    before = telemetry.counters().get("trace.spansDropped", 0)
    with conf.set_temporarily(**{"delta.tpu.trace.dir": spool,
                                 "delta.tpu.trace.sampleRate": 1.0,
                                 "delta.tpu.trace.maxBytes": 400}):
        for i in range(8):
            with telemetry.record_operation("delta.test.capped",
                                            {"i": i, "pad": "x" * 64}):
                pass
        rows = trace_store.read_spools(spool)
    trace_store.reset()
    assert 0 < len(rows) < 8
    assert telemetry.counters()["trace.spansDropped"] > before


def test_disabled_telemetry_spools_nothing_and_allocates_nothing(tmp_path):
    import tracemalloc

    spool = str(tmp_path / "spool")
    with conf.set_temporarily(**{"delta.tpu.trace.dir": spool,
                                 "delta.tpu.telemetry.enabled": False}):
        with telemetry.record_operation("delta.test.dark"):
            telemetry.record_event("delta.test.dark.mark")
        assert not os.path.exists(spool)
        assert telemetry.current_trace_id() is None
        # the hot counter path must stay allocation-free under blackout:
        # steady-state increments of an existing key retain no memory
        telemetry.bump_counter("delta.test.hot")
        tracemalloc.start()
        try:
            base = tracemalloc.get_traced_memory()[0]
            for _ in range(1000):
                telemetry.bump_counter("delta.test.hot")
            grown = tracemalloc.get_traced_memory()[0] - base
        finally:
            tracemalloc.stop()
    assert grown < 512, f"hot counter path retained {grown} bytes"


# -- sharded-executor span topology ------------------------------------------


def test_run_sharded_pool_spans_parent_under_job():
    sizes = [10, 20, 30, 40, 50, 60]
    with telemetry.record_operation("delta.test.harness") as root:
        rep = run_sharded(list(range(6)), lambda x: x * 2, sizes=sizes,
                          workers=2, label="unit")
    assert rep.results == [0, 2, 4, 6, 8, 10]
    evs = telemetry.recent_events("delta.dist")
    [job] = [e for e in evs if e.op_type == "delta.dist.job"]
    assert job.parent_id == root.span_id
    assert job.tags["job"] == "unit"
    assert sum(job.data["lptBytes"]) == sum(sizes)
    assert len(job.data["lptBytes"]) == 2
    workers = [e for e in evs if e.op_type == "delta.dist.worker"]
    assert len(workers) == 2
    assert all(w.parent_id == job.span_id for w in workers)
    assert {w.tags["worker"] for w in workers} == {"0", "1"}
    items = [e for e in evs if e.op_type == "delta.dist.item"]
    assert len(items) == 6
    wids = {w.span_id for w in workers}
    assert all(i.parent_id in wids for i in items)
    assert {i.data["index"] for i in items} == set(range(6))
    assert {i.data["bytes"] for i in items} == set(sizes)
    assert all(isinstance(i.data["stolen"], bool) for i in items)
    # one trace covers the harness, the job, every worker and every item
    assert {e.trace_id for e in evs} == {root.trace_id}


def test_run_sharded_inline_path_spans_items_under_job():
    rep = run_sharded([3, 4], lambda x: x + 1, sizes=[5, 7], workers=1,
                      label="inline")
    assert rep.results == [4, 5]
    evs = telemetry.recent_events("delta.dist")
    [job] = [e for e in evs if e.op_type == "delta.dist.job"]
    assert job.data["lptBytes"] == [12]  # one bin: the whole byte weight
    assert not [e for e in evs if e.op_type == "delta.dist.worker"]
    items = [e for e in evs if e.op_type == "delta.dist.item"]
    assert [i.parent_id for i in items] == [job.span_id] * 2


# -- analysis ----------------------------------------------------------------


def _synthetic_spool(tmp_path) -> str:
    """A hand-built two-worker OPTIMIZE trace with known makespans: worker 0
    holds 100 of 150 bytes and runs 30ms, worker 1 holds 50 and runs 10ms
    (one of its items stolen), under a 40ms root."""
    tid = "ab" * 16
    rows = [
        {"spanId": 1, "parentId": None, "op": "delta.cmd.optimize",
         "tsUs": 0, "durUs": 40000, "tags": {}, "data": {}},
        {"spanId": 2, "parentId": 1, "op": "delta.dist.job",
         "tsUs": 1000, "durUs": 35000, "tags": {"job": "optimize"},
         "data": {"skew": 2.0, "lptBytes": [100, 50], "steals": 1}},
        {"spanId": 3, "parentId": 2, "op": "delta.dist.worker",
         "tsUs": 1000, "durUs": 30000,
         "tags": {"job": "optimize", "worker": "0"}, "data": {}},
        {"spanId": 4, "parentId": 2, "op": "delta.dist.worker",
         "tsUs": 1000, "durUs": 10000,
         "tags": {"job": "optimize", "worker": "1"}, "data": {}},
        {"spanId": 5, "parentId": 3, "op": "delta.dist.item",
         "tsUs": 1000, "durUs": 30000, "tags": {},
         "data": {"index": 0, "bytes": 100, "stolen": False}},
        {"spanId": 6, "parentId": 4, "op": "delta.dist.item",
         "tsUs": 1000, "durUs": 6000, "tags": {},
         "data": {"index": 1, "bytes": 40, "stolen": False}},
        {"spanId": 7, "parentId": 4, "op": "delta.dist.item",
         "tsUs": 8000, "durUs": 3000, "tags": {},
         "data": {"index": 2, "bytes": 10, "stolen": True}},
    ]
    spool = tmp_path / "spool"
    spool.mkdir()
    with open(spool / "spool-7-1.jsonl", "w") as f:
        for r in rows:
            r.update(traceId=tid, pid=7, tid=1, thread="main", error=None)
            f.write(json.dumps(r) + "\n")
    return str(spool), tid


def test_analyze_trace_names_straggler_and_critical_path(tmp_path):
    spool, tid = _synthetic_spool(tmp_path)
    a = trace_store.analyze_trace(spool, tid)
    assert a["traceId"] == tid
    assert a["rootOp"] == "delta.cmd.optimize"
    assert a["spans"] == 7 and a["processes"] == [7] and a["errors"] == []
    assert a["durationUs"] == 40000

    # critical path: root → job → the 30ms worker → its 30ms item
    assert [p["op"] for p in a["criticalPath"]] == [
        "delta.cmd.optimize", "delta.dist.job", "delta.dist.worker",
        "delta.dist.item"]
    assert a["criticalPath"][0]["selfUs"] == 5000  # 40ms minus the 35ms job

    [job] = a["jobs"]
    assert job["label"] == "optimize"
    assert job["workers"] == 2 and job["items"] == 3
    assert job["skew"] == 2.0 and job["lptBytes"] == [100, 50]
    # busy total 40ms; LPT shares 100/150 and 50/150 predict 26.6ms / 13.3ms
    w0, w1 = job["shards"]
    assert (w0["worker"], w0["busyUs"], w0["predictedUs"], w0["deltaUs"]) == \
        (0, 30000, 26666, 3334)
    assert (w1["worker"], w1["busyUs"], w1["deltaUs"]) == (1, 10000, -3333)
    assert (w0["bytes"], w1["bytes"]) == (100, 50)
    assert (w1["items"], w1["stolen"]) == (2, 1)
    assert job["straggler"] == w0 == a["straggler"]
    assert job["slowestItem"] == {"index": 0, "bytes": 100, "durUs": 30000,
                                  "stolen": False, "pid": 7}
    assert job["stealRescue"] == {"items": 1, "bytes": 10, "busyUs": 3000}
    assert trace_store.analyze_trace(spool, "0" * 32) is None


def test_read_spools_skips_corrupt_lines(tmp_path):
    spool, tid = _synthetic_spool(tmp_path)
    # a process killed mid-append leaves a torn tail line
    with open(os.path.join(spool, "spool-7-1.jsonl"), "a") as f:
        f.write('{"traceId": "' + tid + '", "spanId": 8, "op": "torn')
    rows = trace_store.read_spools(spool, tid)
    assert len(rows) == 7
    assert trace_store.analyze_trace(spool, tid)["spans"] == 7


# -- HTTP routes -------------------------------------------------------------


@pytest.fixture
def obs_server():
    srv = ObsServer(port=0)
    yield srv
    srv.stop()


def test_trace_route_op_prefix_and_limit(obs_server):
    with telemetry.record_operation("delta.test.alpha"):
        pass
    with telemetry.record_operation("delta.test.beta"):
        pass
    with telemetry.record_operation("other.gamma"):
        pass
    status, body = _get(obs_server, "/trace?op=delta.test")
    assert status == 200
    names = [r["name"] for r in json.loads(body)["traceEvents"]
             if r.get("cat") == "delta"]
    assert set(names) == {"delta.test.alpha", "delta.test.beta"}
    status, body = _get(obs_server, "/trace?op=delta.test&limit=1")
    names = [r["name"] for r in json.loads(body)["traceEvents"]
             if r.get("cat") == "delta"]
    assert names == ["delta.test.beta"]
    # malformed limit degrades to the default view, never 500s
    status, body = _get(obs_server, "/trace?op=delta.test&limit=abc")
    assert status == 200
    assert len([r for r in json.loads(body)["traceEvents"]
                if r.get("cat") == "delta"]) == 2


def test_traces_routes_serve_index_stitch_and_analysis(tmp_path, obs_server):
    status, body = _get(obs_server, "/traces")
    assert status == 400 and b"delta.tpu.trace.dir" in body

    spool = str(tmp_path / "spool")
    with conf.set_temporarily(**{"delta.tpu.trace.dir": spool,
                                 "delta.tpu.trace.sampleRate": 1.0}):
        with telemetry.record_operation("delta.test.served") as root:
            with telemetry.record_operation("delta.test.served.child"):
                pass
        status, body = _get(obs_server, "/traces")
        assert status == 200
        [row] = json.loads(body)
        assert row["traceId"] == root.trace_id and row["spans"] == 2

        status, body = _get(obs_server, f"/traces/{root.trace_id}")
        assert status == 200
        trace = json.loads(body)
        assert trace["otherData"]["traceId"] == root.trace_id
        assert len([r for r in trace["traceEvents"]
                    if r.get("cat") == "delta"]) == 2

        status, body = _get(obs_server,
                            f"/traces/{root.trace_id}?analyze=1")
        assert status == 200
        assert json.loads(body)["rootOp"] == "delta.test.served"

        status, body = _get(obs_server, "/traces/" + "0" * 32)
        assert status == 404 and b"no spooled spans" in body
    trace_store.reset()


# -- flight-recorder exemplar ------------------------------------------------


def test_incident_carries_trace_id_once_per_exception(tmp_path):
    from delta_tpu.obs import flight_recorder

    inc_dir = str(tmp_path / "incidents")
    spool = str(tmp_path / "spool")
    flight_recorder.install()
    with conf.set_temporarily(**{"delta.tpu.obs.incidentDir": inc_dir,
                                 "delta.tpu.trace.dir": spool,
                                 "delta.tpu.trace.sampleRate": 0.0}):
        with pytest.raises(RuntimeError):
            with telemetry.record_operation("delta.test.outer") as outer:
                with telemetry.record_operation("delta.test.mid"):
                    with telemetry.record_operation("delta.test.inner"):
                        raise RuntimeError("boom")
        rows = trace_store.read_spools(spool)
    trace_store.reset()
    # one exception through three nested spans = ONE incident ...
    [path] = flight_recorder.incident_files(inc_dir)
    with open(path) as f:
        incident = json.load(f)
    assert incident["opType"] == "delta.test.inner"
    assert "boom" in incident["error"]
    # ... whose traceId links to a force-sampled, stitchable trace
    assert incident["traceId"] == outer.trace_id
    assert {r["traceId"] for r in rows} == {outer.trace_id}
    assert len(rows) == 3
