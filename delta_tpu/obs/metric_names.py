"""Single catalog of every observability metric name and public entry point.

The AST lint in ``tests/test_telemetry.py`` enforces that (a) every string
constant passed to ``set_gauge`` anywhere in ``delta_tpu/`` appears in
:data:`GAUGES`, (b) every counter bumped from ``delta_tpu/obs/`` (and the
maintenance/conflict counters wired for the doctor) appears in
:data:`COUNTERS`, and (c) each ``obs/`` module's ``__all__`` matches
:data:`PUBLIC_API` — so dashboards and the doctor never chase stringly-typed
drift: a renamed gauge fails the suite, not a Grafana panel.

``table.health.*`` gauges are emitted by :func:`delta_tpu.obs.doctor.doctor`
(labeled by table path) and validated against this catalog at publish time.
"""
from __future__ import annotations

__all__ = ["GAUGES", "COUNTERS", "PUBLIC_API", "health_gauge"]

#: Every labeled gauge the engine publishes.
GAUGES = frozenset({
    # -- doctor: table-health gauges (obs/doctor.py, label: path) --------
    "table.health.severity",
    "table.health.files.count",
    "table.health.files.bytes",
    "table.health.checkpoint.commitsSince",
    "table.health.checkpoint.tailBytes",
    "table.health.checkpoint.tailFiles",
    "table.health.smallFiles.count",
    "table.health.smallFiles.bytes",
    "table.health.smallFiles.estReduction",
    "table.health.dv.files",
    "table.health.dv.deletedRows",
    "table.health.dv.deletedPct",
    "table.health.dv.filesPastPurge",
    "table.health.stats.coveragePct",
    "table.health.stats.parsedPct",
    "table.health.partition.count",
    "table.health.partition.gini",
    "table.health.tombstones.count",
    "table.health.tombstones.bytes",
    "table.health.protocol.minReader",
    "table.health.protocol.minWriter",
    # -- streaming consumer lag (streaming/source.py, label: path) -------
    "streaming.source.backlogFiles",
    "streaming.source.backlogBytes",
    "streaming.source.lastBatchVersionLag",
    # -- maintenance recency (commands/optimize.py, vacuum.py) -----------
    "table.maintenance.lastOptimizeVersion",
    "table.maintenance.lastVacuumTimestamp",
})

#: Counters introduced by the obs layer and its doctor feeds.
COUNTERS = frozenset({
    "obs.incidents.written",
    "obs.server.requests",
    "commit.conflicts",
    "maintenance.optimize.filesCompacted",
    "maintenance.optimize.filesWritten",
    "maintenance.vacuum.filesDeleted",
    "maintenance.vacuum.bytesReclaimed",
    # -- robustness layer (utils/retries, storage/faults, txn) -----------
    "storage.retry.attempts",     # one per backoff sleep, any store
    "storage.retry.exhausted",    # gave up: surfaced to the caller
    "faults.injected",            # deterministic fault injector fired
    "commit.reconciled",          # ambiguous commit resolved via txnId
    # -- device MERGE router + resident key cache (commands/merge.py,
    #    ops/key_cache.py) — `auto_used_device` made observable on
    #    production tables via /metrics and flight-recorder incidents
    "merge.device.engaged",       # a device join produced this merge's pairs
    "merge.device.declined",      # link cost model chose the host
    "merge.device.cacheHit",      # engaged from an HBM-resident key lane
    "merge.keyCache.builds",      # cold key-lane builds (inline or bg)
    "merge.keyCache.advances",    # incremental log-tail applications
    "merge.keyCache.invalidations",  # entries dropped by a rewrite epoch bump
})

#: Public surface of each obs module, lint-matched against its ``__all__``.
PUBLIC_API = {
    "doctor": ("HealthDimension", "TableHealthReport", "doctor",
               "SEVERITY_RANK"),
    "scan_report": ("ScanReport", "last_scan_report", "clear_last_report",
                    "start_report", "current_report", "contribute",
                    "finish_report"),
    "server": ("ObsServer", "start_server", "stop_server"),
    "flight_recorder": ("install", "uninstall", "record_incident",
                        "incident_files"),
    "metric_names": ("GAUGES", "COUNTERS", "PUBLIC_API", "health_gauge"),
}


def health_gauge(dimension: str, metric: str) -> str:
    """The catalog-checked gauge name for a doctor metric — raises on a name
    that is not registered, so a new metric cannot ship un-cataloged."""
    name = f"table.health.{dimension}.{metric}"
    if name not in GAUGES:
        raise ValueError(f"gauge {name!r} is not registered in "
                         "delta_tpu/obs/metric_names.py")
    return name
