"""Process-wide table registry — the fleet half of the observability plane.

Every observability surface before this module is single-table: ``doctor()``
and ``advise()`` take one path, and the hot gauges/histograms were process
-global, so an operator running one engine over many tables could not ask
"which of my tables is the problem". This module closes both gaps:

* **Registry** — every :class:`~delta_tpu.log.deltalog.DeltaLog`
  auto-registers on construction (weakref'd: the registry never extends a
  table's lifetime; dead handles are pruned on the next read). Strictly
  blackout-inert: with ``delta.tpu.telemetry.enabled=false`` (or
  ``delta.tpu.obs.fleet.enabled=false``) nothing registers.
* **Per-table labels** — :func:`table_label` hashes a table path into a
  short stable label (``table=<sha1[:12]>``) that the hot metric sites
  (commit latency, scan planning, journal flushes, key-cache residency)
  attach to their gauges/histograms, keeping series cardinality and label
  bytes bounded while making cross-table aggregation possible. The
  registry keeps the reverse map so ``/fleet``, ``/slo`` and the autopilot
  can resolve a label back to its path.
* **Fleet sweeps** — :func:`fleet_doctor` / :func:`fleet_advise` run the
  per-table doctor/advisor over every live table and rank the fleet by
  worst dimension (severity, then breadth of debt), so "which table first"
  is one call — the input the autopilot needs to schedule across a fleet
  instead of reacting per table.

Served by ``GET /fleet`` (`obs/server`) and ``tools/fleet_dump.py``.
"""
from __future__ import annotations

import functools
import hashlib
import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf

__all__ = ["enabled", "register", "unregister", "live_tables", "table_label",
           "label_path", "fleet_doctor", "fleet_advise", "fleet_status",
           "FleetEntry", "FleetReport", "reset"]

_LOCK = threading.Lock()
# data_path -> (weakref to the DeltaLog, registered_at_ms)
_TABLES: Dict[str, tuple] = {}
# short hash label -> data path (populated by table_label; labels are
# kept across blackouts — they are pure derived names, not state)
_LABEL_PATHS: Dict[str, str] = {}


def enabled() -> bool:
    """The registry is live: telemetry on AND the fleet switch on."""
    return (conf.get_bool("delta.tpu.telemetry.enabled", True)
            and conf.get_bool("delta.tpu.obs.fleet.enabled", True))


@functools.lru_cache(maxsize=8192)
def table_label(path: str) -> str:
    """Stable short label for a table path (``sha1(path)[:12]``) — the
    value of the ``table=`` metric label. Hashed, not the raw path: label
    cardinality stays bounded-width and scrape lines don't leak full
    filesystem layout. The reverse map is kept for operators
    (:func:`label_path`). lru_cached — the per-commit hot path pays a dict
    probe, not a hash + lock."""
    label = hashlib.sha1(path.encode("utf-8")).hexdigest()[:12]
    with _LOCK:
        _LABEL_PATHS.setdefault(label, path)
        if len(_LABEL_PATHS) > 16384:
            # bounded like the lru_cache above it: under extreme table
            # churn the reverse map must not outgrow the process; dropping
            # the oldest only un-resolves labels of long-dead tables —
            # and the lru_cache must drop too, or a still-hot table whose
            # mapping was evicted would never re-prime it (its calls keep
            # hitting the cache and skipping the setdefault above)
            for k in list(_LABEL_PATHS)[:len(_LABEL_PATHS) - 8192]:
                _LABEL_PATHS.pop(k, None)
            evicted_labels = True
        else:
            evicted_labels = False
    if evicted_labels:
        table_label.cache_clear()
    return label


def label_path(label: str) -> Optional[str]:
    """The table path a ``table=`` label resolves to, if this process has
    seen it."""
    with _LOCK:
        return _LABEL_PATHS.get(label)


def register(delta_log) -> bool:
    """Weakref-register a constructed DeltaLog (called from
    ``DeltaLog.__init__``). Returns False (and stores nothing) under a
    telemetry blackout or with the fleet registry disabled."""
    if not enabled():
        return False
    path = delta_log.data_path
    prev = _TABLES.get(path)  # GIL-atomic probe: the common re-offer from
    if prev is not None and prev[0]() is delta_log:
        return True           # DeltaLog.update stays lock-free
    with _LOCK:
        prev = _TABLES.get(path)
        # re-registration (DeltaLog.update re-offers its handle, covering
        # tables constructed during a blackout that later lifted) keeps
        # the original registration time
        _TABLES[path] = (weakref.ref(delta_log),
                         prev[1] if prev else int(time.time() * 1000))
        if prev is None:
            # published under the lock: racing register/unregister calls
            # must not land their gauge writes out of order
            telemetry.set_gauge("fleet.tables", len(_TABLES))
    table_label(path)  # prime the reverse map outside the registry lock
    return True


def unregister(path: str) -> None:
    with _LOCK:
        _TABLES.pop(path.rstrip("/"), None)
        telemetry.set_gauge("fleet.tables", len(_TABLES))


def live_tables() -> Dict[str, Any]:
    """``{path: DeltaLog}`` for every registered table whose handle is
    still alive; dead weakrefs are pruned as a side effect."""
    out: Dict[str, Any] = {}
    with _LOCK:
        dead = []
        for path, (ref, _at) in _TABLES.items():
            dl = ref()
            if dl is None:
                dead.append(path)
            else:
                out[path] = dl
        for path in dead:
            _TABLES.pop(path, None)
        if dead:
            telemetry.set_gauge("fleet.tables", len(_TABLES))
    for path in dead:
        # the registry never forgets labeled series on its own: drop the
        # dead table's per-table gauges/histograms so scrape work and
        # registry memory track the LIVE fleet, not every table ever seen
        telemetry.drop_labeled_series(table=table_label(path))
        telemetry.drop_labeled_series(path=path)
    return out


# ---------------------------------------------------------------------------
# Fleet sweeps
# ---------------------------------------------------------------------------


@dataclass
class FleetEntry:
    """One table's row in a ranked fleet sweep."""

    path: str
    table: str                      # hashed label (the metric label value)
    severity: str = "ok"            # worst doctor dimension severity
    worst_dimension: str = ""       # name of the worst dimension
    critical_dims: int = 0
    warn_dims: int = 0
    remedies: List[str] = field(default_factory=list)
    top_score: float = 0.0          # advisor sweeps: best recommendation
    detail: Dict[str, Any] = field(default_factory=dict)
    error: Optional[str] = None     # sweep kept going; this table failed

    def to_dict(self) -> Dict[str, Any]:
        return {
            "path": self.path,
            "table": self.table,
            "severity": self.severity,
            "worstDimension": self.worst_dimension,
            "criticalDims": self.critical_dims,
            "warnDims": self.warn_dims,
            "remedies": list(self.remedies),
            "topScore": round(self.top_score, 3),
            "detail": dict(self.detail),
            "error": self.error,
        }


@dataclass
class FleetReport:
    """A ranked sweep over every live table (worst first)."""

    kind: str                       # "doctor" | "advisor"
    generated_at_ms: int
    entries: List[FleetEntry]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "generatedAt": self.generated_at_ms,
            "tables": len(self.entries),
            "entries": [e.to_dict() for e in self.entries],
        }


def _severity_key(e: FleetEntry):
    from delta_tpu.obs.doctor import SEVERITY_RANK

    # worst severity first, then breadth of debt, then advisor score;
    # path last for a deterministic order
    return (-SEVERITY_RANK.get(e.severity, 0), -e.critical_dims,
            -e.warn_dims, -e.top_score, e.path)


def fleet_doctor() -> FleetReport:
    """Run :func:`~delta_tpu.obs.doctor.doctor` over every live table and
    rank the fleet by worst dimension. One failing table never aborts the
    sweep — its entry carries the error instead."""
    from delta_tpu.obs.doctor import SEVERITY_RANK, doctor

    telemetry.bump_counter("fleet.sweeps")
    entries: List[FleetEntry] = []
    for path, dl in sorted(live_tables().items()):
        entry = FleetEntry(path=path, table=table_label(path))
        try:
            rep = doctor(dl)
            worst = max(rep.dimensions,
                        key=lambda d: SEVERITY_RANK[d.severity])
            entry.severity = rep.severity
            entry.worst_dimension = (worst.name
                                     if worst.severity != "ok" else "")
            entry.critical_dims = sum(
                1 for d in rep.dimensions if d.severity == "critical")
            entry.warn_dims = sum(
                1 for d in rep.dimensions if d.severity == "warn")
            entry.remedies = rep.remedies()
            entry.detail = {"version": rep.version,
                            "numFiles": rep.num_files,
                            "sizeInBytes": rep.size_in_bytes}
        except Exception as e:  # noqa: BLE001 — sweep the rest of the fleet
            entry.error = f"{type(e).__name__}: {e}"
        entries.append(entry)
    entries.sort(key=_severity_key)
    return FleetReport("doctor", int(time.time() * 1000), entries)


def fleet_advise() -> FleetReport:
    """Run :func:`~delta_tpu.obs.advisor.advise` over every live table and
    rank by the strongest recommendation score."""
    from delta_tpu.obs.advisor import advise

    telemetry.bump_counter("fleet.sweeps")
    entries: List[FleetEntry] = []
    for path, dl in sorted(live_tables().items()):
        entry = FleetEntry(path=path, table=table_label(path))
        try:
            rep = advise(dl)
            recs = rep.recommendations if rep.status == "ok" else []
            entry.top_score = max((float(r.score) for r in recs), default=0.0)
            entry.remedies = [r.remedy for r in recs]
            entry.detail = {"status": rep.status, "entries": rep.entries,
                            "recommendations": len(recs)}
        except Exception as e:  # noqa: BLE001 — sweep the rest of the fleet
            entry.error = f"{type(e).__name__}: {e}"
        entries.append(entry)
    entries.sort(key=lambda e: (-e.top_score, e.path))
    return FleetReport("advisor", int(time.time() * 1000), entries)


def fleet_status() -> Dict[str, Any]:
    """Registry introspection for ``/fleet``: every registered table with
    its label, liveness, and registration time. Deliberately does NOT
    prune first (unlike :func:`live_tables`): a registered-but-collected
    table must be able to report ``alive=false`` once before the next
    sweep removes it."""
    with _LOCK:
        rows = [
            {"path": path, "table": _label_of(path),
             "registeredAt": at, "alive": ref() is not None}
            for path, (ref, at) in sorted(_TABLES.items())
        ]
    return {"enabled": enabled(), "tables": len(rows), "entries": rows}


def _label_of(path: str) -> str:
    """Label computation without touching the registry lock (callers hold
    ``_LOCK``); does not prime the reverse map."""
    return hashlib.sha1(path.encode("utf-8")).hexdigest()[:12]


def reset() -> None:
    """Drop the registry and label map (tests / bench isolation)."""
    with _LOCK:
        _TABLES.clear()
        _LABEL_PATHS.clear()
    table_label.cache_clear()
